"""AttentionPlan: one owner for dispatch shapes, phases, and kernel choice.

Before this module the shape policy lived in four places that had to agree
by convention: ``engine.py:_bucket_for`` picked prompt buckets, the admission
paths padded to them, ``_install_bucket``/``_flush_installs`` kept their own
pad set for page-table scatters, and ``__init__`` resolved which attention
kernel each cache kind got. Every consumer compiled its own executable per
shape, so mixed-length traffic paid one recompile per (bucket, row-count)
pair — the "bucket tax" BENCH_r05 measured at 23–28% of nominal prefill
TFLOP/s.

The plan centralizes that policy:

* **Row classification & shapes.** A prompt is a PREFILL row (fits one
  dispatch), a CHUNKED-PREFILL row (walks the prompt ``chunk_tokens`` at a
  time), or a DECODE row. In ragged mode every prefill-family dispatch pads
  to ONE token width (``chunk_tokens``, default the largest bucket), so the
  warm executable set is finite and mixed lengths stop recompiling.
* **Partition preservation.** Ragged mode deliberately keeps the LEGACY
  admission partition — group membership via :meth:`bucket_for` and the
  legacy chunk cap — and changes only the padded dispatch widths. The
  engine draws one PRNG key per admission group/single in admission order;
  keeping the partition keeps the key sequence, which is what makes ragged
  on/off byte-exact for sampled decoding, not just greedy (the sampling
  noise depends on the key and row count, never on pad width).
* **Kernel selection.** Resolves ``use_pallas_attention`` (cache-owned
  decode kernels) and the ragged paged kernel (``ops/ragged_attention.py``)
  from one place; the paged cache reads the decision via its
  ``use_kernel``/``use_ragged`` fields.
* **Chunk/decode co-scheduling budget.** A fractional credit accumulator
  (``chunk_decode_share``) rations how many decode ticks also carry a
  chunked-prefill dispatch, so admission of a long prompt stretches over
  ticks instead of stalling the decode batch behind one monolithic prefill.
* **Dispatch telemetry.** Every dispatch funnels through
  :meth:`note_dispatch`, which maintains the seen-shape set behind the
  ``attn_recompiles`` counter (a first-seen (kind, shape) is exactly one
  fresh XLA executable), counts ``attn_ragged_dispatches`` /
  ``attn_chunked_rows``, and publishes ``attn_grid_occupancy`` (valid /
  padded token fraction of the latest prefill-family dispatch).

This is also the fusion point ROADMAP item 4 (batched spec verification)
needs: a verify row is just one more ``num_new == k`` row class.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax

__all__ = ["AttentionPlan", "KernelSelection", "PREFILL", "CHUNKED", "DECODE"]

# Row phases (data, not shape: the ragged kernel serves all three in one
# grid call — see ops/ragged_attention.py).
PREFILL = "prefill"
CHUNKED = "chunked_prefill"
DECODE = "decode"


@dataclasses.dataclass(frozen=True)
class KernelSelection:
    """Resolved kernel routing for one engine instance.

    ``use_pallas``: cache-owned Pallas decode kernels (``use_kernel=`` on
    the cache; also gates the flash prefill swap in ``__init__``).
    ``use_ragged``: paged caches serve multi-token rows through the ragged
    mixed-phase kernel instead of the contiguous ``update_and_gather`` copy.
    """

    use_pallas: bool
    use_ragged: bool


class AttentionPlan:
    """Owns dispatch-shape policy, phase classification, and kernel choice.

    ``enabled`` resolves ``EngineConfig.ragged_attention``: ``None`` means
    auto — ON for paged caches on a real TPU backend (where the ragged
    kernel replaces the gather copy), OFF elsewhere so CPU defaults keep
    the legacy bucketed path (tests opt in explicitly; the plan's shaping
    and co-scheduling are backend-agnostic and byte-exact either way).
    """

    def __init__(self, engine_cfg, cache_cfg, metrics=None, backend=None):
        self.ecfg = engine_cfg
        self.ccfg = cache_cfg
        self.metrics = metrics
        self.backend = backend or jax.default_backend()
        self.buckets: Tuple[int, ...] = tuple(engine_cfg.prefill_buckets)
        if engine_cfg.ragged_attention is not None:
            self.enabled = bool(engine_cfg.ragged_attention)
        else:
            self.enabled = (
                self.backend == "tpu" and cache_cfg.kind == "paged"
            )
        self.chunk_tokens = (
            engine_cfg.prefill_chunk_tokens
            if engine_cfg.prefill_chunk_tokens is not None
            else self.buckets[-1]
        )
        if self.chunk_tokens < 1:
            raise ValueError(
                f"prefill_chunk_tokens must be >= 1, got {self.chunk_tokens}"
            )
        self.share = float(engine_cfg.chunk_decode_share)
        if not 0.0 <= self.share <= 1.0:
            raise ValueError(
                f"chunk_decode_share must be in [0, 1], got {self.share}"
            )
        self._credit = 0.0
        self._shapes = set()
        # Last dispatch seen by note_dispatch, as (kind, shape, valid) —
        # read by the engine's flight recorder so each tick record carries
        # the dispatch shape without a second telemetry funnel.
        self.last_dispatch: Optional[Tuple] = None
        # Set by the engine when the cache stores the latent (MLA) fused
        # form: every dispatch then reads latents and decompresses in
        # place via the page walk, which note_dispatch surfaces as the
        # ``latent_decompress_dispatches`` counter.
        self.latent = False

    # ------------------------------------------------------------------
    # Row classification / shape policy
    # ------------------------------------------------------------------
    def classify(self, new_tokens: int, total_prompt: int) -> str:
        """Phase of a dispatch serving ``new_tokens`` query rows of a
        ``total_prompt``-token prompt (1 query = decode)."""
        if new_tokens <= 1 and total_prompt > 1:
            return DECODE
        if new_tokens < total_prompt:
            return CHUNKED
        return PREFILL

    def bucket_for(self, n: int) -> int:
        """LEGACY prompt bucket — still the admission-partition key in
        ragged mode (see module docstring: partition == PRNG key order)."""
        for b in self.buckets:
            if n <= b:
                return b
        return self.buckets[-1]

    def prefill_stride(self, legacy_cap: int) -> int:
        """Tokens consumed per chunk when a prompt walks in pieces. Capped
        at the legacy chunk cap (sink caches bound it by the window) so the
        default config's chunk boundaries — hence interior/final dispatch
        sequence — match the legacy path exactly."""
        if not self.enabled:
            return legacy_cap
        return min(self.chunk_tokens, legacy_cap)

    def final_shape(self, rest: int, legacy_cap: int) -> int:
        """Pad width for the final (sampled) chunk of a single-row prefill.
        Ragged mode pads every final to the stride — ONE warm shape per row
        count — instead of the rest's bucket."""
        if not self.enabled:
            return self.bucket_for(rest)
        return self.prefill_stride(legacy_cap)

    def group_shape(self, bucket: int, legacy_cap: int) -> int:
        """Pad width for a batched admission group whose members share
        ``bucket``. Ragged mode pads every group to the largest width so
        all buckets share one executable per row count."""
        if not self.enabled:
            return bucket
        return max(self.prefill_stride(legacy_cap), bucket)

    def install_pads(self, batch: int, max_pages: int) -> Tuple[int, int]:
        """Page-table install scatter pads (small burst, big burst) —
        folded in from ``_flush_installs``/``_install_bucket`` so the warm
        executable set for table writes is owned next to the dispatch
        shapes it serves."""
        big = 1
        while big < max(batch, max_pages):
            big *= 2
        return (4, big)

    # ------------------------------------------------------------------
    # Kernel selection
    # ------------------------------------------------------------------
    def select(self) -> KernelSelection:
        cc = self.ccfg
        tpu = self.backend == "tpu"
        # The ragged kernel is TPU-only in production: interpret mode is
        # orders of magnitude slower than XLA on CPU, so off-TPU the plan
        # keeps the gather path (ragged SHAPES still apply — parity is pad-
        # width-invariant) and the kernel is exercised by ops-level tests.
        use_ragged = self.enabled and tpu and cc.kind == "paged"
        if self.ecfg.use_pallas_attention is not None:
            use_pallas = self.ecfg.use_pallas_attention
        else:
            use_pallas = tpu and (
                (cc.kind in ("dense", "sink") and cc.kv_quant == "int8")
                or use_ragged
            )
        return KernelSelection(use_pallas=use_pallas, use_ragged=use_ragged)

    # ------------------------------------------------------------------
    # Chunk/decode co-scheduling
    # ------------------------------------------------------------------
    def co_schedule_ok(self, prompt_rest: int, temperature: float,
                       legacy_cap: int) -> bool:
        """Config-side eligibility for riding a prompt's prefill on the
        decode cadence: ragged mode on, a non-zero tick share, a prompt
        long enough to need chunking, and greedy decoding (a sampled
        session must keep the legacy key-draw position — chunk ticks would
        move its key relative to admission order)."""
        return (
            self.enabled
            and self.share > 0.0
            and temperature == 0.0
            and prompt_rest > self.prefill_stride(legacy_cap)
        )

    def take_chunk_credit(self, decode_active: bool) -> bool:
        """True when this tick may carry a chunk dispatch. With no decode
        rows to protect the chunk streams at full speed; otherwise credits
        accrue at ``chunk_decode_share`` per tick."""
        if not decode_active:
            return True
        self._credit += self.share
        if self._credit >= 1.0:
            self._credit -= 1.0
            return True
        return False

    # ------------------------------------------------------------------
    # Dispatch telemetry
    # ------------------------------------------------------------------
    def note_dispatch(self, kind: str, shape: Tuple[int, ...],
                      valid_tokens: Optional[int] = None) -> None:
        """Record one attention dispatch: first-seen (kind, shape) is one
        fresh executable (``attn_recompiles``); prefill-family dispatches
        under ragged mode count ``attn_ragged_dispatches`` and publish the
        valid/padded occupancy gauge."""
        key = (kind,) + tuple(int(x) for x in shape)
        self.last_dispatch = (
            kind, tuple(int(x) for x in shape), valid_tokens
        )
        if key not in self._shapes:
            self._shapes.add(key)
            if self.metrics is not None:
                self.metrics.counter("attn_recompiles")
        if self.metrics is None:
            return
        if self.latent:
            self.metrics.counter("latent_decompress_dispatches")
        if self.enabled and kind != DECODE:
            self.metrics.counter("attn_ragged_dispatches")
        if valid_tokens is not None:
            padded = 1
            for x in shape:
                padded *= int(x)
            if padded > 0:
                self.metrics.gauge(
                    "attn_grid_occupancy", valid_tokens / padded
                )

    def note_chunk_rows(self, n: int = 1) -> None:
        if self.metrics is not None:
            self.metrics.counter("attn_chunked_rows", n)
