from .engine import InferenceEngine
from .sampling import SamplingOptions, SamplingParams, sample
from .session import Session, SessionState

__all__ = [
    "InferenceEngine",
    "SamplingOptions",
    "SamplingParams",
    "sample",
    "Session",
    "SessionState",
]
