"""Speculative decoding: draft-model proposals verified by the target model.

Capability for BASELINE config 5 ("Llama-3-70B hybrid TPxPP, speculative
decoding") — absent from the reference, which decodes strictly one token per
step (``/root/reference/distributed_llm_inference/models/llama/modules.py:73``
gates its whole fast path on ``q_len == 1``).

Greedy speculation: the draft model proposes ``k`` tokens autoregressively;
the target model verifies all of them in ONE forward over ``k+1`` positions
(turning k sequential HBM sweeps into one — the win on bandwidth-bound
decode). The accepted run is the longest prefix where the target's argmax
agrees with the proposal; the target's own argmax at the first disagreement
is appended as the bonus token, so output is IDENTICAL to target-only greedy
decode — speculation changes latency, never content.

Cache rollback is free by design: the static-shape caches advance lengths
explicitly, so rejected positions are simply never counted (writes past
``lengths`` are invisible — validity derives from lengths, ``cache/dense.py``).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..cache.dense import DenseKVCache
from ..config import ModelConfig
from ..models import llama

__all__ = ["SpeculativeDecoder"]


class SpeculativeDecoder:
    """Greedy speculative decoding for one sequence (bs=1).

    ``draft_cfg``/``draft_params`` is the small proposal model (same
    tokenizer/vocab as the target).
    """

    def __init__(
        self,
        cfg: ModelConfig,
        params,
        draft_cfg: ModelConfig,
        draft_params,
        k: int = 4,
        max_seq_len: int = 512,
        dtype=jnp.bfloat16,
    ):
        if draft_cfg.vocab_size != cfg.vocab_size:
            raise ValueError("draft and target must share a vocabulary")
        if k < 1:
            raise ValueError(f"k must be >= 1, got {k}")
        self.cfg, self.dcfg = cfg, draft_cfg
        self.params, self.dparams = params, draft_params
        self.k = k
        self.max_seq_len = max_seq_len
        self.dtype = dtype

        # One executable per role; all shapes static in k.
        def prefill(cfg_, params_, tokens, cache, n):
            logits, cache = llama.model_apply(cfg_, params_, tokens, cache, n)
            return logits, cache

        self._prefill_t = jax.jit(
            lambda p, t, c, n: prefill(cfg, p, t, c, n)
        )
        self._prefill_d = jax.jit(
            lambda p, t, c, n: prefill(draft_cfg, p, t, c, n)
        )

        def draft_propose(params_, token, cache):
            """k greedy draft tokens from ``token``; cache advances k."""
            def step(carry, _):
                tok, cache = carry
                logits, cache = llama.model_apply(
                    draft_cfg, params_, tok, cache, jnp.ones((1,), jnp.int32)
                )
                nxt = jnp.argmax(logits[:, -1:], -1).astype(jnp.int32)
                return (nxt, cache), nxt[0, 0]

            (_, cache), toks = jax.lax.scan(
                step, (token, cache), None, length=self.k
            )
            return toks, cache  # [k], cache advanced by k

        self._propose = jax.jit(draft_propose)

        def target_verify(params_, last_token, proposal, cache):
            """One target forward over [last, p1..pk]; returns the argmax at
            every position ([k+1]) and the cache (advanced k+1 — the caller
            rolls lengths back to the accepted count)."""
            seq = jnp.concatenate([last_token[0], proposal])[None, :]  # [1,k+1]
            logits, cache = llama.model_apply(
                cfg, params_, seq, cache, jnp.full((1,), self.k + 1, jnp.int32)
            )
            preds = jnp.argmax(logits[0], -1).astype(jnp.int32)  # [k+1]
            return preds, cache

        self._verify = jax.jit(target_verify)

        self.stats = {"proposed": 0, "accepted": 0, "steps": 0}

    def _mk_cache(self, cfg: ModelConfig) -> DenseKVCache:
        return DenseKVCache.create(
            cfg.num_layers, 1, self.max_seq_len, cfg.num_kv_heads,
            cfg.head_dim, self.dtype,
        )

    def generate(
        self,
        prompt: Sequence[int],
        max_new_tokens: int = 64,
        eos_token_id: Optional[int] = None,
    ) -> List[int]:
        """Greedy decode; output identical to target-only greedy decoding."""
        n = len(prompt)
        if n == 0:
            raise ValueError("empty prompt")
        if n + max_new_tokens + self.k + 1 > self.max_seq_len:
            raise ValueError("max_seq_len too small for prompt + generation")
        cache_t = self._mk_cache(self.cfg)
        cache_d = self._mk_cache(self.dcfg)
        tokens = jnp.asarray([list(prompt)], jnp.int32)
        nn = jnp.full((1,), n, jnp.int32)

        logits_t, cache_t = self._prefill_t(self.params, tokens, cache_t, nn)
        _, cache_d = self._prefill_d(self.dparams, tokens, cache_d, nn)
        last = int(jnp.argmax(logits_t[0, n - 1]))
        out = [last]

        while len(out) < max_new_tokens and last != eos_token_id:
            last_tok = jnp.asarray([[last]], jnp.int32)
            proposal, cache_d = self._propose(self.dparams, last_tok, cache_d)
            preds, cache_t = self._verify(
                self.params, last_tok, proposal, cache_t
            )
            prop = np.asarray(proposal)
            pred = np.asarray(preds)

            # Longest agreeing prefix; target's pred at the first mismatch is
            # the bonus token (always emitted — preds[i] is conditioned on
            # prop[:i] which all matched).
            accepted = 0
            while accepted < self.k and prop[accepted] == pred[accepted]:
                accepted += 1
            emitted = [int(t) for t in prop[:accepted]] + [int(pred[accepted])]

            self.stats["proposed"] += self.k
            self.stats["accepted"] += accepted
            self.stats["steps"] += 1

            # Roll both caches back to the true sequence length. The target
            # verify advanced k+1 but only [last, d1..d_accepted] are real —
            # the bonus token is not in any cache yet (it is fed next round).
            cache_t = cache_t.replace(
                lengths=cache_t.lengths - (self.k - accepted)
            )
            if accepted == self.k:
                # Full acceptance: the draft consumed [last, d1..d_{k-1}] but
                # never its own final proposal d_k — catch it up one step so
                # its positions stay aligned with the true sequence.
                _, cache_d = self._prefill_d(
                    self.dparams, jnp.asarray([[int(prop[-1])]], jnp.int32),
                    cache_d, jnp.ones((1,), jnp.int32),
                )
            else:
                cache_d = cache_d.replace(
                    lengths=cache_d.lengths - (self.k - accepted - 1)
                )

            for t in emitted:
                out.append(t)
                if len(out) >= max_new_tokens or t == eos_token_id:
                    break
            last = out[-1]

        return out[:max_new_tokens]

    @property
    def acceptance_rate(self) -> float:
        return self.stats["accepted"] / max(self.stats["proposed"], 1)
