"""Token sampling as pure jitted functions.

The reference has no sampling at all (no client layer exists — SURVEY §1);
this is part of the client-side capability a complete framework needs. All
samplers are batch-vectorized with *per-row* parameters so one compiled decode
step serves heterogeneous sessions (a greedy row and a top-p row share the
batch), matching the multi-tenant design of the caches.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from flax import struct


class SamplingParams(struct.PyTreeNode):
    """Per-row sampling knobs, shape ``[B]`` each.

    ``temperature == 0`` selects greedy for that row. ``top_k <= 0`` disables
    top-k; ``top_p >= 1`` disables nucleus filtering.

    ``all_greedy`` is STATIC (hashable; part of the jit cache key): the
    all-greedy batch — the common serving case — compiles a decode program
    with no full-vocab sort in it at all (milliseconds per step at
    [112, 32k]); the first stochastic session triggers one recompile to the
    mixed program.
    """

    temperature: jax.Array
    top_k: jax.Array
    top_p: jax.Array
    all_greedy: bool = struct.field(pytree_node=False, default=False)

    @staticmethod
    def create(batch: int, temperature=0.0, top_k=0, top_p=1.0) -> "SamplingParams":
        full = lambda v, dt: jnp.full((batch,), v, dt)
        return SamplingParams(
            temperature=full(temperature, jnp.float32),
            top_k=full(top_k, jnp.int32),
            top_p=full(top_p, jnp.float32),
            all_greedy=temperature <= 0.0,
        )

    @staticmethod
    def stack(rows) -> "SamplingParams":
        return SamplingParams(
            temperature=jnp.asarray([r.temperature for r in rows], jnp.float32),
            top_k=jnp.asarray([r.top_k for r in rows], jnp.int32),
            top_p=jnp.asarray([r.top_p for r in rows], jnp.float32),
            all_greedy=all(r.temperature <= 0.0 for r in rows),
        )


@dataclasses.dataclass(frozen=True)
class SamplingOptions:
    """Host-side per-session options (the scheduler stacks them per step)."""

    temperature: float = 0.0
    top_k: int = 0
    top_p: float = 1.0
    max_new_tokens: int = 128
    eos_token_id: int = -1  # -1 = never stop on EOS
    # Opt in to draft-model speculative decoding (engines constructed with a
    # draft model only; greedy rows only — stochastic rows decode normally).
    speculative: bool = False


_NEG = jnp.float32(-1e30)


def _filter_top_k_top_p(
    logits: jnp.ndarray, top_k: jnp.ndarray, top_p: jnp.ndarray
) -> jnp.ndarray:
    """Joint top-k + nucleus filter sharing ONE descending sort (sorting the
    vocab is the dominant cost of stochastic decode ticks).

    Top-k keeps ranks ``< k``; top-p keeps the smallest prefix of the sorted
    distribution with cumulative probability ≥ top_p (rank 0 always survives).
    """
    b, vocab = logits.shape
    sort_idx = jnp.argsort(-logits, axis=-1)
    sorted_logits = jnp.take_along_axis(logits, sort_idx, axis=-1)
    ranks = jax.lax.broadcasted_iota(jnp.int32, (b, vocab), 1)

    keep_k = (ranks < jnp.clip(top_k, 1, vocab)[:, None]) | (top_k[:, None] <= 0)

    probs = jax.nn.softmax(sorted_logits, axis=-1)
    cum = jnp.cumsum(probs, axis=-1)
    keep_p = ((cum - probs) < top_p[:, None]) | (top_p[:, None] >= 1.0)

    keep = jnp.zeros((b, vocab), bool).at[
        jnp.arange(b)[:, None], sort_idx
    ].set(keep_k & keep_p)
    return jnp.where(keep, logits, _NEG)


def sample(
    logits: jnp.ndarray,
    key: jax.Array,
    params: SamplingParams,
) -> jnp.ndarray:
    """Draw one token per row from ``logits [B, V]`` → ``[B]`` int32.

    Greedy rows (temperature 0) and stochastic rows coexist in one call so the
    decode step stays a single compiled function. ``params.all_greedy`` is
    static: the all-greedy program contains no full-vocab sort at all (the
    sort costs milliseconds at [112, 32k] and is the dominant stochastic-tick
    cost); mixed batches compile the full program once.
    """
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    if params.all_greedy:
        return greedy

    temp = jnp.maximum(params.temperature, 1e-6)[:, None]
    scaled = logits.astype(jnp.float32) / temp
    scaled = _filter_top_k_top_p(scaled, params.top_k, params.top_p)
    drawn = jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)

    return jnp.where(params.temperature > 0.0, drawn, greedy)
