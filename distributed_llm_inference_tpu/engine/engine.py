"""Inference engine: bucketed prefill + single compiled decode step over the
active batch, with a continuous-batching scheduler.

This is the TPU-native replacement for the serving machinery the reference
delegated to hivemind and never finished: the batching role of its
``TaskPool(self.forward, …)``
(``/root/reference/distributed_llm_inference/server/backend.py:42``) and the
per-``generation_id`` multi-tenancy of its cache (``models/llama/cache.py:14-19``)
become: sessions pinned to batch rows of ONE preallocated cache, admitted and
evicted between steps, with every device computation a cached ``jax.jit``
executable (the role CUDA-graph capture plays in the reference,
``utils/cuda.py:6`` — XLA compilation *is* the graph; bucketing keeps the
executable count finite).

Step anatomy (host orchestrates, device computes):
  1. admit — move waiting sessions into free slots (pages allocated for paged
     caches), run bucketed single-row prefill(s), sample the first token.
  2. decode — one jitted step over all slots; inactive rows carry
     ``active=0`` and are masked throughout.
  3. retire — EOS / length / capacity sessions leave their slots; pages freed.
"""

from __future__ import annotations

import collections
import dataclasses
import math
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..cache.base import window_ladder
from ..cache.dense import DenseKVCache, QuantizedDenseKVCache
from ..cache.latent import LatentPagedKVCache, QuantizedLatentPagedKVCache
from ..cache.paged import PageAllocator, PagedKVCache, QuantizedPagedKVCache
from ..cache.sink import QuantizedSinkKVCache, SinkKVCache

# Cache kinds implementing the StreamingLLM sink-window policy (unbounded
# streams, fixed memory): scheduler paths that special-case the sink ring
# must cover both the bf16 and the int8/kernel variants.
_SINK_KINDS = (SinkKVCache, QuantizedSinkKVCache)
from ..config import CacheConfig, EngineConfig, ModelConfig, PrefixConfig
from ..models import llama
from ..utils.metrics import Metrics
from ..utils.tracing import FlightRecorder, SpanRecorder, span
from .plan import AttentionPlan
from .sampling import SamplingOptions, SamplingParams, sample
from .session import Session, SessionState


class InferenceEngine:
    """Single-host continuous-batching engine over one model replica.

    ``attention_fn`` lets callers swap the XLA attention for a Pallas kernel;
    ``model_fns`` hooks other model families (Mistral = Llama + sliding
    window; see ``models/registry.py``).
    """

    def __init__(
        self,
        cfg: ModelConfig,
        params,
        engine_cfg: Optional[EngineConfig] = None,
        cache_cfg: Optional[CacheConfig] = None,
        rng: Optional[jax.Array] = None,
        attention_fn=None,
        mesh_cfg=None,
        draft=None,
        prefix_cfg=None,
        trace_cfg=None,
    ):
        """``mesh_cfg`` (a :class:`MeshConfig`) serves one sharded deployment
        of the model: tp/ep shard within a replica, dp shards batch rows, and
        pp>1 runs the GPipe-staged pipeline program per batched step; the
        scheduler is untouched either way.

        ``draft = (draft_cfg, draft_params)`` enables speculative decoding
        for sessions that opt in via ``SamplingOptions.speculative`` (greedy
        rows only): the draft proposes ``EngineConfig.speculative_k`` tokens
        and the target verifies them in ONE forward, with speculative and
        normal sessions sharing that same batched step (normal rows run it
        as a plain 1-token decode via per-row ``num_new`` masking). Output
        is identical to non-speculative greedy decoding."""
        self.cfg = cfg
        self._mesh_cfg = mesh_cfg
        self.ecfg = engine_cfg or EngineConfig()
        if (
            self.ecfg.act_quant_prefill is not None
            or self.ecfg.act_quant_min_seq is not None
        ):
            # Pin the W8A8 prefill-activation policy for this deployment
            # (the flags live at module scope because jitted matmuls capture
            # them at trace time; EngineConfig is the supported way to set
            # them — see config.py).
            from ..ops import quant as _quant

            if self.ecfg.act_quant_prefill is not None:
                _quant.ACT_QUANT_PREFILL = self.ecfg.act_quant_prefill
            if self.ecfg.act_quant_min_seq is not None:
                _quant.ACT_QUANT_MIN_SEQ = self.ecfg.act_quant_min_seq
        if self.ecfg.quantization in ("int8", "int4", "int8_outlier"):
            from ..ops.quant import quantize_params

            qkw = {}
            if self.ecfg.quantization == "int8_outlier":
                # LLM.int8()-inspired decomposition: fp input channels per
                # projection ride a side matmul. APPROXIMATES (does not yet
                # reproduce) bitsandbytes threshold=5.0 — channel choice is
                # steered by calibration activation absmax when
                # EngineConfig.act_scales is provided, else by weight-row
                # energy as a proxy.
                qkw["outlier_channels"] = self.ecfg.outlier_channels
                if self.ecfg.act_scales is not None:
                    qkw["act_scales"] = self.ecfg.act_scales
            if self.ecfg.quantization == "int4":
                # Unsharded (or dp/ep-only) serving decodes through the
                # Pallas half-split kernel; tp/pp meshes keep the grouped
                # XLA layout (the packed channel order doesn't column-shard),
                # with group counts divisible by tp (whole groups per device).
                solo = mesh_cfg is None or (
                    mesh_cfg.tp == 1 and mesh_cfg.pp == 1
                )
                qkw["int4_layout"] = "split" if solo else "grouped"
                if not solo:
                    qkw["group_multiple"] = mesh_cfg.tp
            params = quantize_params(
                params, bits=4 if self.ecfg.quantization == "int4" else 8,
                **qkw,
            )
        elif self.ecfg.quantization is not None:
            raise ValueError(f"unknown quantization {self.ecfg.quantization!r}")
        self.params = params
        self.ccfg = cache_cfg or CacheConfig()
        self.pcfg = prefix_cfg or PrefixConfig()
        self.rng = rng if rng is not None else jax.random.PRNGKey(0)
        self.metrics = Metrics()
        self.spans = SpanRecorder()
        # Flight recorder (``trace_cfg`` = a config.TraceConfig): a bounded
        # ring of per-tick records behind /debug/ticks. None when tracing
        # is off — step() then pays one attribute load + branch, no
        # allocation, no host sync (the DC301 decode-tick contract).
        self.flight = (
            FlightRecorder(trace_cfg.ticks_capacity)
            if trace_cfg is not None and trace_cfg.enabled
            else None
        )
        # Scheduler lock (SURVEY §5.2): slots/cache/allocator are mutated
        # only by step()/collect_finished() under this lock (single-writer).
        # submit()/cancel() are deliberately LOCK-FREE — step() holds the
        # lock across whole device steps, and request admission/cancellation
        # must not stall on that; they rely on GIL-atomic deque/dict ops and
        # state flags the scheduler observes at tick boundaries.
        self._lock = threading.Lock()
        # Deferred page-table installs: (row, slot_idx, page) triples batched
        # into ONE scatter dispatch (sequential assign_pages calls CHAIN —
        # each consumes the previous table — so a growth tick where every row
        # crosses a page boundary paid one ~35 ms tunnel round trip per row).
        self._pending_installs: List[Tuple[int, int, int]] = []

        self.batch = self.ecfg.max_batch_size
        dtype = jnp.dtype(self.ecfg.dtype)
        b, cc = self.batch, self.ccfg
        # Dispatch-shape and kernel policy is owned by the AttentionPlan
        # (engine/plan.py): it resolves use_pallas_attention's auto rule
        # (unchanged: ON for the int8 DENSE cache on a real TPU, where the
        # fused kernel measured +40% through the engine; the paged pool's
        # gathered variant WINS at MHA batch 64 but LOSES at small-batch
        # GQA, so paged DECODE keeps the XLA two-segment path), routes
        # paged multi-token rows through the ragged mixed-phase kernel on
        # TPU, and owns every prefill-family pad width below.
        self.plan = AttentionPlan(self.ecfg, self.ccfg, metrics=self.metrics)
        if mesh_cfg is not None:
            # Mesh engines keep the legacy path end to end: ring/sp prefill
            # is a different collective-bearing program and the ragged
            # kernel is single-device.
            self.plan.enabled = False
        _sel = self.plan.select()
        self._use_pallas = _sel.use_pallas
        # Sessions parked mid chunked-prefill (slot held, decode-ineligible;
        # advanced by _chunk_dispatch on the decode cadence).
        self._chunking: List[Session] = []
        self._windows: Tuple[int, ...] = ()
        # prefixstore state: host spill arena (paged + prefix_caching +
        # spill budget only) and the cumulative prompt-token reuse ratio
        # behind the prefix_hit_rate gauge.
        self._spill = None
        self._prefix_seen = 0
        self._prefix_hits = 0
        if cc.kv_quant not in (None, "int8"):
            raise ValueError(f"unknown kv_quant {cc.kv_quant!r}")
        if cc.kv_quant is not None and cc.kind not in (
            "dense", "paged", "sink"
        ):
            raise ValueError(
                f"kv_quant={cc.kv_quant!r} is only supported for the dense, "
                f"paged, and sink caches (got kind={cc.kind!r})"
            )
        if cc.prefix_caching and cc.kind != "paged":
            raise ValueError(
                f"prefix_caching requires the paged cache (got kind={cc.kind!r})"
            )
        self._latent = cfg.use_latent
        if self._latent:
            # Latent (MLA) attention stores ONE low-rank [rank + dr] vector
            # per token instead of per-head K/V — only the paged pool has
            # the plane machinery (ingest/export/CoW/spill) wired for it,
            # and the mesh programs shard per-head pools.
            if cc.kind != "paged":
                raise ValueError(
                    "ModelConfig.latent requires the paged cache "
                    f"(got kind={cc.kind!r})"
                )
            if mesh_cfg is not None:
                raise ValueError(
                    "latent KV attention is single-device only (mesh "
                    "sharding of the latent pool is not implemented)"
                )
        self.plan.latent = self._latent
        if cc.kind == "dense":
            cache_cls = (
                QuantizedDenseKVCache if cc.kv_quant == "int8" else DenseKVCache
            )
            # For the int8 cache, use_pallas_attention selects its OWN decode
            # kernel (ops/quant_attention.py — streams int8 through VMEM);
            # the flash kernel below expects bf16 K/V and would force the
            # dequantizing fallback.
            create_kw = (
                {"use_kernel": self._use_pallas}
                if cc.kv_quant == "int8" else {}
            )
            # Start at the smallest bucket; _ensure_capacity grows the buffer
            # (one pad-copy per growth) as sequences lengthen. Decode
            # bandwidth tracks the LIVE context, not max_seq_len: a padded
            # max-size buffer costs ~30% of decode throughput at 7B shapes
            # early in long-context serving. Growth re-creates buffers and
            # re-applies the mesh shardings (_reshard_cache) — under pp/dp
            # meshes too: each bucket shape compiles its own pipelined
            # executable exactly as the plain path does.
            self._windows = self._window_ladder()
            first = self._windows[0] if self._windows else self.ecfg.max_seq_len
            self.cache = cache_cls.create(
                cfg.num_layers, b, first, cfg.num_kv_heads,
                cfg.head_dim, dtype, **create_kw,
            )
            self.allocator = None
        elif cc.kind == "paged":
            # The gather path materializes [B, table_width * page_size, ...]
            # per layer, so decode traffic tracks the TABLE WIDTH, not the
            # live length. Start narrow and pad columns as sessions lengthen
            # (cheap: the table is tiny and the pool never moves);
            # max_pages_per_session is the virtual cap.
            grow_ok = mesh_cfg is None or (mesh_cfg.pp == 1 and mesh_cfg.dp == 1)
            self._windows = () if not grow_ok else self._window_ladder(
                cap=min(self.ecfg.max_seq_len,
                        cc.max_pages_per_session * cc.page_size),
                strict=False,  # a small paged capacity caps dense-tuned
                               # ladders rather than rejecting them
            )
            self._first_slots = (
                max(1, -(-self._windows[0] // cc.page_size))
                if self._windows else cc.max_pages_per_session
            )
            if self._latent:
                # One shared latent "head" per token: the pool stores the
                # fused [rank + rope_head_dim] stored form (f32, or int8 +
                # f32 scales) and the kernels decompress in place via the
                # same page-table walk (K = V = stored latent; the value
                # up-projection happens past softmax in the model).
                latent_cls = (
                    QuantizedLatentPagedKVCache
                    if cc.kv_quant == "int8" else LatentPagedKVCache
                )
                self.cache = latent_cls.create(
                    cfg.num_layers, b, cc.num_pages, cc.page_size,
                    self._first_slots, 1, cfg.latent.lat_dim,
                    use_kernel=self._use_pallas,
                    use_ragged=_sel.use_ragged,
                )
            else:
                paged_cls = (
                    QuantizedPagedKVCache
                    if cc.kv_quant == "int8" else PagedKVCache
                )
                self.cache = paged_cls.create(
                    cfg.num_layers, b, cc.num_pages, cc.page_size,
                    self._first_slots, cfg.num_kv_heads, cfg.head_dim, dtype,
                    use_kernel=self._use_pallas,
                    use_ragged=_sel.use_ragged,
                )
            self.allocator = PageAllocator(cc.num_pages)
            # Stored KV footprint per token across all layers — the number
            # the latent cache exists to shrink (bench.py --phase kvbytes
            # reads it back for the latent-vs-baseline comparison).
            self.metrics.gauge(
                "kv_bytes_per_token",
                float(sum(
                    pool.shape[0] * pool.dtype.itemsize
                    * math.prod(pool.shape[2:]) // cc.page_size
                    for pool in (
                        getattr(self.cache, f)
                        for f in type(self.cache).PLANE_FIELDS.values()
                    )
                )),
            )
            if cc.prefix_caching and self.pcfg.spill_bytes_max > 0:
                # Host-DRAM spill tier (prefixstore/): registered prefix
                # pages evicted by the refcount-aware LRU snapshot their
                # stored-form tiles into a bounded host arena instead of
                # vanishing; a later admission whose chain reaches the key
                # reloads them with one host->device copy.
                from ..prefixstore import HostSpillArena

                self._spill = HostSpillArena(self.pcfg.spill_bytes_max)
                self.allocator.on_evict = self._spill_page
            self._warm_table_write()
        elif cc.kind == "sink":
            if cc.kv_quant == "int8":
                self.cache = QuantizedSinkKVCache.create(
                    cfg.num_layers, b, cc.window_length, cc.num_sink_tokens,
                    cfg.num_kv_heads, cfg.head_dim, dtype,
                    use_kernel=self._use_pallas,
                )
            else:
                self.cache = SinkKVCache.create(
                    cfg.num_layers, b, cc.window_length, cc.num_sink_tokens,
                    cfg.num_kv_heads, cfg.head_dim, dtype,
                )
            self.allocator = None
        else:
            raise ValueError(f"unknown cache kind {cc.kind}")

        self.mesh = None
        self._use_pp = False
        self._cache_pspecs = None
        if mesh_cfg is not None:
            from ..parallel import (
                build_mesh, cache_pspecs, param_pspecs, shard_pytree,
                validate_tp,
            )

            if mesh_cfg.sp != 1:
                # sp is a PREFILL-side program (parallel/ring.py): prompts
                # past the ring threshold prefill sequence-sharded over sp,
                # then hand their KV to the (sp-replicated) decode path.
                if mesh_cfg.pp != 1:
                    raise ValueError(
                        "sp>1 ring prefill does not compose with pp serving "
                        f"(got {mesh_cfg})"
                    )
                if cc.kind not in ("dense", "paged"):
                    raise ValueError(
                        "sp>1 ring prefill requires a dense or paged cache "
                        "kind (contiguous ring KV ingest; the sink ring "
                        f"evicts on write; got kind={cc.kind!r})"
                    )
            if mesh_cfg.pp > 1 and cc.kind not in ("dense", "paged"):
                # Paged composes: the pool's layer axis leads every array, so
                # pp stages hold their own layers' pages (pipeline's
                # SHARED_FIELDS path); page-table installs already dispatch
                # the GSPMD-safe chunked DUS route under any mesh. The sink
                # ring's fused write-behind tail has no staged variant.
                raise ValueError(
                    f"pp>1 serving requires the dense or paged cache "
                    f"(got {cc.kind!r})"
                )
            if self.batch % (mesh_cfg.pp * mesh_cfg.dp) != 0:
                raise ValueError(
                    f"max_batch_size {self.batch} must divide by pp*dp = "
                    f"{mesh_cfg.pp}*{mesh_cfg.dp} (microbatch row groups)"
                )
            if mesh_cfg.pp > 1 and cfg.num_layers % mesh_cfg.pp != 0:
                raise ValueError(
                    f"num_layers {cfg.num_layers} not divisible by "
                    f"pp={mesh_cfg.pp}"
                )
            validate_tp(cfg, mesh_cfg.tp, ep=mesh_cfg.ep)
            self._use_pp = mesh_cfg.pp > 1
            self.mesh = build_mesh(mesh_cfg)
            self.params = shard_pytree(
                self.params, self.mesh, param_pspecs(self.params, self._use_pp)
            )
            self._cache_pspecs = lambda c: cache_pspecs(c, self._use_pp)
            self._shard_pytree = shard_pytree
            self.cache = shard_pytree(
                self.cache, self.mesh, self._cache_pspecs(self.cache)
            )
            self._warm_table_write()  # sharded table → new executable

        self.sessions: Dict[str, Session] = {}
        self.waiting: collections.deque[Session] = collections.deque()
        self.slots: List[Optional[str]] = [None] * self.batch



        attention = attention_fn
        if (
            attention is None
            and self._use_pallas
            and not isinstance(
                self.cache,
                (QuantizedDenseKVCache, PagedKVCache, QuantizedSinkKVCache),
            )
        ):
            # Caches with their OWN kernels (int8 dense, paged) must keep
            # attention unset: swapping in flash here would both force their
            # dequantizing/gathering fallbacks AND disable the fused tail
            # path (tail_capable requires the default attention).
            from ..ops.flash_attention import flash_attention

            attention = flash_attention  # falls back to XLA on decode shapes
        mkw = {} if attention is None else {"attention_fn": attention}
        # pp>1: batched steps run the GPipe-staged pipeline program
        # (parallel/pipeline.py). Single-row prefill cannot microbatch (one
        # row), so it keeps the plain program — GSPMD streams each pp stage's
        # layer weights to the computation, which for a once-per-admission
        # bucket-sized prefill is an acceptable ICI cost.
        batch_mkw = dict(mkw)
        if self._use_pp:
            from ..parallel.pipeline import pipeline_block_apply

            mesh = self.mesh
            pkw = dict(mkw)

            def _pp_block_fn(cfg_, layers_, x_, cache_, num_new_):
                return pipeline_block_apply(
                    cfg_, layers_, x_, cache_, num_new_, mesh, **pkw
                )

            batch_mkw["block_fn"] = _pp_block_fn

        def _prefill_row(params, tokens, cache, row, n_valid, key, sp):
            # ``row`` and ``n_valid`` are traced: one compile per prefill
            # bucket shape, not per (row, length) combination.
            sub = cache.select_row(row)
            logits, sub = llama.model_apply(
                cfg, params, tokens, sub, n_valid[None], head="last", **mkw
            )
            cache = cache.merge_row(sub, row)
            token = sample(logits[:, 0], key, sp)
            return token[0], cache

        def _prefill_row_nosample(params, tokens, cache, row, n_valid):
            """Chunked-prefill body: fill cache; head skipped entirely
            (an interior chunk samples nothing — the full-vocab matmul
            over the chunk was pure waste)."""
            sub = cache.select_row(row)
            _, sub = llama.model_apply(
                cfg, params, tokens, sub, n_valid[None], head="none", **mkw
            )
            return cache.merge_row(sub, row)

        def _prefill_rows(params, tokens, cache, rows, n_valid, key, sp):
            """Batched admission: k sessions' prompts in ONE bucketed
            dispatch over a compact k-row sub-cache (``tokens [k, S]``,
            ``rows``/``n_valid`` ``[k]`` traced — one executable per
            (k-bucket, prompt-bucket)). k sequential single-row prefills
            cost k weight sweeps at ~25% MFU each plus k tunnel round
            trips; batched rows share every weight fetch.

            This IN-PLACE form (gather rows → compute → scatter back, full
            cache in one program) is kept for the PAGED pool, whose shared
            page arrays can't live in a standalone sub-cache. Dense/sink
            kinds use the SPLIT pair below: this platform's remote compiler
            crashes on the combined program between b88×T256 (= 22.5k,
            compiles) and b96×T256 (= 24.5k, crashes) — bisected r5: the
            batched-prefill program, not the decode scan; form-independent
            (scatter, DUS-chain, no-donation all crash) —
            while the standalone-prefill + merge-only programs compile at
            every serving shape tried (b160×T256 included)."""
            sub = cache.select_rows(rows)
            logits, sub = llama.model_apply(
                cfg, params, tokens, sub, n_valid, head="last", **mkw
            )
            cache = cache.merge_rows(sub, rows)
            toks = sample(logits[:, 0], key, sp)
            return toks, cache

        def _prefill_rows_standalone(params, tokens, sub, n_valid, key, sp):
            """Split batched admission, program A: prefill into a FRESH
            compact k-row cache — no [L, B, T] array anywhere in the
            program (admission rows start at length 0, so there is nothing
            to gather). Program B (`_merge_rows_only`) scatters the result
            rows into the big cache."""
            logits, sub = llama.model_apply(
                cfg, params, tokens, sub, n_valid, head="last", **mkw
            )
            toks = sample(logits[:, 0], key, sp)
            return toks, sub

        def _merge_rows_only(cache, sub, rows):
            return cache.merge_rows(sub, rows)

        def _decode_step(params, tokens, cache, active, key, sp):
            logits, cache = llama.model_apply(
                cfg, params, tokens, cache, active.astype(jnp.int32),
                **batch_mkw,
            )
            token = sample(logits[:, 0], key, sp)
            return token, cache

        # The write-behind tail composes with tp/ep/dp sharding (its scalar
        # slot writes and flush gather partition) but not with the staged
        # pipeline program, which pp engines use per step instead. The int8
        # paged cache's tail gathers its pool once per fused window (pure
        # XLA); the bf16 paged tail still reads pages in place and requires
        # the Pallas kernel.
        tail_capable = (
            attention is None
            and not self._use_pp
            # Latent caches have no tail protocol: the tail segment would
            # re-apply RoPE to an already-decoupled stored form (tail_init
            # raises by design). They scan model_apply per step instead.
            and not isinstance(self.cache, LatentPagedKVCache)
            and (
                isinstance(
                    self.cache,
                    (DenseKVCache, QuantizedDenseKVCache,
                     QuantizedPagedKVCache, QuantizedSinkKVCache),
                )
                or (
                    isinstance(self.cache, PagedKVCache)
                    and self.cache.use_kernel
                )
            )
        )
        if tail_capable and isinstance(self.cache, QuantizedSinkKVCache):
            # The fused window must fit the ring span: a tail longer than
            # the ring would have tail tokens evicting EACH OTHER, which the
            # tail segment's prefix-validity cannot express. (The bf16 sink
            # ring is never tail-capable — it has no tail protocol.)
            k_want = (
                self.ecfg.decode_steps
                if self.ecfg.decode_steps is not None else 16
            )
            tail_capable = self.cache.ring_slots >= max(1, k_want)
        # decode_steps=None (the default) resolves to the fused fast path
        # wherever it composes: the engine should serve its best configuration
        # out of the box, not behind a flag.
        self.decode_steps = (
            self.ecfg.decode_steps
            if self.ecfg.decode_steps is not None
            else (16 if tail_capable else 1)
        )
        K = self.decode_steps

        def _decode_scan(params, tokens, cache, active, key, sp, eos_ids, budget):
            """``K`` fused decode steps in one dispatch: sampling, EOS stops,
            and per-row token budgets all carried on device. Rows that stop
            (EOS / budget) keep computing but write nothing (``num_new=0``)
            and emit ``-1``. Returns ``(emitted [K, B], cache)``.

            Dense cache kinds run the write-behind-tail fast path
            (``llama.multi_decode_apply`` — big KV buffers read-only through
            all K steps); other caches scan ``model_apply`` per step.
            """
            if tail_capable:
                def step_fn(i, logits, alive):
                    nxt = sample(logits, jax.random.fold_in(key, i), sp)
                    emitted = jnp.where(alive, nxt, -1)
                    alive = alive & (nxt != eos_ids) & (i + 1 < budget)
                    return nxt, alive.astype(jnp.int32), alive, emitted

                return llama.multi_decode_apply(
                    cfg, params, tokens, cache, K, step_fn,
                    active, active.astype(jnp.int32),
                )

            def one(carry, i):
                tok, cache, alive = carry
                logits, cache = llama.model_apply(
                    cfg, params, tok, cache, alive.astype(jnp.int32),
                    **batch_mkw,
                )
                nxt = sample(logits[:, 0], jax.random.fold_in(key, i), sp)
                emitted = jnp.where(alive, nxt, -1)
                alive = alive & (nxt != eos_ids) & (i + 1 < budget)
                return (nxt[:, None], cache, alive), emitted

            (_, cache, _), emitted = jax.lax.scan(
                one, (tokens, cache, active), jnp.arange(K)
            )
            return emitted, cache

        donate = jax.default_backend() == "tpu"
        dk = dict(donate_argnums=(2,)) if donate else {}
        self._prefill = self._with_mesh(jax.jit(_prefill_row, **dk))
        self._prefill_ns = self._with_mesh(jax.jit(_prefill_row_nosample, **dk))
        self._prefill_batch = jax.jit(_prefill_rows, **dk)
        self._prefill_batch_standalone = jax.jit(_prefill_rows_standalone, **dk)
        mdk = (
            dict(donate_argnums=(0,))
            if jax.default_backend() == "tpu" else {}
        )
        self._merge_rows_only = jax.jit(_merge_rows_only, **mdk)
        # Batched admission needs select_rows/merge_rows (gather/scatter over
        # the batch axis) and a single-device computation: a scatter over a
        # dp/pp-sharded batch aborts under GSPMD, and ring prefill is a
        # different program entirely.
        self._batch_admission = (
            self.mesh is None and hasattr(self.cache, "select_rows")
        )
        self._decode = self._with_mesh(jax.jit(_decode_step, **dk))
        self._decode_k = self._with_mesh(jax.jit(_decode_scan, **dk))

        # -- pipelined decode ticks -------------------------------------------
        # Dispatch tick N from a device-resident carry of tick N-1's final
        # tokens, THEN resolve tick N-1's emitted tokens (the host copy
        # overlaps tick N's compute). On tunneled hardware the per-tick
        # host round trip otherwise costs ~35% of serving throughput
        # (engine 1779 vs raw 2701 tok/s at the same b72 int8_kvq config).
        self._pending = None
        self._carry = None
        self._carry_ok = np.zeros(self.batch, np.bool_)
        # -- overlapped (stall-free) admission ---------------------------------
        # With a pipelined tick in flight, admission prefills DISPATCH as
        # usual (the program queues right behind the running tick — JAX
        # dispatch is async) but the host defers the blocking first-token
        # fetch: each record below holds (sessions, device tokens, skips)
        # until the next tick boundary, where the fetch rides the tick
        # resolve's device_get. The sampled tokens scatter into the carry
        # so the very next tick consumes them with NO host round trip, and
        # ``_admit_pend`` charges one conservative in-flight token per row
        # (mirroring the pipelined budget discipline). Device programs and
        # RNG order are identical to the synchronous path — token streams
        # are byte-exact with ``overlap_admission`` on or off.
        self._inflight_admits: List[Tuple[List[Session], jax.Array, List[int]]] = []
        self._admit_pend = np.zeros(self.batch, np.int32)
        # Events produced OUTSIDE step() (admit_prefilled's synchronous
        # first-token delivery happens on a gateway thread): step() drains
        # them into its own event list so streaming consumers see every
        # token through the one event channel they already poll.
        self._ext_produced: List[Tuple[str, int, bool]] = []
        # Admission-ordering hook (set_admission_order): None = FIFO.
        self._admission_order = None
        # Any tail-capable cache pipelines (dense kinds and the paged pools'
        # fused windows); the sink ring (no tail) and draft-model engines
        # keep the synchronous flow.
        self._pipelined = (
            self.ecfg.pipelined_ticks
            and K > 1
            and tail_capable
            and draft is None
        )

        def _carry_combine(fresh, carry, use_carry):
            return jnp.where(use_carry[:, None], carry, fresh)

        def _carry_merge(em_last, old, act):
            return jnp.where(act[:, None], em_last[:, None], old)

        def _carry_scatter(carry, toks, rows):
            # Overlapped admission: deferred first tokens land in the
            # pipelined carry at their rows. Padding entries use an
            # out-of-range row — the scatter drops them (same contract as
            # merge_rows).
            return carry.at[rows, 0].set(toks)

        self._carry_combine = self._with_mesh(jax.jit(_carry_combine))
        self._carry_merge = self._with_mesh(jax.jit(_carry_merge))
        self._carry_scatter = jax.jit(_carry_scatter)

        # -- ring (sequence-parallel) prefill (SURVEY §5.7) -------------------
        self._ring_prefill = None
        self._sp = 1
        if mesh_cfg is not None and mesh_cfg.sp > 1:
            from ..parallel.ring import ring_prefill

            self._sp = mesh_cfg.sp
            mesh = self.mesh

            def _ring_prefill_row(params, tokens, cache, row, n_valid, key, sp):
                """One admitted session's prompt, sequence-sharded over the
                ``sp`` ring; the resulting KV is quantized/laid out by the
                cache's ``ingest_row`` and decode proceeds identically to a
                chunked prefill."""
                logits, ks, vs = ring_prefill(
                    cfg, params, tokens, n_valid[None], mesh
                )
                sub = cache.select_row(row).ingest_row(ks, vs, n_valid)
                cache = cache.merge_row(sub, row)
                token = sample(logits[:, 0], key, sp)
                return token[0], cache

            self._ring_prefill = self._with_mesh(
                jax.jit(_ring_prefill_row, **dk)
            )

        # -- speculative decoding (draft model; BASELINE config 5) ------------
        self.draft = None
        self.spec_stats = {"proposed": 0, "accepted": 0, "steps": 0}
        if draft is not None:
            dcfg, dparams = draft
            if dcfg.vocab_size != cfg.vocab_size:
                raise ValueError("draft and target must share a vocabulary")
            if isinstance(self.cache, _SINK_KINDS):
                raise ValueError(
                    "speculative decoding needs rollback-capable caches "
                    "(dense/paged); the sink ring evicts on write"
                )
            if self.ecfg.speculative_k < 1:
                raise ValueError(
                    f"speculative_k must be >= 1 with a draft model, got "
                    f"{self.ecfg.speculative_k}"
                )
            self.draft = (dcfg, dparams)
            sk = self.ecfg.speculative_k
            self.draft_cache = DenseKVCache.create(
                dcfg.num_layers, b, self.ecfg.max_seq_len, dcfg.num_kv_heads,
                dcfg.head_dim, dtype,
            )

            def _draft_prefill_row(dp_, tokens, dcache, row, n_valid):
                sub = dcache.select_row(row)
                _, sub = llama.model_apply(
                    dcfg, dp_, tokens, sub, n_valid[None], head="none"
                )
                return dcache.merge_row(sub, row)

            def _draft_propose(dp_, tokens, dcache, active):
                """k greedy draft tokens per active row; draft cache
                advances k for active rows."""
                def one(carry, _):
                    tok, dc = carry
                    logits, dc = llama.model_apply(
                        dcfg, dp_, tok, dc, active.astype(jnp.int32)
                    )
                    nxt = jnp.argmax(logits[:, 0], -1).astype(jnp.int32)
                    return (nxt[:, None], dc), nxt

                (_, dcache), toks = jax.lax.scan(
                    one, (tokens, dcache), None, length=sk
                )
                return toks, dcache  # [k, B]

            def _draft_catchup(dp_, tokens, dcache, mask):
                _, dcache = llama.model_apply(
                    dcfg, dp_, tokens, dcache, mask.astype(jnp.int32),
                    head="none",  # cache ingest only — logits unused
                )
                return dcache

            def _verify(params_, tokens, prop, spec_mask, cache, num_new,
                        key, sp):
                """One target forward over [last, p1..pk] (speculative rows,
                num_new=k+1) and [last, pad…] (normal rows, num_new=1). The
                verify sequence is built IN-GRAPH from the draft's proposals
                so the host never has to fetch them before dispatching —
                the proposal copy overlaps the verify compute. Returns
                per-position argmax (acceptance), the position-0 sample
                (normal rows' token), and the cache (advanced per-row; the
                caller rolls speculative rows back)."""
                seq = jnp.concatenate(
                    [tokens, jnp.where(spec_mask[:, None], prop.T, 0)],
                    axis=1,
                )
                logits, cache = llama.model_apply(
                    cfg, params_, seq, cache, num_new, **batch_mkw
                )
                preds = jnp.argmax(logits, -1).astype(jnp.int32)  # [B, k+1]
                sampled = sample(logits[:, 0], key, sp)
                return preds, sampled, cache

            self._draft_prefill = jax.jit(_draft_prefill_row, **dk)
            self._draft_propose = jax.jit(_draft_propose, **dk)
            self._draft_catchup = jax.jit(_draft_catchup, **dk)
            # Donate the CACHE (position 4 in the new signature — NOT the
            # proposals, which the host fetches after dispatch).
            vdk = dict(donate_argnums=(4,)) if donate else {}
            self._verify = self._with_mesh(jax.jit(_verify, **vdk))

            # -- fused multi-round speculation --------------------------------
            # R propose→verify→accept rounds in ONE dispatch: acceptance,
            # EOS/budget stops, target-cache rollback (a per-row lengths
            # decrement — validity derives from lengths) and draft catch-up
            # all carried on device. The synchronous tick pays 2+ tunnel
            # round trips per round (~35 ms each at 7B shapes), which at the
            # latency-bound small batches speculation serves is several
            # times the round's device time. Output is bit-identical to
            # plain greedy decoding (same argmax decisions, same prefixes).
            self.spec_rounds = (
                self.ecfg.speculative_rounds
                if self.ecfg.speculative_rounds is not None
                else max(1, (self.ecfg.decode_steps or 16) // (sk + 1))
            )
            R = self.spec_rounds

            def _spec_round_fn(params_, dparams_, tokens, cache, dcache,
                               spec, active, eos_ids, budget, key, sp,
                               catch_tok, catch):
                """``R`` fused speculative rounds. Returns
                ``(pack [R, B, k+3] int32, tok_carry [B, 1],
                catch_tok [B, 1], catch [B], cache, dcache)`` — pack =
                emits (k+1 slots, -1 padded) ++ acc ++ palive per round,
                ONE array so the host pays ONE fetch (a device_get on this
                platform's tunnel costs ~180 ms regardless of size; three
                of them per tick was most of the r3 speculative path's 6x
                loss).

                ``catch_tok``/``catch`` carry the draft's PENDING catch-up
                token: on full acceptance the draft never consumed its own
                final proposal, and r4 paid a dedicated masked draft
                forward per round (~2.3 ms — a full sweep of the draft
                weights) to feed it back. Instead the NEXT round's first
                draft step consumes ``[p_k, tok]`` as a 2-position forward
                (per-row ``num_new = 1 + catch``) — the catch-up rides a
                weight sweep that was happening anyway, across dispatches
                too (the pending pair is device-carried alongside the
                token carry and returned for the next tick)."""
                b_ = tokens.shape[0]
                jidx = jnp.arange(sk + 1, dtype=jnp.int32)[None, :]

                def one_round(carry, i):
                    tok, cache, dcache, alive, used, ctok, cm = carry
                    palive = (alive & spec).astype(jnp.int32)

                    # First draft step folds the pending catch-up in:
                    # rows with cm consume [p_k, tok] (2 positions), the
                    # rest [tok, pad] (1); the next-token logits sit at
                    # position num_new-1 = cm.
                    cmi = cm.astype(jnp.int32)
                    first_seq = jnp.where(
                        cm[:, None],
                        jnp.concatenate([ctok, tok], axis=1),
                        jnp.concatenate(
                            [tok, jnp.zeros((b_, 1), jnp.int32)], axis=1
                        ),
                    )
                    lgd, dcache = llama.model_apply(
                        dcfg, dparams_, first_seq, dcache,
                        palive * (1 + cmi),
                    )
                    first_nxt = jnp.argmax(
                        jnp.take_along_axis(
                            lgd, cmi[:, None, None], axis=1
                        )[:, 0],
                        -1,
                    ).astype(jnp.int32)

                    def dstep(c2, _):
                        t2, dc = c2
                        lgd2, dc = llama.model_apply(
                            dcfg, dparams_, t2, dc, palive
                        )
                        nxt = jnp.argmax(lgd2[:, 0], -1).astype(jnp.int32)
                        return (nxt[:, None], dc), nxt

                    (_, dcache), rest = jax.lax.scan(
                        dstep, (first_nxt[:, None], dcache), None,
                        length=sk - 1,
                    )
                    prop = jnp.concatenate(
                        [first_nxt[None, :], rest], axis=0
                    )  # [k, B]
                    prop_t = prop.T  # [B, k]
                    seq = jnp.concatenate(
                        [tok, jnp.where(spec[:, None], prop_t, 0)], axis=1
                    )
                    num_new = jnp.where(
                        alive, jnp.where(spec, sk + 1, 1), 0
                    ).astype(jnp.int32)
                    lg, cache = llama.model_apply(
                        cfg, params_, seq, cache, num_new, **batch_mkw
                    )
                    preds = jnp.argmax(lg, -1).astype(jnp.int32)  # [B, k+1]
                    sampled = sample(
                        lg[:, 0], jax.random.fold_in(key, i), sp
                    )

                    agree = prop_t == preds[:, :sk]
                    acc = jnp.sum(
                        jnp.cumprod(agree.astype(jnp.int32), axis=1), axis=1
                    )  # [B] longest agreeing prefix
                    pred_at_acc = jnp.take_along_axis(
                        preds, acc[:, None], axis=1
                    )
                    prop_ext = jnp.pad(prop_t, ((0, 0), (0, 1)))
                    cand = jnp.where(
                        jidx < acc[:, None], prop_ext, pred_at_acc
                    )
                    plain = jnp.concatenate(
                        [sampled[:, None],
                         jnp.zeros((b_, sk), jnp.int32)], axis=1
                    )
                    cand = jnp.where(spec[:, None], cand, plain)

                    count = jnp.where(spec, acc + 1, 1) * alive
                    # EOS: truncate at the first emitted EOS; budget:
                    # truncate at the row's remaining token allowance.
                    iseos = cand == eos_ids[:, None]
                    first_eos = jnp.min(
                        jnp.where(iseos, jidx, sk + 2), axis=1
                    )
                    count = jnp.minimum(count, first_eos + 1)
                    rem = jnp.maximum(budget - used, 0)
                    count = jnp.minimum(count, rem)
                    hit_eos = first_eos < count
                    alive = alive & ~hit_eos & (used + count < budget)

                    # Rollback: the verify wrote num_new positions; the
                    # accepted sequence state is base + count for target
                    # AND draft (both then hold kv for [..., tok,
                    # emitted[0..count-2]]; the next round consumes
                    # emitted[count-1]).
                    cache = cache.replace(
                        lengths=cache.lengths - (num_new - count)
                    )
                    d_roll = palive * jnp.maximum(sk - count, 0)
                    dcache = dcache.replace(
                        lengths=dcache.lengths - d_roll
                    )
                    # Full acceptance: the draft never consumed its own
                    # final proposal — record it as the next round's (or
                    # next DISPATCH's) pending catch-up instead of paying a
                    # dedicated draft forward here. Inactive rows keep any
                    # pending pair untouched.
                    new_catch = (palive == 1) & (count == sk + 1)
                    new_ctok = jnp.take_along_axis(
                        cand, jnp.maximum(count - 2, 0)[:, None], axis=1
                    )
                    cm = jnp.where(palive == 1, new_catch, cm)
                    ctok = jnp.where(palive[:, None] == 1, new_ctok, ctok)

                    emit = jnp.where(jidx < count[:, None], cand, -1)
                    last = jnp.take_along_axis(
                        cand, jnp.maximum(count - 1, 0)[:, None], axis=1
                    )
                    tok = jnp.where(count[:, None] > 0, last, tok)
                    return (
                        (tok, cache, dcache, alive, used + count, ctok, cm),
                        (emit, acc, palive),
                    )

                zero = jnp.zeros((b_,), jnp.int32)
                # UNROLLED rounds: under lax.scan XLA re-stages the loop
                # bodies' small invariant operands (head scales, norms, rope
                # tables) every iteration. R is small.
                carry = (tokens, cache, dcache, active, zero, catch_tok,
                         catch)
                outs = []
                for i in range(R):
                    carry, out = one_round(carry, i)
                    outs.append(out)
                (tok, cache, dcache, _, _, catch_tok, catch) = carry
                pack = jnp.stack([
                    jnp.concatenate(
                        [emit, acc[:, None], palive[:, None]], axis=1
                    )
                    for emit, acc, palive in outs
                ])  # [R, B, k+3]
                return pack, tok, catch_tok, catch, cache, dcache

            sdk = dict(donate_argnums=(3, 4)) if donate else {}
            self._spec_rounds_fn = self._with_mesh(
                jax.jit(_spec_round_fn, **sdk)
            )
            # Pipelined speculation state: the in-flight tick's packed
            # result + bookkeeping, and the device-resident token carry
            # (tick N dispatches from tick N-1's final tokens WITHOUT
            # fetching them — the fetch overlaps tick N's compute).
            # ``_spec_catch`` is the device-carried pending draft catch-up
            # pair (token, mask) the next tick's first draft step consumes.
            self._spec_pending = None
            self._spec_carry = None
            self._spec_catch = None
            self._spec_carry_ok = np.zeros(self.batch, np.bool_)
            self._catch_combine = self._with_mesh(jax.jit(
                lambda c, u: c & u
            ))
            # Adaptive speculation (config.py): a throughput A/B controller.
            # ``mode``: "spec" | "probe_plain" | "plain" | "probe_spec".
            # Rates are measured tokens/s over windows of probe_len ticks;
            # probing the plain path is gated on the MEASURED
            # tokens-per-round EMA sagging below the break-even band (high
            # acceptance never pays the probe's mode-switch cost).
            self._spec_suspended = False
            # Injectable clock for the A/B controller — tests drive window
            # wall time deterministically instead of sleeping through it.
            self._spec_clock = time.monotonic
            self._spec_ctl = {
                "mode": "spec", "win_t0": None, "win_tok0": 0.0,
                "win_ticks": 0, "spec_rate": None, "plain_rate": None,
                "cooldown": 0, "stat0": dict(self.spec_stats),
                "tpr_ema": None,
                # Resident-set signature at the current window's start:
                # composition churn mid-window re-baselines the window
                # (ADVICE r5 — mixed-composition rates bias the A/B).
                "comp": None,
            }

    def _sink_cap(self) -> int:
        """Stream-length bound for sink sessions. The bf16 ring rotates at
        window-relative (bounded) positions, so its streams are limited only
        by the int32 ``seen`` counter; the quantized ring stores keys rotated
        at ABSOLUTE positions, whose f32 RoPE angles (``pos * inv_freq``)
        lose ~``pos * 6e-8`` rad of precision on the highest-frequency
        channel — bound streams at 2^20 tokens (~0.06 rad worst-case drift)
        rather than let attention quality decay silently."""
        return (1 << 20) if isinstance(
            self.cache, QuantizedSinkKVCache
        ) else (1 << 30)

    def _window_ladder(
        self, cap: Optional[int] = None, strict: bool = True
    ) -> Tuple[int, ...]:
        """See :func:`cache.base.window_ladder`; ``decode_windows`` is the
        custom override."""
        return window_ladder(
            cap if cap is not None else self.ecfg.max_seq_len,
            custom=self.ecfg.decode_windows, strict=strict,
        )

    def _ensure_capacity(self, needed_len: int) -> None:
        """Grow the cache's attended span to the smallest bucket covering
        ``needed_len``: dense kinds zero-pad-copy their buffers; the paged
        kind just pads TABLE columns (the pool never moves). Per-bucket
        executables compile once."""
        if not self._windows or needed_len <= self.cache.max_len:
            return
        if isinstance(self.cache, PagedKVCache):
            ps = self.ccfg.page_size
            slots_needed = -(-needed_len // ps)
            # Ladder entries never exceed max_pages_per_session * page_size
            # (the __init__ cap), so each candidate slot count is in range.
            new_slots = next(
                (-(-w // ps) for w in self._windows
                 if -(-w // ps) >= slots_needed),
                self.ccfg.max_pages_per_session,
            )
            pad = new_slots - self.cache.page_table.shape[1]
            if pad > 0:
                self.cache = self.cache.replace(page_table=jnp.pad(
                    self.cache.page_table, ((0, 0), (0, pad))
                ))
                self._reshard_cache()
                self._warm_table_write()  # new table shape → new executable
                self.metrics.counter("cache_growths")
            return
        if not isinstance(self.cache, (DenseKVCache, QuantizedDenseKVCache)):
            return
        new_t = next(
            (w for w in self._windows if w >= needed_len),
            self.ecfg.max_seq_len,
        )
        self.cache = self.cache.grow_to(new_t)
        self._reshard_cache()
        self.metrics.counter("cache_growths")

    def _warm_table_write(self) -> None:
        """Pre-compile the page-table install for the CURRENT table
        shape/sharding (a null-page write over slot (0, 0) — already 0, and
        every row's table is reset at admission anyway). Remote compiles
        cost seconds on this platform; without this the first mid-serving
        page growth after creation, a table widen, or a re-shard stalls a
        decode tick."""
        if isinstance(self.cache, PagedKVCache):
            # DISCARD the results: we only want the executables compiled;
            # the writes themselves would stomp a live row's first page
            # mapping when re-warming after a mid-serving table widen.
            self.cache.assign_pages(0, [0])
            if self._mesh_cfg is not None:
                # Mesh installs dispatch binary-decomposed run chunks:
                # warm every power-of-two length up to the table width.
                n = 2
                while n <= self.cache.page_table.shape[1]:
                    self.cache.assign_pages(0, [0] * n)
                    n *= 2
            if self._mesh_cfg is None:
                # Both batched-install pad buckets (_flush_installs) —
                # mesh engines never dispatch these (their installs stay
                # on the chained per-page path), so don't compile them.
                for pad in set(self._install_pads()):
                    self.cache.assign_pages_batch([0], [0], [0], pad_to=pad)

    def _install_pads(self) -> Tuple[int, int]:
        """(small, large) flush-pad buckets, owned by the plan: the large
        one covers a growth tick (<= one install per row) and any
        admission's prompt pages in one cached executable."""
        return self.plan.install_pads(
            self.batch, self.ccfg.max_pages_per_session
        )

    def _queue_install(self, row: int, slot_idx: int, page: int) -> None:
        """Defer a page-table install; :meth:`_flush_installs` applies every
        pending one in a single batched dispatch (mesh-sharded tables:
        one dynamic-update-slice per CONTIGUOUS per-row run — a scatter
        over a sharded table aborts under GSPMD, but chaining one dispatch
        per page paid a tunnel round trip each)."""
        self._pending_installs.append((row, slot_idx, page))

    def _flush_installs(self) -> None:
        if not self._pending_installs:
            return
        pending = self._pending_installs
        self._pending_installs = []
        if getattr(self, "mesh", None) is not None:
            # Group each row's pages into contiguous slot runs, then split
            # every run into POWER-OF-TWO chunks: one assign_pages (a DUS,
            # GSPMD-safe) per chunk. Binary decomposition keeps the set of
            # dispatched lengths to the pre-warmed {1, 2, 4, ...} ladder —
            # an arbitrary run length would compile a fresh executable per
            # length (~2 s remote stall mid-serving), and padding a run to
            # a bucket cannot work here (the DUS clamps at the table edge
            # and would shift the write window onto other slots).
            runs: List[Tuple[int, int, List[int]]] = []
            for row, slot_idx, page in pending:
                if (
                    runs
                    and runs[-1][0] == row
                    and runs[-1][1] + len(runs[-1][2]) == slot_idx
                ):
                    runs[-1][2].append(page)
                else:
                    runs.append((row, slot_idx, [page]))
            for row, start, pages in runs:
                while pages:
                    n = 1 << (len(pages).bit_length() - 1)  # largest pow2 <=
                    self.cache = self.cache.assign_pages(
                        row, pages[:n], start
                    )
                    start += n
                    pages = pages[n:]
            return
        rows = [r for r, _, _ in pending]
        slots_ = [si for _, si, _ in pending]
        pages = [p for _, _, p in pending]
        # Exactly TWO pad buckets (both pre-compiled by _warm_table_write):
        # small flushes (one admission's prompt pages) and everything else.
        # Arbitrary pow2 pads would each compile mid-serving the first time
        # a new length appeared (~2 s remote-compile stall). A flush larger
        # than the big bucket (growth tick + oversized admission backlog in
        # one tick) splits into bucket-sized chunks — each a warmed
        # executable — instead of silently compiling an unwarmed length.
        small, big = self._install_pads()
        while rows:
            n = small if len(rows) <= small else big
            self.cache = self.cache.assign_pages_batch(
                rows[:n], slots_[:n], pages[:n], pad_to=n
            )
            rows, slots_, pages = rows[n:], slots_[n:], pages[n:]

    def _reshard_cache(self) -> None:
        """Re-apply the mesh shardings after a growth/shrink re-created the
        cache buffers (new arrays come back default-sharded; leaving them so
        would silently replicate the cache and serialize every step)."""
        if self.mesh is not None:
            self.cache = self._shard_pytree(
                self.cache, self.mesh, self._cache_pspecs(self.cache)
            )

    def _with_mesh(self, fn):
        """Run a jitted step inside the mesh context when serving sharded."""
        if self.mesh is None:
            return fn

        def go(*a, **k):
            with self.mesh:
                return fn(*a, **k)

        return go

    # -- public API -----------------------------------------------------------

    def submit(
        self,
        prompt: Sequence[int],
        options: Optional[SamplingOptions] = None,
        deadline: Optional[float] = None,
        sched_key: Optional[tuple] = None,
        trace=None,
    ) -> str:
        """Queue a prompt; returns its generation_id. Thread-safe.

        ``deadline`` is an absolute ``time.monotonic()`` instant: past it the
        scheduler reaps the session like a cancel (finish_reason
        ``"deadline"``), whether it is still queued or actively decoding.

        ``sched_key`` is the gateway scheduler's admission-ordering stamp
        (see :meth:`set_admission_order`); sessions without one are
        admitted FIFO.

        ``trace`` is the request's distributed TraceContext (None for
        unsampled requests); it rides the Session for span attribution
        and never affects scheduling or tokens."""
        return self._submit_session(
            prompt, options, deadline, sched_key=sched_key, trace=trace
        ).generation_id

    def _submit_session(self, prompt, options, deadline=None,
                        sched_key=None, trace=None) -> Session:
        # Lock-free on purpose: step() holds the scheduler lock across whole
        # device steps (hundreds of ms at 7B shapes), and request-handler
        # threads must not stall on it. deque.append and dict insertion are
        # GIL-atomic; the scheduler only observes the session at its next
        # admission pass.
        if len(prompt) == 0:
            raise ValueError("empty prompt")
        s = Session(
            prompt=list(prompt),
            options=options or SamplingOptions(),
            deadline=deadline,
            sched_key=sched_key,
            trace=trace,
        )
        self.sessions[s.generation_id] = s
        self.waiting.append(s)
        self.metrics.counter("sessions_submitted")
        return s

    def set_admission_order(self, fn) -> None:
        """Install the gateway scheduler's admission-ordering hook:
        ``fn(pending_sessions) -> ordered_sessions``, called under the
        engine lock at each tick with the reaped waiting queue. The
        engine admits a PREFIX of the returned order (free slots and
        page-pool pressure permitting) instead of FIFO-popping. The hook
        must be a pure reordering — a result that drops or invents
        sessions is discarded and the tick falls back to FIFO. Ordering
        affects WHICH sessions are admitted each tick, never the tokens
        any individual session produces. ``None`` restores FIFO."""
        self._admission_order = fn

    def cancel(self, generation_id: str) -> None:
        """Thread-safe and non-blocking: sets a monotonic flag; the
        scheduler converts it to the CANCELLED state at the next tick
        boundary (state transitions stay single-writer — a direct state
        write here could race the scheduler's own WAITING→ACTIVE transition
        mid-admission and be silently stomped)."""
        s = self.sessions.get(generation_id)
        if s is None or s.state == SessionState.FINISHED:
            return
        s.cancel_requested = True

    def step(self) -> List[Tuple[str, int, bool]]:
        """One scheduler tick: admit + decode. Returns
        ``[(generation_id, token, finished), …]`` events. ``token == -1``
        signals a finish without a new token (capacity rejection/exhaustion) —
        streaming consumers must not append it.

        Pipelined engines (``EngineConfig.pipelined_ticks``) dispatch the
        next device tick BEFORE resolving the previous one, so a tick's
        tokens arrive one ``step()`` later than they were dispatched."""
        produced: List[Tuple[str, int, bool]] = []
        # Flight recorder: host-clock only (perf_counter — no device_get,
        # no block_until_ready), and None unless a TraceConfig enabled it,
        # so the disabled tick pays one attribute load + branch.
        fr = self.flight
        t0 = time.perf_counter() if fr is not None else 0.0
        queued0 = len(self.waiting) if fr is not None else 0
        with self._lock:
            if self._ext_produced:
                produced.extend(self._ext_produced)
                self._ext_produced.clear()
            if self._pipelined:
                prev = self._pending
                self._pending = self._dispatch_tick(produced, prev)
                self._resolve_pending(produced, prev)
                # Chunked-prefill co-scheduling rides BEHIND the decode
                # dispatch (device-ordered after it) and after the resolve,
                # so a final chunk's deferred first token rides the NEXT
                # tick's device_get exactly like an overlapped admission.
                self._chunk_dispatch(produced)
                self._admit(produced)
            else:
                self._admit(produced)
                self._chunk_dispatch(produced)
                if any(
                    gid is not None and not self.sessions[gid].chunking
                    for gid in self.slots
                ):
                    self._decode_tick(produced)
                elif (
                    self.draft is not None
                    and self._spec_pending is not None
                ):
                    # Every speculative session left (cancel/finish burst)
                    # with a tick in flight and nothing was admitted:
                    # _decode_tick won't run to drain it, so resolve here —
                    # otherwise has_work() reports the orphaned pending
                    # tick forever.
                    self._spec_flush(produced)
        if fr is not None:
            queued1 = len(self.waiting)
            fr.record(
                kind="pipelined" if self._pipelined else "plain",
                occupancy=sum(1 for g in self.slots if g is not None),
                queued=queued1,
                admitted=max(0, queued0 - queued1),
                chunking=len(self._chunking),
                parked=sum(
                    1 for s in self._chunking if s.parked_key is not None
                ),
                overlap_inflight=len(self._inflight_admits),
                pending=self._pending is not None,
                events=len(produced),
                dispatch=self.plan.last_dispatch,
                host_ms=(time.perf_counter() - t0) * 1e3,
            )
        return produced

    def has_work(self) -> bool:
        with self._lock:
            return (
                bool(self.waiting)
                or any(s is not None for s in self.slots)
                or self._pending is not None
                or bool(self._inflight_admits)
                or bool(self._ext_produced)
                or getattr(self, "_spec_pending", None) is not None
            )

    def active_sessions(self) -> int:
        """Resident (decoding) sessions. Lock-free snapshot for
        observability — a concurrent tick may shift it by the time the
        caller reads it."""
        return sum(1 for g in self.slots if g is not None)

    def queue_depth(self) -> int:
        """Sessions waiting for a slot. Lock-free snapshot."""
        return len(self.waiting)

    def generate(
        self,
        prompts: Sequence[Sequence[int]],
        options: Optional[SamplingOptions] = None,
        max_steps: int = 100_000,
    ) -> List[List[int]]:
        """Blocking convenience API: run all prompts to completion."""
        # Hold the Session objects themselves: a concurrent
        # collect_finished() may reap the dict entries at any point.
        subs = [self._submit_session(p, options) for p in prompts]
        for _ in range(max_steps):
            if not self.has_work():
                break
            self.step()
        return [s.generated for s in subs]

    def collect_finished(self) -> Dict[str, Session]:
        """Remove and return finished/cancelled sessions. Callers that stream
        via ``step()`` must collect periodically or host memory grows with
        total requests served."""
        with self._lock:
            # list(): submit() inserts into the dict lock-free; a snapshot
            # keeps concurrent submission from breaking this iteration.
            done = {
                gid: s
                for gid, s in list(self.sessions.items())
                if s.state in (SessionState.FINISHED, SessionState.CANCELLED)
                and s.slot is None
            }
            for gid in done:
                del self.sessions[gid]
            return done

    # -- prefix/KV reuse (prefixstore/) ---------------------------------------

    def _note_prefix(self, total: int, reused: int) -> None:
        """Uniform prefix-reuse accounting: EVERY admission path (local
        ``_admit``, disaggregated ``admit_prefilled``, spill reloads — they
        land in the shared-page count) reports through here, so the
        ``prefix_cached_tokens`` counter and the cumulative token-weighted
        ``prefix_hit_rate`` gauge cannot drift between paths."""
        self._prefix_seen += total
        self._prefix_hits += reused
        if reused:
            self.metrics.counter("prefix_cached_tokens", reused)
        if self._prefix_seen:
            self.metrics.gauge(
                "prefix_hit_rate", self._prefix_hits / self._prefix_seen
            )

    def _spill_page(self, page: int, key: bytes) -> None:
        """Allocator ``on_evict`` hook: snapshot an evicted registered
        prefix page's stored-form tiles into the host arena (runs under the
        scheduler lock, inside ``alloc``, BEFORE the page returns to the
        free list — content still valid; ``read_page`` blocks until pending
        device writes settle)."""
        tiles = self.cache.read_page(page)
        if self._spill.put(key, tiles):
            self.metrics.counter("prefix_spilled_pages")
        self.metrics.gauge("prefix_spill_bytes", float(self._spill.bytes_used))

    def _reload_spilled(self, keys, shared: List[int], cap: int) -> List[int]:
        """Extend a device-registry prefix match with host-arena reloads:
        walk ``keys[len(shared):cap]``, re-checking the registry first (a
        taken entry may have been reloaded by an earlier admission), then
        reloading arena tiles into a fresh page. A rejected (corrupted)
        entry degrades to recompute from that point — never wedges
        admission. Returned pages are referenced like ``lookup``'s."""
        while len(shared) < cap:
            key = keys[len(shared)]
            page = self.allocator.lookup_one(key)
            if page is None:
                tiles = self._spill.take(key)
                if tiles is None:
                    break
                t0 = time.perf_counter()
                try:
                    [page] = self.allocator.alloc(1)
                except MemoryError:
                    self._spill.put(key, tiles)  # park it for a calmer tick
                    break
                try:
                    self.cache = self.cache.write_page(page, tiles)
                except ValueError:
                    # Corrupted arena entry: reject BEFORE it can poison
                    # the pool; recompute covers the rest of the prompt.
                    self.allocator.free([page])
                    self.metrics.counter("prefix_reload_errors")
                    break
                self.allocator.register(page, key)
                self.metrics.counter("prefix_spill_reloads")
                self.metrics.observe(
                    "prefix_reload_ms", (time.perf_counter() - t0) * 1e3
                )
            shared.append(page)
        self.metrics.gauge("prefix_spill_bytes", float(self._spill.bytes_used))
        return shared

    def advertised_prefix_heads(self, limit: int = 1024) -> List[str]:
        """Hex chain keys this node can serve a prefix hit from — device
        registry plus spill arena — newest-biased and bounded; what the
        decode node advertises to the block directory each heartbeat."""
        if self.allocator is None or not self.ccfg.prefix_caching:
            return []
        with self._lock:
            keys = self.allocator.registered_keys(limit)
            if self._spill is not None:
                dev = set(keys)
                keys += [k for k in self._spill.keys() if k not in dev]
        return [k.hex() for k in keys[-limit:]]

    def prefix_match_tokens(self, prompt) -> int:
        """Longest locally-cached prefix of ``prompt`` in TOKENS
        (page-granular), WITHOUT taking page references — the gateway's
        routing probe for preferring a prefix-holding engine."""
        if self.allocator is None or not self.ccfg.prefix_caching:
            return 0
        ps = self.ccfg.page_size
        keys = PageAllocator.chain_keys(prompt, ps)
        matched = 0
        with self._lock:
            for key in keys:
                if self.allocator.peek(key) is None and not (
                    self._spill is not None and key in self._spill
                ):
                    break
                matched += ps
        return matched

    def export_prefix_pages(self, prompt):
        """Stored-form tiles of the longest locally cached prefix of
        ``prompt`` — device registry pages plus spill-arena entries,
        page-granular, WITHOUT taking page references — for the fleet
        page-ship path (``fleet.pages``). Returns ``(page_size, items)``
        where ``items`` is an ordered ``[(chain_key, tiles), ...]`` list
        ready for ``kv_codec.encode_pages``; empty when prefix caching
        is off or nothing matches. Tiles round-trip verbatim, so the
        importer's pages are bit-exact with this node's."""
        if self.allocator is None or not self.ccfg.prefix_caching:
            return self.ccfg.page_size, []
        ps = self.ccfg.page_size
        keys = PageAllocator.chain_keys(prompt, ps)
        items = []
        with self._lock:
            for key in keys:
                page = self.allocator.peek(key)
                if page is not None:
                    items.append((key, self.cache.read_page(page)))
                    continue
                tiles = (self._spill.peek(key)
                         if self._spill is not None else None)
                if tiles is None:
                    break
                items.append((key, tiles))
        return ps, items

    def import_prefix_pages(self, page_size: int, items) -> int:
        """Install shipped prefix pages (``kv_codec.decode_pages`` items)
        into this engine's pool: each page lands registered at refcount
        0 — immediately servable to prefix-matching admissions, evictable
        (LRU, via the spill arena when configured) under pressure, exactly
        like a page left behind by a released session. Already-resident
        keys are skipped; pool pressure parks tiles in the arena instead
        (still servable); a tile/page-shape mismatch raises ``ValueError``
        after freeing the staged page. Returns pages made servable."""
        if self.allocator is None or not self.ccfg.prefix_caching:
            return 0
        if int(page_size) != self.ccfg.page_size:
            raise ValueError(
                f"page-ship size {page_size} != pool page size "
                f"{self.ccfg.page_size}")
        installed = 0
        with self._lock:
            for key, tiles in items:
                if self.allocator.peek(key) is not None:
                    continue  # already device-resident
                if self._spill is not None and key in self._spill:
                    continue  # already arena-resident
                try:
                    [page] = self.allocator.alloc(1)
                except MemoryError:
                    if self._spill is not None and self._spill.put(key, tiles):
                        installed += 1  # servable from the arena
                        continue
                    break
                try:
                    self.cache = self.cache.write_page(page, tiles)
                except ValueError:
                    self.allocator.free([page])
                    self.metrics.counter("prefix_reload_errors")
                    raise
                self.allocator.register(page, key)
                self.allocator.free([page])  # registered, refcount 0
                installed += 1
        if installed:
            self.metrics.counter("fleet_pages_imported", installed)
        return installed

    # -- disaggregated prefill/decode (disagg/) -------------------------------

    def prefill_export(self, prompt, options=None):
        """Prefill-pool entry point: run ONE prompt's bucketed admission
        prefill on this engine, sample its first token, and export
        ``(planes, first_token, chain)`` for a remote decode pool — then
        release the row (the session never decodes here).

        ``planes`` is :meth:`export_kv_row`'s host dict; ``chain`` is the
        prompt's page-granular hash chain (``PageAllocator.chain_keys``
        over ``CacheConfig.page_size``), shipped so the importer can verify
        the KV answers the prompt it asked about. Lifecycle knobs
        (eos/max_new_tokens) are neutralized for the local run — the
        decode pool owns those decisions, and a first token that happened
        to hit eos must not finish-and-free the row before its KV is
        exported. Sampling knobs pass through untouched, so the sampled
        first token is byte-identical to a colocated engine's.

        Raises ``RuntimeError`` when admission fails (capacity rejection
        or page-pool pressure) — callers answer with an error frame and
        the gateway falls back to local prefill."""
        if isinstance(self.cache, _SINK_KINDS):
            raise ValueError(
                "disaggregated prefill unsupported for sink caches"
            )
        run_opts = dataclasses.replace(
            options or SamplingOptions(),
            max_new_tokens=1 << 30, eos_token_id=-1,
        )
        with self._lock:
            produced: List[Tuple[str, int, bool]] = []
            s = self._submit_session(prompt, run_opts)
            try:
                self._admit(produced)
                if not s.generated:
                    reason = s.finish_reason or "pool pressure"
                    raise RuntimeError(
                        f"prefill admission failed: {reason}"
                    )
                planes = self.export_kv_row(s)
                chain = PageAllocator.chain_keys(
                    s.prompt, self.ccfg.page_size
                )
                self.metrics.counter("disagg_prefills")
                return planes, s.generated[0], chain
            finally:
                if s.slot is not None:
                    s.state = SessionState.CANCELLED
                    s.finish_reason = "exported"
                    self._release(s)
                else:
                    # Capacity-rejected (already finished) or still queued
                    # under pool pressure — drop the queue entry either way.
                    try:
                        self.waiting.remove(s)
                    except ValueError:
                        pass
                self.sessions.pop(s.generation_id, None)

    def export_kv_row(self, s: Session, n: Optional[int] = None):
        """Contiguous host copies of a resident session's KV in the
        STORED representation (so a same-config importer is bit-exact):
        value planes ``[L, S, Hkv, D]`` under ``"k"``/``"v"`` — bf16 (or
        engine dtype) for value caches, int8 for quantized ones, the
        latter alongside f32 scale planes ``[L, S, Hkv]`` under
        ``"ks"``/``"vs"``. Latent (MLA) caches ship their stored form
        instead: one fused latent plane ``[L, S, 1, rank + dr]`` under
        ``"c"`` (f32, or int8 beside an f32 ``"cs"`` scale plane
        ``[L, S, 1]``) — per-head K/V are never materialized, which is
        what shrinks the disagg wire and migration checkpoints. ``S = n``
        tokens from position 0 — the default ``len(s.prompt)`` covers the
        prompt (disagg prefill export); session checkpoints pass
        ``total_len - 1`` to take the decoded tail too. Keys are
        post-RoPE, as cached. Caller holds the scheduler lock (or owns
        the engine)."""
        n = len(s.prompt) if n is None else int(n)
        cache = self.cache
        if isinstance(cache, LatentPagedKVCache):
            pages = jnp.asarray(np.asarray(s.pages, np.int32))
            a = jnp.transpose(cache.k_pages[:, pages], (0, 1, 3, 2, 4))
            a = a.reshape(a.shape[0], -1, *a.shape[3:])
            out = {"c": np.asarray(a[:, :n])}
            if isinstance(cache, QuantizedLatentPagedKVCache):
                sc = jnp.transpose(cache.cs_pages[:, pages], (0, 1, 3, 2))
                sc = sc.reshape(sc.shape[0], -1, sc.shape[3])
                out["cs"] = np.asarray(sc[:, :n])
            return out
        if isinstance(cache, PagedKVCache):
            pages = jnp.asarray(np.asarray(s.pages, np.int32))

            def vals(pool):  # [L,P,H,ps,D] -> [L,S,H,D]
                a = jnp.transpose(pool[:, pages], (0, 1, 3, 2, 4))
                a = a.reshape(a.shape[0], -1, *a.shape[3:])
                return np.asarray(a[:, :n])

            out = {"k": vals(cache.k_pages), "v": vals(cache.v_pages)}
            if isinstance(cache, QuantizedPagedKVCache):

                def scales(pool):  # [L,P,H,ps] -> [L,S,H]
                    a = jnp.transpose(pool[:, pages], (0, 1, 3, 2))
                    a = a.reshape(a.shape[0], -1, a.shape[3])
                    return np.asarray(a[:, :n])

                out["ks"] = scales(cache.ks_pages)
                out["vs"] = scales(cache.vs_pages)
            return out
        if isinstance(cache, QuantizedDenseKVCache):
            return {  # head-major [L,B,H,T,D] -> time-major [L,S,H,D]
                "k": np.asarray(jnp.swapaxes(cache.k[:, s.slot, :, :n], 1, 2)),
                "v": np.asarray(jnp.swapaxes(cache.v[:, s.slot, :, :n], 1, 2)),
                "ks": np.asarray(jnp.swapaxes(cache.ks[:, s.slot, :, :n], 1, 2)),
                "vs": np.asarray(jnp.swapaxes(cache.vs[:, s.slot, :, :n], 1, 2)),
            }
        if isinstance(cache, DenseKVCache):
            return {
                "k": np.asarray(cache.k[:, s.slot, :n]),
                "v": np.asarray(cache.v[:, s.slot, :n]),
            }
        raise ValueError(
            f"KV export unsupported for {type(cache).__name__}"
        )

    def _check_planes(self, planes, n: int):
        """Validate shipped KV planes against this cache's stored form and
        return them as device arrays with a batch-1 axis inserted (the
        shape :meth:`_ingest_row` wants). The plane-name set doubles as
        the family/quantization handshake: value caches want ``k``/``v``
        (+ ``ks``/``vs`` when int8), latent caches want ``c`` (+ ``cs``)
        — a mismatch is a structural error, never a silent reinterpret."""
        cache = self.cache
        if isinstance(cache, QuantizedLatentPagedKVCache):
            want = {"c", "cs"}
        elif isinstance(cache, LatentPagedKVCache):
            want = {"c"}
        elif isinstance(
            cache, (QuantizedPagedKVCache, QuantizedDenseKVCache)
        ):
            want = {"k", "v", "ks", "vs"}
        else:
            want = {"k", "v"}
        if set(planes) != want:
            raise ValueError(
                f"KV planes {sorted(planes)} do not match this cache "
                f"(want {sorted(want)}: cache family and quantization "
                f"must agree across pools)"
            )
        if "c" in want:
            shape = (self.cfg.num_layers, n, 1, self.cfg.latent.lat_dim)
        else:
            shape = (
                self.cfg.num_layers, n,
                self.cfg.num_kv_heads, self.cfg.head_dim,
            )
        for name in sorted(want):
            expect = shape if name in ("c", "k", "v") else shape[:3]
            got = tuple(np.asarray(planes[name]).shape)
            if got != expect:
                raise ValueError(
                    f"KV plane {name!r} shape {got} != expected {expect}"
                )
        return {name: jnp.asarray(planes[name])[:, None] for name in want}

    def _ingest_row(self, sub, dev, n: int, first_slot: int = 0):
        """Scatter validated planes (from :meth:`_check_planes`) into a
        batch-1 cache view, dispatching on the stored form."""
        cache = self.cache
        if isinstance(cache, LatentPagedKVCache):
            return sub.ingest_latent_row(dev, n, first_slot=first_slot)
        if isinstance(cache, QuantizedPagedKVCache):
            return sub.ingest_planes_row(
                dev["k"], dev["v"], dev["ks"], dev["vs"], n,
                first_slot=first_slot,
            )
        if isinstance(cache, PagedKVCache):
            return sub.ingest_row(
                dev["k"], dev["v"], n, first_slot=first_slot
            )
        if isinstance(cache, QuantizedDenseKVCache):
            return sub.ingest_planes_row(
                dev["k"], dev["v"], dev["ks"], dev["vs"], n
            )
        return sub.ingest_row(dev["k"], dev["v"], n)

    def admit_prefilled(
        self,
        prompt: Sequence[int],
        planes,
        first_token: int,
        options: Optional[SamplingOptions] = None,
        deadline: Optional[float] = None,
        trace=None,
    ) -> Optional[str]:
        """Admit a session whose prompt KV was prefilled REMOTELY: allocate
        a row (and pages), ingest the shipped planes into a batch-1 view,
        seed the prefix cache from the imported prompt pages, and enter
        decode directly — delivering ``first_token`` through the overlap
        machinery (``_defer_admit``) when a pipelined tick is in flight so
        the import never stalls it, else synchronously via the external
        event buffer ``step()`` drains.

        Returns the generation_id, or ``None`` when no slot (or page-pool
        headroom) is free right now — back-pressure the caller resolves by
        falling back to a local :meth:`submit`. Raises ``ValueError`` when
        the planes are structurally incompatible with this engine (wrong
        quantization, shape, or cache family)."""
        if isinstance(self.cache, _SINK_KINDS):
            raise ValueError(
                "disaggregated admission unsupported for sink caches"
            )
        if self.mesh is not None:
            raise ValueError("disaggregated admission is single-device only")
        if self.draft is not None:
            raise ValueError(
                "disaggregated admission incompatible with a draft model"
            )
        prompt = list(prompt)
        n = len(prompt)
        if n == 0:
            raise ValueError("empty prompt")
        dev = self._check_planes(planes, n)
        with self._lock:
            slot = next(
                (i for i in range(self.batch) if self.slots[i] is None), None
            )
            if slot is None:
                return None
            s = Session(
                prompt=prompt,
                options=options or SamplingOptions(),
                deadline=deadline,
                trace=trace,
            )
            s.disagg = True
            if not self._capacity_ok(s):
                raise ValueError(
                    "prompt exceeds this engine's per-session capacity"
                )
            self._ensure_capacity(n + 1)
            self.cache = self.cache.reset_rows(jnp.arange(self.batch) == slot)
            if isinstance(self.cache, PagedKVCache):
                ps = self.ccfg.page_size
                need = math.ceil((n + 1) / ps)
                shared: List[int] = []
                if self.ccfg.prefix_caching:
                    s.prefix_keys = PageAllocator.chain_keys(prompt, ps)
                    if self.pcfg.prefix_share:
                        # Attach locally cached prefix pages instead of
                        # re-installing the shipped copy of the same
                        # content (bit-exact either way: stored-form
                        # planes round-trip verbatim across pools). The
                        # FULL chain is eligible — first_token already
                        # rode the frame, so no last-token recompute (and
                        # no CoW) is needed here.
                        shared = self.allocator.lookup(s.prefix_keys)
                        if self._spill is not None and len(shared) < len(
                            s.prefix_keys
                        ):
                            shared = self._reload_spilled(
                                s.prefix_keys, shared, len(s.prefix_keys)
                            )
                if need - len(shared) > self.allocator.free_count:
                    if shared:
                        self.allocator.free(shared)
                    return None  # pool pressure: same signal as a full batch
                s.pages = shared + self.allocator.alloc(need - len(shared))
                shared_len = len(shared) * ps
                try:
                    for i, pg in enumerate(s.pages):
                        self._queue_install(slot, i, pg)
                    self._flush_installs()  # the ingest scatter reads the table
                    if shared_len < n:
                        sub = self.cache.select_row(slot)
                        sub = self._ingest_row(
                            sub, dev, n, first_slot=len(shared)
                        )
                        self.cache = self.cache.merge_row(sub, slot)
                    else:
                        # Whole prompt served from shared pages: nothing to
                        # ingest, just set the row's write offset.
                        self.cache = self.cache.replace(
                            lengths=self.cache.lengths.at[slot].set(n)
                        )
                    if shared:
                        self.metrics.counter(
                            "prefix_pages_shared", len(shared)
                        )
                    if self.ccfg.prefix_caching:
                        # Imported prompt pages seed the prefix cache exactly
                        # like locally prefilled ones (no-op for the shared
                        # head — those keys are already registered).
                        for i, key in enumerate(s.prefix_keys):
                            self.allocator.register(s.pages[i], key)
                        self._note_prefix(n, shared_len)
                except BaseException:
                    # The session was never published — nothing else frees
                    # these pages if the ingest/prefix path raises.
                    self.allocator.free(s.pages)
                    s.pages = []
                    s.prefix_keys = []
                    raise
            else:
                sub = self.cache.select_row(slot)
                sub = self._ingest_row(sub, dev, n)
                self.cache = self.cache.merge_row(sub, slot)
            self.sessions[s.generation_id] = s
            s.slot = slot
            s.state = SessionState.ACTIVE
            self.slots[slot] = s.generation_id
            self.metrics.counter("sessions_submitted")
            self.metrics.counter("disagg_admitted")
            # Consume the RNG split a local prefill would have spent on its
            # first-token sample: the decode-tick key sequence then matches
            # a colocated engine's byte-for-byte (sampled-parity contract).
            self._next_key()
            first = int(first_token)
            if self._overlap_ok():
                self._defer_admit(
                    [s], jnp.asarray([first], jnp.int32),
                    np.asarray([slot], np.int32), [n],
                )
            else:
                self.metrics.counter("admit_sync_sessions")
                self._finish_prefill(
                    s, first, np.asarray(prompt, np.int32),
                    self._ext_produced, n,
                )
            return s.generation_id

    # -- session checkpoint / migration (crash recovery) ----------------------

    def export_session(self, generation_id: str):
        """Snapshot a RESIDENT mid-decode session for migration to another
        engine: host KV planes for its first ``total_len - 1`` positions
        (prompt + ``generated[:-1]`` — the KV-after-decode invariant: the
        last generated token is the next decode input and has no cache
        entry yet), the generated-token tail, sampling options, and the
        engine's RNG key state, all JSON/codec-friendly (planes excepted).

        The in-flight pipelined tick (and any overlapped admissions) is
        drained first so device KV and host bookkeeping agree — drained
        tokens land in ``_ext_produced`` and reach consumers through the
        next ``step()``, so none are lost. Checkpoints therefore always
        sit on a tick boundary, which is what makes a resumed engine's
        RNG-key consumption realign with the source's (byte-exact resume
        contract; see :meth:`resume_session`).

        Returns ``None`` when the session is unknown, not resident, or
        finished during the drain (the terminal event is already on its
        way to the consumer — nothing to migrate)."""
        with self._lock:
            s = self.sessions.get(generation_id)
            if s is None or s.state != SessionState.ACTIVE:
                return None
            prev, self._pending = self._pending, None
            if prev is not None or self._inflight_admits:
                self._resolve_pending(self._ext_produced, prev)
            if s.state != SessionState.ACTIVE or s.slot is None:
                return None
            if not s.generated:
                return None  # no committed token yet — nothing to anchor on
            planes = self.export_kv_row(s, s.total_len - 1)
            snapshot = {
                "prompt": list(s.prompt),
                "generated": list(s.generated),
                "options": dataclasses.asdict(s.options),
                "rng": np.asarray(self.rng).tolist(),
                "resumes": s.resumes,
                "planes": planes,
            }
            self.metrics.counter("sessions_exported")
            return snapshot

    def resume_session(
        self,
        snapshot,
        deadline: Optional[float] = None,
        trace=None,
    ) -> Optional[str]:
        """Re-admit a session exported by :meth:`export_session` and keep
        decoding from its exact position: ingest KV for
        ``len(prompt) + len(generated) - 1`` tokens, publish the session
        with its original prompt/generated split (prefix-cache keys cover
        prompt pages only), and let the next tick feed ``last_token`` —
        no token is emitted here, decode simply continues.

        Byte-exact resume contract: when this engine is QUIET (no other
        resident/waiting sessions, no tick in flight) the snapshot's RNG
        key replaces the engine's, so with the same model/config/batch
        the continued sample stream is bit-identical to the source
        engine's — the gateway's recovery replay depends on this. On a
        busy engine the RNG is left alone (greedy streams stay exact;
        sampled ones continue from this engine's key sequence).

        Returns the new generation_id, ``None`` on slot/page pressure
        (caller retries elsewhere), and raises ``ValueError`` on
        structural mismatch (quantization/shape/cache family) or a
        snapshot that is already complete."""
        if isinstance(self.cache, _SINK_KINDS):
            raise ValueError("session resume unsupported for sink caches")
        if self.mesh is not None:
            raise ValueError("session resume is single-device only")
        if self.draft is not None:
            raise ValueError("session resume incompatible with a draft model")
        prompt = [int(t) for t in snapshot["prompt"]]
        generated = [int(t) for t in snapshot["generated"]]
        if not prompt:
            raise ValueError("empty prompt")
        if not generated:
            raise ValueError("snapshot carries no generated tokens")
        opts = snapshot.get("options")
        if isinstance(opts, dict):
            known = {f.name for f in dataclasses.fields(SamplingOptions)}
            opts = SamplingOptions(
                **{k: v for k, v in opts.items() if k in known}
            )
        options = opts or SamplingOptions()
        if len(generated) >= options.max_new_tokens:
            raise ValueError("snapshot is already at max_new_tokens")
        if options.eos_token_id >= 0 and generated[-1] == options.eos_token_id:
            raise ValueError("snapshot already ended at eos")
        planes = snapshot["planes"]
        n = len(prompt) + len(generated) - 1
        limit = (
            self.ecfg.max_seq_len
            if isinstance(self.cache, (DenseKVCache, QuantizedDenseKVCache))
            else self.ccfg.max_pages_per_session * self.ccfg.page_size
        )
        if n + 1 > limit:
            raise ValueError(
                "snapshot exceeds this engine's per-session capacity"
            )
        dev = self._check_planes(planes, n)
        with self._lock:
            slot = next(
                (i for i in range(self.batch) if self.slots[i] is None), None
            )
            if slot is None:
                return None
            quiet = (
                not self.waiting
                and not self._inflight_admits
                and self._pending is None
                and all(g is None for g in self.slots)
            )
            s = Session(
                prompt=prompt,
                options=options,
                deadline=deadline,
                generated=generated,
                trace=trace,
            )
            s.disagg = True
            s.resumes = int(snapshot.get("resumes", 0)) + 1
            self._ensure_capacity(n + 1)
            self.cache = self.cache.reset_rows(jnp.arange(self.batch) == slot)
            if isinstance(self.cache, PagedKVCache):
                ps = self.ccfg.page_size
                need = math.ceil((n + 1) / ps)
                if need > self.allocator.free_count:
                    return None  # pool pressure: same signal as a full batch
                s.pages = self.allocator.alloc(need)
                try:
                    for i, pg in enumerate(s.pages):
                        self._queue_install(slot, i, pg)
                    self._flush_installs()
                    sub = self.cache.select_row(slot)
                    sub = self._ingest_row(sub, dev, n)
                    self.cache = self.cache.merge_row(sub, slot)
                    if self.ccfg.prefix_caching:
                        # Only prompt-covered pages are content-addressable;
                        # generated-tail pages depend on sampling.
                        s.prefix_keys = PageAllocator.chain_keys(prompt, ps)
                        for i, key in enumerate(s.prefix_keys):
                            self.allocator.register(s.pages[i], key)
                except BaseException:
                    self.allocator.free(s.pages)
                    s.pages = []
                    s.prefix_keys = []
                    raise
            else:
                sub = self.cache.select_row(slot)
                sub = self._ingest_row(sub, dev, n)
                self.cache = self.cache.merge_row(sub, slot)
            self.sessions[s.generation_id] = s
            s.slot = slot
            s.state = SessionState.ACTIVE
            self.slots[slot] = s.generation_id
            self._carry_ok[slot] = False  # next tick feeds last_token fresh
            if quiet and snapshot.get("rng") is not None:
                self.rng = jnp.asarray(
                    np.asarray(snapshot["rng"], dtype=np.uint32)
                )
            self.metrics.counter("sessions_submitted")
            self.metrics.counter("sessions_resumed")
            return s.generation_id

    # -- scheduling internals -------------------------------------------------

    def _next_key(self) -> jax.Array:
        self.rng, k = jax.random.split(self.rng)
        return k

    def _bucket_for(self, n: int) -> int:
        # Still the admission-partition key in ragged mode (plan docstring:
        # partition == PRNG key order), even though pad widths differ.
        return self.plan.bucket_for(n)

    def _max_chunk(self) -> int:
        """Largest prefill chunk the cache accepts (sink ring constraint)."""
        if isinstance(self.cache, _SINK_KINDS):
            return min(
                self.ecfg.prefill_buckets[-1],
                self.ccfg.window_length - self.ccfg.num_sink_tokens,
            )
        return self.ecfg.prefill_buckets[-1]

    def _capacity_ok(self, s: Session) -> bool:
        if isinstance(self.cache, _SINK_KINDS):
            return True
        limit = (
            self.ecfg.max_seq_len
            if isinstance(self.cache, (DenseKVCache, QuantizedDenseKVCache))
            else self.ccfg.max_pages_per_session * self.ccfg.page_size
        )
        return len(s.prompt) + 1 <= limit

    def _shrink_if_idle(self) -> None:
        """With no resident sessions, re-create the dense buffer at the
        smallest bucket (nothing to copy) — one long-context session must not
        pin its high-water-mark buffer (and its decode bandwidth cost) for
        the rest of the process. Shapes revisited later hit the jit cache."""
        if not self._windows or any(g is not None for g in self.slots):
            return
        if isinstance(self.cache, PagedKVCache):
            if self.cache.page_table.shape[1] > self._first_slots:
                # With no resident sessions every row is either already
                # reset or will be reset at its next admission (stale ids
                # are masked until then) — truncating columns is free and
                # restores the narrow gather.
                self.cache = self.cache.replace(
                    page_table=self.cache.page_table[:, :self._first_slots]
                )
                self._reshard_cache()
            return
        if not isinstance(self.cache, (DenseKVCache, QuantizedDenseKVCache)):
            return
        if self.cache.max_len > self._windows[0]:
            kw = (
                {"use_kernel": self.cache.use_kernel}
                if isinstance(self.cache, QuantizedDenseKVCache) else {}
            )
            self.cache = type(self.cache).create(
                self.cfg.num_layers, self.batch, self._windows[0],
                self.cfg.num_kv_heads, self.cfg.head_dim,
                jnp.dtype(self.ecfg.dtype), **kw,
            )
            self._reshard_cache()

    def _admit(self, produced) -> None:
        # Installs queued by a tick that ended up dispatching nothing must
        # land before _shrink_if_idle can rebuild (and re-shape) the table.
        self._flush_installs()
        # Reap sessions cancelled or deadline-expired since the last tick
        # (cancel() is non-blocking and only sets the flag; deadlines are
        # observed here, at tick boundaries). Each reap emits a terminal
        # ``(gid, -1, True)`` event so streaming consumers (the HTTP
        # gateway) see every stream end.
        now = time.monotonic()
        for slot, gid in enumerate(self.slots):
            if gid is None:
                continue
            s = self.sessions[gid]
            expired = (
                not s.cancel_requested
                and s.deadline is not None
                and now >= s.deadline
            )
            if (s.cancel_requested or expired) and s.slot is not None:
                s.state = SessionState.CANCELLED
                s.finish_reason = "deadline" if expired else "cancelled"
                if expired:
                    self.metrics.counter("sessions_deadline_expired")
                self._release(s)
                produced.append((gid, -1, True))
        self._shrink_if_idle()
        admitted: List[Tuple[Session, int]] = []
        free_slots = [i for i in range(self.batch) if self.slots[i] is None]
        candidates: List[Session] = []
        if free_slots and self.waiting:
            # Reap cancelled/expired entries anywhere in the queue (the
            # FIFO path only ever saw them at the head; with ordered
            # admission a cancelled mid-queue entry must not linger just
            # because the scheduler ranks it low). Each reap emits the
            # terminal event streaming consumers are owed.
            for dropped in [
                w for w in self.waiting
                if w.cancel_requested
                or (w.deadline is not None and now >= w.deadline)
            ]:
                self.waiting.remove(dropped)
                dropped.state = SessionState.CANCELLED
                if dropped.cancel_requested:
                    dropped.finish_reason = "cancelled"
                else:
                    dropped.finish_reason = "deadline"
                    self.metrics.counter("sessions_deadline_expired")
                produced.append((dropped.generation_id, -1, True))
            candidates = list(self.waiting)
            if self._admission_order is not None and len(candidates) > 1:
                # Scheduler-ordered admission (sched/): the hook ranks the
                # pending sessions; the tick admits a prefix of its order.
                # Defensive: a result that is not a permutation of the
                # queue is discarded — a buggy policy must never lose or
                # invent sessions.
                try:
                    ordered = list(self._admission_order(candidates))
                except Exception:  # noqa: BLE001 - policy must not kill ticks
                    ordered = candidates
                if len(ordered) == len(candidates) and (
                    {id(x) for x in ordered} == {id(x) for x in candidates}
                ):
                    candidates = ordered
        if candidates and free_slots:
            # ONE capacity widen for the whole admission burst (the
            # per-session _ensure_capacity below then no-ops). An oversized
            # backlog landing on the same tick as a growth otherwise walks
            # the ladder one rung per admitted session — each rung a table
            # widen plus a _warm_table_write recompile, observed as
            # back-to-back cache_growths inside one _admit.
            needs = [
                len(c.prompt) + 1
                for c in candidates[: len(free_slots)]
                if self._capacity_ok(c)
            ]
            if needs:
                self._ensure_capacity(max(needs))
        ci = 0
        for slot in free_slots:
            if ci >= len(candidates):
                break
            s = candidates[ci]
            ci += 1
            if not self._capacity_ok(s):
                self.waiting.remove(s)
                self._finish(s, "capacity", produced)
                self.metrics.counter("sessions_rejected")
                continue
            self._ensure_capacity(len(s.prompt) + 1)
            # Reset the row BEFORE installing pages (reset wipes the row's
            # page table).
            self.cache = self.cache.reset_rows(jnp.arange(self.batch) == slot)
            if self.draft is not None:
                self.draft_cache = self.draft_cache.reset_rows(
                    jnp.arange(self.batch) == slot
                )
            shared_len = 0
            if isinstance(self.cache, PagedKVCache):
                ps = self.ccfg.page_size
                n = len(s.prompt)
                need = math.ceil((n + 1) / ps)
                shared: List[int] = []
                cow = False
                if self.ccfg.prefix_caching:
                    if s.prefix_keys is None:
                        s.prefix_keys = PageAllocator.chain_keys(s.prompt, ps)
                    # With CoW sharing every FULL prompt page is eligible:
                    # a fully-matched final page is split copy-on-write
                    # below so the last prompt token (whose logits seed the
                    # first sample) recomputes into a private copy. Without
                    # it, cap so the last token's page is never shared.
                    cap = n // ps if self.pcfg.prefix_share else (n - 1) // ps
                    shared = self.allocator.lookup(s.prefix_keys[:cap])
                    if self._spill is not None and len(shared) < cap:
                        shared = self._reload_spilled(s.prefix_keys, shared, cap)
                    # n > 1: a fully-shared 1-token prompt (page_size 1)
                    # would leave NOTHING to prefill — no logits to sample
                    # from — so it drops the match and recomputes instead.
                    cow = bool(shared) and len(shared) * ps == n and n > 1
                    if not cow and shared and len(shared) * ps == n:
                        self.allocator.free([shared.pop()])
                # CoW takes one extra page for the private copy of the
                # fully-shared final page.
                if need - len(shared) + cow > self.allocator.free_count:
                    if shared:
                        self.allocator.free(shared)  # return the refs
                    break  # pool pressure: hold the queue, retry next tick
                fresh = self.allocator.alloc(need - len(shared) + cow)
                s.pages = shared + fresh  # owned: _release frees via s
                if cow:
                    # Copy-on-write split: the write offset (skip = n-1)
                    # lands INSIDE the last shared page, so the first fresh
                    # page takes its table slot. The device copy is deferred
                    # to dispatch time (_run_prefill) — a same-tick writer's
                    # prefill must enqueue first — so the source ref is
                    # parked on s.cow_src until the copy is enqueued.
                    k = len(shared) - 1
                    s.cow_src = s.pages[k]
                    s.pages[k] = s.pages.pop(k + 1)
                    self.metrics.counter("prefix_cow_copies")
                # Queue the prompt's pages; _flush_installs applies them
                # in ONE pow2-padded scatter dispatch right before the
                # prefill (chained per-page installs paid one tunnel round
                # trip each; per-length whole-run executables paid a ~2 s
                # remote compile per new prompt page count).
                for i, pg in enumerate(s.pages):
                    self._queue_install(slot, i, pg)
                shared_len = n - 1 if cow else len(shared) * ps
                if shared_len:
                    self.cache = self.cache.replace(
                        lengths=self.cache.lengths.at[slot].set(shared_len)
                    )
                    self.metrics.counter("prefix_pages_shared", len(shared))
                if self.ccfg.prefix_caching:
                    self._note_prefix(n, shared_len)
                    if self.pcfg.prefix_share:
                        # Register-at-admission: this session's full prompt
                        # pages become shareable NOW (not at release), so
                        # concurrent sessions attach to the same device
                        # pages while the writer is still decoding. Safe:
                        # owned pages hold refs >= 1 (never evicted) and a
                        # same-tick sharer always dispatches after the
                        # writer (groups before singles; singles in
                        # admission order; a sharer has skip > 0 => single).
                        for i, key in enumerate(s.prefix_keys):
                            if i >= len(s.pages):
                                break
                            self.allocator.register(s.pages[i], key)
            self.waiting.remove(s)
            s.slot = slot
            s.state = SessionState.ACTIVE
            self.slots[slot] = s.generation_id
            admitted.append((s, shared_len))
        self._dispatch_prefills(admitted, produced)

    def _dispatch_prefills(self, admitted, produced) -> None:
        """Prefill freshly admitted sessions: same-bucket groups of >= 2
        simple prompts (no chunking, no shared-prefix skip, no ring path)
        go through ONE batched dispatch each; the rest keep the single-row
        path."""
        if not admitted:
            return
        singles: List[Tuple[Session, int]] = []
        groups: Dict[int, List[Session]] = {}
        chunk_cap = self._max_chunk()
        for s, skip in admitted:
            ring = (
                self._ring_prefill is not None
                and len(s.prompt) > self._ring_threshold()
            )
            if (
                self._batch_admission
                and skip == 0
                and not ring
                and len(s.prompt) <= chunk_cap
            ):
                groups.setdefault(
                    self._bucket_for(len(s.prompt)), []
                ).append(s)
            else:
                singles.append((s, skip))
        for bucket, group in groups.items():
            if len(group) < 2:
                singles.extend((s, 0) for s in group)
                continue
            while group:
                self._prefill_group(group[:8], bucket, produced)
                group = group[8:]
        for s, skip in singles:
            # Long greedy prompts may park for chunk/decode co-scheduling
            # instead of a monolithic synchronous prefill; _chunk_admit
            # draws the session's PRNG key HERE — the same stream position
            # the synchronous path would consume — so parking never
            # perturbs the engine's key order.
            if self._chunk_admit(s, skip):
                continue
            self._run_prefill(s, produced, skip=skip)

    def _overlap_ok(self) -> bool:
        """Overlap THIS admission with the in-flight tick? Requires the
        pipelined carry machinery (so the next tick consumes the deferred
        first token without a host fetch), a tick actually in flight
        (otherwise the synchronous path is already stall-free — there is
        nothing to overlap), a single-device engine (mesh engines keep the
        synchronous flow: ring/sp prefill is a different, collective-
        bearing program, and the same GSPMD scatter constraint that turns
        batched admission off applies to the deferred carry scatter), and
        head-room under the in-flight cap (back-pressure: an admission
        flood spills to the synchronous path instead of queueing unbounded
        prefill work on the device)."""
        if not (
            self.ecfg.overlap_admission
            and self._pipelined
            and self._pending is not None
            and self.mesh is None
        ):
            return False
        if (
            len(self._inflight_admits)
            >= max(1, self.ecfg.overlap_admission_max_inflight)
        ):
            self.metrics.counter("admit_overlap_spill")
            return False
        return True

    def _defer_admit(self, group, toks_dev, rows, skips) -> None:
        """Record an overlapped admission: the prefill (and merge) is
        already dispatched; the sampled first tokens stay device-resident.
        They scatter into the pipelined carry so the next tick consumes
        them with no host round trip; ``_admit_pend`` charges one
        conservative in-flight token per row. ``_resolve_pending`` fetches
        and delivers at the next tick boundary."""
        toks_dev = jnp.reshape(toks_dev, (-1,))
        self._carry = self._carry_scatter(
            self._carry, toks_dev, jnp.asarray(rows, jnp.int32)
        )
        now = time.monotonic()
        for s in group:
            s.prefill_inflight = True
            s.prefill_dispatch_t = now
            self._carry_ok[s.slot] = True
            self._admit_pend[s.slot] = 1
        self._inflight_admits.append((list(group), toks_dev, list(skips)))
        self.metrics.counter("admit_overlap_sessions", len(group))
        self.metrics.gauge(
            "admit_overlap_inflight", float(len(self._inflight_admits))
        )

    def _prefill_group(self, group, bucket, produced) -> None:
        """One batched prefill dispatch for <= 8 same-bucket sessions.
        Rows pad to a power of two (duplicating row 0 with ``n_valid = 0``
        — a no-write, no-deliver placeholder) so a handful of executables
        covers every admission burst."""
        self._flush_installs()
        k = len(group)
        nr = 2
        while nr < k:
            nr *= 2
        # Padding entries use an OUT-OF-RANGE row: select_rows clamps the
        # gather, merge_rows drops the write-back (duplicating a real row
        # instead makes the scatter undefined-order and can clobber it
        # with stale pre-prefill content).
        rows = np.full((nr,), self.batch, np.int32)
        n_valid = np.zeros((nr,), np.int32)
        # Ragged mode pads every group to ONE width per row count (the
        # group keeps its bucket-keyed MEMBERSHIP — that is the PRNG-key
        # partition — only the pad width changes, which parity is
        # invariant to).
        width = self.plan.group_shape(bucket, self._max_chunk())
        tokens = np.zeros((nr, width), np.int32)
        opts = [SamplingOptions()] * nr
        for i, s in enumerate(group):
            rows[i] = s.slot
            n_valid[i] = len(s.prompt)
            tokens[i, : len(s.prompt)] = s.prompt
            opts[i] = s.options
        sp = SamplingParams.stack(opts)
        self.plan.note_dispatch("prefill", (nr, width), int(n_valid.sum()))
        with self.metrics.timer("prefill"), span(
            "prefill_batch", self.spans, sessions=k,
            prompt_tokens=int(n_valid.sum()),
        ):
            sub = self._fresh_sub(nr)
            if sub is not None:
                # Split pair (see _prefill_rows_standalone): compact
                # prefill with NO big-cache arrays, then a merge-only
                # dispatch — the combined program crashes this platform's
                # remote compiler past B×T ≈ 22.5k.
                toks, sub = self._prefill_batch_standalone(
                    self.params, jnp.asarray(tokens), sub,
                    jnp.asarray(n_valid), self._next_key(), sp,
                )
                self.cache = self._merge_rows_only(
                    self.cache, sub, jnp.asarray(rows)
                )
            else:
                toks, self.cache = self._prefill_batch(
                    self.params, jnp.asarray(tokens), self.cache,
                    jnp.asarray(rows), jnp.asarray(n_valid),
                    self._next_key(), sp,
                )
            if self._overlap_ok():
                # Everything above was dispatch-only; defer the blocking
                # token fetch to the next tick boundary (it rides the tick
                # resolve's device_get) so this tick never stalls on
                # prefill completion.
                self.metrics.counter("batched_prefills", k)
                self._defer_admit(group, toks, rows, [0] * k)
                return
            toks = np.asarray(jax.device_get(toks))
        self.metrics.counter("batched_prefills", k)
        self.metrics.counter("admit_sync_sessions", k)
        for i, s in enumerate(group):
            self._finish_prefill(
                s, int(toks[i]), np.asarray(s.prompt, np.int32), produced, 0
            )

    def _fresh_sub(self, nr: int):
        """A fresh ``nr``-row cache of the serving kind/shape for the split
        batched-admission prefill, or None for kinds that must keep the
        in-place program (the paged pool's page arrays are SHARED — a
        standalone sub-cache can't hold them). Stale content is irrelevant:
        validity derives from lengths, exactly as for gathered rows."""
        c = self.cache
        cfg, dtype = self.cfg, jnp.dtype(self.ecfg.dtype)
        if isinstance(c, QuantizedDenseKVCache):
            return QuantizedDenseKVCache.create(
                cfg.num_layers, nr, c.max_len, cfg.num_kv_heads,
                cfg.head_dim, dtype, use_kernel=c.use_kernel,
            )
        if isinstance(c, DenseKVCache):
            return DenseKVCache.create(
                cfg.num_layers, nr, c.max_len, cfg.num_kv_heads,
                cfg.head_dim, dtype,
            )
        if isinstance(c, QuantizedSinkKVCache):
            return QuantizedSinkKVCache.create(
                cfg.num_layers, nr, c.window, c.num_sinks,
                cfg.num_kv_heads, cfg.head_dim, dtype,
                use_kernel=c.use_kernel,
            )
        # bf16 SinkKVCache: no select_rows/merge_rows — batch admission is
        # off for it, so no branch here (adding one would dangle on the
        # missing merge_rows the day select_rows appears).
        return None

    def _ring_threshold(self) -> int:
        thr = self.ecfg.ring_prefill_threshold
        return thr if thr is not None else self.ecfg.prefill_buckets[-1]

    def _ring_bucket(self, n: int) -> int:
        """Padded ring length for an ``n``-token prompt: the doubling ladder
        above the largest prefill bucket, capped at ``max_seq_len`` (the
        ingest crop would discard anything above it — computing attention
        over up-to-2x padding would be pure waste), rounded up to a multiple
        of the ``sp`` degree (one executable per bucket)."""
        b = self.ecfg.prefill_buckets[-1]
        while b < n:
            b *= 2
        b = min(b, max(n, self.ecfg.max_seq_len))
        return -(-b // self._sp) * self._sp

    def _run_prefill(self, s: Session, produced, skip: int = 0) -> None:
        """Chunked, bucketed prefill of one admitted session; samples the
        first generated token from the final chunk. ``skip`` tokens at the
        head are already in the cache (shared prefix pages) — the row's
        write offset (``lengths``) was set past them at admission.

        Prompts past the ring threshold on an ``sp>1`` engine prefill
        sequence-sharded over the ring instead (one dispatch for the whole
        prompt; each sp device computes ``bucket/sp`` positions)."""
        self._flush_installs()  # prefill writes through the page table
        if s.cow_src is not None:
            # Deferred copy-on-write split: enqueue the device copy of the
            # fully-shared final page into this session's private page, then
            # drop the parked source ref. Doing this HERE (not at admission)
            # puts the copy after any same-tick writer's prefill dispatch,
            # so the source page's content is settled in device order.
            ps = self.ccfg.page_size
            self.cache = self.cache.copy_page(s.pages[skip // ps], s.cow_src)
            self.allocator.free([s.cow_src])
            s.cow_src = None
        chunk_cap = self._max_chunk()
        prompt = np.asarray(s.prompt, np.int32)
        sp = SamplingParams.create(
            1, s.options.temperature, s.options.top_k, s.options.top_p
        )
        if (
            self._ring_prefill is not None
            and skip == 0
            and len(prompt) > self._ring_threshold()
        ):
            bucket = self._ring_bucket(len(prompt))
            padded = np.zeros((1, bucket), np.int32)
            padded[0, : len(prompt)] = prompt
            with self.metrics.timer("prefill"), span(
                "ring_prefill", self.spans,
                generation_id=s.generation_id, prompt_tokens=len(s.prompt),
            ):
                token, self.cache = self._ring_prefill(
                    self.params, jnp.asarray(padded), self.cache, s.slot,
                    jnp.int32(len(prompt)), self._next_key(), sp,
                )
            self.metrics.counter("ring_prefills")
            # Ring/sp prefill stays synchronous by design: it only exists
            # on mesh engines (see _overlap_ok's rationale).
            self.metrics.counter("admit_sync_sessions")
            self._finish_prefill(s, int(token), prompt, produced, skip)
            return
        offset = skip
        stride = self.plan.prefill_stride(chunk_cap)
        with self.metrics.timer("prefill"), span(
            "prefill", self.spans,
            generation_id=s.generation_id, prompt_tokens=len(s.prompt),
        ):
            while len(prompt) - offset > stride:
                chunk = prompt[offset : offset + stride]
                padded = jnp.asarray(chunk)[None, :]
                self.plan.note_dispatch("chunk", (1, stride), len(chunk))
                self.cache = self._prefill_ns(
                    self.params, padded, self.cache, s.slot, jnp.int32(len(chunk))
                )
                offset += stride
            rest = prompt[offset:]
            width = self.plan.final_shape(len(rest), chunk_cap)
            padded = np.zeros((1, width), np.int32)
            padded[0, : len(rest)] = rest
            self.plan.note_dispatch("prefill", (1, width), len(rest))
            token, self.cache = self._prefill(
                self.params, jnp.asarray(padded), self.cache, s.slot,
                jnp.int32(len(rest)), self._next_key(), sp,
            )
        if self._overlap_ok():
            # Single-row admissions defer the token fetch exactly like the
            # batched path — the chunked prefill above was dispatch-only.
            self._defer_admit([s], token, np.asarray([s.slot], np.int32),
                              [skip])
            return
        self.metrics.counter("admit_sync_sessions")
        self._finish_prefill(s, int(token), prompt, produced, skip)

    def _chunk_admit(self, s: Session, skip: int) -> bool:
        """Park an admitted long GREEDY prompt for chunk/decode
        co-scheduling instead of a monolithic synchronous prefill: the
        session holds its slot (decode-ineligible) while _chunk_dispatch
        walks the prompt one ``plan.prefill_stride`` chunk per granted
        tick beside the live decode batch. Returns False — caller runs
        the legacy path — unless eligible (ragged mode on, greedy, long
        enough, single-device, no draft, no ring path, and at least one
        OTHER live row to ride beside; alone, the standalone prefill is
        strictly better for TTFT)."""
        if self.mesh is not None or self.draft is not None:
            return False
        if not self.plan.co_schedule_ok(
            len(s.prompt) - skip, s.options.temperature, self._max_chunk()
        ):
            return False
        if (
            self._ring_prefill is not None
            and skip == 0
            and len(s.prompt) > self._ring_threshold()
        ):
            return False
        if self.ccfg.prefix_caching and self.pcfg.prefix_share:
            # Register-at-admission already made this prompt's pages
            # shareable; stretching the writes over ticks would let a later
            # admission attach to pages whose KV isn't written yet. Keep
            # the synchronous path (its writer-before-sharer dispatch
            # ordering is what makes register-at-admission safe).
            return False
        # Park only when another row is decode-LIVE (first token already
        # sampled — a same-tick co-admission that has not prefilled yet
        # does not count): alone, the standalone prefill is strictly
        # better for TTFT, and there is no decode stream to protect.
        others = any(
            gid is not None
            and gid != s.generation_id
            and not self.sessions[gid].chunking
            and self.sessions[gid].generated
            for gid in self.slots
        )
        if not others:
            return False
        if s.cow_src is not None:
            # Deferred CoW split (see _run_prefill): enqueue the device
            # copy before any chunk writes through this row.
            ps = self.ccfg.page_size
            self.cache = self.cache.copy_page(s.pages[skip // ps], s.cow_src)
            self.allocator.free([s.cow_src])
            s.cow_src = None
        s.chunking = True
        s.chunk_off = skip
        s.chunk_skip = skip
        # Draw the admission key NOW — the stream position the synchronous
        # prefill would have consumed — and park it for the final chunk's
        # sample, so co-scheduling never perturbs the engine's key order
        # (byte-exact parity with the legacy path).
        s.parked_key = self._next_key()
        self._chunking.append(s)
        return True

    def _chunk_dispatch(self, produced) -> None:
        """Advance co-scheduled chunked prefills by one chunk per granted
        tick (``plan.take_chunk_credit`` rations grants at
        ``chunk_decode_share`` against live decode; full speed when no
        decode rows remain). Interior chunks are keyless cache writes —
        the exact ``_prefill_ns`` program the legacy chunk loop runs — and
        the final chunk samples the first token with the session's parked
        admission key, entering decode via the overlap machinery when
        available."""
        if not self._chunking:
            return
        decode_active = any(
            gid is not None and not self.sessions[gid].chunking
            for gid in self.slots
        )
        if not self.plan.take_chunk_credit(decode_active):
            return
        chunk_cap = self._max_chunk()
        stride = self.plan.prefill_stride(chunk_cap)
        for s in list(self._chunking):
            if s.state is not SessionState.ACTIVE or s.slot is None:
                # A cancel/deadline reap already released the row (and
                # cleared the chunking flags) — just drop the parked entry.
                if s in self._chunking:
                    self._chunking.remove(s)
                continue
            self._flush_installs()  # chunk writes go through the table
            prompt = np.asarray(s.prompt, np.int32)
            rest = len(prompt) - s.chunk_off
            if rest > stride:
                chunk = prompt[s.chunk_off : s.chunk_off + stride]
                self.plan.note_dispatch("chunk", (1, stride), len(chunk))
                with self.metrics.timer("prefill"):
                    self.cache = self._prefill_ns(
                        self.params, jnp.asarray(chunk)[None, :],
                        self.cache, s.slot, jnp.int32(len(chunk)),
                    )
                s.chunk_off += stride
                self.plan.note_chunk_rows()
                continue
            width = self.plan.final_shape(rest, chunk_cap)
            padded = np.zeros((1, width), np.int32)
            padded[0, :rest] = prompt[s.chunk_off :]
            sp = SamplingParams.create(
                1, s.options.temperature, s.options.top_k, s.options.top_p
            )
            self.plan.note_dispatch("prefill", (1, width), rest)
            with self.metrics.timer("prefill"):
                token, self.cache = self._prefill(
                    self.params, jnp.asarray(padded), self.cache, s.slot,
                    jnp.int32(rest), s.parked_key, sp,
                )
            self.plan.note_chunk_rows()
            s.chunking = False
            s.parked_key = None
            self._chunking.remove(s)
            if self._overlap_ok():
                self._defer_admit(
                    [s], token, np.asarray([s.slot], np.int32),
                    [s.chunk_skip],
                )
                continue
            self.metrics.counter("admit_sync_sessions")
            # distcheck: host-sync-ok(final-chunk first-token fetch — the same one-per-admission sync the legacy _run_prefill path pays)
            tok = int(np.asarray(jax.device_get(token)))
            self._finish_prefill(s, tok, prompt, produced, s.chunk_skip)

    def _finish_prefill(self, s, token, prompt, produced, skip):
        self._deliver(s, int(token), produced)
        self.metrics.counter("prefill_tokens", len(s.prompt) - skip)
        if self._session_speculative(s):
            # Mirror the FULL prompt into the draft cache (no prefix sharing
            # there; proposals start right after the prompt).
            self._draft_mirror(prompt, s.slot)

    def _draft_mirror(self, tokens, slot) -> None:
        """Chunked prefill of ``tokens`` into the draft cache's ``slot`` row
        (admission-time prompt mirror AND adaptive-resume resync share this
        so their chunking can never drift apart)."""
        dparams = self.draft[1]
        cap = self.ecfg.prefill_buckets[-1]
        off = 0
        while len(tokens) - off > cap:
            chunk = tokens[off : off + cap]
            self.draft_cache = self._draft_prefill(
                dparams, jnp.asarray(chunk)[None, :], self.draft_cache,
                jnp.int32(slot), jnp.int32(len(chunk)),
            )
            off += cap
        rest = tokens[off:]
        bucket = self._bucket_for(len(rest))
        padded = np.zeros((1, bucket), np.int32)
        padded[0, : len(rest)] = rest
        self.draft_cache = self._draft_prefill(
            dparams, jnp.asarray(padded), self.draft_cache, jnp.int32(slot),
            jnp.int32(len(rest)),
        )

    def _session_wants_spec(self, s: Session) -> bool:
        return (
            self.draft is not None
            and s.options.speculative
            and s.options.temperature == 0.0
        )

    def _session_speculative(self, s: Session) -> bool:
        """Speculating NOW — wants it and the adaptive controller has not
        suspended speculation engine-wide (the greedy token streams are
        identical either way, so suspension is invisible to outputs)."""
        return self._session_wants_spec(s) and not self._spec_suspended

    # -- adaptive speculation (throughput A/B controller) ---------------------

    def _draft_resync_all(self) -> None:
        """Re-mirror every speculative session's accepted stream (prompt +
        generated[:-1]) into the draft cache — required after plain-mode
        ticks advanced sessions without the draft. One chunked draft
        prefill per session; cost ≈ one draft weight sweep per
        prefill-bucket chunk."""
        for slot, gid in enumerate(self.slots):
            if gid is None:
                continue
            s = self.sessions[gid]
            if not self._session_wants_spec(s):
                continue
            self.draft_cache = self.draft_cache.reset_rows(
                np.arange(self.batch) == slot
            )
            self._draft_mirror(list(s.prompt) + s.generated[:-1], slot)

    def _spec_suspend(self, produced) -> None:
        if self._spec_pending is not None:
            self._spec_flush(produced)
        self._spec_suspended = True

    def _spec_resume(self) -> None:
        self._draft_resync_all()
        # Fresh tokens next dispatch; the device-carried catch pair is
        # gated off with the carry (the resync already consumed everything).
        self._spec_carry_ok[:] = False
        self._spec_suspended = False

    def _decode_tokens_total(self) -> float:
        return self.metrics.get_counter("decode_tokens")

    def _spec_adapt(self, produced) -> None:
        """Windowed throughput controller (config.py's speculative_probe_*
        knobs). Measures tokens/s of the CURRENT path over windows of
        ``probe_len`` ticks; when spec-mode tokens-per-round sags below the
        break-even band it probes the plain fused path, serves whichever
        measured faster, and re-probes speculation every ``probe_period``
        ticks. Token streams are bit-identical in both modes."""
        if self.draft is None or not self.ecfg.speculative_adaptive:
            return
        c = self._spec_ctl
        nspec = sum(
            1
            for g in self.slots
            if g is not None and self._session_wants_spec(self.sessions[g])
        )
        if nspec == 0:
            # Disengaged tick (no speculative sessions resident): the next
            # engaged window must NOT span this gap's wall time or its
            # non-speculative tokens.
            c["win_t0"] = None
            return
        now = self._spec_clock()
        tokens = self._decode_tokens_total()
        comp = tuple(self.slots)
        if comp != c.get("comp"):
            # Batch composition changed mid-window (admission / finish /
            # cancel): the window's tokens/s mixes two resident sets and
            # would bias the spec-vs-plain comparison — session churn could
            # latch the wrong mode until the next probe period. Re-baseline
            # the window instead of folding it into the EMA (mirrors the
            # full-disengagement reset above).
            c["comp"] = comp
            if c["win_t0"] is not None:
                self.metrics.counter("spec_adapt_window_resets")
            c.update(win_t0=now, win_tok0=tokens, win_ticks=0,
                     stat0=dict(self.spec_stats))
            return
        if c["win_t0"] is None or c.get("skip", 0) > 0:
            # (Re-)baseline: after engagement gaps and for the first tick
            # after a mode transition — that tick absorbs the new path's
            # one-time jit compile (~minutes through the remote compiler)
            # and the transition's flushed/resynced tokens, which would
            # otherwise poison the rate EMA.
            c["skip"] = max(0, c.get("skip", 0) - 1)
            c.update(win_t0=now, win_tok0=tokens, win_ticks=0,
                     stat0=dict(self.spec_stats))
            return
        c["win_ticks"] += 1
        if c["win_ticks"] < max(2, self.ecfg.speculative_probe_len):
            return
        # Window boundary: fold this window's rate into the mode's EMA —
        # normalized PER ACTIVE SPECULATIVE ROW. The composition reset
        # above keeps ``nspec`` constant within a window, but consecutive
        # windows can still run at different speculative occupancy (a spec
        # session finished, a new one admitted between windows); comparing
        # raw batch tokens/s across them would credit occupancy to the
        # mode and latch the wrong path until the next probe.
        rate = (
            (tokens - c["win_tok0"])
            / max(now - c["win_t0"], 1e-9)
            / nspec
        )
        mode = c["mode"]
        rkey = "plain_rate" if mode in ("probe_plain", "plain") else "spec_rate"
        c[rkey] = rate if c[rkey] is None else 0.5 * c[rkey] + 0.5 * rate
        if mode in ("spec", "probe_spec"):
            steps_d = self.spec_stats["steps"] - c["stat0"]["steps"]
            if steps_d > 0:
                tpr = 1.0 + (
                    self.spec_stats["accepted"] - c["stat0"]["accepted"]
                ) / steps_d
                c["tpr_ema"] = tpr if c["tpr_ema"] is None else (
                    0.5 * c["tpr_ema"] + 0.5 * tpr
                )
        c.update(win_t0=now, win_tok0=tokens, win_ticks=0,
                 stat0=dict(self.spec_stats))
        c["cooldown"] = max(0, c["cooldown"] - 1)

        k = self.ecfg.speculative_k
        gate = (
            self.ecfg.speculative_probe_below
            if self.ecfg.speculative_probe_below is not None
            else 0.55 * (k + 1)
        )
        period_windows = max(
            1,
            self.ecfg.speculative_probe_period
            // max(2, self.ecfg.speculative_probe_len),
        )
        if mode == "spec":
            if (
                c["tpr_ema"] is not None
                and c["tpr_ema"] < gate
                and c["cooldown"] == 0
            ):
                self._spec_suspend(produced)
                c.update(mode="probe_plain", win_t0=None, skip=1)
                self.metrics.counter("spec_adapt_probes")
        elif mode == "probe_plain":
            # One full window of plain measured — decide.
            if c["plain_rate"] > (c["spec_rate"] or 0.0):
                c["mode"] = "plain"
                self.metrics.counter("spec_adapt_suspensions")
            else:
                self._spec_resume()
                c.update(mode="spec", win_t0=None, skip=1)
            c["cooldown"] = period_windows
        elif mode == "plain":
            if c["cooldown"] == 0:
                self._spec_resume()
                c.update(mode="probe_spec", win_t0=None, skip=1)
        elif mode == "probe_spec":
            if (c["spec_rate"] or 0.0) >= (c["plain_rate"] or 0.0):
                c["mode"] = "spec"
            else:
                self._spec_suspend(produced)
                c.update(mode="plain", win_t0=None, skip=1)
            c["cooldown"] = period_windows

    # -- pipelined ticks ------------------------------------------------------

    def _dispatch_tick(self, produced, prev):
        """Enqueue the next fused K-step tick using the device-resident
        token carry (tick N-1's final sampled tokens) — no host fetch on the
        input path, so the device queue never drains between ticks. Returns
        the new pending tuple (or None when nothing was dispatched).

        Budgets are CONSERVATIVE: they assume the in-flight tick (``prev``)
        delivers its full budget, so a session can never over-write its
        ``max_new_tokens`` or the buffer; a row whose conservative budget
        hits zero idles one tick (its state resolves next step) instead of
        rolling anything back."""
        K = max(1, self.decode_steps)
        if prev is not None:
            # A slot whose tenant changed since the in-flight tick was
            # dispatched (finish → admit) must not be charged the previous
            # tenant's pending budget.
            pend_b = np.where(
                np.array([g == pg for g, pg in zip(self.slots, prev[3])]),
                prev[1], 0,
            )
        else:
            pend_b = np.zeros((self.batch,), np.int32)
        if self._admit_pend.any():
            # Overlapped admissions dispatched last tick: each row's sampled
            # first token is still in flight (device-resident; this tick
            # consumes it via the carry) — charge it like in-flight tick
            # budget so max_new_tokens and capacity stay exact.
            pend_b = pend_b + self._admit_pend
        fresh = np.zeros((self.batch, 1), np.int32)
        use_carry = np.zeros((self.batch,), np.bool_)
        opts: List[SamplingOptions] = [SamplingOptions()] * self.batch
        budget = np.zeros((self.batch,), np.int32)
        paged = isinstance(self.cache, PagedKVCache)
        sink = isinstance(self.cache, _SINK_KINDS)
        for slot, gid in enumerate(self.slots):
            if gid is None:
                continue
            s = self.sessions[gid]
            if s.chunking:
                # Mid chunked-prefill: the row holds its slot (pages, table)
                # but is not decode-eligible until the final chunk samples
                # its first token — budget stays 0 so the mask excludes it.
                continue
            opts[slot] = s.options
            fresh[slot, 0] = s.last_token
            use_carry[slot] = self._carry_ok[slot]
            pend = int(pend_b[slot])
            if sink:  # the ring evicts; streams are (near-)unbounded
                cap = self._sink_cap()
            elif paged:
                cap = len(s.pages) * self.ccfg.page_size
            else:
                cap = self.ecfg.max_seq_len
            if pend == 0 and s.total_len + 1 > cap:
                if paged:
                    # One more growth attempt before declaring capacity.
                    cap = self._grow_pages(s, 1)
                if s.total_len + 1 > cap:
                    # Nothing in flight for this row and no room for one
                    # more token: the session ends here (plain-tick rule).
                    self._finish(s, "capacity", produced)
                    continue
            desired = max(0, min(
                K, s.options.max_new_tokens - len(s.generated) - pend
            ))
            if paged and desired > 0:
                # Conservative: pages must cover the in-flight tick's
                # budget AND this one.
                cap = self._grow_pages(s, pend + desired)
            budget[slot] = max(0, min(
                desired, cap - s.total_len - pend,
            ))
        active = np.array(
            [g is not None for g in self.slots], np.bool_
        ) & (budget > 0)
        if not active.any():
            return None
        if self._windows:
            self._ensure_capacity(max(
                self.sessions[g].total_len + int(pend_b[i]) + int(budget[i])
                for i, g in enumerate(self.slots) if g is not None
            ))
        sp = SamplingParams.stack(opts)
        eos_ids = np.asarray([o.eos_token_id for o in opts], np.int32)
        if self._carry is None:
            tokens_dev = jnp.asarray(fresh)
        else:
            tokens_dev = self._carry_combine(
                jnp.asarray(fresh), self._carry, jnp.asarray(use_carry)
            )
        act_dev = jnp.asarray(active)
        self._flush_installs()
        self.plan.note_dispatch("decode", (
            self.batch, K,
            self.cache.page_table.shape[1] if paged
            else int(getattr(self.cache, "max_len", 0)),
        ))
        with self.metrics.timer("decode_step"), span(
            "decode_step", self.spans, batch=int(active.sum()),
        ):
            emitted, self.cache = self._decode_k(
                self.params, tokens_dev, self.cache, act_dev,
                self._next_key(), sp, jnp.asarray(eos_ids),
                jnp.asarray(budget),
            )
        old = (
            self._carry if self._carry is not None
            else jnp.zeros((self.batch, 1), jnp.int32)
        )
        self._carry = self._carry_merge(emitted[-1], old, act_dev)
        self._carry_ok = self._carry_ok | active
        return (emitted, budget, active, list(self.slots))

    def _resolve_pending(self, produced, prev) -> None:
        """Fetch and deliver the PREVIOUS tick's tokens (the copy overlaps
        the tick just dispatched). Rows that stopped mid-tick but keep
        serving (budget exhaustion) get their device carry invalidated —
        the next dispatch feeds them the host-known last token instead.

        Overlapped admissions dispatched last step resolve here too: their
        deferred first tokens ride the SAME ``device_get`` (one tunnel
        round trip covers the tick and every pending admission — a second
        fetch would cost ~180 ms on this platform regardless of size),
        then the usual prefill bookkeeping runs. Sessions cancelled while
        their prefill was in flight drop the token (``_deliver``'s guard);
        the admission reap frees their slot and pages right after."""
        admits, self._inflight_admits = self._inflight_admits, []
        if prev is None and not admits:
            return
        fetch = [toks for _, toks, _ in admits]
        if prev is not None:
            fetch.append(prev[0])
        with self.metrics.timer("decode_resolve"):
            got = jax.device_get(fetch)
        if admits:
            self._admit_pend[:] = 0
            self.metrics.gauge("admit_overlap_inflight", 0.0)
            now = time.monotonic()
            for (group, _, skips), toks in zip(admits, got):
                toks = np.asarray(toks).reshape(-1)
                for i, s in enumerate(group):
                    s.prefill_inflight = False
                    if s.prefill_dispatch_t is not None:
                        self.metrics.observe(
                            "admit_to_merge", now - s.prefill_dispatch_t
                        )
                        s.prefill_dispatch_t = None
                    self._finish_prefill(
                        s, int(toks[i]), np.asarray(s.prompt, np.int32),
                        produced, skips[i],
                    )
        if prev is None:
            return
        emitted_dev, budget, active, gids = prev
        emitted = np.asarray(got[-1])
        delivered_total = 0
        for slot, gid in enumerate(gids):
            if gid is None or not active[slot]:
                continue
            s = self.sessions.get(gid)
            if s is None or self.slots[slot] != gid:
                continue  # cancelled/reaped since dispatch
            delivered = 0
            for i in range(int(budget[slot])):
                if s.state != SessionState.ACTIVE:
                    break
                tok = int(emitted[i, slot])
                if tok == -1:  # in-graph stop on an earlier step
                    break
                self._deliver(s, tok, produced)
                delivered += 1
            delivered_total += delivered
            if delivered < int(budget[slot]) and s.state == SessionState.ACTIVE:
                self._carry_ok[slot] = False
        self.metrics.counter("decode_tokens", delivered_total)

    def _decode_tick(self, produced) -> None:
        self._spec_adapt(produced)
        if self.draft is not None and any(
            g is not None and self._session_speculative(self.sessions[g])
            for g in self.slots
        ):
            if self.ecfg.pipelined_ticks:
                return self._speculative_rounds_tick(produced)
            return self._speculative_tick(produced)
        if self.draft is not None and self._spec_pending is not None:
            # Last speculative session retired with a tick in flight.
            self._spec_flush(produced)
        K = max(1, self.decode_steps)
        tokens = np.zeros((self.batch, 1), np.int32)
        opts: List[SamplingOptions] = [SamplingOptions()] * self.batch
        for slot, gid in enumerate(self.slots):
            if gid is None:
                continue
            s = self.sessions[gid]
            if s.chunking:  # mid chunked-prefill: not decode-eligible
                continue
            tokens[slot, 0] = s.last_token
            opts[slot] = s.options

        # Per-row token budget for this tick: how many of the K scan steps
        # may actually append (remaining max_new_tokens and cache capacity).
        budget = np.zeros((self.batch,), np.int32)

        # Paged: grow page tables to cover this tick's budget before the step.
        if isinstance(self.cache, PagedKVCache):
            for slot, gid in enumerate(self.slots):
                if gid is None:
                    continue
                s = self.sessions[gid]
                if s.chunking:
                    continue
                want = min(K, s.options.max_new_tokens - len(s.generated))
                cap = self._grow_pages_for(s, want, produced)
                if cap is None:
                    continue
                budget[slot] = min(want, cap - s.total_len)
        elif isinstance(self.cache, (DenseKVCache, QuantizedDenseKVCache)):
            for slot, gid in enumerate(self.slots):
                if gid is None:
                    continue
                s = self.sessions[gid]
                if s.chunking:
                    continue
                if s.total_len + 1 > self.ecfg.max_seq_len:
                    self._finish(s, "capacity", produced)
                    continue
                budget[slot] = min(
                    K,
                    s.options.max_new_tokens - len(s.generated),
                    self.ecfg.max_seq_len - s.total_len,
                )
        else:  # sink ring: (near-)unbounded stream
            cap = self._sink_cap()
            for slot, gid in enumerate(self.slots):
                if gid is None:
                    continue
                s = self.sessions[gid]
                if s.chunking:
                    continue
                if s.total_len + 1 > cap:
                    self._finish(s, "capacity", produced)
                    continue
                budget[slot] = min(
                    K, s.options.max_new_tokens - len(s.generated),
                    cap - s.total_len,
                )

        # Chunking rows hold slots but must NOT be decode-written (their
        # rows are mid-prefill; a decode write would land at the chunk
        # offset and corrupt the prompt KV).
        active = np.array(
            [
                self.slots[i] is not None
                and not self.sessions[self.slots[i]].chunking
                for i in range(self.batch)
            ],
            np.bool_,
        )
        if not active.any():
            return

        if self._windows:
            self._ensure_capacity(max(
                self.sessions[g].total_len + int(budget[i])
                for i, g in enumerate(self.slots) if g is not None
            ))

        sp = SamplingParams.stack(opts)
        self._flush_installs()
        self.plan.note_dispatch("decode", (
            self.batch, K,
            self.cache.page_table.shape[1]
            if isinstance(self.cache, PagedKVCache)
            else int(getattr(self.cache, "max_len", 0)),
        ))
        with self.metrics.timer("decode_step"), span(
            "decode_step", self.spans, batch=int(active.sum()),
        ):
            if K == 1:
                next_tokens, self.cache = self._decode(
                    self.params, jnp.asarray(tokens), self.cache,
                    jnp.asarray(active), self._next_key(), sp,
                )
                # distcheck: host-sync-ok(the one per-tick fetch for K=1)
                emitted = np.asarray(jax.device_get(next_tokens))[None, :]
            else:
                eos_ids = np.asarray(
                    [o.eos_token_id for o in opts], np.int32
                )
                emitted, self.cache = self._decode_k(
                    self.params, jnp.asarray(tokens), self.cache,
                    jnp.asarray(active), self._next_key(), sp,
                    jnp.asarray(eos_ids), jnp.asarray(budget),
                )
                # distcheck: host-sync-ok(the one per-tick fetch for K>1)
                emitted = np.asarray(jax.device_get(emitted))

        delivered = 0
        for slot, gid in enumerate(list(self.slots)):
            if gid is None or not active[slot]:
                continue
            s = self.sessions[gid]
            for i in range(int(budget[slot])):
                if s.state != SessionState.ACTIVE:
                    break
                self._deliver(s, int(emitted[i, slot]), produced)
                delivered += 1
        self.metrics.counter("decode_tokens", delivered)

    def _grow_pages(self, s: Session, want: int) -> int:
        """Grow ``s``'s page run to cover ``want`` more tokens (best
        effort); returns the mapped capacity. Shared by the plain,
        speculative, and pipelined ticks so the table-widen-before-assign
        invariant lives once."""
        ps = self.ccfg.page_size
        while len(s.pages) * ps < s.total_len + want:
            if (
                len(s.pages) >= self.ccfg.max_pages_per_session
                or self.allocator.free_count == 0
            ):
                break
            # Widen the page table first: the new slot index must exist
            # (a clamped update would corrupt another slot).
            self._ensure_capacity(len(s.pages) * ps + 1)
            new = self.allocator.alloc(1)
            self._queue_install(s.slot, len(s.pages), new[0])
            s.pages.extend(new)
        return len(s.pages) * ps

    def _grow_pages_for(self, s: Session, want: int, produced) -> Optional[int]:
        """:meth:`_grow_pages` plus the synchronous ticks' rule: a session
        without room for even one more token finishes (capacity)."""
        cap = self._grow_pages(s, want)
        if s.total_len + 1 > cap:
            self._finish(s, "capacity", produced)
            return None
        return cap

    def _spec_rounds_capacity_ok(self, produced, pend_b=None) -> bool:
        """The fused multi-round dispatch cannot grow pages or finish
        sessions mid-scan, so every resident session must have physical
        room for the worst case (``R * (k+1)`` positions per dispatch —
        each round's verify writes k+1 before the in-graph rollback trims
        it — PLUS the in-flight tick's worst case when pipelined). Grows
        pages/buffers up front; returns False (→ the synchronous
        per-round tick, which handles per-round growth and capacity
        degradation) when any row falls short."""
        worst = self.spec_rounds * (self.ecfg.speculative_k + 1)
        for slot, gid in enumerate(self.slots):
            if gid is None:
                continue
            s = self.sessions[gid]
            need = s.total_len + worst + (
                int(pend_b[slot]) if pend_b is not None else 0
            )
            if isinstance(self.cache, PagedKVCache):
                if self._grow_pages(s, need - s.total_len) < need:
                    return False
            else:
                if need > self.ecfg.max_seq_len:
                    return False
        if self._windows and not isinstance(self.cache, PagedKVCache):
            live = [self.sessions[g] for g in self.slots if g is not None]
            if live:
                self._ensure_capacity(
                    max(s.total_len for s in live) + worst + (
                        int(pend_b.max()) if pend_b is not None else 0
                    )
                )
        return True

    def _speculative_rounds_tick(self, produced) -> None:
        """Fused, PIPELINED speculation: each ``step()`` dispatches
        ``spec_rounds`` propose→verify→accept rounds in ONE device call
        (see ``_spec_round_fn``), from a device-resident token carry, and
        THEN resolves the previous tick's packed result — so the ~180 ms
        tunnel fetch overlaps the new tick's compute. Token streams are
        identical to the synchronous ``_speculative_tick`` (same greedy
        acceptance rule); events arrive one ``step()`` later."""
        prev = self._spec_pending
        if not self._spec_rounds_capacity_ok(produced, self._spec_pend(prev)):
            # Drain the pipeline FIRST (exactly once), then degrade to the
            # synchronous per-round tick, which handles per-round growth
            # and capacity session finishes.
            self._spec_flush(produced)
            return self._speculative_tick(produced)
        self._spec_pending = self._spec_dispatch(produced, prev)
        self._spec_resolve(produced, prev)

    def _spec_pend(self, prev):
        """Conservative in-flight token charge per slot (0 where the slot's
        tenant changed since dispatch)."""
        if prev is None:
            return np.zeros((self.batch,), np.int32)
        return np.where(
            np.array([g == pg for g, pg in zip(self.slots, prev[4])]),
            prev[3], 0,
        )

    def _spec_flush(self, produced) -> None:
        """Resolve any in-flight speculative tick (pipeline drain — used
        before falling back to the synchronous path)."""
        prev = self._spec_pending
        self._spec_pending = None
        self._spec_resolve(produced, prev)

    def _spec_dispatch(self, produced, prev):
        """Enqueue one fused multi-round speculative tick; returns the
        pending tuple (or None). Budgets are conservative against the
        in-flight tick (``prev``), mirroring ``_dispatch_tick``."""
        k = self.ecfg.speculative_k
        R = self.spec_rounds
        b = self.batch
        pend_b = self._spec_pend(prev)
        fresh = np.zeros((b, 1), np.int32)
        use_carry = np.zeros((b,), np.bool_)
        opts: List[SamplingOptions] = [SamplingOptions()] * b
        spec = np.zeros((b,), np.bool_)
        budget = np.zeros((b,), np.int32)
        for slot, gid in enumerate(self.slots):
            if gid is None:
                continue
            s = self.sessions[gid]
            fresh[slot, 0] = s.last_token
            use_carry[slot] = self._spec_carry_ok[slot]
            opts[slot] = s.options
            spec[slot] = self._session_speculative(s)
            budget[slot] = max(
                0,
                s.options.max_new_tokens - len(s.generated)
                - int(pend_b[slot]),
            )
        active = np.array(
            [g is not None for g in self.slots], np.bool_
        ) & (budget > 0)
        if not active.any():
            return None
        sp = SamplingParams.stack(opts)
        eos_ids = np.asarray([o.eos_token_id for o in opts], np.int32)
        if self._spec_carry is None:
            tokens_dev = jnp.asarray(fresh)
        else:
            tokens_dev = self._carry_combine(
                jnp.asarray(fresh), self._spec_carry,
                jnp.asarray(use_carry),
            )
        if self._spec_catch is None:
            ctok_dev = jnp.zeros((b, 1), jnp.int32)
            cmask_dev = jnp.zeros((b,), jnp.bool_)
        else:
            ctok_dev, cmask_dev = self._spec_catch
            # Rows whose carry is invalid (fresh admissions) also have a
            # freshly prefilled draft cache — no pending catch-up.
            cmask_dev = self._catch_combine(
                cmask_dev, jnp.asarray(use_carry)
            )
        self._flush_installs()
        with self.metrics.timer("decode_step"), span(
            "speculative_rounds", self.spans, batch=int(active.sum()),
        ):
            pack_d, tok_d, ctok_d, cmask_d, self.cache, self.draft_cache = (
                self._spec_rounds_fn(
                    self.params, self.draft[1], tokens_dev,
                    self.cache, self.draft_cache, jnp.asarray(spec),
                    jnp.asarray(active), jnp.asarray(eos_ids),
                    jnp.asarray(budget), self._next_key(), sp,
                    ctok_dev, cmask_dev,
                )
            )
        self._spec_carry = tok_d
        self._spec_catch = (ctok_d, cmask_d)
        self._spec_carry_ok = self._spec_carry_ok | active
        # Conservative in-flight charge: the tick can deliver at most
        # min(R*(k+1), budget) per row.
        pend = np.minimum(R * (k + 1), budget).astype(np.int32) * active
        return (pack_d, active, spec, pend, list(self.slots))

    def _spec_resolve(self, produced, prev) -> None:
        """Fetch and deliver the previous speculative tick's tokens (the
        packed single-array copy overlaps the tick just dispatched)."""
        if prev is None:
            return
        pack_d, active, spec, _pend, gids = prev
        k = self.ecfg.speculative_k
        with self.metrics.timer("decode_resolve"):
            # distcheck: host-sync-ok(deferred-fetch: overlaps next dispatch)
            pack = np.asarray(jax.device_get(pack_d))  # [R, B, k+3]
        emits = pack[:, :, : k + 1]
        accs = pack[:, :, k + 1]
        palive = pack[:, :, k + 2]
        delivered_total = 0
        for slot, gid in enumerate(gids):
            if gid is None or not active[slot]:
                continue
            s = self.sessions.get(gid)
            if s is None or self.slots[slot] != gid:
                continue  # cancelled/reaped since dispatch
            emitted_in_graph = int((emits[:, slot] != -1).sum())
            delivered = 0
            for r in range(emits.shape[0]):
                for j in range(k + 1):
                    if s.state != SessionState.ACTIVE:
                        break
                    tok = int(emits[r, slot, j])
                    if tok == -1:
                        break
                    self._deliver(s, tok, produced)
                    delivered += 1
            delivered_total += delivered
            if delivered < emitted_in_graph:
                # Host-side stop mid-pack: the device carry token sits
                # beyond the session's true last token.
                self._spec_carry_ok[slot] = False
            if spec[slot]:
                rounds_run = int(palive[:, slot].sum())
                self.spec_stats["proposed"] += k * rounds_run
                self.spec_stats["accepted"] += int(
                    (accs[:, slot] * palive[:, slot]).sum()
                )
                self.spec_stats["steps"] += rounds_run
        self.metrics.counter("decode_tokens", delivered_total)

    def _speculative_tick(self, produced) -> None:
        """Draft-propose + ONE-forward verify (greedy speculation): the
        target checks all k proposals in a single k+1-position step — k
        sequential HBM sweeps become one on the bandwidth-bound decode path.
        Acceptance = longest agreeing argmax prefix + the target's own token
        at the first disagreement, so output is IDENTICAL to plain greedy
        decoding. Normal (non-speculative) sessions ride the same verify
        step as a 1-token decode via per-row ``num_new``; cache rollback is
        a per-row ``lengths`` decrement (validity derives from lengths)."""
        k = self.ecfg.speculative_k
        b = self.batch
        tokens = np.zeros((b, 1), np.int32)
        opts: List[SamplingOptions] = [SamplingOptions()] * b
        spec = np.zeros((b,), np.bool_)
        for slot, gid in enumerate(self.slots):
            if gid is None:
                continue
            s = self.sessions[gid]
            tokens[slot, 0] = s.last_token
            opts[slot] = s.options
            spec[slot] = self._session_speculative(s)

        # Capacity: speculative rows need k+1 positions this tick, normal
        # rows 1; a row short of k+1 (but not of 1) decodes plainly (the
        # draft is caught up below so speculation can resume in sync).
        if isinstance(self.cache, PagedKVCache):
            for slot, gid in enumerate(self.slots):
                if gid is None:
                    continue
                s = self.sessions[gid]
                cap = self._grow_pages_for(
                    s, (k + 1) if spec[slot] else 1, produced
                )
                if cap is None:
                    continue
                if spec[slot] and s.total_len + k + 1 > cap:
                    spec[slot] = False
        else:
            for slot, gid in enumerate(self.slots):
                if gid is None:
                    continue
                s = self.sessions[gid]
                if s.total_len + 1 > self.ecfg.max_seq_len:
                    self._finish(s, "capacity", produced)
                    continue
                if spec[slot] and s.total_len + k + 1 > self.ecfg.max_seq_len:
                    spec[slot] = False

        active = np.array([g is not None for g in self.slots], np.bool_)
        if not active.any():
            return
        if self._windows:
            self._ensure_capacity(max(
                self.sessions[g].total_len + ((k + 1) if spec[i] else 1)
                for i, g in enumerate(self.slots) if g is not None
            ))

        dparams = self.draft[1]
        if (active & spec).any():
            prop_d, self.draft_cache = self._draft_propose(
                dparams, jnp.asarray(tokens), self.draft_cache,
                jnp.asarray(active & spec),
            )
        else:
            # Every speculative row was capacity-disabled this tick: skip
            # the k draft forwards (the verify below degrades to a plain
            # batched decode with k unused positions).
            prop_d = jnp.zeros((k, b), jnp.int32)

        num_new = np.where(active, np.where(spec, k + 1, 1), 0).astype(
            np.int32
        )
        sp = SamplingParams.stack(opts)
        self._flush_installs()
        with self.metrics.timer("decode_step"), span(
            "speculative_step", self.spans, batch=int(active.sum()),
        ):
            preds_d, sampled_d, self.cache = self._verify(
                self.params, jnp.asarray(tokens), prop_d, jnp.asarray(spec),
                self.cache, jnp.asarray(num_new), self._next_key(), sp,
            )
        # Fetch the proposals AFTER dispatching verify: the copy overlaps
        # the target's k+1-position forward instead of serializing before it.
        # distcheck: host-sync-ok(post-verify fetch overlaps the forward)
        prop = np.asarray(jax.device_get(prop_d)).T  # [B, k]
        # distcheck: host-sync-ok(post-verify fetch overlaps the forward)
        preds = np.asarray(jax.device_get(preds_d))
        # distcheck: host-sync-ok(post-verify fetch overlaps the forward)
        sampled = np.asarray(jax.device_get(sampled_d))

        rollback = np.zeros((b,), np.int32)
        d_rollback = np.zeros((b,), np.int32)
        catch_mask = np.zeros((b,), np.int32)
        catch_tok = np.zeros((b, 1), np.int32)
        delivered = 0
        for slot, gid in enumerate(list(self.slots)):
            if gid is None or not active[slot]:
                continue
            s = self.sessions[gid]
            if spec[slot]:
                a = 0
                while a < k and prop[slot, a] == preds[slot, a]:
                    a += 1
                emitted = [int(t) for t in prop[slot, :a]]
                emitted.append(int(preds[slot, a]) if a < k
                               else int(preds[slot, k]))
                rollback[slot] = k - a
                if a == k:
                    # Full acceptance: the draft never consumed its own
                    # final proposal — catch it up below.
                    catch_mask[slot] = 1
                    catch_tok[slot, 0] = prop[slot, -1]
                else:
                    d_rollback[slot] = k - a - 1
                self.spec_stats["proposed"] += k
                self.spec_stats["accepted"] += a
                self.spec_stats["steps"] += 1
            else:
                emitted = [int(sampled[slot])]
            for t in emitted:
                if s.state != SessionState.ACTIVE:
                    break
                self._deliver(s, t, produced)
                delivered += 1
            if (
                not spec[slot]
                and self._session_speculative(s)
                and s.state == SessionState.ACTIVE
            ):
                # A speculative session that decoded plainly this tick
                # (capacity pressure): its draft cache did not see the
                # consumed token — catch it up, or every later proposal is
                # positionally garbage (speculation cost with ~0 acceptance).
                catch_mask[slot] = 1
                catch_tok[slot, 0] = tokens[slot, 0]
        self.metrics.counter("decode_tokens", delivered)

        # Roll lengths back to the true sequence (rejected positions become
        # invisible). The draft over-ran by k-a-1 on partial acceptance.
        if rollback.any():
            self.cache = self.cache.replace(
                lengths=self.cache.lengths - jnp.asarray(rollback)
            )
        if d_rollback.any():
            self.draft_cache = self.draft_cache.replace(
                lengths=self.draft_cache.lengths - jnp.asarray(d_rollback)
            )
        if catch_mask.any():
            self.draft_cache = self._draft_catchup(
                dparams, jnp.asarray(catch_tok), self.draft_cache,
                jnp.asarray(catch_mask),
            )

    def _deliver(self, s: Session, token: int, produced) -> None:
        if s.cancel_requested or s.state == SessionState.CANCELLED:
            return  # cancelled mid-step; the scheduler reaps the slot next tick
        s.record_token(token)
        done_eos = token == s.options.eos_token_id
        done_len = len(s.generated) >= s.options.max_new_tokens
        if done_eos or done_len:
            self._finish(s, "eos" if done_eos else "length", produced, token_emitted=token)
        else:
            produced.append((s.generation_id, token, False))

    def _finish(self, s: Session, reason: str, produced, token_emitted=None) -> None:
        s.state = SessionState.FINISHED
        s.finish_reason = reason
        s.finish_time = time.monotonic()
        # -1 = finish without a new token (the last real token was already
        # streamed on a prior step); consumers must not append it.
        produced.append(
            (s.generation_id, token_emitted if token_emitted is not None else -1, True)
        )
        self._release(s)
        self.metrics.counter("sessions_finished")

    def _release(self, s: Session) -> None:
        # A session reaped mid chunked-prefill has INCOMPLETE prompt KV
        # (only chunk_off tokens written): its pages must not be registered
        # as shareable prefix content below.
        partial = s.chunking
        if partial:
            s.chunking = False
            s.parked_key = None
        if s in self._chunking:
            self._chunking.remove(s)
        if s.slot is not None:
            self.slots[s.slot] = None
            # The device carry holds THIS session's last token; the slot's
            # next tenant must be fed its own fresh token.
            self._carry_ok[s.slot] = False
            if self.draft is not None:
                self._spec_carry_ok[s.slot] = False
            s.slot = None
        if isinstance(self.cache, PagedKVCache) and s.cow_src is not None:
            # Parked copy-on-write source ref (normally dropped when
            # _run_prefill enqueues the copy) — leak-proof the teardown.
            self.allocator.free([s.cow_src])
            s.cow_src = None
        if isinstance(self.cache, PagedKVCache) and s.pages:
            if self.ccfg.prefix_caching and not partial:
                # Content-address the pages fully covered by PROMPT tokens so
                # later sessions with the same prefix reuse their KV. Pages
                # touching generated tokens are position-pure too, but their
                # content depends on sampling — only prompt pages are shared.
                ps = self.ccfg.page_size
                if s.prefix_keys is None:
                    s.prefix_keys = PageAllocator.chain_keys(s.prompt, ps)
                for i, key in enumerate(s.prefix_keys):
                    if i < len(s.pages):
                        self.allocator.register(s.pages[i], key)
            self.allocator.free(s.pages)
            s.pages = []
