"""Host-side session bookkeeping.

A session is one generation stream — the durable identity behind the
reference's ``generation_id`` threading
(``/root/reference/distributed_llm_inference/models/llama/model.py:27`` →
``modules.py:39`` → ``cache.py:74``). Device state is integer-slot-indexed
(batch row, page table); everything string-keyed lives here on the host.
"""

from __future__ import annotations

import dataclasses
import enum
import itertools
import time
from typing import Any, List, Optional

from .sampling import SamplingOptions

_ids = itertools.count()


class SessionState(enum.Enum):
    WAITING = "waiting"
    ACTIVE = "active"
    FINISHED = "finished"
    CANCELLED = "cancelled"


@dataclasses.dataclass
class Session:
    prompt: List[int]
    options: SamplingOptions
    generation_id: str = dataclasses.field(
        default_factory=lambda: f"gen-{next(_ids)}"
    )
    state: SessionState = SessionState.WAITING
    # Set (only ever False→True) by cancel() from any thread; the scheduler
    # converts it to the CANCELLED state at tick boundaries. A plain state
    # write from cancel() could be stomped by the scheduler's own
    # WAITING→ACTIVE transition mid-admission.
    cancel_requested: bool = False
    slot: Optional[int] = None
    # Absolute time.monotonic() budget: past it the scheduler reaps the
    # session at the next tick boundary exactly like a cancel (the serving
    # gateway's per-request deadline — abandoned requests must not keep
    # burning decode slots). None = no deadline.
    deadline: Optional[float] = None
    pages: List[int] = dataclasses.field(default_factory=list)
    generated: List[int] = dataclasses.field(default_factory=list)
    finish_reason: Optional[str] = None  # "eos" | "length" | "capacity" | "cancelled" | "deadline"
    # Memoized prompt-prefix chain keys (prefix caching; computed once even
    # when pool pressure re-runs admission over many ticks).
    prefix_keys: Optional[List[bytes]] = None
    # Copy-on-write source page: set at admission when the prompt fully
    # matched a cached chain and the final shared page must be split. The
    # device copy (and this ref's release) happens at prefill-dispatch time
    # — after any same-tick writer's prefill is enqueued — in _run_prefill.
    cow_src: Optional[int] = None
    # True while an overlapped-admission prefill is in flight on device
    # (dispatched, first token not yet fetched — engine._inflight_admits).
    # Cancels/deadlines that land in this window drop the fetched result;
    # the scheduler's normal reap frees the slot and pages.
    prefill_inflight: bool = False
    # When the prefill was dispatched (overlap path) — the admit-to-merge
    # latency observed at resolve time is ``resolve_t - prefill_dispatch_t``.
    prefill_dispatch_t: Optional[float] = None
    # Admitted via engine.admit_prefilled (disaggregated serving): the
    # prompt's KV was prefilled on a remote pool and imported here, so TTFT
    # accounting splits into prefill-side (gateway-observed) and
    # decode-side (this session's submit→first-token) components.
    disagg: bool = False
    # How many times this logical stream has been re-admitted from a
    # snapshot (engine.resume_session). Carried through checkpoints so a
    # twice-migrated session reports 2, not 1.
    resumes: int = 0
    # Chunked-prefill co-scheduling state (engine/plan.py): while True the
    # session occupies its slot but is NOT decode-eligible — the engine's
    # chunk dispatcher walks the prompt ``plan.prefill_stride`` tokens per
    # granted tick and flips this off when the final chunk samples the
    # first token. ``chunk_off`` is the next unprefilled prompt offset;
    # ``chunk_skip`` carries the admission-time prefix-cache skip;
    # ``parked_key`` is the PRNG key drawn AT ADMISSION (the stream
    # position the legacy synchronous prefill would have consumed) and
    # spent by the final chunk's sample.
    chunking: bool = False
    chunk_off: int = 0
    chunk_skip: int = 0
    parked_key: Optional[Any] = None
    # Admission-ordering stamp from the gateway scheduler (sched/): a
    # sortable ``(lane_rank, virtual_finish, seq)`` tuple consumed by the
    # engine's admission-order hook. None = direct engine user, admitted
    # in FIFO order ahead of scheduled sessions.
    sched_key: Optional[tuple] = None
    # Distributed-trace context (utils.tracing.TraceContext) minted at the
    # gateway and threaded through Handle/ticket plumbing; None for
    # unsampled requests and direct engine users — every tracing hook
    # short-circuits on that None, keeping the disabled path free.
    trace: Optional[Any] = None
    # timing (metrics: TTFT, tokens/sec — SURVEY §5.5)
    submit_time: float = dataclasses.field(default_factory=time.monotonic)
    first_token_time: Optional[float] = None
    finish_time: Optional[float] = None

    @property
    def last_token(self) -> int:
        return self.generated[-1] if self.generated else self.prompt[-1]

    @property
    def total_len(self) -> int:
        return len(self.prompt) + len(self.generated)

    def record_token(self, token: int) -> None:
        if self.first_token_time is None:
            self.first_token_time = time.monotonic()
        self.generated.append(token)

    @property
    def ttft(self) -> Optional[float]:
        if self.first_token_time is None:
            return None
        return self.first_token_time - self.submit_time
