"""Elastic fleet control plane: drain, rebalance, and autoscale the
decode pool on top of the byte-exact live-migration primitives.

* :mod:`.controller` — :class:`FleetController`, the drain /
  rebalance / autoscale driver (crash recovery's proactive twin).
* :mod:`.costmodel` — :class:`CostModel`, the measured
  bytes-vs-latency arbiter between query-move, page-ship, and plain
  migration when a prefix holder is overloaded.
* :mod:`.policy` — the shared directory-row placement filters used by
  both the controller and the recovery gateway.
"""

from .controller import FleetController
from .costmodel import CostModel
from .policy import hot_rows, least_loaded, live_decode_rows, mean_load

__all__ = [
    "FleetController", "CostModel",
    "live_decode_rows", "least_loaded", "hot_rows", "mean_load",
]
