"""Elastic fleet controller: drain, rebalance, and autoscale the decode
pool with zero token loss.

The controller is a *policy* layer over the PR-8 migration primitives —
the same ``export_session`` → ``migrate.ckpt`` → ``resume_session``
machinery that crash recovery (``serving.backends.FleetBackend``) uses
reactively, driven here proactively:

* :meth:`FleetController.drain` asks a decode node (``fleet.drain``
  frame) to hand off every in-flight session: the node ships a fresh
  checkpoint plus a ``fleet.handoff`` marker down each stream's reply
  queue, the gateways re-home the streams exactly-once through their
  existing recovery path, and only once the node's directory load hits
  zero (or the drain times out — stragglers then re-home through plain
  crash recovery, still exactly-once) is the lease **fenced**.
* :meth:`FleetController.rebalance_once` finds hot nodes from the
  heartbeat load signal and asks them (``fleet.migrate``) to shed their
  longest-running sessions, defragmenting KV for big-batch admissions.
* :meth:`FleetController.start` runs the autoscale control loop:
  sustained high mean load spawns a warm standby (the ``spawn``
  callback registers a fresh decode node), sustained low load drains
  the least-loaded node and retires it (``retire`` callback) —
  drain-then-fence, never fence-then-hope.

Threading contract: the controller is single-owner. Either drive it
from one caller thread (``drain`` / ``rebalance_once`` /
``autoscale_once``), or hand it to the background loop with
``start()`` — not both concurrently (the relay client is
one-connection-per-consumer).
"""

from __future__ import annotations

import logging
import threading
import time
import uuid
from typing import Callable, Optional

from ..config import DisaggConfig, FleetConfig
from ..distributed.directory import DirectoryClient
from ..distributed.messages import pack_frame, unpack_frame
from ..distributed.relay import RelayClient
from ..utils.metrics import Metrics
from ..utils.tracing import TraceContext
from .policy import by_node_id, hot_rows, least_loaded, live_decode_rows, mean_load

log = logging.getLogger(__name__)


class FleetController:
    def __init__(
        self,
        relay_port: int,
        host: str = "127.0.0.1",
        fleet_cfg: Optional[FleetConfig] = None,
        disagg_cfg: Optional[DisaggConfig] = None,
        spawn: Optional[Callable[[], object]] = None,
        retire: Optional[Callable[[str], None]] = None,
        metrics: Optional[Metrics] = None,
    ):
        self.fcfg = fleet_cfg or FleetConfig()
        self.dcfg = disagg_cfg or DisaggConfig()
        self.metrics = metrics or Metrics()
        self._spawn = spawn
        self._retire = retire
        self._directory = DirectoryClient(relay_port, host)
        self._client = RelayClient(host, relay_port)
        self._reply = f"fleet.ctl.{uuid.uuid4().hex[:12]}"
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # Autoscale hysteresis clocks (single-owner; see module docstring).
        self._over_since: Optional[float] = None
        self._under_since: Optional[float] = None

    def close(self) -> None:
        self.stop()
        self._client.close()
        self._directory.close()

    # --- drain -----------------------------------------------------------

    def drain(self, node_id: str, timeout: Optional[float] = None) -> dict:
        """Release ``node_id``: live-migrate its in-flight sessions off,
        then fence its lease. Returns a summary dict with the number of
        sessions the node reported in flight (``-1`` if its ack never
        arrived), whether the load observably hit zero before the fence,
        and the new fence floor. Fencing after a timeout is still safe:
        shipped checkpoints re-home any straggler through the gateways'
        crash-recovery path, exactly-once either way."""
        row = by_node_id(self._directory.alive()).get(node_id)
        if row is None:
            raise LookupError(f"node {node_id!r} not alive in the directory")
        epoch = row.get("epoch")
        self.metrics.counter("fleet_drains")
        # Op-level trace: always sampled — drains are rare control-plane
        # events, and the node's fleet.drain span marks when the command
        # landed relative to the per-request handoff spans it triggers.
        ctx = TraceContext.mint(1.0)
        self._client.put(row["queue"], pack_frame(
            {"op": "fleet.drain", "reply": self._reply,
             "trace": ctx.trace_id, "span": ctx.span_id}))
        ack = self._await_ack("drain", timeout=2.0)
        sessions = int(ack.get("n", 0)) if ack else -1
        budget = self.fcfg.drain_timeout_s if timeout is None else timeout
        deadline = time.monotonic() + budget
        drained = False
        while time.monotonic() < deadline:
            row = by_node_id(self._directory.alive()).get(node_id)
            if row is None:  # lease lapsed: nothing left to wait for
                drained = True
                break
            # Only trust a zero load AFTER the row advertises draining:
            # heartbeats lag the drain command, and fencing on a stale
            # pre-drain "load 0" beat would cut down sessions that never
            # got handed off.
            if row.get("draining") and int(row.get("load", 0)) <= 0:
                drained = True
                break
            time.sleep(0.05)
        floor = self._directory.fence(node_id, epoch)
        log.info("fleet: drained %s (sessions=%d drained=%s floor=%d)",
                 node_id, sessions, drained, floor)
        return {"node_id": node_id, "sessions": sessions,
                "drained": drained, "floor": floor, "trace": ctx.trace_id}

    # --- rebalance -------------------------------------------------------

    def rebalance_once(self) -> int:
        """One hot-node scan: ask every node hotter than
        ``hot_load_factor`` x the pool mean to migrate its
        longest-running sessions off (they land on cooler nodes via the
        gateways' normal pick). Returns sessions asked to move."""
        rows = live_decode_rows(self._directory.alive())
        moved = 0
        for row in hot_rows(rows, self.fcfg.hot_load_factor):
            want = min(self.fcfg.rebalance_max_sessions,
                       int(row.get("load", 0)))
            if want <= 0:
                continue
            ctx = TraceContext.mint(1.0)
            self._client.put(row["queue"], pack_frame(
                {"op": "fleet.migrate", "n": want, "reply": self._reply,
                 "trace": ctx.trace_id, "span": ctx.span_id}))
            ack = self._await_ack("migrate", timeout=2.0)
            got = int(ack.get("n", 0)) if ack else 0
            if got > 0:
                self.metrics.counter("fleet_rebalance_migrations", got)
                moved += got
        return moved

    # --- autoscale -------------------------------------------------------

    def autoscale_once(self, now: Optional[float] = None) -> str:
        """One control-loop evaluation against the directory's offered
        load. Returns the action taken: ``"out"`` (spawned), ``"in"``
        (drained + retired), or ``"hold"``. Scale decisions need the
        load signal to *hold* past ``scale_hold_s`` so a single burst
        tick does not thrash the pool."""
        now = time.monotonic() if now is None else now
        rows = live_decode_rows(self._directory.alive())
        pool = len(rows)
        self.metrics.gauge("fleet_pool_size", float(pool))
        if pool < self.fcfg.min_nodes:
            if self._spawn is not None:
                self._spawn()
                self.metrics.counter("fleet_scale_out")
                return "out"
            return "hold"
        avg = mean_load(rows)
        if avg > self.fcfg.scale_out_load and pool < self.fcfg.max_nodes:
            self._under_since = None
            if self._over_since is None:
                self._over_since = now
            elif now - self._over_since >= self.fcfg.scale_hold_s:
                self._over_since = None
                if self._spawn is not None:
                    self._spawn()
                    self.metrics.counter("fleet_scale_out")
                    return "out"
        elif avg < self.fcfg.scale_in_load and pool > self.fcfg.min_nodes:
            self._over_since = None
            if self._under_since is None:
                self._under_since = now
            elif now - self._under_since >= self.fcfg.scale_hold_s:
                self._under_since = None
                victim = least_loaded(rows)
                if victim is not None:
                    self.drain(victim["node_id"])
                    self.metrics.counter("fleet_scale_in")
                    if self._retire is not None:
                        self._retire(victim["node_id"])
                    return "in"
        else:
            self._over_since = None
            self._under_since = None
        return "hold"

    # --- control loop ----------------------------------------------------

    def start(self) -> None:
        """Run autoscale + rebalance on their configured periods in a
        daemon thread until :meth:`stop`. Takes ownership: do not call
        the public one-shot methods from other threads while running."""
        if self._thread is not None:
            return
        self._stop.clear()
        self._thread = threading.Thread(
            target=self._run, name="fleet-controller", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=5.0)

    def _run(self) -> None:
        next_rebalance = time.monotonic() + self.fcfg.rebalance_interval_s
        while not self._stop.is_set():
            try:
                self.autoscale_once()
                if time.monotonic() >= next_rebalance:
                    self.rebalance_once()
                    next_rebalance = (time.monotonic()
                                      + self.fcfg.rebalance_interval_s)
            except Exception:
                log.exception("fleet: control tick failed; continuing")
            self._stop.wait(self.fcfg.autoscale_interval_s)

    # --- plumbing --------------------------------------------------------

    def status(self) -> dict:
        """Directory snapshot for the CLI: all rows plus the routable
        decode pool size and its mean load."""
        rows = self._directory.alive()
        live = live_decode_rows(rows)
        return {"nodes": rows, "pool": len(live), "mean_load": mean_load(live)}

    def _await_ack(self, what: str, timeout: float) -> Optional[dict]:
        """Wait for a ``fleet.ack`` of kind ``what`` on the controller's
        reply queue; drops unrelated frames (counted)."""
        deadline = time.monotonic() + timeout
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            try:
                frame = self._client.get(self._reply, timeout=remaining)
            except TimeoutError:
                return None
            try:
                header, _ = unpack_frame(frame)
            except Exception:
                self.metrics.counter("malformed_frames")
                continue
            if header.get("op") == "fleet.ack" and header.get("what") == what:
                return header
            self.metrics.counter("unknown_ops_dropped")
