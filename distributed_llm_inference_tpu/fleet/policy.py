"""Shared placement policy over directory ``alive()`` rows.

The fleet layer and the crash-recovery gateway
(:class:`..serving.backends.FleetBackend`) pick decode nodes from the
same directory snapshot; these helpers are the single definition of
which rows are *routable* (decode role, registered — not a pending
``assign()`` reservation — not draining, not locally fenced) so drain
semantics cannot drift between the controller and the gateways.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional


def live_decode_rows(
    rows: Iterable[dict],
    dead_ids: Iterable[str] = (),
    include_draining: bool = False,
) -> List[dict]:
    """Filter directory ``alive()`` rows down to routable decode nodes.

    ``dead_ids`` is the caller's local fence set (nodes it has declared
    dead this stream even if their lease has not expired yet). Draining
    nodes are excluded by default — they still serve in-flight streams
    but must not receive new placements.
    """
    dead = set(dead_ids)
    out = []
    for n in rows:
        if n.get("role") != "decode" or n.get("pending"):
            continue
        if n.get("node_id") in dead:
            continue
        if n.get("draining") and not include_draining:
            continue
        out.append(n)
    return out


def least_loaded(rows: Iterable[dict]) -> Optional[dict]:
    """The row with the lowest heartbeat load (node-id tiebreak so the
    choice is deterministic across gateways seeing the same snapshot)."""
    return min(
        rows,
        key=lambda n: (n.get("load", 0), str(n.get("node_id", ""))),
        default=None,
    )


def mean_load(rows: Iterable[dict]) -> float:
    rows = list(rows)
    if not rows:
        return 0.0
    return sum(int(n.get("load", 0)) for n in rows) / len(rows)


def hot_rows(rows: Iterable[dict], factor: float) -> List[dict]:
    """Rows whose load strictly exceeds ``factor`` x the pool mean —
    rebalance candidates. Needs >= 2 rows (with one node there is
    nowhere to move work) and a strictly positive mean (an idle pool
    has no hot member)."""
    rows = list(rows)
    if len(rows) < 2:
        return []
    mean = mean_load(rows)
    if mean <= 0:
        return []
    return [n for n in rows if int(n.get("load", 0)) > factor * mean]


def by_node_id(rows: Iterable[dict]) -> Dict[str, dict]:
    return {str(n.get("node_id")): n for n in rows}
