"""Bytes-vs-latency placement arbiter for overloaded prefix holders.

When prefix routing finds a cached prefix on a node that is busier than
the best alternative, three placements are on the table ("Move the
Query, Not the Cache", PAPERS.md):

* ``query_move`` — send the request to the holder anyway: pay its queue,
  reuse the cache for free.
* ``page_ship``  — copy the prefix's KV pages holder → target over the
  relay, then decode on the idle target: pay 2x the prefix bytes on the
  wire (holder→gateway→target hops), skip the recompute.
* ``migrate``    — decode on the idle target cold: recompute the prefix
  (prefill) there, touch no extra wire bytes.

Each option's latency is estimated from a mix of config seeds and
online measurements (:class:`FleetConfig` documents the knobs); the
wire rate and prefill rate are refined by EMA from observed transfers
so the crossover tracks the deployment, not the defaults. Every
``decide()`` bumps exactly one of the ``fleet_query_moved`` /
``fleet_pages_fetched`` / ``fleet_migrated`` counters — the /metrics
evidence of which way the fleet is leaning.
"""

from __future__ import annotations

from typing import Optional

from ..config import FleetConfig
from ..utils.metrics import Metrics

# Deterministic preference on exact cost ties: the options ordered by
# operational risk (query_move touches nothing, page_ship moves bytes,
# migrate burns compute).
_TIE_ORDER = ("query_move", "page_ship", "migrate")


class CostModel:
    """Measured latency estimates for the three placements (seconds)."""

    def __init__(self, cfg: Optional[FleetConfig] = None,
                 metrics: Optional[Metrics] = None):
        cfg = cfg or FleetConfig()
        self.cfg = cfg
        self.metrics = metrics
        # Mutable, EMA-refined copies of the config seeds.
        self.wire_bytes_per_s = float(cfg.wire_bytes_per_s)
        self.prefill_s_per_token = float(cfg.prefill_s_per_token)

    # --- estimates -------------------------------------------------------

    def prefix_bytes(self, matched_tokens: int) -> float:
        return float(matched_tokens) * self.cfg.kv_bytes_per_token

    def est_query_move(self, holder_load: float, alt_load: float) -> float:
        """Extra queueing latency of decoding on the busier holder."""
        return max(0.0, float(holder_load) - float(alt_load)) \
            * self.cfg.queue_s_per_load

    def est_page_ship(self, matched_tokens: int) -> float:
        """Wire time of moving the prefix KV holder→gateway→target."""
        return 2.0 * self.prefix_bytes(matched_tokens) \
            / max(self.wire_bytes_per_s, 1.0)

    def est_migrate(self, matched_tokens: int) -> float:
        """Recompute time of re-prefilling the prefix on the target."""
        return float(matched_tokens) * self.prefill_s_per_token

    # --- decision --------------------------------------------------------

    def decide(self, matched_tokens: int, holder_load: float,
               alt_load: float) -> str:
        """Pick the cheapest placement; returns ``"query_move"`` /
        ``"page_ship"`` / ``"migrate"`` and tallies the matching
        decision counter."""
        costs = {
            "query_move": self.est_query_move(holder_load, alt_load),
            "page_ship": self.est_page_ship(matched_tokens),
            "migrate": self.est_migrate(matched_tokens),
        }
        if self.prefix_bytes(matched_tokens) > self.cfg.page_ship_max_bytes:
            del costs["page_ship"]
        choice = min(costs, key=lambda k: (costs[k], _TIE_ORDER.index(k)))
        if self.metrics is not None:
            if choice == "query_move":
                self.metrics.counter("fleet_query_moved")
            elif choice == "page_ship":
                self.metrics.counter("fleet_pages_fetched")
            else:
                self.metrics.counter("fleet_migrated")
        return choice

    # --- online refinement -----------------------------------------------

    def _ema(self, old: float, sample: float) -> float:
        a = self.cfg.cost_ema_alpha
        return old if a <= 0 else (1.0 - a) * old + a * sample

    def observe_ship(self, nbytes: int, seconds: float) -> None:
        """Feed one measured page-ship round trip (``nbytes`` of frames,
        two relay hops) back into the wire-rate estimate."""
        if nbytes <= 0 or seconds <= 0:
            return
        self.wire_bytes_per_s = self._ema(
            self.wire_bytes_per_s, 2.0 * nbytes / seconds)

    def observe_prefill(self, tokens: int, seconds: float) -> None:
        """Feed one measured prefill into the recompute-rate estimate."""
        if tokens <= 0 or seconds <= 0:
            return
        self.prefill_s_per_token = self._ema(
            self.prefill_s_per_token, seconds / tokens)
