"""Paged KV cache: fixed page pool + per-session page tables.

The TPU-native realization of the reference's multi-tenancy goal: its
``PartialLlamaSinkCache`` keys Python dicts of growing tensors by
``generation_id``
(``/root/reference/distributed_llm_inference/models/llama/cache.py:14-19``),
which cannot live under ``jit``. Here the per-``generation_id`` state becomes
integer indexing into a preallocated page pool (PagedAttention-style): sessions
own rows of a ``page_table``; pages are allocated/freed host-side by the
scheduler (``engine/engine.py``) and the device computation only ever sees
static shapes.

Layout:
    ``k_pages``/``v_pages``: ``[L, num_pages, Hkv, page_size, D]`` (keys
    rotated; head-major within a page so the Pallas paged kernel's per-head
    block is a contiguous ``[page_size, D]`` tile — TPU Pallas requires the
    last two block dims to be tiling-aligned)
    ``page_table``: ``[B, max_pages_per_session]`` int32 page ids
    ``lengths``: ``[B]`` tokens currently cached per session row

Page 0 is the NULL page: never allocated to a session, absorbing writes from
padding tokens and unallocated table slots, so a misconfigured row can never
corrupt another session's pages.
"""

from __future__ import annotations

import collections
import hashlib
from typing import Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from flax import struct

from ..ops.attention import causal_mask
from ..ops.rotary import RopeAngles, apply_rope
from .base import GatherAttendMixin, flash_prefill_fn


@jax.jit
def _table_write(table, pages_row, row, start):
    """One cached executable for every page-table install (per pages-row
    length): see :meth:`PagedKVCache.assign_pages`."""
    return jax.lax.dynamic_update_slice(table, pages_row, (row, start))


@jax.jit
def _table_write_batch(table, rows, slots, pages):
    """N (row, slot) ← page installs in ONE dispatch (scatter over the tiny
    int32 table; padded entries carry out-of-range rows and drop).

    Why: sequential :func:`_table_write` calls CHAIN (each consumes the
    previous table), so a growth tick where every row crosses a page
    boundary pays one tunnel round trip per row — measured ~35 ms × 32 rows
    ≈ 1.1 s spikes on the serving tick. One batched executable per padded
    length replaces the chain."""
    return table.at[rows, slots].set(pages, mode="drop")


@jax.jit
def _page_copy(pool, dst, src):
    """Device-side page duplicate (copy-on-write split): pool[:, dst] ←
    pool[:, src]. ``dst``/``src`` are TRACED — one executable per pool
    shape/dtype, not per page pair."""
    tile = jax.lax.dynamic_slice_in_dim(pool, src, 1, axis=1)
    return jax.lax.dynamic_update_slice_in_dim(pool, tile, dst, axis=1)


@jax.jit
def _page_read(pool, page):
    """One page's tile ``[L, heads, PS(, D)]`` (traced index — cached
    executable per pool shape; the host copy happens at np.asarray time)."""
    return jax.lax.dynamic_slice_in_dim(pool, page, 1, axis=1)[:, 0]


@jax.jit
def _page_write(pool, tile, page):
    """Install a host-provided page tile at ``pool[:, page]`` (traced
    index; spill-tier reload path)."""
    return jax.lax.dynamic_update_slice_in_dim(
        pool, tile[:, None], page, axis=1
    )


def _page_chunks(a, cap, slots, ps):
    """Chunk contiguous 1-row ring KV ``[L, 1, S, ...]`` into per-page
    tiles ``[L, slots, heads, PS(, D)]`` (shared by the bf16 and int8 pool
    ingests so the layout cannot drift between them)."""
    a = a[:, 0]
    s = a.shape[1]
    if s >= cap:
        a = jax.lax.slice_in_dim(a, 0, cap, axis=1)
    else:
        widths = [(0, 0)] * a.ndim
        widths[1] = (0, cap - s)
        a = jnp.pad(a, widths)
    a = a.reshape(a.shape[0], slots, ps, *a.shape[2:])
    return jnp.swapaxes(a, 2, 3)


class PagedKVCache(GatherAttendMixin, struct.PyTreeNode):
    k_pages: jax.Array
    v_pages: jax.Array
    page_table: jax.Array
    lengths: jax.Array
    page_size: int = struct.field(pytree_node=False)
    # Use the Pallas paged-attention kernel for decode steps (reads pages in
    # place instead of gathering a contiguous per-row view).
    use_kernel: bool = struct.field(pytree_node=False, default=False)
    # Serve multi-token rows (prefill / chunked prefill) through the ragged
    # mixed-phase kernel (ops/ragged_attention.py) — pages read in place
    # with per-row true lengths, replacing update_and_gather's contiguous
    # [B, max_len, Hkv, D] copy. Set by the engine's AttentionPlan (TPU
    # only; interpret mode is test-grade).
    use_ragged: bool = struct.field(pytree_node=False, default=False)

    # Generic-consumer layout (see DenseKVCache): the page pool is batch-free;
    # only the table/lengths have session rows. Pool fields carry the layer
    # axis and are passed through whole on row slices (SHARED_FIELDS).
    BATCH_AXES = {"page_table": 0, "lengths": 0}
    LAYER_FIELDS = ("k_pages", "v_pages")
    SHARED_FIELDS = ("k_pages", "v_pages")
    # Stored-form plane name -> pool field (export/spill/reload share this
    # map so the host-facing naming cannot drift between them).
    PLANE_FIELDS = {"k": "k_pages", "v": "v_pages"}

    @staticmethod
    def create(
        num_layers: int,
        batch: int,
        num_pages: int,
        page_size: int,
        max_pages_per_session: int,
        num_kv_heads: int,
        head_dim: int,
        dtype=jnp.bfloat16,
        use_kernel: bool = False,
        use_ragged: bool = False,
    ) -> "PagedKVCache":
        shape = (num_layers, num_pages, num_kv_heads, page_size, head_dim)
        return PagedKVCache(
            k_pages=jnp.zeros(shape, dtype),
            v_pages=jnp.zeros(shape, dtype),
            page_table=jnp.zeros((batch, max_pages_per_session), jnp.int32),
            lengths=jnp.zeros((batch,), jnp.int32),
            page_size=page_size,
            use_kernel=use_kernel,
            use_ragged=use_ragged,
        )

    @property
    def max_len(self) -> int:
        return self.page_table.shape[1] * self.page_size

    @property
    def layer_stacks(self):
        return (self.k_pages, self.v_pages)

    def with_layer_stacks(self, new_k, new_v) -> "PagedKVCache":
        return self.replace(k_pages=new_k, v_pages=new_v)

    def q_positions(self, seq_len: int) -> jnp.ndarray:
        return self.lengths[:, None] + jnp.arange(seq_len, dtype=jnp.int32)[None, :]

    def rope_positions(self, seq_len: int, num_new: jnp.ndarray) -> jnp.ndarray:
        return self.q_positions(seq_len)

    def fits(self, num_new) -> jnp.ndarray:
        """Scheduler contract as in ``DenseKVCache.fits`` — additionally the
        scheduler must have mapped enough pages in ``page_table``."""
        return self.lengths + num_new <= self.max_len

    def _slot_pages(self, q_pos: jnp.ndarray, num_new: jnp.ndarray):
        """Map incoming tokens' absolute positions ``[B, S]`` →
        ``(physical page, in-page offset)``, both ``[B, S]``.

        Inactive rows / padding positions (``>= num_new``) and out-of-range
        table slots divert to the NULL page 0 — an inactive slot's old pages
        may already belong to ANOTHER session (freed + reallocated), so a
        write there would corrupt it. Shared by the bf16 and int8 pool
        scatters so the safety mapping cannot drift between them.
        """
        s = q_pos.shape[1]
        table_slot = q_pos // self.page_size
        offset = q_pos % self.page_size
        in_range = (
            jnp.arange(s, dtype=jnp.int32)[None, :] < num_new[:, None]
        ) & (table_slot < self.page_table.shape[1])
        phys = jnp.take_along_axis(
            self.page_table,
            jnp.minimum(table_slot, self.page_table.shape[1] - 1),
            axis=1,
        )
        return jnp.where(in_range, phys, 0), offset

    def _scatter(
        self,
        layer_k: jnp.ndarray,
        layer_v: jnp.ndarray,
        k_rot: jnp.ndarray,
        v_new: jnp.ndarray,
        q_pos: jnp.ndarray,
        num_new: jnp.ndarray,
    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Scatter rotated k / raw v into the page pool at each incoming
        token's (physical page, offset) per the row's page table."""
        b, s, hkv, d = k_rot.shape
        phys_page, offset_bs = self._slot_pages(q_pos, num_new)
        if s == 1:
            # Decode: one (page, offset) per row. A sequential per-row
            # dynamic_update_slice chain updates the donated pool in place;
            # the general scatter below costs ~2x a decode step at 7B shapes
            # (measured: XLA rewrites the pool).
            page = phys_page[:, 0]
            offset = offset_bs[:, 0]

            def body(r, bufs):
                bk, bv = bufs
                kv = k_rot[r, 0][:, None, :].astype(bk.dtype)  # [Hkv, 1, D]
                vv = v_new[r, 0][:, None, :].astype(bv.dtype)
                start = (page[r], 0, offset[r], 0)
                return (
                    jax.lax.dynamic_update_slice(bk, kv[None], start),
                    jax.lax.dynamic_update_slice(bv, vv[None], start),
                )

            return jax.lax.fori_loop(0, b, body, (layer_k, layer_v))
        flat_page = phys_page.reshape(-1)
        flat_off = offset_bs.reshape(-1)
        # Pool is [P, Hkv, PS, D]: advanced indices (page, offset) around the
        # head slice put the broadcast dim first → writes are [N, Hkv, D].
        new_k = layer_k.at[flat_page, :, flat_off].set(
            k_rot.reshape(b * s, hkv, d), mode="drop"
        )
        new_v = layer_v.at[flat_page, :, flat_off].set(
            v_new.reshape(b * s, hkv, d), mode="drop"
        )
        return new_k, new_v

    def attend(
        self,
        layer_state,
        q,
        k_new,
        v_new,
        rope,
        q_pos,
        num_new,
        sliding_window,
        attention_fn,
        scale=None,
    ):
        """Decode steps with ``use_kernel``: scatter into the pool, then run
        the Pallas paged kernel over the pages in place — no contiguous
        gather. Multi-token rows with ``use_ragged`` go through the ragged
        mixed-phase kernel the same way (per-row true lengths, phase is
        data). Everything else uses the default gather+``attention_fn``
        (``GatherAttendMixin``)."""
        if self.use_ragged and q.shape[1] > 1:
            from ..ops.ragged_attention import ragged_paged_attention

            layer_k, layer_v = layer_state
            q_rot = apply_rope(q, rope.cos, rope.sin)
            k_rot = apply_rope(k_new, rope.cos, rope.sin)
            new_k, new_v = self._scatter(
                layer_k, layer_v, k_rot, v_new, q_pos, num_new
            )
            out = ragged_paged_attention(
                q_rot, new_k, new_v, self.page_table,
                self.lengths + num_new, num_new,
                scale=scale, sliding_window=sliding_window,
            )
            return out, (new_k, new_v)
        if not self.use_kernel or q.shape[1] != 1:
            return super().attend(
                layer_state, q, k_new, v_new, rope, q_pos, num_new,
                sliding_window, attention_fn, scale,
            )
        from ..ops.paged_attention import paged_attention

        layer_k, layer_v = layer_state
        q_rot = apply_rope(q, rope.cos, rope.sin)
        k_rot = apply_rope(k_new, rope.cos, rope.sin)
        new_k, new_v = self._scatter(
            layer_k, layer_v, k_rot, v_new, q_pos, num_new
        )
        out = paged_attention(
            q_rot, new_k, new_v, self.page_table, self.lengths + num_new,
            scale=scale, sliding_window=sliding_window,
        )
        return out, (new_k, new_v)

    # -- write-behind tail (fused multi-step decode) --------------------------
    #
    # Kernel-only: the XLA fallback's per-step page gather is exactly the
    # materialization the tail exists to avoid, so the engine gates the tail
    # path on use_kernel for this cache. The page POOL stays read-only
    # through all K steps (it rides the layer scan as a sliced operand —
    # the carry-slice version costs two full pool copies plus relayouts per
    # layer per step, ~4x the kernel's own time at 7B shapes) and new
    # tokens live in a small dense tail merged into pages once per K steps.

    def tail_init(self, k_steps: int):
        l = self.k_pages.shape[0]
        b = self.page_table.shape[0]
        hkv, d = self.k_pages.shape[2], self.k_pages.shape[4]
        z = jnp.zeros((l, b, k_steps, hkv, d), self.k_pages.dtype)
        return (z, z)

    def tail_attend(self, big_state, tail_state, q, k_new, v_new, rope,
                    base_len, tail_len, step_idx, num_new, sliding_window,
                    scale=None):
        from ..ops.attention import merge_softmax_segments
        from ..ops.paged_attention import paged_attention

        pool_k, pool_v = big_state
        tk, tv = tail_state
        q_rot = apply_rope(q, rope.cos, rope.sin)
        k_rot = apply_rope(k_new, rope.cos, rope.sin)
        tk = jax.lax.dynamic_update_slice_in_dim(tk, k_rot, step_idx, axis=1)
        tv = jax.lax.dynamic_update_slice_in_dim(tv, v_new, step_idx, axis=1)

        q_pos = base_len + tail_len  # [B]
        out_pool, m_pool, l_pool = paged_attention(
            q_rot, pool_k, pool_v, self.page_table, base_len,
            scale=scale, sliding_window=sliding_window,
            q_positions=q_pos, return_stats=True,
        )

        kk = tk.shape[1]
        tail_pos = base_len[:, None] + jnp.arange(kk, dtype=jnp.int32)[None, :]
        tail_valid = (
            jnp.arange(kk, dtype=jnp.int32)[None, :]
            < (tail_len + num_new)[:, None]
        )
        if sliding_window is not None:
            tail_valid &= tail_pos > (q_pos[:, None] - sliding_window)
        out = merge_softmax_segments(
            q_rot, out_pool, m_pool, l_pool, tk, tv, tail_valid, scale
        )
        return out, (tk, tv)

    def tail_flush(self, tail, tail_len):
        """Merge the tail into the page pool: the prefill scatter path, once
        per K fused steps, batched over layers via vmap."""
        wk, wv = tail  # [L, B, K, Hkv, D]
        kk = wk.shape[2]
        q_pos = (
            self.lengths[:, None] + jnp.arange(kk, dtype=jnp.int32)[None, :]
        )
        num_new = tail_len
        new_k, new_v = jax.vmap(
            lambda lk, lv, tkl, tvl: self._scatter(
                lk, lv, tkl, tvl, q_pos, num_new
            )
        )(self.k_pages, self.v_pages, wk, wv)
        return self.replace(
            k_pages=new_k, v_pages=new_v, lengths=self.lengths + tail_len
        )

    def update_and_gather(
        self,
        layer_state: Tuple[jnp.ndarray, ...],
        q: jnp.ndarray,
        k_new: jnp.ndarray,
        v_new: jnp.ndarray,
        rope: RopeAngles,
        q_pos: jnp.ndarray,
        num_new: jnp.ndarray,
        sliding_window: Optional[int] = None,
    ) -> Tuple[jnp.ndarray, ...]:
        """Scatter new k/v into pages; gather each row's pages for attention.

        ``layer_state``: ``(layer_k, layer_v)``, each ``[P, Hkv, page_size,
        D]`` (one layer). The gather materializes
        ``[B, max_pages_per_session * page_size, …]`` per layer — the
        XLA-fused correctness baseline. The Pallas paged kernel
        (``ops/paged_attention.py``) reads pages in place instead.
        """
        layer_k, layer_v = layer_state
        b, s, hkv, d = k_new.shape
        q_rot = apply_rope(q, rope.cos, rope.sin)
        k_rot = apply_rope(k_new, rope.cos, rope.sin)
        new_k, new_v = self._scatter(
            layer_k, layer_v, k_rot, v_new, q_pos, num_new
        )

        # Gather this row's pages into a contiguous view. Slot i of the view
        # holds absolute position i because table slots are position-ordered.
        # [B, T, Hkv, PS, D] → [B, T, PS, Hkv, D] → [B, max_len, Hkv, D].
        k_all = jnp.take(new_k, self.page_table, axis=0).transpose(
            0, 1, 3, 2, 4
        ).reshape(b, self.max_len, hkv, d)
        v_all = jnp.take(new_v, self.page_table, axis=0).transpose(
            0, 1, 3, 2, 4
        ).reshape(b, self.max_len, hkv, d)

        kv_pos = jnp.broadcast_to(
            jnp.arange(self.max_len, dtype=jnp.int32)[None, :], (b, self.max_len)
        )
        kv_valid = kv_pos < (self.lengths + num_new)[:, None]
        mask = causal_mask(q_pos, kv_pos, kv_valid, sliding_window)
        return q_rot, k_all, v_all, mask, (new_k, new_v)

    def advance(self, num_new: jnp.ndarray) -> "PagedKVCache":
        return self.replace(lengths=self.lengths + num_new)

    def reset_rows(self, row_mask: jnp.ndarray) -> "PagedKVCache":
        """Clear sessions (host frees their pages via the allocator)."""
        return self.replace(
            lengths=jnp.where(row_mask, 0, self.lengths),
            page_table=jnp.where(row_mask[:, None], 0, self.page_table),
        )

    def select_row(self, row) -> "PagedKVCache":
        """Batch-1 view: row-local page table/length over the SHARED page
        pool, so a single-row prefill writes straight into the pool."""
        return self.replace(
            page_table=jax.lax.dynamic_slice_in_dim(self.page_table, row, 1, axis=0),
            lengths=jax.lax.dynamic_slice_in_dim(self.lengths, row, 1),
        )

    def merge_row(self, sub: "PagedKVCache", row) -> "PagedKVCache":
        return self.replace(
            k_pages=sub.k_pages,
            v_pages=sub.v_pages,
            page_table=jax.lax.dynamic_update_slice_in_dim(
                self.page_table, sub.page_table, row, axis=0
            ),
            lengths=jax.lax.dynamic_update_slice_in_dim(
                self.lengths, sub.lengths, row, axis=0
            ),
        )

    def select_rows(self, rows) -> "PagedKVCache":
        """Compact multi-row view for the batched-admission prefill (see
        ``cache/dense.py`` — padding entries are out-of-range rows, clamped
        here and dropped on merge): row-local tables/lengths over the
        SHARED page pool, so the sub-prefill writes straight into the
        pool. A clamped padding row's table is harmless: its ``num_new=0``
        prefill diverts every write to the null page."""
        return self.replace(
            page_table=jnp.take(self.page_table, rows, axis=0, mode="clip"),
            lengths=jnp.take(self.lengths, rows, axis=0, mode="clip"),
        )

    def merge_rows(self, sub, rows):
        updated = {
            name: getattr(sub, name) for name in self.SHARED_FIELDS
        }
        return self.replace(
            page_table=self.page_table.at[rows].set(
                sub.page_table, mode="drop"
            ),
            lengths=self.lengths.at[rows].set(sub.lengths, mode="drop"),
            **updated,
        )

    def ingest_row(self, ks, vs, n_valid, first_slot=0):
        """Install ring-prefill KV into the page pool (cf.
        ``DenseKVCache.ingest_row``; 1-row ``select_row`` view — the pool
        is SHARED, so the pages land in place and ``merge_row`` writes the
        table/length back): the contiguous ``[L, 1, S, Hkv, D]`` ring KV is
        chunked into page-size pieces and scattered to this row's table
        slots. Slots past the assigned run hold the null page; their junk
        writes are never read (validity derives from ``lengths``), and
        duplicate null-page indices are harmless for the same reason.

        ``first_slot`` > 0 additionally diverts the HEAD of the run: slots
        below it map SHARED prefix pages whose content is already resident
        (disaggregated admission with a local prefix hit) and must not be
        overwritten with the shipped copy."""
        return self._ingest_planes(
            {"k_pages": ks, "v_pages": vs}, n_valid, first_slot
        )

    def _ingest_planes(self, planes, n_valid, first_slot=0):
        """Shared ring-ingest write pattern (bf16 values and int8+scale
        planes alike): chunk each contiguous plane into page tiles and
        scatter to this row's table slots, then set lengths. Batch-1 views
        ONLY — a multi-row cache would broadcast ``n_valid`` into rows
        whose pages received nothing (silent corruption), so fail loudly."""
        if self.lengths.shape[0] != 1:
            raise ValueError(
                "paged ingest_row needs a batch-1 select_row view, got "
                f"batch {self.lengths.shape[0]}"
            )
        ps = self.page_size
        slots = self.page_table.shape[1]
        # Scatter ONLY slots [first_slot, ceil(n_valid/page_size)) — the run
        # this ingest actually owns. Slots outside it are diverted to the
        # null page (page 0): past the run they hold the null page anyway,
        # and below ``first_slot`` they map shared prefix pages that must
        # not be overwritten with this ingest's copy of the same content.
        n_owned = (jnp.asarray(n_valid, jnp.int32) + ps - 1) // ps
        arange = jnp.arange(slots, dtype=jnp.int32)
        owned = (arange >= jnp.asarray(first_slot, jnp.int32)) & (
            arange < n_owned
        )
        pages = jnp.where(owned, self.page_table[0], 0)
        updates = {
            name: getattr(self, name).at[:, pages].set(
                _page_chunks(a, slots * ps, slots, ps).astype(
                    getattr(self, name).dtype
                )
            )
            for name, a in planes.items()
        }
        return self.replace(
            lengths=jnp.broadcast_to(
                jnp.asarray(n_valid, jnp.int32), self.lengths.shape
            ),
            **updates,
        )

    def copy_page(self, dst: int, src: int) -> "PagedKVCache":
        """Duplicate page ``src`` into ``dst`` across every pool plane —
        the device half of a copy-on-write split. Pure page-pool op: the
        table/lengths are untouched (the scheduler remaps the splitting
        session's slot to ``dst`` itself)."""
        dst = jnp.int32(dst)
        src = jnp.int32(src)
        return self.replace(**{
            f: _page_copy(getattr(self, f), dst, src)
            for f in self.PLANE_FIELDS.values()
        })

    def read_page(self, page: int) -> Dict[str, np.ndarray]:
        """Host copies of one page's tiles in STORED form, keyed by plane
        name (``{"k": [L, Hkv, PS, D], "v": …}``, plus ``ks``/``vs``
        ``[L, Hkv, PS]`` scales on the quantized pool). ``np.asarray``
        blocks until pending device writes to the page have completed, so
        the spill tier always captures settled content."""
        p = jnp.int32(page)
        return {
            name: np.asarray(_page_read(getattr(self, f), p))
            for name, f in self.PLANE_FIELDS.items()
        }

    def write_page(self, page: int, tiles: Dict[str, np.ndarray]) -> "PagedKVCache":
        """Install :meth:`read_page`-form tiles at ``page`` (spill-tier
        reload). Validates plane names, shapes, and dtypes and raises
        ``ValueError`` on any mismatch — a corrupted arena entry must be
        rejected here, before it can poison the pool."""
        want = set(self.PLANE_FIELDS)
        if set(tiles) != want:
            raise ValueError(
                f"page tiles {sorted(tiles)} do not match this pool "
                f"(want {sorted(want)})"
            )
        out = {}
        for name, f in self.PLANE_FIELDS.items():
            pool = getattr(self, f)
            tile = np.asarray(tiles[name])
            expect = pool.shape[:1] + pool.shape[2:]
            if tuple(tile.shape) != tuple(expect):
                raise ValueError(
                    f"page tile {name!r} shape {tile.shape} != {tuple(expect)}"
                )
            if tile.dtype.name != pool.dtype.name:
                raise ValueError(
                    f"page tile {name!r} dtype {tile.dtype.name} != "
                    f"{pool.dtype.name}"
                )
            out[f] = _page_write(pool, jnp.asarray(tile), jnp.int32(page))
        return self.replace(**out)

    def assign_pages(self, row: int, pages, start_slot: int = 0) -> "PagedKVCache":
        """Host-side helper: install allocator-chosen page ids for a row.

        ``row``/``start_slot`` go in TRACED (via the jitted helper): baked-in
        constants would compile a fresh executable per (row, slot) pair —
        measured as a ~2 s stall the first time a serving tick crosses a page
        boundary (one tiny compile per growing row)."""
        pages = jnp.asarray(pages, jnp.int32)
        return self.replace(
            page_table=_table_write(
                self.page_table, pages[None, :], jnp.int32(row),
                jnp.int32(start_slot),
            )
        )

    def assign_pages_batch(self, rows, slots, pages,
                           pad_to: int = 0) -> "PagedKVCache":
        """Install N (row, slot) ← page mappings in ONE device dispatch.

        Sequential :meth:`assign_pages` calls chain through the tunnel (one
        round trip each); the batched scatter replaces the chain on ticks
        where many rows grow at once. ``pad_to`` pads the arrays to a
        fixed length so a few bucketed lengths cover every tick with cached
        executables; padded entries use a past-the-end row (negative would
        WRAP) and drop."""
        n = max(len(rows), pad_to)
        r = np.full((n,), self.page_table.shape[0], np.int32)
        s = np.zeros((n,), np.int32)
        p = np.zeros((n,), np.int32)
        r[: len(rows)] = rows
        s[: len(rows)] = slots
        p[: len(rows)] = pages
        return self.replace(
            page_table=_table_write_batch(
                self.page_table, jnp.asarray(r), jnp.asarray(s),
                jnp.asarray(p),
            )
        )


class PageAllocator:
    """Host-side page allocator (page 0 reserved as the null page) with
    refcounts and a prompt-prefix registry for automatic prefix caching.

    Plays the role hivemind's runtime state played for the reference's server:
    pure Python, not traced — only its *outputs* (page tables) reach the
    device. Guarded by the engine's scheduler lock (SURVEY §5.2).

    Prefix caching (vLLM-style): a page holding a FULL page-sized chunk of a
    session's prompt is content-addressed by the hash chain of the prompt up
    to and including that chunk. On release such pages are ``register``-ed
    instead of freed; a later session with the same prompt prefix ``lookup``s
    the chain and maps the cached pages into its table read-only (refcounted;
    writes never touch them — the session's write offset starts past the
    shared span). Unreferenced registered pages form an LRU that ``alloc``
    evicts from under pool pressure.
    """

    def __init__(self, num_pages: int):
        self._free = list(range(num_pages - 1, 0, -1))  # pop() yields low ids first
        self._free_set = set(self._free)
        self.num_pages = num_pages
        self._refs: Dict[int, int] = {}
        self._registry: Dict[bytes, int] = {}      # chain key -> page
        self._page_key: Dict[int, bytes] = {}      # page -> chain key
        self._lru: "collections.OrderedDict[int, None]" = collections.OrderedDict()
        # Eviction hook (prefixstore spill tier): called with (page, key)
        # BEFORE the page returns to the free list, while its content is
        # still valid — the engine snapshots the tiles to its host arena.
        # Runs under the engine's scheduler lock like every allocator call;
        # a hook failure must not wedge eviction (callers catch their own).
        self.on_evict = None

    @property
    def free_count(self) -> int:
        """Pages obtainable right now (free list + evictable cached pages)."""
        return len(self._free) + len(self._lru)

    @staticmethod
    def chain_keys(tokens, page_size: int) -> List[bytes]:
        """Hash-chain keys of every FULL page-sized chunk of ``tokens``."""
        keys, h = [], hashlib.sha1()
        for i in range(len(tokens) // page_size):
            chunk = tokens[i * page_size : (i + 1) * page_size]
            h.update(np.asarray(chunk, np.int64).tobytes())
            keys.append(h.digest())
        return keys

    def _evict_one(self) -> None:
        page, _ = self._lru.popitem(last=False)  # oldest
        key = self._page_key.pop(page)
        del self._registry[key]
        del self._refs[page]
        if self.on_evict is not None:
            self.on_evict(page, key)
        self._free.append(page)
        self._free_set.add(page)

    def alloc(self, n: int):
        """n fresh (private, refcount-1) pages; evicts cached pages if needed."""
        while len(self._free) < n and self._lru:
            self._evict_one()
        if n > len(self._free):
            raise MemoryError(
                f"page pool exhausted: want {n}, have {len(self._free)}"
            )
        pages = [self._free.pop() for _ in range(n)]
        self._free_set.difference_update(pages)
        for p in pages:
            self._refs[p] = 1
        return pages

    def lookup(self, keys: Sequence[bytes]) -> List[int]:
        """Longest cached run of prefix pages for ``keys``; each returned
        page's refcount is incremented (caller owns a reference)."""
        pages: List[int] = []
        for key in keys:
            page = self._registry.get(key)
            if page is None:
                break
            self._refs[page] += 1
            self._lru.pop(page, None)  # referenced: not evictable
            pages.append(page)
        return pages

    def lookup_one(self, key: bytes) -> Optional[int]:
        """One registered page by key, refcounted like :meth:`lookup`
        (caller owns a reference), or ``None`` when the key is not cached —
        the spill-reload walk checks the device registry page-by-page."""
        page = self._registry.get(key)
        if page is None:
            return None
        self._refs[page] += 1
        self._lru.pop(page, None)
        return page

    def peek(self, key: bytes) -> Optional[int]:
        """Registered page for ``key`` WITHOUT taking a reference — for
        match-length probes (routing) that must not pin pages."""
        return self._registry.get(key)

    def registered_keys(self, limit: int = 0) -> List[bytes]:
        """Registered chain keys, oldest first (dict insertion order);
        ``limit`` > 0 keeps only the NEWEST that many — the bounded set a
        node advertises to the directory."""
        keys = list(self._registry)
        return keys[-limit:] if limit > 0 else keys

    def register(self, page: int, key: bytes) -> None:
        """Content-address ``page`` (a full prompt-prefix page) under ``key``.
        If ``key`` is already registered to a different page, the existing
        entry wins (first writer; duplicates just stay private)."""
        if key in self._registry or page in self._page_key:
            return
        self._registry[key] = page
        self._page_key[page] = key

    def free(self, pages) -> None:
        """Drop one reference per page; unreferenced pages return to the free
        list, or to the evictable LRU if they are registered prefixes.

        Iterates in REVERSE so a prefix chain's deepest chunks enter the LRU
        first (oldest): eviction then trims chains from the tail, keeping a
        usable shorter prefix — evicting the chain root first would orphan
        every deeper cached page.

        The whole list is validated BEFORE any state changes: a bad id must
        raise with the pool untouched, not after earlier pages in the list
        were already freed/decref'd (a caught exception would otherwise
        leave refcounts inconsistent with the caller's page lists)."""
        pages = list(pages)
        drops: Dict[int, int] = {}
        for p in pages:
            if not 0 < p < self.num_pages:
                raise ValueError(
                    f"page {p} outside pool (1..{self.num_pages - 1}; 0 is the "
                    "reserved null page)"
                )
            drops[p] = drops.get(p, 0) + 1
            refs = self._refs.get(p)
            if (
                refs is None
                or refs == 0
                or p in self._free_set
                or drops[p] > refs  # duplicates within ONE call over-release
            ):
                raise ValueError(f"double free of page {p}")
        for p in reversed(pages):
            refs = self._refs[p]
            if refs > 1:
                self._refs[p] = refs - 1
                continue
            if p in self._page_key:  # cached prefix: evictable, not freed
                self._lru[p] = None
                self._refs[p] = 0
            else:
                del self._refs[p]
                self._free.append(p)
                self._free_set.add(p)


class QuantizedPagedKVCache(PagedKVCache):
    """Page pool with int8 K/V + per-(slot, head) fp32 scale planes.

    The paged counterpart of :class:`cache.dense.QuantizedDenseKVCache`:
    decode reads every live page each step, so int8 pages halve the pool's
    HBM traffic. Scales ride separate ``[L, P, Hkv, PS]`` planes (≈1.5%
    byte overhead at D=128); the Pallas kernel dequantizes ON THE SCORES
    (``q·(k·s) = s·(q·k)``) so the int8 pages stream through VMEM as-is,
    and the XLA gather fallback dequantizes its contiguous view.
    """

    # Dataclass inheritance: fields after the parent's defaulted ones need
    # defaults; create() always supplies real arrays.
    ks_pages: jax.Array = None
    vs_pages: jax.Array = None

    BATCH_AXES = {"page_table": 0, "lengths": 0}
    LAYER_FIELDS = ("k_pages", "v_pages", "ks_pages", "vs_pages")
    SHARED_FIELDS = ("k_pages", "v_pages", "ks_pages", "vs_pages")
    PLANE_FIELDS = {
        "k": "k_pages", "v": "v_pages", "ks": "ks_pages", "vs": "vs_pages",
    }

    @staticmethod
    def create(
        num_layers: int,
        batch: int,
        num_pages: int,
        page_size: int,
        max_pages_per_session: int,
        num_kv_heads: int,
        head_dim: int,
        dtype=jnp.bfloat16,  # interface parity; values are int8
        use_kernel: bool = False,
        use_ragged: bool = False,
    ) -> "QuantizedPagedKVCache":
        shape = (num_layers, num_pages, num_kv_heads, page_size, head_dim)
        return QuantizedPagedKVCache(
            k_pages=jnp.zeros(shape, jnp.int8),
            v_pages=jnp.zeros(shape, jnp.int8),
            ks_pages=jnp.zeros(shape[:-1], jnp.float32),
            vs_pages=jnp.zeros(shape[:-1], jnp.float32),
            page_table=jnp.zeros((batch, max_pages_per_session), jnp.int32),
            lengths=jnp.zeros((batch,), jnp.int32),
            page_size=page_size,
            use_kernel=use_kernel,
            use_ragged=use_ragged,
        )

    @property
    def layer_stacks(self):
        return (self.k_pages, self.v_pages, self.ks_pages, self.vs_pages)

    def with_layer_stacks(self, k, v, ks, vs) -> "QuantizedPagedKVCache":
        return self.replace(k_pages=k, v_pages=v, ks_pages=ks, vs_pages=vs)

    def merge_row(self, sub, row) -> "QuantizedPagedKVCache":
        return self.replace(
            k_pages=sub.k_pages,
            v_pages=sub.v_pages,
            ks_pages=sub.ks_pages,
            vs_pages=sub.vs_pages,
            page_table=jax.lax.dynamic_update_slice_in_dim(
                self.page_table, sub.page_table, row, axis=0
            ),
            lengths=jax.lax.dynamic_update_slice_in_dim(
                self.lengths, sub.lengths, row, axis=0
            ),
        )

    def ingest_row(self, ks, vs, n_valid, first_slot=0):
        """Ring-prefill ingest, quantized pool form: per-(token, head)
        int8 + scale planes (cf. ``QuantizedDenseKVCache.ingest_row``)."""
        from .dense import _quantize_kv

        k_q, k_s = _quantize_kv(ks)  # [L, 1, S, H, D] / [L, 1, S, H]
        v_q, v_s = _quantize_kv(vs)
        return self.ingest_planes_row(k_q, v_q, k_s, v_s, n_valid, first_slot)

    def ingest_planes_row(self, k_q, v_q, k_s, v_s, n_valid, first_slot=0):
        """Install ALREADY-quantized planes (int8 values ``[L, 1, S, H, D]``
        + f32 scales ``[L, 1, S, H]``) without requantizing — disaggregated
        decode imports the prefill pool's STORED planes bit-exact (cf.
        ``QuantizedDenseKVCache.ingest_planes_row``)."""
        return self._ingest_planes(
            {"k_pages": k_q, "v_pages": v_q,
             "ks_pages": k_s, "vs_pages": v_s},
            n_valid,
            first_slot,
        )

    def _scatter_q(self, layer_k, layer_v, layer_ks, layer_vs, k_rot, v_new,
                   q_pos, num_new):
        """Quantize incoming k/v, then the :meth:`_scatter` write pattern
        over the four planes."""
        from .dense import _quantize_kv

        b, s, hkv, d = k_rot.shape
        k_q, k_s = _quantize_kv(k_rot)
        v_q, v_s = _quantize_kv(v_new)
        if s > 1:
            return self._scatter_planes(
                layer_k, layer_v, layer_ks, layer_vs, k_q, v_q, k_s, v_s,
                q_pos, num_new,
            )
        # s == 1 from here (the s > 1 path returned above).
        phys_page, offset_bs = self._slot_pages(q_pos, num_new)
        page = phys_page[:, 0]
        offset = offset_bs[:, 0]

        def body(r, bufs):
            bk, bv, bks, bvs = bufs
            kv = k_q[r, 0][:, None, :]
            vv = v_q[r, 0][:, None, :]
            ks1 = k_s[r, 0][:, None]
            vs1 = v_s[r, 0][:, None]
            start = (page[r], 0, offset[r], 0)
            start3 = (page[r], 0, offset[r])
            return (
                jax.lax.dynamic_update_slice(bk, kv[None], start),
                jax.lax.dynamic_update_slice(bv, vv[None], start),
                jax.lax.dynamic_update_slice(bks, ks1[None], start3),
                jax.lax.dynamic_update_slice(bvs, vs1[None], start3),
            )

        return jax.lax.fori_loop(
            0, b, body, (layer_k, layer_v, layer_ks, layer_vs)
        )

    def _scatter_planes(self, layer_k, layer_v, layer_ks, layer_vs,
                        k_q, v_q, k_s, v_s, q_pos, num_new):
        """Scatter PRE-QUANTIZED ``[B, S, Hkv(, D)]`` values + scales into
        the pool (the fused kernel quantizes in-kernel; its tail flushes
        through here without a second quantization)."""
        b, s, hkv, d = k_q.shape
        phys_page, offset_bs = self._slot_pages(q_pos, num_new)
        flat_page = phys_page.reshape(-1)
        flat_off = offset_bs.reshape(-1)
        return (
            layer_k.at[flat_page, :, flat_off].set(
                k_q.reshape(b * s, hkv, d), mode="drop"
            ),
            layer_v.at[flat_page, :, flat_off].set(
                v_q.reshape(b * s, hkv, d), mode="drop"
            ),
            layer_ks.at[flat_page, :, flat_off].set(
                k_s.reshape(b * s, hkv), mode="drop"
            ),
            layer_vs.at[flat_page, :, flat_off].set(
                v_s.reshape(b * s, hkv), mode="drop"
            ),
        )

    def attend(self, layer_state, q, k_new, v_new, rope, q_pos, num_new,
               sliding_window, attention_fn, scale=None):
        if self.use_ragged and q.shape[1] > 1:
            from ..ops.ragged_attention import (
                quantized_ragged_paged_attention,
            )

            lk, lv, lks, lvs = layer_state
            q_rot = apply_rope(q, rope.cos, rope.sin)
            k_rot = apply_rope(k_new, rope.cos, rope.sin)
            new = self._scatter_q(
                lk, lv, lks, lvs, k_rot, v_new, q_pos, num_new
            )
            out = quantized_ragged_paged_attention(
                q_rot, new[0], new[2], new[1], new[3], self.page_table,
                self.lengths + num_new, num_new,
                scale=scale, sliding_window=sliding_window,
            )
            return out, new
        if not self.use_kernel or q.shape[1] != 1:
            # Long prefill: flash over the dequantized pool view (see
            # cache/base.py flash_prefill_fn — the full-score path
            # dominates at S >~ 1k).
            flash = flash_prefill_fn(q.shape[1], self.max_len, attention_fn)
            if flash is not None:
                attention_fn = flash
            return super(PagedKVCache, self).attend(
                layer_state, q, k_new, v_new, rope, q_pos, num_new,
                sliding_window, attention_fn, scale,
            )
        from ..ops.paged_attention import quantized_paged_attention

        lk, lv, lks, lvs = layer_state
        q_rot = apply_rope(q, rope.cos, rope.sin)
        k_rot = apply_rope(k_new, rope.cos, rope.sin)
        new = self._scatter_q(lk, lv, lks, lvs, k_rot, v_new, q_pos, num_new)
        out = quantized_paged_attention(
            q_rot, new[0], new[2], new[1], new[3], self.page_table,
            self.lengths + num_new, scale=scale,
            sliding_window=sliding_window,
        )
        return out, new

    def update_and_gather(self, layer_state, q, k_new, v_new, rope, q_pos,
                          num_new, sliding_window=None):
        """Gather fallback: contiguous int8 view dequantized to the model
        dtype (prefill / non-kernel decode)."""
        lk, lv, lks, lvs = layer_state
        b, s, hkv, d = k_new.shape
        q_rot = apply_rope(q, rope.cos, rope.sin)
        k_rot = apply_rope(k_new, rope.cos, rope.sin)
        new = self._scatter_q(lk, lv, lks, lvs, k_rot, v_new, q_pos, num_new)
        nk, nv, nks, nvs = new
        dt = q.dtype

        def view(pages, scales):
            g = jnp.take(pages, self.page_table, axis=0).astype(dt)
            sc = jnp.take(scales, self.page_table, axis=0).astype(dt)
            return (g * sc[..., None]).transpose(0, 1, 3, 2, 4).reshape(
                b, self.max_len, hkv, d
            )

        k_all = view(nk, nks)
        v_all = view(nv, nvs)
        kv_pos = jnp.broadcast_to(
            jnp.arange(self.max_len, dtype=jnp.int32)[None, :],
            (b, self.max_len),
        )
        kv_valid = kv_pos < (self.lengths + num_new)[:, None]
        mask = causal_mask(q_pos, kv_pos, kv_valid, sliding_window)
        return q_rot, k_all, v_all, mask, new

    # -- write-behind tail ----------------------------------------------------
    #
    # r3 redesign: the fused K-step window GATHERS each row's live pages to
    # contiguous head-major buffers once (``tail_big_stacks``) and runs the
    # same two-segment int8 attention as the quantized dense cache. The
    # previous design read pages in place via the Pallas kernel per layer per
    # step, but (profiled, b64 7B) the per-layer pool slices the scan feeds a
    # kernel operand MATERIALIZE a full pool copy each (~9.6 ms/step of pure
    # copies) and the per-page kernel grid pays ~3x the dense attention in
    # fixed per-step cost. Amortized over K steps the gather is ~2% of a
    # step; the pool itself stays read-only until ``tail_flush`` scatters the
    # window back.
    #
    # r4: PAST the context threshold below, the fused window reads the pool
    # in place again — but through ``quantized_paged_fused_attention``, which
    # takes the WHOLE ``[L, P, …]`` pool with the layer resolved in its block
    # index map (zero-copy, the mechanism r2 lacked) and the tail io-aliased
    # in-kernel. At long contexts the r3 gather's second contiguous copy of
    # the live KV was the binding constraint: it halved the admissible batch
    # (paged_kvq_1k capped at b8 while dense served b24) and re-copied the
    # whole working set every window.

    #: TABLE CAPACITY (``max_len`` = table width x page size) at/above which
    #: the fused window switches from gather-per-window to the in-place
    #: whole-pool kernel. The switch must be static per executable, so it
    #: keys on capacity — a faithful proxy for live context under the
    #: engine's growth ladder, which widens the table bucket-by-bucket as
    #: sessions lengthen (grow-disabled mesh configs sit at full capacity
    #: and always take the in-place form, a conservative choice). Below the
    #: threshold the gathered form wins (r3 measurement: +40% at 256-token
    #: contexts, where the gather is cheap and row-blocked 256-wide tiles
    #: beat per-page DMAs); above it the gather's second copy of the live
    #: KV dominates (halved admissible batch at 1k ctx).
    INPLACE_CTX = 768

    @property
    def _fused_inplace(self) -> bool:
        return self.use_kernel and self.max_len >= self.INPLACE_CTX

    def tail_big_stacks(self):
        """Read-only stacks for the fused window: past ``INPLACE_CTX`` the
        whole pool planes (in-place kernel); below it a contiguous
        head-major gather of every row's table span:
        ``(k [L,B,Hkv,Tmax,D] int8, v, ks [L,B,Hkv,Tmax] f32, vs)``. Unmapped
        table slots read the null page — masked by ``pos < base_len``."""
        if self._fused_inplace:
            return (self.k_pages, self.v_pages, self.ks_pages, self.vs_pages)
        table = self.page_table  # [B, T]

        def g(pages):  # [L, P, H, PS, D] → [L, B, H, T*PS, D]
            v = jnp.take(pages, table, axis=1)       # [L, B, T, H, PS, D]
            v = v.transpose(0, 1, 3, 2, 4, 5)        # [L, B, H, T, PS, D]
            l, b, h, t, ps, d = v.shape
            return v.reshape(l, b, h, t * ps, d)

        def gs(scales):  # [L, P, H, PS] → [L, B, H, T*PS]
            v = jnp.take(scales, table, axis=1).transpose(0, 1, 3, 2, 4)
            l, b, h, t, ps = v.shape
            return v.reshape(l, b, h, t * ps)

        return (
            g(self.k_pages), g(self.v_pages),
            gs(self.ks_pages), gs(self.vs_pages),
        )

    @property
    def _kernel_tail_ok(self) -> bool:
        """The gathered fused path feeds ``quantized_fused_decode_attention``
        whose io-aliased operands cannot pad — its time axis (= table
        capacity here) must be a 32 multiple, like the dense cache's gate;
        the in-place whole-pool kernel tiles by page instead and has no
        such constraint. Odd capacities (e.g. page_size 8 x 5 slots) keep
        the XLA segments path."""
        return self.use_kernel and (
            self._fused_inplace or self.max_len % 32 == 0
        )

    @property
    def tail_reads_whole_big(self) -> bool:
        """Kernel mode: the GATHERED contiguous stacks pass to the fused
        kernel whole (+ layer index) — slicing a layer out of them to feed
        the custom call would copy it through HBM every (layer, step)."""
        return self._kernel_tail_ok

    @property
    def tail_in_kernel(self) -> bool:
        return self._kernel_tail_ok

    def tail_init(self, k_steps: int):
        l = self.k_pages.shape[0]
        b = self.page_table.shape[0]
        hkv, d = self.k_pages.shape[2], self.k_pages.shape[4]
        if self._kernel_tail_ok:
            # int8 + scale planes, quantized IN-KERNEL with the same
            # symmetric absmax scheme ``_scatter_q`` uses — the flush
            # scatters these planes into the pool directly, so pool
            # contents are bit-identical to the per-step write path.
            # Distinct buffers: the kernel aliases each operand.
            return (
                jnp.zeros((l, b, hkv, k_steps, d), jnp.int8),
                jnp.zeros((l, b, hkv, k_steps, d), jnp.int8),
                jnp.zeros((l, b, hkv, k_steps), jnp.float32),
                jnp.zeros((l, b, hkv, k_steps), jnp.float32),
            )
        # bf16 head-major tail (quantized into pages only at flush, exactly
        # like the per-step path quantizes on write — pool contents match).
        z = jnp.zeros((l, b, hkv, k_steps, d), jnp.bfloat16)
        return (z, z)

    def tail_attend(self, big_state, tail_state, q, k_new, v_new, rope,
                    base_len, tail_len, step_idx, num_new, sliding_window,
                    scale=None):
        from ..ops.attention import gqa_attention_quantized_segments
        from .dense import segment_valids

        q_rot = apply_rope(q, rope.cos, rope.sin)
        k_rot = apply_rope(k_new, rope.cos, rope.sin)
        if self._kernel_tail_ok and q.shape[1] == 1:
            gk, gv, gks, gvs, lidx = big_state  # whole [L, ...] + layer idx
            tk, tv, tks, tvs = tail_state
            if self._fused_inplace:
                from ..ops.paged_attention import (
                    quantized_paged_fused_attention,
                )

                out, ntk, ntks, ntv, ntvs = quantized_paged_fused_attention(
                    q_rot, k_rot, v_new,
                    gk, gks, gv, gvs,
                    tk, tks, tv, tvs,
                    layer_idx=lidx, step_idx=step_idx,
                    page_table=self.page_table, base_len=base_len,
                    tail_valid_len=tail_len + num_new,
                    q_positions=base_len + tail_len,
                    scale=scale, sliding_window=sliding_window,
                )
                return out, (ntk, ntv, ntks, ntvs)
            from ..ops.quant_attention import (
                quantized_fused_decode_attention,
            )

            out, ntk, ntks, ntv, ntvs = quantized_fused_decode_attention(
                q_rot, k_rot, v_new,
                gk, gks, gv, gvs,
                tk, tks, tv, tvs,
                layer_idx=lidx, step_idx=step_idx,
                base_len=base_len, tail_valid_len=tail_len + num_new,
                q_positions=base_len + tail_len,
                scale=scale, sliding_window=sliding_window,
            )
            return out, (ntk, ntv, ntks, ntvs)
        gk, gv, gks, gvs = big_state   # [B, Hkv, Tmax, D] int8 / f32 scales
        tk, tv = tail_state            # [B, Hkv, K, D] bf16
        tk = jax.lax.dynamic_update_slice_in_dim(
            tk, jnp.moveaxis(k_rot, 1, 2).astype(tk.dtype), step_idx, axis=2
        )
        tv = jax.lax.dynamic_update_slice_in_dim(
            tv, jnp.moveaxis(v_new, 1, 2).astype(tv.dtype), step_idx, axis=2
        )
        big_valid, tail_valid = segment_valids(
            base_len, tail_len, num_new, gk.shape[2], tk.shape[2],
            sliding_window,
        )
        ones = jnp.ones(tk.shape[:3], jnp.float32)
        out = gqa_attention_quantized_segments(
            q_rot,
            [(gk, gks, gv, gvs, big_valid), (tk, ones, tv, ones, tail_valid)],
            scale,
        )
        return out, (tk, tv)

    def tail_flush(self, tail, tail_len):
        kk = tail[0].shape[3]
        q_pos = (
            self.lengths[:, None] + jnp.arange(kk, dtype=jnp.int32)[None, :]
        )
        num_new = tail_len
        if len(tail) == 4:  # kernel mode: pre-quantized int8 + scales
            wk, wv, wks, wvs = tail  # [L, B, Hkv, K, D] / [L, B, Hkv, K]
            if kk <= self.page_size:
                # Blocked page RMW (Pallas): the XLA scatter below prefers a
                # transposed pool layout, making XLA insert a whole-pool
                # relayout copy into the fused-decode executable (2x3.2 GB
                # HLO temp at b24 1k-ctx 7B — an OOM; a silent bandwidth tax
                # below that).
                from ..ops.paged_attention import paged_tail_flush

                new_k, new_ks, new_v, new_vs = paged_tail_flush(
                    self.k_pages, self.ks_pages, self.v_pages, self.vs_pages,
                    wk, wks, wv, wvs,
                    self.page_table, self.lengths, tail_len,
                )
                return self.replace(
                    k_pages=new_k, v_pages=new_v,
                    ks_pages=new_ks, vs_pages=new_vs,
                    lengths=self.lengths + tail_len,
                )
            new_k, new_v, new_ks, new_vs = jax.vmap(
                lambda lk, lv, lks, lvs, tkl, tvl, tksl, tvsl:
                self._scatter_planes(
                    lk, lv, lks, lvs,
                    jnp.moveaxis(tkl, 1, 2), jnp.moveaxis(tvl, 1, 2),
                    jnp.swapaxes(tksl, 1, 2), jnp.swapaxes(tvsl, 1, 2),
                    q_pos, num_new,
                )
            )(self.k_pages, self.v_pages, self.ks_pages, self.vs_pages,
              wk, wv, wks, wvs)
        else:
            wk, wv = tail  # [L, B, Hkv, K, D] bf16 (keys already rotated)
            new_k, new_v, new_ks, new_vs = jax.vmap(
                lambda lk, lv, lks, lvs, tkl, tvl: self._scatter_q(
                    lk, lv, lks, lvs,
                    jnp.moveaxis(tkl, 1, 2), jnp.moveaxis(tvl, 1, 2),
                    q_pos, num_new,
                )
            )(self.k_pages, self.v_pages, self.ks_pages, self.vs_pages, wk, wv)
        return self.replace(
            k_pages=new_k, v_pages=new_v, ks_pages=new_ks, vs_pages=new_vs,
            lengths=self.lengths + tail_len,
        )
