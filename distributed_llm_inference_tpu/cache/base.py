"""Shared cache behavior: the layer-state protocol and the ``attend`` step.

Every cache policy exposes its per-layer device state as a TUPLE of stacked
arrays (leading axis = layer): ``layer_stacks`` / ``with_layer_stacks``. The
model's scan (``models/llama.py:block_apply``) slices one layer's entry from
each stack and hands the tuple to ``attend``; the tuple shape lets cache
policies carry more than raw K/V — the int8-quantized cache threads
per-token/head scale planes alongside the value planes.

``attend`` is the single entry the model layer calls per decoder layer:
write the new k/v into the cache, run attention, return
``(attn_out, new_layer_state)``. The default implementation is the
always-correct XLA path — ``update_and_gather`` into a contiguous view, then
the caller-supplied ``attention_fn``. Cache policies override it to fuse
cache reads into a kernel (``PagedKVCache`` + ``ops/paged_attention.py``
reads pages in place at decode).
"""

from __future__ import annotations

from typing import Optional, Tuple


class GatherAttendMixin:
    """Default ``attend``: gather-to-contiguous + ``attention_fn``."""

    def attend(
        self,
        layer_state: Tuple,
        q,
        k_new,
        v_new,
        rope,
        q_pos,
        num_new,
        sliding_window: Optional[int],
        attention_fn,
        scale: Optional[float] = None,
    ):
        q_rot, k_all, v_all, mask, new_state = self.update_and_gather(
            layer_state, q, k_new, v_new, rope, q_pos, num_new,
            sliding_window=sliding_window,
        )
        return attention_fn(q_rot, k_all, v_all, mask, scale=scale), new_state
