"""Shared cache behavior: the ``attend`` step.

``attend`` is the single entry the model layer calls per decoder layer
(``models/llama.py:_decoder_layer``): write the new k/v into the cache, run
attention, return ``(attn_out, new_layer_k, new_layer_v)``. The default
implementation is the always-correct XLA path — ``update_and_gather`` into a
contiguous view, then the caller-supplied ``attention_fn``. Cache policies
override it to fuse cache reads into a kernel (``PagedKVCache`` +
``ops/paged_attention.py`` reads pages in place at decode).
"""

from __future__ import annotations

from typing import Optional


class GatherAttendMixin:
    """Default ``attend``: gather-to-contiguous + ``attention_fn``."""

    def attend(
        self,
        layer_k,
        layer_v,
        q,
        k_new,
        v_new,
        rope,
        q_pos,
        num_new,
        sliding_window: Optional[int],
        attention_fn,
        scale: Optional[float] = None,
    ):
        q_rot, k_all, v_all, mask, new_k, new_v = self.update_and_gather(
            layer_k, layer_v, q, k_new, v_new, rope, q_pos, num_new,
            sliding_window=sliding_window,
        )
        return attention_fn(q_rot, k_all, v_all, mask, scale=scale), new_k, new_v
