"""Shared cache behavior: the layer-state protocol and the ``attend`` step.

Every cache policy exposes its per-layer device state as a TUPLE of stacked
arrays (leading axis = layer): ``layer_stacks`` / ``with_layer_stacks``. The
model's scan (``models/llama.py:block_apply``) slices one layer's entry from
each stack and hands the tuple to ``attend``; the tuple shape lets cache
policies carry more than raw K/V — the int8-quantized cache threads
per-token/head scale planes alongside the value planes.

``attend`` is the single entry the model layer calls per decoder layer:
write the new k/v into the cache, run attention, return
``(attn_out, new_layer_state)``. The default implementation is the
always-correct XLA path — ``update_and_gather`` into a contiguous view, then
the caller-supplied ``attention_fn``. Cache policies override it to fuse
cache reads into a kernel (``PagedKVCache`` + ``ops/paged_attention.py``
reads pages in place at decode).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple


def window_ladder(
    cap: int,
    custom: Optional[Sequence[int]] = None,
    strict: bool = True,
) -> Tuple[int, ...]:
    """Buffer-size buckets for live-context growth: ~1.25x geometric,
    32-aligned, ending exactly at ``cap``. ``custom`` overrides the ladder
    ((), the empty ladder, disables growth); ``strict`` rejects a custom
    ladder lying entirely above ``cap``, non-strict callers get ``(cap,)``.
    Shared by the serving engine and the distributed block backend so the
    bucket arithmetic cannot drift between them."""
    if custom is not None:
        if not custom:
            return ()
        if any(w <= 0 for w in custom):
            raise ValueError(f"window buckets must be positive: {custom}")
        ws = tuple(sorted(w for w in custom if w <= cap))
        if not ws:
            if strict:
                raise ValueError(
                    f"every window bucket exceeds the cache capacity "
                    f"{cap}: {custom}"
                )
            return (cap,)
        return ws if ws[-1] == cap else ws + (cap,)
    ws, w = [], 32
    while w < cap:
        ws.append(w)
        nxt = ((int(w * 1.25) + 31) // 32) * 32
        w = nxt if nxt > w else w + 32
    ws.append(cap)
    return tuple(ws)


# Prefills at least this long route the quantized caches' attention through
# the flash kernel's gather path instead of the int8-score formulation: the
# materialized [B, Hq, S, T] scores turn dominant around S ~ 1k (measured 8B
# b1 device: S=512 int8-path 93 ms vs flash 119; S=2048 743 vs 593).
FLASH_PREFILL_MIN_S = 1024


def flash_prefill_fn(s: int, t: int, attention_fn):
    """The flash-for-long-prefill policy, in ONE place for every quantized
    cache kind: returns the flash kernel when the caller's default-attention
    prefill is long enough and tiles cleanly, else None (keep the int8-score
    path). ``s``/``t`` = query/buffer lengths."""
    from ..ops.attention import gqa_attention

    if (
        attention_fn is gqa_attention
        and s >= FLASH_PREFILL_MIN_S
        and s % 128 == 0
        and t % 128 == 0
    ):
        from ..ops.flash_attention import flash_attention

        return flash_attention
    return None


class GatherAttendMixin:
    """Default ``attend``: gather-to-contiguous + ``attention_fn``."""

    def attend(
        self,
        layer_state: Tuple,
        q,
        k_new,
        v_new,
        rope,
        q_pos,
        num_new,
        sliding_window: Optional[int],
        attention_fn,
        scale: Optional[float] = None,
    ):
        q_rot, k_all, v_all, mask, new_state = self.update_and_gather(
            layer_state, q, k_new, v_new, rope, q_pos, num_new,
            sliding_window=sliding_window,
        )
        return attention_fn(q_rot, k_all, v_all, mask, scale=scale), new_state
