"""Latent (low-rank) paged KV cache — MLA-style compression.

Instead of per-head K/V (``2 * Hkv * D`` values/token/layer) the pool
stores ONE fused latent per token: ``[c ; k_rope]`` where ``c`` is the
shared ``rank``-dim KV latent and ``k_rope`` the ``rope_head_dim``-dim
decoupled rotary key (``lat_dim = rank + rope_head_dim`` values/token).
At D=128, Hkv=8, rank=64, rope=16 that is a 32x raw reduction (bf16
baseline -> f32 latent still 12.8x), which shrinks together everything
priced in KV bytes/token: resident HBM, the disagg wire, migration
checkpoints, and the host spill arena.

The trick that makes one stored latent serve every query head with NO
per-token decompression is the absorbed-MLA formulation
(``models/llama.py:_latent_decoder_layer``): the key up-projection is
folded into the query (``q_lat[h] = q_nope[h] @ w_uk[h]``) and the value
up-projection is applied AFTER attention, so the attention itself runs
over the stored form — ``K = V = [c ; k_rope]`` with a single KV head.
Every existing paged kernel is generic over ``(Hkv, head_dim)``, so the
"fused decompression" is literally the kernels' existing page-table walk
reading the latent pool in place (``ops/ragged_attention.py:
latent_ragged_paged_attention`` and ``ops/paged_attention.py:
latent_paged_attention`` are the named entry points the AttentionPlan
selects).

Two consequences shape this module:

* Rope is applied by the MODEL (to the ``k_rope`` slice only, before the
  latent is handed to the cache) — the latent itself is position-free.
  So unlike every other cache, ``attend``/``update_and_gather`` must NOT
  re-apply rope; ``k_new`` arrives in stored form.
* The pool is the serialization format. Stored planes are ``c`` (f32
  ``[lat_dim]`` per token) or ``c``+``cs`` (int8 + per-token f32 scale),
  flowing unchanged through export/ingest/spill/page-ship — the same
  page/refcount/CoW machinery as the parent, via ``PLANE_FIELDS``.

``v_pages`` survives as a 1-element placeholder (flax dataclass fields
cannot be removed in a subclass); no code path reads it — every pool
consumer walks ``PLANE_FIELDS``/``LAYER_FIELDS``, which name only the
latent planes.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..ops.attention import causal_mask
from .paged import PagedKVCache

__all__ = ["LatentPagedKVCache", "QuantizedLatentPagedKVCache"]


class LatentPagedKVCache(PagedKVCache):
    """Paged pool storing one f32 ``[lat_dim]`` latent per token.

    ``k_pages``: ``[L, num_pages, 1, page_size, lat_dim]`` f32 — the
    fused ``[c ; k_rope]`` stored form (f32: the latent is the ONLY copy
    of the KV information; rounding it to bf16 at rank ~64 measurably
    moves logits, and the byte win over per-head K/V is already >10x).
    """

    LAYER_FIELDS = ("k_pages",)
    SHARED_FIELDS = ("k_pages",)
    PLANE_FIELDS = {"c": "k_pages"}

    @staticmethod
    def create(
        num_layers: int,
        batch: int,
        num_pages: int,
        page_size: int,
        max_pages_per_session: int,
        num_kv_heads: int,
        lat_dim: int,
        dtype=jnp.float32,  # interface parity; the stored form is f32
        use_kernel: bool = False,
        use_ragged: bool = False,
    ) -> "LatentPagedKVCache":
        if num_kv_heads != 1:
            raise ValueError(
                f"latent cache stores ONE shared latent head, got "
                f"num_kv_heads={num_kv_heads}"
            )
        shape = (num_layers, num_pages, 1, page_size, lat_dim)
        return LatentPagedKVCache(
            k_pages=jnp.zeros(shape, jnp.float32),
            v_pages=jnp.zeros((num_layers, 1, 1, 1, 1), jnp.float32),
            page_table=jnp.zeros((batch, max_pages_per_session), jnp.int32),
            lengths=jnp.zeros((batch,), jnp.int32),
            page_size=page_size,
            use_kernel=use_kernel,
            use_ragged=use_ragged,
        )

    @property
    def lat_dim(self) -> int:
        return self.k_pages.shape[-1]

    @property
    def layer_stacks(self):
        return (self.k_pages,)

    def with_layer_stacks(self, new_c) -> "LatentPagedKVCache":
        return self.replace(k_pages=new_c)

    # -- pool writes / reads ------------------------------------------------
    def _scatter_latent(self, layer_state, c_new, q_pos, num_new):
        """Scatter incoming fused latents ``[B, S, 1, lat_dim]`` into the
        page pool (the parent's :meth:`_scatter` write pattern, one
        plane)."""
        (layer_c,) = layer_state
        b, s, _, d = c_new.shape
        phys_page, offset_bs = self._slot_pages(q_pos, num_new)
        if s == 1:
            page = phys_page[:, 0]
            offset = offset_bs[:, 0]

            def body(r, buf):
                cv = c_new[r, 0][:, None, :].astype(buf.dtype)  # [1, 1, D]
                return jax.lax.dynamic_update_slice(
                    buf, cv[None], (page[r], 0, offset[r], 0)
                )

            return (jax.lax.fori_loop(0, b, body, layer_c),)
        new_c = layer_c.at[
            phys_page.reshape(-1), :, offset_bs.reshape(-1)
        ].set(c_new.reshape(b * s, 1, d).astype(layer_c.dtype), mode="drop")
        return (new_c,)

    def _contiguous_view(self, layer_state, batch, dt):
        """Gather each row's pages into ``[B, max_len, 1, lat_dim]``."""
        (new_c,) = layer_state
        return jnp.take(new_c, self.page_table, axis=0).transpose(
            0, 1, 3, 2, 4
        ).reshape(batch, self.max_len, 1, self.lat_dim).astype(dt)

    # -- attention ----------------------------------------------------------
    def attend(self, layer_state, q, k_new, v_new, rope, q_pos, num_new,
               sliding_window, attention_fn, scale=None):
        """``q`` is the absorbed query ``[B, S, Hq, lat_dim]`` and
        ``k_new`` (== ``v_new``) the fused latent — both already carry
        rope on their ``k_rope`` slice, so no path here rotates
        anything. Kernel paths read the latent pool in place."""
        new_state = self._scatter_latent(layer_state, k_new, q_pos, num_new)
        if self.use_ragged and q.shape[1] > 1:
            from ..ops.ragged_attention import latent_ragged_paged_attention

            out = latent_ragged_paged_attention(
                q, new_state[0], self.page_table, self.lengths + num_new,
                num_new, scale=scale, sliding_window=sliding_window,
            )
            return out, new_state
        if self.use_kernel and q.shape[1] == 1:
            from ..ops.paged_attention import latent_paged_attention

            out = latent_paged_attention(
                q, new_state[0], self.page_table, self.lengths + num_new,
                scale=scale, sliding_window=sliding_window,
            )
            return out, new_state
        c_all = self._contiguous_view(new_state, q.shape[0], q.dtype)
        mask = self._latent_mask(q.shape[0], q_pos, num_new, sliding_window)
        return attention_fn(q, c_all, c_all, mask, scale=scale), new_state

    def _latent_mask(self, b, q_pos, num_new, sliding_window):
        kv_pos = jnp.broadcast_to(
            jnp.arange(self.max_len, dtype=jnp.int32)[None, :],
            (b, self.max_len),
        )
        kv_valid = kv_pos < (self.lengths + num_new)[:, None]
        return causal_mask(q_pos, kv_pos, kv_valid, sliding_window)

    def update_and_gather(self, layer_state, q, k_new, v_new, rope, q_pos,
                          num_new, sliding_window: Optional[int] = None):
        """Gather fallback view (NO rope — see :meth:`attend`)."""
        new_state = self._scatter_latent(layer_state, k_new, q_pos, num_new)
        c_all = self._contiguous_view(new_state, q.shape[0], q.dtype)
        mask = self._latent_mask(q.shape[0], q_pos, num_new, sliding_window)
        return q, c_all, c_all, mask, new_state

    # -- serialization ------------------------------------------------------
    def ingest_row(self, ks, vs, n_valid, first_slot=0):
        raise TypeError(
            "latent cache has no k/v planes; use ingest_latent_row"
        )

    def ingest_latent_row(self, planes, n_valid, first_slot=0):
        """Install STORED-form latent planes (``{"c": [L, 1, S, 1,
        lat_dim]}``, plus ``"cs"`` scales on the int8 pool) bit-exact —
        the latent counterpart of ``ingest_planes_row``; shares the
        parent's page-chunk scatter via ``PLANE_FIELDS``."""
        if set(planes) != set(self.PLANE_FIELDS):
            raise ValueError(
                f"latent ingest planes {sorted(planes)} != "
                f"{sorted(self.PLANE_FIELDS)}"
            )
        return self._ingest_planes(
            {self.PLANE_FIELDS[name]: a for name, a in planes.items()},
            n_valid,
            first_slot,
        )

    # -- write-behind tail: never used (the engine's tail gate excludes
    # latent caches — the parent's tail re-applies rope, which would
    # corrupt the pre-rotated stored form). Fail loudly if reached.
    def tail_init(self, k_steps: int):
        raise NotImplementedError("latent cache has no write-behind tail")


class QuantizedLatentPagedKVCache(LatentPagedKVCache):
    """Latent pool in int8 with per-token f32 scales.

    ``k_pages``: int8 ``[L, P, 1, PS, lat_dim]``; ``cs_pages``: f32
    ``[L, P, 1, PS]`` (one absmax scale per token per layer — the fused
    latent is a single "head"). ~4x the f32 form's density at ~0.4%
    scale overhead; the gather path dequantizes its contiguous view, the
    kernel path dequantizes on the scores exactly like the per-head int8
    pool."""

    # Dataclass inheritance: fields after the parent's defaulted ones need
    # defaults; create() always supplies real arrays.
    cs_pages: jax.Array = None

    LAYER_FIELDS = ("k_pages", "cs_pages")
    SHARED_FIELDS = ("k_pages", "cs_pages")
    PLANE_FIELDS = {"c": "k_pages", "cs": "cs_pages"}

    @staticmethod
    def create(
        num_layers: int,
        batch: int,
        num_pages: int,
        page_size: int,
        max_pages_per_session: int,
        num_kv_heads: int,
        lat_dim: int,
        dtype=jnp.float32,  # interface parity; values are int8
        use_kernel: bool = False,
        use_ragged: bool = False,
    ) -> "QuantizedLatentPagedKVCache":
        if num_kv_heads != 1:
            raise ValueError(
                f"latent cache stores ONE shared latent head, got "
                f"num_kv_heads={num_kv_heads}"
            )
        shape = (num_layers, num_pages, 1, page_size, lat_dim)
        return QuantizedLatentPagedKVCache(
            k_pages=jnp.zeros(shape, jnp.int8),
            v_pages=jnp.zeros((num_layers, 1, 1, 1, 1), jnp.float32),
            cs_pages=jnp.zeros(shape[:-1], jnp.float32),
            page_table=jnp.zeros((batch, max_pages_per_session), jnp.int32),
            lengths=jnp.zeros((batch,), jnp.int32),
            page_size=page_size,
            use_kernel=use_kernel,
            use_ragged=use_ragged,
        )

    @property
    def layer_stacks(self):
        return (self.k_pages, self.cs_pages)

    def with_layer_stacks(self, new_c, new_cs) -> "QuantizedLatentPagedKVCache":
        return self.replace(k_pages=new_c, cs_pages=new_cs)

    def merge_row(self, sub, row) -> "QuantizedLatentPagedKVCache":
        return super().merge_row(sub, row).replace(cs_pages=sub.cs_pages)

    def _scatter_latent(self, layer_state, c_new, q_pos, num_new):
        from .dense import _quantize_kv

        layer_c, layer_cs = layer_state
        b, s, _, d = c_new.shape
        c_q, c_s = _quantize_kv(c_new)  # int8 [B,S,1,D] / f32 [B,S,1]
        phys_page, offset_bs = self._slot_pages(q_pos, num_new)
        if s == 1:
            page = phys_page[:, 0]
            offset = offset_bs[:, 0]

            def body(r, bufs):
                bc, bcs = bufs
                cv = c_q[r, 0][:, None, :]
                sv = c_s[r, 0][:, None]
                return (
                    jax.lax.dynamic_update_slice(
                        bc, cv[None], (page[r], 0, offset[r], 0)
                    ),
                    jax.lax.dynamic_update_slice(
                        bcs, sv[None], (page[r], 0, offset[r])
                    ),
                )

            return jax.lax.fori_loop(0, b, body, (layer_c, layer_cs))
        flat_page = phys_page.reshape(-1)
        flat_off = offset_bs.reshape(-1)
        return (
            layer_c.at[flat_page, :, flat_off].set(
                c_q.reshape(b * s, 1, d), mode="drop"
            ),
            layer_cs.at[flat_page, :, flat_off].set(
                c_s.reshape(b * s, 1), mode="drop"
            ),
        )

    def _contiguous_view(self, layer_state, batch, dt):
        new_c, new_cs = layer_state
        g = jnp.take(new_c, self.page_table, axis=0).astype(dt)
        sc = jnp.take(new_cs, self.page_table, axis=0).astype(dt)
        return (g * sc[..., None]).transpose(0, 1, 3, 2, 4).reshape(
            batch, self.max_len, 1, self.lat_dim
        )

    def attend(self, layer_state, q, k_new, v_new, rope, q_pos, num_new,
               sliding_window, attention_fn, scale=None):
        new_state = self._scatter_latent(layer_state, k_new, q_pos, num_new)
        if self.use_ragged and q.shape[1] > 1:
            from ..ops.ragged_attention import (
                quantized_latent_ragged_paged_attention,
            )

            out = quantized_latent_ragged_paged_attention(
                q, new_state[0], new_state[1], self.page_table,
                self.lengths + num_new, num_new,
                scale=scale, sliding_window=sliding_window,
            )
            return out, new_state
        if self.use_kernel and q.shape[1] == 1:
            from ..ops.paged_attention import quantized_latent_paged_attention

            out = quantized_latent_paged_attention(
                q, new_state[0], new_state[1], self.page_table,
                self.lengths + num_new,
                scale=scale, sliding_window=sliding_window,
            )
            return out, new_state
        c_all = self._contiguous_view(new_state, q.shape[0], q.dtype)
        mask = self._latent_mask(q.shape[0], q_pos, num_new, sliding_window)
        return attention_fn(q, c_all, c_all, mask, scale=scale), new_state
