"""Dense (contiguous, preallocated) KV cache — bf16 and int8-quantized.

The simplest of the cache policies (dense / paged / sink). Unlike the
reference's ``torch.cat`` growth pattern
(``/root/reference/distributed_llm_inference/models/llama/cache.py:108-109``),
the buffer is preallocated at ``max_seq_len`` and written with per-row
``dynamic_update_slice`` — XLA requires static shapes, and a fixed buffer also
means decode steps always hit the same compiled executable (the role CUDA-graph
capture plays in the reference, ``utils/cuda.py:6``).

Batch rows are independent sessions with their own write offsets
(``lengths``), which is what makes continuous batching possible: the
``generation_id``-keyed dict-of-tensors in the reference
(``models/llama/cache.py:14-19``) becomes integer slot indexing into the batch
dimension.

:class:`QuantizedDenseKVCache` stores K/V as int8 with per-(token, head)
fp32 scales — decode attention reads the whole active KV working set every
step, so halving KV bytes directly buys decode bandwidth (KV traffic
dominates weights at large batch). Dequantization is a broadcast multiply
fused by XLA into the attention operand read; scales ride the layer-state
tuple alongside the value planes (see ``cache/base.py``).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from flax import struct

from ..ops.attention import causal_mask
from ..ops.rotary import RopeAngles, apply_rope
from .base import GatherAttendMixin, flash_prefill_fn


def _tail_flush_rows(big, tail, lengths, tail_len, axis):
    """Merge a write-behind tail into the big buffer at per-row offsets.

    ``big``/``tail``: ``[L, B, …]`` with the time axis (length ``T`` / ``K``)
    at per-row axis ``axis`` (coordinates of the ``[L, …]`` row view; the
    full-array axis is ``axis + 1``, batch being axis 1). A vectorized
    gather+select, chunked over GROUPS of layers: the whole-stack form holds
    two full-cache-sized temps live (shrinks the largest servable batch by
    ~25% in the 7B-in-16GB fit), while per-layer chunking (or per-row
    slice/merge/write-back) pays heavy per-iteration overhead / crashes the
    compiler. ~8-layer slabs keep temps <1/4 of the cache with near-zero
    iteration cost.
    """
    kk = tail.shape[axis + 1]
    b = big.shape[1]
    t = big.shape[axis + 1]
    nd = big.ndim
    src = jnp.arange(t, dtype=jnp.int32)[None, :] - lengths[:, None]  # [B, T]
    sel = (src >= 0) & (src < tail_len[:, None])
    shp = [1] * nd
    shp[1] = b
    shp[axis + 1] = t
    idx = jnp.clip(src, 0, kk - 1).reshape(shp)
    selb = sel.reshape(shp)

    def merge(args):
        big_c, tail_c = args  # [chunk, B, …]
        return jnp.where(
            selb, jnp.take_along_axis(tail_c, idx, axis=axis + 1), big_c
        )

    num_layers = big.shape[0]
    chunk = next((c for c in (8, 4, 2) if num_layers % c == 0), 1)
    if chunk == 1 or num_layers <= chunk:
        return merge((big, tail))
    groups = num_layers // chunk
    gshape = lambda a: (groups, chunk) + a.shape[1:]
    out = jax.lax.map(
        lambda args: merge(args),
        (big.reshape(gshape(big)), tail.reshape(gshape(tail))),
    )
    return out.reshape(big.shape)


def segment_valids(base_len, tail_len, num_new, t, kk, sliding_window):
    """Validity masks ``([B, T], [B, K])`` for the (big, tail) segments of
    the fused decode — shared by the bf16/int8 dense ``tail_attend`` and the
    gathered paged tail so the window/validity rules cannot diverge."""
    q_pos = base_len + tail_len
    big_pos = jnp.arange(t, dtype=jnp.int32)[None, :]
    big_valid = big_pos < base_len[:, None]
    tail_pos = (
        base_len[:, None] + jnp.arange(kk, dtype=jnp.int32)[None, :]
    )
    tail_valid = (
        jnp.arange(kk, dtype=jnp.int32)[None, :]
        < (tail_len + num_new)[:, None]
    )
    if sliding_window is not None:
        big_valid &= big_pos > (q_pos[:, None] - sliding_window)
        tail_valid &= tail_pos > (q_pos[:, None] - sliding_window)
    return big_valid, tail_valid


class _DenseRowsMixin(GatherAttendMixin):
    """Shared row bookkeeping for contiguous per-row caches: absolute
    positions from ``lengths``, bucket-safe writes, causal masking, and
    generic (BATCH_AXES-driven) row slicing."""

    def q_positions(self, seq_len: int) -> jnp.ndarray:
        """Absolute positions of the incoming tokens: ``[B, S]``."""
        return self.lengths[:, None] + jnp.arange(seq_len, dtype=jnp.int32)[None, :]

    def rope_positions(self, seq_len: int, num_new: jnp.ndarray) -> jnp.ndarray:
        """Positions at which incoming queries are rotated (= absolute here;
        the sink cache overrides this with window-relative positions)."""
        return self.q_positions(seq_len)

    def fits(self, num_new) -> jnp.ndarray:
        """Per-row: can ``num_new`` more tokens be appended without overflow?

        The scheduler MUST check this before admitting tokens: past capacity,
        writes are dropped (see ``_write``) and the overflowing tokens
        silently never enter the cache (engine contract).
        """
        return self.lengths + num_new <= self.max_len

    def advance(self, num_new: jnp.ndarray):
        return self.replace(lengths=self.lengths + num_new)

    def reset_rows(self, row_mask: jnp.ndarray):
        """Zero the lengths of rows where ``row_mask`` is True (slot reuse for
        a new session — the analog of a fresh ``generation_id``, reference
        ``models/llama/cache.py:78-84``). Stale k/v need no clearing: validity
        derives from ``lengths``."""
        return self.replace(lengths=jnp.where(row_mask, 0, self.lengths))

    def _fields(self):
        return [
            f.name for f in dataclasses.fields(self)
            if f.metadata.get("pytree_node", True)
        ]

    def select_row(self, row):
        """Batch-1 view of one session row (jit-safe, ``row`` may be traced).
        Used by the engine to prefill a newly admitted session without
        touching (or recomputing over) the other rows."""
        return self.replace(**{
            name: jax.lax.dynamic_slice_in_dim(
                getattr(self, name), row, 1, axis=self.BATCH_AXES[name]
            )
            for name in self._fields()
        })

    def merge_row(self, sub, row):
        return self.replace(**{
            name: jax.lax.dynamic_update_slice_in_dim(
                getattr(self, name), getattr(sub, name), row,
                axis=self.BATCH_AXES[name],
            )
            for name in self._fields()
        })

    def select_rows(self, rows):
        """Compact ``len(rows)``-row view (jit-safe, ``rows`` traced int32
        ``[NR]``). Padding entries use an OUT-OF-RANGE row index: the
        gather clamps them (content irrelevant — their ``num_new = 0``
        prefill never writes) and :meth:`merge_rows` drops their
        write-back. (Padding by DUPLICATING a real row corrupts it: a
        duplicate-index scatter with differing values is undefined-order,
        and the stale pad copy can win over the real row's fresh KV.) The
        batched-admission prefill runs ONE bucketed dispatch over k
        freshly admitted sessions instead of k sequential single-row
        prefills (each a full weight sweep + a tunnel round trip)."""
        def take(name):
            ax = self.BATCH_AXES[name]
            return jnp.take(getattr(self, name), rows, axis=ax, mode="clip")

        return self.replace(**{name: take(name) for name in self._fields()})

    def merge_rows(self, sub, rows):
        """Scatter a :meth:`select_rows` sub-cache back; out-of-range
        (padding) rows drop."""
        def put(name):
            ax = self.BATCH_AXES[name]
            idx = (slice(None),) * ax + (rows,)
            return getattr(self, name).at[idx].set(
                getattr(sub, name), mode="drop"
            )

        return self.replace(**{name: put(name) for name in self._fields()})

    def _write(self, layer_buf, new_vals, num_new):
        """Merge incoming ``[B, S, ...]`` rows into ``[B, T, ...]`` at each
        row's write offset (``lengths``)."""
        b, s = new_vals.shape[:2]
        t = layer_buf.shape[1]
        if s == 1:
            # Decode hot path: single-token contiguous write. Always in
            # bounds — the scheduler's capacity check guarantees
            # ``lengths + 1 <= max_len`` for active rows — and it partitions
            # cleanly under SPMD (a scatter here ABORTS in GSPMD inside the
            # shard_map pipeline; and the per-row traced offsets make this
            # vmap lower to a serial while over rows on TPU, ~26ms/step at
            # batch 80 7B shapes — the write-behind decode path in
            # ``llama.multi_decode_apply`` exists to keep this off the hot
            # loop).
            # Inactive rows (num_new == 0) must write NOTHING: their offset
            # may sit at a full buffer's end, where the DUS clamp would
            # overwrite the row's last real token (an idle co-batched
            # session would silently corrupt). Re-writing the old value
            # keeps the write unconditional but harmless.
            def write_row(buf, val, start, n):
                start_idx = (start,) + (0,) * (buf.ndim - 1)
                old = jax.lax.dynamic_slice(buf, start_idx, val.shape)
                return jax.lax.dynamic_update_slice(
                    buf, jnp.where(n > 0, val, old), start_idx
                )

            return jax.vmap(write_row)(
                layer_buf, new_vals, self.lengths, num_new
            )
        # Prefill: the chunk is padded to a bucket that may extend past
        # the buffer end (bucket > remaining capacity), where a contiguous
        # dynamic_update_slice would either fail to compile (update wider
        # than operand) or clamp the start offset and silently overwrite
        # earlier tokens. Rebuild the buffer as a gather + select instead
        # (SPMD-friendly, unlike a scatter): buffer position p takes
        # incoming row ``p - lengths`` when that lies in [0, num_new).
        src = (
            jnp.arange(t, dtype=jnp.int32)[None, :] - self.lengths[:, None]
        )  # [B, T]: index into the incoming chunk
        take = (src >= 0) & (src < num_new[:, None])
        extra = new_vals.ndim - 2
        idx = jnp.clip(src, 0, s - 1).reshape(b, t, *([1] * extra))
        sel = take.reshape(b, t, *([1] * extra))
        return jnp.where(
            sel, jnp.take_along_axis(new_vals, idx, axis=1), layer_buf
        )

    def grow_to(self, new_len: int):
        """Zero-pad every layer-stacked buffer's time axis (2) to
        ``new_len`` — the growth-ladder step shared by the engine and the
        block backend."""
        pad = new_len - self.max_len
        if pad <= 0:
            return self

        def grow(a):
            widths = [(0, 0)] * a.ndim
            widths[2] = (0, pad)
            return jnp.pad(a, widths)

        return self.with_layer_stacks(*(grow(a) for a in self.layer_stacks))

    def _mask(self, q, q_pos, num_new, sliding_window):
        t = self.max_len
        kv_pos = jnp.broadcast_to(
            jnp.arange(t, dtype=jnp.int32)[None, :], (q.shape[0], t)
        )
        kv_valid = kv_pos < (self.lengths + num_new)[:, None]
        return causal_mask(q_pos, kv_pos, kv_valid, sliding_window)

    def _segment_valids(self, base_len, tail_len, num_new, t, kk,
                        sliding_window):
        return segment_valids(base_len, tail_len, num_new, t, kk,
                              sliding_window)


class DenseKVCache(_DenseRowsMixin, struct.PyTreeNode):
    """``k``/``v``: ``[L, B, T, Hkv, D]`` (keys stored rotated); ``lengths``: ``[B]``."""

    k: jax.Array
    v: jax.Array
    lengths: jax.Array

    # Declarative layout for generic consumers (pipeline row slicing, pp
    # sharding specs): field → batch axis; fields with a leading layer axis.
    BATCH_AXES = {"k": 1, "v": 1, "lengths": 0}
    LAYER_FIELDS = ("k", "v")

    @staticmethod
    def create(
        num_layers: int,
        batch: int,
        max_seq_len: int,
        num_kv_heads: int,
        head_dim: int,
        dtype=jnp.bfloat16,
    ) -> "DenseKVCache":
        shape = (num_layers, batch, max_seq_len, num_kv_heads, head_dim)
        return DenseKVCache(
            k=jnp.zeros(shape, dtype),
            v=jnp.zeros(shape, dtype),
            lengths=jnp.zeros((batch,), jnp.int32),
        )

    @property
    def max_len(self) -> int:
        return self.k.shape[2]

    @property
    def layer_stacks(self):
        """Per-layer stacks (leading dim = layers) for the model's scan."""
        return (self.k, self.v)

    def with_layer_stacks(self, new_k, new_v) -> "DenseKVCache":
        return self.replace(k=new_k, v=new_v)

    def update_and_gather(
        self,
        layer_state: Tuple[jnp.ndarray, ...],
        q: jnp.ndarray,
        k_new: jnp.ndarray,
        v_new: jnp.ndarray,
        rope: RopeAngles,
        q_pos: jnp.ndarray,
        num_new: jnp.ndarray,
        sliding_window: Optional[int] = None,
    ) -> Tuple[jnp.ndarray, ...]:
        """Rotate q/k, write k/v into this layer's buffer, build the mask.

        ``layer_state``: ``(layer_k, layer_v)``, each ``[B, T, Hkv, D]`` (one
        layer's slice, as delivered by ``lax.scan`` over the leading layer
        axis). ``rope`` holds cos/sin precomputed once per block for
        ``q_pos``. Returns ``(q_rot, k_all, v_all, mask, new_layer_state)``.
        """
        layer_k, layer_v = layer_state
        q_rot = apply_rope(q, rope.cos, rope.sin)
        k_rot = apply_rope(k_new, rope.cos, rope.sin)
        new_k = self._write(layer_k, k_rot, num_new)
        new_v = self._write(layer_v, v_new, num_new)
        mask = self._mask(q, q_pos, num_new, sliding_window)
        return q_rot, new_k, new_v, mask, (new_k, new_v)

    def ingest_row(self, ks, vs, n_valid):
        """Install ring-prefill KV — ``[L, B, S, Hkv, D]``, keys already
        rotated (``parallel/ring.py:ring_prefill`` output; ``B`` matches this
        cache's batch, 1 for the engine's per-admission sub-cache) — as the
        rows' prefix; ``lengths`` ← ``n_valid`` (scalar or ``[B]``). ``S``
        beyond ``max_len`` is cropped (ring buckets round up past the buffer;
        callers guarantee ``n_valid <= max_len``)."""
        t = self.max_len
        s = ks.shape[2]
        if s >= t:
            k_new, v_new = ks[:, :, :t], vs[:, :, :t]
        else:
            pad = [(0, 0), (0, 0), (0, t - s), (0, 0), (0, 0)]
            k_new, v_new = jnp.pad(ks, pad), jnp.pad(vs, pad)
        lengths = jnp.broadcast_to(
            jnp.asarray(n_valid, jnp.int32), self.lengths.shape
        )
        return self.replace(
            k=k_new.astype(self.k.dtype),
            v=v_new.astype(self.v.dtype),
            lengths=lengths,
        )

    # -- write-behind tail (fused multi-step decode) --------------------------

    def tail_init(self, k_steps: int):
        l, b, t, h, d = self.k.shape
        z = jnp.zeros((l, b, k_steps, h, d), self.k.dtype)
        return (z, z)

    def tail_attend(self, big_state, tail_state, q, k_new, v_new, rope,
                    base_len, tail_len, step_idx, num_new, sliding_window,
                    scale=None):
        """Two-segment attention: the big buffer stays read-only; the new
        token's k/v lands in the tail at scalar slot ``step_idx`` (one
        vectorized write — see ``multi_decode_apply``)."""
        from ..ops.attention import gqa_attention_segments

        big_k, big_v = big_state
        tk, tv = tail_state
        q_rot = apply_rope(q, rope.cos, rope.sin)
        k_rot = apply_rope(k_new, rope.cos, rope.sin)
        tk = jax.lax.dynamic_update_slice_in_dim(tk, k_rot, step_idx, axis=1)
        tv = jax.lax.dynamic_update_slice_in_dim(tv, v_new, step_idx, axis=1)

        big_valid, tail_valid = self._segment_valids(
            base_len, tail_len, num_new, big_k.shape[1], tk.shape[1],
            sliding_window,
        )
        out = gqa_attention_segments(
            q_rot,
            [(big_k, big_v, big_valid), (tk, tv, tail_valid)],
            scale,
        )
        return out, (tk, tv)

    def tail_flush(self, tail, tail_len):
        """Merge the tail into the big buffers (per-row K-token windows,
        amortized over the K fused steps) and advance lengths."""
        wk, wv = tail  # [L, B, K, Hkv, D]
        return self.replace(
            k=_tail_flush_rows(self.k, wk, self.lengths, tail_len, axis=1),
            v=_tail_flush_rows(self.v, wv, self.lengths, tail_len, axis=1),
            lengths=self.lengths + tail_len,
        )


def _quantize_kv(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-(token, head) symmetric int8: ``x`` ``[B, S, H, D]`` →
    ``(q int8 [B, S, H, D], scale f32 [B, S, H])``."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(
        jnp.round(x.astype(jnp.float32) / scale[..., None]), -127, 127
    ).astype(jnp.int8)
    return q, scale


class QuantizedDenseKVCache(_DenseRowsMixin, struct.PyTreeNode):
    """Dense cache with int8 K/V + per-(token, head) fp32 scales.

    ``k``/``v``: int8 ``[L, B, Hkv, T, D]``; ``ks``/``vs``: f32
    ``[L, B, Hkv, T]`` (≈3% byte overhead at D=128). The layout is
    HEAD-major (time axis 3, unlike the bf16 cache's ``[L, B, T, Hkv, D]``):
    the attention contractions then consume the int8 buffers directly with no
    transpose, which is what lets XLA keep the int8→bf16 convert inside the
    dot instead of materializing a bf16 copy of K and V every decode step.
    The reference's cache is unquantized fp16 torch tensors
    (``models/llama/cache.py``); int8 KV is the TPU-native bandwidth play for
    the decode path, analogous to its bitsandbytes int8 *weights*
    (``utils/model.py:93-123``) applied to the cache instead.
    """

    k: jax.Array
    v: jax.Array
    ks: jax.Array
    vs: jax.Array
    lengths: jax.Array
    # Decode via the Pallas kernel (ops/quant_attention.py): int8 K/V stream
    # through VMEM once instead of XLA materializing bf16 copies each step.
    use_kernel: bool = struct.field(pytree_node=False, default=False)

    BATCH_AXES = {"k": 1, "v": 1, "ks": 1, "vs": 1, "lengths": 0}
    LAYER_FIELDS = ("k", "v", "ks", "vs")

    @staticmethod
    def create(
        num_layers: int,
        batch: int,
        max_seq_len: int,
        num_kv_heads: int,
        head_dim: int,
        dtype=jnp.bfloat16,  # accepted for interface parity; values are int8
        use_kernel: bool = False,
    ) -> "QuantizedDenseKVCache":
        shape = (num_layers, batch, num_kv_heads, max_seq_len, head_dim)
        sshape = shape[:-1]
        return QuantizedDenseKVCache(
            k=jnp.zeros(shape, jnp.int8),
            v=jnp.zeros(shape, jnp.int8),
            ks=jnp.zeros(sshape, jnp.float32),
            vs=jnp.zeros(sshape, jnp.float32),
            lengths=jnp.zeros((batch,), jnp.int32),
            use_kernel=use_kernel,
        )

    @property
    def max_len(self) -> int:
        return self.k.shape[3]

    @property
    def _kernel_tail_ok(self) -> bool:
        """Kernel-mode fused tail requires a 32-aligned time axis: the
        io-aliased whole-stack operands cannot be padded (engine buffers
        are always 32-aligned via the window ladder; direct API users with
        odd buffers keep the XLA segments path end to end)."""
        return self.use_kernel and self.max_len % 32 == 0

    @property
    def tail_reads_whole_big(self) -> bool:
        """Fused decode passes the big K/V stacks UNSLICED (plus a layer
        index) so the Pallas kernel reads the cache in place — slicing a
        layer out of the stack to feed a custom call copies it through HBM
        every (layer, step), which measured ~3x decode cost at batch 112."""
        return self._kernel_tail_ok

    @property
    def layer_stacks(self):
        return (self.k, self.v, self.ks, self.vs)

    def with_layer_stacks(self, k, v, ks, vs) -> "QuantizedDenseKVCache":
        return self.replace(k=k, v=v, ks=ks, vs=vs)

    def _write(self, layer_buf, new_vals, num_new):
        """Head-major write: incoming ``[B, S, Hkv(, D)]`` rows merged into
        ``[B, Hkv, T(, D)]`` at each row's offset (cf. the time-major mixin
        version, whose regimes this mirrors)."""
        b, s = new_vals.shape[:2]
        t = layer_buf.shape[2]
        nv = jnp.moveaxis(new_vals, 1, 2)  # [B, Hkv, S(, D)]
        if s == 1:
            # Per-row DUS (see the time-major mixin's notes: scatter aborts
            # under GSPMD; inactive rows re-write the old value so a clamped
            # offset cannot corrupt; the fused multi-step decode keeps this
            # write off the hot path).
            def write_row(buf, val, start, n):
                start_idx = (0, start) + (0,) * (buf.ndim - 2)
                old = jax.lax.dynamic_slice(buf, start_idx, val.shape)
                return jax.lax.dynamic_update_slice(
                    buf, jnp.where(n > 0, val, old), start_idx
                )

            return jax.vmap(write_row)(layer_buf, nv, self.lengths, num_new)
        src = (
            jnp.arange(t, dtype=jnp.int32)[None, :] - self.lengths[:, None]
        )  # [B, T]
        take = (src >= 0) & (src < num_new[:, None])
        extra = nv.ndim - 3  # 1 for k/v (trailing D), 0 for scale planes
        idx = jnp.clip(src, 0, s - 1).reshape(b, 1, t, *([1] * extra))
        sel = take.reshape(b, 1, t, *([1] * extra))
        return jnp.where(
            sel, jnp.take_along_axis(nv, idx, axis=2), layer_buf
        )

    def grow_to(self, new_len: int):
        """Zero-pad the time axis — axis 3 for values AND scale planes in
        the head-major layout."""
        pad = new_len - self.max_len
        if pad <= 0:
            return self

        def grow(a):
            widths = [(0, 0)] * a.ndim
            widths[3] = (0, pad)
            return jnp.pad(a, widths)

        return self.with_layer_stacks(*(grow(a) for a in self.layer_stacks))

    def attend(
        self,
        layer_state,
        q,
        k_new,
        v_new,
        rope,
        q_pos,
        num_new,
        sliding_window,
        attention_fn,
        scale=None,
    ):
        """Quantized fast path: int8 K/V feed the attention matmuls directly,
        per-(token, head) scales applied to the scores (see
        :func:`ops.attention.gqa_attention_quantized` — the dequant-multiply
        formulation materializes bf16 K/V copies each step). A non-default
        ``attention_fn`` (Pallas kernels expect bf16 K/V) falls back to the
        dequantizing gather path.

        LONG prefills (S >= ``FLASH_PREFILL_MIN_S``, tiles permitting) also
        take the gather path — through the flash kernel: the int8-score
        formulation materializes [B, Hq, S, T] scores in HBM, which turns
        from noise at S=512 (int8 path 93 ms vs flash 119 for an 8B-shape
        prefill) into the dominant cost at S=2048 (743 vs 593 ms) — flash's
        online softmax never materializes them."""
        from ..ops.attention import gqa_attention, gqa_attention_quantized

        if attention_fn is not gqa_attention:
            return super().attend(
                layer_state, q, k_new, v_new, rope, q_pos, num_new,
                sliding_window, attention_fn, scale,
            )
        # head-major layout: T is axis 2 of the per-layer k plane.
        flash = flash_prefill_fn(
            q.shape[1], layer_state[0].shape[2], attention_fn
        )
        if flash is not None:
            return super().attend(
                layer_state, q, k_new, v_new, rope, q_pos, num_new,
                sliding_window, flash, scale,
            )
        layer_k, layer_v, layer_ks, layer_vs = layer_state
        q_rot = apply_rope(q, rope.cos, rope.sin)
        k_rot = apply_rope(k_new, rope.cos, rope.sin)
        k_q, k_s = _quantize_kv(k_rot)
        v_q, v_s = _quantize_kv(v_new)
        new_k = self._write(layer_k, k_q, num_new)
        new_v = self._write(layer_v, v_q, num_new)
        new_ks = self._write(layer_ks, k_s, num_new)
        new_vs = self._write(layer_vs, v_s, num_new)
        if self.use_kernel and q.shape[1] == 1:
            from ..ops.quant_attention import quantized_decode_attention

            out = quantized_decode_attention(
                q_rot, new_k, new_ks, new_v, new_vs,
                self.lengths + num_new, scale, sliding_window,
            )
        else:
            mask = self._mask(q, q_pos, num_new, sliding_window)
            out = gqa_attention_quantized(
                q_rot, new_k, new_ks, new_v, new_vs, mask, scale
            )
        return out, (new_k, new_v, new_ks, new_vs)

    def update_and_gather(
        self,
        layer_state: Tuple[jnp.ndarray, ...],
        q: jnp.ndarray,
        k_new: jnp.ndarray,
        v_new: jnp.ndarray,
        rope: RopeAngles,
        q_pos: jnp.ndarray,
        num_new: jnp.ndarray,
        sliding_window: Optional[int] = None,
    ) -> Tuple[jnp.ndarray, ...]:
        """As :meth:`DenseKVCache.update_and_gather`, but values are stored
        int8 and returned DEQUANTIZED and transposed back to time-major
        ``[B, T, Hkv, D]`` (the fallback path for non-default attention fns;
        the default path is :meth:`attend` above)."""
        layer_k, layer_v, layer_ks, layer_vs = layer_state
        q_rot = apply_rope(q, rope.cos, rope.sin)
        k_rot = apply_rope(k_new, rope.cos, rope.sin)

        k_q, k_s = _quantize_kv(k_rot)
        v_q, v_s = _quantize_kv(v_new)
        new_k = self._write(layer_k, k_q, num_new)
        new_v = self._write(layer_v, v_q, num_new)
        new_ks = self._write(layer_ks, k_s, num_new)
        new_vs = self._write(layer_vs, v_s, num_new)

        dt = q.dtype
        k_all = (new_k.astype(dt) * new_ks[..., None].astype(dt)).transpose(
            0, 2, 1, 3
        )
        v_all = (new_v.astype(dt) * new_vs[..., None].astype(dt)).transpose(
            0, 2, 1, 3
        )
        mask = self._mask(q, q_pos, num_new, sliding_window)
        return q_rot, k_all, v_all, mask, (new_k, new_v, new_ks, new_vs)

    def ingest_row(self, ks, vs, n_valid):
        """Ring-prefill ingest (cf. :meth:`DenseKVCache.ingest_row`):
        quantize the ``[L, B, S, Hkv, D]`` ring KV per (token, head) and lay
        it out head-major."""
        k_q, k_s = _quantize_kv(ks)  # [L, 1, S, H, D] / [L, 1, S, H]
        v_q, v_s = _quantize_kv(vs)
        return self.ingest_planes_row(k_q, v_q, k_s, v_s, n_valid)

    def ingest_planes_row(self, k_q, v_q, k_s, v_s, n_valid):
        """Install ALREADY-quantized time-major planes (int8 values
        ``[L, B, S, Hkv, D]`` + f32 scales ``[L, B, S, Hkv]``) without
        requantizing: disaggregated decode imports the prefill pool's
        STORED planes bit-exact — quantizing a dequantized copy would
        not round-trip."""
        k_q = jnp.moveaxis(jnp.asarray(k_q), 2, 3)  # [L, 1, H, S, D]
        v_q = jnp.moveaxis(jnp.asarray(v_q), 2, 3)
        k_s = jnp.swapaxes(jnp.asarray(k_s), 2, 3)  # [L, 1, H, S]
        v_s = jnp.swapaxes(jnp.asarray(v_s), 2, 3)
        t = self.max_len
        s = k_q.shape[3]

        def fit(a):
            if s >= t:
                return jax.lax.slice_in_dim(a, 0, t, axis=3)
            widths = [(0, 0)] * a.ndim
            widths[3] = (0, t - s)
            return jnp.pad(a, widths)

        return self.replace(
            k=fit(k_q), v=fit(v_q),
            ks=fit(k_s.astype(jnp.float32)), vs=fit(v_s.astype(jnp.float32)),
            lengths=jnp.broadcast_to(
                jnp.asarray(n_valid, jnp.int32), self.lengths.shape
            ),
        )

    # -- write-behind tail (fused multi-step decode) --------------------------

    @property
    def tail_in_kernel(self) -> bool:
        """Kernel mode handles the tail INSIDE the Pallas kernel: the whole
        tail stacks pass through as io-aliased operands (no per-layer
        slicing in the scan), the step's K/V quantize in-kernel, and the
        tail is the final online-softmax tile."""
        return self._kernel_tail_ok

    def tail_init(self, k_steps: int):
        l, b, h, t, d = self.k.shape
        zs = jnp.zeros((l, b, h, k_steps), jnp.float32)
        if self._kernel_tail_ok:
            # Distinct buffers: the fused kernel aliases each tail operand
            # to an output; a shared k/v zeros array cannot be donated twice.
            return (
                jnp.zeros((l, b, h, k_steps, d), jnp.int8),
                jnp.zeros((l, b, h, k_steps, d), jnp.int8),
                zs,
                jnp.zeros((l, b, h, k_steps), jnp.float32),
            )
        zq = jnp.zeros((l, b, h, k_steps, d), jnp.int8)
        return (zq, zq, zs, zs)

    def tail_attend(self, big_state, tail_state, q, k_new, v_new, rope,
                    base_len, tail_len, step_idx, num_new, sliding_window,
                    scale=None):
        """Two-segment int8 attention; the big head-major buffer is
        read-only, the new token is quantized into the tail at scalar slot
        ``step_idx``."""
        from ..ops.attention import gqa_attention_quantized_segments

        big_k, big_v, big_ks, big_vs = big_state[:4]
        tk, tv, tks, tvs = tail_state
        q_rot = apply_rope(q, rope.cos, rope.sin)
        k_rot = apply_rope(k_new, rope.cos, rope.sin)
        if self._kernel_tail_ok and q.shape[1] == 1:
            # Everything in ONE Pallas call: the step's K/V quantize
            # in-kernel and land in the io-aliased whole-stack tail, and
            # the tail joins the big sweep as the final online-softmax
            # tile. XLA never touches the int8 planes (the XLA-side tail —
            # quantize, 4 update-slices, einsums, merge — measured ~8
            # ms/step at batch 112 under the custom call's layout
            # constraints).
            from ..ops.quant_attention import (
                quantized_fused_decode_attention,
            )

            out, ntk, ntks, ntv, ntvs = quantized_fused_decode_attention(
                q_rot, k_rot, v_new,
                big_k, big_ks, big_v, big_vs,
                tk, tks, tv, tvs,
                layer_idx=big_state[4], step_idx=step_idx,
                base_len=base_len, tail_valid_len=tail_len + num_new,
                q_positions=base_len + tail_len,
                scale=scale, sliding_window=sliding_window,
            )
            return out, (ntk, ntv, ntks, ntvs)
        k_q, k_s = _quantize_kv(k_rot)   # [B, 1, Hkv, D] / [B, 1, Hkv]
        v_q, v_s = _quantize_kv(v_new)
        tk = jax.lax.dynamic_update_slice_in_dim(
            tk, jnp.moveaxis(k_q, 1, 2), step_idx, axis=2
        )
        tv = jax.lax.dynamic_update_slice_in_dim(
            tv, jnp.moveaxis(v_q, 1, 2), step_idx, axis=2
        )
        tks = jax.lax.dynamic_update_slice_in_dim(
            tks, jnp.moveaxis(k_s, 1, 2), step_idx, axis=2
        )
        tvs = jax.lax.dynamic_update_slice_in_dim(
            tvs, jnp.moveaxis(v_s, 1, 2), step_idx, axis=2
        )

        big_valid, tail_valid = self._segment_valids(
            base_len, tail_len, num_new, big_k.shape[2], tk.shape[2],
            sliding_window,
        )
        out = gqa_attention_quantized_segments(
            q_rot,
            [
                (big_k, big_ks, big_v, big_vs, big_valid),
                (tk, tks, tv, tvs, tail_valid),
            ],
            scale,
        )
        return out, (tk, tv, tks, tvs)

    def tail_flush(self, tail, tail_len):
        """Per-row K-token window merge (head-major: time axis 2 of the
        ``[L, Hkv, T(, D)]`` row view)."""
        wk, wv, wks, wvs = tail  # [L, B, Hkv, K, D] / [L, B, Hkv, K]
        if self._kernel_tail_ok:
            # Blocked RMW merge: the XLA where/take rewrite of the whole
            # big buffers costs ~58 ms per fused call at batch 112. (Tiny
            # non-32-multiple buffers keep the XLA path.)
            from ..ops.quant_attention import fused_tail_flush

            nk, nks, nv, nvs = fused_tail_flush(
                self.k, self.ks, self.v, self.vs, wk, wks, wv, wvs,
                self.lengths, tail_len,
            )
            return self.replace(
                k=nk, v=nv, ks=nks, vs=nvs,
                lengths=self.lengths + tail_len,
            )
        merge = lambda big, tl: _tail_flush_rows(
            big, tl, self.lengths, tail_len, axis=2
        )
        return self.replace(
            k=merge(self.k, wk), v=merge(self.v, wv),
            ks=merge(self.ks, wks), vs=merge(self.vs, wvs),
            lengths=self.lengths + tail_len,
        )
