"""Dense (contiguous, preallocated) KV cache — bf16 and int8-quantized.

The simplest of the cache policies (dense / paged / sink). Unlike the
reference's ``torch.cat`` growth pattern
(``/root/reference/distributed_llm_inference/models/llama/cache.py:108-109``),
the buffer is preallocated at ``max_seq_len`` and written with per-row
``dynamic_update_slice`` — XLA requires static shapes, and a fixed buffer also
means decode steps always hit the same compiled executable (the role CUDA-graph
capture plays in the reference, ``utils/cuda.py:6``).

Batch rows are independent sessions with their own write offsets
(``lengths``), which is what makes continuous batching possible: the
``generation_id``-keyed dict-of-tensors in the reference
(``models/llama/cache.py:14-19``) becomes integer slot indexing into the batch
dimension.

:class:`QuantizedDenseKVCache` stores K/V as int8 with per-(token, head)
fp32 scales — decode attention reads the whole active KV working set every
step, so halving KV bytes directly buys decode bandwidth (KV traffic
dominates weights at large batch). Dequantization is a broadcast multiply
fused by XLA into the attention operand read; scales ride the layer-state
tuple alongside the value planes (see ``cache/base.py``).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from flax import struct

from ..ops.attention import causal_mask
from ..ops.rotary import RopeAngles, apply_rope
from .base import GatherAttendMixin


class _DenseRowsMixin(GatherAttendMixin):
    """Shared row bookkeeping for contiguous per-row caches: absolute
    positions from ``lengths``, bucket-safe writes, causal masking, and
    generic (BATCH_AXES-driven) row slicing."""

    def q_positions(self, seq_len: int) -> jnp.ndarray:
        """Absolute positions of the incoming tokens: ``[B, S]``."""
        return self.lengths[:, None] + jnp.arange(seq_len, dtype=jnp.int32)[None, :]

    def rope_positions(self, seq_len: int, num_new: jnp.ndarray) -> jnp.ndarray:
        """Positions at which incoming queries are rotated (= absolute here;
        the sink cache overrides this with window-relative positions)."""
        return self.q_positions(seq_len)

    def fits(self, num_new) -> jnp.ndarray:
        """Per-row: can ``num_new`` more tokens be appended without overflow?

        The scheduler MUST check this before admitting tokens: past capacity,
        writes are dropped (see ``_write``) and the overflowing tokens
        silently never enter the cache (engine contract).
        """
        return self.lengths + num_new <= self.max_len

    def advance(self, num_new: jnp.ndarray):
        return self.replace(lengths=self.lengths + num_new)

    def reset_rows(self, row_mask: jnp.ndarray):
        """Zero the lengths of rows where ``row_mask`` is True (slot reuse for
        a new session — the analog of a fresh ``generation_id``, reference
        ``models/llama/cache.py:78-84``). Stale k/v need no clearing: validity
        derives from ``lengths``."""
        return self.replace(lengths=jnp.where(row_mask, 0, self.lengths))

    def _fields(self):
        return [
            f.name for f in dataclasses.fields(self)
            if f.metadata.get("pytree_node", True)
        ]

    def select_row(self, row):
        """Batch-1 view of one session row (jit-safe, ``row`` may be traced).
        Used by the engine to prefill a newly admitted session without
        touching (or recomputing over) the other rows."""
        return self.replace(**{
            name: jax.lax.dynamic_slice_in_dim(
                getattr(self, name), row, 1, axis=self.BATCH_AXES[name]
            )
            for name in self._fields()
        })

    def merge_row(self, sub, row):
        return self.replace(**{
            name: jax.lax.dynamic_update_slice_in_dim(
                getattr(self, name), getattr(sub, name), row,
                axis=self.BATCH_AXES[name],
            )
            for name in self._fields()
        })

    def _write(self, layer_buf, new_vals, num_new):
        """Merge incoming ``[B, S, ...]`` rows into ``[B, T, ...]`` at each
        row's write offset (``lengths``)."""
        b, s = new_vals.shape[:2]
        t = layer_buf.shape[1]
        if s == 1:
            # Decode hot path: single-token contiguous write. Always in
            # bounds — the scheduler's capacity check guarantees
            # ``lengths + 1 <= max_len`` for active rows — and it partitions
            # cleanly under SPMD (a scatter here trips XLA's partitioner).
            def write_row(buf, val, start):
                start_idx = (start,) + (0,) * (buf.ndim - 1)
                return jax.lax.dynamic_update_slice(buf, val, start_idx)

            return jax.vmap(write_row)(layer_buf, new_vals, self.lengths)
        # Prefill: the chunk is padded to a bucket that may extend past
        # the buffer end (bucket > remaining capacity), where a contiguous
        # dynamic_update_slice would either fail to compile (update wider
        # than operand) or clamp the start offset and silently overwrite
        # earlier tokens. Rebuild the buffer as a gather + select instead
        # (SPMD-friendly, unlike a scatter): buffer position p takes
        # incoming row ``p - lengths`` when that lies in [0, num_new).
        src = (
            jnp.arange(t, dtype=jnp.int32)[None, :] - self.lengths[:, None]
        )  # [B, T]: index into the incoming chunk
        take = (src >= 0) & (src < num_new[:, None])
        extra = new_vals.ndim - 2
        idx = jnp.clip(src, 0, s - 1).reshape(b, t, *([1] * extra))
        sel = take.reshape(b, t, *([1] * extra))
        return jnp.where(
            sel, jnp.take_along_axis(new_vals, idx, axis=1), layer_buf
        )

    def grow_to(self, new_len: int):
        """Zero-pad every layer-stacked buffer's time axis (2) to
        ``new_len`` — the growth-ladder step shared by the engine and the
        block backend."""
        pad = new_len - self.max_len
        if pad <= 0:
            return self

        def grow(a):
            widths = [(0, 0)] * a.ndim
            widths[2] = (0, pad)
            return jnp.pad(a, widths)

        return self.with_layer_stacks(*(grow(a) for a in self.layer_stacks))

    def _mask(self, q, q_pos, num_new, sliding_window):
        t = self.max_len
        kv_pos = jnp.broadcast_to(
            jnp.arange(t, dtype=jnp.int32)[None, :], (q.shape[0], t)
        )
        kv_valid = kv_pos < (self.lengths + num_new)[:, None]
        return causal_mask(q_pos, kv_pos, kv_valid, sliding_window)


class DenseKVCache(_DenseRowsMixin, struct.PyTreeNode):
    """``k``/``v``: ``[L, B, T, Hkv, D]`` (keys stored rotated); ``lengths``: ``[B]``."""

    k: jax.Array
    v: jax.Array
    lengths: jax.Array

    # Declarative layout for generic consumers (pipeline row slicing, pp
    # sharding specs): field → batch axis; fields with a leading layer axis.
    BATCH_AXES = {"k": 1, "v": 1, "lengths": 0}
    LAYER_FIELDS = ("k", "v")

    @staticmethod
    def create(
        num_layers: int,
        batch: int,
        max_seq_len: int,
        num_kv_heads: int,
        head_dim: int,
        dtype=jnp.bfloat16,
    ) -> "DenseKVCache":
        shape = (num_layers, batch, max_seq_len, num_kv_heads, head_dim)
        return DenseKVCache(
            k=jnp.zeros(shape, dtype),
            v=jnp.zeros(shape, dtype),
            lengths=jnp.zeros((batch,), jnp.int32),
        )

    @property
    def max_len(self) -> int:
        return self.k.shape[2]

    @property
    def layer_stacks(self):
        """Per-layer stacks (leading dim = layers) for the model's scan."""
        return (self.k, self.v)

    def with_layer_stacks(self, new_k, new_v) -> "DenseKVCache":
        return self.replace(k=new_k, v=new_v)

    def update_and_gather(
        self,
        layer_state: Tuple[jnp.ndarray, ...],
        q: jnp.ndarray,
        k_new: jnp.ndarray,
        v_new: jnp.ndarray,
        rope: RopeAngles,
        q_pos: jnp.ndarray,
        num_new: jnp.ndarray,
        sliding_window: Optional[int] = None,
    ) -> Tuple[jnp.ndarray, ...]:
        """Rotate q/k, write k/v into this layer's buffer, build the mask.

        ``layer_state``: ``(layer_k, layer_v)``, each ``[B, T, Hkv, D]`` (one
        layer's slice, as delivered by ``lax.scan`` over the leading layer
        axis). ``rope`` holds cos/sin precomputed once per block for
        ``q_pos``. Returns ``(q_rot, k_all, v_all, mask, new_layer_state)``.
        """
        layer_k, layer_v = layer_state
        q_rot = apply_rope(q, rope.cos, rope.sin)
        k_rot = apply_rope(k_new, rope.cos, rope.sin)
        new_k = self._write(layer_k, k_rot, num_new)
        new_v = self._write(layer_v, v_new, num_new)
        mask = self._mask(q, q_pos, num_new, sliding_window)
        return q_rot, new_k, new_v, mask, (new_k, new_v)


def _quantize_kv(x: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Per-(token, head) symmetric int8: ``x`` ``[B, S, H, D]`` →
    ``(q int8 [B, S, H, D], scale f32 [B, S, H])``."""
    amax = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(
        jnp.round(x.astype(jnp.float32) / scale[..., None]), -127, 127
    ).astype(jnp.int8)
    return q, scale


class QuantizedDenseKVCache(_DenseRowsMixin, struct.PyTreeNode):
    """Dense cache with int8 K/V + per-(token, head) fp32 scales.

    ``k``/``v``: int8 ``[L, B, T, Hkv, D]``; ``ks``/``vs``: f32
    ``[L, B, T, Hkv]`` (≈3% byte overhead at D=128). The reference's cache is
    unquantized fp16 torch tensors (``models/llama/cache.py``); int8 KV is
    the TPU-native bandwidth play for the decode path, analogous to its
    bitsandbytes int8 *weights* (``utils/model.py:93-123``) applied to the
    cache instead.
    """

    k: jax.Array
    v: jax.Array
    ks: jax.Array
    vs: jax.Array
    lengths: jax.Array

    BATCH_AXES = {"k": 1, "v": 1, "ks": 1, "vs": 1, "lengths": 0}
    LAYER_FIELDS = ("k", "v", "ks", "vs")

    @staticmethod
    def create(
        num_layers: int,
        batch: int,
        max_seq_len: int,
        num_kv_heads: int,
        head_dim: int,
        dtype=jnp.bfloat16,  # accepted for interface parity; values are int8
    ) -> "QuantizedDenseKVCache":
        shape = (num_layers, batch, max_seq_len, num_kv_heads, head_dim)
        sshape = shape[:-1]
        return QuantizedDenseKVCache(
            k=jnp.zeros(shape, jnp.int8),
            v=jnp.zeros(shape, jnp.int8),
            ks=jnp.zeros(sshape, jnp.float32),
            vs=jnp.zeros(sshape, jnp.float32),
            lengths=jnp.zeros((batch,), jnp.int32),
        )

    @property
    def max_len(self) -> int:
        return self.k.shape[2]

    @property
    def layer_stacks(self):
        return (self.k, self.v, self.ks, self.vs)

    def with_layer_stacks(self, k, v, ks, vs) -> "QuantizedDenseKVCache":
        return self.replace(k=k, v=v, ks=ks, vs=vs)

    def update_and_gather(
        self,
        layer_state: Tuple[jnp.ndarray, ...],
        q: jnp.ndarray,
        k_new: jnp.ndarray,
        v_new: jnp.ndarray,
        rope: RopeAngles,
        q_pos: jnp.ndarray,
        num_new: jnp.ndarray,
        sliding_window: Optional[int] = None,
    ) -> Tuple[jnp.ndarray, ...]:
        """As :meth:`DenseKVCache.update_and_gather`, but values are stored
        int8 and returned DEQUANTIZED (a broadcast multiply XLA fuses into
        the attention operand read — no materialized bf16 copy)."""
        layer_k, layer_v, layer_ks, layer_vs = layer_state
        q_rot = apply_rope(q, rope.cos, rope.sin)
        k_rot = apply_rope(k_new, rope.cos, rope.sin)

        k_q, k_s = _quantize_kv(k_rot)
        v_q, v_s = _quantize_kv(v_new)
        new_k = self._write(layer_k, k_q, num_new)
        new_v = self._write(layer_v, v_q, num_new)
        new_ks = self._write(layer_ks, k_s, num_new)
        new_vs = self._write(layer_vs, v_s, num_new)

        dt = q.dtype
        k_all = new_k.astype(dt) * new_ks[..., None].astype(dt)
        v_all = new_v.astype(dt) * new_vs[..., None].astype(dt)
        mask = self._mask(q, q_pos, num_new, sliding_window)
        return q_rot, k_all, v_all, mask, (new_k, new_v, new_ks, new_vs)
