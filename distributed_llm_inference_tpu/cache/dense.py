"""Dense (contiguous, preallocated) KV cache.

The simplest of the three cache policies (dense / paged / sink). Unlike the
reference's ``torch.cat`` growth pattern
(``/root/reference/distributed_llm_inference/models/llama/cache.py:108-109``),
the buffer is preallocated at ``max_seq_len`` and written with per-row
``dynamic_update_slice`` — XLA requires static shapes, and a fixed buffer also
means decode steps always hit the same compiled executable (the role CUDA-graph
capture plays in the reference, ``utils/cuda.py:6``).

Batch rows are independent sessions with their own write offsets
(``lengths``), which is what makes continuous batching possible: the
``generation_id``-keyed dict-of-tensors in the reference
(``models/llama/cache.py:14-19``) becomes integer slot indexing into the batch
dimension.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from flax import struct

from ..ops.attention import causal_mask
from ..ops.rotary import RopeAngles, apply_rope
from .base import GatherAttendMixin


class DenseKVCache(GatherAttendMixin, struct.PyTreeNode):
    """``k``/``v``: ``[L, B, T, Hkv, D]`` (keys stored rotated); ``lengths``: ``[B]``."""

    k: jax.Array
    v: jax.Array
    lengths: jax.Array

    # Declarative layout for generic consumers (pipeline row slicing, pp
    # sharding specs): field → batch axis; fields with a leading layer axis.
    BATCH_AXES = {"k": 1, "v": 1, "lengths": 0}
    LAYER_FIELDS = ("k", "v")

    @staticmethod
    def create(
        num_layers: int,
        batch: int,
        max_seq_len: int,
        num_kv_heads: int,
        head_dim: int,
        dtype=jnp.bfloat16,
    ) -> "DenseKVCache":
        shape = (num_layers, batch, max_seq_len, num_kv_heads, head_dim)
        return DenseKVCache(
            k=jnp.zeros(shape, dtype),
            v=jnp.zeros(shape, dtype),
            lengths=jnp.zeros((batch,), jnp.int32),
        )

    @property
    def max_len(self) -> int:
        return self.k.shape[2]

    @property
    def layer_kv(self):
        """Per-layer k/v stacks (leading dim = layers) for the model's scan."""
        return self.k, self.v

    def with_layer_kv(self, new_k, new_v) -> "DenseKVCache":
        return self.replace(k=new_k, v=new_v)

    def q_positions(self, seq_len: int) -> jnp.ndarray:
        """Absolute positions of the incoming tokens: ``[B, S]``."""
        return self.lengths[:, None] + jnp.arange(seq_len, dtype=jnp.int32)[None, :]

    def rope_positions(self, seq_len: int, num_new: jnp.ndarray) -> jnp.ndarray:
        """Positions at which incoming queries are rotated (= absolute here;
        the sink cache overrides this with window-relative positions)."""
        return self.q_positions(seq_len)

    def reset_rows(self, row_mask: jnp.ndarray) -> "DenseKVCache":
        """Zero the lengths of rows where ``row_mask`` is True (slot reuse for
        a new session — the analog of a fresh ``generation_id``, reference
        ``models/llama/cache.py:78-84``). Stale k/v need no clearing: validity
        derives from ``lengths``."""
        return self.replace(lengths=jnp.where(row_mask, 0, self.lengths))

    def select_row(self, row) -> "DenseKVCache":
        """Batch-1 view of one session row (jit-safe, ``row`` may be traced).
        Used by the engine to prefill a newly admitted session without
        touching (or recomputing over) the other rows."""
        return self.replace(
            k=jax.lax.dynamic_slice_in_dim(self.k, row, 1, axis=1),
            v=jax.lax.dynamic_slice_in_dim(self.v, row, 1, axis=1),
            lengths=jax.lax.dynamic_slice_in_dim(self.lengths, row, 1),
        )

    def merge_row(self, sub: "DenseKVCache", row) -> "DenseKVCache":
        return self.replace(
            k=jax.lax.dynamic_update_slice_in_dim(self.k, sub.k, row, axis=1),
            v=jax.lax.dynamic_update_slice_in_dim(self.v, sub.v, row, axis=1),
            lengths=jax.lax.dynamic_update_slice_in_dim(
                self.lengths, sub.lengths, row, axis=0
            ),
        )

    def fits(self, num_new) -> jnp.ndarray:
        """Per-row: can ``num_new`` more tokens be appended without overflow?

        The scheduler MUST check this before admitting tokens: past capacity,
        writes are dropped (see ``update_and_gather``) and the overflowing
        tokens silently never enter the cache (engine contract).
        """
        return self.lengths + num_new <= self.max_len

    def update_and_gather(
        self,
        layer_k: jnp.ndarray,
        layer_v: jnp.ndarray,
        q: jnp.ndarray,
        k_new: jnp.ndarray,
        v_new: jnp.ndarray,
        rope: RopeAngles,
        q_pos: jnp.ndarray,
        num_new: jnp.ndarray,
        sliding_window: Optional[int] = None,
    ) -> Tuple[jnp.ndarray, ...]:
        """Rotate q/k, write k/v into this layer's buffer, build the mask.

        ``layer_k``/``layer_v``: ``[B, T, Hkv, D]`` (one layer's slice, as
        delivered by ``lax.scan`` over the leading layer axis). ``rope`` holds
        cos/sin precomputed once per block for ``q_pos``.
        Returns ``(q_rot, k_all, v_all, mask, new_layer_k, new_layer_v)``.
        """
        q_rot = apply_rope(q, rope.cos, rope.sin)
        k_rot = apply_rope(k_new, rope.cos, rope.sin)

        b, s, hkv, d = k_new.shape
        t = layer_k.shape[1]
        if s == 1:
            # Decode hot path: single-token contiguous write. Always in
            # bounds — the scheduler's capacity check guarantees
            # ``lengths + 1 <= max_len`` for active rows — and it partitions
            # cleanly under SPMD (a scatter here trips XLA's partitioner).
            def write_row(buf, val, start):
                return jax.lax.dynamic_update_slice(buf, val, (start, 0, 0))

            new_k = jax.vmap(write_row)(layer_k, k_rot, self.lengths)
            new_v = jax.vmap(write_row)(layer_v, v_new, self.lengths)
        else:
            # Prefill: the chunk is padded to a bucket that may extend past
            # the buffer end (bucket > remaining capacity), where a contiguous
            # dynamic_update_slice would either fail to compile (update wider
            # than operand) or clamp the start offset and silently overwrite
            # earlier tokens. Rebuild the buffer as a gather + select instead
            # (SPMD-friendly, unlike a scatter): buffer position p takes
            # incoming row ``p - lengths`` when that lies in [0, num_new).
            src = (
                jnp.arange(t, dtype=jnp.int32)[None, :] - self.lengths[:, None]
            )  # [B, T]: index into the incoming chunk
            take = (src >= 0) & (src < num_new[:, None])
            idx = jnp.clip(src, 0, s - 1)[:, :, None, None]
            sel = take[:, :, None, None]
            new_k = jnp.where(
                sel, jnp.take_along_axis(k_rot, idx, axis=1), layer_k
            )
            new_v = jnp.where(
                sel, jnp.take_along_axis(v_new, idx, axis=1), layer_v
            )
        kv_pos = jnp.broadcast_to(
            jnp.arange(t, dtype=jnp.int32)[None, :], (q.shape[0], t)
        )
        kv_valid = kv_pos < (self.lengths + num_new)[:, None]
        mask = causal_mask(q_pos, kv_pos, kv_valid, sliding_window)
        return q_rot, new_k, new_v, mask, new_k, new_v

    def advance(self, num_new: jnp.ndarray) -> "DenseKVCache":
        return self.replace(lengths=self.lengths + num_new)
