"""Sink (StreamingLLM) KV cache as a static ring buffer.

Capability-parity redesign of the reference's signature feature,
``PartialLlamaSinkCache``
(``/root/reference/distributed_llm_inference/models/llama/cache.py:7-135``):
``num_sink_tokens`` attention sinks plus a sliding window of the most recent
tokens, giving constant memory over unbounded streams, with keys positioned
*window-relatively* so RoPE never sees unbounded positions.

The reference implements eviction by slicing the kept keys out, re-rotating
them by the accumulated shift (``cache.py:111-133``, rerotation matrices cached
at ``:21-48``), and ``torch.cat``-ing — data movement plus compounding float
error from composed rotations. The TPU-native design inverts it:

* Keys are stored **unrotated** in a fixed ``[window]`` ring buffer; nothing
  ever moves on eviction — a new token simply overwrites the ring slot of the
  evicted one.
* At attention time each live slot's *effective position* (sinks at
  ``0..s-1``, window tokens at ``s..W-1``, query on top) is computed from
  ``seen`` by modular arithmetic, and keys are rotated directly to those
  angles — one fused elementwise op over data attention reads anyway, and a
  single rotation instead of the reference's rotation-composition chain.

Eviction granularity is the update chunk: positions are framed by the
post-update stream length, exact for token-by-token decode (the StreamingLLM
regime). The engine keeps prefill chunks ≤ ``window - sinks`` (scheduler
contract, as with ``DenseKVCache.fits``).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from flax import struct

from ..ops.attention import causal_mask
from ..ops.rotary import RopeAngles, apply_rope, rope_cos_sin
from .base import GatherAttendMixin


class SinkKVCache(GatherAttendMixin, struct.PyTreeNode):
    """``k`` (unrotated)/``v``: ``[L, B, W, Hkv, D]``; ``seen``: ``[B]`` total
    stream length per session row."""

    k: jax.Array
    v: jax.Array
    seen: jax.Array
    num_sinks: int = struct.field(pytree_node=False)

    # Generic-consumer layout (see DenseKVCache).
    BATCH_AXES = {"k": 1, "v": 1, "seen": 0}
    LAYER_FIELDS = ("k", "v")

    @staticmethod
    def create(
        num_layers: int,
        batch: int,
        window_length: int,
        num_sink_tokens: int,
        num_kv_heads: int,
        head_dim: int,
        dtype=jnp.bfloat16,
    ) -> "SinkKVCache":
        if not 0 <= num_sink_tokens < window_length:
            raise ValueError("need 0 <= num_sink_tokens < window_length")
        shape = (num_layers, batch, window_length, num_kv_heads, head_dim)
        return SinkKVCache(
            k=jnp.zeros(shape, dtype),
            v=jnp.zeros(shape, dtype),
            seen=jnp.zeros((batch,), jnp.int32),
            num_sinks=num_sink_tokens,
        )

    @property
    def window(self) -> int:
        return self.k.shape[2]

    @property
    def layer_stacks(self):
        return (self.k, self.v)

    def with_layer_stacks(self, new_k, new_v) -> "SinkKVCache":
        return self.replace(k=new_k, v=new_v)

    # -- position bookkeeping -------------------------------------------------

    def _slot_of(self, pos: jnp.ndarray) -> jnp.ndarray:
        """Ring slot of the token with absolute stream position ``pos``."""
        s, w = self.num_sinks, self.window
        return jnp.where(pos < s, pos, s + (pos - s) % (w - s))

    def _slot_positions(self, total: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Absolute position held by each ring slot after ``total`` tokens.

        Returns ``(pos[B, W], valid[B, W])``; the latest write wins a slot.
        """
        s, w = self.num_sinks, self.window
        slot = jnp.arange(w, dtype=jnp.int32)[None, :]
        n = total[:, None]
        # Non-sink slot j (rel = j - s) holds p = s + rel + m*(w-s) for the
        # largest m with p < n.
        rel = slot - s
        m = (n - 1 - s - rel) // (w - s)
        pos_ring = s + rel + jnp.maximum(m, 0) * (w - s)
        pos = jnp.where(slot < s, slot, pos_ring)
        valid = pos < n
        return pos, valid

    def _effective(self, pos: jnp.ndarray, total: jnp.ndarray) -> jnp.ndarray:
        """Window-relative position used for rotation: sinks keep 0..s-1; the
        oldest surviving window token sits at s (reference semantics — after
        eviction the kept keys are re-rotated to close ranks, ``cache.py:116-124``)."""
        s, w = self.num_sinks, self.window
        oldest = jnp.maximum(s, total - (w - s))
        if pos.ndim == 2 and total.ndim == 1:
            oldest = oldest[:, None]
        return jnp.where(pos < s, pos, s + pos - oldest)

    # -- cache interface ------------------------------------------------------

    def q_positions(self, seq_len: int) -> jnp.ndarray:
        """Absolute stream positions of incoming tokens (used for causal
        masking, which stays exact under eviction)."""
        return self.seen[:, None] + jnp.arange(seq_len, dtype=jnp.int32)[None, :]

    def rope_positions(self, seq_len: int, num_new: jnp.ndarray) -> jnp.ndarray:
        """Window-relative positions at which queries are rotated."""
        total = self.seen + num_new
        return self._effective(self.q_positions(seq_len), total)

    def fits(self, num_new) -> jnp.ndarray:
        """A sink cache never overflows — chunks just must not exceed the
        ring's non-sink span (engine contract)."""
        return jnp.broadcast_to(
            jnp.asarray(num_new) <= self.window - self.num_sinks, self.seen.shape
        )

    def update_and_gather(
        self,
        layer_state: Tuple[jnp.ndarray, ...],
        q: jnp.ndarray,
        k_new: jnp.ndarray,
        v_new: jnp.ndarray,
        rope: RopeAngles,
        q_pos: jnp.ndarray,
        num_new: jnp.ndarray,
        sliding_window: Optional[int] = None,
    ) -> Tuple[jnp.ndarray, ...]:
        """Write unrotated k/v into ring slots; rotate live keys to their
        effective positions; build the exact causal+liveness mask.

        ``layer_state``: ``(layer_k, layer_v)``, each ``[B, W, Hkv, D]``.
        ``sliding_window`` is ignored — the ring *is* the window policy.
        """
        layer_k, layer_v = layer_state
        b, s_len = q.shape[:2]
        total = self.seen + num_new

        q_rot = apply_rope(q, rope.cos, rope.sin)

        slots = self._slot_of(q_pos)  # [B, S]
        in_chunk = jnp.arange(s_len, dtype=jnp.int32)[None, :] < num_new[:, None]
        # Padding tokens must not clobber live slots: divert them out of
        # bounds, where scatter mode="drop" discards the write.
        slots = jnp.where(in_chunk, slots, self.window)

        def write_row(buf, vals, idx):
            return buf.at[idx].set(vals, mode="drop")

        new_k = jax.vmap(write_row)(layer_k, k_new, slots)
        new_v = jax.vmap(write_row)(layer_v, v_new, slots)

        kv_pos, kv_live = self._slot_positions(total)
        eff = self._effective(kv_pos, total)
        cos_k, sin_k = rope_cos_sin(eff, rope.inv_freq)
        k_eff = apply_rope(new_k, cos_k, sin_k)

        # Causal on absolute positions; liveness excludes evicted/empty slots.
        mask = causal_mask(q_pos, kv_pos, kv_live)
        return q_rot, k_eff, new_v, mask, (new_k, new_v)

    def advance(self, num_new: jnp.ndarray) -> "SinkKVCache":
        return self.replace(seen=self.seen + num_new)

    def reset_rows(self, row_mask: jnp.ndarray) -> "SinkKVCache":
        return self.replace(seen=jnp.where(row_mask, 0, self.seen))

    def select_row(self, row) -> "SinkKVCache":
        return self.replace(
            k=jax.lax.dynamic_slice_in_dim(self.k, row, 1, axis=1),
            v=jax.lax.dynamic_slice_in_dim(self.v, row, 1, axis=1),
            seen=jax.lax.dynamic_slice_in_dim(self.seen, row, 1),
        )

    def merge_row(self, sub: "SinkKVCache", row) -> "SinkKVCache":
        return self.replace(
            k=jax.lax.dynamic_update_slice_in_dim(self.k, sub.k, row, axis=1),
            v=jax.lax.dynamic_update_slice_in_dim(self.v, sub.v, row, axis=1),
            seen=jax.lax.dynamic_update_slice_in_dim(self.seen, sub.seen, row, axis=0),
        )
