"""Sink (StreamingLLM) KV cache as a static ring buffer.

Capability-parity redesign of the reference's signature feature,
``PartialLlamaSinkCache``
(``/root/reference/distributed_llm_inference/models/llama/cache.py:7-135``):
``num_sink_tokens`` attention sinks plus a sliding window of the most recent
tokens, giving constant memory over unbounded streams, with keys positioned
*window-relatively* so RoPE never sees unbounded positions.

The reference implements eviction by slicing the kept keys out, re-rotating
them by the accumulated shift (``cache.py:111-133``, rerotation matrices cached
at ``:21-48``), and ``torch.cat``-ing — data movement plus compounding float
error from composed rotations. The TPU-native design inverts it:

* Keys are stored **unrotated** in a fixed ``[window]`` ring buffer; nothing
  ever moves on eviction — a new token simply overwrites the ring slot of the
  evicted one.
* At attention time each live slot's *effective position* (sinks at
  ``0..s-1``, window tokens at ``s..W-1``, query on top) is computed from
  ``seen`` by modular arithmetic, and keys are rotated directly to those
  angles — one fused elementwise op over data attention reads anyway, and a
  single rotation instead of the reference's rotation-composition chain.

Eviction granularity is the update chunk: positions are framed by the
post-update stream length, exact for token-by-token decode (the StreamingLLM
regime). The engine keeps prefill chunks ≤ ``window - sinks`` (scheduler
contract, as with ``DenseKVCache.fits``).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from flax import struct

from ..ops.attention import causal_mask
from ..ops.rotary import RopeAngles, apply_rope, rope_cos_sin
from .base import GatherAttendMixin
from .dense import _DenseRowsMixin, _quantize_kv


class SinkKVCache(GatherAttendMixin, struct.PyTreeNode):
    """``k`` (unrotated)/``v``: ``[L, B, W, Hkv, D]``; ``seen``: ``[B]`` total
    stream length per session row."""

    k: jax.Array
    v: jax.Array
    seen: jax.Array
    num_sinks: int = struct.field(pytree_node=False)

    # Generic-consumer layout (see DenseKVCache).
    BATCH_AXES = {"k": 1, "v": 1, "seen": 0}
    LAYER_FIELDS = ("k", "v")

    @staticmethod
    def create(
        num_layers: int,
        batch: int,
        window_length: int,
        num_sink_tokens: int,
        num_kv_heads: int,
        head_dim: int,
        dtype=jnp.bfloat16,
    ) -> "SinkKVCache":
        if not 0 <= num_sink_tokens < window_length:
            raise ValueError("need 0 <= num_sink_tokens < window_length")
        shape = (num_layers, batch, window_length, num_kv_heads, head_dim)
        return SinkKVCache(
            k=jnp.zeros(shape, dtype),
            v=jnp.zeros(shape, dtype),
            seen=jnp.zeros((batch,), jnp.int32),
            num_sinks=num_sink_tokens,
        )

    @property
    def window(self) -> int:
        return self.k.shape[2]

    @property
    def layer_stacks(self):
        return (self.k, self.v)

    def with_layer_stacks(self, new_k, new_v) -> "SinkKVCache":
        return self.replace(k=new_k, v=new_v)

    # -- position bookkeeping -------------------------------------------------

    def _slot_of(self, pos: jnp.ndarray) -> jnp.ndarray:
        """Ring slot of the token with absolute stream position ``pos``."""
        s, w = self.num_sinks, self.window
        return jnp.where(pos < s, pos, s + (pos - s) % (w - s))

    def _slot_positions(self, total: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray]:
        """Absolute position held by each ring slot after ``total`` tokens.

        Returns ``(pos[B, W], valid[B, W])``; the latest write wins a slot.
        """
        s, w = self.num_sinks, self.window
        slot = jnp.arange(w, dtype=jnp.int32)[None, :]
        n = total[:, None]
        # Non-sink slot j (rel = j - s) holds p = s + rel + m*(w-s) for the
        # largest m with p < n.
        rel = slot - s
        m = (n - 1 - s - rel) // (w - s)
        pos_ring = s + rel + jnp.maximum(m, 0) * (w - s)
        pos = jnp.where(slot < s, slot, pos_ring)
        valid = pos < n
        return pos, valid

    def _effective(self, pos: jnp.ndarray, total: jnp.ndarray) -> jnp.ndarray:
        """Window-relative position used for rotation: sinks keep 0..s-1; the
        oldest surviving window token sits at s (reference semantics — after
        eviction the kept keys are re-rotated to close ranks, ``cache.py:116-124``)."""
        s, w = self.num_sinks, self.window
        oldest = jnp.maximum(s, total - (w - s))
        if pos.ndim == 2 and total.ndim == 1:
            oldest = oldest[:, None]
        return jnp.where(pos < s, pos, s + pos - oldest)

    # -- cache interface ------------------------------------------------------

    def q_positions(self, seq_len: int) -> jnp.ndarray:
        """Absolute stream positions of incoming tokens (used for causal
        masking, which stays exact under eviction)."""
        return self.seen[:, None] + jnp.arange(seq_len, dtype=jnp.int32)[None, :]

    def rope_positions(self, seq_len: int, num_new: jnp.ndarray) -> jnp.ndarray:
        """Window-relative positions at which queries are rotated."""
        total = self.seen + num_new
        return self._effective(self.q_positions(seq_len), total)

    def fits(self, num_new) -> jnp.ndarray:
        """A sink cache never overflows — chunks just must not exceed the
        ring's non-sink span (engine contract)."""
        return jnp.broadcast_to(
            jnp.asarray(num_new) <= self.window - self.num_sinks, self.seen.shape
        )

    def update_and_gather(
        self,
        layer_state: Tuple[jnp.ndarray, ...],
        q: jnp.ndarray,
        k_new: jnp.ndarray,
        v_new: jnp.ndarray,
        rope: RopeAngles,
        q_pos: jnp.ndarray,
        num_new: jnp.ndarray,
        sliding_window: Optional[int] = None,
    ) -> Tuple[jnp.ndarray, ...]:
        """Write unrotated k/v into ring slots; rotate live keys to their
        effective positions; build the exact causal+liveness mask.

        ``layer_state``: ``(layer_k, layer_v)``, each ``[B, W, Hkv, D]``.
        ``sliding_window`` is ignored — the ring *is* the window policy.
        """
        layer_k, layer_v = layer_state
        b, s_len = q.shape[:2]
        total = self.seen + num_new

        q_rot = apply_rope(q, rope.cos, rope.sin)

        slots = self._slot_of(q_pos)  # [B, S]
        in_chunk = jnp.arange(s_len, dtype=jnp.int32)[None, :] < num_new[:, None]
        # Padding tokens must not clobber live slots: divert them out of
        # bounds, where scatter mode="drop" discards the write.
        slots = jnp.where(in_chunk, slots, self.window)

        def write_row(buf, vals, idx):
            return buf.at[idx].set(vals, mode="drop")

        new_k = jax.vmap(write_row)(layer_k, k_new, slots)
        new_v = jax.vmap(write_row)(layer_v, v_new, slots)

        kv_pos, kv_live = self._slot_positions(total)
        eff = self._effective(kv_pos, total)
        cos_k, sin_k = rope_cos_sin(eff, rope.inv_freq)
        k_eff = apply_rope(new_k, cos_k, sin_k)

        # Causal on absolute positions; liveness excludes evicted/empty slots.
        mask = causal_mask(q_pos, kv_pos, kv_live)
        return q_rot, k_eff, new_v, mask, (new_k, new_v)

    def advance(self, num_new: jnp.ndarray) -> "SinkKVCache":
        return self.replace(seen=self.seen + num_new)

    def reset_rows(self, row_mask: jnp.ndarray) -> "SinkKVCache":
        return self.replace(seen=jnp.where(row_mask, 0, self.seen))

    def select_row(self, row) -> "SinkKVCache":
        return self.replace(
            k=jax.lax.dynamic_slice_in_dim(self.k, row, 1, axis=1),
            v=jax.lax.dynamic_slice_in_dim(self.v, row, 1, axis=1),
            seen=jax.lax.dynamic_slice_in_dim(self.seen, row, 1),
        )

    def merge_row(self, sub: "SinkKVCache", row) -> "SinkKVCache":
        return self.replace(
            k=jax.lax.dynamic_update_slice_in_dim(self.k, sub.k, row, axis=1),
            v=jax.lax.dynamic_update_slice_in_dim(self.v, sub.v, row, axis=1),
            seen=jax.lax.dynamic_update_slice_in_dim(self.seen, sub.seen, row, axis=0),
        )


def _round_up(n: int, m: int) -> int:
    return -(-n // m) * m


def _multi_q_quantized_segments(segments, scale):
    """Per-segment-query joint softmax (see
    :func:`ops.attention.gqa_attention_quantized_multi_q_segments`): the
    sink segment attends with the window-relative-rotated query, the
    ring/tail segments with the absolute-rotated one."""
    from ..ops.attention import gqa_attention_quantized_multi_q_segments

    return gqa_attention_quantized_multi_q_segments(segments, scale)


class QuantizedSinkKVCache(_DenseRowsMixin, struct.PyTreeNode):
    """Serving-grade StreamingLLM cache: int8 ring + int8 sinks + fused tail.

    The bf16 :class:`SinkKVCache` stores keys unrotated and re-rotates the
    WHOLE window to its effective positions inside attention every step —
    correct, but ~2.6x slower than even the bf16 dense cache at window 1024
    (round-3 bench). This redesign makes the sink cache structurally
    identical to :class:`~..cache.dense.QuantizedDenseKVCache` (int8 planes,
    Pallas fused-decode kernel, write-behind tail) by moving the position
    bookkeeping out of the data path:

    * RoPE attention scores depend only on position DIFFERENCES
      (``<R(a)q, R(b)k>`` is a function of ``b - a``), so ring keys are
      stored rotated at their ABSOLUTE stream positions — written once,
      never re-rotated — and queries rotate at their absolute position too.
      Window scores match the reference's window-relative convention
      (``/root/reference/distributed_llm_inference/models/llama/cache.py:111-133``)
      exactly, in exact arithmetic.
    * Only the ``num_sinks`` sink tokens have COMPRESSED positions (the
      StreamingLLM trick that keeps query-to-sink distances bounded): they
      are stored rotated at their fixed slots ``0..s-1`` and attended with
      a SECOND query rotated at the window-relative position
      ``min(q_pos, window - 1)`` — one extra tiny rotation per step instead
      of a whole-window re-rotation.
    * Eviction is a mask, not data movement: ring slot ``j``'s occupant is
      derivable from ``lengths``; the slots the in-flight fused tail has
      logically evicted are masked in-kernel (exact per-step window
      semantics) and physically overwritten at flush (mod-ring blocked RMW,
      ``ops/quant_attention.py:sink_tail_flush``).

    ``k``/``v``: int8 ``[L, B, Hkv, TR, D]`` head-major ring (TR = ring
    span padded to 32); ``ks``/``vs``: f32 scales; ``sk``/``sv``/
    ``sks``/``svs``: the sink planes ``[L, B, Hkv, SP, D]`` (SP = 32);
    ``lengths``: total stream length per row (the bf16 class calls it
    ``seen``).
    """

    k: jax.Array
    v: jax.Array
    ks: jax.Array
    vs: jax.Array
    sk: jax.Array
    sv: jax.Array
    sks: jax.Array
    svs: jax.Array
    lengths: jax.Array
    num_sinks: int = struct.field(pytree_node=False)
    ring_slots: int = struct.field(pytree_node=False)
    use_kernel: bool = struct.field(pytree_node=False, default=False)

    BATCH_AXES = {
        "k": 1, "v": 1, "ks": 1, "vs": 1,
        "sk": 1, "sv": 1, "sks": 1, "svs": 1, "lengths": 0,
    }
    LAYER_FIELDS = ("k", "v", "ks", "vs", "sk", "sv", "sks", "svs")
    SINK_PAD = 32

    @staticmethod
    def create(
        num_layers: int,
        batch: int,
        window_length: int,
        num_sink_tokens: int,
        num_kv_heads: int,
        head_dim: int,
        dtype=jnp.bfloat16,  # interface parity; values are int8
        use_kernel: bool = False,
    ) -> "QuantizedSinkKVCache":
        if not 0 <= num_sink_tokens < window_length:
            raise ValueError("need 0 <= num_sink_tokens < window_length")
        r = window_length - num_sink_tokens
        tr = max(32, _round_up(r, 32))
        sp = QuantizedSinkKVCache.SINK_PAD
        shape = (num_layers, batch, num_kv_heads, tr, head_dim)
        sshape = (num_layers, batch, num_kv_heads, sp, head_dim)
        return QuantizedSinkKVCache(
            k=jnp.zeros(shape, jnp.int8),
            v=jnp.zeros(shape, jnp.int8),
            ks=jnp.zeros(shape[:-1], jnp.float32),
            vs=jnp.zeros(shape[:-1], jnp.float32),
            sk=jnp.zeros(sshape, jnp.int8),
            sv=jnp.zeros(sshape, jnp.int8),
            sks=jnp.zeros(sshape[:-1], jnp.float32),
            svs=jnp.zeros(sshape[:-1], jnp.float32),
            lengths=jnp.zeros((batch,), jnp.int32),
            num_sinks=num_sink_tokens,
            ring_slots=r,
            use_kernel=use_kernel,
        )

    # -- geometry -------------------------------------------------------------

    @property
    def window(self) -> int:
        return self.ring_slots + self.num_sinks

    @property
    def seen(self) -> jax.Array:
        """bf16-class-compatible alias (total stream length per row)."""
        return self.lengths

    @property
    def layer_stacks(self):
        return (self.k, self.v, self.ks, self.vs,
                self.sk, self.sv, self.sks, self.svs)

    def with_layer_stacks(self, k, v, ks, vs, sk, sv, sks, svs):
        return self.replace(k=k, v=v, ks=ks, vs=vs,
                            sk=sk, sv=sv, sks=sks, svs=svs)

    def fits(self, num_new) -> jnp.ndarray:
        """Never overflows; chunks must fit the ring span (engine
        contract, as with the bf16 class)."""
        return jnp.broadcast_to(
            jnp.asarray(num_new) <= self.ring_slots, self.lengths.shape
        )

    def grow_to(self, new_len: int):
        raise TypeError("the sink ring is fixed-size; nothing to grow")

    # -- position bookkeeping -------------------------------------------------

    def _ring_kv_positions(self, total: jnp.ndarray):
        """Absolute position held by each ring slot after ``total`` stream
        tokens (latest write wins) + liveness: ``(pos [B, TR], live)``."""
        s, r = self.num_sinks, self.ring_slots
        tr = self.k.shape[3]
        slot = jnp.arange(tr, dtype=jnp.int32)[None, :]
        n = total[:, None]
        m = (n - 1 - s - slot) // r
        pos = s + slot + jnp.maximum(m, 0) * r
        live = (slot < r) & (pos < n)
        return pos, live

    def _eff_query(self, q, q_pos, total, inv_freq):
        """Rotate ``q`` at its window-relative position (for sink scores):
        ``eff = q_pos - (oldest - s)`` with ``oldest`` framed by ``total``
        (chunk-granular eviction, matching the bf16 class)."""
        s, r = self.num_sinks, self.ring_slots
        oldest = jnp.maximum(s, total - r)
        eff = q_pos - (oldest - s)[:, None]
        cos, sin = rope_cos_sin(eff, inv_freq)
        return apply_rope(q, cos, sin)

    # -- writes ---------------------------------------------------------------

    def _ring_write(self, layer_buf, new_vals, num_new):
        """Merge incoming ``[B, S, Hkv(, D)]`` rows into the head-major ring
        ``[B, Hkv, TR(, D)]`` at mod-``ring_slots`` slots. Gather+select
        (SPMD-friendly): ring slot ``t`` takes the LAST chunk token landing
        on it — chunk index ``i ≡ t - (lengths - s) (mod r)`` maximal with
        ``i < num_new`` and stream position ``>= s``."""
        s, r = self.num_sinks, self.ring_slots
        b, sl = new_vals.shape[:2]
        tr = layer_buf.shape[2]
        nv = jnp.moveaxis(new_vals, 1, 2)  # [B, Hkv, S(, D)]
        t = jnp.arange(tr, dtype=jnp.int32)[None, :]
        a = (self.lengths - s)[:, None]  # may be negative (sink phase)
        cand = jnp.mod(t - a, r)
        # Largest i ≡ cand (mod r) below num_new (covers multi-wrap chunks).
        i = cand + jnp.maximum(
            (num_new[:, None] - 1 - cand) // r, 0
        ) * r
        take = (
            (t < r)
            & (i < num_new[:, None])
            & (a + i >= 0)  # stream position >= s (not sink-bound)
        )
        extra = nv.ndim - 3
        idx = jnp.clip(i, 0, sl - 1).reshape(b, 1, tr, *([1] * extra))
        sel = take.reshape(b, 1, tr, *([1] * extra))
        return jnp.where(
            sel, jnp.take_along_axis(nv, idx, axis=2), layer_buf
        )

    def _sink_write(self, layer_buf, new_vals, num_new):
        """Sink slot ``j`` takes chunk token ``j - lengths`` when that token
        exists (stream positions below ``num_sinks`` — keys rotated at their
        absolute position, which IS the sink slot)."""
        s = self.num_sinks
        b, sl = new_vals.shape[:2]
        sp = layer_buf.shape[2]
        nv = jnp.moveaxis(new_vals, 1, 2)  # [B, Hkv, S(, D)]
        j = jnp.arange(sp, dtype=jnp.int32)[None, :]
        i = j - self.lengths[:, None]
        take = (j < s) & (i >= 0) & (i < num_new[:, None])
        extra = nv.ndim - 3
        idx = jnp.clip(i, 0, sl - 1).reshape(b, 1, sp, *([1] * extra))
        sel = take.reshape(b, 1, sp, *([1] * extra))
        return jnp.where(
            sel, jnp.take_along_axis(nv, idx, axis=2), layer_buf
        )

    # -- attention ------------------------------------------------------------

    def attend(
        self,
        layer_state,
        q,
        k_new,
        v_new,
        rope,
        q_pos,
        num_new,
        sliding_window,
        attention_fn,
        scale=None,
    ):
        """Prefill and per-step decode: quantize the chunk (keys rotated at
        ABSOLUTE positions), write ring (mod) + sink (prefix) planes, run
        the three-segment joint softmax. ``attention_fn`` is ignored — the
        segments math is the cache's own (the engine never swaps attention
        for own-kernel caches); ``sliding_window`` is ignored — the ring is
        the window policy."""
        (layer_k, layer_v, layer_ks, layer_vs,
         layer_sk, layer_sv, layer_sks, layer_svs) = layer_state
        s = self.num_sinks
        total = self.lengths + num_new

        q_rot = apply_rope(q, rope.cos, rope.sin)
        k_rot = apply_rope(k_new, rope.cos, rope.sin)
        k_q, k_s = _quantize_kv(k_rot)
        v_q, v_s = _quantize_kv(v_new)

        new_k = self._ring_write(layer_k, k_q, num_new)
        new_v = self._ring_write(layer_v, v_q, num_new)
        new_ks = self._ring_write(layer_ks, k_s, num_new)
        new_vs = self._ring_write(layer_vs, v_s, num_new)
        new_sk = self._sink_write(layer_sk, k_q, num_new)
        new_sv = self._sink_write(layer_sv, v_q, num_new)
        new_sks = self._sink_write(layer_sks, k_s, num_new)
        new_svs = self._sink_write(layer_svs, v_s, num_new)
        new_state = (new_k, new_v, new_ks, new_vs,
                     new_sk, new_sv, new_sks, new_svs)

        q_eff = self._eff_query(q, q_pos, total, rope.inv_freq)

        kv_pos, kv_live = self._ring_kv_positions(total)
        ring_mask = causal_mask(q_pos, kv_pos, kv_live)

        sp = layer_sk.shape[2]
        sink_idx = jnp.broadcast_to(
            jnp.arange(sp, dtype=jnp.int32)[None, :], (q.shape[0], sp)
        )
        sink_live = sink_idx < jnp.minimum(total, s)[:, None]
        sink_mask = causal_mask(q_pos, sink_idx, sink_live)

        out = _multi_q_quantized_segments(
            [
                (q_eff, new_sk, new_sks, new_sv, new_svs, sink_mask),
                (q_rot, new_k, new_ks, new_v, new_vs, ring_mask),
            ],
            scale,
        )
        return out, new_state

    # -- write-behind tail (fused multi-step decode) --------------------------

    @property
    def tail_reads_whole_big(self) -> bool:
        return self.use_kernel

    @property
    def tail_in_kernel(self) -> bool:
        return self.use_kernel

    def tail_init(self, k_steps: int):
        l, b, h, _, d = self.k.shape
        zs = jnp.zeros((l, b, h, k_steps), jnp.float32)
        if self.use_kernel:
            return (
                jnp.zeros((l, b, h, k_steps, d), jnp.int8),
                jnp.zeros((l, b, h, k_steps, d), jnp.int8),
                zs,
                jnp.zeros((l, b, h, k_steps), jnp.float32),
            )
        zq = jnp.zeros((l, b, h, k_steps, d), jnp.int8)
        return (zq, zq, zs, zs)

    def _tail_scalars(self, base_len, tail_len, num_new):
        s, r = self.num_sinks, self.ring_slots
        ring_len = jnp.clip(base_len - s, 0, r)
        ring_ptr = jnp.mod(jnp.maximum(base_len - s, 0), r)
        # Ring tokens evicted so far INCLUDING by the token being appended
        # this step: the post-append window is [total - r, total) with
        # total = base + tail_len + num_new, so the oldest
        # ``tail_len + num_new`` ring slots are dead (an ``evict = tail_len``
        # off-by-one leaves the current step's victim attended — caught by a
        # 0.009 logit gap vs per-step decode on a fully wrapped ring).
        evict = tail_len + num_new
        sink_len = jnp.minimum(base_len, s)
        vlen = tail_len + num_new
        return ring_len, ring_ptr, evict, sink_len, vlen

    def tail_attend(self, big_state, tail_state, q, k_new, v_new, rope,
                    base_len, tail_len, step_idx, num_new, sliding_window,
                    scale=None):
        """Three-segment decode attention (sink + ring + tail); the big
        planes stay read-only, the step's K/V is quantized into the tail at
        scalar slot ``step_idx`` (in-kernel when ``use_kernel``)."""
        s = self.num_sinks
        q_pos = base_len + tail_len
        q_rot = apply_rope(q, rope.cos, rope.sin)
        k_rot = apply_rope(k_new, rope.cos, rope.sin)
        # Window-relative query for the sink segment, framed at the
        # post-step total (q_pos + 1), as token-by-token decode demands.
        q_eff = self._eff_query(
            q, q_pos[:, None], q_pos + 1, rope.inv_freq
        )
        ring_len, ring_ptr, evict, sink_len, vlen = self._tail_scalars(
            base_len, tail_len, num_new
        )

        if self.use_kernel and q.shape[1] == 1:
            from ..ops.quant_attention import sink_fused_decode_attention

            (big_k, big_v, big_ks, big_vs,
             big_sk, big_sv, big_sks, big_svs) = big_state[:8]
            tk, tv, tks, tvs = tail_state
            out, ntk, ntks, ntv, ntvs = sink_fused_decode_attention(
                q_rot, q_eff, k_rot, v_new,
                big_k, big_ks, big_v, big_vs,
                big_sk, big_sks, big_sv, big_svs,
                tk, tks, tv, tvs,
                layer_idx=big_state[8], step_idx=step_idx,
                ring_len=ring_len, ring_ptr=ring_ptr, evict_len=evict,
                sink_len=sink_len, tail_valid_len=vlen,
                ring_slots=self.ring_slots, scale=scale,
            )
            return out, (ntk, ntv, ntks, ntvs)

        (big_k, big_v, big_ks, big_vs,
         big_sk, big_sv, big_sks, big_svs) = big_state[:8]
        tk, tv, tks, tvs = tail_state
        k_q, k_s = _quantize_kv(k_rot)   # [B, 1, Hkv, D] / [B, 1, Hkv]
        v_q, v_s = _quantize_kv(v_new)
        tk = jax.lax.dynamic_update_slice_in_dim(
            tk, jnp.moveaxis(k_q, 1, 2), step_idx, axis=2
        )
        tv = jax.lax.dynamic_update_slice_in_dim(
            tv, jnp.moveaxis(v_q, 1, 2), step_idx, axis=2
        )
        tks = jax.lax.dynamic_update_slice_in_dim(
            tks, jnp.moveaxis(k_s, 1, 2), step_idx, axis=2
        )
        tvs = jax.lax.dynamic_update_slice_in_dim(
            tvs, jnp.moveaxis(v_s, 1, 2), step_idx, axis=2
        )

        b = q.shape[0]
        r = self.ring_slots
        tr = big_k.shape[2]
        slot = jnp.broadcast_to(
            jnp.arange(tr, dtype=jnp.int32)[None, :], (b, tr)
        )
        dd = slot - ring_ptr[:, None]
        dd = dd + jnp.where(dd < 0, r, 0)
        ring_valid = (
            (slot < ring_len[:, None]) & (dd >= evict[:, None])
        )[:, None, :]
        sp = big_sk.shape[2]
        sidx = jnp.broadcast_to(
            jnp.arange(sp, dtype=jnp.int32)[None, :], (b, sp)
        )
        sink_valid = (sidx < sink_len[:, None])[:, None, :]
        kt = tk.shape[2]
        tidx = jnp.broadcast_to(
            jnp.arange(kt, dtype=jnp.int32)[None, :], (b, kt)
        )
        tail_valid = (tidx < vlen[:, None])[:, None, :]

        out = _multi_q_quantized_segments(
            [
                (q_eff, big_sk, big_sks, big_sv, big_svs, sink_valid),
                (q_rot, big_k, big_ks, big_v, big_vs, ring_valid),
                (q_rot, tk, tks, tv, tvs, tail_valid),
            ],
            scale,
        )
        return out, (tk, tv, tks, tvs)

    def tail_flush(self, tail, tail_len):
        """Physically place the tail: ring tokens via the mod-ring blocked
        RMW kernel (XLA gather fallback off-kernel), sink-bound tokens (the
        rare sub-``num_sinks`` stream heads) via a cheap masked merge of the
        small sink planes; ``lengths`` advances by ``tail_len``."""
        wk, wv, wks, wvs = tail  # [L, B, Hkv, KT, D] / [L, B, Hkv, KT]
        s, r = self.num_sinks, self.ring_slots
        kt = wk.shape[3]
        skip = jnp.clip(s - self.lengths, 0, kt)
        ring_ptr = jnp.mod(jnp.maximum(self.lengths - s, 0), r)

        if self.use_kernel and kt <= 32:
            from ..ops.quant_attention import sink_tail_flush

            nk, nks, nv, nvs = sink_tail_flush(
                self.k, self.ks, self.v, self.vs, wk, wks, wv, wvs,
                ring_ptr, skip, tail_len, self.ring_slots,
            )
        else:
            nk, nks, nv, nvs = (
                self._ring_flush_xla(big, tl, tail_len, skip, ring_ptr)
                for big, tl in (
                    (self.k, wk), (self.ks, wks),
                    (self.v, wv), (self.vs, wvs),
                )
            )

        new_sk = self._sink_flush_xla(self.sk, wk, tail_len)
        new_sv = self._sink_flush_xla(self.sv, wv, tail_len)
        new_sks = self._sink_flush_xla(self.sks, wks, tail_len)
        new_svs = self._sink_flush_xla(self.svs, wvs, tail_len)
        return self.replace(
            k=nk, v=nv, ks=nks, vs=nvs,
            sk=new_sk, sv=new_sv, sks=new_sks, svs=new_svs,
            lengths=self.lengths + tail_len,
        )

    def _ring_flush_xla(self, big, tl_buf, tail_len, skip, ring_ptr):
        """Gather+select ring merge: ring slot ``t`` takes the LAST live
        tail token targeting it (``i ≡ t - ring_ptr + skip (mod r)``)."""
        r = self.ring_slots
        b = big.shape[1]
        tr = big.shape[3]
        kt = tl_buf.shape[3]
        t = jnp.arange(tr, dtype=jnp.int32)[None, :]
        cand = skip[:, None] + jnp.mod(t - ring_ptr[:, None], r)
        i = cand + jnp.maximum(
            (tail_len[:, None] - 1 - cand) // r, 0
        ) * r
        take = (t < r) & (i >= skip[:, None]) & (i < tail_len[:, None])
        extra = big.ndim - 4  # 1 for value planes, 0 for scales
        idx = jnp.clip(i, 0, kt - 1).reshape(1, b, 1, tr, *([1] * extra))
        sel = take.reshape(1, b, 1, tr, *([1] * extra))
        return jnp.where(
            sel, jnp.take_along_axis(tl_buf, idx, axis=3), big
        )

    def _sink_flush_xla(self, sink_buf, tl_buf, tail_len):
        s = self.num_sinks
        b = sink_buf.shape[1]
        sp = sink_buf.shape[3]
        kt = tl_buf.shape[3]
        j = jnp.arange(sp, dtype=jnp.int32)[None, :]
        i = j - self.lengths[:, None]
        take = (j < s) & (i >= 0) & (i < tail_len[:, None])
        extra = sink_buf.ndim - 4
        idx = jnp.clip(i, 0, kt - 1).reshape(1, b, 1, sp, *([1] * extra))
        sel = take.reshape(1, b, 1, sp, *([1] * extra))
        return jnp.where(
            sel, jnp.take_along_axis(tl_buf, idx, axis=3), sink_buf
        )
