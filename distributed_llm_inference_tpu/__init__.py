"""TPU-native distributed LLM inference framework.

A ground-up JAX/XLA/Pallas re-design of the capabilities of
``Dylan102938/distributed-llm-inference`` (block-sharded distributed inference
with multi-tenant KV caches, batched serving, per-block weight streaming,
compiled decode, quantization), built TPU-first: SPMD over ``jax.sharding.Mesh``
for tensor/pipeline/data/sequence parallelism, Pallas kernels for the attention
hot paths, and a native relay for the cross-host (DCN) hop.
"""

from .config import (
    CacheConfig,
    EngineConfig,
    LatentConfig,
    MeshConfig,
    ModelConfig,
    RopeScaling,
)

__version__ = "0.1.0"

__all__ = [
    "CacheConfig",
    "EngineConfig",
    "LatentConfig",
    "MeshConfig",
    "ModelConfig",
    "RopeScaling",
    "__version__",
]
