// Native zero-copy safetensors reader.
//
// The reference's checkpoint reads go through the Rust `safetensors` wheel
// (/root/reference/distributed_llm_inference/utils/model.py:4,19 — safe_open);
// this is the C++ equivalent for the TPU framework's data-loading tier:
// mmap the file once, hand Python a pointer to the JSON header (parsed
// host-side — it is tiny), and service tensor reads as multithreaded memcpy
// straight out of the mapping. madvise(WILLNEED) warms the page cache ahead
// of the copies, so cold NVMe reads overlap with header processing.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this image).
//
// Build: g++ -O2 -std=c++17 -shared -fPIC streader.cc -o _streader.so -pthread

#include <atomic>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct StFile {
  int fd = -1;
  uint8_t* map = nullptr;
  uint64_t size = 0;
  uint64_t header_len = 0;  // JSON byte length (excludes the 8-byte prefix)
};

}  // namespace

extern "C" {

// Returns nullptr on any failure (missing file, truncated, bad header len).
void* st_open(const char* path) {
  int fd = ::open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size < 8) {
    ::close(fd);
    return nullptr;
  }
  uint64_t size = static_cast<uint64_t>(st.st_size);
  void* map = mmap(nullptr, size, PROT_READ, MAP_PRIVATE, fd, 0);
  if (map == MAP_FAILED) {
    ::close(fd);
    return nullptr;
  }
  uint64_t header_len;
  std::memcpy(&header_len, map, 8);  // little-endian u64 prefix
  if (header_len > size - 8) {
    munmap(map, size);
    ::close(fd);
    return nullptr;
  }
  auto* f = new StFile();
  f->fd = fd;
  f->map = static_cast<uint8_t*>(map);
  f->size = size;
  f->header_len = header_len;
  return f;
}

uint64_t st_header_len(void* h) { return static_cast<StFile*>(h)->header_len; }

const uint8_t* st_header(void* h) { return static_cast<StFile*>(h)->map + 8; }

uint64_t st_data_len(void* h) {
  auto* f = static_cast<StFile*>(h);
  return f->size - 8 - f->header_len;
}

// Warm the data section (or a slice of it) into the page cache.
void st_prefetch(void* h, uint64_t off, uint64_t len) {
  auto* f = static_cast<StFile*>(h);
  uint64_t base = 8 + f->header_len + off;
  if (base >= f->size) return;
  if (len == 0 || base + len > f->size) len = f->size - base;
  // Align down to page size as madvise requires.
  uint64_t page = static_cast<uint64_t>(sysconf(_SC_PAGESIZE));
  uint64_t start = (base / page) * page;
  madvise(f->map + start, len + (base - start), MADV_WILLNEED);
}

// Copy [off, off+len) of the DATA section into dst. Returns 0 on success,
// -1 if the range falls outside the file.
int32_t st_copy(void* h, uint64_t off, uint64_t len, void* dst) {
  auto* f = static_cast<StFile*>(h);
  uint64_t data_len = f->size - 8 - f->header_len;
  if (off > data_len || len > data_len - off) return -1;
  std::memcpy(dst, f->map + 8 + f->header_len + off, len);
  return 0;
}

// Parallel variant: n (offset, length, destination) tasks drained by
// `threads` workers. Large host copies are memory-bandwidth bound; a few
// threads saturate it where one does not. Returns 0, or -1 if ANY task was
// out of range (in-range tasks still complete).
int32_t st_copy_many(void* h, const uint64_t* offs, const uint64_t* lens,
                     uint8_t** dsts, int32_t n, int32_t threads) {
  auto* f = static_cast<StFile*>(h);
  uint64_t data_len = f->size - 8 - f->header_len;
  const uint8_t* data = f->map + 8 + f->header_len;
  std::atomic<int32_t> next{0};
  std::atomic<int32_t> bad{0};
  auto worker = [&]() {
    for (;;) {
      int32_t i = next.fetch_add(1);
      if (i >= n) return;
      if (offs[i] > data_len || lens[i] > data_len - offs[i]) {
        bad.store(1);
        continue;
      }
      std::memcpy(dsts[i], data + offs[i], lens[i]);
    }
  };
  if (threads < 1) threads = 1;
  std::vector<std::thread> pool;
  for (int32_t t = 1; t < threads; ++t) pool.emplace_back(worker);
  worker();
  for (auto& t : pool) t.join();
  return bad.load() ? -1 : 0;
}

void st_close(void* h) {
  auto* f = static_cast<StFile*>(h);
  if (f->map) munmap(f->map, f->size);
  if (f->fd >= 0) ::close(f->fd);
  delete f;
}

}  // extern "C"
