// Activation relay: a native message hub for the cross-host (DCN) tier.
//
// TPU-native replacement for the transport the reference delegated entirely
// to hivemind -- libp2p daemon + gRPC + msgpack (SURVEY §2.2 row 5;
// /root/reference/distributed_llm_inference/server/backend.py:4-7 imports,
// poetry.lock:485-488,367-370,692). Inside a slice, XLA collectives over ICI
// replace networking altogether (parallel/); BETWEEN hosts, pipeline-stage
// activations hop through this relay: a single epoll loop forwarding
// length-prefixed binary frames between named FIFO queues.
//
// Protocol (all integers big-endian):
//   request:  [op:1][qlen:2][queue bytes][len:8][crc:4][payload]
//     op 1 = PUT     payload appended to `queue` (no ack -- fire and forget).
//                    `crc` is the CRC-32 (IEEE, zlib-compatible) of the
//                    payload; a mismatch at ingress means the bytes were
//                    damaged in flight and the frame is DROPPED -- a lost
//                    frame the endpoints already know how to handle (reply
//                    timeout -> client replays under a fresh generation_id)
//                    instead of garbage tokens reaching a model layer.
//     op 2 = GET     blocks until `queue` has a message; reply
//                    [len:8][crc:4][payload] (crc recomputed at egress so
//                    the hub->client leg is covered independently)
//     op 3 = PING    reply [len:8 = 4][crc:4]["PONG"]  (health / liveness)
//     op 4 = CANCEL  unpark this connection's pending GET; always acked with
//                    the bare sentinel frame [len:8 = ~0] (no crc). If a
//                    reply raced ahead
//                    of the CANCEL it precedes the ack on the wire, so the
//                    client can distinguish "timed out" from "arrived late"
//                    without tearing down the connection (a raw close loses
//                    the message: the first TCP send after the peer's FIN
//                    still succeeds).
//   Multiple concurrent GETs on one queue are served FIFO. A connection that
//   dies while parked requeues any reply it never received.
//
//   PUTs may be PIPELINED: a client can concatenate any number of complete
//   PUT frames into one TCP send (RelayClient.put_many) and the hub applies
//   them in order -- process_input() loops over every complete frame in the
//   read buffer, so a node's whole fan-out of replies costs one syscall on
//   each side. No new opcode: pipelining is a property of the stream.
//
// Exposed as a C API (relay_start / relay_stop) so Python drives it via
// ctypes -- no pybind11 in this image. Clients speak the socket protocol
// directly (distributed_llm_inference_tpu/distributed/relay.py).

#include <arpa/inet.h>
#include <cerrno>
#include <cstdint>
#include <cstring>
#include <deque>
#include <fcntl.h>
#include <map>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string>
#include <sys/epoll.h>
#include <sys/eventfd.h>
#include <sys/socket.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

constexpr uint8_t kOpPut = 1;
constexpr uint8_t kOpGet = 2;
constexpr uint8_t kOpPing = 3;
constexpr uint8_t kOpCancel = 4;
constexpr uint64_t kCancelAck = ~0ull;
constexpr uint64_t kMaxPayload = 1ull << 30;  // 1 GiB per frame
constexpr size_t kMaxQueueName = 255;

struct Inflight {
  std::string queue;  // source queue of an undelivered GET reply
  uint64_t begin;     // total_enqueued before this reply's 8-byte length
  uint64_t end;       // total_enqueued after the reply
};

struct Conn {
  int fd = -1;
  std::vector<uint8_t> rbuf;   // partially received request bytes
  std::vector<uint8_t> wbuf;   // pending reply bytes not yet written
  size_t woff = 0;             // write offset into wbuf
  bool parked = false;         // waiting in some queue's getter list
  std::string parked_queue;
  // Delivery tracking: a GET reply counts as delivered only once its bytes
  // are fully flushed to the socket; replies still in flight when the
  // connection dies are requeued so no message is ever lost to a dead getter.
  uint64_t total_enqueued = 0;
  uint64_t total_flushed = 0;
  std::deque<Inflight> inflight;
};

struct Server {
  int epfd = -1;
  int listen_fd = -1;
  int wake_fd = -1;  // eventfd: wakes the loop for shutdown
  int port = 0;
  std::thread loop;
  volatile bool stopping = false;
  std::map<int, Conn*> conns;
  std::map<std::string, std::deque<std::vector<uint8_t>>> queues;
  std::map<std::string, std::deque<int>> getters;  // parked conn fds, FIFO
};

void set_nonblock(int fd) {
  int flags = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, flags | O_NONBLOCK);
}

void be64(uint8_t* dst, uint64_t v) {
  for (int i = 7; i >= 0; --i) {
    dst[i] = v & 0xff;
    v >>= 8;
  }
}

uint64_t rd64(const uint8_t* src) {
  uint64_t v = 0;
  for (int i = 0; i < 8; ++i) v = (v << 8) | src[i];
  return v;
}

void be32(uint8_t* dst, uint32_t v) {
  for (int i = 3; i >= 0; --i) {
    dst[i] = v & 0xff;
    v >>= 8;
  }
}

uint32_t rd32(const uint8_t* src) {
  uint32_t v = 0;
  for (int i = 0; i < 4; ++i) v = (v << 8) | src[i];
  return v;
}

// CRC-32 (IEEE 802.3, reflected poly 0xEDB88320) -- bit-identical to
// Python's zlib.crc32, so both ends of a frame agree without linking zlib.
// Only the epoll-loop thread calls this, so the lazy table init is safe.
uint32_t crc32_ieee(const uint8_t* p, uint64_t n) {
  static uint32_t table[256];
  static bool ready = false;
  if (!ready) {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k) c = (c & 1) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      table[i] = c;
    }
    ready = true;
  }
  uint32_t c = 0xFFFFFFFFu;
  for (uint64_t i = 0; i < n; ++i) c = table[(c ^ p[i]) & 0xFFu] ^ (c >> 8);
  return c ^ 0xFFFFFFFFu;
}

void arm_write(Server* s, Conn* c) {
  epoll_event ev{};
  ev.events = EPOLLIN | (c->wbuf.size() > c->woff ? EPOLLOUT : 0u);
  ev.data.fd = c->fd;
  epoll_ctl(s->epfd, EPOLL_CTL_MOD, c->fd, &ev);
}

void send_reply(Server* s, Conn* c, const uint8_t* payload, uint64_t len,
                const std::string* track_queue = nullptr) {
  size_t base = c->wbuf.size();
  c->wbuf.resize(base + 12 + len);
  be64(c->wbuf.data() + base, len);
  be32(c->wbuf.data() + base + 8, crc32_ieee(payload, len));
  if (len) memcpy(c->wbuf.data() + base + 12, payload, len);
  uint64_t begin = c->total_enqueued;
  c->total_enqueued += 12 + len;
  // Tracking stores offsets only — the bytes live in wbuf; a second payload
  // copy is taken just-in-time at requeue (connection death, the rare path).
  if (track_queue) {
    c->inflight.push_back({*track_queue, begin, c->total_enqueued});
  }
  arm_write(s, c);
}

void pump_queue(Server* s, const std::string& q);

void close_conn(Server* s, Conn* c) {
  if (c->parked) {
    auto& dq = s->getters[c->parked_queue];
    for (auto it = dq.begin(); it != dq.end(); ++it) {
      if (*it == c->fd) {
        dq.erase(it);
        break;
      }
    }
  }
  epoll_ctl(s->epfd, EPOLL_CTL_DEL, c->fd, nullptr);
  close(c->fd);
  s->conns.erase(c->fd);
  // Requeue GET replies this connection never fully received (front-most
  // first so FIFO order is preserved for the next getter). wbuf still holds
  // every unflushed byte: it is only cleared when fully flushed, and then
  // inflight is empty — so offset math into the current wbuf is safe.
  std::vector<std::string> touched;
  uint64_t wbase = c->total_enqueued - c->wbuf.size();
  for (auto it = c->inflight.rbegin(); it != c->inflight.rend(); ++it) {
    if (it->end > c->total_flushed) {
      const uint8_t* p = c->wbuf.data() + (it->begin - wbase) + 12;
      s->queues[it->queue].emplace_front(p, p + (it->end - it->begin - 12));
      touched.push_back(it->queue);
    }
  }
  delete c;
  for (const auto& queue : touched) pump_queue(s, queue);
}

// Deliver queued messages to parked getters (called after every PUT/GET).
void pump_queue(Server* s, const std::string& q) {
  auto& msgs = s->queues[q];
  auto& waiters = s->getters[q];
  while (!msgs.empty() && !waiters.empty()) {
    int fd = waiters.front();
    waiters.pop_front();
    auto it = s->conns.find(fd);
    if (it == s->conns.end()) continue;  // getter died meanwhile
    Conn* c = it->second;
    c->parked = false;
    send_reply(s, c, msgs.front().data(), msgs.front().size(), &q);
    msgs.pop_front();
  }
  if (msgs.empty()) s->queues.erase(q);
  if (waiters.empty()) s->getters.erase(q);
}

// Parse complete frames out of c->rbuf; returns false when c must close
// (protocol violation).
bool process_input(Server* s, Conn* c) {
  for (;;) {
    const uint8_t* b = c->rbuf.data();
    size_t n = c->rbuf.size();
    if (n < 3) return true;
    uint8_t op = b[0];
    uint16_t qlen = (uint16_t(b[1]) << 8) | b[2];
    if (op < kOpPut || op > kOpCancel) return false;
    if (qlen > kMaxQueueName) return false;
    size_t header = 3 + qlen;
    uint64_t plen = 0;
    uint32_t crc = 0;
    if (op == kOpPut) {
      if (n < header + 12) return true;
      plen = rd64(b + header);
      crc = rd32(b + header + 8);
      if (plen > kMaxPayload) return false;
      header += 12;
    }
    if (n < header + plen) return true;
    std::string q(reinterpret_cast<const char*>(b + 3), qlen);

    if (op == kOpPut) {
      // Ingress integrity gate: a payload damaged on the sender->hub leg is
      // dropped HERE, so a consumer can never be handed corrupt activation
      // bytes -- the frame simply "never arrived" and the sender's timeout/
      // failover machinery takes over.
      if (crc32_ieee(b + header, plen) == crc) {
        s->queues[q].emplace_back(b + header, b + header + plen);
        pump_queue(s, q);
      }
    } else if (op == kOpGet) {
      s->getters[q].push_back(c->fd);
      c->parked = true;
      c->parked_queue = q;
      pump_queue(s, q);
    } else if (op == kOpPing) {
      send_reply(s, c, reinterpret_cast<const uint8_t*>("PONG"), 4);
    } else {  // CANCEL
      if (c->parked) {
        auto& dq = s->getters[c->parked_queue];
        for (auto it = dq.begin(); it != dq.end(); ++it) {
          if (*it == c->fd) {
            dq.erase(it);
            break;
          }
        }
        c->parked = false;
      }
      size_t base = c->wbuf.size();
      c->wbuf.resize(base + 8);
      be64(c->wbuf.data() + base, kCancelAck);
      c->total_enqueued += 8;
      arm_write(s, c);
    }
    c->rbuf.erase(c->rbuf.begin(), c->rbuf.begin() + header + plen);
  }
}

void loop_body(Server* s) {
  epoll_event events[64];
  while (!s->stopping) {
    int nev = epoll_wait(s->epfd, events, 64, 200);
    for (int i = 0; i < nev; ++i) {
      int fd = events[i].data.fd;
      if (fd == s->wake_fd) {
        uint64_t tmp;
        ssize_t r = read(s->wake_fd, &tmp, 8);
        (void)r;
        continue;
      }
      if (fd == s->listen_fd) {
        for (;;) {
          int cfd = accept(s->listen_fd, nullptr, nullptr);
          if (cfd < 0) break;
          set_nonblock(cfd);
          int one = 1;
          setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
          Conn* c = new Conn();
          c->fd = cfd;
          s->conns[cfd] = c;
          epoll_event ev{};
          ev.events = EPOLLIN;
          ev.data.fd = cfd;
          epoll_ctl(s->epfd, EPOLL_CTL_ADD, cfd, &ev);
        }
        continue;
      }
      auto it = s->conns.find(fd);
      if (it == s->conns.end()) continue;
      Conn* c = it->second;
      bool dead = false;
      // NB: EPOLLHUP often arrives together with the connection's final
      // data (fire-and-forget PUT then close). Drain and process the input
      // FIRST; recv() returning 0 marks the connection dead afterwards.
      if ((events[i].events & (EPOLLHUP | EPOLLERR)) &&
          !(events[i].events & EPOLLIN)) {
        dead = true;
      }
      if (events[i].events & EPOLLIN) {
        uint8_t buf[1 << 16];
        for (;;) {
          ssize_t r = recv(fd, buf, sizeof(buf), 0);
          if (r > 0) {
            c->rbuf.insert(c->rbuf.end(), buf, buf + r);
          } else if (r == 0) {
            dead = true;
            break;
          } else {
            if (errno == EAGAIN || errno == EWOULDBLOCK) break;
            dead = true;
            break;
          }
        }
        // Process drained frames even when the peer already closed — a
        // fire-and-forget PUT's bytes arrive together with the EOF.
        if (!process_input(s, c)) dead = true;
      }
      if (!dead && (events[i].events & EPOLLOUT)) {
        while (c->woff < c->wbuf.size()) {
          ssize_t r =
              send(fd, c->wbuf.data() + c->woff, c->wbuf.size() - c->woff, 0);
          if (r > 0) {
            c->woff += size_t(r);
            c->total_flushed += uint64_t(r);
            while (!c->inflight.empty() &&
                   c->inflight.front().end <= c->total_flushed) {
              c->inflight.pop_front();
            }
          } else {
            if (errno == EAGAIN || errno == EWOULDBLOCK) break;
            dead = true;
            break;
          }
        }
        if (c->woff == c->wbuf.size()) {
          c->wbuf.clear();
          c->woff = 0;
        }
        arm_write(s, c);
      }
      if (dead) close_conn(s, c);
    }
  }
}

}  // namespace

extern "C" {

// Starts the relay on `port` (0 = ephemeral) in a background thread.
// Returns an opaque handle, or null on failure.
void* relay_start(int port) {
  Server* s = new Server();
  s->listen_fd = socket(AF_INET, SOCK_STREAM, 0);
  if (s->listen_fd < 0) {
    delete s;
    return nullptr;
  }
  int one = 1;
  setsockopt(s->listen_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = htonl(INADDR_ANY);
  addr.sin_port = htons(uint16_t(port));
  if (bind(s->listen_fd, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) <
          0 ||
      listen(s->listen_fd, 128) < 0) {
    close(s->listen_fd);
    delete s;
    return nullptr;
  }
  socklen_t alen = sizeof(addr);
  getsockname(s->listen_fd, reinterpret_cast<sockaddr*>(&addr), &alen);
  s->port = ntohs(addr.sin_port);
  set_nonblock(s->listen_fd);

  s->epfd = epoll_create1(0);
  s->wake_fd = eventfd(0, EFD_NONBLOCK);
  epoll_event ev{};
  ev.events = EPOLLIN;
  ev.data.fd = s->listen_fd;
  epoll_ctl(s->epfd, EPOLL_CTL_ADD, s->listen_fd, &ev);
  ev.data.fd = s->wake_fd;
  epoll_ctl(s->epfd, EPOLL_CTL_ADD, s->wake_fd, &ev);

  s->loop = std::thread(loop_body, s);
  return s;
}

int relay_port(void* handle) {
  return handle ? static_cast<Server*>(handle)->port : -1;
}

void relay_stop(void* handle) {
  if (!handle) return;
  Server* s = static_cast<Server*>(handle);
  s->stopping = true;
  uint64_t one = 1;
  ssize_t r = write(s->wake_fd, &one, 8);
  (void)r;
  s->loop.join();
  for (auto& [fd, c] : s->conns) {
    close(fd);
    delete c;
  }
  close(s->listen_fd);
  close(s->wake_fd);
  close(s->epfd);
  delete s;
}

}  // extern "C"
