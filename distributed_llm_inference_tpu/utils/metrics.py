"""Structured metrics and timing.

The reference's only observability is two ``print`` statements in its weight
loader (``/root/reference/distributed_llm_inference/utils/model.py:61,82``;
SURVEY §5.5). Here: counters + latency histograms good enough to derive the
BASELINE metrics (tokens/sec/chip, p50 TTFT, batch occupancy) plus structured
logging hooks.
"""

from __future__ import annotations

import collections
import contextlib
import json
import logging
import re
import statistics
import threading
import time
from typing import Dict, List, Optional

_PROM_NAME = re.compile(r"[^a-zA-Z0-9_:]")

logger = logging.getLogger("distributed_llm_inference_tpu")

# Central metric registry: every name emitted anywhere in the package,
# declared once — name -> (kind, help). ``tools/distcheck`` (DC400/DC401)
# enforces that emitters and this table never drift: an undeclared emit or
# a dead declaration fails tier-1. ``*`` entries match dynamically
# suffixed families (f-string names). Kinds: ``counter`` (monotonic,
# ``_total`` on /metrics), ``gauge`` (last-write-wins), ``summary``
# (observe()/timer() histories; ``_seconds`` on /metrics unless the name
# carries its own unit suffix). Names here are pre-exposition — the
# prometheus() renderer appends the suffixes, so declarations must not.
METRICS = {
    # engine: admission + sessions
    "sessions_submitted": ("counter", "Sessions accepted by submit()"),
    "sessions_finished": ("counter", "Sessions retired (any reason)"),
    "sessions_rejected": ("counter", "Sessions refused at admission"),
    "sessions_deadline_expired": ("counter", "Sessions reaped past deadline"),
    "admit_sync_sessions": ("counter", "Sessions admitted synchronously"),
    "admit_overlap_sessions": ("counter", "Sessions admitted via overlap"),
    "admit_overlap_spill": ("counter", "Overlap admissions spilled to sync"),
    "admit_overlap_inflight": ("gauge", "Prefills in flight behind decode"),
    "admit_to_merge": ("summary", "Overlap admission to KV-merge latency"),
    # engine: prefill / decode hot path
    "prefill": ("summary", "Prefill dispatch latency"),
    "prefill_tokens": ("counter", "Prompt tokens prefilled"),
    "batched_prefills": ("counter", "Prefills served by batched dispatch"),
    "ring_prefills": ("counter", "Prefills served by the ring pipeline"),
    "prefix_cached_tokens": ("counter", "Prompt tokens served from prefix cache"),
    # prefixstore: CoW sharing / host-DRAM spill tier / prefix routing
    "prefix_hit_rate": ("gauge", "Cumulative fraction of prompt tokens reused"),
    "prefix_pages_shared": ("counter", "Shared prefix-page attachments"),
    "prefix_cow_copies": ("counter", "Copy-on-write splits of shared pages"),
    "prefix_spill_bytes": ("gauge", "Host spill arena bytes resident"),
    "prefix_spilled_pages": ("counter", "Prefix pages spilled to host DRAM"),
    "prefix_spill_reloads": ("counter", "Prefix pages reloaded from the arena"),
    "prefix_reload_ms": ("summary", "Host->device prefix page reload time"),
    "prefix_reload_errors": ("counter", "Arena entries rejected at reload"),
    "routed_by_prefix": ("counter", "Requests routed to a prefix-holding node"),
    # engine: attention plan (ragged mixed-phase dispatch — engine/plan.py)
    "attn_recompiles": ("counter", "First-seen attention dispatch shapes"),
    "attn_ragged_dispatches": ("counter", "Prefill-family ragged dispatches"),
    "attn_chunked_rows": ("counter", "Chunk rows co-scheduled with decode"),
    "attn_grid_occupancy": ("gauge", "Valid/padded tokens, last dispatch"),
    "decode_step": ("summary", "One decode tick (dispatch+resolve)"),
    "decode_resolve": ("summary", "Deferred decode fetch latency"),
    "decode_tokens": ("counter", "Tokens emitted by decode"),
    "cache_growths": ("counter", "KV cache reallocations"),
    # latent (MLA) KV compression (cache/latent.py)
    "kv_bytes_per_token": ("gauge", "Stored KV bytes per token, all layers"),
    "latent_decompress_dispatches": (
        "counter", "Attention dispatches reading the latent stored form"
    ),
    # engine: speculative decoding
    "spec_adapt_window_resets": ("counter", "Adaptive-k A/B window resets"),
    "spec_adapt_probes": ("counter", "Adaptive-k probe windows started"),
    "spec_adapt_suspensions": ("counter", "Speculation suspensions (low accept)"),
    # disaggregated prefill/decode
    "disagg_prefills": ("counter", "Remote prefills exported"),
    "disagg_admitted": ("counter", "Sessions admitted from shipped KV"),
    "disagg_fallback_local": ("counter", "Disagg failures served locally"),
    "disagg_kv_frames_sent": ("counter", "KV frames shipped to decode pool"),
    "disagg_prefill_errors": ("counter", "Prefill-pool requests that errored"),
    "kv_transfer_bytes": ("summary", "Shipped KV payload size per session"),
    "kv_transfer_ms": ("summary", "KV ship+decode wall time per session"),
    # distributed client / worker / relay plane
    "connections_opened": ("counter", "Relay connections dialed"),
    "failovers": ("counter", "Mid-generation worker re-routes"),
    "stale_replies_discarded": ("counter", "Replies from abandoned attempts"),
    "row_errors": ("counter", "Per-row errors inside batched replies"),
    "client_batch_group": ("summary", "generate_many co-batch group size"),
    "client_generate_errors": ("counter", "Client-side generate failures"),
    "malformed_frames": ("counter", "Frames dropped by schema checks"),
    "unknown_ops_dropped": ("counter", "Frames dropped for an unknown op"),
    "duplicate_hops_skipped": ("counter", "At-most-once hop dedup skips"),
    "worker_restarts": ("counter", "Consume-thread watchdog restarts"),
    "pool_batch_occupancy": ("summary", "Items per task-pool device call"),
    "pool_batches_size_*": ("counter", "Task-pool batches by exact size"),
    # serving gateway
    "http_requests": ("counter", "Completion requests received"),
    "http_429": ("counter", "Requests shed at capacity"),
    "http_503_breaker": ("counter", "Requests failed fast by the breaker"),
    "ttft": ("summary", "Gateway time to first token"),
    "gateway_tokens": ("counter", "Tokens delivered to HTTP clients"),
    "queue_depth": ("gauge", "Backend queue depth at scrape"),
    "active_sessions": ("gauge", "Live backend sessions at scrape"),
    "http_inflight": ("gauge", "Gateway in-flight completions"),
    "engine_ttft": ("summary", "Engine-side TTFT (sync admission)"),
    "engine_ttft_decode": ("summary", "Engine-side TTFT (overlap admission)"),
    "engine_ttft_prefill": ("summary", "Engine-side TTFT (disagg prefill)"),
    # multi-tenant admission scheduler (sched/)
    "sched_admitted": ("counter", "Tickets admitted by the scheduler"),
    "sched_tenant_admit_*": ("counter", "Admitted tickets by tenant"),
    "sched_reject_rate_limit": ("counter", "429s from a tenant token bucket"),
    "sched_reject_queue_full": ("counter", "429s from lane/gateway depth caps"),
    "sched_shed_early": ("counter", "Requests shed pre-prefill by deadline"),
    "sched_lane_depth_*": ("gauge", "Pending tickets per admission lane"),
    "sched_queue_wait": ("summary", "Ticket admission to first token"),
    # distributed request tracing (utils/tracing.py + serving gateway)
    "traces_sampled": ("counter", "Requests minted a TraceContext"),
    "trace_spans_dropped": ("counter", "Spans evicted by recorder capacity"),
    "trace_pull_failures": ("counter", "trace.pull node collections failed"),
    # circuit breaker
    "breaker_state": ("gauge", "0 closed / 1 open / 2 half-open"),
    "breaker_*_transitions": ("counter", "Breaker transitions into a state"),
    "breaker_failures_recorded": ("counter", "Failure signals seen"),
    # session migration / crash recovery (migrate.* frame plane)
    "sessions_exported": ("counter", "Mid-decode sessions snapshotted"),
    "sessions_resumed": ("counter", "Sessions re-admitted from a snapshot"),
    "checkpoints_shipped": ("counter", "Session checkpoints sent to gateway"),
    "checkpoint_frames_sent": ("counter", "Checkpoint KV frames shipped"),
    "node_deaths_detected": ("counter", "Decode nodes declared dead mid-stream"),
    "resume_attempts": ("counter", "Stream migrations started after a death"),
    "resume_failures": ("counter", "Streams failed after resume budget spent"),
    "resume_shed": ("counter", "Resumes shed by deadline headroom"),
    "tokens_deduped": ("counter", "Replayed tokens suppressed by seq dedup"),
    "stale_frames_fenced": ("counter", "Frames dropped from fenced attempts"),
    "mttr_ms": ("summary", "Death detection to first post-resume token"),
    # elastic fleet controller (fleet/): drain / rebalance / autoscale
    "fleet_drains": ("counter", "Drain operations issued to decode nodes"),
    "fleet_drained_sessions": ("counter", "Streams re-homed by a drain handoff"),
    "fleet_handoffs_sent": ("counter", "Session handoffs shipped by nodes"),
    "fleet_rebalance_migrations": ("counter", "Sessions asked off hot nodes"),
    "fleet_scale_out": ("counter", "Autoscaler pool-grow decisions"),
    "fleet_scale_in": ("counter", "Autoscaler drain-then-fence decisions"),
    "fleet_pool_size": ("gauge", "Live (non-draining) decode nodes at scrape"),
    # bytes-vs-latency placement decisions (fleet/costmodel.py)
    "fleet_query_moved": ("counter", "Placements routed to the prefix holder"),
    "fleet_pages_fetched": ("counter", "Placements that shipped prefix pages"),
    "fleet_migrated": ("counter", "Placements that recompute elsewhere"),
    "fleet_pages_served": ("counter", "Prefix pages exported for a page-ship"),
    "fleet_pages_imported": ("counter", "Shipped prefix pages installed"),
    "fleet_page_ship_failed": ("counter", "Page-ships abandoned (cold fallback)"),
    "fleet_page_ship_ms": ("summary", "Page-ship round trip wall time"),
}


class Metrics:
    """Thread-safe counters and timers (the serving loop runs host threads
    around the jitted steps — SURVEY §5.2's concurrency caution)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = collections.defaultdict(float)
        self._timings: Dict[str, List[float]] = collections.defaultdict(list)
        self._gauges: Dict[str, float] = {}

    def counter(self, name: str, inc: float = 1.0) -> None:
        with self._lock:
            self._counters[name] += inc

    def gauge(self, name: str, value: float) -> None:
        """Set a persistent gauge (last-write-wins) — for state that an
        owner updates on transition (circuit-breaker state, pool size)
        rather than the caller sampling it at scrape time."""
        with self._lock:
            self._gauges[name] = float(value)

    def get_gauge(self, name: str, default: float = 0.0) -> float:
        with self._lock:
            return self._gauges.get(name, default)

    @contextlib.contextmanager
    def timer(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            with self._lock:
                self._timings[name].append(time.perf_counter() - t0)

    def get_counter(self, name: str) -> float:
        """One counter's current value (snapshot() is unsuitable for
        per-tick reads — it sorts every timing list)."""
        with self._lock:
            return self._counters.get(name, 0.0)

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            self._timings[name].append(value)

    def percentile(self, name: str, q: float) -> float:
        with self._lock:
            vals = sorted(self._timings.get(name, []))
        if not vals:
            return float("nan")
        idx = min(len(vals) - 1, int(q / 100.0 * len(vals)))
        return vals[idx]

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            out = dict(self._counters)
            out.update(self._gauges)
            for name, vals in self._timings.items():
                if not vals:
                    continue
                out[f"{name}_count"] = len(vals)
                out[f"{name}_mean_s"] = statistics.fmean(vals)
                srt = sorted(vals)
                out[f"{name}_p50_s"] = srt[len(srt) // 2]
                out[f"{name}_p99_s"] = srt[min(len(srt) - 1, int(0.99 * len(srt)))]
        return out

    def log_snapshot(self) -> None:
        logger.info("metrics %s", json.dumps(self.snapshot(), sort_keys=True))

    def prometheus(
        self,
        prefix: str = "dli",
        extra_gauges: Optional[Dict[str, float]] = None,
    ) -> str:
        """Prometheus text exposition (the ``/metrics`` endpoint body).

        Counters become ``<prefix>_<name>_total`` counters; timings become
        ``<prefix>_<name>_seconds`` summaries (p50/p99 quantiles + _sum +
        _count); ``extra_gauges`` are point-in-time gauges (queue depth,
        active sessions) sampled by the caller and merged over the
        persistent ``gauge()`` values."""

        def clean(name: str) -> str:
            return _PROM_NAME.sub("_", f"{prefix}_{name}")

        with self._lock:
            counters = dict(self._counters)
            timings = {k: list(v) for k, v in self._timings.items()}
            gauges = dict(self._gauges)
        gauges.update(extra_gauges or {})
        lines: List[str] = []
        for name in sorted(counters):
            metric = clean(name) + "_total"
            lines.append(f"# TYPE {metric} counter")
            lines.append(f"{metric} {counters[name]:.10g}")
        for name in sorted(timings):
            vals = sorted(timings[name])
            if not vals:
                continue
            # Summaries default to seconds; names that already carry their
            # unit (kv_transfer_bytes, kv_transfer_ms) keep it as-is.
            suffix = "" if name.endswith(("_bytes", "_ms")) else "_seconds"
            metric = clean(name) + suffix
            lines.append(f"# TYPE {metric} summary")
            p50 = vals[len(vals) // 2]
            p99 = vals[min(len(vals) - 1, int(0.99 * len(vals)))]
            lines.append(f'{metric}{{quantile="0.5"}} {p50:.10g}')
            lines.append(f'{metric}{{quantile="0.99"}} {p99:.10g}')
            lines.append(f"{metric}_sum {sum(vals):.10g}")
            lines.append(f"{metric}_count {len(vals)}")
        for name in sorted(gauges):
            metric = clean(name)
            lines.append(f"# TYPE {metric} gauge")
            lines.append(f"{metric} {gauges[name]:.10g}")
        return "\n".join(lines) + "\n"
