"""Structured metrics and timing.

The reference's only observability is two ``print`` statements in its weight
loader (``/root/reference/distributed_llm_inference/utils/model.py:61,82``;
SURVEY §5.5). Here: counters + latency histograms good enough to derive the
BASELINE metrics (tokens/sec/chip, p50 TTFT, batch occupancy) plus structured
logging hooks.
"""

from __future__ import annotations

import collections
import contextlib
import json
import logging
import statistics
import threading
import time
from typing import Dict, List

logger = logging.getLogger("distributed_llm_inference_tpu")


class Metrics:
    """Thread-safe counters and timers (the serving loop runs host threads
    around the jitted steps — SURVEY §5.2's concurrency caution)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = collections.defaultdict(float)
        self._timings: Dict[str, List[float]] = collections.defaultdict(list)

    def counter(self, name: str, inc: float = 1.0) -> None:
        with self._lock:
            self._counters[name] += inc

    @contextlib.contextmanager
    def timer(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            with self._lock:
                self._timings[name].append(time.perf_counter() - t0)

    def get_counter(self, name: str) -> float:
        """One counter's current value (snapshot() is unsuitable for
        per-tick reads — it sorts every timing list)."""
        with self._lock:
            return self._counters.get(name, 0.0)

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            self._timings[name].append(value)

    def percentile(self, name: str, q: float) -> float:
        with self._lock:
            vals = sorted(self._timings.get(name, []))
        if not vals:
            return float("nan")
        idx = min(len(vals) - 1, int(q / 100.0 * len(vals)))
        return vals[idx]

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            out = dict(self._counters)
            for name, vals in self._timings.items():
                if not vals:
                    continue
                out[f"{name}_count"] = len(vals)
                out[f"{name}_mean_s"] = statistics.fmean(vals)
                srt = sorted(vals)
                out[f"{name}_p50_s"] = srt[len(srt) // 2]
                out[f"{name}_p99_s"] = srt[min(len(srt) - 1, int(0.99 * len(srt)))]
        return out

    def log_snapshot(self) -> None:
        logger.info("metrics %s", json.dumps(self.snapshot(), sort_keys=True))
