"""Structured metrics and timing.

The reference's only observability is two ``print`` statements in its weight
loader (``/root/reference/distributed_llm_inference/utils/model.py:61,82``;
SURVEY §5.5). Here: counters + latency histograms good enough to derive the
BASELINE metrics (tokens/sec/chip, p50 TTFT, batch occupancy) plus structured
logging hooks.
"""

from __future__ import annotations

import collections
import contextlib
import json
import logging
import re
import statistics
import threading
import time
from typing import Dict, List, Optional

_PROM_NAME = re.compile(r"[^a-zA-Z0-9_:]")

logger = logging.getLogger("distributed_llm_inference_tpu")


class Metrics:
    """Thread-safe counters and timers (the serving loop runs host threads
    around the jitted steps — SURVEY §5.2's concurrency caution)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = collections.defaultdict(float)
        self._timings: Dict[str, List[float]] = collections.defaultdict(list)
        self._gauges: Dict[str, float] = {}

    def counter(self, name: str, inc: float = 1.0) -> None:
        with self._lock:
            self._counters[name] += inc

    def gauge(self, name: str, value: float) -> None:
        """Set a persistent gauge (last-write-wins) — for state that an
        owner updates on transition (circuit-breaker state, pool size)
        rather than the caller sampling it at scrape time."""
        with self._lock:
            self._gauges[name] = float(value)

    def get_gauge(self, name: str, default: float = 0.0) -> float:
        with self._lock:
            return self._gauges.get(name, default)

    @contextlib.contextmanager
    def timer(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            with self._lock:
                self._timings[name].append(time.perf_counter() - t0)

    def get_counter(self, name: str) -> float:
        """One counter's current value (snapshot() is unsuitable for
        per-tick reads — it sorts every timing list)."""
        with self._lock:
            return self._counters.get(name, 0.0)

    def observe(self, name: str, value: float) -> None:
        with self._lock:
            self._timings[name].append(value)

    def percentile(self, name: str, q: float) -> float:
        with self._lock:
            vals = sorted(self._timings.get(name, []))
        if not vals:
            return float("nan")
        idx = min(len(vals) - 1, int(q / 100.0 * len(vals)))
        return vals[idx]

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            out = dict(self._counters)
            out.update(self._gauges)
            for name, vals in self._timings.items():
                if not vals:
                    continue
                out[f"{name}_count"] = len(vals)
                out[f"{name}_mean_s"] = statistics.fmean(vals)
                srt = sorted(vals)
                out[f"{name}_p50_s"] = srt[len(srt) // 2]
                out[f"{name}_p99_s"] = srt[min(len(srt) - 1, int(0.99 * len(srt)))]
        return out

    def log_snapshot(self) -> None:
        logger.info("metrics %s", json.dumps(self.snapshot(), sort_keys=True))

    def prometheus(
        self,
        prefix: str = "dli",
        extra_gauges: Optional[Dict[str, float]] = None,
    ) -> str:
        """Prometheus text exposition (the ``/metrics`` endpoint body).

        Counters become ``<prefix>_<name>_total`` counters; timings become
        ``<prefix>_<name>_seconds`` summaries (p50/p99 quantiles + _sum +
        _count); ``extra_gauges`` are point-in-time gauges (queue depth,
        active sessions) sampled by the caller and merged over the
        persistent ``gauge()`` values."""

        def clean(name: str) -> str:
            return _PROM_NAME.sub("_", f"{prefix}_{name}")

        with self._lock:
            counters = dict(self._counters)
            timings = {k: list(v) for k, v in self._timings.items()}
            gauges = dict(self._gauges)
        gauges.update(extra_gauges or {})
        lines: List[str] = []
        for name in sorted(counters):
            metric = clean(name) + "_total"
            lines.append(f"# TYPE {metric} counter")
            lines.append(f"{metric} {counters[name]:.10g}")
        for name in sorted(timings):
            vals = sorted(timings[name])
            if not vals:
                continue
            # Summaries default to seconds; names that already carry their
            # unit (kv_transfer_bytes, kv_transfer_ms) keep it as-is.
            suffix = "" if name.endswith(("_bytes", "_ms")) else "_seconds"
            metric = clean(name) + suffix
            lines.append(f"# TYPE {metric} summary")
            p50 = vals[len(vals) // 2]
            p99 = vals[min(len(vals) - 1, int(0.99 * len(vals)))]
            lines.append(f'{metric}{{quantile="0.5"}} {p50:.10g}')
            lines.append(f'{metric}{{quantile="0.99"}} {p99:.10g}')
            lines.append(f"{metric}_sum {sum(vals):.10g}")
            lines.append(f"{metric}_count {len(vals)}")
        for name in sorted(gauges):
            metric = clean(name)
            lines.append(f"# TYPE {metric} gauge")
            lines.append(f"{metric} {gauges[name]:.10g}")
        return "\n".join(lines) + "\n"
