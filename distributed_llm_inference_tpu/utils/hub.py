"""Remote checkpoint streaming: an HTTP(S) ``resolve`` hook for the loader.

The reference's loader pulls the index and every needed shard straight from
the HuggingFace hub via ``cached_file``
(``/root/reference/distributed_llm_inference/utils/model.py:27-34,47-50``);
our loader (``utils/checkpoint.py``) parameterizes filename→path lookup with
a ``resolve`` callable. :class:`HttpResolver` implements it over plain
HTTP(S): on first request a file streams into a local content cache
(resumable — interrupted downloads continue with a ``Range`` request from
the partial file's length) and every later request is a cache hit, so a
worker can cold-start onto a fresh host with nothing but a URL: the index
downloads first, ``weight_map`` prefix filtering picks the node's shards,
and ONLY those shards ever cross the network (a 70B mid-pipeline node pulls
its ~GBs, not the checkpoint).

stdlib ``urllib`` only — no hub SDK dependency; :func:`hub_resolver` builds
the HF-hub URL layout (``{endpoint}/{repo_id}/resolve/{revision}``) on top.
"""

from __future__ import annotations

import os
import urllib.error
import urllib.parse
import urllib.request
from typing import Optional

__all__ = ["HttpResolver", "hub_resolver"]

_CHUNK = 1 << 20  # 1 MiB read chunks


class HttpResolver:
    """``resolve(name) -> local path`` backed by ``base_url``.

    Missing files (HTTP 404) return ``None`` — exactly the contract
    :func:`utils.checkpoint.find_index` probes its pattern list with.
    Other HTTP/network failures raise (a worker must not silently treat an
    unreachable registry as an absent checkpoint).
    """

    def __init__(self, base_url: str, cache_dir: str, timeout: float = 60.0):
        self.base_url = base_url.rstrip("/")
        self.cache_dir = cache_dir
        self.timeout = timeout
        os.makedirs(cache_dir, exist_ok=True)

    def _url(self, name: str) -> str:
        return f"{self.base_url}/{urllib.parse.quote(name)}"

    def __call__(self, name: str) -> Optional[str]:
        # Names come from DOWNLOADED indexes (weight_map values) — reject
        # traversal so a hostile checkpoint cannot write outside the cache
        # (backslashes rejected outright: no real checkpoint uses them, and
        # they would separate paths on Windows).
        if (
            name.startswith("/")
            or "\\" in name
            or ".." in name.split("/")
        ):
            raise ValueError(f"unsafe checkpoint file name: {name!r}")
        local = os.path.join(self.cache_dir, name.replace("/", os.sep))
        if os.path.exists(local):
            return local
        part = f"{local}.part"
        # Per-process scratch: two nodes sharing a cache dir (co-located
        # pipeline stages, same --model URL) must not interleave writes
        # into one file; the shared ``.part`` is only ever a read-only
        # resume SOURCE and an atomically-replaced checkpoint.
        tmp = f"{part}.{os.getpid()}"
        os.makedirs(os.path.dirname(local) or ".", exist_ok=True)
        offset = 0
        if os.path.exists(part):
            with open(part, "rb") as src, open(tmp, "wb") as dst:
                while True:
                    chunk = src.read(_CHUNK)
                    if not chunk:
                        break
                    dst.write(chunk)
                offset = dst.tell()
        req = urllib.request.Request(self._url(name))
        if offset:
            req.add_header("Range", f"bytes={offset}-")
        try:
            resp = urllib.request.urlopen(req, timeout=self.timeout)
        except urllib.error.HTTPError as e:
            if os.path.exists(tmp) and e.code != 416:
                os.remove(tmp)
            if e.code == 404:
                return None
            if e.code == 416 and offset:
                # Range past EOF: the partial already holds everything (the
                # previous run died between the last write and the rename).
                os.replace(tmp, local)
                return local
            raise
        try:
            with resp:
                # A server ignoring the Range header replays the whole file
                # (status 200, not 206): restart from zero.
                resumed = bool(offset) and resp.status == 206
                expect = resp.headers.get("Content-Length")
                expect = int(expect) if expect is not None else None
                mode = "ab" if resumed else "wb"
                written = offset if resumed else 0
                with open(tmp, mode) as f:
                    if not resumed:
                        f.truncate(0)
                    while True:
                        chunk = resp.read(_CHUNK)
                        if not chunk:
                            break
                        f.write(chunk)
                        written += len(chunk)
            if expect is not None and written != (
                offset + expect if resumed else expect
            ):
                # Early FIN: http.client returns short data then b'' rather
                # than raising, so verify against Content-Length — a
                # truncated file must never be promoted to the cache.
                os.replace(tmp, part)  # checkpoint for the next resume
                raise IOError(
                    f"truncated download of {name!r}: got {written} bytes"
                )
        except Exception:
            if os.path.exists(tmp):
                os.replace(tmp, part)  # keep the bytes for resume
            raise
        os.replace(tmp, local)  # atomic: readers see whole files only
        return local


def hub_resolver(
    repo_id: str,
    cache_dir: str,
    revision: str = "main",
    endpoint: str = "https://huggingface.co",
) -> HttpResolver:
    """Resolver over the HF hub's ``/{repo}/resolve/{revision}/{file}`` URL
    layout (the reference's ``cached_file`` route, ``utils/model.py:29``) —
    or any mirror serving the same path shape via ``endpoint``."""
    return HttpResolver(
        f"{endpoint.rstrip('/')}/{repo_id}/resolve/{revision}", cache_dir
    )
