"""Minimal xplane.pb parser: aggregate TPU device-op durations from a
``jax.profiler`` trace.

The reference has no profiling story at all (SURVEY §5.1 — its only
observability is two ``print`` calls in the weight loader,
``/root/reference/distributed_llm_inference/utils/model.py:61,82``); here the
profiler is a first-class tool: ``tools/xplane_profile.py`` drives this module
interactively, and ``bench.py`` uses :func:`device_time_ps` to report the
device-only component of TTFT (the axon tunnel adds ~80 ms of round-trip
latency to every synchronous wall-clock measurement on this platform).

Durations in the xplane protobuf are picoseconds.
"""

from __future__ import annotations

import collections
import glob
import os
from typing import Counter, Tuple


def read_varint(buf: bytes, i: int):
    r = 0
    s = 0
    while True:
        b = buf[i]
        i += 1
        r |= (b & 0x7F) << s
        if not b & 0x80:
            return r, i
        s += 7


def fields(buf: bytes):
    """Iterate (field_number, value) over a serialized protobuf message."""
    i = 0
    n = len(buf)
    while i < n:
        tag, i = read_varint(buf, i)
        fnum, wt = tag >> 3, tag & 7
        if wt == 0:
            v, i = read_varint(buf, i)
            yield fnum, v
        elif wt == 2:
            ln, i = read_varint(buf, i)
            yield fnum, buf[i : i + ln]
            i += ln
        elif wt == 5:
            yield fnum, buf[i : i + 4]
            i += 4
        elif wt == 1:
            yield fnum, buf[i : i + 8]
            i += 8
        else:
            raise ValueError(f"wire type {wt}")


def aggregate(path: str, device: str = "/device:TPU:0") -> Tuple[
    int, Counter, Counter
]:
    """Parse one ``*.xplane.pb`` and sum per-op durations on ``device``.

    Returns ``(total_ps, dur_ps_by_op, count_by_op)``. Umbrella lines
    ("Steps", "XLA Modules") are excluded so the total counts each op once.
    """
    space = open(path, "rb").read()
    for fnum, plane_buf in fields(space):
        if fnum != 1:
            continue
        name = None
        meta = {}
        lines = []
        for pf, pv in fields(plane_buf):
            if pf == 2 and isinstance(pv, bytes):
                name = pv.decode(errors="replace")
            elif pf == 4:  # event_metadata map entry
                mid, mname = None, ""
                for mf, mv in fields(pv):
                    if mf == 1:
                        mid = mv
                    elif mf == 2:
                        for ef, ev in fields(mv):
                            if ef == 2 and isinstance(ev, bytes):
                                mname = ev.decode(errors="replace")
                meta[mid] = mname
            elif pf == 3:
                lines.append(pv)
        if name != device:
            continue
        agg: Counter = collections.Counter()
        cnt: Counter = collections.Counter()
        for line_buf in lines:
            lname = ""
            evs = []
            for lf, lv in fields(line_buf):
                if lf == 2 and isinstance(lv, bytes):
                    try:
                        lname = lv.decode()
                    except Exception:
                        lname = repr(lv)
                elif lf == 4:
                    evs.append(lv)
            if "Step" in lname or "Modules" in lname:
                continue  # whole-program umbrella lines
            for ev in evs:
                mid, dur = None, 0
                for ef, v in fields(ev):
                    if ef == 1:
                        mid = v
                    elif ef == 3:
                        dur = v
                agg[meta.get(mid, f"id{mid}")] += dur
                cnt[meta.get(mid, f"id{mid}")] += 1
        return sum(agg.values()), agg, cnt
    return 0, collections.Counter(), collections.Counter()


def find_xplane(trace_dir: str) -> str:
    """Locate the ``*.xplane.pb`` under a ``jax.profiler.trace`` output dir."""
    hits = glob.glob(
        os.path.join(trace_dir, "**", "*.xplane.pb"), recursive=True
    )
    if not hits:
        raise FileNotFoundError(f"no xplane.pb under {trace_dir}")
    return max(hits, key=os.path.getmtime)


def device_time_ps(trace_dir: str, device: str = "/device:TPU:0") -> int:
    """Total device-op time (picoseconds) recorded in a trace directory."""
    total, _, _ = aggregate(find_xplane(trace_dir), device)
    return total
