"""ctypes driver for the native safetensors reader (``native/streader.cc``).

The TPU-native analog of the Rust ``safetensors`` extension the reference
leans on (``/root/reference/distributed_llm_inference/utils/model.py:4,19``):
the C++ side mmaps the checkpoint and services tensor reads as multithreaded
copies out of the mapping (with ``madvise`` prefetch); the tiny JSON header
is parsed here. Falls back cleanly: callers should use
:func:`native_available` / catch and take the pure-Python ``safetensors``
path (``utils/checkpoint.py`` does).
"""

from __future__ import annotations

import ctypes
import json
import os
import subprocess
import threading
from typing import Dict, List, Optional, Sequence

import numpy as np

__all__ = ["NativeSafetensors", "build_native", "native_available", "DTYPES"]

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)), "native")
_SRC = os.path.join(_NATIVE_DIR, "streader.cc")
_SO = os.path.join(_NATIVE_DIR, "_streader.so")
_build_lock = threading.Lock()
_lib = None


def _bf16():
    import ml_dtypes

    return np.dtype(ml_dtypes.bfloat16)


# safetensors dtype tag → numpy dtype factory (bf16 needs ml_dtypes).
DTYPES = {
    "F64": lambda: np.dtype(np.float64),
    "F32": lambda: np.dtype(np.float32),
    "F16": lambda: np.dtype(np.float16),
    "BF16": _bf16,
    "I64": lambda: np.dtype(np.int64),
    "I32": lambda: np.dtype(np.int32),
    "I16": lambda: np.dtype(np.int16),
    "I8": lambda: np.dtype(np.int8),
    "U8": lambda: np.dtype(np.uint8),
    "BOOL": lambda: np.dtype(np.bool_),
}


def build_native(force: bool = False) -> str:
    """Compile ``streader.cc`` → ``_streader.so`` (cached by source mtime).

    Compiles to a pid-suffixed temp path then ``os.replace``s it in, so a
    concurrent process never ``dlopen``s a half-written library."""
    with _build_lock:
        if (
            not force
            and os.path.exists(_SO)
            and os.path.getmtime(_SO) >= os.path.getmtime(_SRC)
        ):
            return _SO
        tmp = f"{_SO}.tmp.{os.getpid()}"
        subprocess.run(
            ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", _SRC, "-o", tmp,
             "-pthread"],
            check=True,
            capture_output=True,
        )
        os.replace(tmp, _SO)
        return _SO


def _load_lib():
    global _lib
    if _lib is False:
        raise RuntimeError("native streader unavailable (earlier build failed)")
    if _lib is not None:
        return _lib
    try:
        lib = ctypes.CDLL(build_native())
    except Exception:
        # Cache the failure: without this, every shard read on the startup
        # path would re-spawn a doomed g++ subprocess.
        _lib = False
        raise
    lib.st_open.restype = ctypes.c_void_p
    lib.st_open.argtypes = [ctypes.c_char_p]
    lib.st_header_len.restype = ctypes.c_uint64
    lib.st_header_len.argtypes = [ctypes.c_void_p]
    lib.st_header.restype = ctypes.POINTER(ctypes.c_uint8)
    lib.st_header.argtypes = [ctypes.c_void_p]
    lib.st_data_len.restype = ctypes.c_uint64
    lib.st_data_len.argtypes = [ctypes.c_void_p]
    lib.st_prefetch.argtypes = [ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64]
    lib.st_copy.restype = ctypes.c_int32
    lib.st_copy.argtypes = [
        ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_void_p
    ]
    lib.st_copy_many.restype = ctypes.c_int32
    lib.st_copy_many.argtypes = [
        ctypes.c_void_p,
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_uint64),
        ctypes.POINTER(ctypes.c_void_p),
        ctypes.c_int32,
        ctypes.c_int32,
    ]
    lib.st_close.argtypes = [ctypes.c_void_p]
    _lib = lib
    return lib


def native_available() -> bool:
    try:
        _load_lib()
        return True
    except Exception:
        return False


class NativeSafetensors:
    """One open safetensors file; read tensors by name.

    Usage::

        with NativeSafetensors(path) as f:
            state = f.read_many([k for k in f.keys() if wanted(k)])
    """

    def __init__(self, path: str, threads: Optional[int] = None):
        lib = _load_lib()
        self._lib = lib
        self._h = lib.st_open(path.encode())
        if not self._h:
            raise OSError(f"st_open failed for {path!r} (missing/truncated?)")
        self.threads = threads or min(8, os.cpu_count() or 1)
        hlen = lib.st_header_len(self._h)
        raw = ctypes.string_at(lib.st_header(self._h), hlen)
        header = json.loads(raw)
        header.pop("__metadata__", None)
        self._meta: Dict[str, dict] = header
        self._data_len = lib.st_data_len(self._h)

    def keys(self) -> List[str]:
        return list(self._meta)

    def _spec(self, name: str):
        m = self._meta[name]
        dtype = DTYPES[m["dtype"]]()
        begin, end = m["data_offsets"]
        shape = tuple(m["shape"])
        expect = int(np.prod(shape, dtype=np.int64)) * dtype.itemsize
        if end - begin != expect or end > self._data_len:
            raise ValueError(f"corrupt tensor entry {name!r}")
        return dtype, shape, begin, end

    def read(self, name: str) -> np.ndarray:
        dtype, shape, begin, end = self._spec(name)
        out = np.empty(shape, dtype)
        if self._lib.st_copy(
            self._h, begin, end - begin, out.ctypes.data_as(ctypes.c_void_p)
        ):
            raise ValueError(f"out-of-range read for {name!r}")
        return out

    def read_many(self, names: Sequence[str]) -> Dict[str, np.ndarray]:
        """Allocate destinations, then drain all copies with the native
        thread pool (prefetching the spanned range first)."""
        specs = {n: self._spec(n) for n in names}
        if not specs:
            return {}
        lo = min(s[2] for s in specs.values())
        hi = max(s[3] for s in specs.values())
        self._lib.st_prefetch(self._h, lo, hi - lo)

        out = {n: np.empty(shape, dtype) for n, (dtype, shape, _, _) in specs.items()}
        n = len(names)
        offs = (ctypes.c_uint64 * n)(*(specs[k][2] for k in names))
        lens = (ctypes.c_uint64 * n)(*(specs[k][3] - specs[k][2] for k in names))
        dsts = (ctypes.c_void_p * n)(
            *(out[k].ctypes.data_as(ctypes.c_void_p).value for k in names)
        )
        if self._lib.st_copy_many(self._h, offs, lens, dsts, n, self.threads):
            raise ValueError("out-of-range read in batch")
        return out

    def close(self) -> None:
        if self._h:
            self._lib.st_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def __del__(self):  # best-effort; close() is the real API
        try:
            self.close()
        except Exception:
            pass
