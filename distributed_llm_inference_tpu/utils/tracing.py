"""Tracing / profiling (SURVEY §5.1 — absent in the reference).

The reference has no profiler hooks at all (its only observability is two
``print`` statements, ``/root/reference/distributed_llm_inference/utils/
model.py:61,82``). This module supplies the two tiers the TPU rebuild needs:

* **Device profiling** — :func:`profile_trace` / :func:`start_profile` wrap
  ``jax.profiler`` so a serving window dumps an XLA trace (TensorBoard /
  Perfetto-viewable) with the engine's step names attached via
  ``jax.profiler.TraceAnnotation``.
* **Host spans** — :class:`SpanRecorder` records named wall-clock spans
  (per-request prefill/decode/queue segments) and exports standard Chrome
  trace-event JSON (``chrome://tracing`` / Perfetto load it directly), so
  request-level timelines exist even off-TPU and without the profiler
  running.

Both tiers are cheap no-ops when idle: ``span`` costs two ``perf_counter``
calls when no profiler is active, and the recorder is bounded.
"""

from __future__ import annotations

import collections
import contextlib
import json
import threading
import time
from dataclasses import dataclass
from typing import Any, Dict, Iterator, List, Optional

import jax

__all__ = [
    "Span",
    "SpanRecorder",
    "span",
    "profile_trace",
    "start_profile",
    "stop_profile",
]


@dataclass
class Span:
    name: str
    start_s: float  # perf_counter timestamp
    duration_s: float
    args: Optional[Dict[str, Any]] = None


class SpanRecorder:
    """Bounded, thread-safe span log with Chrome trace-event export.

    The engine's host threads (SURVEY §5.2's concurrency caution) may record
    concurrently; the newest ``capacity`` spans are kept.
    """

    def __init__(self, capacity: int = 100_000):
        self.capacity = capacity
        self._lock = threading.Lock()
        # deque(maxlen): O(1) append-with-evict — record() sits on the
        # per-decode-step hot path.
        self._spans: collections.deque[Span] = collections.deque(maxlen=capacity)

    def record(self, s: Span) -> None:
        with self._lock:
            self._spans.append(s)

    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    def chrome_trace(self) -> Dict:
        """Chrome trace-event JSON object (load in Perfetto / about:tracing)."""
        events = []
        for s in self.spans():
            ev = {
                "name": s.name,
                "ph": "X",  # complete event
                "ts": s.start_s * 1e6,  # microseconds
                "dur": s.duration_s * 1e6,
                "pid": 0,
                "tid": 0,
            }
            if s.args:
                ev["args"] = s.args
            events.append(ev)
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def dump_chrome_trace(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)


@contextlib.contextmanager
def span(
    name: str,
    recorder: Optional[SpanRecorder] = None,
    **args: Any,
) -> Iterator[None]:
    """Time a host-side region; annotate any device work launched inside it.

    ``TraceAnnotation`` threads ``name`` into the XLA profiler timeline when a
    device trace is running (so engine steps show up named in the Perfetto
    dump); the wall-clock span goes to ``recorder`` if given.
    """
    t0 = time.perf_counter()
    try:
        with jax.profiler.TraceAnnotation(name):
            yield
    finally:
        # Record even when the region raises — the failing/slow step is
        # exactly the one worth having on the timeline.
        if recorder is not None:
            recorder.record(
                Span(name, t0, time.perf_counter() - t0, args or None)
            )


_profile_lock = threading.Lock()
_profile_dir: Optional[str] = None


def start_profile(log_dir: str) -> bool:
    """Begin a ``jax.profiler`` device trace into ``log_dir``. Returns True
    when this call started the trace; False when one was already running (the
    running trace is left untouched)."""
    global _profile_dir
    with _profile_lock:
        if _profile_dir is not None:
            return False
        jax.profiler.start_trace(log_dir)
        _profile_dir = log_dir
        return True


def stop_profile() -> Optional[str]:
    """Stop the running device trace; returns its log dir (None if idle)."""
    global _profile_dir
    with _profile_lock:
        if _profile_dir is None:
            return None
        out, _profile_dir = _profile_dir, None
        jax.profiler.stop_trace()
        return out


@contextlib.contextmanager
def profile_trace(log_dir: Optional[str]) -> Iterator[None]:
    """Profile the enclosed region into ``log_dir`` (no-op when None).

    Only stops a trace this context actually started — nesting inside an
    externally started ``start_profile`` window leaves that trace running.
    """
    if log_dir is None:
        yield
        return
    started = start_profile(log_dir)
    try:
        yield
    finally:
        if started:
            stop_profile()
