"""Tracing / profiling (SURVEY §5.1 — absent in the reference).

The reference has no profiler hooks at all (its only observability is two
``print`` statements, ``/root/reference/distributed_llm_inference/utils/
model.py:61,82``). This module supplies the two tiers the TPU rebuild needs:

* **Device profiling** — :func:`profile_trace` / :func:`start_profile` wrap
  ``jax.profiler`` so a serving window dumps an XLA trace (TensorBoard /
  Perfetto-viewable) with the engine's step names attached via
  ``jax.profiler.TraceAnnotation``.
* **Host spans** — :class:`SpanRecorder` records named wall-clock spans
  (per-request prefill/decode/queue segments) and exports standard Chrome
  trace-event JSON (``chrome://tracing`` / Perfetto load it directly), so
  request-level timelines exist even off-TPU and without the profiler
  running.
* **Distributed request tracing** — :class:`TraceContext` carries a
  (trace_id, span_id, parent) triple from the gateway across relay frame
  headers (the flat ``"trace"``/``"span"`` keys, so the distcheck DC500/
  DC501 closed world sees them); every node records child spans with
  **epoch** (``time.time``) timestamps into its own recorder, and
  :func:`stitch_chrome_trace` merges the per-node span sets the
  ``trace.pull`` collector gathers into ONE Chrome trace-event document —
  one ``pid`` lane per node, all on the shared epoch clock.
* **Flight recorder** — :class:`FlightRecorder` keeps a bounded ring of
  per-engine-tick records (tick kind, occupancy, admitted/chunked/parked
  rows, dispatch shape, host ms) for the ``/debug/ticks`` endpoint. It is
  ``None`` on engines without a :class:`~..config.TraceConfig`, so the
  decode tick pays exactly one attribute load + branch when disabled.

Both tiers are cheap no-ops when idle: ``span`` costs two ``perf_counter``
calls when no profiler is active, and the recorder is bounded.
"""

from __future__ import annotations

import collections
import contextlib
import json
import random
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional

import jax

__all__ = [
    "Span",
    "SpanRecorder",
    "TraceContext",
    "FlightRecorder",
    "span",
    "trace_span",
    "stitch_chrome_trace",
    "profile_trace",
    "start_profile",
    "stop_profile",
]


@dataclass
class Span:
    name: str
    start_s: float  # perf_counter timestamp (epoch for trace spans)
    duration_s: float
    args: Optional[Dict[str, Any]] = None
    # Distributed-trace attribution (None for plain local spans). Trace
    # spans use time.time() epoch start_s so spans from different
    # processes stitch onto one timeline.
    trace_id: Optional[str] = None
    span_id: Optional[str] = None
    parent_id: Optional[str] = None
    node: str = ""

    def to_dict(self) -> Dict[str, Any]:
        """JSON-safe form for the ``trace.spans`` wire reply."""
        d: Dict[str, Any] = {
            "name": self.name,
            "start_s": self.start_s,
            "duration_s": self.duration_s,
        }
        if self.args:
            d["args"] = self.args
        if self.trace_id is not None:
            d["trace_id"] = self.trace_id
            d["span_id"] = self.span_id
            d["parent_id"] = self.parent_id
        if self.node:
            d["node"] = self.node
        return d


@dataclass(frozen=True)
class TraceContext:
    """One request's position in a distributed trace: which trace it
    belongs to, the current span, and that span's parent. Immutable —
    :meth:`child` derives the context a sub-operation records under, and
    :meth:`to_header` / :meth:`from_header` move it across relay frame
    headers as the flat ``"trace"`` / ``"span"`` keys."""

    trace_id: str
    span_id: str = field(default="")
    parent_id: Optional[str] = None

    @staticmethod
    def mint(sample_rate: float = 1.0) -> Optional["TraceContext"]:
        """Gateway entry point: a fresh root context, or ``None`` when the
        request is not sampled (the whole tracing path then short-circuits
        on ``is None`` checks — sampling is the zero-cost switch)."""
        if sample_rate <= 0.0 or random.random() >= sample_rate:
            return None
        return TraceContext(
            trace_id=uuid.uuid4().hex[:16], span_id=uuid.uuid4().hex[:8]
        )

    def child(self) -> "TraceContext":
        return TraceContext(
            trace_id=self.trace_id,
            span_id=uuid.uuid4().hex[:8],
            parent_id=self.span_id,
        )

    def to_header(self) -> Dict[str, str]:
        """Flat frame-header keys (merge into an outgoing frame dict)."""
        return {"trace": self.trace_id, "span": self.span_id}

    @staticmethod
    def from_header(header: Dict[str, Any]) -> Optional["TraceContext"]:
        tid = header.get("trace")
        if not tid:
            return None
        return TraceContext(
            trace_id=str(tid), span_id=str(header.get("span") or "")
        )


class SpanRecorder:
    """Bounded, thread-safe span log with Chrome trace-event export.

    The engine's host threads (SURVEY §5.2's concurrency caution) may record
    concurrently; the newest ``capacity`` spans are kept. Eviction is NOT
    silent (the repo's "no silent caps" rule): :attr:`dropped` counts
    evicted spans and, when a ``metrics`` sink is attached, every eviction
    bumps the ``trace_spans_dropped`` counter.
    """

    def __init__(self, capacity: int = 100_000, metrics=None):
        self.capacity = capacity
        self.metrics = metrics
        self.dropped = 0
        self._lock = threading.Lock()
        # deque(maxlen): O(1) append-with-evict — record() sits on the
        # per-decode-step hot path.
        self._spans: collections.deque[Span] = collections.deque(maxlen=capacity)

    def record(self, s: Span) -> None:
        with self._lock:
            evicting = len(self._spans) >= self.capacity
            self._spans.append(s)
            if evicting:
                self.dropped += 1
        if evicting and self.metrics is not None:
            self.metrics.counter("trace_spans_dropped")

    def spans(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    def depth(self) -> int:
        with self._lock:
            return len(self._spans)

    def spans_for(self, trace_id: str) -> List[Span]:
        """Spans attributed to one distributed trace (collector op)."""
        with self._lock:
            return [s for s in self._spans if s.trace_id == trace_id]

    def clear(self) -> None:
        with self._lock:
            self._spans.clear()

    def chrome_trace(self) -> Dict:
        """Chrome trace-event JSON object (load in Perfetto / about:tracing)."""
        events = []
        for s in self.spans():
            ev = {
                "name": s.name,
                "ph": "X",  # complete event
                "ts": s.start_s * 1e6,  # microseconds
                "dur": s.duration_s * 1e6,
                "pid": 0,
                "tid": 0,
            }
            if s.args:
                ev["args"] = s.args
            events.append(ev)
        return {"traceEvents": events, "displayTimeUnit": "ms"}

    def dump_chrome_trace(self, path: str) -> None:
        with open(path, "w") as f:
            json.dump(self.chrome_trace(), f)


@contextlib.contextmanager
def trace_span(
    recorder: Optional[SpanRecorder],
    name: str,
    ctx: Optional[TraceContext],
    node: str = "",
    **args: Any,
) -> Iterator[Optional[TraceContext]]:
    """Record one distributed-trace child span (epoch clock).

    Yields the child :class:`TraceContext` the region runs under — put it
    on outgoing frame headers so remote spans parent correctly. A ``None``
    recorder or context makes the whole region a no-op yielding ``None``
    (the unsampled fast path)."""
    if recorder is None or ctx is None:
        yield None
        return
    child = ctx.child()
    t0 = time.time()
    try:
        yield child
    finally:
        # Record even when the region raises — a failed KV transfer or
        # admission is exactly the segment worth seeing on the timeline.
        recorder.record(Span(
            name, t0, time.time() - t0, args or None,
            trace_id=child.trace_id, span_id=child.span_id,
            parent_id=child.parent_id, node=node,
        ))


def stitch_chrome_trace(
    trace_id: str, node_spans: Dict[str, List[Dict[str, Any]]]
) -> Dict:
    """Assemble per-node span dicts (``Span.to_dict`` form, as gathered by
    the ``trace.pull`` collector) into ONE Chrome trace-event document:
    one ``pid`` lane per node, events on the shared epoch clock, sorted by
    start time. Nodes that failed to answer the pull are simply absent —
    a partial trace renders fine, it just has fewer lanes."""
    events = []
    for node, spans in sorted(node_spans.items()):
        for s in spans:
            if s.get("trace_id") not in (None, trace_id):
                continue
            ev = {
                "name": s.get("name", "?"),
                "ph": "X",
                "ts": float(s.get("start_s", 0.0)) * 1e6,
                "dur": float(s.get("duration_s", 0.0)) * 1e6,
                "pid": node,
                "tid": 0,
            }
            args = dict(s.get("args") or {})
            for k in ("span_id", "parent_id"):
                if s.get(k):
                    args[k] = s[k]
            if args:
                ev["args"] = args
            events.append(ev)
    events.sort(key=lambda e: e["ts"])
    return {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"trace_id": trace_id, "nodes": sorted(node_spans)},
    }


class FlightRecorder:
    """Bounded ring of per-engine-tick records — the "what was the engine
    doing at 14:32:07" tool. The engine appends one dict per ``step()``
    (tick kind, batch occupancy, admitted/chunked/parked rows, dispatch
    shape, host ms); ``/debug/ticks`` snapshots the ring. Thread-safe:
    ``step()`` appends from the drive thread while HTTP handlers read."""

    def __init__(self, capacity: int = 512):
        self.capacity = capacity
        self._lock = threading.Lock()
        self._ring: collections.deque = collections.deque(maxlen=capacity)
        self._tick = 0

    def record(self, **fields: Any) -> None:
        with self._lock:
            fields["tick"] = self._tick
            fields["t"] = time.time()
            self._tick += 1
            self._ring.append(fields)

    def snapshot(self, last: Optional[int] = None) -> List[Dict[str, Any]]:
        with self._lock:
            items = list(self._ring)
        if last is not None and last > 0:
            items = items[-last:]
        return items


@contextlib.contextmanager
def span(
    name: str,
    recorder: Optional[SpanRecorder] = None,
    **args: Any,
) -> Iterator[None]:
    """Time a host-side region; annotate any device work launched inside it.

    ``TraceAnnotation`` threads ``name`` into the XLA profiler timeline when a
    device trace is running (so engine steps show up named in the Perfetto
    dump); the wall-clock span goes to ``recorder`` if given.
    """
    t0 = time.perf_counter()
    try:
        with jax.profiler.TraceAnnotation(name):
            yield
    finally:
        # Record even when the region raises — the failing/slow step is
        # exactly the one worth having on the timeline.
        if recorder is not None:
            recorder.record(
                Span(name, t0, time.perf_counter() - t0, args or None)
            )


_profile_lock = threading.Lock()
_profile_dir: Optional[str] = None


def start_profile(log_dir: str) -> bool:
    """Begin a ``jax.profiler`` device trace into ``log_dir``. Returns True
    when this call started the trace; False when one was already running (the
    running trace is left untouched)."""
    global _profile_dir
    with _profile_lock:
        if _profile_dir is not None:
            return False
        jax.profiler.start_trace(log_dir)
        _profile_dir = log_dir
        return True


def stop_profile() -> Optional[str]:
    """Stop the running device trace; returns its log dir (None if idle)."""
    global _profile_dir
    with _profile_lock:
        if _profile_dir is None:
            return None
        out, _profile_dir = _profile_dir, None
        jax.profiler.stop_trace()
        return out


@contextlib.contextmanager
def profile_trace(log_dir: Optional[str]) -> Iterator[None]:
    """Profile the enclosed region into ``log_dir`` (no-op when None).

    Only stops a trace this context actually started — nesting inside an
    externally started ``start_profile`` window leaves that trace running.
    """
    if log_dir is None:
        yield
        return
    started = start_profile(log_dir)
    try:
        yield
    finally:
        if started:
            stop_profile()
