"""Per-block checkpoint streaming: load ONLY the layers this node serves.

TPU-native rebuild of the reference's weight loader
(``/root/reference/distributed_llm_inference/utils/model.py``):

* index discovery over the same four layouts — safetensors index, single
  ``model.safetensors``, torch ``.bin`` index, single ``.bin``
  (``utils/model.py:13,27-34``);
* ``weight_map`` prefix filtering so a node serving layers ``[i..j]`` opens
  only those layers' shard files (``utils/model.py:40-44``);
* tensors come out as numpy, get cast to ``bfloat16`` (the reference casts
  non-integer tensors to fp16 for CUDA, ``utils/model.py:66-68``; bf16 is the
  TPU-native choice), converted to this package's stacked-layer layout, and
  ``device_put`` with their ``NamedSharding`` — placement *is* the sharding
  story, replacing accelerate's ``set_module_tensor_to_device``
  (``utils/model.py:70``).

Paths are local snapshot directories (an HF hub cache dir works as-is); a
``resolve`` callable parameterizes filename→path lookup so a hub/remote
resolver can be plugged in where the reference used ``cached_file``
(``utils/model.py:29``).
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..config import ModelConfig
from ..models import llama

__all__ = [
    "find_index",
    "block_state_dict",
    "load_block_params",
    "load_model_params",
    "load_client_params",
    "load_config",
    "save_safetensors",
    "shard_put",
]

INDEX_FILE_PATTERNS = (
    "model.safetensors.index.json",
    "model.safetensors",
    "pytorch_model.bin.index.json",
    "pytorch_model.bin",
)

_NON_LAYER_KEYS = (
    "model.embed_tokens.weight",
    "model.norm.weight",
    "lm_head.weight",
)


def _default_resolve(model_dir: str) -> Callable[[str], Optional[str]]:
    def resolve(name: str) -> Optional[str]:
        path = os.path.join(model_dir, name)
        return path if os.path.exists(path) else None

    return resolve


def find_index(resolve: Callable[[str], Optional[str]]) -> str:
    """First existing checkpoint entry file, in the reference's pattern order
    (``utils/model.py:13,27-34``)."""
    for pattern in INDEX_FILE_PATTERNS:
        path = resolve(pattern)
        if path is not None:
            return path
    raise FileNotFoundError(
        f"no checkpoint index/weights found (tried {INDEX_FILE_PATTERNS})"
    )


def _read_tensors_safetensors(path: str, wanted: Callable[[str], bool]):
    # Native C++ reader first (mmap + multithreaded copies,
    # ``native/streader.cc`` — the data-loader tier the reference delegates
    # to the Rust safetensors extension); pure-Python wheel as fallback.
    from . import streader

    if streader.native_available():
        try:
            with streader.NativeSafetensors(path) as f:
                return f.read_many([k for k in f.keys() if wanted(k)])
        except Exception:
            pass  # unreadable via native path: fall through to the wheel
    from safetensors import safe_open

    out: Dict[str, np.ndarray] = {}
    with safe_open(path, framework="numpy") as f:
        for key in f.keys():
            if wanted(key):
                out[key] = f.get_tensor(key)
    return out


def _read_tensors_torch(path: str, wanted: Callable[[str], bool]):
    import torch

    state = torch.load(path, map_location="cpu", weights_only=True)
    return {
        k: v.to(torch.float32).numpy() if v.dtype == torch.bfloat16 else v.numpy()
        for k, v in state.items()
        if wanted(k)
    }


def _read_tensors(path: str, wanted: Callable[[str], bool]):
    if path.endswith(".safetensors"):
        return _read_tensors_safetensors(path, wanted)
    return _read_tensors_torch(path, wanted)


def block_state_dict(
    model_dir: str,
    layer_ids: Optional[Sequence[int]] = None,
    include_non_layer: bool = False,
    resolve: Optional[Callable[[str], Optional[str]]] = None,
) -> Dict[str, np.ndarray]:
    """HF-keyed numpy state dict for the given layers, reading only the shard
    files that contain them.

    ``layer_ids=None`` loads every layer. ``include_non_layer`` adds the
    embedding / final-norm / lm_head tensors (the client-side weights a
    mid-pipeline node never needs — the reference's loader is layers-only,
    ``utils/model.py:40``).
    """
    resolve = resolve or _default_resolve(model_dir)
    entry = find_index(resolve)

    prefixes = None
    if layer_ids is not None:
        prefixes = tuple(f"model.layers.{i}." for i in layer_ids)

    def wanted(key: str) -> bool:
        if prefixes is None:
            return include_non_layer or key.startswith("model.layers.")
        if key.startswith(prefixes):
            return True
        return include_non_layer and key in _NON_LAYER_KEYS

    if entry.endswith(".index.json"):
        with open(entry) as f:
            index = json.load(f)
        if "weight_map" not in index:
            raise ValueError(f"{entry} has no weight_map")
        shard_files = sorted({
            shard for key, shard in index["weight_map"].items() if wanted(key)
        })
        state: Dict[str, np.ndarray] = {}
        for shard in shard_files:
            path = resolve(shard)
            if path is None:
                raise FileNotFoundError(f"shard {shard} listed in index not found")
            state.update(_read_tensors(path, wanted))
        return state
    return _read_tensors(entry, wanted)


def load_block_params(
    model_dir: str,
    cfg: ModelConfig,
    layer_ids: Sequence[int],
    dtype=jnp.bfloat16,
    resolve: Optional[Callable[[str], Optional[str]]] = None,
    cache_dir: Optional[str] = None,
) -> Dict[str, Any]:
    """Stacked layer params for the block a node serves — the analog of
    ``load_block`` (``utils/model.py:75-90``), returning ``{"layers": …}``
    ready for :func:`models.llama.block_apply`.

    ``cache_dir`` enables the pre-converted on-disk cache (SURVEY §5.4): the
    first load writes the already-stacked/transposed arrays there; repeat
    bring-up of the same block then skips the HF-layout conversion and the
    unrelated-layer shard reads entirely.
    """
    def build():
        state = block_state_dict(model_dir, layer_ids, resolve=resolve)
        return llama.convert_hf_state_dict(cfg, state, layer_ids, dtype)

    return _cached_load(
        build, model_dir, cache_dir, layer_ids, dtype, resolve, tag="block"
    )


def load_model_params(
    model_dir: str,
    cfg: ModelConfig,
    dtype=jnp.bfloat16,
    resolve: Optional[Callable[[str], Optional[str]]] = None,
    cache_dir: Optional[str] = None,
) -> Dict[str, Any]:
    """Full-model params (embedding + all layers + head) for single-node /
    client use. ``cache_dir``: see :func:`load_block_params`."""
    def build():
        state = block_state_dict(
            model_dir, None, include_non_layer=True, resolve=resolve
        )
        return llama.convert_hf_state_dict(cfg, state, None, dtype)

    return _cached_load(
        build, model_dir, cache_dir, None, dtype, resolve, tag="model"
    )


# ---------------------------------------------------------------------------
# Pre-converted on-disk cache (SURVEY §5.4: "optional on-disk cache of
# pre-sharded arrays" — the reference re-parses HF shards on every bring-up)
# ---------------------------------------------------------------------------


def _flatten_params(params: Mapping[str, Any], prefix="") -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for k, v in params.items():
        key = f"{prefix}{k}"
        if isinstance(v, Mapping):
            out.update(_flatten_params(v, prefix=f"{key}."))
        else:
            out[key] = v
    return out


def _unflatten_params(flat: Mapping[str, Any]) -> Dict[str, Any]:
    out: Dict[str, Any] = {}
    for key, v in flat.items():
        node = out
        parts = key.split(".")
        for p in parts[:-1]:
            node = node.setdefault(p, {})
        node[parts[-1]] = v
    return out


def _cache_key(
    entry_path: str,
    layer_ids: Optional[Sequence[int]],
    dtype,
    tag: str,
    resolve: Callable[[str], Optional[str]],
) -> str:
    """Content key: identity (path + size + mtime) of the entry file, every
    shard it maps to, and config.json, × layer span × dtype × layout
    version — so replacing any shard (or the model config) invalidates the
    cache even when the index file itself is byte-identical."""
    def ident(path: Optional[str]):
        if path is None or not os.path.exists(path):
            return None
        st = os.stat(path)
        return [os.path.abspath(path), st.st_size, int(st.st_mtime_ns)]

    files = [ident(entry_path)]
    if entry_path.endswith(".index.json"):
        with open(entry_path) as f:
            shards = sorted(set(json.load(f).get("weight_map", {}).values()))
        files += [ident(resolve(s)) for s in shards]
    files.append(ident(resolve("config.json")))
    blob = json.dumps([
        "v1", tag, files,
        list(layer_ids) if layer_ids is not None else None,
        str(jnp.dtype(dtype)),
    ])
    import hashlib

    return hashlib.sha1(blob.encode()).hexdigest()[:20]


def _cached_load(build, model_dir, cache_dir, layer_ids, dtype, resolve, tag):
    if cache_dir is None:
        return build()
    # NOTE: numpy framework (via save_safetensors' forced host-contiguous
    # copies), NOT safetensors.flax — flax's writer serializes TPU-resident
    # buffers with their padded tile layout, silently corrupting
    # non-tile-aligned shapes (observed on v5e). bf16 round-trips as
    # ml_dtypes.bfloat16.
    from safetensors.numpy import load_file

    resolve = resolve or _default_resolve(model_dir)
    entry = find_index(resolve)
    key = _cache_key(entry, layer_ids, dtype, tag, resolve)
    path = os.path.join(cache_dir, f"{tag}-{key}.safetensors")
    if os.path.exists(path):
        try:
            flat = load_file(path)
        except Exception:
            pass  # corrupt/partial cache entry: rebuild below
        else:
            return _unflatten_params(
                {k: jnp.asarray(v) for k, v in flat.items()}
            )
    params = build()
    os.makedirs(cache_dir, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    save_safetensors(_flatten_params(params), tmp)
    os.replace(tmp, path)  # atomic: concurrent loaders see whole files only
    return params


def load_client_params(
    model_dir: str,
    cfg: ModelConfig,
    dtype=jnp.bfloat16,
    resolve: Optional[Callable[[str], Optional[str]]] = None,
) -> Dict[str, Any]:
    """Embedding + final-norm + lm_head ONLY — what ``DistributedClient``
    runs locally. Skips every decoder layer's shards, so a client fronting a
    70B chain loads megabytes, not the full model."""
    state = block_state_dict(model_dir, [], include_non_layer=True, resolve=resolve)
    return llama.convert_hf_non_layer(cfg, state, dtype)


def save_safetensors(state: Mapping[str, Any], path: str) -> None:
    """Write an HF-keyed state dict as a ``.safetensors`` file (the save path
    the reference lacks — its loader is read-only, ``utils/model.py``).

    Every tensor is forced C-contiguous first: safetensors' numpy writer
    serializes the array's underlying buffer without consulting strides, so a
    transposed view — or an array fetched from a TPU device, which may come
    back with a non-row-major layout — would be silently written with its
    bytes permuted.
    """
    from safetensors.numpy import save_file

    save_file(
        {k: np.ascontiguousarray(np.asarray(v)) for k, v in state.items()},
        path,
    )


def load_config(
    model_dir: str,
    validate: bool = True,
    resolve: Optional[Callable[[str], Optional[str]]] = None,
) -> ModelConfig:
    """``config.json`` → :class:`ModelConfig` (the ``AutoConfig`` role,
    ``utils/model.py:83``, without requiring transformers).

    ``validate`` checks the model family against the registry — an
    unsupported ``model_type`` fails HERE rather than silently running the
    llama program over a foreign architecture's weights. ``resolve`` lets a
    remote resolver (``utils/hub.py``) fetch the config like any other
    checkpoint file.
    """
    resolve = resolve or _default_resolve(model_dir)
    path = resolve("config.json")
    if path is None:
        raise FileNotFoundError(f"no config.json under {model_dir!r}")
    with open(path) as f:
        cfg = ModelConfig.from_hf_config(json.load(f))
    if validate:
        from ..models import registry

        registry.validate_config(cfg)
    return cfg


def shard_put(params: Dict[str, Any], mesh, use_pp: bool = False):
    """Place a loaded param pytree onto the mesh with its TP/PP shardings
    (replaces ``set_module_tensor_to_device`` + ``.to("cuda")``,
    ``utils/model.py:70,121``)."""
    from ..parallel import tp

    return tp.shard_pytree(params, mesh, tp.param_pspecs(params, use_pp))
