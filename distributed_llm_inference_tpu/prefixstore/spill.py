"""Bounded host-DRAM spill arena for evicted prefix pages.

When the page pool evicts a registered prefix page (refcount-aware LRU
under allocation pressure), the engine snapshots the page's tiles in
STORED form — int8 values + f32 scales for the quantized pool, raw
value-dtype bits otherwise — into this arena via the allocator's
``on_evict`` hook. A later admission whose prefix chain reaches the key
reloads the tiles through ``PagedKVCache.write_page`` (one host→device
copy) instead of recomputing the prefill. Contents round-trip verbatim,
so reloaded pages are bit-exact with the originals.

Plain LRU dict under the engine's scheduler lock (every put/take happens
inside allocator calls the engine already serializes); bounded by bytes,
evicting oldest-first until a new entry fits. ``take`` REMOVES the entry
— the page is device-resident (and registry-addressable) again, so a
second copy in the arena would only double-count the byte budget.
"""

from __future__ import annotations

import collections
from typing import Dict, Optional

import numpy as np

__all__ = ["HostSpillArena"]


class HostSpillArena:
    """LRU byte-bounded store of ``{chain key -> page tiles}``."""

    def __init__(self, max_bytes: int):
        self.max_bytes = int(max_bytes)
        self._entries: "collections.OrderedDict[bytes, Dict[str, np.ndarray]]" = (
            collections.OrderedDict()
        )
        self._sizes: Dict[bytes, int] = {}
        self.bytes_used = 0

    def __len__(self) -> int:
        return len(self._entries)

    def __contains__(self, key: bytes) -> bool:
        return key in self._entries

    @staticmethod
    def _nbytes(tiles: Dict[str, np.ndarray]) -> int:
        return sum(int(np.asarray(t).nbytes) for t in tiles.values())

    def put(self, key: bytes, tiles: Dict[str, np.ndarray]) -> bool:
        """Store one evicted page's tiles; evicts oldest entries until the
        new one fits. Returns ``False`` (arena unchanged) when the entry
        alone exceeds the whole budget or the key is already present."""
        size = self._nbytes(tiles)
        if size > self.max_bytes or key in self._entries:
            return False
        while self.bytes_used + size > self.max_bytes and self._entries:
            old, _ = self._entries.popitem(last=False)
            self.bytes_used -= self._sizes.pop(old)
        self._entries[key] = tiles
        self._sizes[key] = size
        self.bytes_used += size
        return True

    def take(self, key: bytes) -> Optional[Dict[str, np.ndarray]]:
        """Remove and return the tiles for ``key`` (``None`` on miss)."""
        tiles = self._entries.pop(key, None)
        if tiles is not None:
            self.bytes_used -= self._sizes.pop(key)
        return tiles

    def peek(self, key: bytes) -> Optional[Dict[str, np.ndarray]]:
        """Return the tiles for ``key`` WITHOUT removing them (``None``
        on miss) — for read-only exports like the fleet page-ship, where
        the page stays arena-resident and servable here. Refreshes the
        entry's LRU recency (a shipped page is evidently in demand)."""
        tiles = self._entries.get(key)
        if tiles is not None:
            self._entries.move_to_end(key)
        return tiles

    def keys(self):
        return list(self._entries)
