"""Prefix hash-chain helpers shared by the engine, directory, and gateways.

``PageAllocator.chain_keys`` (cache/paged.py) defines the canonical
content address of a prompt's page-sized chunks: a running sha1 over each
chunk's int64 token bytes. The directory and routing layers need the SAME
keys but must not import jax (the directory service is a pure control
plane) — :func:`chain_keys_hex` reproduces the byte stream with
``struct`` alone, and a contract test pins the two implementations
together. Keys travel as hex strings (JSON directory frames and
``kv_codec`` headers both already use hex chains).
"""

from __future__ import annotations

import hashlib
import struct
from typing import Iterable, List, Sequence, Set

__all__ = ["chain_keys_hex", "match_tokens"]


def chain_keys_hex(tokens: Sequence[int], page_size: int) -> List[str]:
    """Hex hash-chain keys of every FULL ``page_size`` chunk of ``tokens``
    — byte-identical to ``PageAllocator.chain_keys(...)[i].hex()``
    (``np.asarray(chunk, np.int64).tobytes()`` is native-order int64,
    which ``struct.pack("=%dq")`` reproduces without numpy)."""
    if page_size <= 0:
        raise ValueError(f"page_size must be positive, got {page_size}")
    keys, h = [], hashlib.sha1()
    for i in range(len(tokens) // page_size):
        chunk = tokens[i * page_size : (i + 1) * page_size]
        h.update(struct.pack("=%dq" % len(chunk), *(int(t) for t in chunk)))
        keys.append(h.hexdigest())
    return keys


def match_tokens(
    prompt: Sequence[int], page_size: int, heads: Iterable[str]
) -> int:
    """Longest prefix of ``prompt`` (in TOKENS, page-granular) whose chain
    keys are all present in ``heads`` (a node's advertised hex key set).
    Walks from the root and stops at the first miss — a deeper key without
    its ancestors is unreachable on the advertising node too."""
    head_set: Set[str] = set(heads)
    if not head_set:
        return 0
    matched = 0
    for key in chain_keys_hex(prompt, page_size):
        if key not in head_set:
            break
        matched += page_size
    return matched
