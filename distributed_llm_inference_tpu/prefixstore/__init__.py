"""Fleet-wide prefix/KV reuse (PagedAttention §4 copy-on-write sharing +
"Move the Query, Not the Cache" prefix-aware routing).

Three cooperating layers, built around the paged cache's hash-chain keys:

* **Engine-level CoW sharing** — lives in ``engine/engine.py`` +
  ``cache/paged.py``: sessions register their full prompt pages at
  admission, concurrent sessions attach to the same device pages
  read-only, and a session whose write offset lands inside a shared page
  splits it copy-on-write first.
* **Host-DRAM spill tier** — :class:`.spill.HostSpillArena`: evicted
  prefix pages spill to a bounded host arena in stored form and reload
  through the page pool on a future hit (host→device copy instead of
  recompute).
* **Prefix-aware routing** — :mod:`.index` hash-chain helpers shared by
  the block directory (``prefix.advertise`` / ``prefix.match`` ops) and
  the gateway backends, which route a request to the decode node holding
  the longest matching prefix.
"""

from .index import chain_keys_hex, match_tokens
from .spill import HostSpillArena

__all__ = ["HostSpillArena", "chain_keys_hex", "match_tokens"]
