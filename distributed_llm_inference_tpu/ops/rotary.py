"""Rotary position embeddings (RoPE).

The reference computes cos/sin once per block via an HF ``LlamaRotaryEmbedding``
module (``/root/reference/distributed_llm_inference/models/llama/model.py:23,55``
— note the bug there: it passes ``position_ids`` as the dtype-carrying ``x``
argument, SURVEY §2.9.4) and replays a CUDA-graphed ``apply_rotary_pos_emb``
for the decode path (``modules.py:28-34,73-76``). Here RoPE is a pair of pure
functions; XLA fuses them into the surrounding attention computation, so no
graph capture is needed.

Conventions match HF ``transformers`` (non-interleaved halves, ``rotate_half``).
Includes Llama-3 "llama3" frequency scaling.
"""

from __future__ import annotations

import math
from typing import NamedTuple, Optional, Tuple

import jax.numpy as jnp

from ..config import RopeScaling


class RopeAngles(NamedTuple):
    """Precomputed rotary state for one forward step.

    ``cos``/``sin`` are the tables for the *query* positions (``[B, S, D]``),
    computed once per block and shared by every layer (the reference computes
    them once per block too, ``models/llama/model.py:55``). ``inv_freq`` rides
    along for cache policies that must re-derive per-slot key angles (the sink
    cache's effective-position rotation).
    """

    inv_freq: jnp.ndarray
    cos: jnp.ndarray
    sin: jnp.ndarray


def rope_inv_freq(
    head_dim: int,
    theta: float,
    scaling: Optional[RopeScaling] = None,
) -> jnp.ndarray:
    """Per-frequency inverse wavelengths ``[head_dim // 2]`` (fp32)."""
    exponent = jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim
    inv_freq = 1.0 / (theta**exponent)
    if scaling is None or scaling.rope_type == "default":
        return inv_freq
    if scaling.rope_type == "linear":
        return inv_freq / scaling.factor
    if scaling.rope_type == "llama3":
        orig = scaling.original_max_position_embeddings
        low_wavelen = orig / scaling.low_freq_factor
        high_wavelen = orig / scaling.high_freq_factor
        wavelen = 2.0 * math.pi / inv_freq
        scaled = inv_freq / scaling.factor
        smooth = (orig / wavelen - scaling.low_freq_factor) / (
            scaling.high_freq_factor - scaling.low_freq_factor
        )
        smoothed = (1.0 - smooth) * scaled + smooth * inv_freq
        out = jnp.where(wavelen > low_wavelen, scaled, inv_freq)
        is_medium = (wavelen <= low_wavelen) & (wavelen >= high_wavelen)
        return jnp.where(is_medium, smoothed, out)
    raise ValueError(f"unsupported rope_type: {scaling.rope_type}")


def rope_cos_sin(
    positions: jnp.ndarray,
    inv_freq: jnp.ndarray,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """cos/sin tables for integer ``positions`` ``[...]`` → ``[..., head_dim]``.

    The tables duplicate the half-dim frequencies across both halves, matching
    HF's ``emb = cat(freqs, freqs)`` layout.
    """
    freqs = positions.astype(jnp.float32)[..., None] * inv_freq  # [..., hd/2]
    emb = jnp.concatenate([freqs, freqs], axis=-1)
    return jnp.cos(emb), jnp.sin(emb)


def rotate_half(x: jnp.ndarray) -> jnp.ndarray:
    half = x.shape[-1] // 2
    return jnp.concatenate([-x[..., half:], x[..., :half]], axis=-1)


def apply_rope(
    x: jnp.ndarray,
    cos: jnp.ndarray,
    sin: jnp.ndarray,
) -> jnp.ndarray:
    """Rotate ``x[..., seq, heads, head_dim]`` by ``cos/sin[..., seq, head_dim]``.

    Computed in fp32 and cast back — rotary precision matters for long-context
    position fidelity.
    """
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    c = cos[..., :, None, :]
    s = sin[..., :, None, :]
    return (xf * c + rotate_half(xf) * s).astype(dtype)
