"""Mixture-of-experts MLP (Mixtral-style) with expert-parallel sharding.

The reference has no MoE layers — it only reuses hivemind's *moe.server*
machinery for serving scaffolding (SURVEY §2.3;
``/root/reference/distributed_llm_inference/server/backend.py:5``). MoE here is
a capability extension required for the Mixtral model family.

Routing follows Mixtral: softmax over ALL expert logits in fp32, top-k
selection, renormalize the selected probabilities.

Two compute strategies, both all-static shapes:

* **dense-combine** (decode, S == 1) — every expert processes every token and
  a ``[B, S, E]`` combine matrix (zero off the top-k) weights the outputs.
  Decode is bound by READING every expert's weights regardless, so the
  overcompute is free, and with experts sharded over ``ep`` the combine
  contraction becomes a ``psum`` XLA inserts automatically.
* **sorted dispatch** (prefill) — (token, expert) pairs argsort to their
  experts; each expert computes only its capacity-bounded slice
  (``moe_mlp_dispatch``), cutting MLP FLOPs by E/(k·capacity_factor). The
  whole path is gathers (a scatter would serialize on TPU).
"""

from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..config import ModelConfig
from . import quant

__all__ = ["moe_mlp", "router_weights"]


def router_weights(
    cfg: ModelConfig, x: jnp.ndarray, router: jnp.ndarray
) -> jnp.ndarray:
    """Mixtral routing: fp32 softmax over all experts → top-k → renormalize.

    ``x``: ``[B, S, H]``; ``router``: ``[H, E]``. Returns the dense combine
    matrix ``[B, S, E]`` (sums to 1 over the selected experts, 0 elsewhere).
    """
    logits = x.astype(jnp.float32) @ router.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    top_p, top_i = jax.lax.top_k(probs, cfg.num_experts_per_tok)
    top_p = top_p / jnp.sum(top_p, axis=-1, keepdims=True)
    one_hot = jax.nn.one_hot(top_i, cfg.num_experts, dtype=jnp.float32)
    return jnp.einsum("bsk,bske->bse", top_p, one_hot)


def moe_mlp(
    cfg: ModelConfig,
    p,
    x: jnp.ndarray,
    valid=None,
) -> jnp.ndarray:
    """SwiGLU expert MLPs + weighted combine.

    ``p["router"]``: ``[H, E]``; ``p["we_g"]``/``p["we_u"]``: ``[E, H, F]``;
    ``p["we_d"]``: ``[E, F, H]`` (E shardable over ``ep``, F over ``tp``).

    Dense-combine is the default everywhere: exact, shape-static, and every
    token's output independent of co-batched rows (decode and verify steps
    are bound by reading every expert's weights regardless, so the
    overcompute is free there). Setting ``ModelConfig.moe_capacity_factor``
    OPTS IN to sorted dispatch for prefill-scale steps (S >= 16):
    E/(k·factor)× less MLP compute at the cost of capacity drops — which
    also make results depend on prefill chunk boundaries, hence opt-in.
    ``valid`` (``[B, S]`` bool) marks real tokens; bucket-padding positions
    must not consume expert capacity in the dispatched path.
    """
    if cfg.moe_capacity_factor is not None and x.shape[1] >= 16:
        return moe_mlp_dispatch(cfg, p, x, cfg.moe_capacity_factor, valid)
    combine = router_weights(cfg, x, p["router"]).astype(x.dtype)
    t = quant.einsum("bsh,ehf->bsef", x, p["we_g"])
    u = quant.einsum("bsh,ehf->bsef", x, p["we_u"])
    y = quant.einsum("bsef,efh->bseh", jax.nn.silu(t) * u, p["we_d"])
    return jnp.einsum("bse,bseh->bsh", combine, y)


def _expert_matmul(spec: str, x: jnp.ndarray, w) -> jnp.ndarray:
    """Per-expert einsum that handles quantized expert stacks. The generic
    ``quant.einsum`` needs the weight's non-contracted axes LAST in the
    output; here the expert axis leads (``ecf``/``ech``), so the
    per-(expert, out-channel) scale ``[E, out]`` broadcasts at axis -1 with
    the capacity axis in between."""
    if isinstance(w, quant.QuantizedTensor):
        y = jnp.einsum(spec, x, w.q.astype(x.dtype))
        return y * w.scale[:, None, :].astype(x.dtype)
    return jnp.einsum(spec, x, w)


def moe_mlp_dispatch(
    cfg: ModelConfig,
    p,
    x: jnp.ndarray,
    capacity_factor: float = 2.0,
    valid=None,
    capacity=None,
) -> jnp.ndarray:
    """Sorted (capacity-based) expert dispatch — the prefill MoE path.

    Gather-only by construction (a scatter lowers to a serial row loop on
    TPU and trips GSPMD — see cache/dense.py): (token, expert) pairs are
    argsorted by expert, each expert's slots gather their tokens, the
    per-expert MLP runs on ``[E, C, H]``, and undoing the sort turns the
    combine into a dense ``[N, k]`` weighted sum. ``C = N·k/E ·
    capacity_factor`` rounds to a static shape; pairs past an expert's
    capacity are dropped (their routing weight contributes nothing) — rare
    at factor 2 under Mixtral's near-uniform routing, and bounded: a dropped
    pair loses at most its renormalized probability share of one token.

    ``valid`` (``[B, S]`` bool): invalid (bucket-padding) tokens route to a
    sentinel expert id ``E`` — the stable sort parks them AFTER every real
    expert's group, so padding can never evict a real token from capacity.

    NOTE: under an ``ep``-sharded mesh the expert-indexed gathers here have
    not been perf-verified (GSPMD may all-gather the expert stacks); the
    dense-combine path is the ep-proven one. Dispatch is opt-in
    (``ModelConfig.moe_capacity_factor``) partly for this reason.
    """
    b, s, h = x.shape
    e, k = cfg.num_experts, cfg.num_experts_per_tok
    n = b * s
    xf = x.reshape(n, h)

    logits = xf.astype(jnp.float32) @ p["router"].astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    top_p, top_i = jax.lax.top_k(probs, k)
    top_p = top_p / jnp.sum(top_p, axis=-1, keepdims=True)

    pair_e = top_i.reshape(-1)                                  # [N*k]
    pair_t = jnp.repeat(jnp.arange(n, dtype=jnp.int32), k)      # [N*k]
    if valid is not None:
        vf = valid.reshape(-1)
        pair_e = jnp.where(jnp.repeat(vf, k), pair_e, e)
        top_p = top_p * vf[:, None].astype(top_p.dtype)

    order = jnp.argsort(pair_e, stable=True)
    sorted_e = pair_e[order]
    sorted_t = pair_t[order]
    # e+1 bounds so sentinel (padding) pairs sit past EVERY group_end.
    bounds = jnp.searchsorted(sorted_e, jnp.arange(e + 1), side="left")
    group_start, group_end = bounds[:e], bounds[1:]
    pos_in_group = jnp.arange(n * k, dtype=jnp.int32) - group_start[
        jnp.clip(sorted_e, 0, e - 1)
    ]

    c = capacity if capacity is not None else max(
        1, min(n, math.ceil((n * k) / e * capacity_factor))
    )
    # Slot (expert, c) holds the token at sorted position start_e + c.
    slot_pos = group_start[:, None] + jnp.arange(c, dtype=jnp.int32)[None, :]
    slot_valid = slot_pos < group_end[:, None]
    slot_tok = sorted_t[jnp.clip(slot_pos, 0, n * k - 1)]       # [E, C]

    gathered = xf[slot_tok] * slot_valid[..., None].astype(x.dtype)
    t = _expert_matmul("ech,ehf->ecf", gathered, p["we_g"])
    u = _expert_matmul("ech,ehf->ecf", gathered, p["we_u"])
    y = _expert_matmul("ecf,efh->ech", jax.nn.silu(t) * u, p["we_d"])

    # Back to pair order (pure gathers: undo the sort), then a dense [N, k]
    # weighted combine.
    kept = pos_in_group < c
    pair_out_sorted = y[
        sorted_e, jnp.clip(pos_in_group, 0, c - 1)
    ] * kept[:, None].astype(x.dtype)                           # [N*k, H]
    inv = jnp.argsort(order)
    pair_out = pair_out_sorted[inv].reshape(n, k, h)
    out = jnp.einsum(
        "nk,nkh->nh", top_p.astype(jnp.float32),
        pair_out.astype(jnp.float32),
    )
    return out.reshape(b, s, h).astype(x.dtype)
