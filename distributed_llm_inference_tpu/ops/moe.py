"""Mixture-of-experts MLP (Mixtral-style) with expert-parallel sharding.

The reference has no MoE layers — it only reuses hivemind's *moe.server*
machinery for serving scaffolding (SURVEY §2.3;
``/root/reference/distributed_llm_inference/server/backend.py:5``). MoE here is
a capability extension required for the Mixtral model family.

Routing follows Mixtral: softmax over ALL expert logits in fp32, top-k
selection, renormalize the selected probabilities.

Compute strategy: **dense-combine** — every expert processes every token and a
``[B, S, E]`` combine matrix (zero off the top-k) weights the outputs. On TPU
this keeps all shapes static and every FLOP on the MXU; with the experts axis
sharded over the ``ep`` mesh axis, each device computes only its local experts
and the combine contraction becomes a ``psum`` over ``ep`` that XLA inserts
automatically. For E/k = 4 (Mixtral 8x7B, k=2) the overcompute is bounded and
decode (S=1) stays bandwidth-bound; a sorted-dispatch (ragged) Pallas kernel is
the prefill optimization path.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from ..config import ModelConfig
from . import quant

__all__ = ["moe_mlp", "router_weights"]


def router_weights(
    cfg: ModelConfig, x: jnp.ndarray, router: jnp.ndarray
) -> jnp.ndarray:
    """Mixtral routing: fp32 softmax over all experts → top-k → renormalize.

    ``x``: ``[B, S, H]``; ``router``: ``[H, E]``. Returns the dense combine
    matrix ``[B, S, E]`` (sums to 1 over the selected experts, 0 elsewhere).
    """
    logits = x.astype(jnp.float32) @ router.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1)
    top_p, top_i = jax.lax.top_k(probs, cfg.num_experts_per_tok)
    top_p = top_p / jnp.sum(top_p, axis=-1, keepdims=True)
    one_hot = jax.nn.one_hot(top_i, cfg.num_experts, dtype=jnp.float32)
    return jnp.einsum("bsk,bske->bse", top_p, one_hot)


def moe_mlp(cfg: ModelConfig, p, x: jnp.ndarray) -> jnp.ndarray:
    """SwiGLU expert MLPs + weighted combine.

    ``p["router"]``: ``[H, E]``; ``p["we_g"]``/``p["we_u"]``: ``[E, H, F]``;
    ``p["we_d"]``: ``[E, F, H]`` (E shardable over ``ep``, F over ``tp``).
    """
    combine = router_weights(cfg, x, p["router"]).astype(x.dtype)
    t = quant.einsum("bsh,ehf->bsef", x, p["we_g"])
    u = quant.einsum("bsh,ehf->bsef", x, p["we_u"])
    y = quant.einsum("bsef,efh->bseh", jax.nn.silu(t) * u, p["we_d"])
    return jnp.einsum("bse,bseh->bsh", combine, y)
