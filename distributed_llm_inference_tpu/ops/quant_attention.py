"""Pallas decode-attention kernel over the int8-quantized dense KV cache.

Why a kernel: the XLA path must feed the attention matmuls bf16 operands, so
the int8 cache is dequantized first — and depending on layout/formulation XLA
can materialize a full bf16 copy of K and V through HBM every step (measured
~13 GB extra per step at batch 80, Llama-7B shapes — more than the entire
ideal step traffic). Here the int8 buffers stream through VMEM exactly once:
scores are computed on the int8 values and the per-(token, head) scales are
applied to the scores (``q·(k·s_t) = s_t·(q·k)``); the v scales fold into the
probs before PV.

Structure follows ``paged_attention.py`` (grid over (batch, time-tiles),
online-softmax scratch carried across the inner axis, VPU multiply-reduce for
MHA / batched ``dot_general`` for GQA); the operand here is the contiguous
HEAD-major ``[B, Hkv, T, D]`` dense buffer instead of a page pool — the same
head-major tile shape the paged pool uses — with time-tiles past the row's
live length clamped to tile 0 so short rows in a long batch fetch one hot
tile instead of the padded span.

This is the decode half of the int8-KV serving mode (the reference's only
deployment optimization is int8 *weights*,
``/root/reference/distributed_llm_inference/utils/model.py:93-123``; int8 KV
is its TPU-native counterpart for the bandwidth-bound decode path). Runs in
interpret mode off-TPU so the CPU test mesh exercises it.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .attention import _NEG_INF

__all__ = [
    "quantized_decode_attention",
    "quantized_fused_decode_attention",
    "fused_tail_flush",
    "sink_fused_decode_attention",
    "sink_tail_flush",
]


def _qdense_kernel(
    len_ref,    # SMEM [B] int32 (scalar prefetch)
    qpos_ref,   # SMEM [B] int32 (query positions, for the sliding window)
    q_ref,      # [1, Hkv, G, D]
    k_ref,      # [1, Hkv, BT, D] int8
    ks_ref,     # [1, Hkv, BT] f32
    v_ref,      # [1, Hkv, BT, D] int8
    vs_ref,     # [1, Hkv, BT] f32
    out_ref,    # [1, Hkv, G, D]
    acc_ref,    # VMEM [Hkv*G, D] f32
    m_ref,      # VMEM [Hkv*G, 128] f32
    l_ref,      # VMEM [Hkv*G, 128] f32
    *,
    scale: float,
    block_t: int,
    num_blocks: int,
    sliding_window: Optional[int],
    hkv: int,
    g: int,
):
    b = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    kv_len = len_ref[b]
    pos = j * block_t + jax.lax.broadcasted_iota(jnp.int32, (1, block_t), 1)
    valid = pos < kv_len  # decode: causality ≡ slot validity
    if sliding_window is not None:
        valid &= pos > qpos_ref[b] - sliding_window

    q = q_ref[0]                       # [Hkv, G, D]
    k = k_ref[0]                       # [Hkv, BT, D] int8
    ks = ks_ref[0]                     # [Hkv, BT] f32

    if g == 1:
        # MHA: VPU multiply-reduce (1-row MXU matmuls waste the array).
        qv = q[:, 0, :][:, None, :].astype(jnp.float32)      # [Hkv, 1, D]
        s = jnp.sum(k.astype(jnp.float32) * qv, axis=-1)     # [Hkv, BT]
        s = s * ks
    else:
        s = jax.lax.dot_general(
            q.astype(jnp.float32), k.astype(jnp.float32),
            (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )                                                    # [Hkv, G, BT]
        s = s * ks[:, None, :]
        s = s.reshape(hkv * g, block_t)
    s = s * scale
    s = jnp.where(valid, s, _NEG_INF)

    m_prev = m_ref[:, :1]
    l_prev = l_ref[:, :1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.where(valid, jnp.exp(s - m_new), 0.0)            # [Hkv*G, BT]

    l_ref[:] = jnp.broadcast_to(
        alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True), l_ref.shape
    )
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)

    v = v_ref[0]                       # [Hkv, BT, D] int8
    vs = vs_ref[0]                     # [Hkv, BT] f32
    if g == 1:
        pw = p.reshape(hkv, block_t) * vs                    # [Hkv, BT]
        pv = jnp.sum(pw[:, :, None] * v.astype(jnp.float32), axis=1)
        acc_ref[:] = acc_ref[:] * alpha + pv                 # [Hkv, D]
    else:
        pw = p.reshape(hkv, g, block_t) * vs[:, None, :]
        pv = jax.lax.dot_general(
            pw, v.astype(jnp.float32), (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        acc_ref[:] = acc_ref[:] * alpha + pv.reshape(hkv * g, -1)

    @pl.when(j == num_blocks - 1)
    def _finalize():
        l = l_ref[:, :1]
        out = acc_ref[:] / jnp.maximum(l, 1e-20)
        out_ref[0] = out.reshape(hkv, g, -1).astype(out_ref.dtype)


def quantized_decode_attention(
    q: jnp.ndarray,
    k_q: jnp.ndarray,
    ks: jnp.ndarray,
    v_q: jnp.ndarray,
    vs: jnp.ndarray,
    kv_lengths: jnp.ndarray,
    scale: Optional[float] = None,
    sliding_window: Optional[int] = None,
    block_t: int = 128,
    interpret: Optional[bool] = None,
    q_positions: Optional[jnp.ndarray] = None,
) -> jnp.ndarray:
    """Decode attention straight over the int8 head-major dense cache.

    ``q``: ``[B, 1, Hq, D]`` (already rotated); ``k_q``/``v_q``: int8
    ``[B, Hkv, T, D]`` (keys stored rotated); ``ks``/``vs``: f32
    ``[B, Hkv, T]`` per-(token, head) scales; ``kv_lengths``: ``[B]`` live kv
    count per row *including* tokens written this step. Returns
    ``[B, 1, Hq, D]`` in q's dtype.

    ``q_positions`` (``[B]``, default ``kv_lengths - 1``): the absolute
    position of each row's query, which anchors the sliding window.
    """
    b, s, hq, d = q.shape
    if s != 1:
        raise ValueError(f"decode-only kernel (S=1), got S={s}")
    hkv, t = k_q.shape[1], k_q.shape[2]
    g = hq // hkv
    if scale is None:
        scale = d**-0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if q_positions is None:
        q_positions = kv_lengths - 1
    bt = min(block_t, t)
    num_blocks = -(-t // bt)
    if t % bt:
        pad = num_blocks * bt - t
        k_q = jnp.pad(k_q, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v_q = jnp.pad(v_q, ((0, 0), (0, 0), (0, pad), (0, 0)))
        ks = jnp.pad(ks, ((0, 0), (0, 0), (0, pad)))
        vs = jnp.pad(vs, ((0, 0), (0, 0), (0, pad)))

    qr = q.reshape(b, hkv, g, d)

    def _tile_index(bi, ji, lens, qpos):
        # Tiles past the row's live span clamp to tile 0 (one hot fetch).
        live = ji * bt < lens[bi]
        return (bi, 0, jnp.where(live, ji, 0), 0)

    def _tile_index3(bi, ji, lens, qpos):
        live = ji * bt < lens[bi]
        return (bi, 0, jnp.where(live, ji, 0))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, num_blocks),
        in_specs=[
            pl.BlockSpec(
                (1, hkv, g, d), lambda bi, ji, lens, qpos: (bi, 0, 0, 0)
            ),
            pl.BlockSpec((1, hkv, bt, d), _tile_index),
            pl.BlockSpec((1, hkv, bt), _tile_index3),
            pl.BlockSpec((1, hkv, bt, d), _tile_index),
            pl.BlockSpec((1, hkv, bt), _tile_index3),
        ],
        out_specs=pl.BlockSpec(
            (1, hkv, g, d), lambda bi, ji, lens, qpos: (bi, 0, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((hkv * g, d), jnp.float32),
            pltpu.VMEM((hkv * g, 128), jnp.float32),
            pltpu.VMEM((hkv * g, 128), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _qdense_kernel,
        scale=scale,
        block_t=bt,
        num_blocks=num_blocks,
        sliding_window=sliding_window,
        hkv=hkv,
        g=g,
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, hkv, g, d), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(kv_lengths.astype(jnp.int32), q_positions.astype(jnp.int32),
      qr, k_q, ks, v_q, vs)
    return out.reshape(b, 1, hq, d)


def quantized_fused_decode_attention(
    q: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
    big_k: jnp.ndarray,
    big_ks: jnp.ndarray,
    big_v: jnp.ndarray,
    big_vs: jnp.ndarray,
    tail_k: jnp.ndarray,
    tail_ks: jnp.ndarray,
    tail_v: jnp.ndarray,
    tail_vs: jnp.ndarray,
    layer_idx: jnp.ndarray,
    step_idx: jnp.ndarray,
    base_len: jnp.ndarray,
    tail_valid_len: jnp.ndarray,
    q_positions: jnp.ndarray,
    scale: Optional[float] = None,
    sliding_window: Optional[int] = None,
    # 256 swallows short-context buffers in ONE time block (the 2-block
    # split at T=160 measured ~8% slower: the second, mostly-clamped tile
    # still pays a full grid step); longer buffers tile at 256 and keep the
    # short-row clamp optimization.
    block_t: int = 256,
    block_b: int = 8,
    interpret: Optional[bool] = None,
):
    """ONE kernel for a whole fused-decode attention step: quantizes the
    step's fresh K/V, writes them into the write-behind tail IN PLACE
    (io-aliased whole-stack tail operands), and runs the joint softmax over
    the read-only big segment plus the updated tail — the tail is simply the
    final online-softmax tile.

    Why: with the tail handled in XLA around a big-segment-only kernel, the
    quantize + four dynamic-update-slices + tail einsums + stats merge cost
    ~8 ms/step at batch 112 (Llama-7B shapes) — more than the big segment's
    entire byte cost — because the custom call's layout constraints de-fuse
    and re-layout every tail op. In-kernel, the tail round-trips VMEM once
    per (layer, step) (~0.5 MB/row-block) and XLA never touches the int8
    planes at all.

    Shapes: ``q`` ``[B, 1, Hq, D]`` (rotated); ``k_new``/``v_new``
    ``[B, 1, Hkv, D]`` (k rotated); big stacks ``[L, B, Hkv, T, D]`` (+
    ``[L, B, Hkv, T]`` scales); tail stacks ``[L, B, Hkv, KT, D]`` (+
    scales). Scalars: ``layer_idx``/``step_idx`` traced ints; ``base_len``
    ``[B]`` live big-segment length; ``tail_valid_len`` ``[B]`` =
    ``tail_len + num_new`` (valid tail slots AFTER this write — a finished
    row keeps its shorter span, so its slot-``step_idx`` garbage write is
    never read); ``q_positions`` ``[B]`` = ``base_len + tail_len`` anchors
    the sliding window.

    Returns ``(out [B, 1, Hq, D], tail_k', tail_ks', tail_v', tail_vs')``
    with the tail outputs aliased to the inputs (callers must treat the
    inputs as consumed).
    """
    b, s, hq, d = q.shape
    if s != 1:
        raise ValueError(f"decode-only kernel (S=1), got S={s}")
    num_l, _, hkv, t, _ = big_k.shape
    kt = tail_k.shape[3]
    g = hq // hkv
    if scale is None:
        scale = d**-0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if t % 32 and not interpret:
        # The io-aliased whole-stack operands cannot pad on TPU, so the
        # time axis must sit on an int8 sublane boundary; callers
        # (tail_attend) gate on max_len % 32 == 0 and keep the XLA
        # segments path for odd buffers. 32-aligned t keeps the r3 tiling
        # UNCHANGED — min(block_t, t) blocks with a partial (32-aligned)
        # last tile, which Mosaic handles and which the perf record is
        # built on. (An r4 attempt to force bt to a divisor of t regressed
        # 1k-ctx decode 4.6x — bt=96 tiles — and broke kernels whose
        # forced bt fell below the 128-lane scale-plane block at other
        # buffer lengths.)
        raise ValueError(
            f"big-buffer length {t} must be a multiple of 32 on TPU"
        )
    bt = min(block_t, t)
    num_blocks = -(-t // bt)
    # The io-aliased tail stacks cannot be batch-padded, so the row block
    # must DIVIDE the batch: largest divisor <= block_b (worst case 1).
    nb = next(n for n in range(min(block_b, b), 0, -1) if b % n == 0)
    num_row_blocks = b // nb

    qr = q.reshape(b, hkv, g, d)
    knr = jnp.moveaxis(k_new, 1, 2)  # [B, Hkv, 1, D]
    vnr = jnp.moveaxis(v_new, 1, 2)
    lref = jnp.asarray(layer_idx, jnp.int32).reshape(1)
    sref = jnp.asarray(step_idx, jnp.int32).reshape(1)

    def _row_live(bi, ji, lens):
        live = ji * bt < lens[bi * nb]
        for r in range(1, nb):
            live |= ji * bt < lens[bi * nb + r]
        return live

    def _big_index(bi, ji, lidx, step, lens, vlen, qpos):
        return (lidx[0], bi, 0,
                jnp.where(_row_live(bi, ji, lens), ji, 0), 0)

    def _big_index3(bi, ji, lidx, step, lens, vlen, qpos):
        return (lidx[0], bi, 0, jnp.where(_row_live(bi, ji, lens), ji, 0))

    def _tail_index(bi, ji, lidx, step, lens, vlen, qpos):
        return (lidx[0], bi, 0, 0, 0)

    def _tail_index3(bi, ji, lidx, step, lens, vlen, qpos):
        return (lidx[0], bi, 0, 0)

    def _row_index(bi, ji, lidx, step, lens, vlen, qpos):
        return (bi, 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=5,
        grid=(num_row_blocks, num_blocks),
        in_specs=[
            pl.BlockSpec((nb, hkv, g, d), _row_index),
            pl.BlockSpec((nb, hkv, 1, d), _row_index),
            pl.BlockSpec((nb, hkv, 1, d), _row_index),
            pl.BlockSpec((1, nb, hkv, bt, d), _big_index),
            pl.BlockSpec((1, nb, hkv, bt), _big_index3),
            pl.BlockSpec((1, nb, hkv, bt, d), _big_index),
            pl.BlockSpec((1, nb, hkv, bt), _big_index3),
            pl.BlockSpec((1, nb, hkv, kt, d), _tail_index),
            pl.BlockSpec((1, nb, hkv, kt), _tail_index3),
            pl.BlockSpec((1, nb, hkv, kt, d), _tail_index),
            pl.BlockSpec((1, nb, hkv, kt), _tail_index3),
        ],
        out_specs=(
            pl.BlockSpec((nb, hkv, g, d), _row_index),
            pl.BlockSpec((1, nb, hkv, kt, d), _tail_index),
            pl.BlockSpec((1, nb, hkv, kt), _tail_index3),
            pl.BlockSpec((1, nb, hkv, kt, d), _tail_index),
            pl.BlockSpec((1, nb, hkv, kt), _tail_index3),
        ),
        scratch_shapes=[
            pltpu.VMEM((nb, hkv * g, d), jnp.float32),
            pltpu.VMEM((nb, hkv * g, 128), jnp.float32),
            pltpu.VMEM((nb, hkv * g, 128), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _qfused_kernel,
        scale=scale,
        block_t=bt,
        num_blocks=num_blocks,
        sliding_window=sliding_window,
        hkv=hkv,
        g=g,
        nb=nb,
        kt=kt,
    )
    out, tk, tks, tv, tvs = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((b, hkv, g, d), q.dtype),
            jax.ShapeDtypeStruct(tail_k.shape, tail_k.dtype),
            jax.ShapeDtypeStruct(tail_ks.shape, tail_ks.dtype),
            jax.ShapeDtypeStruct(tail_v.shape, tail_v.dtype),
            jax.ShapeDtypeStruct(tail_vs.shape, tail_vs.dtype),
        ),
        grid_spec=grid_spec,
        interpret=interpret,
        # Tail stacks update in place; indices count every flattened input
        # including the 5 scalar-prefetch operands.
        input_output_aliases={12: 1, 13: 2, 14: 3, 15: 4},
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
            vmem_limit_bytes=100 * 1024 * 1024,
        ),
    )(lref, sref, base_len.astype(jnp.int32),
      tail_valid_len.astype(jnp.int32), q_positions.astype(jnp.int32),
      qr, knr, vnr, big_k, big_ks, big_v, big_vs,
      tail_k, tail_ks, tail_v, tail_vs)
    return out.reshape(b, 1, hq, d), tk, tks, tv, tvs


def _qfused_kernel(
    lidx_ref,   # SMEM [1] int32 (layer; consumed by index maps)
    step_ref,   # SMEM [1] int32 (tail write slot)
    len_ref,    # SMEM [B] int32 (big live length)
    vlen_ref,   # SMEM [B] int32 (valid tail slots incl. this write)
    qpos_ref,   # SMEM [B] int32 (query positions)
    q_ref,      # [NB, Hkv, G, D]
    kn_ref,     # [NB, Hkv, 1, D] (rotated, unquantized)
    vn_ref,     # [NB, Hkv, 1, D]
    k_ref,      # [1, NB, Hkv, BT, D] int8
    ks_ref,     # [1, NB, Hkv, BT] f32
    v_ref,      # [1, NB, Hkv, BT, D] int8
    vs_ref,     # [1, NB, Hkv, BT] f32
    tk_ref,     # [1, NB, Hkv, KT, D] int8 (in)
    tks_ref,    # [1, NB, Hkv, KT] f32 (in)
    tv_ref,     # [1, NB, Hkv, KT, D] int8 (in)
    tvs_ref,    # [1, NB, Hkv, KT] f32 (in)
    out_ref,    # [NB, Hkv, G, D]
    tk_out,     # aliased tail outputs
    tks_out,
    tv_out,
    tvs_out,
    acc_ref,    # VMEM [NB, Hkv*G, D] f32
    m_ref,      # VMEM [NB, Hkv*G, 128] f32
    l_ref,      # VMEM [NB, Hkv*G, 128] f32
    *,
    scale: float,
    block_t: int,
    num_blocks: int,
    sliding_window: Optional[int],
    hkv: int,
    g: int,
    nb: int,
    kt: int,
):
    bi = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q = q_ref[:]                               # [NB, Hkv, G, D]

    def _accumulate(s, valid):
        """One online-softmax tile: scores ``s`` [NB, Hkv*G, W] masked by
        ``valid`` [NB, 1, W]; returns probs for the PV accumulation."""
        s = jnp.where(valid, s, _NEG_INF)
        m_prev = m_ref[:, :, :1]
        l_prev = l_ref[:, :, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.where(valid, jnp.exp(s - m_new), 0.0)
        l_ref[:] = jnp.broadcast_to(
            alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True), l_ref.shape
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        return p, alpha

    def _big_tile():
        pos = j * block_t + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_t), 1
        )
        row_valids = []
        for r in range(nb):
            vr = pos < len_ref[bi * nb + r]
            if sliding_window is not None:
                vr &= pos > qpos_ref[bi * nb + r] - sliding_window
            row_valids.append(vr)
        valid = jnp.stack(row_valids)          # [NB, 1, BT]

        k = k_ref[0]                           # [NB, Hkv, BT, D] int8
        ks = ks_ref[0]
        s = jax.lax.dot_general(
            q.astype(jnp.bfloat16).reshape(nb * hkv, g, -1),
            k.astype(jnp.bfloat16).reshape(nb * hkv, block_t, -1),
            (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ).reshape(nb, hkv, g, block_t)
        s = (s * ks[:, :, None, :] * scale).reshape(nb, hkv * g, block_t)
        p, alpha = _accumulate(s, valid)

        v = v_ref[0]
        vs = vs_ref[0]
        pw = p.reshape(nb, hkv, g, block_t) * vs[:, :, None, :]
        pv = jax.lax.dot_general(
            pw.astype(jnp.bfloat16).reshape(nb * hkv, g, block_t),
            v.astype(jnp.bfloat16).reshape(nb * hkv, block_t, -1),
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        acc_ref[:] = acc_ref[:] * alpha + pv.reshape(nb, hkv * g, -1)

    _big_tile()

    @pl.when(j == num_blocks - 1)
    def _tail_tile():
        step = step_ref[0]
        # Quantize this step's K/V (must match cache._quantize_kv: symmetric
        # per-(token, head) absmax int8 with a 1e-8 floor and RNE rounding).
        kn = kn_ref[:].astype(jnp.float32)     # [NB, Hkv, 1, D]
        vn = vn_ref[:].astype(jnp.float32)
        ksc = jnp.maximum(jnp.max(jnp.abs(kn), axis=-1), 1e-8) / 127.0
        vsc = jnp.maximum(jnp.max(jnp.abs(vn), axis=-1), 1e-8) / 127.0
        kq = jnp.clip(jnp.round(kn / ksc[..., None]), -127, 127).astype(
            jnp.int8
        )
        vq = jnp.clip(jnp.round(vn / vsc[..., None]), -127, 127).astype(
            jnp.int8
        )

        slot = jax.lax.broadcasted_iota(jnp.int32, (1, 1, kt, 1), 2)
        hit4 = slot == step
        hit3 = hit4[..., 0]
        tk = jnp.where(hit4, kq, tk_ref[0])    # [NB, Hkv, KT, D]
        tv = jnp.where(hit4, vq, tv_ref[0])
        tks = jnp.where(hit3, ksc, tks_ref[0])  # [NB, Hkv, KT]
        tvs = jnp.where(hit3, vsc, tvs_ref[0])
        tk_out[0] = tk
        tv_out[0] = tv
        tks_out[0] = tks
        tvs_out[0] = tvs

        pos1 = jax.lax.broadcasted_iota(jnp.int32, (1, kt), 1)
        row_valids = []
        for r in range(nb):
            row = bi * nb + r
            vr = pos1 < vlen_ref[row]
            if sliding_window is not None:
                tail_pos = len_ref[row] + pos1
                vr &= tail_pos > qpos_ref[row] - sliding_window
            row_valids.append(vr)
        valid = jnp.stack(row_valids)          # [NB, 1, KT]

        s = jax.lax.dot_general(
            q.astype(jnp.bfloat16).reshape(nb * hkv, g, -1),
            tk.astype(jnp.bfloat16).reshape(nb * hkv, kt, -1),
            (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ).reshape(nb, hkv, g, kt)
        s = (s * tks[:, :, None, :] * scale).reshape(nb, hkv * g, kt)
        p, alpha = _accumulate(s, valid)

        pw = p.reshape(nb, hkv, g, kt) * tvs[:, :, None, :]
        pv = jax.lax.dot_general(
            pw.astype(jnp.bfloat16).reshape(nb * hkv, g, kt),
            tv.astype(jnp.bfloat16).reshape(nb * hkv, kt, -1),
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        acc_ref[:] = acc_ref[:] * alpha + pv.reshape(nb, hkv * g, -1)

        l = l_ref[:, :, :1]
        out = acc_ref[:] / jnp.maximum(l, 1e-20)
        out_ref[:] = out.reshape(nb, hkv, g, -1).astype(out_ref.dtype)

def fused_tail_flush(
    big_k: jnp.ndarray,
    big_ks: jnp.ndarray,
    big_v: jnp.ndarray,
    big_vs: jnp.ndarray,
    tail_k: jnp.ndarray,
    tail_ks: jnp.ndarray,
    tail_v: jnp.ndarray,
    tail_vs: jnp.ndarray,
    base_len: jnp.ndarray,
    tail_len: jnp.ndarray,
    interpret: Optional[bool] = None,
):
    """Merge the write-behind tail into the big head-major buffers by
    read-modify-writing only the 32-token-aligned blocks each row's window
    touches.

    The XLA formulation (where/take_along_axis over the whole time axis)
    re-reads AND re-writes every byte of the big buffers to place KT tokens
    per row — measured ~58 ms per fused-16-step call at batch 112
    (3.7 ms/step, a quarter of the attention itself); per-row
    ``dynamic_update_slice`` lowers to a serial loop, ``lax.scatter``
    aborts under GSPMD, and raw DMAs at per-row offsets fail Mosaic's
    tile-divisibility rule. Here each (layer, row) round-trips two
    32-token value blocks (and two 128-slot scale blocks) through VMEM,
    composing the tail in with POSITION-based masks: a row whose window
    fits one block has both grid steps clamp to the same block index and
    compose identical content, so the duplicate write is idempotent.

    ``tail_len`` may be any value in ``[0, KT]`` per row (masks cover
    partial and empty tails, and edge rows whose window would run past the
    buffer write only their live slots). Returns the four updated big
    buffers (inputs are consumed — aliased).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    num_l, b, hkv, t, d = big_k.shape
    kt = tail_k.shape[3]
    BV = 32    # value-plane block width (int8 sublane tile multiple)
    BS = 128   # scale-plane block width (f32 lane tile)
    nbv = t // BV
    nbs = -(-t // BS)
    # A KT-token window starting anywhere touches at most ceil(KT/BV)+1
    # value blocks (and fewer scale blocks — their visits clamp and the
    # position-based compose is idempotent, so extra visits are no-ops).
    nj = -(-kt // BV) + 1

    def _vidx(li, bi, ji, lens, tl):
        blk = jnp.minimum(lens[bi] // BV + ji, nbv - 1)
        return (li, bi, 0, blk, 0)

    def _sidx(li, bi, ji, lens, tl):
        blk = jnp.minimum(lens[bi] // BS + ji, nbs - 1)
        return (li, bi, 0, blk)

    def _tidx(li, bi, ji, lens, tl):
        return (li, bi, 0, 0, 0)

    def _tidx3(li, bi, ji, lens, tl):
        return (li, bi, 0, 0)

    def kernel(lens_ref, tl_ref,
               tk, tks, tv, tvs,
               bk_in, bks_in, bv_in, bvs_in,
               bk_out, bks_out, bv_out, bvs_out):
        bi = pl.program_id(1)
        ji = pl.program_id(2)
        start = lens_ref[bi]
        tl = tl_ref[bi]

        def compose_values(big_ref, tail_ref, out_ref):
            blk = jnp.minimum(start // BV + ji, nbv - 1)
            pos = blk * BV + jax.lax.broadcasted_iota(
                jnp.int32, (1, BV, 1), 1
            )
            cur = big_ref[0, 0]                        # [Hkv, BV, D]
            tail = tail_ref[0, 0]                      # [Hkv, KT, D]
            for i in range(kt):
                hit = (pos == start + i) & (i < tl)
                cur = jnp.where(hit, tail[:, i : i + 1], cur)
            out_ref[0, 0] = cur

        def compose_scales(big_ref, tail_ref, out_ref):
            blk = jnp.minimum(start // BS + ji, nbs - 1)
            pos = blk * BS + jax.lax.broadcasted_iota(
                jnp.int32, (1, BS), 1
            )
            cur = big_ref[0, 0]                        # [Hkv, BS]
            tail = tail_ref[0, 0]                      # [Hkv, KT]
            for i in range(kt):
                hit = (pos == start + i) & (i < tl)
                cur = jnp.where(hit, tail[:, i : i + 1], cur)
            out_ref[0, 0] = cur

        compose_values(bk_in, tk, bk_out)
        compose_values(bv_in, tv, bv_out)
        compose_scales(bks_in, tks, bks_out)
        compose_scales(bvs_in, tvs, bvs_out)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(num_l, b, nj),
        in_specs=[
            pl.BlockSpec((1, 1, hkv, kt, d), _tidx),
            pl.BlockSpec((1, 1, hkv, kt), _tidx3),
            pl.BlockSpec((1, 1, hkv, kt, d), _tidx),
            pl.BlockSpec((1, 1, hkv, kt), _tidx3),
            pl.BlockSpec((1, 1, hkv, BV, d), _vidx),
            pl.BlockSpec((1, 1, hkv, BS), _sidx),
            pl.BlockSpec((1, 1, hkv, BV, d), _vidx),
            pl.BlockSpec((1, 1, hkv, BS), _sidx),
        ],
        out_specs=(
            pl.BlockSpec((1, 1, hkv, BV, d), _vidx),
            pl.BlockSpec((1, 1, hkv, BS), _sidx),
            pl.BlockSpec((1, 1, hkv, BV, d), _vidx),
            pl.BlockSpec((1, 1, hkv, BS), _sidx),
        ),
        scratch_shapes=[],
    )
    return pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct(big_k.shape, big_k.dtype),
            jax.ShapeDtypeStruct(big_ks.shape, big_ks.dtype),
            jax.ShapeDtypeStruct(big_v.shape, big_v.dtype),
            jax.ShapeDtypeStruct(big_vs.shape, big_vs.dtype),
        ),
        grid_spec=grid_spec,
        interpret=interpret,
        # Inputs counting scalars: lens 0, tl 1, tails 2-5, bigs 6-9.
        input_output_aliases={6: 0, 7: 1, 8: 2, 9: 3},
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary", "arbitrary"),
            vmem_limit_bytes=100 * 1024 * 1024,
        ),
    )(base_len.astype(jnp.int32), tail_len.astype(jnp.int32),
      tail_k, tail_ks, tail_v, tail_vs,
      big_k, big_ks, big_v, big_vs)


def sink_fused_decode_attention(
    q: jnp.ndarray,
    q_sink: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
    big_k: jnp.ndarray,
    big_ks: jnp.ndarray,
    big_v: jnp.ndarray,
    big_vs: jnp.ndarray,
    sink_k: jnp.ndarray,
    sink_ks: jnp.ndarray,
    sink_v: jnp.ndarray,
    sink_vs: jnp.ndarray,
    tail_k: jnp.ndarray,
    tail_ks: jnp.ndarray,
    tail_v: jnp.ndarray,
    tail_vs: jnp.ndarray,
    layer_idx: jnp.ndarray,
    step_idx: jnp.ndarray,
    ring_len: jnp.ndarray,
    ring_ptr: jnp.ndarray,
    evict_len: jnp.ndarray,
    sink_len: jnp.ndarray,
    tail_valid_len: jnp.ndarray,
    ring_slots: int,
    scale: Optional[float] = None,
    block_t: int = 256,
    block_b: int = 8,
    interpret: Optional[bool] = None,
):
    """The fused decode step over the QUANTIZED SINK cache: one kernel per
    (layer, step) sweeping three joint-softmax segments — the int8 ring of
    recent tokens, the int8 attention sinks, and the write-behind tail the
    step's fresh K/V is quantized into in place.

    Position design (see ``cache/sink.py:QuantizedSinkKVCache``): RoPE
    scores depend only on position DIFFERENCES, so ring keys are stored
    rotated at their ABSOLUTE stream positions (write-once — the per-step
    whole-window re-rotation of the bf16 ring, the reference's
    ``cache.py:111-133`` re-rotation chain, disappears) and ``q`` is rotated
    at the absolute query position. Only the handful of sink tokens need the
    StreamingLLM compressed positions: they are stored rotated at their
    fixed slots ``0..s-1`` and attended with ``q_sink``, the same query
    rotated at its window-relative position.

    Ring validity: live slots are the prefix ``[0, ring_len)``; of those,
    the ``evict_len`` slots starting at ``ring_ptr`` (mod ``ring_slots``)
    hold tokens the in-flight tail has already evicted (exact per-step
    StreamingLLM window semantics, ahead of the physical overwrite at
    flush). ``evict_len`` = this step's tail length; callers guarantee
    the tail never exceeds the ring span (engine guard).

    Shapes: ``q``/``q_sink`` ``[B, 1, Hq, D]``; ``k_new``/``v_new``
    ``[B, 1, Hkv, D]`` (k abs-rotated); big stacks ``[L, B, Hkv, TR, D]``
    (+ scales, TR = padded ring span); sink stacks ``[L, B, Hkv, SP, D]``
    (+ scales); tail stacks ``[L, B, Hkv, KT, D]`` (+ scales, io-aliased).
    Returns ``(out, tail_k', tail_ks', tail_v', tail_vs')``.
    """
    b, s, hq, d = q.shape
    if s != 1:
        raise ValueError(f"decode-only kernel (S=1), got S={s}")
    num_l, _, hkv, t, _ = big_k.shape
    kt = tail_k.shape[3]
    sp = sink_k.shape[3]
    g = hq // hkv
    if scale is None:
        scale = d**-0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    # Largest 32-multiple divisor of TR (caches pad TR to a 32 multiple) so
    # tiles never straddle the buffer end; fall back to 32 (always a
    # divisor) rather than a whole-axis tile.
    bt = 32
    for cand in range(min(block_t, t), 31, -32):
        if t % cand == 0:
            bt = cand
            break
    num_blocks = t // bt
    nb = next(n for n in range(min(block_b, b), 0, -1) if b % n == 0)
    num_row_blocks = b // nb

    qr = q.reshape(b, hkv, g, d)
    qsr = q_sink.reshape(b, hkv, g, d)
    knr = jnp.moveaxis(k_new, 1, 2)  # [B, Hkv, 1, D]
    vnr = jnp.moveaxis(v_new, 1, 2)
    lref = jnp.asarray(layer_idx, jnp.int32).reshape(1)
    sref = jnp.asarray(step_idx, jnp.int32).reshape(1)

    def _row_live(bi, ji, lens):
        live = ji * bt < lens[bi * nb]
        for r in range(1, nb):
            live |= ji * bt < lens[bi * nb + r]
        return live

    def _big_index(bi, ji, lidx, step, lens, ptr, ev, slen, vlen):
        return (lidx[0], bi, 0,
                jnp.where(_row_live(bi, ji, lens), ji, 0), 0)

    def _big_index3(bi, ji, lidx, step, lens, ptr, ev, slen, vlen):
        return (lidx[0], bi, 0, jnp.where(_row_live(bi, ji, lens), ji, 0))

    def _lay_index(bi, ji, lidx, step, lens, ptr, ev, slen, vlen):
        return (lidx[0], bi, 0, 0, 0)

    def _lay_index3(bi, ji, lidx, step, lens, ptr, ev, slen, vlen):
        return (lidx[0], bi, 0, 0)

    def _row_index(bi, ji, lidx, step, lens, ptr, ev, slen, vlen):
        return (bi, 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=7,
        grid=(num_row_blocks, num_blocks),
        in_specs=[
            pl.BlockSpec((nb, hkv, g, d), _row_index),
            pl.BlockSpec((nb, hkv, g, d), _row_index),
            pl.BlockSpec((nb, hkv, 1, d), _row_index),
            pl.BlockSpec((nb, hkv, 1, d), _row_index),
            pl.BlockSpec((1, nb, hkv, bt, d), _big_index),
            pl.BlockSpec((1, nb, hkv, bt), _big_index3),
            pl.BlockSpec((1, nb, hkv, bt, d), _big_index),
            pl.BlockSpec((1, nb, hkv, bt), _big_index3),
            pl.BlockSpec((1, nb, hkv, sp, d), _lay_index),
            pl.BlockSpec((1, nb, hkv, sp), _lay_index3),
            pl.BlockSpec((1, nb, hkv, sp, d), _lay_index),
            pl.BlockSpec((1, nb, hkv, sp), _lay_index3),
            pl.BlockSpec((1, nb, hkv, kt, d), _lay_index),
            pl.BlockSpec((1, nb, hkv, kt), _lay_index3),
            pl.BlockSpec((1, nb, hkv, kt, d), _lay_index),
            pl.BlockSpec((1, nb, hkv, kt), _lay_index3),
        ],
        out_specs=(
            pl.BlockSpec((nb, hkv, g, d), _row_index),
            pl.BlockSpec((1, nb, hkv, kt, d), _lay_index),
            pl.BlockSpec((1, nb, hkv, kt), _lay_index3),
            pl.BlockSpec((1, nb, hkv, kt, d), _lay_index),
            pl.BlockSpec((1, nb, hkv, kt), _lay_index3),
        ),
        scratch_shapes=[
            pltpu.VMEM((nb, hkv * g, d), jnp.float32),
            pltpu.VMEM((nb, hkv * g, 128), jnp.float32),
            pltpu.VMEM((nb, hkv * g, 128), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _qsink_kernel,
        scale=scale,
        block_t=bt,
        num_blocks=num_blocks,
        ring_slots=ring_slots,
        hkv=hkv,
        g=g,
        nb=nb,
        sp=sp,
        kt=kt,
    )
    out, tk, tks, tv, tvs = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((b, hkv, g, d), q.dtype),
            jax.ShapeDtypeStruct(tail_k.shape, tail_k.dtype),
            jax.ShapeDtypeStruct(tail_ks.shape, tail_ks.dtype),
            jax.ShapeDtypeStruct(tail_v.shape, tail_v.dtype),
            jax.ShapeDtypeStruct(tail_vs.shape, tail_vs.dtype),
        ),
        grid_spec=grid_spec,
        interpret=interpret,
        # Tail stacks update in place; indices count every flattened input
        # including the 7 scalar-prefetch operands.
        input_output_aliases={19: 1, 20: 2, 21: 3, 22: 4},
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
            vmem_limit_bytes=100 * 1024 * 1024,
        ),
    )(lref, sref, ring_len.astype(jnp.int32), ring_ptr.astype(jnp.int32),
      evict_len.astype(jnp.int32), sink_len.astype(jnp.int32),
      tail_valid_len.astype(jnp.int32),
      qr, qsr, knr, vnr,
      big_k, big_ks, big_v, big_vs,
      sink_k, sink_ks, sink_v, sink_vs,
      tail_k, tail_ks, tail_v, tail_vs)
    return out.reshape(b, 1, hq, d), tk, tks, tv, tvs


def _qsink_kernel(
    lidx_ref,   # SMEM [1] int32 (layer; consumed by index maps)
    step_ref,   # SMEM [1] int32 (tail write slot)
    rlen_ref,   # SMEM [B] int32 (live ring prefix length)
    rptr_ref,   # SMEM [B] int32 (ring write pointer = oldest live slot)
    ev_ref,     # SMEM [B] int32 (slots evicted by the in-flight tail)
    slen_ref,   # SMEM [B] int32 (valid sink slots)
    vlen_ref,   # SMEM [B] int32 (valid tail slots incl. this write)
    q_ref,      # [NB, Hkv, G, D] (abs-rotated)
    qs_ref,     # [NB, Hkv, G, D] (window-relative-rotated, for sinks)
    kn_ref,     # [NB, Hkv, 1, D]
    vn_ref,     # [NB, Hkv, 1, D]
    k_ref,      # [1, NB, Hkv, BT, D] int8 (ring)
    ks_ref,     # [1, NB, Hkv, BT] f32
    v_ref,      # [1, NB, Hkv, BT, D] int8
    vs_ref,     # [1, NB, Hkv, BT] f32
    sk_ref,     # [1, NB, Hkv, SP, D] int8 (sinks; read-only)
    sks_ref,    # [1, NB, Hkv, SP] f32
    sv_ref,     # [1, NB, Hkv, SP, D] int8
    svs_ref,    # [1, NB, Hkv, SP] f32
    tk_ref,     # [1, NB, Hkv, KT, D] int8 (in)
    tks_ref,    # [1, NB, Hkv, KT] f32 (in)
    tv_ref,     # [1, NB, Hkv, KT, D] int8 (in)
    tvs_ref,    # [1, NB, Hkv, KT] f32 (in)
    out_ref,    # [NB, Hkv, G, D]
    tk_out,     # aliased tail outputs
    tks_out,
    tv_out,
    tvs_out,
    acc_ref,    # VMEM [NB, Hkv*G, D] f32
    m_ref,      # VMEM [NB, Hkv*G, 128] f32
    l_ref,      # VMEM [NB, Hkv*G, 128] f32
    *,
    scale: float,
    block_t: int,
    num_blocks: int,
    ring_slots: int,
    hkv: int,
    g: int,
    nb: int,
    sp: int,
    kt: int,
):
    bi = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q = q_ref[:]                               # [NB, Hkv, G, D]

    def _accumulate(s, valid):
        s = jnp.where(valid, s, _NEG_INF)
        m_prev = m_ref[:, :, :1]
        l_prev = l_ref[:, :, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.where(valid, jnp.exp(s - m_new), 0.0)
        l_ref[:] = jnp.broadcast_to(
            alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True), l_ref.shape
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        return p, alpha

    def _tile(qq, kk, kks, vv, vvs, valid, width):
        """One online-softmax tile over ``width`` int8 slots."""
        s = jax.lax.dot_general(
            qq.astype(jnp.bfloat16).reshape(nb * hkv, g, -1),
            kk.astype(jnp.bfloat16).reshape(nb * hkv, width, -1),
            (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ).reshape(nb, hkv, g, width)
        s = (s * kks[:, :, None, :] * scale).reshape(nb, hkv * g, width)
        p, alpha = _accumulate(s, valid)
        pw = p.reshape(nb, hkv, g, width) * vvs[:, :, None, :]
        pv = jax.lax.dot_general(
            pw.astype(jnp.bfloat16).reshape(nb * hkv, g, width),
            vv.astype(jnp.bfloat16).reshape(nb * hkv, width, -1),
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        acc_ref[:] = acc_ref[:] * alpha + pv.reshape(nb, hkv * g, -1)

    def _ring_tile():
        slot = j * block_t + jax.lax.broadcasted_iota(
            jnp.int32, (1, block_t), 1
        )
        row_valids = []
        for r in range(nb):
            row = bi * nb + r
            live = slot < rlen_ref[row]
            # Slots in [ring_ptr, ring_ptr + evict_len) mod R hold tokens
            # the in-flight tail has evicted (exact per-step window).
            w = rptr_ref[row]
            dd = slot - w + jnp.where(slot < w, ring_slots, 0)
            row_valids.append(live & (dd >= ev_ref[row]))
        valid = jnp.stack(row_valids)          # [NB, 1, BT]
        _tile(q, k_ref[0], ks_ref[0], v_ref[0], vs_ref[0], valid, block_t)

    _ring_tile()

    @pl.when(j == num_blocks - 1)
    def _final_tiles():
        # -- sink tile (window-relative query) --------------------------------
        slot1 = jax.lax.broadcasted_iota(jnp.int32, (1, sp), 1)
        sink_valid = jnp.stack(
            [slot1 < slen_ref[bi * nb + r] for r in range(nb)]
        )
        _tile(qs_ref[:], sk_ref[0], sks_ref[0], sv_ref[0], svs_ref[0],
              sink_valid, sp)

        # -- tail tile (quantize-in-kernel write + attend) --------------------
        step = step_ref[0]
        kn = kn_ref[:].astype(jnp.float32)     # [NB, Hkv, 1, D]
        vn = vn_ref[:].astype(jnp.float32)
        ksc = jnp.maximum(jnp.max(jnp.abs(kn), axis=-1), 1e-8) / 127.0
        vsc = jnp.maximum(jnp.max(jnp.abs(vn), axis=-1), 1e-8) / 127.0
        kq = jnp.clip(jnp.round(kn / ksc[..., None]), -127, 127).astype(
            jnp.int8
        )
        vq = jnp.clip(jnp.round(vn / vsc[..., None]), -127, 127).astype(
            jnp.int8
        )
        slot4 = jax.lax.broadcasted_iota(jnp.int32, (1, 1, kt, 1), 2)
        hit4 = slot4 == step
        hit3 = hit4[..., 0]
        tk = jnp.where(hit4, kq, tk_ref[0])    # [NB, Hkv, KT, D]
        tv = jnp.where(hit4, vq, tv_ref[0])
        tks = jnp.where(hit3, ksc, tks_ref[0])  # [NB, Hkv, KT]
        tvs = jnp.where(hit3, vsc, tvs_ref[0])
        tk_out[0] = tk
        tv_out[0] = tv
        tks_out[0] = tks
        tvs_out[0] = tvs

        pos1 = jax.lax.broadcasted_iota(jnp.int32, (1, kt), 1)
        tail_valid = jnp.stack(
            [pos1 < vlen_ref[bi * nb + r] for r in range(nb)]
        )
        _tile(q, tk, tks, tv, tvs, tail_valid, kt)

        l = l_ref[:, :, :1]
        out = acc_ref[:] / jnp.maximum(l, 1e-20)
        out_ref[:] = out.reshape(nb, hkv, g, -1).astype(out_ref.dtype)


def sink_tail_flush(
    big_k: jnp.ndarray,
    big_ks: jnp.ndarray,
    big_v: jnp.ndarray,
    big_vs: jnp.ndarray,
    tail_k: jnp.ndarray,
    tail_ks: jnp.ndarray,
    tail_v: jnp.ndarray,
    tail_vs: jnp.ndarray,
    ring_ptr: jnp.ndarray,
    skip: jnp.ndarray,
    tail_len: jnp.ndarray,
    ring_slots: int,
    interpret: Optional[bool] = None,
):
    """:func:`fused_tail_flush` for the sink RING: merge the write-behind
    tail into the int8 ring planes at per-row slots that WRAP mod
    ``ring_slots``. Tail token ``i`` (for ``skip <= i < tail_len``) lands at
    ring slot ``(ring_ptr + i - skip) % ring_slots``; the first ``skip``
    tokens are sink-bound (stream positions below the sink span) and are
    merged into the small sink planes by the caller in XLA.

    Blocked RMW like the dense flush, with a third block visit pinned to
    block 0 so a wrapped window's head is always covered (a consecutive
    mod-``nbv`` sweep can miss it when the ring spans >2 blocks).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    num_l, b, hkv, t, d = big_k.shape
    kt = tail_k.shape[3]
    BV = 32
    BS = 128
    nbv = t // BV
    nbs = -(-t // BS)
    nj = 3  # {ptr block, next mod, block 0} — covers straddle AND wrap

    def _vidx(li, bi, ji, ptr, sk, tl):
        blk = jnp.where(
            ji == nj - 1, 0, (ptr[bi] // BV + ji) % nbv
        )
        return (li, bi, 0, blk, 0)

    def _sidx(li, bi, ji, ptr, sk, tl):
        blk = jnp.where(
            ji == nj - 1, 0, (ptr[bi] // BS + ji) % nbs
        )
        return (li, bi, 0, blk)

    def _tidx(li, bi, ji, ptr, sk, tl):
        return (li, bi, 0, 0, 0)

    def _tidx3(li, bi, ji, ptr, sk, tl):
        return (li, bi, 0, 0)

    def kernel(ptr_ref, skip_ref, tl_ref,
               tk, tks, tv, tvs,
               bk_in, bks_in, bv_in, bvs_in,
               bk_out, bks_out, bv_out, bvs_out):
        bi = pl.program_id(1)
        ji = pl.program_id(2)
        ptr = ptr_ref[bi]
        sk_n = skip_ref[bi]
        tl = tl_ref[bi]

        def targets():
            """Ring slot of each tail index (mod ring_slots) + liveness."""
            out = []
            for i in range(kt):
                t0 = ptr + (i - sk_n)
                tgt = jax.lax.rem(
                    jnp.maximum(t0, 0), jnp.int32(ring_slots)
                )
                out.append((tgt, (i >= sk_n) & (i < tl)))
            return out

        tgts = targets()

        def compose_values(big_ref, tail_ref, out_ref, blk):
            pos = blk * BV + jax.lax.broadcasted_iota(
                jnp.int32, (1, BV, 1), 1
            )
            cur = big_ref[0, 0]                        # [Hkv, BV, D]
            tail = tail_ref[0, 0]                      # [Hkv, KT, D]
            for i in range(kt):
                tgt, live = tgts[i]
                hit = (pos == tgt) & live
                cur = jnp.where(hit, tail[:, i : i + 1], cur)
            out_ref[0, 0] = cur

        def compose_scales(big_ref, tail_ref, out_ref, blk):
            pos = blk * BS + jax.lax.broadcasted_iota(
                jnp.int32, (1, BS), 1
            )
            cur = big_ref[0, 0]                        # [Hkv, BS]
            tail = tail_ref[0, 0]                      # [Hkv, KT]
            for i in range(kt):
                tgt, live = tgts[i]
                hit = (pos == tgt) & live
                cur = jnp.where(hit, tail[:, i : i + 1], cur)
            out_ref[0, 0] = cur

        vblk = jnp.where(ji == nj - 1, 0, (ptr // BV + ji) % nbv)
        sblk = jnp.where(ji == nj - 1, 0, (ptr // BS + ji) % nbs)
        compose_values(bk_in, tk, bk_out, vblk)
        compose_values(bv_in, tv, bv_out, vblk)
        compose_scales(bks_in, tks, bks_out, sblk)
        compose_scales(bvs_in, tvs, bvs_out, sblk)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(num_l, b, nj),
        in_specs=[
            pl.BlockSpec((1, 1, hkv, kt, d), _tidx),
            pl.BlockSpec((1, 1, hkv, kt), _tidx3),
            pl.BlockSpec((1, 1, hkv, kt, d), _tidx),
            pl.BlockSpec((1, 1, hkv, kt), _tidx3),
            pl.BlockSpec((1, 1, hkv, BV, d), _vidx),
            pl.BlockSpec((1, 1, hkv, BS), _sidx),
            pl.BlockSpec((1, 1, hkv, BV, d), _vidx),
            pl.BlockSpec((1, 1, hkv, BS), _sidx),
        ],
        out_specs=(
            pl.BlockSpec((1, 1, hkv, BV, d), _vidx),
            pl.BlockSpec((1, 1, hkv, BS), _sidx),
            pl.BlockSpec((1, 1, hkv, BV, d), _vidx),
            pl.BlockSpec((1, 1, hkv, BS), _sidx),
        ),
        scratch_shapes=[],
    )
    return pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct(big_k.shape, big_k.dtype),
            jax.ShapeDtypeStruct(big_ks.shape, big_ks.dtype),
            jax.ShapeDtypeStruct(big_v.shape, big_v.dtype),
            jax.ShapeDtypeStruct(big_vs.shape, big_vs.dtype),
        ),
        grid_spec=grid_spec,
        interpret=interpret,
        # Inputs counting scalars: ptr 0, skip 1, tl 2, tails 3-6, bigs 7-10.
        input_output_aliases={7: 0, 8: 1, 9: 2, 10: 3},
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary", "arbitrary"),
            vmem_limit_bytes=100 * 1024 * 1024,
        ),
    )(ring_ptr.astype(jnp.int32), skip.astype(jnp.int32),
      tail_len.astype(jnp.int32),
      tail_k, tail_ks, tail_v, tail_vs,
      big_k, big_ks, big_v, big_vs)
