"""Pallas decode-attention kernel over the int8-quantized dense KV cache.

Why a kernel: the XLA path must feed the attention matmuls bf16 operands, so
the int8 cache is dequantized first — and depending on layout/formulation XLA
can materialize a full bf16 copy of K and V through HBM every step (measured
~13 GB extra per step at batch 80, Llama-7B shapes — more than the entire
ideal step traffic). Here the int8 buffers stream through VMEM exactly once:
scores are computed on the int8 values and the per-(token, head) scales are
applied to the scores (``q·(k·s_t) = s_t·(q·k)``); the v scales fold into the
probs before PV.

Structure follows ``paged_attention.py`` (grid over (batch, time-tiles),
online-softmax scratch carried across the inner axis, VPU multiply-reduce for
MHA / batched ``dot_general`` for GQA); the operand here is the contiguous
HEAD-major ``[B, Hkv, T, D]`` dense buffer instead of a page pool — the same
head-major tile shape the paged pool uses — with time-tiles past the row's
live length clamped to tile 0 so short rows in a long batch fetch one hot
tile instead of the padded span.

This is the decode half of the int8-KV serving mode (the reference's only
deployment optimization is int8 *weights*,
``/root/reference/distributed_llm_inference/utils/model.py:93-123``; int8 KV
is its TPU-native counterpart for the bandwidth-bound decode path). Runs in
interpret mode off-TPU so the CPU test mesh exercises it.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .attention import _NEG_INF

__all__ = ["quantized_decode_attention"]


def _qdense_kernel(
    len_ref,    # SMEM [B] int32 (scalar prefetch)
    q_ref,      # [1, Hkv, G, D]
    k_ref,      # [1, Hkv, BT, D] int8
    ks_ref,     # [1, Hkv, BT] f32
    v_ref,      # [1, Hkv, BT, D] int8
    vs_ref,     # [1, Hkv, BT] f32
    out_ref,    # [1, Hkv, G, D]
    acc_ref,    # VMEM [Hkv*G, D] f32
    m_ref,      # VMEM [Hkv*G, 128] f32
    l_ref,      # VMEM [Hkv*G, 128] f32
    *,
    scale: float,
    block_t: int,
    num_blocks: int,
    sliding_window: Optional[int],
    hkv: int,
    g: int,
):
    b = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    kv_len = len_ref[b]
    pos = j * block_t + jax.lax.broadcasted_iota(jnp.int32, (1, block_t), 1)
    valid = pos < kv_len  # decode: causality ≡ slot validity
    if sliding_window is not None:
        valid &= pos > kv_len - 1 - sliding_window

    q = q_ref[0]                       # [Hkv, G, D]
    k = k_ref[0]                       # [Hkv, BT, D] int8
    ks = ks_ref[0]                     # [Hkv, BT] f32

    if g == 1:
        # MHA: VPU multiply-reduce (1-row MXU matmuls waste the array).
        qv = q[:, 0, :][:, None, :].astype(jnp.float32)      # [Hkv, 1, D]
        s = jnp.sum(k.astype(jnp.float32) * qv, axis=-1)     # [Hkv, BT]
        s = s * ks
    else:
        s = jax.lax.dot_general(
            q.astype(jnp.float32), k.astype(jnp.float32),
            (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )                                                    # [Hkv, G, BT]
        s = s * ks[:, None, :]
        s = s.reshape(hkv * g, block_t)
    s = s * scale
    s = jnp.where(valid, s, _NEG_INF)

    m_prev = m_ref[:, :1]
    l_prev = l_ref[:, :1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.where(valid, jnp.exp(s - m_new), 0.0)            # [Hkv*G, BT]

    l_ref[:] = jnp.broadcast_to(
        alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True), l_ref.shape
    )
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)

    v = v_ref[0]                       # [Hkv, BT, D] int8
    vs = vs_ref[0]                     # [Hkv, BT] f32
    if g == 1:
        pw = p.reshape(hkv, block_t) * vs                    # [Hkv, BT]
        pv = jnp.sum(pw[:, :, None] * v.astype(jnp.float32), axis=1)
        acc_ref[:] = acc_ref[:] * alpha + pv                 # [Hkv, D]
    else:
        pw = p.reshape(hkv, g, block_t) * vs[:, None, :]
        pv = jax.lax.dot_general(
            pw, v.astype(jnp.float32), (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        acc_ref[:] = acc_ref[:] * alpha + pv.reshape(hkv * g, -1)

    @pl.when(j == num_blocks - 1)
    def _finalize():
        l = l_ref[:, :1]
        out = acc_ref[:] / jnp.maximum(l, 1e-20)
        out_ref[0] = out.reshape(hkv, g, -1).astype(out_ref.dtype)


def quantized_decode_attention(
    q: jnp.ndarray,
    k_q: jnp.ndarray,
    ks: jnp.ndarray,
    v_q: jnp.ndarray,
    vs: jnp.ndarray,
    kv_lengths: jnp.ndarray,
    scale: Optional[float] = None,
    sliding_window: Optional[int] = None,
    block_t: int = 128,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Decode attention straight over the int8 head-major dense cache.

    ``q``: ``[B, 1, Hq, D]`` (already rotated); ``k_q``/``v_q``: int8
    ``[B, Hkv, T, D]`` (keys stored rotated); ``ks``/``vs``: f32
    ``[B, Hkv, T]`` per-(token, head) scales; ``kv_lengths``: ``[B]`` live kv
    count per row *including* tokens written this step. Returns
    ``[B, 1, Hq, D]`` in q's dtype.
    """
    b, s, hq, d = q.shape
    if s != 1:
        raise ValueError(f"decode-only kernel (S=1), got S={s}")
    hkv, t = k_q.shape[1], k_q.shape[2]
    g = hq // hkv
    if scale is None:
        scale = d**-0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    bt = min(block_t, t)
    num_blocks = -(-t // bt)
    if t % bt:
        pad = num_blocks * bt - t
        k_q = jnp.pad(k_q, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v_q = jnp.pad(v_q, ((0, 0), (0, 0), (0, pad), (0, 0)))
        ks = jnp.pad(ks, ((0, 0), (0, 0), (0, pad)))
        vs = jnp.pad(vs, ((0, 0), (0, 0), (0, pad)))

    qr = q.reshape(b, hkv, g, d)

    def _tile_index(bi, ji, lens):
        # Tiles past the row's live span clamp to tile 0 (one hot fetch).
        live = ji * bt < lens[bi]
        return (bi, 0, jnp.where(live, ji, 0), 0)

    def _tile_index3(bi, ji, lens):
        live = ji * bt < lens[bi]
        return (bi, 0, jnp.where(live, ji, 0))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(b, num_blocks),
        in_specs=[
            pl.BlockSpec((1, hkv, g, d), lambda bi, ji, lens: (bi, 0, 0, 0)),
            pl.BlockSpec((1, hkv, bt, d), _tile_index),
            pl.BlockSpec((1, hkv, bt), _tile_index3),
            pl.BlockSpec((1, hkv, bt, d), _tile_index),
            pl.BlockSpec((1, hkv, bt), _tile_index3),
        ],
        out_specs=pl.BlockSpec(
            (1, hkv, g, d), lambda bi, ji, lens: (bi, 0, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((hkv * g, d), jnp.float32),
            pltpu.VMEM((hkv * g, 128), jnp.float32),
            pltpu.VMEM((hkv * g, 128), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _qdense_kernel,
        scale=scale,
        block_t=bt,
        num_blocks=num_blocks,
        sliding_window=sliding_window,
        hkv=hkv,
        g=g,
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, hkv, g, d), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(kv_lengths.astype(jnp.int32), qr, k_q, ks, v_q, vs)
    return out.reshape(b, 1, hq, d)
