"""Pallas decode-attention kernel over the int8-quantized dense KV cache.

Why a kernel: the XLA path must feed the attention matmuls bf16 operands, so
the int8 cache is dequantized first — and depending on layout/formulation XLA
can materialize a full bf16 copy of K and V through HBM every step (measured
~13 GB extra per step at batch 80, Llama-7B shapes — more than the entire
ideal step traffic). Here the int8 buffers stream through VMEM exactly once:
scores are computed on the int8 values and the per-(token, head) scales are
applied to the scores (``q·(k·s_t) = s_t·(q·k)``); the v scales fold into the
probs before PV.

Structure follows ``paged_attention.py`` (grid over (batch, time-tiles),
online-softmax scratch carried across the inner axis, VPU multiply-reduce for
MHA / batched ``dot_general`` for GQA); the operand here is the contiguous
HEAD-major ``[B, Hkv, T, D]`` dense buffer instead of a page pool — the same
head-major tile shape the paged pool uses — with time-tiles past the row's
live length clamped to tile 0 so short rows in a long batch fetch one hot
tile instead of the padded span.

This is the decode half of the int8-KV serving mode (the reference's only
deployment optimization is int8 *weights*,
``/root/reference/distributed_llm_inference/utils/model.py:93-123``; int8 KV
is its TPU-native counterpart for the bandwidth-bound decode path). Runs in
interpret mode off-TPU so the CPU test mesh exercises it.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .attention import _NEG_INF

__all__ = ["quantized_decode_attention"]


def _qdense_kernel(
    len_ref,    # SMEM [B] int32 (scalar prefetch)
    qpos_ref,   # SMEM [B] int32 (query positions, for the sliding window)
    q_ref,      # [1, Hkv, G, D]
    k_ref,      # [1, Hkv, BT, D] int8
    ks_ref,     # [1, Hkv, BT] f32
    v_ref,      # [1, Hkv, BT, D] int8
    vs_ref,     # [1, Hkv, BT] f32
    *refs,      # out_ref [, m_out_ref, l_out_ref], acc_ref, m_ref, l_ref
    scale: float,
    block_t: int,
    num_blocks: int,
    sliding_window: Optional[int],
    hkv: int,
    g: int,
    with_stats: bool,
):
    if with_stats:
        out_ref, m_out_ref, l_out_ref, acc_ref, m_ref, l_ref = refs
    else:
        out_ref, acc_ref, m_ref, l_ref = refs
        m_out_ref = l_out_ref = None
    b = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    kv_len = len_ref[b]
    pos = j * block_t + jax.lax.broadcasted_iota(jnp.int32, (1, block_t), 1)
    valid = pos < kv_len  # decode: causality ≡ slot validity
    if sliding_window is not None:
        valid &= pos > qpos_ref[b] - sliding_window

    q = q_ref[0]                       # [Hkv, G, D]
    k = k_ref[0]                       # [Hkv, BT, D] int8
    ks = ks_ref[0]                     # [Hkv, BT] f32

    if g == 1:
        # MHA: VPU multiply-reduce (1-row MXU matmuls waste the array).
        qv = q[:, 0, :][:, None, :].astype(jnp.float32)      # [Hkv, 1, D]
        s = jnp.sum(k.astype(jnp.float32) * qv, axis=-1)     # [Hkv, BT]
        s = s * ks
    else:
        s = jax.lax.dot_general(
            q.astype(jnp.float32), k.astype(jnp.float32),
            (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )                                                    # [Hkv, G, BT]
        s = s * ks[:, None, :]
        s = s.reshape(hkv * g, block_t)
    s = s * scale
    s = jnp.where(valid, s, _NEG_INF)

    m_prev = m_ref[:, :1]
    l_prev = l_ref[:, :1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.where(valid, jnp.exp(s - m_new), 0.0)            # [Hkv*G, BT]

    l_ref[:] = jnp.broadcast_to(
        alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True), l_ref.shape
    )
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)

    v = v_ref[0]                       # [Hkv, BT, D] int8
    vs = vs_ref[0]                     # [Hkv, BT] f32
    if g == 1:
        pw = p.reshape(hkv, block_t) * vs                    # [Hkv, BT]
        pv = jnp.sum(pw[:, :, None] * v.astype(jnp.float32), axis=1)
        acc_ref[:] = acc_ref[:] * alpha + pv                 # [Hkv, D]
    else:
        pw = p.reshape(hkv, g, block_t) * vs[:, None, :]
        pv = jax.lax.dot_general(
            pw, v.astype(jnp.float32), (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        acc_ref[:] = acc_ref[:] * alpha + pv.reshape(hkv * g, -1)

    @pl.when(j == num_blocks - 1)
    def _finalize():
        l = l_ref[:, :1]
        out = acc_ref[:] / jnp.maximum(l, 1e-20)
        out_ref[0] = out.reshape(hkv, g, -1).astype(out_ref.dtype)
        if with_stats:
            m_out_ref[0] = m_ref[:]
            l_out_ref[0] = l_ref[:]


def quantized_decode_attention(
    q: jnp.ndarray,
    k_q: jnp.ndarray,
    ks: jnp.ndarray,
    v_q: jnp.ndarray,
    vs: jnp.ndarray,
    kv_lengths: jnp.ndarray,
    scale: Optional[float] = None,
    sliding_window: Optional[int] = None,
    block_t: int = 128,
    interpret: Optional[bool] = None,
    q_positions: Optional[jnp.ndarray] = None,
    return_stats: bool = False,
):
    """Decode attention straight over the int8 head-major dense cache.

    ``q``: ``[B, 1, Hq, D]`` (already rotated); ``k_q``/``v_q``: int8
    ``[B, Hkv, T, D]`` (keys stored rotated); ``ks``/``vs``: f32
    ``[B, Hkv, T]`` per-(token, head) scales; ``kv_lengths``: ``[B]`` live kv
    count per row *including* tokens written this step. Returns
    ``[B, 1, Hq, D]`` in q's dtype.

    ``q_positions`` (``[B]``, default ``kv_lengths - 1``): the absolute
    position of each row's query, which anchors the sliding window — the
    fused-decode caller passes ``base_len + tail_len`` so the window stays
    correct while the big segment is frozen at ``base_len``.
    ``return_stats=True`` additionally returns the online-softmax stats
    ``(m, l)`` as ``[B, Hkv, G]`` f32 for a joint merge with another segment
    (``ops.attention.merge_softmax_segments``).
    """
    b, s, hq, d = q.shape
    if s != 1:
        raise ValueError(f"decode-only kernel (S=1), got S={s}")
    hkv, t = k_q.shape[1], k_q.shape[2]
    g = hq // hkv
    if scale is None:
        scale = d**-0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if q_positions is None:
        q_positions = kv_lengths - 1
    bt = min(block_t, t)
    num_blocks = -(-t // bt)
    if t % bt:
        pad = num_blocks * bt - t
        k_q = jnp.pad(k_q, ((0, 0), (0, 0), (0, pad), (0, 0)))
        v_q = jnp.pad(v_q, ((0, 0), (0, 0), (0, pad), (0, 0)))
        ks = jnp.pad(ks, ((0, 0), (0, 0), (0, pad)))
        vs = jnp.pad(vs, ((0, 0), (0, 0), (0, pad)))

    qr = q.reshape(b, hkv, g, d)

    def _tile_index(bi, ji, lens, qpos):
        # Tiles past the row's live span clamp to tile 0 (one hot fetch).
        live = ji * bt < lens[bi]
        return (bi, 0, jnp.where(live, ji, 0), 0)

    def _tile_index3(bi, ji, lens, qpos):
        live = ji * bt < lens[bi]
        return (bi, 0, jnp.where(live, ji, 0))

    out_specs = [
        pl.BlockSpec(
            (1, hkv, g, d), lambda bi, ji, lens, qpos: (bi, 0, 0, 0)
        ),
    ]
    out_shapes = [jax.ShapeDtypeStruct((b, hkv, g, d), q.dtype)]
    if return_stats:
        # m/l outputs exist only when a caller merges with another segment;
        # the plain decode path skips them (2*B*Hkv*G*128*4 bytes of HBM
        # writes per (layer, step) it would otherwise discard).
        out_specs += [
            pl.BlockSpec(
                (1, hkv * g, 128), lambda bi, ji, lens, qpos: (bi, 0, 0)
            ),
            pl.BlockSpec(
                (1, hkv * g, 128), lambda bi, ji, lens, qpos: (bi, 0, 0)
            ),
        ]
        out_shapes += [
            jax.ShapeDtypeStruct((b, hkv * g, 128), jnp.float32),
            jax.ShapeDtypeStruct((b, hkv * g, 128), jnp.float32),
        ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(b, num_blocks),
        in_specs=[
            pl.BlockSpec(
                (1, hkv, g, d), lambda bi, ji, lens, qpos: (bi, 0, 0, 0)
            ),
            pl.BlockSpec((1, hkv, bt, d), _tile_index),
            pl.BlockSpec((1, hkv, bt), _tile_index3),
            pl.BlockSpec((1, hkv, bt, d), _tile_index),
            pl.BlockSpec((1, hkv, bt), _tile_index3),
        ],
        out_specs=tuple(out_specs),
        scratch_shapes=[
            pltpu.VMEM((hkv * g, d), jnp.float32),
            pltpu.VMEM((hkv * g, 128), jnp.float32),
            pltpu.VMEM((hkv * g, 128), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _qdense_kernel,
        scale=scale,
        block_t=bt,
        num_blocks=num_blocks,
        sliding_window=sliding_window,
        hkv=hkv,
        g=g,
        with_stats=return_stats,
    )
    res = pl.pallas_call(
        kernel,
        out_shape=tuple(out_shapes),
        grid_spec=grid_spec,
        interpret=interpret,
    )(kv_lengths.astype(jnp.int32), q_positions.astype(jnp.int32),
      qr, k_q, ks, v_q, vs)
    if return_stats:
        out, m, l = res
        out = out.reshape(b, 1, hq, d)
        return out, m[:, :, 0].reshape(b, hkv, g), l[:, :, 0].reshape(b, hkv, g)
    return res[0].reshape(b, 1, hq, d)


def quantized_decode_attention_stacked(
    q: jnp.ndarray,
    k_q: jnp.ndarray,
    ks: jnp.ndarray,
    v_q: jnp.ndarray,
    vs: jnp.ndarray,
    layer_idx: jnp.ndarray,
    kv_lengths: jnp.ndarray,
    scale: Optional[float] = None,
    sliding_window: Optional[int] = None,
    block_t: int = 128,
    block_b: int = 8,
    interpret: Optional[bool] = None,
    q_positions: Optional[jnp.ndarray] = None,
):
    """As :func:`quantized_decode_attention` + stats, but over the WHOLE
    layer-stacked cache ``[L, B, Hkv, T, D]`` with a traced ``layer_idx``.

    Two deliberate structural choices, both measured on v5e at batch 112
    (Llama-7B shapes, fused 16-step decode):

    * Zero-copy operands. Inside the fused decode's layer scan, slicing one
      layer's K/V out of the stack to feed a ``pallas_call`` materializes a
      full HBM copy of that layer's buffers every (layer, step) — XLA cannot
      fuse a dynamic-slice into a custom call's operand (tripled decode
      cost). The stack passes through whole; the block index map resolves
      the traced ``layer_idx``.
    * Row-blocked grid. One batch row per grid step (the natural port of the
      per-row paged kernel) issues ~1 MB DMAs and its per-step overhead
      dominates: measured 1.57 ms per (layer, step) vs the XLA segment
      path's 0.42 ms. ``block_b`` rows per step turn that into ~8 MB DMAs
      over an 8x smaller grid.

    Always returns ``(out, m, l)`` (stats for the tail merge);
    ``kv_lengths`` is per-row live length of the big segment, and
    ``q_positions`` anchors the sliding window.
    """
    b, s, hq, d = q.shape
    if s != 1:
        raise ValueError(f"decode-only kernel (S=1), got S={s}")
    num_l, _, hkv, t, _ = k_q.shape
    g = hq // hkv
    if scale is None:
        scale = d**-0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if q_positions is None:
        q_positions = kv_lengths - 1
    bt = min(block_t, t)
    num_blocks = -(-t // bt)
    nb = min(block_b, b)
    num_row_blocks = -(-b // nb)
    bp = num_row_blocks * nb
    if bp != b:
        # Pad the small per-row operands only (q/lengths); the KV stack is
        # never padded — padding it would copy the multi-GB buffer inside
        # the decode loop. Pad rows read KV tile 0 (masked: length 0).
        q = jnp.pad(q, ((0, bp - b), (0, 0), (0, 0), (0, 0)))
        kv_lengths = jnp.pad(kv_lengths, (0, bp - b))
        q_positions = jnp.pad(q_positions, (0, bp - b))

    qr = q.reshape(bp, hkv, g, d)
    lref = jnp.asarray(layer_idx, jnp.int32).reshape(1)

    def _row_live(bi, ji, lens):
        # A KV time-tile is fetched iff ANY row in this row-block still has
        # live tokens there; otherwise clamp to tile 0 (the pipeline elides
        # the repeat fetch). Padded rows have length 0, never forcing tiles.
        # ``lens`` is an SMEM ref: scalar reads only, unrolled over the block.
        live = ji * bt < lens[bi * nb]
        for r in range(1, nb):
            live |= ji * bt < lens[bi * nb + r]
        return live

    def _tile_index(bi, ji, lidx, lens, qpos):
        return (lidx[0], bi, 0, jnp.where(_row_live(bi, ji, lens), ji, 0), 0)

    def _tile_index3(bi, ji, lidx, lens, qpos):
        return (lidx[0], bi, 0, jnp.where(_row_live(bi, ji, lens), ji, 0))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(num_row_blocks, num_blocks),
        in_specs=[
            pl.BlockSpec(
                (nb, hkv, g, d),
                lambda bi, ji, lidx, lens, qpos: (bi, 0, 0, 0),
            ),
            pl.BlockSpec((1, nb, hkv, bt, d), _tile_index),
            pl.BlockSpec((1, nb, hkv, bt), _tile_index3),
            pl.BlockSpec((1, nb, hkv, bt, d), _tile_index),
            pl.BlockSpec((1, nb, hkv, bt), _tile_index3),
        ],
        out_specs=(
            pl.BlockSpec(
                (nb, hkv, g, d),
                lambda bi, ji, lidx, lens, qpos: (bi, 0, 0, 0),
            ),
            pl.BlockSpec(
                (nb, hkv * g, 128),
                lambda bi, ji, lidx, lens, qpos: (bi, 0, 0),
            ),
            pl.BlockSpec(
                (nb, hkv * g, 128),
                lambda bi, ji, lidx, lens, qpos: (bi, 0, 0),
            ),
        ),
        scratch_shapes=[
            pltpu.VMEM((nb, hkv * g, d), jnp.float32),
            pltpu.VMEM((nb, hkv * g, 128), jnp.float32),
            pltpu.VMEM((nb, hkv * g, 128), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _qdense_stacked_kernel,
        scale=scale,
        block_t=bt,
        num_blocks=num_blocks,
        sliding_window=sliding_window,
        hkv=hkv,
        g=g,
        nb=nb,
    )
    out, m, l = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((bp, hkv, g, d), q.dtype),
            jax.ShapeDtypeStruct((bp, hkv * g, 128), jnp.float32),
            jax.ShapeDtypeStruct((bp, hkv * g, 128), jnp.float32),
        ),
        grid_spec=grid_spec,
        interpret=interpret,
        compiler_params=pltpu.CompilerParams(
            # Row blocks are independent; time-tiles carry the softmax
            # scratch. The default 16 MB scoped-vmem budget rejects the
            # double-buffered 4 MB K/V tiles, so raise it (v5e has 128 MB).
            dimension_semantics=("parallel", "arbitrary"),
            vmem_limit_bytes=100 * 1024 * 1024,
        ),
    )(lref, kv_lengths.astype(jnp.int32), q_positions.astype(jnp.int32),
      qr, k_q, ks, v_q, vs)
    out = out[:b].reshape(b, 1, hq, d)
    return (
        out,
        m[:b, :, 0].reshape(b, hkv, g),
        l[:b, :, 0].reshape(b, hkv, g),
    )


def _qdense_stacked_kernel(
    lidx_ref,   # SMEM [1] int32 (layer index; consumed by the index maps)
    len_ref,    # SMEM [B] int32
    qpos_ref,   # SMEM [B] int32
    q_ref,      # [NB, Hkv, G, D]
    k_ref,      # [1, NB, Hkv, BT, D] int8
    ks_ref,     # [1, NB, Hkv, BT] f32
    v_ref,      # [1, NB, Hkv, BT, D] int8
    vs_ref,     # [1, NB, Hkv, BT] f32
    out_ref,    # [NB, Hkv, G, D]
    m_out_ref,  # [NB, Hkv*G, 128] f32
    l_out_ref,  # [NB, Hkv*G, 128] f32
    acc_ref,    # VMEM [NB, Hkv*G, D] f32
    m_ref,      # VMEM [NB, Hkv*G, 128] f32
    l_ref,      # VMEM [NB, Hkv*G, 128] f32
    *,
    scale: float,
    block_t: int,
    num_blocks: int,
    sliding_window: Optional[int],
    hkv: int,
    g: int,
    nb: int,
):
    """Row-blocked variant of :func:`_qdense_kernel`: NB batch rows per grid
    step share one (much larger) KV DMA; online-softmax state carries a
    leading row axis."""
    bi = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    # Per-row masks from SMEM scalars, unrolled over the row block (vector
    # builds like ``.at[r].set`` lower to scatter, which Mosaic lacks).
    pos = j * block_t + jax.lax.broadcasted_iota(
        jnp.int32, (1, block_t), 1
    )
    row_valids = []
    for r in range(nb):
        vr = pos < len_ref[bi * nb + r]
        if sliding_window is not None:
            vr &= pos > qpos_ref[bi * nb + r] - sliding_window
        row_valids.append(vr)
    valid = jnp.stack(row_valids)              # [NB, 1, BT]

    q = q_ref[:]                               # [NB, Hkv, G, D]
    k = k_ref[0]                               # [NB, Hkv, BT, D] int8
    ks = ks_ref[0]                             # [NB, Hkv, BT] f32

    s = jax.lax.dot_general(
        q.astype(jnp.bfloat16).reshape(nb * hkv, g, -1),
        k.astype(jnp.bfloat16).reshape(nb * hkv, block_t, -1),
        (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    ).reshape(nb, hkv, g, block_t)     # bf16 MXU (Mosaic: one batch dim max)
    s = s * ks[:, :, None, :]
    s = (s * scale).reshape(nb, hkv * g, block_t)
    s = jnp.where(valid, s, _NEG_INF)          # valid [NB, 1, BT] broadcasts

    m_prev = m_ref[:, :, :1]                   # [NB, Hkv*G, 1]
    l_prev = l_ref[:, :, :1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.where(valid, jnp.exp(s - m_new), 0.0)

    l_ref[:] = jnp.broadcast_to(
        alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True), l_ref.shape
    )
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)

    v = v_ref[0]                               # [NB, Hkv, BT, D] int8
    vs = vs_ref[0]                             # [NB, Hkv, BT] f32
    pw = p.reshape(nb, hkv, g, block_t) * vs[:, :, None, :]
    pv = jax.lax.dot_general(
        pw.astype(jnp.bfloat16).reshape(nb * hkv, g, block_t),
        v.astype(jnp.bfloat16).reshape(nb * hkv, block_t, -1),
        (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )                                          # [NB*Hkv, G, D]
    acc_ref[:] = acc_ref[:] * alpha + pv.reshape(nb, hkv * g, -1)

    @pl.when(j == num_blocks - 1)
    def _finalize():
        l = l_ref[:, :, :1]
        out = acc_ref[:] / jnp.maximum(l, 1e-20)
        out_ref[:] = out.reshape(nb, hkv, g, -1).astype(out_ref.dtype)
        m_out_ref[:] = m_ref[:]
        l_out_ref[:] = l_ref[:]
