"""Normalization ops.

The reference wraps HF ``LlamaRMSNorm`` modules in CUDA-graph replays for the
decode path (``/root/reference/distributed_llm_inference/models/llama/modules.py:130-144``).
On TPU there is nothing to capture: a jitted RMSNorm is a single fused
XLA computation, so the whole "graphed norm" machinery collapses into this
pure function.
"""

from __future__ import annotations

import jax.numpy as jnp


def rms_norm(x: jnp.ndarray, weight: jnp.ndarray, eps: float) -> jnp.ndarray:
    """RMSNorm in fp32 accumulation, output cast back to input dtype."""
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    normed = xf * jnp.reciprocal(jnp.sqrt(var + eps))
    return (normed * weight.astype(jnp.float32)).astype(dtype)
