"""Reference (XLA-fused) GQA attention and mask construction.

Replaces the reference's eager attention at
``/root/reference/distributed_llm_inference/models/llama/modules.py:87-97``:
QK^T/sqrt(d), additive causal mask, fp32 softmax, PV. Two TPU-first changes:

* No ``repeat_kv`` materialization (reference ``modules.py:87-88``): queries are
  reshaped to ``[B, S, Hkv, G, D]`` and contracted against KV heads directly, so
  the GQA expansion never touches HBM.
* Masks are boolean and fused into the softmax via ``where`` rather than a
  precomputed additive min-dtype tensor (reference ``models/llama/model.py:103-135``)
  — XLA folds the select into the fused softmax.

The Pallas flash/paged kernels in ``flash_attention.py`` / ``paged_attention.py``
are drop-in replacements for the hot paths; this module is the always-correct
fallback and the oracle for their tests.
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

_NEG_INF = -0.7 * float(jnp.finfo(jnp.float32).max)


def causal_mask(
    q_positions: jnp.ndarray,
    kv_positions: jnp.ndarray,
    kv_valid: Optional[jnp.ndarray] = None,
    sliding_window: Optional[int] = None,
) -> jnp.ndarray:
    """Boolean attend-mask ``[..., S, T]`` from per-token positions.

    ``q_positions``: ``[..., S]`` absolute positions of the queries.
    ``kv_positions``: ``[..., T]`` absolute positions of the cached keys.
    ``kv_valid``: optional ``[..., T]`` validity of each cache slot (ring
    buffers / padding).
    ``sliding_window``: Mistral-style window — key visible iff
    ``q_pos - w < k_pos <= q_pos``.
    """
    q = q_positions[..., :, None]
    k = kv_positions[..., None, :]
    mask = k <= q
    if sliding_window is not None:
        mask &= k > (q - sliding_window)
    if kv_valid is not None:
        mask &= kv_valid[..., None, :]
    return mask


def gqa_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Grouped-query attention.

    ``q``: ``[B, S, Hq, D]``; ``k``/``v``: ``[B, T, Hkv, D]`` with
    ``Hq = G * Hkv``. ``mask``: boolean ``[B, S, T]`` or ``[B, 1, S, T]``
    (True = attend). Returns ``[B, S, Hq, D]`` in q's dtype; softmax in fp32
    (parity with reference ``modules.py:96``).
    """
    b, s, hq, d = q.shape
    t = k.shape[1]
    hkv = k.shape[2]
    g = hq // hkv
    if scale is None:
        scale = d**-0.5

    qg = q.reshape(b, s, hkv, g, d)
    # [B, Hkv, G, S, T]
    scores = jnp.einsum("bskgd,btkd->bkgst", qg, k, preferred_element_type=jnp.float32)
    scores = scores * scale

    if mask is not None:
        if mask.ndim == 3:
            m = mask[:, None, None, :, :]
        elif mask.ndim == 4:  # [B, 1, S, T]
            m = mask[:, :, None, :, :]
        else:
            raise ValueError(f"mask ndim {mask.ndim}")
        scores = jnp.where(m, scores, _NEG_INF)

    # Guard fully-masked rows (e.g. padded slots): softmax of all -inf → 0.
    weights = jnp.exp(scores - jnp.max(scores, axis=-1, keepdims=True))
    if mask is not None:
        weights = jnp.where(m, weights, 0.0)
    denom = jnp.sum(weights, axis=-1, keepdims=True)
    weights = weights / jnp.maximum(denom, 1e-20)

    out = jnp.einsum(
        "bkgst,btkd->bskgd", weights.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return out.reshape(b, s, hq, d).astype(q.dtype)
