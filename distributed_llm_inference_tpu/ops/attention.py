"""Reference (XLA-fused) GQA attention and mask construction.

Replaces the reference's eager attention at
``/root/reference/distributed_llm_inference/models/llama/modules.py:87-97``:
QK^T/sqrt(d), additive causal mask, fp32 softmax, PV. Two TPU-first changes:

* No ``repeat_kv`` materialization (reference ``modules.py:87-88``): queries are
  reshaped to ``[B, S, Hkv, G, D]`` and contracted against KV heads directly, so
  the GQA expansion never touches HBM.
* Masks are boolean and fused into the softmax via ``where`` rather than a
  precomputed additive min-dtype tensor (reference ``models/llama/model.py:103-135``)
  — XLA folds the select into the fused softmax.

The Pallas flash/paged kernels in ``flash_attention.py`` / ``paged_attention.py``
are drop-in replacements for the hot paths; this module is the always-correct
fallback and the oracle for their tests.
"""

from __future__ import annotations

import functools
from typing import Optional, Sequence, Tuple

import jax.numpy as jnp

_NEG_INF = -0.7 * float(jnp.finfo(jnp.float32).max)


def causal_mask(
    q_positions: jnp.ndarray,
    kv_positions: jnp.ndarray,
    kv_valid: Optional[jnp.ndarray] = None,
    sliding_window: Optional[int] = None,
) -> jnp.ndarray:
    """Boolean attend-mask ``[..., S, T]`` from per-token positions.

    ``q_positions``: ``[..., S]`` absolute positions of the queries.
    ``kv_positions``: ``[..., T]`` absolute positions of the cached keys.
    ``kv_valid``: optional ``[..., T]`` validity of each cache slot (ring
    buffers / padding).
    ``sliding_window``: Mistral-style window — key visible iff
    ``q_pos - w < k_pos <= q_pos``.
    """
    q = q_positions[..., :, None]
    k = kv_positions[..., None, :]
    mask = k <= q
    if sliding_window is not None:
        mask &= k > (q - sliding_window)
    if kv_valid is not None:
        mask &= kv_valid[..., None, :]
    return mask


def gqa_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Grouped-query attention.

    ``q``: ``[B, S, Hq, D]``; ``k``/``v``: ``[B, T, Hkv, D]`` with
    ``Hq = G * Hkv``. ``mask``: boolean ``[B, S, T]`` or ``[B, 1, S, T]``
    (True = attend). Returns ``[B, S, Hq, D]`` in q's dtype; softmax in fp32
    (parity with reference ``modules.py:96``).
    """
    b, s, hq, d = q.shape
    t = k.shape[1]
    hkv = k.shape[2]
    g = hq // hkv
    if scale is None:
        scale = d**-0.5

    qg = q.reshape(b, s, hkv, g, d)
    # [B, Hkv, G, S, T]
    scores = jnp.einsum("bskgd,btkd->bkgst", qg, k, preferred_element_type=jnp.float32)
    scores = scores * scale

    if mask is not None:
        if mask.ndim == 3:
            m = mask[:, None, None, :, :]
        elif mask.ndim == 4:  # [B, 1, S, T]
            m = mask[:, :, None, :, :]
        else:
            raise ValueError(f"mask ndim {mask.ndim}")
        scores = jnp.where(m, scores, _NEG_INF)

    # Guard fully-masked rows (e.g. padded slots): softmax of all -inf → 0.
    weights = jnp.exp(scores - jnp.max(scores, axis=-1, keepdims=True))
    if mask is not None:
        weights = jnp.where(m, weights, 0.0)
    denom = jnp.sum(weights, axis=-1, keepdims=True)
    weights = weights / jnp.maximum(denom, 1e-20)

    out = jnp.einsum(
        "bkgst,btkd->bskgd", weights.astype(v.dtype), v,
        preferred_element_type=jnp.float32,
    )
    return out.reshape(b, s, hq, d).astype(q.dtype)


def gqa_attention_quantized(
    q: jnp.ndarray,
    k_q: jnp.ndarray,
    ks: jnp.ndarray,
    v_q: jnp.ndarray,
    vs: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """GQA attention over an int8-quantized KV cache WITHOUT dequantizing it.

    ``k_q``/``v_q``: int8 ``[B, Hkv, T, D]`` (HEAD-major); ``ks``/``vs``:
    fp32 ``[B, Hkv, T]`` per-(token, head) scales. Two things keep the big
    int8 buffers on the minimal-traffic path:

    * the scales commute past the contractions — ``q·(k·s_t) = s_t·(q·k)``
      and ``p·(v·s_t) = (p·s_t)·v`` — so they are applied to the
      SCORES/probs (``[B, Hkv, G, S, T]``, small). The elementwise
      dequant-multiply formulation makes XLA materialize bf16 copies of K
      and V every step (write + re-read ≈ 3x the KV traffic; measured ~45%
      of the whole decode step at batch 80, Llama-7B shapes);
    * the head-major layout matches the contraction's batch(B, Hkv) ×
      contract(D or T) structure, so the int8→bf16 convert needs no
      relayout and stays fused in the dot's operand read.
    """
    b, s, hq, d = q.shape
    hkv, t = k_q.shape[1], k_q.shape[2]
    g = hq // hkv
    if scale is None:
        scale = d**-0.5

    qg = q.reshape(b, s, hkv, g, d)
    scores = jnp.einsum(
        "bskgd,bktd->bkgst", qg, k_q.astype(q.dtype),
        preferred_element_type=jnp.float32,
    )
    # [B, Hkv, T] → [B, Hkv, 1, 1, T] broadcast over (G, S).
    k_scales = ks[:, :, None, None, :]
    scores = scores * (k_scales * scale)

    if mask is not None:
        if mask.ndim == 3:
            m = mask[:, None, None, :, :]
        elif mask.ndim == 4:  # [B, 1, S, T]
            m = mask[:, :, None, :, :]
        else:
            raise ValueError(f"mask ndim {mask.ndim}")
        scores = jnp.where(m, scores, _NEG_INF)

    weights = jnp.exp(scores - jnp.max(scores, axis=-1, keepdims=True))
    if mask is not None:
        weights = jnp.where(m, weights, 0.0)
    denom = jnp.sum(weights, axis=-1, keepdims=True)
    weights = weights / jnp.maximum(denom, 1e-20)

    v_scales = vs[:, :, None, None, :]
    wv = (weights * v_scales).astype(q.dtype)
    out = jnp.einsum(
        "bkgst,bktd->bskgd", wv, v_q.astype(q.dtype),
        preferred_element_type=jnp.float32,
    )
    return out.reshape(b, s, hq, d).astype(q.dtype)


def gqa_attention_segments(
    q: jnp.ndarray,
    segments: Sequence[Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray]],
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """GQA attention over MULTIPLE KV segments under one joint softmax.

    Exact (not an approximation): softmax is linear in its pieces once a
    global max is shared, so splitting the keys into segments changes only
    the association order. Used by the fused multi-step decode
    (``models/llama.py:multi_decode_apply``): segment 0 is the big read-only
    cache, segment 1 the small write-behind tail.

    ``q``: ``[B, S, Hq, D]``; each segment ``(k, v, valid)`` with
    ``k``/``v`` ``[B, Ti, Hkv, D]`` (time-major) and ``valid`` ``[B, Ti]``
    (True = attend). Returns ``[B, S, Hq, D]``.
    """
    b, s, hq, d = q.shape
    hkv = segments[0][0].shape[2]
    g = hq // hkv
    if scale is None:
        scale = d**-0.5
    qg = q.reshape(b, s, hkv, g, d)

    scored = []
    for k, v, valid in segments:
        sc = jnp.einsum(
            "bskgd,btkd->bkgst", qg, k,
            preferred_element_type=jnp.float32,
        ) * scale
        m = valid[:, None, None, None, :]
        scored.append((jnp.where(m, sc, _NEG_INF), m))

    gmax = functools.reduce(
        jnp.maximum,
        [jnp.max(sc, axis=-1, keepdims=True) for sc, _ in scored],
    )
    denom = 0.0
    out = 0.0
    for (sc, m), (k, v, valid) in zip(scored, segments):
        w = jnp.where(m, jnp.exp(sc - gmax), 0.0)
        denom = denom + jnp.sum(w, axis=-1, keepdims=True)
        out = out + jnp.einsum(
            "bkgst,btkd->bskgd", w.astype(v.dtype), v,
            preferred_element_type=jnp.float32,
        )
    denom = jnp.maximum(denom, 1e-20).transpose(0, 3, 1, 2, 4)
    return (out / denom).reshape(b, s, hq, d).astype(q.dtype)


def gqa_attention_quantized_multi_q_segments(
    segments: Sequence[Tuple[jnp.ndarray, ...]],
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Joint softmax over int8 head-major segments, each with its OWN query
    and full mask.

    The general form behind :func:`gqa_attention_quantized_segments`, needed
    by the quantized sink cache: its sink segment is attended with a
    window-relative-rotated query while the ring/tail segments use the
    absolute-rotated one (RoPE scores depend only on position differences —
    ``cache/sink.py``). Each segment is ``(q [B, S, Hq, D], k_q [B, Hkv,
    Ti, D] int8, ks [B, Hkv, Ti] f32, v_q, vs, mask)`` with ``mask`` either
    ``[B, S, Ti]`` or a broadcastable ``[B, 1, Ti]``.
    """
    q0 = segments[0][0]
    b, s, hq, d = q0.shape
    hkv = segments[0][1].shape[1]
    g = hq // hkv
    if scale is None:
        scale = d**-0.5

    scored = []
    for q, k_q, ks, v_q, vs, mask in segments:
        qg = q.reshape(b, s, hkv, g, d)
        sc = jnp.einsum(
            "bskgd,bktd->bkgst", qg, k_q.astype(q.dtype),
            preferred_element_type=jnp.float32,
        )
        sc = sc * (ks[:, :, None, None, :] * scale)
        m = mask[:, None, None, :, :]  # [B, 1, 1, S, T]
        scored.append((jnp.where(m, sc, _NEG_INF), m))

    gmax = functools.reduce(
        jnp.maximum,
        [jnp.max(sc, axis=-1, keepdims=True) for sc, _ in scored],
    )
    denom = 0.0
    out = 0.0
    for (sc, m), (q, k_q, ks, v_q, vs, mask) in zip(scored, segments):
        w = jnp.where(m, jnp.exp(sc - gmax), 0.0)
        denom = denom + jnp.sum(w, axis=-1, keepdims=True)
        wv = (w * vs[:, :, None, None, :]).astype(q0.dtype)
        out = out + jnp.einsum(
            "bkgst,bktd->bskgd", wv, v_q.astype(q0.dtype),
            preferred_element_type=jnp.float32,
        )
    denom = jnp.maximum(denom, 1e-20).transpose(0, 3, 1, 2, 4)
    return (out / denom).reshape(b, s, hq, d).astype(q0.dtype)


def gqa_attention_quantized_segments(
    q: jnp.ndarray,
    segments: Sequence[Tuple[jnp.ndarray, ...]],
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """As :func:`gqa_attention_segments` for int8 head-major segments.

    Each segment is ``(k_q, ks, v_q, vs, valid)`` with ``k_q``/``v_q`` int8
    ``[B, Hkv, Ti, D]``, ``ks``/``vs`` f32 ``[B, Hkv, Ti]``, ``valid``
    ``[B, Ti]``. Scales apply to scores/probs (see
    :func:`gqa_attention_quantized`), so the int8 buffers feed the matmuls
    directly. Delegates to the general shared-query-free form.
    """
    return gqa_attention_quantized_multi_q_segments(
        [
            (q, k_q, ks, v_q, vs, valid[:, None, :])
            for k_q, ks, v_q, vs, valid in segments
        ],
        scale,
    )


def merge_softmax_segments(
    q: jnp.ndarray,
    out_a: jnp.ndarray,
    m_a: jnp.ndarray,
    l_a: jnp.ndarray,
    k_tail: jnp.ndarray,
    v_tail: jnp.ndarray,
    tail_valid: jnp.ndarray,
    scale: Optional[float] = None,
) -> jnp.ndarray:
    """Joint softmax of a PRE-COMPUTED attention segment with a small tail.

    ``out_a`` (``[B, 1, Hq, D]``, already normalized) with online-softmax
    stats ``m_a``/``l_a`` (``[B, Hkv, G]``) comes from a kernel that swept
    its own keys (the paged pool); the tail segment (``k_tail``/``v_tail``
    ``[B, K, Hkv, D]`` time-major, ``tail_valid`` ``[B, K]``) holds the
    fused decode steps' fresh tokens. Flash-attention-style merge: exact,
    not an approximation.
    """
    b, s, hq, d = q.shape
    hkv = k_tail.shape[2]
    g = hq // hkv
    if scale is None:
        scale = d**-0.5
    qg = q.reshape(b, s, hkv, g, d)

    sc = jnp.einsum(
        "bskgd,btkd->bkgst", qg, k_tail, preferred_element_type=jnp.float32
    ) * scale                                            # [B, Hkv, G, 1, K]
    mask = tail_valid[:, None, None, None, :]
    sc = jnp.where(mask, sc, _NEG_INF)
    m_t = jnp.max(sc, axis=-1)                           # [B, Hkv, G, 1]
    w = jnp.where(mask, jnp.exp(sc - m_t[..., None]), 0.0)
    l_t = jnp.sum(w, axis=-1)                            # [B, Hkv, G, 1]
    pv_t = jnp.einsum(
        "bkgst,btkd->bskgd", w.astype(v_tail.dtype), v_tail,
        preferred_element_type=jnp.float32,
    )                                                    # [B, 1, Hkv, G, D]
    out_t = pv_t / jnp.maximum(l_t, 1e-20).reshape(b, 1, hkv, g, 1)

    m_t = m_t[..., 0]
    l_t = l_t[..., 0]
    m = jnp.maximum(m_a, m_t)                            # [B, Hkv, G]
    w_a = l_a * jnp.exp(m_a - m)
    w_t = l_t * jnp.exp(m_t - m)
    denom = jnp.maximum(w_a + w_t, 1e-20)
    fa = (w_a / denom)[:, None, :, :, None]
    ft = (w_t / denom)[:, None, :, :, None]
    out = (
        out_a.reshape(b, s, hkv, g, d).astype(jnp.float32) * fa
        + out_t * ft
    )
    return out.reshape(b, s, hq, d).astype(q.dtype)
