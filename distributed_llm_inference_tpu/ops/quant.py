"""Weight-only int8 / int4 quantization for bandwidth-bound decode.

TPU-native replacement for the reference's bitsandbytes ``Linear8bitLt`` swap
(``/root/reference/distributed_llm_inference/utils/model.py:93-123``, CUDA-only
guard at ``:117-118``). Instead of a module-tree surgery, quantization is a
pytree transform: each projection matrix becomes a :class:`QuantizedTensor`
(int8 values + per-output-channel fp scales) or :class:`QuantizedTensor4`
(int4 values + per-(input-group, output-channel) scales), and the matmul
helper dequantizes in-kernel.

Why weight-only symmetric int8: decode is HBM-bandwidth-bound (the whole
weight set is read once per token), so halving weight bytes ≈ doubles decode
throughput and frees HBM for larger batches; XLA fuses the
``int8→bf16 convert × scale`` into the matmul's operand read, so there is no
extra memory pass. A true int8×int8 MXU path (dynamic per-token activation
scales, AQT-style) is the prefill compute optimization — weight-only keeps
activations in bf16 and loses no MXU throughput at decode shapes.

int4 halves weight bytes again (XLA packs two ``s4`` values per byte on TPU)
at the cost of per-group scales: a per-output-channel scale alone is too
coarse at 4 bits, so the input dimension is split into groups of
``group_size`` (AWQ/GPTQ-style) and each (group, out-channel) pair gets its
own scale; the matmul computes per-group partial sums and scales them before
reduction, keeping the int4→bf16 convert fused into the operand read.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from flax import struct

__all__ = [
    "QuantizedTensor",
    "QuantizedTensorOutlier",
    "QuantizedTensor4",
    "QuantizedTensor4Split",
    "QuantizedTensor4SplitView",
    "quantize_int8",
    "quantize_int8_outlier",
    "quantize_int4",
    "quantize_int4_split",
    "matmul",
    "quantize_params",
    "QUANTIZED_WEIGHTS",
    "INT4_WEIGHTS",
]

# Layer-stack weights worth quantizing (the large matmuls). Norm gains and
# biases stay in bf16 — they are O(hidden) and scale-sensitive.
QUANTIZED_WEIGHTS = (
    "wq", "wk", "wv", "wo", "wg", "wu", "wd",  # dense attention + MLP
    "we_g", "we_u", "we_d",                    # MoE experts
    "lm_head",
)

# Weights eligible for group-wise int4 (plain ``x @ w`` projections).
INT4_WEIGHTS = ("wq", "wk", "wv", "wo", "wg", "wu", "wd", "lm_head")


class QuantizedTensor(struct.PyTreeNode):
    """``q``: int8 values, original shape ``[..., in, out]``; ``scale``: fp
    per-output-channel scales, shape ``[..., out]`` (leading dims = layer
    stack / experts)."""

    q: jax.Array
    scale: jax.Array

    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):
        return self.scale.dtype


class QuantizedTensorOutlier(struct.PyTreeNode):
    """Mixed-precision int8: LLM.int8()-style outlier decomposition.

    bitsandbytes keeps outlier features in fp16 next to the int8 body
    (``Linear8bitLt(threshold=5.0)``, the reference's serving-node swap at
    ``/root/reference/distributed_llm_inference/utils/model.py:102-108``) —
    the handful of activation channels with huge magnitudes otherwise
    dominate the per-channel scale and crush the resolution of everything
    else. TPU-native form: a FIXED number of input channels (static shape —
    a data-dependent threshold would make the weight layout dynamic under
    ``jit``) are carried at full precision and ZEROED in the int8 body;
    the matmul adds ``x[..., idx] @ outlier_w`` back, a [rows, K] x
    [K, out] side matmul whose cost is noise for K ≈ 32 next to the int8
    sweep. Channel choice: calibration activation scales when provided,
    weight-column energy otherwise (quantize_int8_outlier).

    ``q``/``scale``: as :class:`QuantizedTensor` (outlier rows zeroed);
    ``outlier_idx``: int32 ``[..., K]`` input-channel indices;
    ``outlier_w``: fp ``[..., K, out]`` original rows.
    """

    q: jax.Array
    scale: jax.Array
    outlier_idx: jax.Array
    outlier_w: jax.Array

    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):
        return self.outlier_w.dtype


def quantize_int8_outlier(
    w: jax.Array,
    num_outliers: int = 32,
    act_scales: Optional[jax.Array] = None,
    scale_dtype=jnp.bfloat16,
) -> QuantizedTensorOutlier:
    """Outlier-decomposed symmetric int8 of ``[..., in, out]``.

    ``act_scales`` (``[..., in]`` per-input-channel activation absmax from a
    calibration pass) selects the channels the way LLM.int8() does — by the
    ACTIVATIONS that flow through them; without calibration the fallback
    proxy is weight-row energy (the rows whose magnitude dominates the
    column absmax and therefore the quantization step)."""
    *lead, in_dim, out = w.shape
    k = min(num_outliers, in_dim)
    wf = w.astype(jnp.float32)
    score = (
        act_scales.astype(jnp.float32)
        if act_scales is not None
        else jnp.max(jnp.abs(wf), axis=-1)
    )  # [..., in]
    # A shared per-channel calibration vector ([in]) broadcasts across a
    # stacked projection's lead (layer) axes.
    score = jnp.broadcast_to(jnp.asarray(score), (*lead, in_dim))
    _, idx = jax.lax.top_k(score, k)  # [..., k]
    outlier_w = jnp.take_along_axis(wf, idx[..., None], axis=-2)
    mask = jnp.any(
        jnp.arange(in_dim) == idx[..., :, None], axis=-2
    )  # [..., in]
    body = jnp.where(mask[..., None], 0.0, wf)
    amax = jnp.max(jnp.abs(body), axis=-2, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(body / scale), -127, 127).astype(jnp.int8)
    return QuantizedTensorOutlier(
        q=q,
        scale=scale.squeeze(-2).astype(scale_dtype),
        outlier_idx=idx.astype(jnp.int32),
        outlier_w=outlier_w.astype(scale_dtype),
    )


def _unpack_nibbles(q: jax.Array):
    """``(low, high)`` int4-valued int8 halves of a nibble-packed byte via
    arithmetic shift-and-sign-extend. The ONLY sanctioned unpack:
    ``lax.bitcast_convert_type`` to int4 reads the nibbles differently on
    XLA:TPU than on CPU (cos ≈ -0.3 vs the fp reference on a real v5e —
    caught by tools/quant_accuracy.py in r4)."""
    lo = jnp.right_shift(jnp.left_shift(q, jnp.int8(4)), jnp.int8(4))
    hi = jnp.right_shift(q, jnp.int8(4))
    return lo, hi


class QuantizedTensor4(struct.PyTreeNode):
    """int4 weight with per-(input-group, output-channel) scales.

    ``q``: **nibble-packed int8** ``[..., G, group_size, out // 2]`` — two
    adjacent output channels per byte (even channel in the low nibble). The
    int8 container keeps the pytree leaf a universally supported dtype (the
    tunneled TPU platform can't transfer ``s4`` arrays across the jit
    boundary); :func:`matmul` unpacks the nibbles ARITHMETICALLY
    (shift + sign-extend, fused into the operand read) — HBM traffic is the
    packed half byte per value. ``lax.bitcast_convert_type`` to ``int4``
    must NOT be used here: XLA:TPU interprets the nibbles differently from
    CPU (measured cos ≈ -0.3 against the fp reference on a real v5e, exact
    on CPU — caught by ``tools/quant_accuracy.py`` in r4). ``scale``: fp
    ``[..., G, out]``. ``shape`` reports the logical ``[..., in, out]``.
    """

    q: jax.Array
    scale: jax.Array

    @property
    def shape(self):
        *lead, g, gs, out_packed = self.q.shape
        return (*lead, g * gs, out_packed * 2)

    @property
    def dtype(self):
        return self.scale.dtype

    def unpack(self) -> jax.Array:
        """In-graph int4-valued int8 view ``[..., G, gs, out]`` (low nibble
        = even channel), via arithmetic shift-and-sign-extend — portable
        across CPU and TPU (the int4 bitcast is not; see class docstring)."""
        *lead, g, gs, out_packed = self.q.shape
        lo, hi = _unpack_nibbles(self.q)
        return jnp.stack([lo, hi], axis=-1).reshape(
            *lead, g, gs, out_packed * 2
        )


class QuantizedTensor4Split(struct.PyTreeNode):
    """int4 weight in the Pallas decode-matmul layout (half-split packing).

    ``q``: int8 ``[..., in_pad, out_pad // 2]`` — byte column ``j`` holds
    channel ``j`` (low nibble) and channel ``j + out_pad/2`` (high nibble);
    padded to the kernel's tile multiples at quantization time (see
    ``ops/quant_matmul.py``). ``scale_lo``/``scale_hi``: f32
    ``[..., 1, out_pad // 2]`` per-output-channel scales for the two halves —
    stored PRE-SPLIT so the kernel call slices nothing per step (a
    ``[2, outp]`` array would need per-call row slices that XLA materializes,
    and a (1, x) block of a 2-row array is not a legal Mosaic tile). Coarser
    than :class:`QuantizedTensor4`'s grouped scales (per-channel only) but
    decode reads stream straight through the MXU kernel — this is the
    throughput configuration; grouped pair-packing is the accuracy
    configuration.
    """

    q: jax.Array
    scale_lo: jax.Array
    scale_hi: jax.Array
    in_dim: int = struct.field(pytree_node=False, default=0)
    out_dim: int = struct.field(pytree_node=False, default=0)

    @property
    def shape(self):
        return (*self.q.shape[:-2], self.in_dim, self.out_dim)

    @property
    def dtype(self):
        return self.scale_lo.dtype

    def full_scale(self) -> jax.Array:
        """``[..., out_pad]`` concatenated per-channel scales (fallback /
        oracle paths)."""
        return jnp.concatenate(
            [self.scale_lo, self.scale_hi], axis=-1
        ).reshape(*self.q.shape[:-2], -1)


class QuantizedTensor4SplitView(struct.PyTreeNode):
    """One layer's int4 weight, VIEWED out of the layer-stacked tensor with
    a traced ``layer`` index instead of being sliced.

    Why this exists: inside ``lax.scan`` over layers, slicing a
    :class:`QuantizedTensor4Split` leaf out of the ``[L, ...]`` stack to
    feed the Pallas matmul materializes a full HBM copy of that layer's
    packed weight every (layer, step) — XLA cannot fuse a dynamic-slice
    into a custom call operand. That copy traffic (read + write + re-read ≈
    3x the weight bytes) is exactly why the int4 deployment measured SLOWER
    than int8 despite reading half the bytes. The view keeps the whole
    stack as the kernel operand and folds ``layer`` into the block index
    map (same pattern as the whole-stack KV kernels, quant_attention.py).
    """

    q: jax.Array         # [L, in_pad, out_pad // 2] int8
    scale_lo: jax.Array  # [L, 1, out_pad // 2] f32
    scale_hi: jax.Array  # [L, 1, out_pad // 2] f32
    layer: jax.Array     # scalar int32 (traced)
    in_dim: int = struct.field(pytree_node=False, default=0)
    out_dim: int = struct.field(pytree_node=False, default=0)

    @property
    def shape(self):
        return (self.in_dim, self.out_dim)

    @property
    def dtype(self):
        return self.scale_lo.dtype


def quantize_int4_split(w: jax.Array) -> QuantizedTensor4Split:
    """Symmetric per-output-channel int4 in the half-split Pallas layout.

    Scales are always f32: the kernel accumulates in f32 and multiplies the
    scales in at the epilogue, so there is no bf16 round trip to save, and
    per-channel scale bytes are noise next to the packed weights.
    """
    from .quant_matmul import pack_int4_split

    *lead, in_dim, out = w.shape
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=-2, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 7.0
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -7, 7).astype(
        jnp.int8
    )
    packed = pack_int4_split(q)
    out_pad = packed.shape[-1] * 2
    sc = jnp.pad(
        scale.squeeze(-2).astype(jnp.float32),
        [(0, 0)] * len(lead) + [(0, out_pad - out)],
    )
    half = out_pad // 2
    return QuantizedTensor4Split(
        q=packed,
        scale_lo=sc[..., None, :half],
        scale_hi=sc[..., None, half:],
        in_dim=in_dim,
        out_dim=out,
    )


def quantize_int8(w: jax.Array, scale_dtype=jnp.bfloat16) -> QuantizedTensor:
    """Symmetric per-output-channel int8 quantization of ``[..., in, out]``."""
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=-2, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127, 127).astype(
        jnp.int8
    )
    return QuantizedTensor(q=q, scale=scale.squeeze(-2).astype(scale_dtype))


def quantize_int4(
    w: jax.Array, group_size: Optional[int] = 128, scale_dtype=jnp.bfloat16
) -> QuantizedTensor4:
    """Symmetric group-wise int4 quantization of ``[..., in, out]``.

    ``in`` must be divisible by ``group_size`` (true for every transformer
    projection at real model shapes; pad otherwise before calling).
    ``group_size=None`` uses one group (per-output-channel scales only):
    fastest decode (a single ungrouped matmul) but coarser quantization —
    prefer grouped scales for accuracy-sensitive serving.
    """
    *lead, in_dim, out = w.shape
    if group_size is None:
        group_size = in_dim
    if in_dim % group_size:
        raise ValueError(f"in dim {in_dim} not divisible by group {group_size}")
    if out % 2:
        raise ValueError(f"out dim {out} must be even (nibble packing)")
    g = in_dim // group_size
    wf = w.astype(jnp.float32).reshape(*lead, g, group_size, out)
    amax = jnp.max(jnp.abs(wf), axis=-2, keepdims=True)  # [..., G, 1, out]
    scale = jnp.maximum(amax, 1e-8) / 7.0
    q = jnp.clip(jnp.round(wf / scale), -7, 7).astype(jnp.int8)
    # Pack adjacent output channels: even → low nibble, odd → high nibble
    # (matches the little-endian pair order of bitcast int8 → int4[..., 2]).
    lo = jnp.bitwise_and(q[..., 0::2], jnp.int8(0x0F))
    hi = jnp.left_shift(q[..., 1::2], jnp.int8(4))
    return QuantizedTensor4(
        q=jnp.bitwise_or(lo, hi), scale=scale.squeeze(-2).astype(scale_dtype)
    )


# Prefill calls (>= this many sequence positions) against int8 weights run
# int8 x int8 on the MXU with dynamic per-token activation scales (AQT
# style) instead of dequantizing the weight into a bf16 matmul: the int8
# systolic path has 2x the bf16 peak on v5e, and at prefill row counts the
# per-token abs-max/round VPU work amortizes. Measured 8B-shape prefill
# device time (r5, b1): S=512 213 → 93 ms, S=2048 1109 → 743 ms, S=128
# 42.8 → 39.4 ms. Decode (S == 1) and short verifies keep the weight-only
# path: they are HBM-bound, and W8A8 would change their numerics for no
# throughput.
ACT_QUANT_PREFILL = True
ACT_QUANT_MIN_SEQ = 128


def w8a8_matmul(x: jax.Array, w: QuantizedTensor) -> jax.Array:
    """int8 x int8 MXU matmul with dynamic symmetric per-token activation
    scales: ``y = (q_x @ q_w) * x_scale * w_scale``. The int32 accumulator
    is exact and the scales are applied in f32 BEFORE the cast to the
    activation dtype (casting the ~1e5-magnitude accumulator to bf16 first
    would round away ~2^-9 relative); the only additional quantization
    error vs weight-only int8 is the activations' own rounding — per-token
    scales keep the combined matmul error ~1% relative
    (tests/test_quant.py::test_w8a8_matmul_close_to_fp)."""
    amax = jnp.max(jnp.abs(x).astype(jnp.float32), axis=-1, keepdims=True)
    xs = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(
        jnp.round(x.astype(jnp.float32) / xs), -127, 127
    ).astype(jnp.int8)
    y = jax.lax.dot_general(
        q, w.q, (((x.ndim - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.int32,
    )
    return (
        y.astype(jnp.float32) * xs * w.scale.astype(jnp.float32)
    ).astype(x.dtype)


def matmul(x: jax.Array, w) -> jax.Array:
    """``x @ w`` that transparently handles quantized weights.

    For a :class:`QuantizedTensor`, computes ``(x @ q) * scale`` with the
    int8→bf16 convert fused into the matmul operand read by XLA — except
    prefill-shaped calls on TPU, which take :func:`w8a8_matmul`'s int8 MXU
    path (see ``ACT_QUANT_PREFILL``). For a :class:`QuantizedTensor4`,
    per-group partial sums are scaled before the group reduction.
    """
    if (
        ACT_QUANT_PREFILL
        and isinstance(w, QuantizedTensor)
        and w.q.ndim == 2
        and x.ndim >= 3
        and x.shape[-2] >= ACT_QUANT_MIN_SEQ
        and jax.default_backend() == "tpu"
    ):
        return w8a8_matmul(x, w)
    if isinstance(w, QuantizedTensorOutlier):
        y = (x @ w.q.astype(x.dtype)) * w.scale.astype(x.dtype)
        xo = jnp.take(x, w.outlier_idx, axis=-1)
        return y + xo @ w.outlier_w.astype(x.dtype)
    if isinstance(w, QuantizedTensor):
        y = x @ w.q.astype(x.dtype)
        return y * w.scale.astype(x.dtype)
    if isinstance(w, QuantizedTensor4SplitView):
        import numpy as np

        from .quant_matmul import int4_matmul_stacked, unpack_int4_split

        rows = int(np.prod(x.shape[:-1]))
        # Decode (S == 1) takes the stacked kernel at ANY batch — the
        # row-count heuristic alone would route large-batch decode (e.g.
        # b384 GQA serving) to the slice path and reintroduce the
        # per-(layer, step) weight copy this view exists to remove. The
        # row threshold only gates genuine many-row prefill, where the
        # XLA unpack amortizes and MXU shapes are already efficient.
        decode = x.ndim >= 3 and x.shape[-2] == 1
        if decode or rows <= 256:
            return int4_matmul_stacked(
                x, w.q, w.scale_lo, w.scale_hi, w.layer, w.out_dim
            )
        # Many-row (prefill) calls: slice the layer (amortized over rows)
        # and run the plain XLA dequant matmul.
        wq = jax.lax.dynamic_index_in_dim(w.q, w.layer, 0, keepdims=False)
        slo = jax.lax.dynamic_index_in_dim(
            w.scale_lo, w.layer, 0, keepdims=False
        )
        shi = jax.lax.dynamic_index_in_dim(
            w.scale_hi, w.layer, 0, keepdims=False
        )
        w4 = unpack_int4_split(wq)[: x.shape[-1]]
        y = x @ w4.astype(x.dtype)
        sc = jnp.concatenate([slo, shi], axis=-1).reshape(-1)
        return (y * sc.astype(x.dtype))[..., : w.out_dim]
    if isinstance(w, QuantizedTensor4Split):
        import numpy as np

        from .quant_matmul import int4_matmul, unpack_int4_split

        if w.q.ndim != 2:
            raise ValueError(
                "QuantizedTensor4Split matmul expects a per-layer 2D packed "
                f"weight (scan-sliced), got shape {w.q.shape}"
            )
        rows = int(np.prod(x.shape[:-1]))
        if rows <= 256:
            return int4_matmul(x, w.q, w.scale_lo, w.scale_hi, w.out_dim)
        # Many-row (prefill) calls: plain XLA dequant matmul — the unpack is
        # amortized over the rows and the MXU shape is already efficient.
        w4 = unpack_int4_split(w.q)[: x.shape[-1]]
        y = x @ w4.astype(x.dtype)
        return (y * w.full_scale().astype(x.dtype))[..., : w.out_dim]
    if isinstance(w, QuantizedTensor4):
        g, gs, outp = w.q.shape[-3:]
        # Unpack nibbles ARITHMETICALLY (shift-and-sign-extend), not via
        # bitcast_convert_type(int4): the int4 bitcast produces a DIFFERENT
        # nibble interpretation on XLA:TPU than on CPU — measured cos ≈ -0.3
        # against the fp reference at every width on a real v5e while CPU was
        # exact (caught by the r4 accuracy harness; the split/Pallas layout
        # was unaffected, so perf phases never saw it). Two half-matmuls with
        # the int8->bf16 convert fused into the operand read replace it.
        lo, hi = _unpack_nibbles(w.q)
        xg = x.reshape(*x.shape[:-1], g, gs).astype(jnp.float32)
        # f32 operands: full-precision group accumulation (this is the
        # ACCURACY configuration), and XLA:CPU's dot thunk rejects
        # bf16 x bf16 -> f32.
        part = jnp.stack(
            [
                jnp.einsum(
                    "...gi,gio->...go", xg, h.astype(jnp.float32),
                    preferred_element_type=jnp.float32,
                )
                for h in (lo, hi)
            ],
            axis=-1,
        )  # [..., G, outp, 2]
        sc = w.scale.reshape(*w.scale.shape[:-1], outp, 2).astype(jnp.float32)
        y = jnp.sum(part * sc, axis=-3)  # reduce groups
        return y.reshape(*y.shape[:-2], outp * 2).astype(x.dtype)
    return x @ w


def einsum(spec: str, x: jax.Array, w) -> jax.Array:
    """``jnp.einsum`` that transparently handles quantized weights.

    Requires the weight's non-contracted subscripts to appear LAST in the
    output (true for the MoE einsums here), so the ``[..., out]`` scale
    broadcasts against the result's trailing dims.
    """
    if isinstance(w, QuantizedTensor):
        y = jnp.einsum(spec, x, w.q.astype(x.dtype))
        return y * w.scale.astype(x.dtype)
    return jnp.einsum(spec, x, w)


def quantize_params(
    params: Dict[str, Any],
    names=QUANTIZED_WEIGHTS,
    scale_dtype=jnp.bfloat16,
    bits: int = 8,
    group_size: int = 128,
    int4_layout: str = "grouped",
    group_multiple: int = 1,
    outlier_channels: int = 0,
    act_scales: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """Quantize the named weights in a param pytree (full-model or block-only);
    everything else passes through unchanged.

    ``bits=4`` uses int4 for the dense projections (:data:`INT4_WEIGHTS`);
    MoE expert stacks stay int8 (the ``einsum`` helper's scale broadcast
    doesn't cover grouped contraction). ``int4_layout``: "grouped" =
    pair-packed group-wise scales (accuracy configuration, XLA path; group
    size degrades to ``gcd(group_size, in_dim)`` so small test shapes
    divide); "split" = half-split per-channel layout consumed by the Pallas
    decode matmul (throughput configuration, ``ops/quant_matmul.py``).
    ``group_multiple``: force the group COUNT divisible by this — tp-sharded
    serving puts the contracted-axis sharding on the group axis (whole groups
    per device, ``parallel/tp.py``), so engines pass their tp degree.
    ``outlier_channels > 0`` (bits=8) switches the dense projections to the
    LLM.int8()-style outlier decomposition (:func:`quantize_int8_outlier`,
    the reference's ``threshold=5.0`` capability) with that many fp
    channels; ``act_scales`` optionally maps weight name → per-input-channel
    calibration activation absmax. MoE expert stacks stay plain int8 (the
    grouped-expert einsum has no outlier side-path).
    """
    if bits not in (4, 8):
        raise ValueError(f"bits must be 4 or 8, got {bits}")
    if int4_layout not in ("grouped", "split"):
        raise ValueError(f"unknown int4_layout {int4_layout!r}")

    def quantize_one(name, w):
        if bits == 4 and name in INT4_WEIGHTS and w.shape[-1] % 2 == 0:
            if int4_layout == "split":
                return quantize_int4_split(w)
            gs = math.gcd(group_size, w.shape[-2])
            while gs > 1 and (w.shape[-2] // gs) % group_multiple:
                gs //= 2
            return quantize_int4(w, gs, scale_dtype)
        if outlier_channels > 0 and name in INT4_WEIGHTS:
            return quantize_int8_outlier(
                w, outlier_channels,
                (act_scales or {}).get(name), scale_dtype,
            )
        return quantize_int8(w, scale_dtype)

    out: Dict[str, Any] = {}
    for k, v in params.items():
        if k == "layers":
            out[k] = {
                n: quantize_one(n, w) if n in names else w
                for n, w in v.items()
            }
        elif k in names:
            out[k] = quantize_one(k, v)
        else:
            out[k] = v
    return out
