"""Weight-only int8 quantization for bandwidth-bound decode.

TPU-native replacement for the reference's bitsandbytes ``Linear8bitLt`` swap
(``/root/reference/distributed_llm_inference/utils/model.py:93-123``, CUDA-only
guard at ``:117-118``). Instead of a module-tree surgery, quantization is a
pytree transform: each projection matrix becomes a :class:`QuantizedTensor`
(int8 values + per-output-channel fp scales), and the matmul helper
dequantizes in-kernel.

Why weight-only symmetric int8: decode is HBM-bandwidth-bound (the whole
weight set is read once per token), so halving weight bytes ≈ doubles decode
throughput and frees HBM for larger batches; XLA fuses the
``int8→bf16 convert × scale`` into the matmul's operand read, so there is no
extra memory pass. A true int8×int8 MXU path (dynamic per-token activation
scales, AQT-style) is the prefill compute optimization — weight-only keeps
activations in bf16 and loses no MXU throughput at decode shapes.
"""

from __future__ import annotations

from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from flax import struct

__all__ = [
    "QuantizedTensor",
    "quantize_int8",
    "matmul",
    "quantize_params",
    "QUANTIZED_WEIGHTS",
]

# Layer-stack weights worth quantizing (the large matmuls). Norm gains and
# biases stay in bf16 — they are O(hidden) and scale-sensitive.
QUANTIZED_WEIGHTS = (
    "wq", "wk", "wv", "wo", "wg", "wu", "wd",  # dense attention + MLP
    "we_g", "we_u", "we_d",                    # MoE experts
    "lm_head",
)


class QuantizedTensor(struct.PyTreeNode):
    """``q``: int8 values, original shape ``[..., in, out]``; ``scale``: fp
    per-output-channel scales, shape ``[..., out]`` (leading dims = layer
    stack / experts)."""

    q: jax.Array
    scale: jax.Array

    @property
    def shape(self):
        return self.q.shape

    @property
    def dtype(self):
        return self.scale.dtype


def quantize_int8(w: jax.Array, scale_dtype=jnp.bfloat16) -> QuantizedTensor:
    """Symmetric per-output-channel int8 quantization of ``[..., in, out]``."""
    amax = jnp.max(jnp.abs(w.astype(jnp.float32)), axis=-2, keepdims=True)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.round(w.astype(jnp.float32) / scale), -127, 127).astype(
        jnp.int8
    )
    return QuantizedTensor(q=q, scale=scale.squeeze(-2).astype(scale_dtype))


def matmul(x: jax.Array, w) -> jax.Array:
    """``x @ w`` that transparently handles quantized weights.

    For a :class:`QuantizedTensor`, computes ``(x @ q) * scale`` with the
    int8→bf16 convert fused into the matmul operand read by XLA.
    """
    if isinstance(w, QuantizedTensor):
        y = x @ w.q.astype(x.dtype)
        return y * w.scale.astype(x.dtype)
    return x @ w


def einsum(spec: str, x: jax.Array, w) -> jax.Array:
    """``jnp.einsum`` that transparently handles quantized weights.

    Requires the weight's non-contracted subscripts to appear LAST in the
    output (true for the MoE einsums here), so the ``[..., out]`` scale
    broadcasts against the result's trailing dims.
    """
    if isinstance(w, QuantizedTensor):
        y = jnp.einsum(spec, x, w.q.astype(x.dtype))
        return y * w.scale.astype(x.dtype)
    return jnp.einsum(spec, x, w)


def quantize_params(
    params: Dict[str, Any], names=QUANTIZED_WEIGHTS, scale_dtype=jnp.bfloat16
) -> Dict[str, Any]:
    """Quantize the named weights in a param pytree (full-model or block-only);
    everything else passes through unchanged."""
    out: Dict[str, Any] = {}
    for k, v in params.items():
        if k == "layers":
            out[k] = {
                n: quantize_int8(w, scale_dtype) if n in names else w
                for n, w in v.items()
            }
        elif k in names:
            out[k] = quantize_int8(v, scale_dtype)
        else:
            out[k] = v
    return out
