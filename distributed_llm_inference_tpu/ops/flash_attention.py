"""Pallas flash-attention kernel (prefill hot path).

The Pallas realization of the attention the reference computes eagerly —
QK^T, additive mask, fp32 softmax, PV with a materialized ``[B, H, S, T]``
score tensor (``/root/reference/distributed_llm_inference/models/llama/
modules.py:87-97``). Flash tiling never materializes scores in HBM: the grid
walks (batch, kv-head, q-block, kv-block) with the online-softmax running
max/denominator and the output accumulator living in VMEM scratch, carried
across the kv-block grid dimension (TPU grids iterate the last axis
innermost, so scratch persists across the kv sweep for one q-block).

GQA is folded into the matmul rows: the ``G = Hq/Hkv`` query heads sharing a
kv head are flattened into the q-block's row dimension, so every MXU call
contracts ``[BQ*G, D] x [D, BK]`` — the ``repeat_kv`` HBM expansion of the
reference (``modules.py:87-88``) never exists.

Same signature as :func:`ops.attention.gqa_attention` (the XLA fallback and
test oracle): boolean mask carries causality, cache validity, sliding window,
and sink structure, so every cache policy works unchanged. Runs in interpret
mode off-TPU, making the kernel testable on the CPU mesh.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .attention import _NEG_INF, gqa_attention

__all__ = ["flash_attention"]


def _flash_kernel(
    q_ref,      # [1, 1, BQ, G, D]
    k_ref,      # [1, 1, BK, D]
    v_ref,      # [1, 1, BK, D]
    mask_ref,   # [1, BQ, BK] bool
    out_ref,    # [1, 1, BQ, G, D]
    acc_ref,    # VMEM [BQ*G, D] f32
    m_ref,      # VMEM [BQ*G, 128] f32 (stats broadcast across lanes)
    l_ref,      # VMEM [BQ*G, 128] f32
    *,
    scale: float,
    num_k_blocks: int,
):
    ki = pl.program_id(3)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    _, _, bq, g, d = q_ref.shape
    bk = k_ref.shape[2]
    rows = bq * g

    q = q_ref[0, 0].reshape(rows, d)
    k = k_ref[0, 0]
    v = v_ref[0, 0]

    # [BQ*G, BK] scores on the MXU, fp32.
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
    ) * scale
    mask = jnp.repeat(mask_ref[0], g, axis=0)  # [BQ, BK] -> [BQ*G, BK]
    s = jnp.where(mask, s, _NEG_INF)

    m_prev = m_ref[:, :1]  # [rows, 1]
    l_prev = l_ref[:, :1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.where(mask, jnp.exp(s - m_new), 0.0)

    l_new = alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True)
    acc_ref[:] = acc_ref[:] * alpha + jax.lax.dot_general(
        p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
    l_ref[:] = jnp.broadcast_to(l_new, l_ref.shape)

    @pl.when(ki == num_k_blocks - 1)
    def _finalize():
        # Fully-masked rows (query padding) have l == 0 -> emit zeros.
        l = l_ref[:, :1]
        out = acc_ref[:] / jnp.maximum(l, 1e-20)
        out_ref[0, 0] = out.reshape(bq, g, d).astype(out_ref.dtype)


def flash_attention(
    q: jnp.ndarray,
    k: jnp.ndarray,
    v: jnp.ndarray,
    mask: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """Drop-in for :func:`gqa_attention` on shapes the tiling accepts;
    delegates to the XLA path otherwise (decode steps, ragged tiles).

    ``q``: ``[B, S, Hq, D]``; ``k``/``v``: ``[B, T, Hkv, D]``;
    ``mask``: bool ``[B, S, T]`` (True = attend).
    """
    b, s, hq, d = q.shape
    t, hkv = k.shape[1], k.shape[2]
    g = hq // hkv
    if scale is None:
        scale = d**-0.5

    bq = min(block_q, s)
    bk = min(block_k, t)
    # Tiling preconditions; anything else takes the always-correct XLA path
    # (notably S == 1 decode, whose attention is bandwidth-trivial).
    if s % bq or t % bk or s < 8 or mask is None or mask.ndim != 3:
        return gqa_attention(q, k, v, mask, scale)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    # [B, Hkv, S, G, D]: kv-head-major so one grid cell's q rows are the G
    # query heads of one kv head.
    qr = q.reshape(b, s, hkv, g, d).transpose(0, 2, 1, 3, 4)
    kr = k.transpose(0, 2, 1, 3)  # [B, Hkv, T, D]
    vr = v.transpose(0, 2, 1, 3)

    grid = (b, hkv, s // bq, t // bk)
    kernel = functools.partial(
        _flash_kernel, scale=scale, num_k_blocks=t // bk
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, hkv, s, g, d), q.dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, 1, bq, g, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, 1, bk, d), lambda bi, hi, qi, ki: (bi, hi, ki, 0)),
            pl.BlockSpec((1, bq, bk), lambda bi, hi, qi, ki: (bi, qi, ki)),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, bq, g, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((bq * g, d), jnp.float32),
            pltpu.VMEM((bq * g, 128), jnp.float32),
            pltpu.VMEM((bq * g, 128), jnp.float32),
        ],
        interpret=interpret,
    )(qr, kr, vr, mask)
    # [B, Hkv, S, G, D] -> [B, S, Hq, D]
    return out.transpose(0, 2, 1, 3, 4).reshape(b, s, hq, d)
