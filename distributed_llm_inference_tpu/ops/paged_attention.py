"""Pallas paged-attention kernel (decode hot path).

The decode-side companion of ``flash_attention.py`` (SURVEY §7 step 4): at
decode the XLA path first gathers every session's pages into a contiguous
``[B, max_len, Hkv, D]`` view (``cache/paged.py:update_and_gather``) — a full
copy of the active KV working set through HBM per layer per token. This kernel
instead reads K/V **in place** from the page pool: the grid walks
``(batch, page)`` with the page table riding as a scalar-prefetch operand, so
each step DMAs one whole physical page (all KV heads — ``[Hkv, PS, D]``, a
megabyte-scale contiguous block) straight from where it lives (the TPU analog
of vLLM's paged attention; the reference's multi-tenancy never got past a
dict of growing tensors,
``/root/reference/distributed_llm_inference/models/llama/cache.py:14-19``).

Bandwidth properties:
* no materialized contiguous copy — pages stream through VMEM once;
* page blocks past a row's live length are clamped to the null page 0 in the
  index map, so short rows in a long-table batch fetch (cheap, cached) zeros
  instead of the whole table span — the dense cache by contrast always reads
  its full padded buffer;
* MHA (``G == 1``) uses a VPU multiply-reduce for QK^T and PV — a 1-row MXU
  matmul per head wastes the systolic array; GQA (``G > 1``) uses
  ``Hkv``-batched ``dot_general``.

Online-softmax state (running max / denominator / accumulator) lives in VMEM
scratch carried across the page-grid axis (innermost ⇒ scratch persists
across one row's page sweep). The per-row (m, l) stats are ALSO emitted so
callers can merge this segment with others under one joint softmax — the
write-behind-tail decode (``models/llama.py:multi_decode_apply``) combines
the pool segment with the small tail segment that holds the fused steps' new
tokens.

``q_positions`` decouples the query's absolute position from the pool length:
in the tail regime the query sits ``tail_len`` tokens PAST the pool contents
(sliding-window masking needs the true position; plain causality over the
pool is just slot validity either way).

Runs in interpret mode off-TPU so the CPU test mesh exercises it.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .attention import _NEG_INF

__all__ = ["paged_attention", "quantized_paged_attention"]


def _paged_kernel(
    table_ref,  # SMEM [B, T] int32 (scalar prefetch)
    len_ref,    # SMEM [B] int32 (scalar prefetch)
    qpos_ref,   # SMEM [B] int32 (scalar prefetch): query's absolute position
    q_ref,      # [1, Hkv, G, D]
    k_ref,      # [1, Hkv, PS, D]
    v_ref,      # [1, Hkv, PS, D]
    out_ref,    # [1, Hkv, G, D]
    m_out_ref,  # [1, Hkv*G, 128] f32
    l_out_ref,  # [1, Hkv*G, 128] f32
    acc_ref,    # VMEM [Hkv*G, D] f32
    m_ref,      # VMEM [Hkv*G, 128] f32
    l_ref,      # VMEM [Hkv*G, 128] f32
    *,
    scale: float,
    page_size: int,
    num_page_blocks: int,
    sliding_window: Optional[int],
    hkv: int,
    g: int,
):
    b = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    kv_len = len_ref[b]

    # Live-kv mask for this page's slots (pool slots < kv_len precede the
    # query, so causality ≡ slot validity); the sliding window is measured
    # from the query's true position.
    pos = j * page_size + jax.lax.broadcasted_iota(
        jnp.int32, (1, page_size), 1
    )
    valid = pos < kv_len
    if sliding_window is not None:
        valid &= pos > qpos_ref[b] - sliding_window

    q = q_ref[0]  # [Hkv, G, D]
    k = k_ref[0]  # [Hkv, PS, D]
    v = v_ref[0]

    if g == 1:
        # MHA: VPU multiply-reduce; a [1, D] x [D, PS] MXU call per head
        # would waste the systolic array on 1-row matmuls.
        qv = q[:, 0, :][:, None, :].astype(jnp.float32)     # [Hkv, 1, D]
        s = jnp.sum(qv * k.astype(jnp.float32), axis=-1)    # [Hkv, PS]
    else:
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ).reshape(hkv * g, page_size)                        # [Hkv*G, PS]
    s = s * scale
    s = jnp.where(valid, s, _NEG_INF)

    m_prev = m_ref[:, :1]
    l_prev = l_ref[:, :1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.where(valid, jnp.exp(s - m_new), 0.0)

    l_ref[:] = jnp.broadcast_to(
        alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True), l_ref.shape
    )
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)

    if g == 1:
        pv = jnp.sum(p[:, :, None] * v.astype(jnp.float32), axis=1)  # [Hkv, D]
        acc_ref[:] = acc_ref[:] * alpha + pv
    else:
        pg = p.reshape(hkv, g, page_size).astype(v.dtype)
        pv = jax.lax.dot_general(
            pg, v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        acc_ref[:] = acc_ref[:] * alpha + pv.reshape(hkv * g, -1)

    @pl.when(j == num_page_blocks - 1)
    def _finalize():
        # Fully-masked rows (kv_len == 0) have l == 0 → emit zeros.
        l = l_ref[:, :1]
        out = acc_ref[:] / jnp.maximum(l, 1e-20)
        out_ref[0] = out.reshape(hkv, g, -1).astype(out_ref.dtype)
        m_out_ref[0] = m_ref[:]
        l_out_ref[0] = l_ref[:]


def paged_attention(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,
    kv_lengths: jnp.ndarray,
    scale: Optional[float] = None,
    sliding_window: Optional[int] = None,
    interpret: Optional[bool] = None,
    q_positions: Optional[jnp.ndarray] = None,
    return_stats: bool = False,
):
    """Decode attention straight over the page pool.

    ``q``: ``[B, 1, Hq, D]`` (already rotated); ``k_pages``/``v_pages``:
    ``[P, Hkv, page_size, D]`` — one layer's pool, keys stored rotated;
    ``page_table``: ``[B, T]`` int32 physical page ids (slot order = position
    order, 0 = null page); ``kv_lengths``: ``[B]`` int32 live kv count per
    row; ``q_positions``: ``[B]`` absolute query positions (defaults to
    ``kv_lengths - 1`` — the classic decode step attending to itself last).
    Returns ``[B, 1, Hq, D]``, or with ``return_stats`` a tuple
    ``(out, m, l)`` with ``m``/``l`` ``[B, Hkv, G]`` fp32 online-softmax
    stats for joint-softmax merging with other segments.
    """
    b, s, hq, d = q.shape
    if s != 1:
        raise ValueError(f"paged_attention is decode-only (S=1), got S={s}")
    _, hkv, page_size, _ = k_pages.shape
    t = page_table.shape[1]
    g = hq // hkv
    if scale is None:
        scale = d**-0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if q_positions is None:
        q_positions = kv_lengths - 1

    qr = q.reshape(b, hkv, g, d)  # kv-head-major grouping, as gqa_attention

    def _page_index(bi, ji, table, lens, qpos):
        # Clamp blocks past the row's live span to the null page: the fetch
        # still happens (BlockSpec semantics) but hits one hot page.
        live = ji * page_size < lens[bi]
        return (jnp.where(live, table[bi, ji], 0), 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b, t),
        in_specs=[
            pl.BlockSpec(
                (1, hkv, g, d), lambda bi, ji, table, lens, qpos: (bi, 0, 0, 0)
            ),
            pl.BlockSpec((1, hkv, page_size, d), _page_index),
            pl.BlockSpec((1, hkv, page_size, d), _page_index),
        ],
        out_specs=(
            pl.BlockSpec(
                (1, hkv, g, d), lambda bi, ji, table, lens, qpos: (bi, 0, 0, 0)
            ),
            pl.BlockSpec(
                (1, hkv * g, 128),
                lambda bi, ji, table, lens, qpos: (bi, 0, 0),
            ),
            pl.BlockSpec(
                (1, hkv * g, 128),
                lambda bi, ji, table, lens, qpos: (bi, 0, 0),
            ),
        ),
        scratch_shapes=[
            pltpu.VMEM((hkv * g, d), jnp.float32),
            pltpu.VMEM((hkv * g, 128), jnp.float32),
            pltpu.VMEM((hkv * g, 128), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _paged_kernel,
        scale=scale,
        page_size=page_size,
        num_page_blocks=t,
        sliding_window=sliding_window,
        hkv=hkv,
        g=g,
    )
    out, m, l = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((b, hkv, g, d), q.dtype),
            jax.ShapeDtypeStruct((b, hkv * g, 128), jnp.float32),
            jax.ShapeDtypeStruct((b, hkv * g, 128), jnp.float32),
        ),
        grid_spec=grid_spec,
        interpret=interpret,
    )(page_table.astype(jnp.int32), kv_lengths.astype(jnp.int32),
      q_positions.astype(jnp.int32), qr, k_pages, v_pages)
    out = out.reshape(b, 1, hq, d)
    if return_stats:
        return out, m[:, :, 0].reshape(b, hkv, g), l[:, :, 0].reshape(b, hkv, g)
    return out


def _qpaged_kernel(
    table_ref,  # SMEM [B, T] int32
    len_ref,    # SMEM [B] int32
    qpos_ref,   # SMEM [B] int32
    q_ref,      # [1, Hkv, G, D]
    k_ref,      # [1, Hkv, PS, D] int8
    ks_ref,     # [1, Hkv, PS] f32
    v_ref,      # [1, Hkv, PS, D] int8
    vs_ref,     # [1, Hkv, PS] f32
    out_ref,    # [1, Hkv, G, D]
    m_out_ref,  # [1, Hkv*G, 128] f32
    l_out_ref,  # [1, Hkv*G, 128] f32
    acc_ref,
    m_ref,
    l_ref,
    *,
    scale: float,
    page_size: int,
    num_page_blocks: int,
    sliding_window: Optional[int],
    hkv: int,
    g: int,
):
    """int8 page variant of :func:`_paged_kernel`: the per-(slot, head)
    scales apply to the SCORES/probs (``q·(k·s) = s·(q·k)``), so the int8
    pages stream through VMEM without a dequantized copy."""
    b = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    kv_len = len_ref[b]
    pos = j * page_size + jax.lax.broadcasted_iota(
        jnp.int32, (1, page_size), 1
    )
    valid = pos < kv_len
    if sliding_window is not None:
        valid &= pos > qpos_ref[b] - sliding_window

    q = q_ref[0]                      # [Hkv, G, D]
    k = k_ref[0]                      # [Hkv, PS, D] int8
    ks = ks_ref[0]                    # [Hkv, PS] f32

    if g == 1:
        qv = q[:, 0, :][:, None, :].astype(jnp.float32)
        s = jnp.sum(qv * k.astype(jnp.float32), axis=-1) * ks  # [Hkv, PS]
    else:
        s = jax.lax.dot_general(
            q.astype(jnp.float32), k.astype(jnp.float32),
            (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * ks[:, None, :]
        s = s.reshape(hkv * g, page_size)
    s = s * scale
    s = jnp.where(valid, s, _NEG_INF)

    m_prev = m_ref[:, :1]
    l_prev = l_ref[:, :1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.where(valid, jnp.exp(s - m_new), 0.0)

    l_ref[:] = jnp.broadcast_to(
        alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True), l_ref.shape
    )
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)

    v = v_ref[0]                      # [Hkv, PS, D] int8
    vs = vs_ref[0]                    # [Hkv, PS] f32
    if g == 1:
        pw = p.reshape(hkv, page_size) * vs
        pv = jnp.sum(pw[:, :, None] * v.astype(jnp.float32), axis=1)
        acc_ref[:] = acc_ref[:] * alpha + pv
    else:
        pw = p.reshape(hkv, g, page_size) * vs[:, None, :]
        pv = jax.lax.dot_general(
            pw, v.astype(jnp.float32), (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        acc_ref[:] = acc_ref[:] * alpha + pv.reshape(hkv * g, -1)

    @pl.when(j == num_page_blocks - 1)
    def _finalize():
        l = l_ref[:, :1]
        out = acc_ref[:] / jnp.maximum(l, 1e-20)
        out_ref[0] = out.reshape(hkv, g, -1).astype(out_ref.dtype)
        m_out_ref[0] = m_ref[:]
        l_out_ref[0] = l_ref[:]


def quantized_paged_attention(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    ks_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    vs_pages: jnp.ndarray,
    page_table: jnp.ndarray,
    kv_lengths: jnp.ndarray,
    scale: Optional[float] = None,
    sliding_window: Optional[int] = None,
    interpret: Optional[bool] = None,
    q_positions: Optional[jnp.ndarray] = None,
    return_stats: bool = False,
):
    """As :func:`paged_attention` over int8 pages with per-(slot, head)
    scale planes (``ks_pages``/``vs_pages``: ``[P, Hkv, page_size]`` f32)."""
    b, s, hq, d = q.shape
    if s != 1:
        raise ValueError(f"decode-only kernel (S=1), got S={s}")
    _, hkv, page_size, _ = k_pages.shape
    t = page_table.shape[1]
    g = hq // hkv
    if scale is None:
        scale = d**-0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if q_positions is None:
        q_positions = kv_lengths - 1

    qr = q.reshape(b, hkv, g, d)

    def _page_index(bi, ji, table, lens, qpos):
        live = ji * page_size < lens[bi]
        return (jnp.where(live, table[bi, ji], 0), 0, 0, 0)

    def _page_index3(bi, ji, table, lens, qpos):
        live = ji * page_size < lens[bi]
        return (jnp.where(live, table[bi, ji], 0), 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b, t),
        in_specs=[
            pl.BlockSpec(
                (1, hkv, g, d), lambda bi, ji, table, lens, qpos: (bi, 0, 0, 0)
            ),
            pl.BlockSpec((1, hkv, page_size, d), _page_index),
            pl.BlockSpec((1, hkv, page_size), _page_index3),
            pl.BlockSpec((1, hkv, page_size, d), _page_index),
            pl.BlockSpec((1, hkv, page_size), _page_index3),
        ],
        out_specs=(
            pl.BlockSpec(
                (1, hkv, g, d), lambda bi, ji, table, lens, qpos: (bi, 0, 0, 0)
            ),
            pl.BlockSpec(
                (1, hkv * g, 128),
                lambda bi, ji, table, lens, qpos: (bi, 0, 0),
            ),
            pl.BlockSpec(
                (1, hkv * g, 128),
                lambda bi, ji, table, lens, qpos: (bi, 0, 0),
            ),
        ),
        scratch_shapes=[
            pltpu.VMEM((hkv * g, d), jnp.float32),
            pltpu.VMEM((hkv * g, 128), jnp.float32),
            pltpu.VMEM((hkv * g, 128), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _qpaged_kernel,
        scale=scale,
        page_size=page_size,
        num_page_blocks=t,
        sliding_window=sliding_window,
        hkv=hkv,
        g=g,
    )
    out, m, l = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((b, hkv, g, d), q.dtype),
            jax.ShapeDtypeStruct((b, hkv * g, 128), jnp.float32),
            jax.ShapeDtypeStruct((b, hkv * g, 128), jnp.float32),
        ),
        grid_spec=grid_spec,
        interpret=interpret,
    )(page_table.astype(jnp.int32), kv_lengths.astype(jnp.int32),
      q_positions.astype(jnp.int32), qr, k_pages, ks_pages, v_pages, vs_pages)
    out = out.reshape(b, 1, hq, d)
    if return_stats:
        return out, m[:, :, 0].reshape(b, hkv, g), l[:, :, 0].reshape(b, hkv, g)
    return out
