"""Pallas paged-attention kernel (decode hot path).

The decode-side companion of ``flash_attention.py`` (SURVEY §7 step 4): at
decode the XLA path first gathers every session's pages into a contiguous
``[B, max_len, Hkv, D]`` view (``cache/paged.py:update_and_gather``) — a full
copy of the active KV working set through HBM per layer per token. This kernel
instead reads K/V **in place** from the page pool: the grid walks
``(batch, page)`` with the page table riding as a scalar-prefetch operand, so
each step DMAs one whole physical page (all KV heads — ``[Hkv, PS, D]``, a
megabyte-scale contiguous block) straight from where it lives (the TPU analog
of vLLM's paged attention; the reference's multi-tenancy never got past a
dict of growing tensors,
``/root/reference/distributed_llm_inference/models/llama/cache.py:14-19``).

Bandwidth properties:
* no materialized contiguous copy — pages stream through VMEM once;
* page blocks past a row's live length are clamped to the null page 0 in the
  index map, so short rows in a long-table batch fetch (cheap, cached) zeros
  instead of the whole table span — the dense cache by contrast always reads
  its full padded buffer;
* MHA (``G == 1``) uses a VPU multiply-reduce for QK^T and PV — a 1-row MXU
  matmul per head wastes the systolic array; GQA (``G > 1``) uses
  ``Hkv``-batched ``dot_general``.

Online-softmax state (running max / denominator / accumulator) lives in VMEM
scratch carried across the page-grid axis (innermost ⇒ scratch persists
across one row's page sweep). The per-row (m, l) stats are ALSO emitted so
callers can merge this segment with others under one joint softmax — the
write-behind-tail decode (``models/llama.py:multi_decode_apply``) combines
the pool segment with the small tail segment that holds the fused steps' new
tokens.

``q_positions`` decouples the query's absolute position from the pool length:
in the tail regime the query sits ``tail_len`` tokens PAST the pool contents
(sliding-window masking needs the true position; plain causality over the
pool is just slot validity either way).

Runs in interpret mode off-TPU so the CPU test mesh exercises it.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .attention import _NEG_INF

__all__ = [
    "paged_attention",
    "quantized_paged_attention",
    "latent_paged_attention",
    "quantized_latent_paged_attention",
    "quantized_paged_fused_attention",
]


def _paged_kernel(
    table_ref,  # SMEM [B, T] int32 (scalar prefetch)
    len_ref,    # SMEM [B] int32 (scalar prefetch)
    qpos_ref,   # SMEM [B] int32 (scalar prefetch): query's absolute position
    q_ref,      # [1, Hkv, G, D]
    k_ref,      # [1, Hkv, PS, D]
    v_ref,      # [1, Hkv, PS, D]
    out_ref,    # [1, Hkv, G, D]
    m_out_ref,  # [1, Hkv*G, 128] f32
    l_out_ref,  # [1, Hkv*G, 128] f32
    acc_ref,    # VMEM [Hkv*G, D] f32
    m_ref,      # VMEM [Hkv*G, 128] f32
    l_ref,      # VMEM [Hkv*G, 128] f32
    *,
    scale: float,
    page_size: int,
    num_page_blocks: int,
    sliding_window: Optional[int],
    hkv: int,
    g: int,
):
    b = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    kv_len = len_ref[b]

    # Live-kv mask for this page's slots (pool slots < kv_len precede the
    # query, so causality ≡ slot validity); the sliding window is measured
    # from the query's true position.
    pos = j * page_size + jax.lax.broadcasted_iota(
        jnp.int32, (1, page_size), 1
    )
    valid = pos < kv_len
    if sliding_window is not None:
        valid &= pos > qpos_ref[b] - sliding_window

    q = q_ref[0]  # [Hkv, G, D]
    k = k_ref[0]  # [Hkv, PS, D]
    v = v_ref[0]

    if g == 1:
        # MHA: VPU multiply-reduce; a [1, D] x [D, PS] MXU call per head
        # would waste the systolic array on 1-row matmuls.
        qv = q[:, 0, :][:, None, :].astype(jnp.float32)     # [Hkv, 1, D]
        s = jnp.sum(qv * k.astype(jnp.float32), axis=-1)    # [Hkv, PS]
    else:
        s = jax.lax.dot_general(
            q, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ).reshape(hkv * g, page_size)                        # [Hkv*G, PS]
    s = s * scale
    s = jnp.where(valid, s, _NEG_INF)

    m_prev = m_ref[:, :1]
    l_prev = l_ref[:, :1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.where(valid, jnp.exp(s - m_new), 0.0)

    l_ref[:] = jnp.broadcast_to(
        alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True), l_ref.shape
    )
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)

    if g == 1:
        pv = jnp.sum(p[:, :, None] * v.astype(jnp.float32), axis=1)  # [Hkv, D]
        acc_ref[:] = acc_ref[:] * alpha + pv
    else:
        pg = p.reshape(hkv, g, page_size).astype(v.dtype)
        pv = jax.lax.dot_general(
            pg, v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        acc_ref[:] = acc_ref[:] * alpha + pv.reshape(hkv * g, -1)

    @pl.when(j == num_page_blocks - 1)
    def _finalize():
        # Fully-masked rows (kv_len == 0) have l == 0 → emit zeros.
        l = l_ref[:, :1]
        out = acc_ref[:] / jnp.maximum(l, 1e-20)
        out_ref[0] = out.reshape(hkv, g, -1).astype(out_ref.dtype)
        m_out_ref[0] = m_ref[:]
        l_out_ref[0] = l_ref[:]


def paged_attention(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,
    kv_lengths: jnp.ndarray,
    scale: Optional[float] = None,
    sliding_window: Optional[int] = None,
    interpret: Optional[bool] = None,
    q_positions: Optional[jnp.ndarray] = None,
    return_stats: bool = False,
):
    """Decode attention straight over the page pool.

    ``q``: ``[B, 1, Hq, D]`` (already rotated); ``k_pages``/``v_pages``:
    ``[P, Hkv, page_size, D]`` — one layer's pool, keys stored rotated;
    ``page_table``: ``[B, T]`` int32 physical page ids (slot order = position
    order, 0 = null page); ``kv_lengths``: ``[B]`` int32 live kv count per
    row; ``q_positions``: ``[B]`` absolute query positions (defaults to
    ``kv_lengths - 1`` — the classic decode step attending to itself last).
    Returns ``[B, 1, Hq, D]``, or with ``return_stats`` a tuple
    ``(out, m, l)`` with ``m``/``l`` ``[B, Hkv, G]`` fp32 online-softmax
    stats for joint-softmax merging with other segments.
    """
    b, s, hq, d = q.shape
    if s != 1:
        raise ValueError(f"paged_attention is decode-only (S=1), got S={s}")
    _, hkv, page_size, _ = k_pages.shape
    t = page_table.shape[1]
    g = hq // hkv
    if scale is None:
        scale = d**-0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if q_positions is None:
        q_positions = kv_lengths - 1

    qr = q.reshape(b, hkv, g, d)  # kv-head-major grouping, as gqa_attention

    def _page_index(bi, ji, table, lens, qpos):
        # Clamp blocks past the row's live span to the null page: the fetch
        # still happens (BlockSpec semantics) but hits one hot page.
        live = ji * page_size < lens[bi]
        return (jnp.where(live, table[bi, ji], 0), 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b, t),
        in_specs=[
            pl.BlockSpec(
                (1, hkv, g, d), lambda bi, ji, table, lens, qpos: (bi, 0, 0, 0)
            ),
            pl.BlockSpec((1, hkv, page_size, d), _page_index),
            pl.BlockSpec((1, hkv, page_size, d), _page_index),
        ],
        out_specs=(
            pl.BlockSpec(
                (1, hkv, g, d), lambda bi, ji, table, lens, qpos: (bi, 0, 0, 0)
            ),
            pl.BlockSpec(
                (1, hkv * g, 128),
                lambda bi, ji, table, lens, qpos: (bi, 0, 0),
            ),
            pl.BlockSpec(
                (1, hkv * g, 128),
                lambda bi, ji, table, lens, qpos: (bi, 0, 0),
            ),
        ),
        scratch_shapes=[
            pltpu.VMEM((hkv * g, d), jnp.float32),
            pltpu.VMEM((hkv * g, 128), jnp.float32),
            pltpu.VMEM((hkv * g, 128), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _paged_kernel,
        scale=scale,
        page_size=page_size,
        num_page_blocks=t,
        sliding_window=sliding_window,
        hkv=hkv,
        g=g,
    )
    out, m, l = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((b, hkv, g, d), q.dtype),
            jax.ShapeDtypeStruct((b, hkv * g, 128), jnp.float32),
            jax.ShapeDtypeStruct((b, hkv * g, 128), jnp.float32),
        ),
        grid_spec=grid_spec,
        interpret=interpret,
    )(page_table.astype(jnp.int32), kv_lengths.astype(jnp.int32),
      q_positions.astype(jnp.int32), qr, k_pages, v_pages)
    out = out.reshape(b, 1, hq, d)
    if return_stats:
        return out, m[:, :, 0].reshape(b, hkv, g), l[:, :, 0].reshape(b, hkv, g)
    return out


def _qpaged_kernel(
    table_ref,  # SMEM [B, T] int32
    len_ref,    # SMEM [B] int32
    qpos_ref,   # SMEM [B] int32
    q_ref,      # [1, Hkv, G, D]
    k_ref,      # [1, Hkv, PS, D] int8
    ks_ref,     # [1, Hkv, PS] f32
    v_ref,      # [1, Hkv, PS, D] int8
    vs_ref,     # [1, Hkv, PS] f32
    out_ref,    # [1, Hkv, G, D]
    m_out_ref,  # [1, Hkv*G, 128] f32
    l_out_ref,  # [1, Hkv*G, 128] f32
    acc_ref,
    m_ref,
    l_ref,
    *,
    scale: float,
    page_size: int,
    num_page_blocks: int,
    sliding_window: Optional[int],
    hkv: int,
    g: int,
):
    """int8 page variant of :func:`_paged_kernel`: the per-(slot, head)
    scales apply to the SCORES/probs (``q·(k·s) = s·(q·k)``), so the int8
    pages stream through VMEM without a dequantized copy."""
    b = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    kv_len = len_ref[b]
    pos = j * page_size + jax.lax.broadcasted_iota(
        jnp.int32, (1, page_size), 1
    )
    valid = pos < kv_len
    if sliding_window is not None:
        valid &= pos > qpos_ref[b] - sliding_window

    q = q_ref[0]                      # [Hkv, G, D]
    k = k_ref[0]                      # [Hkv, PS, D] int8
    ks = ks_ref[0]                    # [Hkv, PS] f32

    if g == 1:
        qv = q[:, 0, :][:, None, :].astype(jnp.float32)
        s = jnp.sum(qv * k.astype(jnp.float32), axis=-1) * ks  # [Hkv, PS]
    else:
        s = jax.lax.dot_general(
            q.astype(jnp.float32), k.astype(jnp.float32),
            (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ) * ks[:, None, :]
        s = s.reshape(hkv * g, page_size)
    s = s * scale
    s = jnp.where(valid, s, _NEG_INF)

    m_prev = m_ref[:, :1]
    l_prev = l_ref[:, :1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.where(valid, jnp.exp(s - m_new), 0.0)

    l_ref[:] = jnp.broadcast_to(
        alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True), l_ref.shape
    )
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)

    v = v_ref[0]                      # [Hkv, PS, D] int8
    vs = vs_ref[0]                    # [Hkv, PS] f32
    if g == 1:
        pw = p.reshape(hkv, page_size) * vs
        pv = jnp.sum(pw[:, :, None] * v.astype(jnp.float32), axis=1)
        acc_ref[:] = acc_ref[:] * alpha + pv
    else:
        pw = p.reshape(hkv, g, page_size) * vs[:, None, :]
        pv = jax.lax.dot_general(
            pw, v.astype(jnp.float32), (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        acc_ref[:] = acc_ref[:] * alpha + pv.reshape(hkv * g, -1)

    @pl.when(j == num_page_blocks - 1)
    def _finalize():
        l = l_ref[:, :1]
        out = acc_ref[:] / jnp.maximum(l, 1e-20)
        out_ref[0] = out.reshape(hkv, g, -1).astype(out_ref.dtype)
        m_out_ref[0] = m_ref[:]
        l_out_ref[0] = l_ref[:]


def quantized_paged_attention(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    ks_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    vs_pages: jnp.ndarray,
    page_table: jnp.ndarray,
    kv_lengths: jnp.ndarray,
    scale: Optional[float] = None,
    sliding_window: Optional[int] = None,
    interpret: Optional[bool] = None,
    q_positions: Optional[jnp.ndarray] = None,
    return_stats: bool = False,
):
    """As :func:`paged_attention` over int8 pages with per-(slot, head)
    scale planes (``ks_pages``/``vs_pages``: ``[P, Hkv, page_size]`` f32)."""
    b, s, hq, d = q.shape
    if s != 1:
        raise ValueError(f"decode-only kernel (S=1), got S={s}")
    _, hkv, page_size, _ = k_pages.shape
    t = page_table.shape[1]
    g = hq // hkv
    if scale is None:
        scale = d**-0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if q_positions is None:
        q_positions = kv_lengths - 1

    qr = q.reshape(b, hkv, g, d)

    def _page_index(bi, ji, table, lens, qpos):
        live = ji * page_size < lens[bi]
        return (jnp.where(live, table[bi, ji], 0), 0, 0, 0)

    def _page_index3(bi, ji, table, lens, qpos):
        live = ji * page_size < lens[bi]
        return (jnp.where(live, table[bi, ji], 0), 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(b, t),
        in_specs=[
            pl.BlockSpec(
                (1, hkv, g, d), lambda bi, ji, table, lens, qpos: (bi, 0, 0, 0)
            ),
            pl.BlockSpec((1, hkv, page_size, d), _page_index),
            pl.BlockSpec((1, hkv, page_size), _page_index3),
            pl.BlockSpec((1, hkv, page_size, d), _page_index),
            pl.BlockSpec((1, hkv, page_size), _page_index3),
        ],
        out_specs=(
            pl.BlockSpec(
                (1, hkv, g, d), lambda bi, ji, table, lens, qpos: (bi, 0, 0, 0)
            ),
            pl.BlockSpec(
                (1, hkv * g, 128),
                lambda bi, ji, table, lens, qpos: (bi, 0, 0),
            ),
            pl.BlockSpec(
                (1, hkv * g, 128),
                lambda bi, ji, table, lens, qpos: (bi, 0, 0),
            ),
        ),
        scratch_shapes=[
            pltpu.VMEM((hkv * g, d), jnp.float32),
            pltpu.VMEM((hkv * g, 128), jnp.float32),
            pltpu.VMEM((hkv * g, 128), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _qpaged_kernel,
        scale=scale,
        page_size=page_size,
        num_page_blocks=t,
        sliding_window=sliding_window,
        hkv=hkv,
        g=g,
    )
    out, m, l = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((b, hkv, g, d), q.dtype),
            jax.ShapeDtypeStruct((b, hkv * g, 128), jnp.float32),
            jax.ShapeDtypeStruct((b, hkv * g, 128), jnp.float32),
        ),
        grid_spec=grid_spec,
        interpret=interpret,
    )(page_table.astype(jnp.int32), kv_lengths.astype(jnp.int32),
      q_positions.astype(jnp.int32), qr, k_pages, ks_pages, v_pages, vs_pages)
    out = out.reshape(b, 1, hq, d)
    if return_stats:
        return out, m[:, :, 0].reshape(b, hkv, g), l[:, :, 0].reshape(b, hkv, g)
    return out


def latent_paged_attention(
    q: jnp.ndarray,
    c_pages: jnp.ndarray,
    page_table: jnp.ndarray,
    kv_lengths: jnp.ndarray,
    scale: Optional[float] = None,
    sliding_window: Optional[int] = None,
    interpret: Optional[bool] = None,
    q_positions: Optional[jnp.ndarray] = None,
    return_stats: bool = False,
):
    """Absorbed-MLA decode attention over the latent pool, in place — the
    non-ragged fallback of ``ops/ragged_attention.py:
    latent_ragged_paged_attention`` (same contract: ``c_pages``
    ``[P, 1, page_size, lat_dim]`` fused ``[c ; k_rope]`` latents, ``q``
    the absorbed ``[B, 1, Hq, lat_dim]`` query, ``K = V =`` stored
    latents, so the page walk is the decompression fusion)."""
    return paged_attention(
        q, c_pages, c_pages, page_table, kv_lengths, scale=scale,
        sliding_window=sliding_window, interpret=interpret,
        q_positions=q_positions, return_stats=return_stats,
    )


def quantized_latent_paged_attention(
    q: jnp.ndarray,
    c_pages: jnp.ndarray,
    cs_pages: jnp.ndarray,
    page_table: jnp.ndarray,
    kv_lengths: jnp.ndarray,
    scale: Optional[float] = None,
    sliding_window: Optional[int] = None,
    interpret: Optional[bool] = None,
    q_positions: Optional[jnp.ndarray] = None,
    return_stats: bool = False,
):
    """As :func:`latent_paged_attention` over the int8 latent pool with
    per-token f32 scales (``cs_pages``: ``[P, 1, page_size]``)."""
    return quantized_paged_attention(
        q, c_pages, cs_pages, c_pages, cs_pages, page_table, kv_lengths,
        scale=scale, sliding_window=sliding_window, interpret=interpret,
        q_positions=q_positions, return_stats=return_stats,
    )


def quantized_paged_fused_attention(
    q: jnp.ndarray,
    k_new: jnp.ndarray,
    v_new: jnp.ndarray,
    pool_k: jnp.ndarray,
    pool_ks: jnp.ndarray,
    pool_v: jnp.ndarray,
    pool_vs: jnp.ndarray,
    tail_k: jnp.ndarray,
    tail_ks: jnp.ndarray,
    tail_v: jnp.ndarray,
    tail_vs: jnp.ndarray,
    layer_idx: jnp.ndarray,
    step_idx: jnp.ndarray,
    page_table: jnp.ndarray,
    base_len: jnp.ndarray,
    tail_valid_len: jnp.ndarray,
    q_positions: jnp.ndarray,
    scale: Optional[float] = None,
    interpret: Optional[bool] = None,
    sliding_window: Optional[int] = None,
):
    """ONE kernel for a fused-decode step over the int8 page pool IN PLACE:
    the WHOLE ``[L, P, Hkv, PS, D]`` pool passes through unsliced (the block
    index map resolves ``(layer, physical page)``, so the operand is
    zero-copy — the r2 per-layer pool slices materialized a full pool copy
    per (layer, step), and the r3 gather-per-window fix held a second
    contiguous copy of the live KV alive, halving the admissible batch at
    long contexts); the step's fresh K/V quantizes in-kernel into the
    io-aliased write-behind tail, which joins the page sweep as the final
    online-softmax tile.

    Shapes: ``q`` ``[B, 1, Hq, D]`` (rotated); ``k_new``/``v_new``
    ``[B, 1, Hkv, D]`` (k rotated); pool planes ``[L, P, Hkv, PS, D]`` int8
    (+ ``[L, P, Hkv, PS]`` f32 scales); tail planes ``[L, B, Hkv, KT, D]``
    (+ scales, io-aliased). Returns ``(out, tail_k', tail_ks', tail_v',
    tail_vs')``.
    """
    b, s, hq, d = q.shape
    if s != 1:
        raise ValueError(f"decode-only kernel (S=1), got S={s}")
    num_l, _, hkv, page_size, _ = pool_k.shape
    kt = tail_k.shape[3]
    t = page_table.shape[1]
    g = hq // hkv
    if scale is None:
        scale = d**-0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    qr = q.reshape(b, hkv, g, d)
    knr = jnp.moveaxis(k_new, 1, 2)  # [B, Hkv, 1, D]
    vnr = jnp.moveaxis(v_new, 1, 2)
    lref = jnp.asarray(layer_idx, jnp.int32).reshape(1)
    sref = jnp.asarray(step_idx, jnp.int32).reshape(1)

    def _pool_index(bi, ji, lidx, step, table, lens, vlen, qpos):
        live = ji * page_size < lens[bi]
        return (lidx[0], jnp.where(live, table[bi, ji], 0), 0, 0, 0)

    def _pool_index4(bi, ji, lidx, step, table, lens, vlen, qpos):
        live = ji * page_size < lens[bi]
        return (lidx[0], jnp.where(live, table[bi, ji], 0), 0, 0)

    def _tail_index(bi, ji, lidx, step, table, lens, vlen, qpos):
        return (lidx[0], bi, 0, 0, 0)

    def _tail_index3(bi, ji, lidx, step, table, lens, vlen, qpos):
        return (lidx[0], bi, 0, 0)

    def _row_index(bi, ji, lidx, step, table, lens, vlen, qpos):
        return (bi, 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=6,
        grid=(b, t),
        in_specs=[
            pl.BlockSpec((1, hkv, g, d), _row_index),
            pl.BlockSpec((1, hkv, 1, d), _row_index),
            pl.BlockSpec((1, hkv, 1, d), _row_index),
            pl.BlockSpec((1, 1, hkv, page_size, d), _pool_index),
            pl.BlockSpec((1, 1, hkv, page_size), _pool_index4),
            pl.BlockSpec((1, 1, hkv, page_size, d), _pool_index),
            pl.BlockSpec((1, 1, hkv, page_size), _pool_index4),
            pl.BlockSpec((1, 1, hkv, kt, d), _tail_index),
            pl.BlockSpec((1, 1, hkv, kt), _tail_index3),
            pl.BlockSpec((1, 1, hkv, kt, d), _tail_index),
            pl.BlockSpec((1, 1, hkv, kt), _tail_index3),
        ],
        out_specs=(
            pl.BlockSpec((1, hkv, g, d), _row_index),
            pl.BlockSpec((1, 1, hkv, kt, d), _tail_index),
            pl.BlockSpec((1, 1, hkv, kt), _tail_index3),
            pl.BlockSpec((1, 1, hkv, kt, d), _tail_index),
            pl.BlockSpec((1, 1, hkv, kt), _tail_index3),
        ),
        scratch_shapes=[
            pltpu.VMEM((hkv * g, d), jnp.float32),
            pltpu.VMEM((hkv * g, 128), jnp.float32),
            pltpu.VMEM((hkv * g, 128), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _qpaged_fused_kernel,
        scale=scale,
        page_size=page_size,
        num_page_blocks=t,
        sliding_window=sliding_window,
        hkv=hkv,
        g=g,
        kt=kt,
    )
    out, tk, tks, tv, tvs = pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct((b, hkv, g, d), q.dtype),
            jax.ShapeDtypeStruct(tail_k.shape, tail_k.dtype),
            jax.ShapeDtypeStruct(tail_ks.shape, tail_ks.dtype),
            jax.ShapeDtypeStruct(tail_v.shape, tail_v.dtype),
            jax.ShapeDtypeStruct(tail_vs.shape, tail_vs.dtype),
        ),
        grid_spec=grid_spec,
        interpret=interpret,
        # Tail planes update in place; indices count every flattened input
        # including the 6 scalar-prefetch operands.
        input_output_aliases={13: 1, 14: 2, 15: 3, 16: 4},
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("parallel", "arbitrary"),
            vmem_limit_bytes=100 * 1024 * 1024,
        ),
    )(lref, sref, page_table.astype(jnp.int32), base_len.astype(jnp.int32),
      tail_valid_len.astype(jnp.int32), q_positions.astype(jnp.int32),
      qr, knr, vnr,
      pool_k, pool_ks, pool_v, pool_vs,
      tail_k, tail_ks, tail_v, tail_vs)
    return out.reshape(b, 1, hq, d), tk, tks, tv, tvs


def _qpaged_fused_kernel(
    lidx_ref,   # SMEM [1] int32 (layer; consumed by index maps)
    step_ref,   # SMEM [1] int32 (tail write slot)
    table_ref,  # SMEM [B, T] int32 (consumed by index maps)
    len_ref,    # SMEM [B] int32 (live pool tokens)
    vlen_ref,   # SMEM [B] int32 (valid tail slots incl. this write)
    qpos_ref,   # SMEM [B] int32 (query positions)
    q_ref,      # [1, Hkv, G, D]
    kn_ref,     # [1, Hkv, 1, D]
    vn_ref,     # [1, Hkv, 1, D]
    k_ref,      # [1, 1, Hkv, PS, D] int8 (one physical page)
    ks_ref,     # [1, 1, Hkv, PS] f32
    v_ref,      # [1, 1, Hkv, PS, D] int8
    vs_ref,     # [1, 1, Hkv, PS] f32
    tk_ref,     # [1, 1, Hkv, KT, D] int8 (in)
    tks_ref,    # [1, 1, Hkv, KT] f32 (in)
    tv_ref,     # [1, 1, Hkv, KT, D] int8 (in)
    tvs_ref,    # [1, 1, Hkv, KT] f32 (in)
    out_ref,    # [1, Hkv, G, D]
    tk_out,     # aliased tail outputs
    tks_out,
    tv_out,
    tvs_out,
    acc_ref,    # VMEM [Hkv*G, D] f32
    m_ref,      # VMEM [Hkv*G, 128] f32
    l_ref,      # VMEM [Hkv*G, 128] f32
    *,
    scale: float,
    page_size: int,
    num_page_blocks: int,
    sliding_window: Optional[int],
    hkv: int,
    g: int,
    kt: int,
):
    b = pl.program_id(0)
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    q = q_ref[0]                               # [Hkv, G, D]

    def _accumulate(s, valid):
        s = jnp.where(valid, s, _NEG_INF)
        m_prev = m_ref[:, :1]
        l_prev = l_ref[:, :1]
        m_cur = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        alpha = jnp.exp(m_prev - m_new)
        p = jnp.where(valid, jnp.exp(s - m_new), 0.0)
        l_ref[:] = jnp.broadcast_to(
            alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True), l_ref.shape
        )
        m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)
        return p, alpha

    def _tile(kk, kks, vv, vvs, valid, width):
        s = jax.lax.dot_general(
            q.astype(jnp.bfloat16).reshape(hkv, g, -1),
            kk.astype(jnp.bfloat16).reshape(hkv, width, -1),
            (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )                                        # [Hkv, G, W]
        s = (s * kks[:, None, :] * scale).reshape(hkv * g, width)
        p, alpha = _accumulate(s, valid)
        pw = p.reshape(hkv, g, width) * vvs[:, None, :]
        pv = jax.lax.dot_general(
            pw.astype(jnp.bfloat16), vv.astype(jnp.bfloat16),
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        acc_ref[:] = acc_ref[:] * alpha + pv.reshape(hkv * g, -1)

    pos = j * page_size + jax.lax.broadcasted_iota(
        jnp.int32, (1, page_size), 1
    )
    valid = pos < len_ref[b]
    if sliding_window is not None:
        valid &= pos > qpos_ref[b] - sliding_window
    _tile(k_ref[0, 0], ks_ref[0, 0], v_ref[0, 0], vs_ref[0, 0], valid,
          page_size)

    @pl.when(j == num_page_blocks - 1)
    def _tail_tile():
        step = step_ref[0]
        kn = kn_ref[0].astype(jnp.float32)     # [Hkv, 1, D]
        vn = vn_ref[0].astype(jnp.float32)
        ksc = jnp.maximum(jnp.max(jnp.abs(kn), axis=-1), 1e-8) / 127.0
        vsc = jnp.maximum(jnp.max(jnp.abs(vn), axis=-1), 1e-8) / 127.0
        kq = jnp.clip(jnp.round(kn / ksc[..., None]), -127, 127).astype(
            jnp.int8
        )
        vq = jnp.clip(jnp.round(vn / vsc[..., None]), -127, 127).astype(
            jnp.int8
        )
        slot = jax.lax.broadcasted_iota(jnp.int32, (1, kt, 1), 1)
        hit3 = slot == step
        hit2 = hit3[..., 0]
        tk = jnp.where(hit3, kq, tk_ref[0, 0])    # [Hkv, KT, D]
        tv = jnp.where(hit3, vq, tv_ref[0, 0])
        tks = jnp.where(hit2, ksc, tks_ref[0, 0])  # [Hkv, KT]
        tvs = jnp.where(hit2, vsc, tvs_ref[0, 0])
        tk_out[0, 0] = tk
        tv_out[0, 0] = tv
        tks_out[0, 0] = tks
        tvs_out[0, 0] = tvs

        pos1 = jax.lax.broadcasted_iota(jnp.int32, (1, kt), 1)
        tail_valid = pos1 < vlen_ref[b]
        if sliding_window is not None:
            tail_pos = len_ref[b] + pos1
            tail_valid &= tail_pos > qpos_ref[b] - sliding_window
        _tile(tk, tks, tv, tvs, tail_valid, kt)

        l = l_ref[:, :1]
        out = acc_ref[:] / jnp.maximum(l, 1e-20)
        out_ref[0] = out.reshape(hkv, g, -1).astype(out_ref.dtype)


def paged_tail_flush(
    pool_k: jnp.ndarray,
    pool_ks: jnp.ndarray,
    pool_v: jnp.ndarray,
    pool_vs: jnp.ndarray,
    tail_k: jnp.ndarray,
    tail_ks: jnp.ndarray,
    tail_v: jnp.ndarray,
    tail_vs: jnp.ndarray,
    page_table: jnp.ndarray,
    base_len: jnp.ndarray,
    tail_len: jnp.ndarray,
    interpret: Optional[bool] = None,
):
    """Merge the fused window's int8 tail into the page pool by
    read-modify-writing ONLY the pages each row's window touches.

    Why a kernel: the XLA scatter (``cache/paged.py:_scatter_planes``)
    prefers a transposed pool layout, so XLA inserts a whole-pool relayout
    copy into the fused-decode executable feeding the Pallas attention's
    default-layout operand — a 2x3.2 GB HLO temp at b24/1k-ctx 7B shapes
    that OOMs the chip (and silently taxes smaller batches). Here each
    (layer, row) round-trips at most ``ceil(KT/PS)+1`` physical pages
    through VMEM with position-based composition (idempotent under clamped
    duplicate visits), and the pool keeps its default layout end to end.

    ``tail_*``: ``[L, B, Hkv, KT, D]`` int8 (+ ``[L, B, Hkv, KT]`` f32
    scales), KT <= page_size. Rows must have table slots mapped through
    ``base_len + tail_len`` (engine growth contract); clamped visits hit
    the null page 0 and compose no changes. Returns the four updated pool
    planes (inputs consumed — aliased).
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    num_l, _, hkv, ps, d = pool_k.shape
    b = page_table.shape[0]
    t = page_table.shape[1]
    kt = tail_k.shape[3]
    if kt > ps:
        raise ValueError(f"tail ({kt}) must fit one page ({ps})")
    nj = -(-kt // ps) + 1  # straddle: at most 2 pages per row's window

    def _pidx(li, bi, ji, table, lens, tl):
        slot = jnp.minimum(lens[bi] // ps + ji, t - 1)
        return (li, table[bi, slot], 0, 0, 0)

    def _pidx4(li, bi, ji, table, lens, tl):
        slot = jnp.minimum(lens[bi] // ps + ji, t - 1)
        return (li, table[bi, slot], 0, 0)

    def _tidx(li, bi, ji, table, lens, tl):
        return (li, bi, 0, 0, 0)

    def _tidx3(li, bi, ji, table, lens, tl):
        return (li, bi, 0, 0)

    def kernel(table_ref, lens_ref, tl_ref,
               tk, tks, tv, tvs,
               pk_in, pks_in, pv_in, pvs_in,
               pk_out, pks_out, pv_out, pvs_out):
        bi = pl.program_id(1)
        ji = pl.program_id(2)
        start = lens_ref[bi]
        tl = tl_ref[bi]
        slot = jnp.minimum(start // ps + ji, t - 1)

        def compose_values(pool_ref, tail_ref, out_ref):
            pos = slot * ps + jax.lax.broadcasted_iota(
                jnp.int32, (1, ps, 1), 1
            )
            cur = pool_ref[0, 0]                       # [Hkv, PS, D]
            tail = tail_ref[0, 0]                      # [Hkv, KT, D]
            for i in range(kt):
                hit = (pos == start + i) & (i < tl)
                cur = jnp.where(hit, tail[:, i : i + 1], cur)
            out_ref[0, 0] = cur

        def compose_scales(pool_ref, tail_ref, out_ref):
            pos = slot * ps + jax.lax.broadcasted_iota(
                jnp.int32, (1, ps), 1
            )
            cur = pool_ref[0, 0]                       # [Hkv, PS]
            tail = tail_ref[0, 0]                      # [Hkv, KT]
            for i in range(kt):
                hit = (pos == start + i) & (i < tl)
                cur = jnp.where(hit, tail[:, i : i + 1], cur)
            out_ref[0, 0] = cur

        compose_values(pk_in, tk, pk_out)
        compose_values(pv_in, tv, pv_out)
        compose_scales(pks_in, tks, pks_out)
        compose_scales(pvs_in, tvs, pvs_out)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(num_l, b, nj),
        in_specs=[
            pl.BlockSpec((1, 1, hkv, kt, d), _tidx),
            pl.BlockSpec((1, 1, hkv, kt), _tidx3),
            pl.BlockSpec((1, 1, hkv, kt, d), _tidx),
            pl.BlockSpec((1, 1, hkv, kt), _tidx3),
            pl.BlockSpec((1, 1, hkv, ps, d), _pidx),
            pl.BlockSpec((1, 1, hkv, ps), _pidx4),
            pl.BlockSpec((1, 1, hkv, ps, d), _pidx),
            pl.BlockSpec((1, 1, hkv, ps), _pidx4),
        ],
        out_specs=(
            pl.BlockSpec((1, 1, hkv, ps, d), _pidx),
            pl.BlockSpec((1, 1, hkv, ps), _pidx4),
            pl.BlockSpec((1, 1, hkv, ps, d), _pidx),
            pl.BlockSpec((1, 1, hkv, ps), _pidx4),
        ),
        scratch_shapes=[],
    )
    return pl.pallas_call(
        kernel,
        out_shape=(
            jax.ShapeDtypeStruct(pool_k.shape, pool_k.dtype),
            jax.ShapeDtypeStruct(pool_ks.shape, pool_ks.dtype),
            jax.ShapeDtypeStruct(pool_v.shape, pool_v.dtype),
            jax.ShapeDtypeStruct(pool_vs.shape, pool_vs.dtype),
        ),
        grid_spec=grid_spec,
        interpret=interpret,
        # Inputs counting scalars: table 0, lens 1, tl 2, tails 3-6,
        # pools 7-10.
        input_output_aliases={7: 0, 8: 1, 9: 2, 10: 3},
        compiler_params=pltpu.CompilerParams(
            dimension_semantics=("arbitrary", "arbitrary", "arbitrary"),
            vmem_limit_bytes=100 * 1024 * 1024,
        ),
    )(page_table.astype(jnp.int32), base_len.astype(jnp.int32),
      tail_len.astype(jnp.int32),
      tail_k, tail_ks, tail_v, tail_vs,
      pool_k, pool_ks, pool_v, pool_vs)
