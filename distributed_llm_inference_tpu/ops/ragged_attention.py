"""Pallas ragged mixed-phase paged attention (one grid for every phase).

``paged_attention.py`` killed the decode-side gather; prefill and chunked
prefill still route through ``cache/paged.py:update_and_gather`` — a full
contiguous ``[B, max_len, Hkv, D]`` copy of every row's pages per layer —
and through per-bucket padded dispatches (``engine/engine.py:_bucket_for``),
whose one-executable-per-bucket tax BENCH_r05 measured at 23–28% of nominal
prefill TFLOP/s and a 4258→479 tok/s decode collapse from 128 to 2k context.

This kernel serves rows with PER-ROW true lengths in ONE grid call:

* ``num_new[b]`` query tokens for row ``b`` start at absolute position
  ``q_start[b]`` and attend causally over that row's first ``kv_lengths[b]``
  pool slots. A full prefill row (``q_start == 0``), a chunked-prefill row
  (``q_start > 0``, ``num_new == C``), and a decode row (``num_new == 1``)
  are the SAME cell of the same grid — phase is data, not shape, so mixed
  prefill/decode batches never recompile.
* K/V stream IN PLACE from the page pool exactly as the decode kernel: the
  grid walks ``(batch, q-block, page)`` with the page table scalar-prefetched,
  and the index map clamps dead blocks — a page past the row's live span,
  past the causal frontier of this q-block, or under a q-block past the
  row's query count — to the null page 0, so short rows in a ragged batch
  fetch one hot cached page instead of the table span.
* The query tile ``[BQ, Hkv, G, D]`` rides the MXU as an ``Hkv``-batched
  ``[BQ*G, D] x [D, PS]`` ``dot_general`` (prefill has real row counts; the
  1-row VPU special case in ``_paged_kernel`` only pays off at ``BQ*G == 1``).

Online-softmax state is VMEM scratch carried across the page axis (innermost,
so one (row, q-block)'s sweep owns it), in the exact idiom of
``paged_attention._paged_kernel``. Runs in interpret mode off-TPU so tier-1
CPU tests exercise the same code path as the chip.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from .attention import _NEG_INF

__all__ = [
    "ragged_paged_attention",
    "quantized_ragged_paged_attention",
    "latent_ragged_paged_attention",
    "quantized_latent_ragged_paged_attention",
    "ragged_attention_reference",
]


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def _ragged_kernel(
    table_ref,   # SMEM [B, T] int32 (scalar prefetch)
    len_ref,     # SMEM [B] int32: live kv per row (incl. this call's tokens)
    qstart_ref,  # SMEM [B] int32: absolute position of the row's first query
    nnew_ref,    # SMEM [B] int32: valid query rows in this call
    q_ref,       # [1, BQ, Hkv, G, D]
    k_ref,       # [1, Hkv, PS, D]
    v_ref,       # [1, Hkv, PS, D]
    out_ref,     # [1, BQ, Hkv, G, D]
    acc_ref,     # VMEM [Hkv*BQ*G, D] f32
    m_ref,       # VMEM [Hkv*BQ*G, 128] f32
    l_ref,       # VMEM [Hkv*BQ*G, 128] f32
    *,
    scale: float,
    page_size: int,
    num_page_blocks: int,
    block_q: int,
    sliding_window: Optional[int],
    hkv: int,
    g: int,
):
    b = pl.program_id(0)
    qi = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    kv_len = len_ref[b]
    rows = hkv * block_q * g

    # Flat scratch row r covers (head = r // (BQ*G), query = (r % (BQ*G))
    # // G); its query's position inside the dispatch and in the sequence:
    ridx = jax.lax.broadcasted_iota(jnp.int32, (rows, 1), 0)
    q_rel = qi * block_q + (ridx % (block_q * g)) // g
    q_pos = qstart_ref[b] + q_rel

    # Per-(query, slot) mask: slot live, causal vs the query's absolute
    # position, and the query itself valid (pad rows past num_new mask to
    # all-dead → l == 0 → zeros at finalize).
    pos = j * page_size + jax.lax.broadcasted_iota(
        jnp.int32, (1, page_size), 1
    )
    valid = (pos < kv_len) & (pos <= q_pos) & (q_rel < nnew_ref[b])
    if sliding_window is not None:
        valid &= pos > q_pos - sliding_window

    # [BQ, Hkv, G, D] -> kv-head-major [Hkv, BQ*G, D] so QK^T/PV batch over
    # kv heads with real MXU row counts.
    q = jnp.transpose(q_ref[0], (1, 0, 2, 3)).reshape(hkv, block_q * g, -1)
    k = k_ref[0]  # [Hkv, PS, D]
    v = v_ref[0]

    s = jax.lax.dot_general(
        q, k, (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    ).reshape(rows, page_size)
    s = s * scale
    s = jnp.where(valid, s, _NEG_INF)

    m_prev = m_ref[:, :1]
    l_prev = l_ref[:, :1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.where(valid, jnp.exp(s - m_new), 0.0)

    l_ref[:] = jnp.broadcast_to(
        alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True), l_ref.shape
    )
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)

    pg = p.reshape(hkv, block_q * g, page_size).astype(v.dtype)
    pv = jax.lax.dot_general(
        pg, v, (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )
    acc_ref[:] = acc_ref[:] * alpha + pv.reshape(rows, -1)

    @pl.when(j == num_page_blocks - 1)
    def _finalize():
        # Fully-masked rows (pad queries, kv_len == 0) have l == 0 → zeros.
        l = l_ref[:, :1]
        out = acc_ref[:] / jnp.maximum(l, 1e-20)
        out = out.reshape(hkv, block_q, g, -1)
        out_ref[0] = jnp.transpose(out, (1, 0, 2, 3)).astype(out_ref.dtype)


def _qragged_kernel(
    table_ref,   # SMEM [B, T] int32
    len_ref,     # SMEM [B] int32
    qstart_ref,  # SMEM [B] int32
    nnew_ref,    # SMEM [B] int32
    q_ref,       # [1, BQ, Hkv, G, D]
    k_ref,       # [1, Hkv, PS, D] int8
    ks_ref,      # [1, Hkv, PS] f32
    v_ref,       # [1, Hkv, PS, D] int8
    vs_ref,      # [1, Hkv, PS] f32
    out_ref,     # [1, BQ, Hkv, G, D]
    acc_ref,
    m_ref,
    l_ref,
    *,
    scale: float,
    page_size: int,
    num_page_blocks: int,
    block_q: int,
    sliding_window: Optional[int],
    hkv: int,
    g: int,
):
    """int8 page variant of :func:`_ragged_kernel`: per-(slot, head) scales
    apply to the SCORES/probs (``q·(k·s) = s·(q·k)``), so the int8 pages
    stream through VMEM without a dequantized copy."""
    b = pl.program_id(0)
    qi = pl.program_id(1)
    j = pl.program_id(2)

    @pl.when(j == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)

    kv_len = len_ref[b]
    rows = hkv * block_q * g

    ridx = jax.lax.broadcasted_iota(jnp.int32, (rows, 1), 0)
    q_rel = qi * block_q + (ridx % (block_q * g)) // g
    q_pos = qstart_ref[b] + q_rel

    pos = j * page_size + jax.lax.broadcasted_iota(
        jnp.int32, (1, page_size), 1
    )
    valid = (pos < kv_len) & (pos <= q_pos) & (q_rel < nnew_ref[b])
    if sliding_window is not None:
        valid &= pos > q_pos - sliding_window

    q = jnp.transpose(q_ref[0], (1, 0, 2, 3)).reshape(hkv, block_q * g, -1)
    k = k_ref[0]   # [Hkv, PS, D] int8
    ks = ks_ref[0]  # [Hkv, PS] f32

    s = jax.lax.dot_general(
        q.astype(jnp.float32), k.astype(jnp.float32),
        (((2,), (2,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    ) * ks[:, None, :]
    s = s.reshape(rows, page_size) * scale
    s = jnp.where(valid, s, _NEG_INF)

    m_prev = m_ref[:, :1]
    l_prev = l_ref[:, :1]
    m_cur = jnp.max(s, axis=-1, keepdims=True)
    m_new = jnp.maximum(m_prev, m_cur)
    alpha = jnp.exp(m_prev - m_new)
    p = jnp.where(valid, jnp.exp(s - m_new), 0.0)

    l_ref[:] = jnp.broadcast_to(
        alpha * l_prev + jnp.sum(p, axis=-1, keepdims=True), l_ref.shape
    )
    m_ref[:] = jnp.broadcast_to(m_new, m_ref.shape)

    v = v_ref[0]    # [Hkv, PS, D] int8
    vs = vs_ref[0]  # [Hkv, PS] f32
    pw = p.reshape(hkv, block_q * g, page_size) * vs[:, None, :]
    pv = jax.lax.dot_general(
        pw, v.astype(jnp.float32), (((2,), (1,)), ((0,), (0,))),
        preferred_element_type=jnp.float32,
    )
    acc_ref[:] = acc_ref[:] * alpha + pv.reshape(rows, -1)

    @pl.when(j == num_page_blocks - 1)
    def _finalize():
        l = l_ref[:, :1]
        out = acc_ref[:] / jnp.maximum(l, 1e-20)
        out = out.reshape(hkv, block_q, g, -1)
        out_ref[0] = jnp.transpose(out, (1, 0, 2, 3)).astype(out_ref.dtype)


def _prep(q, page_size, block_q):
    b, s, hq, d = q.shape
    if block_q is None:
        block_q = min(128, _next_pow2(s))
    s_pad = -(-s // block_q) * block_q
    return b, s, hq, d, block_q, s_pad


def ragged_paged_attention(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,
    kv_lengths: jnp.ndarray,
    num_new: jnp.ndarray,
    q_start: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
    sliding_window: Optional[int] = None,
    block_q: Optional[int] = None,
    interpret: Optional[bool] = None,
):
    """Ragged mixed-phase attention straight over the page pool.

    ``q``: ``[B, S, Hq, D]`` (already rotated; rows ragged — row ``b``'s
    first ``num_new[b]`` tokens are real, the rest pad); ``k_pages`` /
    ``v_pages``: ``[P, Hkv, page_size, D]`` one layer's pool, keys stored
    rotated; ``page_table``: ``[B, T]`` int32 physical page ids (slot order
    = position order, 0 = null page); ``kv_lengths``: ``[B]`` int32 live kv
    per row INCLUDING this call's scattered tokens; ``num_new``: ``[B]``
    int32 valid query count per row (1 = decode row, C = chunk row, full
    prompt = prefill row — one grid serves all three); ``q_start``: ``[B]``
    absolute position of each row's first query (defaults to
    ``kv_lengths - num_new`` — queries are the newest tokens). Returns
    ``[B, S, Hq, D]`` with pad query rows zeroed.
    """
    _, hkv, page_size, _ = k_pages.shape
    b, s, hq, d, bq, s_pad = _prep(q, page_size, block_q)
    t = page_table.shape[1]
    g = hq // hkv
    if scale is None:
        scale = d**-0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if q_start is None:
        q_start = kv_lengths - num_new

    qr = q.reshape(b, s, hkv, g, d)
    if s_pad != s:
        qr = jnp.pad(qr, ((0, 0), (0, s_pad - s), (0, 0), (0, 0), (0, 0)))

    def _page_index(bi, qi, ji, table, lens, qstart, nnew):
        # Clamp dead blocks to the null page: past the row's live span, past
        # this q-block's causal frontier, or under a q-block past the row's
        # query count. The fetch still happens (BlockSpec semantics) but
        # hits one hot page.
        live = (
            (ji * page_size < lens[bi])
            & (qi * bq < nnew[bi])
            & (ji * page_size <= qstart[bi] + qi * bq + bq - 1)
        )
        return (jnp.where(live, table[bi, ji], 0), 0, 0, 0)

    def _q_index(bi, qi, ji, table, lens, qstart, nnew):
        return (bi, qi, 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(b, s_pad // bq, t),
        in_specs=[
            pl.BlockSpec((1, bq, hkv, g, d), _q_index),
            pl.BlockSpec((1, hkv, page_size, d), _page_index),
            pl.BlockSpec((1, hkv, page_size, d), _page_index),
        ],
        out_specs=pl.BlockSpec((1, bq, hkv, g, d), _q_index),
        scratch_shapes=[
            pltpu.VMEM((hkv * bq * g, d), jnp.float32),
            pltpu.VMEM((hkv * bq * g, 128), jnp.float32),
            pltpu.VMEM((hkv * bq * g, 128), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _ragged_kernel,
        scale=scale,
        page_size=page_size,
        num_page_blocks=t,
        block_q=bq,
        sliding_window=sliding_window,
        hkv=hkv,
        g=g,
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, s_pad, hkv, g, d), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(page_table.astype(jnp.int32), kv_lengths.astype(jnp.int32),
      q_start.astype(jnp.int32), num_new.astype(jnp.int32),
      qr, k_pages, v_pages)
    return out[:, :s].reshape(b, s, hq, d)


def quantized_ragged_paged_attention(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    ks_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    vs_pages: jnp.ndarray,
    page_table: jnp.ndarray,
    kv_lengths: jnp.ndarray,
    num_new: jnp.ndarray,
    q_start: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
    sliding_window: Optional[int] = None,
    block_q: Optional[int] = None,
    interpret: Optional[bool] = None,
):
    """As :func:`ragged_paged_attention` over int8 pages with per-(slot,
    head) scale planes (``ks_pages``/``vs_pages``: ``[P, Hkv, page_size]``
    f32)."""
    _, hkv, page_size, _ = k_pages.shape
    b, s, hq, d, bq, s_pad = _prep(q, page_size, block_q)
    t = page_table.shape[1]
    g = hq // hkv
    if scale is None:
        scale = d**-0.5
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    if q_start is None:
        q_start = kv_lengths - num_new

    qr = q.reshape(b, s, hkv, g, d)
    if s_pad != s:
        qr = jnp.pad(qr, ((0, 0), (0, s_pad - s), (0, 0), (0, 0), (0, 0)))

    def _page_index(bi, qi, ji, table, lens, qstart, nnew):
        live = (
            (ji * page_size < lens[bi])
            & (qi * bq < nnew[bi])
            & (ji * page_size <= qstart[bi] + qi * bq + bq - 1)
        )
        return (jnp.where(live, table[bi, ji], 0), 0, 0, 0)

    def _page_index3(bi, qi, ji, table, lens, qstart, nnew):
        live = (
            (ji * page_size < lens[bi])
            & (qi * bq < nnew[bi])
            & (ji * page_size <= qstart[bi] + qi * bq + bq - 1)
        )
        return (jnp.where(live, table[bi, ji], 0), 0, 0)

    def _q_index(bi, qi, ji, table, lens, qstart, nnew):
        return (bi, qi, 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(b, s_pad // bq, t),
        in_specs=[
            pl.BlockSpec((1, bq, hkv, g, d), _q_index),
            pl.BlockSpec((1, hkv, page_size, d), _page_index),
            pl.BlockSpec((1, hkv, page_size), _page_index3),
            pl.BlockSpec((1, hkv, page_size, d), _page_index),
            pl.BlockSpec((1, hkv, page_size), _page_index3),
        ],
        out_specs=pl.BlockSpec((1, bq, hkv, g, d), _q_index),
        scratch_shapes=[
            pltpu.VMEM((hkv * bq * g, d), jnp.float32),
            pltpu.VMEM((hkv * bq * g, 128), jnp.float32),
            pltpu.VMEM((hkv * bq * g, 128), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _qragged_kernel,
        scale=scale,
        page_size=page_size,
        num_page_blocks=t,
        block_q=bq,
        sliding_window=sliding_window,
        hkv=hkv,
        g=g,
    )
    out = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((b, s_pad, hkv, g, d), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(page_table.astype(jnp.int32), kv_lengths.astype(jnp.int32),
      q_start.astype(jnp.int32), num_new.astype(jnp.int32),
      qr, k_pages, ks_pages, v_pages, vs_pages)
    return out[:, :s].reshape(b, s, hq, d)


def latent_ragged_paged_attention(
    q: jnp.ndarray,
    c_pages: jnp.ndarray,
    page_table: jnp.ndarray,
    kv_lengths: jnp.ndarray,
    num_new: jnp.ndarray,
    q_start: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
    sliding_window: Optional[int] = None,
    block_q: Optional[int] = None,
    interpret: Optional[bool] = None,
):
    """Absorbed-MLA ragged attention reading the latent pool in place.

    ``c_pages``: ``[P, 1, page_size, lat_dim]`` — one layer's pool of
    fused ``[c ; k_rope]`` latents (f32, rope pre-applied to the rope
    slice by the model); ``q``: the absorbed query ``[B, S, Hq,
    lat_dim]``. Because the key up-projection is folded into ``q`` and
    the value up-projection is deferred past the softmax
    (``models/llama.py:_latent_decoder_layer``), attention runs with
    ``K = V =`` the STORED latent: the kernel's existing page-table walk
    IS the latent→K/V decompression fusion — no per-token K/V ever
    materializes, on-chip or off. Output: ``[B, S, Hq, lat_dim]`` whose
    first ``rank`` dims are the latent-space attention result.
    """
    return ragged_paged_attention(
        q, c_pages, c_pages, page_table, kv_lengths, num_new,
        q_start=q_start, scale=scale, sliding_window=sliding_window,
        block_q=block_q, interpret=interpret,
    )


def quantized_latent_ragged_paged_attention(
    q: jnp.ndarray,
    c_pages: jnp.ndarray,
    cs_pages: jnp.ndarray,
    page_table: jnp.ndarray,
    kv_lengths: jnp.ndarray,
    num_new: jnp.ndarray,
    q_start: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
    sliding_window: Optional[int] = None,
    block_q: Optional[int] = None,
    interpret: Optional[bool] = None,
):
    """As :func:`latent_ragged_paged_attention` over the int8 latent pool
    (``cs_pages``: ``[P, 1, page_size]`` per-token f32 scales); the int8
    pages stream through VMEM as-is and dequantize on the scores."""
    return quantized_ragged_paged_attention(
        q, c_pages, cs_pages, c_pages, cs_pages, page_table, kv_lengths,
        num_new, q_start=q_start, scale=scale,
        sliding_window=sliding_window, block_q=block_q, interpret=interpret,
    )


def ragged_attention_reference(
    q: jnp.ndarray,
    k_pages: jnp.ndarray,
    v_pages: jnp.ndarray,
    page_table: jnp.ndarray,
    kv_lengths: jnp.ndarray,
    num_new: jnp.ndarray,
    ks_pages: Optional[jnp.ndarray] = None,
    vs_pages: Optional[jnp.ndarray] = None,
    q_start: Optional[jnp.ndarray] = None,
    scale: Optional[float] = None,
    sliding_window: Optional[int] = None,
):
    """XLA oracle for the ragged kernels: gathers the table span into a
    contiguous view (the exact copy the kernel exists to avoid) and runs a
    masked f32 softmax. Tests compare against this; dequantizes int8 pools
    when scale planes are given."""
    b, s, hq, d = q.shape
    _, hkv, page_size, _ = k_pages.shape
    t = page_table.shape[1]
    g = hq // hkv
    if scale is None:
        scale = d**-0.5
    if q_start is None:
        q_start = kv_lengths - num_new

    k = jnp.take(k_pages, page_table, axis=0)  # [B, T, Hkv, PS, D]
    v = jnp.take(v_pages, page_table, axis=0)
    k = jnp.moveaxis(k, 2, 3).reshape(b, t * page_size, hkv, d)
    v = jnp.moveaxis(v, 2, 3).reshape(b, t * page_size, hkv, d)
    if ks_pages is not None:
        ks = jnp.take(ks_pages, page_table, axis=0)  # [B, T, Hkv, PS]
        vs = jnp.take(vs_pages, page_table, axis=0)
        ks = jnp.moveaxis(ks, 2, 3).reshape(b, t * page_size, hkv)
        vs = jnp.moveaxis(vs, 2, 3).reshape(b, t * page_size, hkv)
        k = k.astype(jnp.float32) * ks[..., None]
        v = v.astype(jnp.float32) * vs[..., None]

    qr = q.reshape(b, s, hkv, g, d)
    scores = jnp.einsum(
        "bshgd,bthd->bhgst", qr.astype(jnp.float32), k.astype(jnp.float32)
    ) * scale

    q_pos = q_start[:, None] + jnp.arange(s)[None, :]          # [B, S]
    kv_pos = jnp.arange(t * page_size)[None, :]                # [1, KV]
    valid = (
        (kv_pos[:, None, :] <= q_pos[:, :, None])
        & (kv_pos[:, None, :] < kv_lengths[:, None, None])
    )                                                          # [B, S, KV]
    if sliding_window is not None:
        valid &= kv_pos[:, None, :] > q_pos[:, :, None] - sliding_window
    scores = jnp.where(valid[:, None, None], scores, _NEG_INF)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum("bhgst,bthd->bshgd", probs, v.astype(jnp.float32))
    q_valid = jnp.arange(s)[None, :] < num_new[:, None]        # [B, S]
    out = jnp.where(q_valid[..., None, None, None], out, 0.0)
    return out.reshape(b, s, hq, d).astype(q.dtype)
