"""Pallas int4-weight matmul for bandwidth-bound decode.

Why a kernel: the XLA int4 path (``ops/quant.py:matmul`` on a
:class:`QuantizedTensor4` — arithmetic nibble unpack + einsum over
the packed pair axis) reads only the packed half-byte per value from HBM, but
the pair-axis contraction shape keeps the MXU from tiling it like a plain
matmul — measured r2: int4 weights LOST to int8 (2,682 vs 3,139 tok/s at
Llama-7B decode) despite half the weight bytes. Here the packed bytes stream
through VMEM once, nibbles are sign-extended in VMEM (int32 domain — Mosaic
has no int8 shifts), and two plain
``[BIN, BOUTP]`` MXU matmuls consume the halves with per-channel scales folded
in at the epilogue — HBM traffic is the int4 bytes.

Packing layout ("half-split", cf. the XLA path's adjacent-pair packing): byte
column ``j`` holds channel ``j`` in the low nibble and channel
``j + OUT_pad/2`` in the high nibble, so the two unpacked tiles are the
*contiguous* first/last halves of the output. The kernel keeps the halves as
two separate outputs with clean ``[B, BOUTP]`` blocks — a fused ``[B, 2, X]``
output forces a degenerate ``T(2,128)`` tiling (4x sublane waste on every
accumulate; measured 60x off the roofline in the first version of this
kernel) — and the caller concatenates once. Weights are padded to tile
multiples at quantization time (``pack_int4_split``), not per step.

The reference's deployment play was quantized serving via bitsandbytes
(``/root/reference/distributed_llm_inference/utils/model.py:93-123``,
CUDA-only); this is its TPU-native int4 half. Runs in interpret mode off-TPU
so CPU tests exercise the same code path.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

__all__ = ["pack_int4_split", "int4_matmul", "unpack_int4_split"]

# Tile sizes: BIN x BOUTP packed bytes per DMA (512 KB) — big enough that
# DMA issue overhead amortizes, small enough that the unpacked halves and two
# f32 accumulators stay a few MB of VMEM.
_BIN = 1024
_BOUTP = 512


def _pad_to(n: int, m: int) -> int:
    return -(-n // m) * m


def pack_int4_split(
    q: jnp.ndarray, in_pad: Optional[int] = None, out_pad: Optional[int] = None
) -> jnp.ndarray:
    """Pack int4 values ``[..., in, out]`` (int8 container, range [-7, 7])
    into half-split bytes ``[..., in_pad, out_pad // 2]``.

    Channel ``j`` → low nibble of byte column ``j``; channel
    ``j + out_pad/2`` → high nibble. Padding rows/channels are zero.
    """
    *lead, in_dim, out = q.shape
    in_pad = in_pad or _pad_to(in_dim, _BIN)
    out_pad = out_pad or _pad_to(out, 2 * _BOUTP)
    widths = [(0, 0)] * len(lead) + [(0, in_pad - in_dim), (0, out_pad - out)]
    qp = jnp.pad(q, widths)
    lo = qp[..., : out_pad // 2]
    hi = qp[..., out_pad // 2 :]
    return jnp.bitwise_or(
        jnp.bitwise_and(lo, jnp.int8(0x0F)), jnp.left_shift(hi, jnp.int8(4))
    )


def unpack_int4_split(packed: jnp.ndarray) -> jnp.ndarray:
    """Inverse of :func:`pack_int4_split` (padded shape): ``[..., in_pad,
    out_pad]`` int8 values. XLA fallback path for many-row (prefill) calls."""
    lo = jnp.right_shift(
        jnp.left_shift(packed, jnp.int8(4)), jnp.int8(4)
    )
    hi = jnp.right_shift(packed, jnp.int8(4))
    return jnp.concatenate([lo, hi], axis=-1)


def _int4_kernel(
    x_ref, w_ref, slo_ref, shi_ref, olo_ref, ohi_ref, alo_ref, ahi_ref,
    *, n_in: int,
):
    """One (out-tile, in-tile) grid step.

    ``x_ref``: ``[B, BIN]``; ``w_ref``: packed int8 ``[BIN, BOUTP]``;
    ``slo_ref``/``shi_ref``: f32 ``[1, BOUTP]`` channel scales;
    ``olo_ref``/``ohi_ref``: ``[B, BOUTP]`` halves of the output;
    ``alo_ref``/``ahi_ref``: f32 VMEM accumulators ``[B, BOUTP]``.

    """
    ii = pl.program_id(1)

    @pl.when(ii == 0)
    def _init():
        alo_ref[:] = jnp.zeros_like(alo_ref)
        ahi_ref[:] = jnp.zeros_like(ahi_ref)

    # int32-domain unpack: Mosaic cannot lower int8 left_shift (HTTP 500 on
    # this platform's compiler); the sign-extending int8→int32 convert makes
    # the arithmetic right shift recover hi directly.
    w32 = w_ref[...].astype(jnp.int32)
    x = x_ref[...]
    lo = jnp.right_shift(jnp.left_shift(w32, 28), 28).astype(x.dtype)
    hi = jnp.right_shift(w32, 4).astype(x.dtype)
    alo_ref[...] += jnp.dot(x, lo, preferred_element_type=jnp.float32)
    ahi_ref[...] += jnp.dot(x, hi, preferred_element_type=jnp.float32)

    @pl.when(ii == n_in - 1)
    def _finalize():
        olo_ref[...] = (alo_ref[...] * slo_ref[...]).astype(olo_ref.dtype)
        ohi_ref[...] = (ahi_ref[...] * shi_ref[...]).astype(ohi_ref.dtype)


def _kernel_tiles(in_pad: int, outp: int) -> Tuple[int, int]:
    bin_ = _BIN if in_pad % _BIN == 0 else np.gcd(in_pad, _BIN)
    boutp = _BOUTP if outp % _BOUTP == 0 else np.gcd(outp, _BOUTP)
    return int(bin_), int(boutp)


def int4_matmul(
    x: jnp.ndarray,
    packed: jnp.ndarray,
    scale_lo: jnp.ndarray,
    scale_hi: jnp.ndarray,
    out_dim: int,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """``x @ w`` with half-split-packed int4 weights and per-channel scales.

    ``x``: ``[..., in]``; ``packed``: ``[in_pad, out_pad // 2]`` int8
    (:func:`pack_int4_split`); ``scale_lo``/``scale_hi``: f32
    ``[1, out_pad // 2]`` (pre-split at quantization time — per-call slicing
    of a combined array materializes copies every decode step); returns
    ``[..., out_dim]`` in x's dtype.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    *lead, in_dim = x.shape
    in_pad, outp = packed.shape
    b = int(np.prod(lead)) if lead else 1
    x2 = x.reshape(b, in_dim)
    # Row padding: bf16 VMEM tiles are (16, 128); f32 accumulators (8, 128).
    bp = _pad_to(max(b, 16), 16)
    if in_pad != in_dim or bp != b:
        x2 = jnp.pad(x2, ((0, bp - b), (0, in_pad - in_dim)))

    bin_, boutp = _kernel_tiles(in_pad, outp)
    n_in = in_pad // bin_
    n_out = outp // boutp

    s_lo = scale_lo.reshape(1, outp).astype(jnp.float32)
    s_hi = scale_hi.reshape(1, outp).astype(jnp.float32)

    out_lo, out_hi = pl.pallas_call(
        functools.partial(_int4_kernel, n_in=n_in),
        grid=(n_out, n_in),
        in_specs=[
            pl.BlockSpec((bp, bin_), lambda oi, ii: (0, ii)),
            pl.BlockSpec((bin_, boutp), lambda oi, ii: (ii, oi)),
            pl.BlockSpec((1, boutp), lambda oi, ii: (0, oi)),
            pl.BlockSpec((1, boutp), lambda oi, ii: (0, oi)),
        ],
        out_specs=[
            pl.BlockSpec((bp, boutp), lambda oi, ii: (0, oi)),
            pl.BlockSpec((bp, boutp), lambda oi, ii: (0, oi)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bp, outp), x.dtype),
            jax.ShapeDtypeStruct((bp, outp), x.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((bp, boutp), jnp.float32),
            pltpu.VMEM((bp, boutp), jnp.float32),
        ],
        interpret=interpret,
    )(x2, packed, s_lo, s_hi)
    y = jnp.concatenate([out_lo, out_hi], axis=-1)[:b, :out_dim]
    return y.reshape(*lead, out_dim)


def _int4_stacked_kernel(
    lidx_ref, x_ref, w_ref, slo_ref, shi_ref, olo_ref, ohi_ref,
    alo_ref, ahi_ref, *, n_in: int,
):
    """As :func:`_int4_kernel`, but the weight/scale operands carry a
    leading layer axis the block index map already resolved (refs peel one
    unit dim)."""
    ii = pl.program_id(1)

    @pl.when(ii == 0)
    def _init():
        alo_ref[:] = jnp.zeros_like(alo_ref)
        ahi_ref[:] = jnp.zeros_like(ahi_ref)

    w32 = w_ref[0].astype(jnp.int32)
    x = x_ref[...]
    lo = jnp.right_shift(jnp.left_shift(w32, 28), 28).astype(x.dtype)
    hi = jnp.right_shift(w32, 4).astype(x.dtype)
    alo_ref[...] += jnp.dot(x, lo, preferred_element_type=jnp.float32)
    ahi_ref[...] += jnp.dot(x, hi, preferred_element_type=jnp.float32)

    @pl.when(ii == n_in - 1)
    def _finalize():
        olo_ref[...] = (alo_ref[...] * slo_ref[0]).astype(olo_ref.dtype)
        ohi_ref[...] = (ahi_ref[...] * shi_ref[0]).astype(ohi_ref.dtype)


def int4_matmul_stacked(
    x: jnp.ndarray,
    packed: jnp.ndarray,
    scale_lo: jnp.ndarray,
    scale_hi: jnp.ndarray,
    layer_idx: jnp.ndarray,
    out_dim: int,
    interpret: Optional[bool] = None,
) -> jnp.ndarray:
    """:func:`int4_matmul` over the WHOLE layer-stacked weight with a traced
    layer index resolved in the block index map.

    Inside the decode's layer scan, slicing one layer's packed weight to
    feed the kernel materializes an HBM copy of it every (layer, step) —
    read + write + kernel re-read ≈ 3x the weight bytes, which is why int4
    decode measured SLOWER than int8 despite half the bytes. The stacked
    operand is zero-copy; the kernel DMAs exactly the tiles it contracts.

    ``packed``: int8 ``[L, in_pad, out_pad // 2]``; ``scale_lo/hi``: f32
    ``[L, 1, out_pad // 2]``; ``layer_idx``: traced int32 scalar.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    *lead, in_dim = x.shape
    num_l, in_pad, outp = packed.shape
    b = int(np.prod(lead)) if lead else 1
    x2 = x.reshape(b, in_dim)
    bp = _pad_to(max(b, 16), 16)
    if in_pad != in_dim or bp != b:
        x2 = jnp.pad(x2, ((0, bp - b), (0, in_pad - in_dim)))

    bin_, boutp = _kernel_tiles(in_pad, outp)
    n_in = in_pad // bin_
    n_out = outp // boutp

    s_lo = scale_lo.reshape(num_l, 1, outp).astype(jnp.float32)
    s_hi = scale_hi.reshape(num_l, 1, outp).astype(jnp.float32)
    lref = jnp.asarray(layer_idx, jnp.int32).reshape(1)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_out, n_in),
        in_specs=[
            pl.BlockSpec((bp, bin_), lambda oi, ii, lidx: (0, ii)),
            pl.BlockSpec(
                (1, bin_, boutp), lambda oi, ii, lidx: (lidx[0], ii, oi)
            ),
            pl.BlockSpec(
                (1, 1, boutp), lambda oi, ii, lidx: (lidx[0], 0, oi)
            ),
            pl.BlockSpec(
                (1, 1, boutp), lambda oi, ii, lidx: (lidx[0], 0, oi)
            ),
        ],
        out_specs=(
            pl.BlockSpec((bp, boutp), lambda oi, ii, lidx: (0, oi)),
            pl.BlockSpec((bp, boutp), lambda oi, ii, lidx: (0, oi)),
        ),
        scratch_shapes=[
            pltpu.VMEM((bp, boutp), jnp.float32),
            pltpu.VMEM((bp, boutp), jnp.float32),
        ],
    )
    out_lo, out_hi = pl.pallas_call(
        functools.partial(_int4_stacked_kernel, n_in=n_in),
        grid_spec=grid_spec,
        out_shape=(
            jax.ShapeDtypeStruct((bp, outp), x.dtype),
            jax.ShapeDtypeStruct((bp, outp), x.dtype),
        ),
        interpret=interpret,
    )(lref, x2, packed, s_lo, s_hi)
    y = jnp.concatenate([out_lo, out_hi], axis=-1)[:b, :out_dim]
    return y.reshape(*lead, out_dim)
