"""KV-plane wire codec for disaggregated prefill ("Move the Query, Not
the Cache", arxiv 2606.01502, characterizes the redistribution cost this
engineers around).

A prefill worker exports a session's KV as contiguous per-layer planes —
``{"k": [L, S, Hkv, D], "v": ...}`` for value caches, plus
``{"ks": [L, S, Hkv], "vs": ...}`` f32 scales when the source cache is
int8-quantized. The codec serializes the planes into ONE payload blob
(per-plane records, each length-prefixed so ragged dtypes coexist) and
splits it into relay frames of at most ``max_frame_bytes`` payload each.

Frame layout mirrors ``distributed.messages.pack_frame``::

    [header_len:4 BE][JSON header][payload chunk]

Every frame carries the full metadata header — ``gens`` (session ids),
``n_valid`` (tokens of valid KV), ``first_token``, ``quant``, ``chain``
(prompt hash chain, hex), ``ps`` (chain page size), ``dtypes``, frame
index ``i`` of ``n``, and a CRC-32 + total length over the whole blob —
so a receiver can detect loss, duplication, truncation, and reordering
without trusting frame arrival order. The relay's own per-frame CRC
handles transport corruption (a corrupt frame is dropped at the socket
layer and surfaces as a timeout here); the codec-level CRC guards the
reassembly itself. Any integrity violation raises ``ValueError`` — the
gateway treats that exactly like a timeout and falls back to local
prefill.
"""

from __future__ import annotations

import json
import struct
import zlib
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

from ..distributed.relay import RelayClient

__all__ = [
    "encode_kv", "decode_kv", "encode_error",
    "encode_session", "decode_session",
    "SchemaError", "VERSION", "LAYOUTS",
]

# Codec schema version. v2 added the explicit ``layout`` header key
# (value-cache vs latent stored form) — a v1 peer would misread a latent
# transfer as k/v planes, so decoders REJECT any version other than this
# one with :class:`SchemaError` (surfaced as a ``schema`` error reply by
# the workers) instead of guessing.
VERSION = 2

# Stored-form layouts a transfer may declare: conventional per-head K/V
# planes (``k``/``v`` + optional int8 scales) vs the latent (MLA) fused
# form (``c`` + optional ``cs`` scales). The importer still validates
# shapes/names against its own cache; the header key exists so skew is a
# typed schema error at decode time, never a misparse.
LAYOUTS = ("kv", "latent")

# Header keys that must agree across every frame of one transfer.
# ``op``/``session``/``att`` arrived with session migration (checkpoint
# frames); pre-migration frames simply lack them, which reads back as a
# consistent ``None`` — the codec stays wire-compatible in both
# directions. ``att`` is the gateway's attempt tag: recovery consumers
# fence frames whose tag predates the current attempt (zombie replies).
_CONSISTENT = ("gens", "n", "n_valid", "first_token", "quant", "chain",
               "ps", "crc", "total", "dtypes", "op", "session", "att",
               "layout")


class SchemaError(ValueError):
    """A frame whose schema this codec does not speak: unknown codec
    version or undeclared/unknown stored-form layout. Distinct from the
    plain ``ValueError`` integrity violations (loss, truncation, CRC)
    so workers can answer with a ``schema`` error code — the peer's
    fix is an upgrade, not a retry."""


def _pack(header: dict, chunk: bytes = b"") -> bytes:
    hdr = json.dumps(header).encode()
    return struct.pack(">I", len(hdr)) + hdr + chunk


def _unpack(frame: bytes) -> Tuple[dict, bytes]:
    if len(frame) < 4:
        raise ValueError("kv frame shorter than its header length field")
    (hlen,) = struct.unpack_from(">I", frame, 0)
    if len(frame) < 4 + hlen:
        raise ValueError("kv frame truncated inside its header")
    header = json.loads(frame[4 : 4 + hlen].decode())
    return header, frame[4 + hlen :]


def _layout_of(planes: Dict[str, "np.ndarray"]) -> str:
    """Stored-form layout of a plane dict — ``"latent"`` when any plane
    (bare or page-prefixed ``"<i>/<plane>"``) is a latent record."""
    for name in planes:
        if name.rpartition("/")[2] in ("c", "cs"):
            return "latent"
    return "kv"


def _encode_plane(name: str, arr) -> bytes:
    a = np.asarray(arr)
    if a.dtype.name == "bfloat16":  # ml_dtypes: ship raw bits (relay idiom)
        body = RelayClient.encode_array(a.view(np.uint16), "bfloat16")
    else:
        body = RelayClient.encode_array(a)
    nb = name.encode()
    return struct.pack(">B", len(nb)) + nb + struct.pack(">Q", len(body)) + body


def encode_kv(
    gen_id: str,
    planes: Dict[str, "np.ndarray"],
    n_valid: int,
    first_token: int,
    chain: Sequence[bytes] = (),
    *,
    page_size: int = 0,
    quant: bool = False,
    max_frame_bytes: int = 4 * 1024 * 1024,
    op: Optional[str] = None,
    session: Optional[dict] = None,
    att: Optional[str] = None,
    trace=None,
) -> List[bytes]:
    """Serialize one session's KV planes into an ordered list of frames.

    ``op`` labels the transfer's purpose on the wire (``migrate.ckpt``
    for session checkpoints; ``None`` for plain prefill exports) and
    ``session`` carries the JSON-safe mid-decode state dict a checkpoint
    needs beyond KV — both ride every frame's header, like the rest of
    the consistent metadata. ``trace`` (a
    :class:`~..utils.tracing.TraceContext`, or None) stamps the standard
    flat ``trace``/``span`` ids on every frame so the transfer is
    attributable to its distributed trace."""
    payload = b"".join(_encode_plane(k, v) for k, v in planes.items())
    step = max(int(max_frame_bytes), 1)
    chunks = [payload[i : i + step] for i in range(0, len(payload), step)]
    if not chunks:
        chunks = [b""]
    header = {
        "v": VERSION,
        "layout": _layout_of(planes),
        "gens": [gen_id],
        "n": len(chunks),
        "n_valid": int(n_valid),
        "first_token": int(first_token),
        "quant": bool(quant),
        "chain": [c.hex() for c in chain],
        "ps": int(page_size),
        "crc": zlib.crc32(payload) & 0xFFFFFFFF,
        "total": len(payload),
        "dtypes": {k: np.asarray(v).dtype.name for k, v in planes.items()},
        "op": op,
        "session": session,
        "att": att,
        # Distributed-trace attribution (not in _CONSISTENT: absent on
        # pre-trace peers, and the ids never gate reassembly).
        "trace": trace.trace_id if trace is not None else None,
        "span": trace.span_id if trace is not None else None,
    }
    return [_pack(dict(header, i=i), c) for i, c in enumerate(chunks)]


def encode_error(gen_id: str, code: str) -> bytes:
    """Single error frame a prefill worker answers with on failure, so the
    gateway falls back immediately instead of waiting out its timeout."""
    return _pack({"v": VERSION, "gens": [gen_id], "error": code, "n": 1,
                  "i": 0})


def decode_kv(
    frames: Iterable[bytes],
) -> Tuple[Optional[Dict[str, "np.ndarray"]], dict]:
    """Reassemble and validate frames from :func:`encode_kv`.

    Returns ``(planes, meta)`` with ``meta["chain"]`` back as ``bytes``
    keys. An error frame returns ``(None, meta)`` with ``meta["error"]``
    set. Raises :class:`SchemaError` (a ``ValueError`` subclass) on
    version or layout skew, and plain ``ValueError`` on any other
    integrity violation: duplicate/missing/out-of-range frame index,
    inconsistent headers, length or CRC mismatch, or a malformed plane
    record.
    """
    base: Optional[dict] = None
    chunks: Dict[int, bytes] = {}
    for frame in frames:
        header, chunk = _unpack(frame)
        if header.get("v") != VERSION:
            raise SchemaError(
                f"unsupported kv codec version {header.get('v')!r} "
                f"(this decoder speaks v{VERSION})"
            )
        if "error" in header:
            return None, header
        if header.get("layout") not in LAYOUTS:
            raise SchemaError(
                f"unknown kv stored-form layout {header.get('layout')!r} "
                f"(known: {LAYOUTS})"
            )
        i = header.get("i")
        if base is None:
            base = {k: header.get(k) for k in _CONSISTENT}
            if None in (base["n"], base["crc"], base["total"]):
                raise ValueError("kv frame header missing required fields")
        elif any(header.get(k) != base[k] for k in _CONSISTENT):
            raise ValueError("kv frames disagree on transfer metadata")
        if not isinstance(i, int) or not 0 <= i < base["n"]:
            raise ValueError(f"kv frame index {i!r} outside 0..{base['n']}")
        if i in chunks:
            raise ValueError(f"duplicate kv frame {i}")
        chunks[i] = chunk
    if base is None:
        raise ValueError("empty kv transfer")
    if len(chunks) != base["n"]:
        missing = sorted(set(range(base["n"])) - set(chunks))
        raise ValueError(f"kv transfer missing frames {missing}")
    payload = b"".join(chunks[i] for i in range(base["n"]))
    if len(payload) != base["total"]:
        raise ValueError(
            f"kv payload length {len(payload)} != declared {base['total']}"
        )
    if zlib.crc32(payload) & 0xFFFFFFFF != base["crc"]:
        raise ValueError("kv payload CRC mismatch")
    planes: Dict[str, np.ndarray] = {}
    off = 0
    while off < len(payload):
        (nlen,) = struct.unpack_from(">B", payload, off)
        off += 1
        name = payload[off : off + nlen].decode()
        off += nlen
        (blen,) = struct.unpack_from(">Q", payload, off)
        off += 8
        body = payload[off : off + blen]
        off += blen
        if len(body) != blen:
            raise ValueError(f"kv plane {name!r} record truncated")
        arr, dtype = RelayClient.decode_array(body)
        if dtype == "bfloat16":
            import ml_dtypes

            arr = arr.view(ml_dtypes.bfloat16)
        planes[name] = arr
    meta = dict(base)
    meta["chain"] = [bytes.fromhex(c) for c in meta.get("chain") or []]
    return planes, meta


# Keys of engine.export_session's snapshot that travel in the header's
# ``session`` dict (everything but the binary KV planes).
_SESSION_FIELDS = ("prompt", "generated", "options", "rng", "resumes")


def encode_session(
    gen_id: str,
    snapshot: dict,
    *,
    page_size: int = 0,
    max_frame_bytes: int = 4 * 1024 * 1024,
    op: str = "migrate.ckpt",
    att: Optional[str] = None,
    extra_chain: Sequence[bytes] = (),
    trace=None,
) -> List[bytes]:
    """Serialize an ``engine.export_session`` snapshot into kv_codec
    frames: the KV planes ride the payload exactly like a prefill
    export, the JSON-safe session state (token tail, options, RNG key)
    rides every header. ``n_valid`` follows the KV-after-decode
    invariant (``len(prompt) + len(generated) - 1``) and
    ``first_token`` is the next decode input (``generated[-1]``)."""
    planes = snapshot["planes"]
    sess = {k: snapshot[k] for k in _SESSION_FIELDS}
    generated = snapshot["generated"]
    return encode_kv(
        gen_id,
        planes,
        n_valid=len(snapshot["prompt"]) + len(generated) - 1,
        first_token=int(generated[-1]),
        chain=extra_chain,
        page_size=page_size,
        quant="ks" in planes or "cs" in planes,
        max_frame_bytes=max_frame_bytes,
        op=op,
        session=sess,
        att=att,
        trace=trace,
    )


def decode_session(
    frames: Iterable[bytes],
) -> Tuple[Optional[dict], dict]:
    """Reassemble :func:`encode_session` frames back into a snapshot dict
    ``engine.resume_session`` accepts (planes + session state merged).

    Returns ``(snapshot, meta)``; an error frame returns ``(None, meta)``
    with ``meta["error"]`` set. Raises ``ValueError`` on any integrity
    violation :func:`decode_kv` detects, or when the frames carry no
    session state (a plain prefill transfer fed to the wrong decoder)."""
    planes, meta = decode_kv(frames)
    if planes is None:
        return None, meta
    sess = meta.get("session")
    if not isinstance(sess, dict):
        raise ValueError("kv frames carry no session state")
    missing = [k for k in _SESSION_FIELDS if k not in sess]
    if missing:
        raise ValueError(f"session snapshot missing fields {missing}")
    snapshot = dict(sess)
    snapshot["planes"] = planes
    return snapshot, meta


def encode_pages(
    gen_id: str,
    page_size: int,
    items: Sequence[Tuple[bytes, Dict[str, "np.ndarray"]]],
    *,
    max_frame_bytes: int = 4 * 1024 * 1024,
    op: str = "fleet.pages",
) -> List[bytes]:
    """Serialize content-addressed prefix pages for a fleet page-ship
    (node-to-node cache copy). Each ``(key, tiles)`` item is one page's
    stored-form tiles from ``engine.export_prefix_pages``; the tiles
    ride the payload as planes named ``"<index>/<plane>"`` and the page
    keys ride the header's ``chain`` in the same order, so the transfer
    reuses the existing frame schema with no new header fields."""
    planes: Dict[str, "np.ndarray"] = {}
    quant = False
    for i, (_, tiles) in enumerate(items):
        quant = quant or "ks" in tiles or "cs" in tiles
        for name, arr in tiles.items():
            planes[f"{i}/{name}"] = arr
    return encode_kv(
        gen_id, planes,
        n_valid=len(items) * int(page_size),
        first_token=-1,
        chain=[key for key, _ in items],
        page_size=page_size,
        quant=quant,
        max_frame_bytes=max_frame_bytes,
        op=op,
    )


def decode_pages(
    frames: Iterable[bytes],
) -> Tuple[Optional[List[Tuple[bytes, Dict[str, "np.ndarray"]]]], dict]:
    """Reassemble :func:`encode_pages` frames back into the ordered
    ``(key, tiles)`` list ``engine.import_prefix_pages`` accepts; the
    page size rides back in ``meta["ps"]``.

    Returns ``(items, meta)``; an error frame returns ``(None, meta)``
    with ``meta["error"]`` set. Raises ``ValueError`` on any integrity
    violation :func:`decode_kv` detects, or when a chain key has no
    tiles in the payload (a torn or mislabeled transfer)."""
    planes, meta = decode_kv(frames)
    if planes is None:
        return None, meta
    by_page: Dict[int, Dict[str, "np.ndarray"]] = {}
    for name, arr in planes.items():
        idx, _, plane = name.partition("/")
        by_page.setdefault(int(idx), {})[plane] = arr
    items: List[Tuple[bytes, Dict[str, "np.ndarray"]]] = []
    for i, key in enumerate(meta["chain"]):
        tiles = by_page.get(i)
        if not tiles:
            raise ValueError(f"page-ship payload missing page {i}")
        items.append((key, tiles))
    return items, meta
