"""Prefill-only worker: the admission half of disaggregated serving.

A :class:`PrefillWorker` wraps a full-model :class:`InferenceEngine` used
ONLY for bucketed prefill + the first-token sample: it registers with the
block directory under ``role="prefill"`` (so it never appears in decode
layer routes), consumes prompt requests off its relay queue, and answers
each with the session's KV planes as :mod:`.kv_codec` frames — or a
single error frame, so the gateway falls back to local prefill instead of
waiting out its transfer timeout.

Request frame (``messages.pack_frame`` JSON header, no array)::

    {"op": "prefill", "gen": <gateway id>, "reply": <reply queue>,
     "prompt": [int, ...], "options": {SamplingOptions fields},
     "max_frame_bytes": int, "trace": <id|None>, "span": <id|None>}

The ``trace``/``span`` ids (None when the request is unsampled) attach
this worker's ``prefill.export`` span to the request's distributed
trace; the gateway collects it back with ``op: "trace.pull"``.
``op: "shutdown"`` stops the worker (tests). Anything malformed is
dropped — a poisoned frame must not kill the pool member.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import uuid
from typing import Optional

from ..config import DisaggConfig
from ..distributed.directory import DirectoryClient
from ..distributed.messages import pack_frame, unpack_frame
from ..distributed.relay import RelayClient
from ..engine.sampling import SamplingOptions
from ..utils.tracing import SpanRecorder, TraceContext, trace_span
from .kv_codec import encode_error, encode_kv

__all__ = ["PrefillWorker"]

logger = logging.getLogger("distributed_llm_inference_tpu")

_OPT_FIELDS = {f.name for f in dataclasses.fields(SamplingOptions)}


def _options_from(payload) -> SamplingOptions:
    kw = {
        k: v for k, v in (payload or {}).items() if k in _OPT_FIELDS
    }
    return SamplingOptions(**kw)


class PrefillWorker:
    """Serve ``prefill_export`` over the relay (background threads)."""

    def __init__(
        self,
        relay_port: int,
        engine,
        host: str = "127.0.0.1",
        node_id: Optional[str] = None,
        disagg_cfg: Optional[DisaggConfig] = None,
        lease_ttl: float = 10.0,
        epoch: int = 1,
    ):
        self.engine = engine
        self.node_id = node_id or f"prefill-{uuid.uuid4().hex[:8]}"
        self.queue = f"prefill.{self.node_id}"
        self.host, self.relay_port = host, relay_port
        self.dcfg = disagg_cfg or DisaggConfig()
        self.lease_ttl = lease_ttl
        self.epoch = int(epoch)  # incarnation number (lease fencing)
        self.metrics = engine.metrics
        # Per-node span log for distributed traces: prefill.export spans
        # land here and ``trace.pull`` ships them back to the gateway.
        self.tracer = SpanRecorder(metrics=self.metrics)
        self._stop = threading.Event()
        # Directory load hint: the consume thread counts in-flight prefills,
        # the heartbeat thread reports them — cross-thread, so locked
        # (distcheck DC101: unguarded += here raced the heartbeat read).
        self._busy_lock = threading.Lock()
        self._busy = 0
        # Register FIRST (mirrors ServingNode): a directory/relay failure
        # here must not leak threads or sockets.
        self._directory = DirectoryClient(relay_port, host)
        try:
            self._register()
            self._out = RelayClient(host, relay_port)
        except Exception:
            self._directory.close()
            raise
        self._consume_thread = threading.Thread(
            target=self._consume, daemon=True, name=f"{self.node_id}.consume"
        )
        self._consume_thread.start()
        self._health_thread = threading.Thread(
            target=self._health_loop, daemon=True,
            name=f"{self.node_id}.health",
        )
        self._health_thread.start()

    def _register(self) -> bool:
        # A prefill worker holds the FULL model (it runs whole-prompt
        # prefill), so its advertised range is every layer; the role keeps
        # it out of decode routes regardless.
        return self._directory.register(
            self.node_id, 0, self.engine.cfg.num_layers - 1, self.queue,
            ttl=self.lease_ttl, role="prefill", epoch=self.epoch,
        )

    # -- serve loop -----------------------------------------------------------

    def _consume(self) -> None:
        client = RelayClient(self.host, self.relay_port)
        try:
            while not self._stop.is_set():
                try:
                    frame = client.get(self.queue, timeout=0.5)
                except TimeoutError:
                    continue
                except (ConnectionError, OSError):
                    return
                try:
                    header, _ = unpack_frame(frame)
                    op = header.get("op")
                except Exception:
                    # Malformed frame: drop but count — the sender's request
                    # is gone and only /metrics can say so.
                    self.metrics.counter("malformed_frames")
                    continue
                if op == "shutdown":
                    return  # distcheck: reply-ok(shutdown frames are fire-and-forget)
                if op == "trace.pull":
                    self._handle_trace_pull(header)
                    continue  # distcheck: reply-ok(trace.spans sent by _handle_trace_pull)
                if op != "prefill":
                    self.metrics.counter("unknown_ops_dropped")
                    continue
                reply = header.get("reply")
                if not reply:
                    continue  # distcheck: reply-ok(frame carries no reply address)
                with self._busy_lock:
                    self._busy += 1
                try:
                    self._handle(header, reply)
                finally:
                    with self._busy_lock:
                        self._busy -= 1
        finally:
            client.close()

    def _handle_trace_pull(self, header: dict) -> None:
        """Answer a gateway's span collection for one trace with a single
        ``trace.spans`` frame (spans ride the JSON header). Best-effort:
        the gateway budgets the whole round and renders partial traces."""
        reply, tid = header.get("reply"), header.get("trace")
        if not reply or not tid:
            return  # distcheck: reply-ok(frame carries no reply address)
        spans = [s.to_dict() for s in self.tracer.spans_for(str(tid))]
        try:
            self._out.put(reply, pack_frame({
                "op": "trace.spans", "trace": tid, "node": self.node_id,
                "spans": spans,
            }))
        except (ConnectionError, OSError):
            pass  # gateway's collect budget expires; partial trace renders

    def _handle(self, header: dict, reply: str) -> None:
        gen = str(header.get("gen", ""))
        ctx = TraceContext.from_header(header)
        try:
            prompt = [int(t) for t in header["prompt"]]
            opts = _options_from(header.get("options"))
            # The worker-side segment of the distributed trace: prompt
            # prefill + first-token sample + frame encode, parented under
            # the gateway's kv_transfer span; the encoded frames carry the
            # same child ids so transfer and compute stitch together.
            with trace_span(self.tracer, "prefill.export", ctx,
                            node=self.node_id, gen=gen,
                            prompt_tokens=len(header.get("prompt") or []),
                            ) as child:
                planes, first, chain = self.engine.prefill_export(
                    prompt, opts
                )
                frames = encode_kv(
                    gen, planes, len(prompt), first, chain,
                    page_size=self.engine.ccfg.page_size,
                    quant="ks" in planes or "cs" in planes,
                    max_frame_bytes=int(
                        header.get("max_frame_bytes")
                        or self.dcfg.kv_frame_bytes
                    ),
                    trace=child,
                )
            self.metrics.counter("disagg_kv_frames_sent", len(frames))
        except Exception as e:  # answer with an error, never wedge the peer
            logger.warning(
                "prefill %s failed on %s: %r", gen, self.node_id, e
            )
            self.metrics.counter("disagg_prefill_errors")
            try:
                self._out.put(reply, encode_error(gen, repr(e)))
            except (ConnectionError, OSError):
                pass
            return
        try:
            self._out.put_many((reply, f) for f in frames)
        except (ConnectionError, OSError):
            pass  # gateway times out and falls back locally

    # -- health ---------------------------------------------------------------

    def _health_loop(self) -> None:
        while not self._stop.wait(self.dcfg.heartbeat_s):
            try:
                with self._busy_lock:
                    load = self._busy
                alive = self._directory.heartbeat(
                    self.node_id, load=load, ttl=self.lease_ttl,
                    epoch=self.epoch,
                )
                if not alive:  # lease lapsed (e.g. directory restart)
                    if not self._register():
                        # Fenced: a gateway declared this incarnation dead.
                        # Stop serving rather than split-brain the pool.
                        self._stop.set()
                        return
            except Exception:
                continue  # transient control-plane failure: keep serving

    def is_healthy(self) -> bool:
        return self._consume_thread.is_alive()

    def stop(self) -> None:
        self._stop.set()
        self._consume_thread.join(timeout=5)
        self._health_thread.join(timeout=5)
        try:
            self._directory.remove(self.node_id)
        except Exception:
            pass
        self._directory.close()
        self._out.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
