"""Decode-pool node: the serving half of crash-recoverable decode.

A :class:`DecodeNode` wraps a full-model :class:`InferenceEngine` that
runs DECODE for remote gateways: it registers with the block directory
under ``role="decode"`` with a lease-fencing epoch, consumes session ops
off its relay queue, streams every generated token back as a
sequence-stamped ``migrate.tok`` frame, and periodically ships a full
session checkpoint (``kv_codec.encode_session`` frames, KV planes + RNG
key + token tail) so a gateway can re-home the stream onto another node
after this one dies — with zero token loss.

Request frames (``messages.pack_frame`` JSON headers)::

    {"op": "migrate.submit", "gen": <gateway id>, "reply": <queue>,
     "att": <attempt tag>, "prompt": [int, ...],
     "options": {SamplingOptions fields}, "deadline_s": float|None}

    {"op": "migrate.resume", "gen", "reply", "att",
     "kv": <queue holding a checkpoint>, "nf": <frame count>,
     "from": <gateway's delivered-token count>, "deadline_s": ...}

    {"op": "migrate.cancel", "gen"}       # stop one stream
    {"op": "shutdown"}                    # stop the node (tests)

Reply frames (to the request's ``reply`` queue, all stamped with the
request's ``att`` so a fenced attempt's frames are discardable)::

    {"op": "migrate.tok", "gen", "att", "seq", "tok", "fin", "reason"}
    {"op": "migrate.err", "gen", "att", "error"}     # admission failed
    kv_codec session frames with header op = "migrate.ckpt"

``seq`` is the token's index in the stream's GENERATED sequence — the
exactly-once dedup key. On ``migrate.resume`` the node first REPLAYS the
checkpoint's token tail from the gateway's ``from`` index (tokens the
source emitted after the gateway's last delivery but before its death
would otherwise be lost), then continues decoding; with the snapshot's
RNG restored on a quiet engine the continued stream is byte-exact vs an
uninterrupted run (see ``engine.resume_session``).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
import uuid
from typing import Dict, List, Optional, Tuple

from ..config import DisaggConfig
from ..distributed.directory import DirectoryClient
from ..distributed.messages import pack_frame, unpack_frame
from ..distributed.relay import RelayClient
from ..engine.sampling import SamplingOptions
from ..utils.tracing import Span, SpanRecorder, TraceContext, trace_span
from .kv_codec import (
    SchemaError, decode_pages, decode_session, encode_error, encode_pages,
    encode_session,
)


def _err_code(e: Exception) -> str:
    """Wire error code for a failed transfer: schema violations (codec
    version/layout skew — peer needs an upgrade, not a retry) answer with
    the typed ``schema`` code; everything else ships its repr."""
    return "schema" if isinstance(e, SchemaError) else repr(e)

__all__ = ["DecodeNode"]

logger = logging.getLogger("distributed_llm_inference_tpu")

_OPT_FIELDS = {f.name for f in dataclasses.fields(SamplingOptions)}


@dataclasses.dataclass
class _Route:
    """Per-stream bookkeeping: where tokens go and how they are stamped."""

    gen: str  # gateway-side request id
    reply: str  # relay queue the gateway consumes
    att: str  # attempt tag (fencing: stale attempts' frames are dropped)
    seq: int  # next sequence index to assign
    seq0: int  # first fresh index (tokens before it came from a snapshot)
    # Checkpoint tail replay for a resumed stream: (seq, token) pairs the
    # gateway had not yet delivered. Flushed before any fresh token.
    replay: List[Tuple[int, int]] = dataclasses.field(default_factory=list)
    ckpted: bool = False
    last_ckpt_tick: int = 0
    # Marked by a fleet.migrate rebalance request: the driver hands this
    # stream back to its gateway at the next tick boundary.
    handoff: bool = False
    # Distributed-trace context from the submit/resume frame's trace ids
    # (None = unsampled request: every tracing hook below skips), plus the
    # epoch time the route was admitted — the ``decode.first_token`` span
    # closes against it once.
    trace: Optional[object] = None
    t0: float = 0.0
    first_done: bool = False


class DecodeNode:
    """Serve recoverable decode streams over the relay (background
    threads): consume loop for ops, driver loop stepping the engine and
    fanning tokens/checkpoints out, heartbeat loop renewing the
    epoch-fenced directory lease."""

    def __init__(
        self,
        relay_port: int,
        engine,
        host: str = "127.0.0.1",
        node_id: Optional[str] = None,
        disagg_cfg: Optional[DisaggConfig] = None,
        lease_ttl: Optional[float] = None,
        epoch: int = 1,
    ):
        self.engine = engine
        self.node_id = node_id or f"decode-{uuid.uuid4().hex[:8]}"
        self.queue = f"decode.{self.node_id}"
        self.host, self.relay_port = host, relay_port
        self.dcfg = disagg_cfg or DisaggConfig()
        self.lease_ttl = (
            lease_ttl if lease_ttl is not None else self.dcfg.lease_ttl_s
        )
        self.epoch = int(epoch)  # incarnation number (lease fencing)
        self.metrics = engine.metrics
        # Per-node span log for distributed traces: decode admit/resume,
        # first-token and drain-handoff spans land here and ``trace.pull``
        # ships them back to the collecting gateway.
        self.tracer = SpanRecorder(metrics=self.metrics)
        self._stop = threading.Event()
        self._ticks = 0
        # distcheck: unguarded-ok(one-way bool set by the consume thread on
        # fleet.drain; the drive/health threads only read it, and a stale
        # read just delays the handoff/draining-advertise by one iteration)
        self._draining = False
        # engine gen_id -> _Route, plus the gateway-id reverse map for
        # cancels. Consume thread inserts, driver thread reads/retires —
        # every access under the lock; frames are SENT outside it.
        self._rlock = threading.Lock()
        self._routes: Dict[str, _Route] = {}
        self._by_gen: Dict[str, str] = {}
        # Register FIRST (mirrors PrefillWorker): a directory/relay
        # failure here must not leak threads or sockets.
        self._directory = DirectoryClient(relay_port, host)
        try:
            if not self._register():
                raise RuntimeError(
                    f"registration fenced: node {self.node_id} epoch "
                    f"{self.epoch} is stale — restart with a higher epoch"
                )
            self._out = RelayClient(host, relay_port)
        except Exception:
            self._directory.close()
            raise
        self._consume_thread = threading.Thread(
            target=self._consume, daemon=True, name=f"{self.node_id}.consume"
        )
        self._consume_thread.start()
        self._drive_thread = threading.Thread(
            target=self._drive, daemon=True, name=f"{self.node_id}.drive"
        )
        self._drive_thread.start()
        self._health_thread = threading.Thread(
            target=self._health_loop, daemon=True,
            name=f"{self.node_id}.health",
        )
        self._health_thread.start()

    def _register(self) -> bool:
        return self._directory.register(
            self.node_id, 0, self.engine.cfg.num_layers - 1, self.queue,
            ttl=self.lease_ttl, role="decode", epoch=self.epoch,
        )

    # -- op consume loop ------------------------------------------------------

    def _consume(self) -> None:
        client = RelayClient(self.host, self.relay_port)
        try:
            while not self._stop.is_set():
                try:
                    frame = client.get(self.queue, timeout=0.5)
                except TimeoutError:
                    continue
                except (ConnectionError, OSError):
                    return
                try:
                    header, _ = unpack_frame(frame)
                    op = header.get("op")
                except Exception:
                    self.metrics.counter("malformed_frames")
                    continue
                if op == "shutdown":
                    return  # distcheck: reply-ok(shutdown frames are fire-and-forget)
                if op == "migrate.cancel":
                    self._handle_cancel(header)
                    continue  # distcheck: reply-ok(cancel acks ride the token stream)
                if op == "fleet.drain":
                    self._handle_drain(header)
                    continue  # distcheck: reply-ok(fleet.ack sent by _handle_drain)
                if op == "fleet.migrate":
                    self._handle_migrate(header)
                    continue  # distcheck: reply-ok(fleet.ack sent by _handle_migrate)
                if op == "fleet.pages":
                    self._handle_pages(header)
                    continue  # distcheck: reply-ok(page frames or an error frame sent)
                if op == "fleet.pages.put":
                    self._handle_pages_put(header, client)
                    continue  # distcheck: reply-ok(fleet.ack/nack sent by the handler)
                if op == "trace.pull":
                    self._handle_trace_pull(header)
                    continue  # distcheck: reply-ok(trace.spans sent by _handle_trace_pull)
                if op not in ("migrate.submit", "migrate.resume"):
                    self.metrics.counter("unknown_ops_dropped")
                    continue
                reply = header.get("reply")
                if not reply:
                    continue  # distcheck: reply-ok(frame carries no reply address)
                if op == "migrate.submit":
                    self._handle_submit(header, reply)
                else:
                    self._handle_resume(header, reply, client)
        finally:
            client.close()

    @staticmethod
    def _deadline_from(header: dict) -> Optional[float]:
        d = header.get("deadline_s")
        return None if d is None else time.monotonic() + float(d)

    def _handle_submit(self, header: dict, reply: str) -> None:
        gen = str(header.get("gen", ""))
        att = str(header.get("att", ""))
        ctx = TraceContext.from_header(header)
        try:
            prompt = [int(t) for t in header["prompt"]]
            opts = SamplingOptions(**{
                k: v for k, v in (header.get("options") or {}).items()
                if k in _OPT_FIELDS
            })
            with trace_span(self.tracer, "decode.admit", ctx,
                            node=self.node_id, gen=gen):
                gid = self.engine.submit(
                    prompt, opts, deadline=self._deadline_from(header),
                    trace=ctx,
                )
        except Exception as e:
            logger.warning("submit %s failed on %s: %r", gen, self.node_id, e)
            self._send_err(reply, gen, att, repr(e))
            return  # distcheck: reply-ok(migrate.err reply sent via _send_err)
        with self._rlock:
            self._routes[gid] = _Route(gen=gen, reply=reply, att=att,
                                       seq=0, seq0=0,
                                       trace=ctx, t0=time.time())
            self._by_gen[gen] = gid

    def _handle_resume(self, header: dict, reply: str,
                       client: RelayClient) -> None:
        gen = str(header.get("gen", ""))
        att = str(header.get("att", ""))
        ctx = TraceContext.from_header(header)
        try:
            kvq = header["kv"]
            nf = int(header["nf"])
            frm = int(header.get("from") or 0)
            with trace_span(self.tracer, "decode.resume", ctx,
                            node=self.node_id, gen=gen):
                budget = time.monotonic() + self.dcfg.transfer_timeout_s
                frames = []
                for _ in range(nf):
                    frames.append(client.get(
                        kvq, timeout=max(budget - time.monotonic(), 0.001)
                    ))
                snap, _meta = decode_session(frames)
                if snap is None:
                    raise ValueError(
                        "checkpoint transfer carried an error frame"
                    )
                tail = [int(t) for t in snap["generated"]]
                gid = self.engine.resume_session(
                    snap, deadline=self._deadline_from(header), trace=ctx,
                )
                if gid is None:
                    raise RuntimeError("no decode slot free (pool pressure)")
        except Exception as e:
            logger.warning("resume %s failed on %s: %r", gen, self.node_id, e)
            self._send_err(reply, gen, att, _err_code(e))
            return  # distcheck: reply-ok(migrate.err reply sent via _send_err)
        g0 = len(tail)
        replay = [(i, tail[i]) for i in range(max(0, min(frm, g0)), g0)]
        with self._rlock:
            self._routes[gid] = _Route(
                gen=gen, reply=reply, att=att, seq=g0, seq0=g0,
                replay=replay, last_ckpt_tick=self._ticks,
                trace=ctx, t0=time.time(),
            )
            self._by_gen[gen] = gid

    def _handle_cancel(self, header: dict) -> None:
        gen = str(header.get("gen", ""))
        with self._rlock:
            gid = self._by_gen.get(gen)
        if gid is not None:
            self.engine.cancel(gid)

    def _handle_trace_pull(self, header: dict) -> None:
        """Answer a gateway's span collection for one trace with a single
        ``trace.spans`` frame (spans ride the JSON header). Best-effort:
        the gateway budgets the whole round and renders partial traces."""
        reply, tid = header.get("reply"), header.get("trace")
        if not reply or not tid:
            return  # distcheck: reply-ok(frame carries no reply address)
        spans = [s.to_dict() for s in self.tracer.spans_for(str(tid))]
        self._send([(reply, pack_frame({
            "op": "trace.spans", "trace": tid, "node": self.node_id,
            "spans": spans,
        }))])

    # -- fleet ops (drain / rebalance / page-ship) ----------------------------

    def _handle_drain(self, header: dict) -> None:
        """fleet.drain: stop taking routing traffic (the next heartbeat
        advertises ``draining``) and hand every in-flight stream back to
        its gateway at the next tick boundary. The ack reports how many
        sessions are in flight; the controller then watches the
        directory load and fences the lease once it reaches zero."""
        self._draining = True
        with self._rlock:
            n = len(self._routes)
        ctx = TraceContext.from_header(header)
        if ctx is not None:
            # Zero-duration marker under the controller's op-level trace:
            # when drain mode flipped on and how many streams it covered.
            c = ctx.child()
            self.tracer.record(Span(
                "fleet.drain", time.time(), 0.0, {"sessions": n},
                trace_id=c.trace_id, span_id=c.span_id,
                parent_id=c.parent_id, node=self.node_id,
            ))
        reply = header.get("reply")
        if reply:
            self._send([(reply, pack_frame({
                "op": "fleet.ack", "what": "drain", "ok": True, "n": n,
            }))])

    def _handle_migrate(self, header: dict) -> None:
        """fleet.migrate: mark up to ``n`` streams for a tick-boundary
        handoff — longest-running first (most decode ticks survived), the
        rebalance heuristic: old streams hold the most KV pages, so
        moving them defragments this node fastest."""
        want = int(header.get("n") or 0)
        marked = 0
        with self._rlock:
            routes = sorted(self._routes.values(),
                            key=lambda r: r.seq - r.seq0, reverse=True)
            for r in routes:
                if marked >= want:
                    break
                if not r.handoff:
                    r.handoff = True
                    marked += 1
        reply = header.get("reply")
        if reply:
            self._send([(reply, pack_frame({
                "op": "fleet.ack", "what": "migrate", "ok": True, "n": marked,
            }))])

    def _handle_pages(self, header: dict) -> None:
        """fleet.pages: export this node's cached prefix pages for the
        prompt as kv_codec frames (the holder side of a page-ship)."""
        gen = str(header.get("gen", ""))
        reply = header.get("reply")
        if not reply:
            self.metrics.counter("malformed_frames")
            return  # distcheck: reply-ok(frame carries no reply address)
        try:
            prompt = [int(t) for t in header["prompt"]]
            ps, items = self.engine.export_prefix_pages(prompt)
            if not items:
                raise LookupError("no cached prefix pages for prompt")
            frames = encode_pages(
                gen, ps, items, max_frame_bytes=self.dcfg.kv_frame_bytes,
            )
        except Exception as e:
            self._send([(reply, encode_error(gen, repr(e)))])
            return  # distcheck: reply-ok(error frame sent)
        if self._send([(reply, f) for f in frames]):
            self.metrics.counter("fleet_pages_served", len(items))

    def _handle_pages_put(self, header: dict, client: RelayClient) -> None:
        """fleet.pages.put: pull shipped prefix-page frames off the relay
        and install them into this engine's pool (the target side of a
        page-ship); ack with the count made servable."""
        gen = str(header.get("gen", ""))
        reply = header.get("reply")
        try:
            kvq = header["kv"]
            nf = int(header["nf"])
            budget = time.monotonic() + self.dcfg.transfer_timeout_s
            frames = [
                client.get(kvq, timeout=max(budget - time.monotonic(), 0.001))
                for _ in range(nf)
            ]
            items, meta = decode_pages(frames)
            if items is None:
                raise ValueError("page-ship transfer carried an error frame")
            n = self.engine.import_prefix_pages(
                int(meta.get("ps") or 0), items)
        except Exception as e:
            logger.warning(
                "page import %s failed on %s: %r", gen, self.node_id, e)
            if reply:
                self._send([(reply, pack_frame({
                    "op": "fleet.ack", "what": "pages", "ok": False,
                    "gen": gen, "error": _err_code(e),
                }))])
            return  # distcheck: reply-ok(nack sent when a reply address exists)
        if reply:
            self._send([(reply, pack_frame({
                "op": "fleet.ack", "what": "pages", "ok": True,
                "gen": gen, "n": n,
            }))])

    def _send_err(self, reply: str, gen: str, att: str, error: str) -> None:
        try:
            self._out.put(reply, pack_frame(
                {"op": "migrate.err", "gen": gen, "att": att, "error": error}
            ))
        except (ConnectionError, OSError):
            pass  # gateway's death detector takes it from here

    # -- driver loop ----------------------------------------------------------

    def _drive(self) -> None:
        while not self._stop.is_set():
            self._run_handoffs()
            if not self.engine.has_work():
                self._flush_replays()
                time.sleep(0.002)
                continue
            events = self.engine.step()
            # distcheck: unguarded-ok(driver-owned monotonic counter; the
            # consume thread only reads it to seed checkpoint pacing, and a
            # one-tick-stale read just shifts a checkpoint by one tick)
            self._ticks += 1
            self._flush_replays()
            retired: List[str] = []
            for gid, tok, fin in events:
                with self._rlock:
                    r = self._routes.get(gid)
                if r is None:
                    continue
                self._flush_replay_route(r)
                reason = None
                if fin:
                    s = self.engine.sessions.get(gid)
                    reason = s.finish_reason if s is not None else None
                frames: List[Tuple[str, bytes]] = []
                if tok >= 0:
                    if r.trace is not None and not r.first_done:
                        # Admission → first generated token on this node:
                        # the decode-side half of the request's TTFT.
                        r.first_done = True
                        c = r.trace.child()
                        self.tracer.record(Span(
                            "decode.first_token", r.t0, time.time() - r.t0,
                            {"gen": r.gen, "seq": r.seq},
                            trace_id=c.trace_id, span_id=c.span_id,
                            parent_id=c.parent_id, node=self.node_id,
                        ))
                    frames.append((r.reply, pack_frame({
                        "op": "migrate.tok", "gen": r.gen, "att": r.att,
                        "seq": r.seq, "tok": int(tok), "fin": bool(fin),
                        "reason": reason,
                    })))
                    r.seq += 1
                else:  # finish without a new token
                    frames.append((r.reply, pack_frame({
                        "op": "migrate.tok", "gen": r.gen, "att": r.att,
                        "seq": None, "tok": -1, "fin": True,
                        "reason": reason,
                    })))
                if not self._send(frames):
                    # Reply path is gone (gateway died or we are
                    # partitioned): stop burning decode on this stream.
                    self.engine.cancel(gid)
                    retired.append(gid)
                elif fin:
                    retired.append(gid)
            if retired:
                with self._rlock:
                    for gid in retired:
                        r = self._routes.pop(gid, None)
                        if r is not None:
                            self._by_gen.pop(r.gen, None)
            self._ship_checkpoints()
            self.engine.collect_finished()

    def _run_handoffs(self) -> None:
        """Tick-boundary session handoffs: every route when draining,
        marked routes after a fleet.migrate. Runs between engine steps so
        exported snapshots are quiesced (no in-flight tick)."""
        with self._rlock:
            if self._draining:
                due = list(self._routes.items())
            else:
                due = [(g, r) for g, r in self._routes.items() if r.handoff]
        for gid, r in due:
            self._handoff_route(gid, r)

    def _handoff_route(self, gid: str, r: _Route) -> None:
        """Hand one stream back to its gateway: flush any replay tail,
        ship a fresh tick-boundary checkpoint, then the ``fleet.handoff``
        marker the gateway re-homes the stream from (exactly-once: the
        gateway's seq dedup absorbs any token overlap between the stream
        and the checkpoint tail). A WAITING session (never streamed)
        exports ``None`` and hands off cold — the gateway resubmits the
        prompt, still zero-loss because nothing was ever delivered."""
        self._flush_replay_route(r)
        h0 = time.time()
        child = r.trace.child() if r.trace is not None else None
        frames: List[Tuple[str, bytes]] = []
        snap = self.engine.export_session(gid)
        if snap is not None:
            frames = [(r.reply, f) for f in encode_session(
                r.gen, snap,
                page_size=self.engine.ccfg.page_size,
                max_frame_bytes=self.dcfg.kv_frame_bytes,
                att=r.att, trace=child,
            )]
        frames.append((r.reply, pack_frame({
            "op": "fleet.handoff", "gen": r.gen, "att": r.att,
            "trace": child.trace_id if child is not None else None,
            "span": child.span_id if child is not None else None,
        })))
        # Retire the route BEFORE cancelling: the cancel's finish event
        # must not chase the handoff down the reply queue as a bogus fin.
        with self._rlock:
            self._routes.pop(gid, None)
            self._by_gen.pop(r.gen, None)
        if self._send(frames):
            self.metrics.counter("fleet_handoffs_sent")
            if child is not None:
                # Snapshot export + checkpoint/marker send: the node-side
                # segment of a drain or rebalance re-home.
                self.tracer.record(Span(
                    "drain.handoff", h0, time.time() - h0,
                    {"gen": r.gen, "frames": len(frames)},
                    trace_id=child.trace_id, span_id=child.span_id,
                    parent_id=child.parent_id, node=self.node_id,
                ))
        # Either way the session leaves this engine: on send failure the
        # gateway's death detector re-homes from its last checkpoint.
        self.engine.cancel(gid)

    def _flush_replays(self) -> None:
        with self._rlock:
            routes = [r for r in self._routes.values() if r.replay]
        for r in routes:
            self._flush_replay_route(r)

    def _flush_replay_route(self, r: _Route) -> None:
        """Emit a resumed stream's checkpoint-tail tokens (never a finish:
        export_session only snapshots ACTIVE sessions, so the tail cannot
        contain eos and cannot exhaust max_new_tokens)."""
        if not r.replay:
            return
        pending, r.replay = r.replay, []
        self._send([
            (r.reply, pack_frame({
                "op": "migrate.tok", "gen": r.gen, "att": r.att,
                "seq": seq, "tok": tok, "fin": False, "reason": None,
            }))
            for seq, tok in pending
        ])

    def _ship_checkpoints(self) -> None:
        interval = self.dcfg.checkpoint_interval_ticks
        with self._rlock:
            routes = list(self._routes.items())
        for gid, r in routes:
            if r.seq <= r.seq0:
                continue  # nothing streamed yet — the gateway can resubmit
            due = not r.ckpted or (
                interval > 0 and self._ticks - r.last_ckpt_tick >= interval
            )
            if not due:
                continue
            snap = self.engine.export_session(gid)
            if snap is None:
                continue  # finished under us; the fin frame already went out
            frames = encode_session(
                r.gen, snap,
                page_size=self.engine.ccfg.page_size,
                max_frame_bytes=self.dcfg.kv_frame_bytes,
                att=r.att, trace=r.trace,
            )
            if self._send([(r.reply, f) for f in frames]):
                r.ckpted = True
                r.last_ckpt_tick = self._ticks
                self.metrics.counter("checkpoints_shipped")
                self.metrics.counter("checkpoint_frames_sent", len(frames))

    def _send(self, frames: List[Tuple[str, bytes]]) -> bool:
        if not frames:
            return True
        try:
            self._out.put_many(frames)
            return True
        except (ConnectionError, OSError):
            return False

    # -- health ---------------------------------------------------------------

    def _health_loop(self) -> None:
        beat = min(self.dcfg.heartbeat_s, max(self.lease_ttl / 3.0, 0.05))
        while not self._stop.wait(beat):
            try:
                # Load counts every in-flight ROUTE, not just resident
                # engine slots: a queued (WAITING) stream is offered load
                # to a gateway picking seats, and the fleet controller's
                # drain poll must not read "0" while un-handed-off
                # sessions still sit in this node's admission queue.
                with self._rlock:
                    n_routes = len(self._routes)
                alive = self._directory.heartbeat(
                    self.node_id,
                    load=max(self.engine.active_sessions(), n_routes),
                    ttl=self.lease_ttl, epoch=self.epoch,
                    draining=self._draining,
                )
                if not alive:  # lease lapsed (e.g. partition healed)
                    if not self._register():
                        # Fenced: a gateway declared this incarnation dead
                        # and re-homed its streams. Serving on would race
                        # the successor — wind down instead.
                        logger.warning(
                            "node %s epoch %d fenced; stopping",
                            self.node_id, self.epoch,
                        )
                        self._stop.set()
                        return
                if self.engine.ccfg.prefix_caching:
                    # Prefix-aware routing (prefixstore/): piggyback this
                    # node's cached-prefix key set on the heartbeat cadence
                    # so gateways can route a prompt to the node already
                    # holding its prefix. Whole-set refresh: eviction needs
                    # no tombstones, staleness costs only a suboptimal
                    # route (the engine recomputes on a miss).
                    self._directory.advertise_prefixes(
                        self.node_id, self.engine.ccfg.page_size,
                        self.engine.advertised_prefix_heads(),
                    )
            except Exception:
                continue  # transient control-plane failure: keep serving

    def is_healthy(self) -> bool:
        return (
            self._consume_thread.is_alive()
            and self._drive_thread.is_alive()
            and not self._stop.is_set()
        )

    def stop(self) -> None:
        self._stop.set()
        self._consume_thread.join(timeout=5)
        self._drive_thread.join(timeout=5)
        self._health_thread.join(timeout=5)
        try:
            self._directory.remove(self.node_id)
        except Exception:
            pass
        self._directory.close()
        self._out.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
