"""Disaggregated prefill/decode (TPLA, arxiv 2508.15881): prefill-only
workers run bucketed prefill + the first-token sample, then ship the
session's KV planes over the CRC-checked relay to a decode-pool engine
that imports them via ``admit_prefilled`` and enters decode directly.

Pieces:

* :mod:`.kv_codec` — chunked (de)serialization of per-layer KV planes
  (bf16 values, or int8 values + f32 scales from the quantized caches)
  into relay frames.
* :mod:`.prefill_worker` — the prefill-only role: registers with the
  block directory under ``role="prefill"``, consumes prompt requests,
  and answers with KV frames (or a single error frame).

The gateway side lives in ``serving.backends.DisaggBackend``.
"""

from .kv_codec import decode_kv, encode_error, encode_kv
from .prefill_worker import PrefillWorker

__all__ = ["encode_kv", "decode_kv", "encode_error", "PrefillWorker"]
