"""Disaggregated prefill/decode (TPLA, arxiv 2508.15881): prefill-only
workers run bucketed prefill + the first-token sample, then ship the
session's KV planes over the CRC-checked relay to a decode-pool engine
that imports them via ``admit_prefilled`` and enters decode directly.

Pieces:

* :mod:`.kv_codec` — chunked (de)serialization of per-layer KV planes
  (bf16 values, or int8 values + f32 scales from the quantized caches)
  into relay frames.
* :mod:`.prefill_worker` — the prefill-only role: registers with the
  block directory under ``role="prefill"``, consumes prompt requests,
  and answers with KV frames (or a single error frame).
* :mod:`.decode_node` — the recoverable decode role: streams
  sequence-stamped tokens back to a gateway and ships periodic session
  checkpoints (``encode_session`` frames) so the stream can be re-homed
  onto another node after a crash with zero token loss.

The gateway sides live in ``serving.backends.DisaggBackend`` (prefill
shipping) and ``serving.backends.FleetBackend`` (crash recovery).
"""

from .decode_node import DecodeNode
from .kv_codec import (
    SchemaError, decode_kv, decode_pages, decode_session, encode_error,
    encode_kv, encode_pages, encode_session,
)
from .prefill_worker import PrefillWorker

__all__ = [
    "encode_kv", "decode_kv", "encode_error",
    "encode_session", "decode_session",
    "encode_pages", "decode_pages",
    "SchemaError",
    "PrefillWorker", "DecodeNode",
]
