"""Model-family registry.

The reference hardcodes one family — Llama
(``/root/reference/distributed_llm_inference/models/llama/``). Here the
decoder stack (``models/llama.py``) is a single parameterized program whose
config switches cover the supported families; this registry is the explicit
map from HF ``model_type`` to that program plus each family's architectural
quirks, and the extension point for families that need more than config
switches (a new entry supplies its own ``convert_state_dict`` / ``apply``).

Families:

* ``llama``   — the baseline (GQA, RoPE incl. llama3 scaling, SwiGLU).
* ``mistral`` — + sliding-window attention (``ModelConfig.sliding_window``).
* ``qwen2``   — + q/k/v projection biases (``qkv_bias``) and (2.5-era
  configs) tied embeddings.
* ``mixtral`` — + MoE MLP (``num_experts``/``num_experts_per_tok``), expert
  parallelism over the ``ep`` mesh axis (``ops/moe.py``).
* ``mla``     — latent (low-rank) KV attention (``ModelConfig.latent``):
  DeepSeek-V2-style MLA with a shared per-token KV latent and a decoupled
  rotary key, served through the latent paged cache (``cache/latent.py``).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Dict, Tuple

from ..config import ModelConfig
from . import llama

__all__ = ["ModelFamily", "FAMILIES", "get_family", "validate_config"]


@dataclasses.dataclass(frozen=True)
class ModelFamily:
    name: str
    # HF `model_type` strings served by this entry.
    hf_model_types: Tuple[str, ...]
    # Capability switches the family is allowed to use.
    sliding_window: bool = False
    qkv_bias: bool = False
    moe: bool = False
    # Latent (MLA) KV attention: the family both permits AND requires
    # ``ModelConfig.latent`` — the latent decoder path has its own
    # projection set, so a family is one or the other, never both.
    latent: bool = False
    # The compute/conversion program (shared stack for all current families).
    apply: Callable = llama.model_apply
    block_apply: Callable = llama.block_apply
    init_params: Callable = llama.init_params
    convert_state_dict: Callable = llama.convert_hf_state_dict


FAMILIES: Dict[str, ModelFamily] = {
    f.name: f
    for f in (
        ModelFamily("llama", ("llama",)),
        ModelFamily("mistral", ("mistral",), sliding_window=True),
        ModelFamily("qwen2", ("qwen2",), sliding_window=True, qkv_bias=True),
        ModelFamily("mixtral", ("mixtral",), sliding_window=True, moe=True),
        ModelFamily(
            "mla", ("mla", "deepseek_v2", "deepseek_v3"), latent=True
        ),
    )
}

_BY_HF_TYPE = {
    t: fam for fam in FAMILIES.values() for t in fam.hf_model_types
}


def get_family(name_or_cfg) -> ModelFamily:
    """Look up by family name, HF ``model_type``, or a :class:`ModelConfig`."""
    name = (
        name_or_cfg.family
        if isinstance(name_or_cfg, ModelConfig)
        else str(name_or_cfg)
    )
    fam = FAMILIES.get(name) or _BY_HF_TYPE.get(name)
    if fam is None:
        raise KeyError(
            f"unsupported model family {name!r} (supported: "
            f"{sorted(FAMILIES)})"
        )
    return fam


def validate_config(cfg: ModelConfig) -> ModelFamily:
    """Fail fast when a config uses switches its family doesn't support
    (e.g. an MoE llama config is almost certainly a conversion bug)."""
    fam = get_family(cfg)
    if cfg.sliding_window is not None and not fam.sliding_window:
        raise ValueError(
            f"family {fam.name!r} does not use sliding_window "
            f"(got {cfg.sliding_window})"
        )
    if cfg.num_experts > 0 and not fam.moe:
        raise ValueError(
            f"family {fam.name!r} is dense but config has "
            f"num_experts={cfg.num_experts}"
        )
    if cfg.qkv_bias and not fam.qkv_bias:
        raise ValueError(f"family {fam.name!r} does not use qkv_bias")
    if cfg.latent is not None and not fam.latent:
        raise ValueError(
            f"family {fam.name!r} does not use latent KV attention "
            f"(use the 'mla' family)"
        )
    if fam.latent and (cfg.latent is None or not cfg.latent.enabled):
        raise ValueError(
            f"family {fam.name!r} requires an enabled ModelConfig.latent"
        )
    return fam
