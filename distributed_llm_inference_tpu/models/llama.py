"""Llama-family decoder stack as pure JAX functions.

TPU-first re-expression of the reference's model layer
(``/root/reference/distributed_llm_inference/models/llama/model.py`` and
``modules.py``). Design notes:

* ``LlamaBlock`` — a module holding a Python list of decoder layers iterated in
  a Python loop (``model.py:22,59-71``) — becomes ``block_apply``: a pure
  function over *stacked* layer parameters driven by ``lax.scan``, so compile
  time is O(1) in depth and the whole block is one XLA computation.
* The CUDA-graphed decode fast paths (``modules.py:73-76,159-162,176-179``)
  disappear: ``jax.jit`` of the step function is the graph.
* The vestigial single-device ``pretraining_tp`` weight slicing
  (``modules.py:44-59,107-110``) is dropped; real tensor parallelism is applied
  externally via ``NamedSharding`` on these same parameter arrays
  (see ``parallel/tp.py``).
* Like the reference's block (``model.py:16-76``), ``block_apply`` is strictly a
  hidden-states→hidden-states pipeline stage; embedding / final norm / lm_head
  live in ``model_apply`` (the client-side layers the reference never wrote,
  SURVEY §1).

Weight layout: all projections are stored ``[in_features, out_features]``
(transposed from torch ``nn.Linear``) so the forward is plain ``x @ w``.
"""

from __future__ import annotations

from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..config import ModelConfig
from ..ops.attention import gqa_attention
from ..ops.moe import moe_mlp
from ..ops.norms import rms_norm
from ..ops.quant import matmul as qmatmul
from ..ops.rotary import RopeAngles, apply_rope, rope_cos_sin, rope_inv_freq

Params = Dict[str, Any]


# ---------------------------------------------------------------------------
# Initialization
# ---------------------------------------------------------------------------


def init_layer_params(
    cfg: ModelConfig, key: jax.Array, num_layers: int, dtype=jnp.bfloat16
) -> Params:
    """Random (normal 0.02) stacked parameters for ``num_layers`` decoder layers."""
    h, d = cfg.hidden_size, cfg.head_dim
    hq, hkv, inter = cfg.num_heads, cfg.num_kv_heads, cfg.intermediate_size
    keys = jax.random.split(key, 8)

    def w(k, *shape):
        return (jax.random.normal(k, (num_layers, *shape), jnp.float32) * 0.02).astype(
            dtype
        )

    if cfg.use_latent:
        # MLA (latent KV) attention parameter set — see
        # :func:`_latent_attention` for how each projection is consumed.
        lat = cfg.latent
        dn = lat.nope_head_dim or d
        dr = lat.rope_head_dim
        p = {
            "attn_norm": jnp.ones((num_layers, h), dtype),
            "wq": w(keys[0], h, hq * (dn + dr)),
            # Down-projection to the stored form: [c ; k_rope_pre].
            "wkv_a": w(keys[1], h, lat.rank + dr),
            "kv_norm": jnp.ones((num_layers, lat.rank), dtype),
            # Key up-projection (absorbed into the query at apply time).
            "wk_b": w(keys[2], lat.rank, hq, dn),
            # Value up-projection (applied after the softmax).
            "wv_b": w(keys[7], lat.rank, hq, d),
            "wo": w(keys[3], hq * d, h),
            "mlp_norm": jnp.ones((num_layers, h), dtype),
        }
    else:
        p = {
            "attn_norm": jnp.ones((num_layers, h), dtype),
            "wq": w(keys[0], h, hq * d),
            "wk": w(keys[1], h, hkv * d),
            "wv": w(keys[2], h, hkv * d),
            "wo": w(keys[3], hq * d, h),
            "mlp_norm": jnp.ones((num_layers, h), dtype),
        }
    if cfg.num_experts > 0:
        e = cfg.num_experts
        p["router"] = w(keys[7], h, e)
        p["we_g"] = w(keys[4], e, h, inter)
        p["we_u"] = w(keys[5], e, h, inter)
        p["we_d"] = w(keys[6], e, inter, h)
    else:
        p["wg"] = w(keys[4], h, inter)
        p["wu"] = w(keys[5], h, inter)
        p["wd"] = w(keys[6], inter, h)
    if cfg.qkv_bias:
        p["bq"] = jnp.zeros((num_layers, hq * d), dtype)
        p["bk"] = jnp.zeros((num_layers, hkv * d), dtype)
        p["bv"] = jnp.zeros((num_layers, hkv * d), dtype)
    return p


def init_params(cfg: ModelConfig, key: jax.Array, dtype=jnp.bfloat16) -> Params:
    """Full-model parameters (embedding + stacked layers + head)."""
    k_embed, k_layers, k_head = jax.random.split(key, 3)
    params: Params = {
        "embed": (
            jax.random.normal(k_embed, (cfg.vocab_size, cfg.hidden_size), jnp.float32)
            * 0.02
        ).astype(dtype),
        "layers": init_layer_params(cfg, k_layers, cfg.num_layers, dtype),
        "final_norm": jnp.ones((cfg.hidden_size,), dtype),
    }
    if not cfg.tie_word_embeddings:
        params["lm_head"] = (
            jax.random.normal(k_head, (cfg.hidden_size, cfg.vocab_size), jnp.float32)
            * 0.02
        ).astype(dtype)
    return params


# ---------------------------------------------------------------------------
# Forward
# ---------------------------------------------------------------------------


def _decoder_layer(
    cfg: ModelConfig,
    p: Params,
    x: jnp.ndarray,
    layer_state: Tuple[jnp.ndarray, ...],
    cache,
    rope: RopeAngles,
    q_pos: jnp.ndarray,
    num_new: jnp.ndarray,
    attention_fn=gqa_attention,
) -> Tuple[jnp.ndarray, Tuple[jnp.ndarray, ...]]:
    """One decoder layer: pre-norm attention + pre-norm SwiGLU MLP.

    Mirrors the reference layer structure (``modules.py:146-184``) minus its
    double-residual deviation (SURVEY §2.9.3).
    """
    b, s, _ = x.shape
    hq, hkv, d = cfg.num_heads, cfg.num_kv_heads, cfg.head_dim

    h = rms_norm(x, p["attn_norm"], cfg.rms_norm_eps)
    if cfg.use_latent:
        attn_flat, new_state = _latent_attention(
            cfg, p, h, layer_state, cache, rope, q_pos, num_new, attention_fn
        )
        o = qmatmul(attn_flat, p["wo"])
        if "bo" in p:
            o = o + p["bo"]
        x = x + o
        return _mlp_residual(cfg, p, x, s, num_new), new_state
    q = qmatmul(h, p["wq"])
    k = qmatmul(h, p["wk"])
    v = qmatmul(h, p["wv"])
    # Biases applied iff the checkpoint carries them (HF `attention_bias`).
    if "bq" in p:
        q = q + p["bq"]
        k = k + p["bk"]
        v = v + p["bv"]
    q = q.reshape(b, s, hq, d)
    k = k.reshape(b, s, hkv, d)
    v = v.reshape(b, s, hkv, d)

    attn, new_state = cache.attend(
        layer_state, q, k, v, rope, q_pos, num_new,
        cfg.sliding_window, attention_fn, d**-0.5,
    )
    o = qmatmul(attn.reshape(b, s, hq * d), p["wo"])
    if "bo" in p:
        o = o + p["bo"]
    x = x + o
    return _mlp_residual(cfg, p, x, s, num_new), new_state


def _mlp_residual(cfg, p, x, s, num_new):
    """Pre-norm MLP + residual (shared by the dense and latent attention
    branches of :func:`_decoder_layer`)."""
    b = x.shape[0]
    h2 = rms_norm(x, p["mlp_norm"], cfg.rms_norm_eps)
    if cfg.num_experts > 0:
        # Bucket-padding positions (>= num_new) must not consume expert
        # capacity in the dispatched prefill path.
        valid = None
        if s > 1:
            valid = (
                jax.lax.broadcasted_iota(jnp.int32, (b, s), 1)
                < num_new[:, None]
            )
        mlp = moe_mlp(cfg, p, h2, valid=valid)
    else:
        mlp = qmatmul(jax.nn.silu(qmatmul(h2, p["wg"])) * qmatmul(h2, p["wu"]), p["wd"])
    return x + mlp


def _latent_attention(
    cfg: ModelConfig,
    p: Params,
    h: jnp.ndarray,
    layer_state: Tuple[jnp.ndarray, ...],
    cache,
    rope: RopeAngles,
    q_pos: jnp.ndarray,
    num_new: jnp.ndarray,
    attention_fn=gqa_attention,
) -> Tuple[jnp.ndarray, Tuple[jnp.ndarray, ...]]:
    """Absorbed-MLA attention over the latent cache.

    The cache stores ONE fused ``[c ; k_rope]`` latent per token (``c`` =
    shared ``rank``-dim KV latent, ``k_rope`` = decoupled rotary key,
    shared across heads). Two algebraic moves let attention run directly
    over that stored form, so the kernels' page walk doubles as the
    latent→K/V decompression (no per-token K/V ever materializes):

    * The key up-projection is ABSORBED into the query:
      ``q·k = q_nope·(w_uk c) = (q_nope w_uk)·c`` — so the query handed to
      the cache is ``[q_nope @ w_uk[h] ; q_rope]`` and K is the latent
      itself (one KV "head"; GQA broadcast covers all ``Hq`` heads).
    * The value up-projection is DEFERRED past the softmax: with
      ``V = [c ; k_rope]`` the attention output's first ``rank`` dims are
      ``sum_j p_j c_j``, up-projected per head afterwards
      (``sum_j p_j v_j = w_uv (sum_j p_j c_j)``).

    Rope is applied here, to the rope slices only (``rope`` tables are
    built for ``rope_head_dim`` — see :func:`block_apply`); the cache must
    not rotate anything. Softmax scale is ``(dn + dr)**-0.5``, the
    effective per-head query dim of the UN-absorbed formulation.
    """
    lat = cfg.latent
    b, s, _ = h.shape
    hq, d = cfg.num_heads, cfg.head_dim
    dn = lat.nope_head_dim or d
    dr = lat.rope_head_dim
    rank = lat.rank

    q = qmatmul(h, p["wq"]).reshape(b, s, hq, dn + dr)
    q_nope, q_rope = q[..., :dn], q[..., dn:]
    ckv = qmatmul(h, p["wkv_a"])  # [B, S, rank + dr]
    c = rms_norm(ckv[..., :rank], p["kv_norm"], cfg.rms_norm_eps)
    k_rope = apply_rope(
        ckv[..., rank:][:, :, None, :], rope.cos, rope.sin
    )  # [B, S, 1, dr]
    q_rope = apply_rope(q_rope, rope.cos, rope.sin)
    # Absorbed query: q_lat[b,s,h,r] = q_nope[b,s,h,:] · w_uk[r,h,:].
    q_lat = jnp.einsum("bshd,rhd->bshr", q_nope, p["wk_b"])
    q_eff = jnp.concatenate([q_lat, q_rope], axis=-1)  # [B, S, Hq, rank+dr]
    kv = jnp.concatenate(
        [c[:, :, None, :], k_rope], axis=-1
    )  # [B, S, 1, rank+dr] — the STORED form the cache scatters verbatim
    attn, new_state = cache.attend(
        layer_state, q_eff, kv, kv, rope, q_pos, num_new,
        None, attention_fn, (dn + dr) ** -0.5,
    )
    # Deferred value up-projection from the latent-space attention result.
    o = jnp.einsum("bshr,rhd->bshd", attn[..., :rank], p["wv_b"])
    return o.reshape(b, s, hq * d), new_state


def _rope_dim(cfg: ModelConfig) -> int:
    """Rotary table width: the decoupled rope key dim under latent (MLA)
    attention — only that slice of q/k is rotated — else the head dim."""
    return (
        cfg.latent.rope_head_dim if cfg.use_latent else cfg.head_dim
    )


def _split_int4_stacks(layer_params: Params):
    """Partition the layer dict: half-split int4 leaves are captured WHOLE
    (their Pallas matmul indexes the layer in its block index map);
    everything else rides the scan's xs and gets sliced for free. Slicing
    an int4 stack per scan step would copy the layer's packed weight
    through HBM before every kernel call — the copy traffic is why int4
    decode measured slower than int8 before this split."""
    from ..ops.quant import QuantizedTensor4Split

    whole = {
        k: v
        for k, v in layer_params.items()
        if isinstance(v, QuantizedTensor4Split)
    }
    scanned = {k: v for k, v in layer_params.items() if k not in whole}
    return whole, scanned


def _int4_views(whole: Params, idx) -> Params:
    from ..ops.quant import QuantizedTensor4SplitView

    return {
        k: QuantizedTensor4SplitView(
            v.q, v.scale_lo, v.scale_hi, idx, v.in_dim, v.out_dim
        )
        for k, v in whole.items()
    }


def block_apply(
    cfg: ModelConfig,
    layer_params: Params,
    x: jnp.ndarray,
    cache,
    num_new: jnp.ndarray,
    attention_fn=gqa_attention,
):
    """Run a block (contiguous or not) of decoder layers over hidden states.

    The pipeline-stage analog of ``LlamaBlock.forward``
    (``/root/reference/distributed_llm_inference/models/llama/model.py:25-76``):
    hidden states in, hidden states out, cache threaded explicitly. ``cache``
    holds stacked per-layer k/v with leading dim equal to this block's layer
    count; ``lax.scan`` slices one layer's params+cache per step.

    Returns ``(x, cache)`` with the cache's k/v updated (lengths NOT advanced —
    call ``cache.advance(num_new)`` after the last block of the model so that
    multiple blocks of one pipeline see consistent write offsets).
    """
    inv_freq = rope_inv_freq(_rope_dim(cfg), cfg.rope_theta, cfg.rope_scaling)
    q_pos = cache.q_positions(x.shape[1])
    rot_pos = cache.rope_positions(x.shape[1], num_new)
    cos, sin = rope_cos_sin(rot_pos, inv_freq)
    rope = RopeAngles(inv_freq, cos, sin)

    stacks = cache.layer_stacks  # tuple of [L, ...] arrays (k/v [+ scales])
    num_stack = stacks[0].shape[0]

    # Cache buffers ride the scan CARRY and are updated in place at the layer
    # index — carries are aliased by XLA, so a decode step writes one token
    # per layer. Returning per-layer state as stacked scan outputs instead
    # would materialize a full copy of the whole cache every step, doubling
    # HBM traffic on the bandwidth-bound decode path.
    whole_w, scanned_w = _split_int4_stacks(layer_params)

    def step(carry, xs):
        x, bufs = carry
        p, idx = xs
        p = {**p, **_int4_views(whole_w, idx)}
        layer_state = tuple(
            jax.lax.dynamic_index_in_dim(b, idx, 0, keepdims=False)
            for b in bufs
        )
        out, new_state = _decoder_layer(
            cfg, p, x, layer_state, cache, rope, q_pos, num_new, attention_fn
        )
        bufs = tuple(
            jax.lax.dynamic_update_index_in_dim(b, n, idx, 0)
            for b, n in zip(bufs, new_state)
        )
        return (out, bufs), None

    (x, new_stacks), _ = jax.lax.scan(
        step, (x, stacks), (scanned_w, jnp.arange(num_stack))
    )
    return x, cache.with_layer_stacks(*new_stacks)


def model_apply(
    cfg: ModelConfig,
    params: Params,
    tokens: jnp.ndarray,
    cache,
    num_new: jnp.ndarray,
    attention_fn=gqa_attention,
    block_fn=None,
    head: str = "all",
):
    """Full model forward: embed → layers → final norm → logits.

    This is the client-side capability the reference lacks entirely (SURVEY §1:
    "There is no client layer"). Returns ``(logits[B, S, V], cache)`` with the
    cache advanced. ``block_fn`` overrides how the layer stack runs (e.g. the
    ``pp``-staged pipeline, ``parallel/pipeline.py``); it must match
    :func:`block_apply`'s signature minus ``attention_fn``.

    ``head``: "all" computes logits at every position; "last" only at each
    row's final valid position (``num_new - 1``) — a prefill only samples
    there, and the full-vocab matmul over S positions is pure waste (at
    Llama-3-8B's 128k vocab it is ~6% of a 128-token prefill, and S/chunk of
    every chunked long-prompt step); "none" skips the head (chunked prefill
    interiors), returning ``None`` logits. Shapes: "last" → [B, 1, V].
    """
    x = jnp.take(params["embed"], tokens, axis=0)
    if block_fn is None:
        x, cache = block_apply(
            cfg, params["layers"], x, cache, num_new, attention_fn
        )
    else:
        x, cache = block_fn(cfg, params["layers"], x, cache, num_new)
    if head == "none":
        return None, cache.advance(num_new)
    if head == "last":
        x = jnp.take_along_axis(
            x,
            jnp.maximum(num_new - 1, 0)[:, None, None].astype(jnp.int32),
            axis=1,
        )
    logits = apply_head(cfg, params, x)
    return logits, cache.advance(num_new)


class _TailView:
    """Cache stand-in handed to ``_decoder_layer`` inside the fused decode
    scan: its ``layer_state`` is the concatenation of the real cache's
    READ-ONLY big planes and the small mutable tail planes; ``attend``
    splits them and delegates to the cache's ``tail_attend``. Returned
    layer state echoes the big planes unchanged (the driver writes back
    only the tail half)."""

    def __init__(self, cache, base_len, tail_len, step_idx, num_big):
        self.cache = cache
        self.base_len = base_len
        self.tail_len = tail_len
        self.step_idx = step_idx
        self.num_big = num_big

    def q_positions(self, seq_len):
        return (self.base_len + self.tail_len)[:, None]

    def rope_positions(self, seq_len, num_new):
        return self.q_positions(seq_len)

    def attend(self, layer_state, q, k_new, v_new, rope, q_pos, num_new,
               sliding_window, attention_fn, scale=None):
        big = layer_state[: self.num_big]
        tail = layer_state[self.num_big:]
        out, new_tail = self.cache.tail_attend(
            big, tail, q, k_new, v_new, rope, self.base_len, self.tail_len,
            self.step_idx, num_new, sliding_window, scale,
        )
        return out, (*big, *new_tail)


def multi_decode_apply(
    cfg: ModelConfig,
    params: Params,
    tokens: jnp.ndarray,
    cache,
    num_steps: int,
    step_fn,
    init_state,
    init_num_new: jnp.ndarray,
):
    """``num_steps`` fused decode steps with a WRITE-BEHIND KV tail.

    The per-step scan path writes each new token into the big KV buffers
    with per-row dynamic offsets — which lowers to a serial while-loop over
    batch rows on TPU (measured ~26 ms/step at batch 80, Llama-7B shapes,
    more than the step's entire ideal HBM traffic; a scatter instead aborts
    under GSPMD). Here the big buffers stay READ-ONLY for all K steps: they
    ride the layer scan as sliced operands (like the weights, which scan
    slices for free), each step's fresh k/v lands in a small per-layer tail
    buffer at a SCALAR slot index (one vectorized write), and the tail is
    merged into the big buffers once at the end. Attention runs over the two
    segments (big + tail) under one joint softmax
    (``ops.attention.gqa_attention_segments``).

    ``tokens``: ``[B, 1]`` first input tokens. ``step_fn(i, logits, state)``
    → ``(next_tokens [B], next_num_new [B] int32, state, emit)`` carries
    sampling/stop logic; ``num_new`` must be non-increasing per row across
    steps (a finished row stays finished) so each row's tail slots stay
    contiguous. Returns ``(emits stacked [K, ...], cache flushed+advanced)``.

    The dense cache kinds implement the tail protocol
    (``tail_init`` / ``tail_attend`` / ``tail_flush``) natively, and
    ``PagedKVCache`` implements it over its page pool (kernel-gated: the
    pool segment runs the Pallas paged kernel with exported softmax stats,
    joint-merged with the tail — see ``cache/paged.py``); callers fall back
    to per-step ``model_apply`` for other caches.
    """
    inv_freq = rope_inv_freq(_rope_dim(cfg), cfg.rope_theta, cfg.rope_scaling)
    # ``tail_big_stacks`` lets a cache hand the scan a DIFFERENT read-only
    # view of its big planes than its storage layout — the quantized paged
    # cache gathers its page pool to contiguous per-row buffers ONCE here
    # (per-layer pool slices feeding a kernel materialize a full pool copy
    # per layer per step; the gather amortizes to ~2% of a step over K).
    big_stacks = (
        cache.tail_big_stacks()
        if hasattr(cache, "tail_big_stacks")
        else cache.layer_stacks
    )
    num_big = len(big_stacks)
    num_stack = big_stacks[0].shape[0]
    base_len = cache.lengths
    # Whole-stack mode (Pallas big-segment kernels): the big buffers are NOT
    # sliced per layer — a dynamic-slice feeding a custom call materializes a
    # full HBM copy of that layer's K/V every (layer, step). Instead the
    # stacks pass through whole with the layer index appended; the kernel's
    # block index map resolves the layer, so the operand is zero-copy.
    whole_big = getattr(cache, "tail_reads_whole_big", False)
    # Whole-tail mode (in-kernel tail): like the big stacks, the tail
    # buffers pass through UNSLICED — the kernel aliases them in place and
    # indexes the layer itself, so the scan neither slices nor re-inserts
    # per-layer tail state.
    whole_tail = getattr(cache, "tail_in_kernel", False)
    view_num_big = num_big + 1 if whole_big else num_big
    whole_w, scanned_w = _split_int4_stacks(params["layers"])

    def token_step(carry, i):
        tokens, tail, tail_len, num_new, state = carry
        x = jnp.take(params["embed"], tokens, axis=0)
        view = _TailView(cache, base_len, tail_len, i, view_num_big)
        q_pos = view.q_positions(1)
        cos, sin = rope_cos_sin(q_pos, inv_freq)
        rope = RopeAngles(inv_freq, cos, sin)

        def layer_step(carry2, xs):
            x, tail_bufs = carry2
            p = xs[0]
            idx = xs[-1]
            p = {**p, **_int4_views(whole_w, idx)}
            if whole_big:
                big_state = (*big_stacks, idx)
            else:
                big_state = tuple(xs[1 : 1 + num_big])
            if whole_tail:
                tail_state = tail_bufs
            else:
                tail_state = tuple(
                    jax.lax.dynamic_index_in_dim(b, idx, 0, keepdims=False)
                    for b in tail_bufs
                )
            out, new_state = _decoder_layer(
                cfg, p, x, (*big_state, *tail_state), view, rope, q_pos,
                num_new,
            )
            if whole_tail:
                tail_bufs = tuple(new_state[view_num_big:])
            else:
                tail_bufs = tuple(
                    jax.lax.dynamic_update_index_in_dim(b, n, idx, 0)
                    for b, n in zip(tail_bufs, new_state[view_num_big:])
                )
            return (out, tail_bufs), None

        (x, tail), _ = jax.lax.scan(
            layer_step, (x, tail),
            (scanned_w,
             *(() if whole_big else big_stacks),
             jnp.arange(num_stack)),
        )
        logits = apply_head(cfg, params, x)
        next_tokens, next_num_new, state, emit = step_fn(i, logits[:, 0], state)
        tail_len = tail_len + num_new
        return (
            (next_tokens[:, None], tail, tail_len, next_num_new, state), emit
        )

    zero_len = jnp.zeros_like(base_len)
    (_, tail, tail_len, _, _), emits = jax.lax.scan(
        token_step,
        (tokens, cache.tail_init(num_steps), zero_len, init_num_new,
         init_state),
        jnp.arange(num_steps),
    )
    return emits, cache.tail_flush(tail, tail_len)


def apply_head(cfg: ModelConfig, params: Params, x: jnp.ndarray) -> jnp.ndarray:
    """Final norm + lm_head (tied to the embedding when absent): ``[..., H]``
    hidden states → fp32 logits ``[..., V]``."""
    x = rms_norm(x, params["final_norm"], cfg.rms_norm_eps)
    head = params.get("lm_head")
    if head is None:
        head = params["embed"].T
    return qmatmul(x, head).astype(jnp.float32)


# ---------------------------------------------------------------------------
# HF checkpoint conversion
# ---------------------------------------------------------------------------

_LAYER_KEY_MAP = {
    "input_layernorm.weight": ("attn_norm", False),
    "self_attn.q_proj.weight": ("wq", True),
    "self_attn.k_proj.weight": ("wk", True),
    "self_attn.v_proj.weight": ("wv", True),
    "self_attn.o_proj.weight": ("wo", True),
    "self_attn.q_proj.bias": ("bq", False),
    "self_attn.k_proj.bias": ("bk", False),
    "self_attn.v_proj.bias": ("bv", False),
    "self_attn.o_proj.bias": ("bo", False),
    "post_attention_layernorm.weight": ("mlp_norm", False),
    "mlp.gate_proj.weight": ("wg", True),
    "mlp.up_proj.weight": ("wu", True),
    "mlp.down_proj.weight": ("wd", True),
}


def convert_hf_layer(
    cfg: ModelConfig,
    state: Mapping[str, np.ndarray],
    layer_idx: int,
    dtype=jnp.bfloat16,
) -> Dict[str, np.ndarray]:
    """Convert one HF decoder layer's tensors to our naming/layout.

    ``state`` maps full HF keys (``model.layers.{i}.…``) to numpy arrays — the
    per-layer streaming analog of the reference's
    ``get_block_state_dict`` prefix filter
    (``/root/reference/distributed_llm_inference/utils/model.py:40-44``).
    """
    prefix = f"model.layers.{layer_idx}."
    out: Dict[str, np.ndarray] = {}
    for suffix, (name, transpose) in _LAYER_KEY_MAP.items():
        key = prefix + suffix
        if key not in state:
            continue
        arr = np.asarray(state[key])
        if transpose:
            arr = arr.T
        out[name] = arr.astype(jnp.dtype(dtype))
    # MLA (DeepSeek-V2-style) latent attention: the joint kv_b_proj
    # [Hq*(dn+dv), rank] splits into the key up-projection (absorbed into
    # the query) and the value up-projection (applied post-softmax).
    kvb_key = prefix + "self_attn.kv_b_proj.weight"
    if cfg.use_latent and kvb_key in state:
        lat = cfg.latent
        dn = lat.nope_head_dim or cfg.head_dim
        kvb = np.asarray(state[kvb_key]).T.reshape(
            lat.rank, cfg.num_heads, dn + cfg.head_dim
        )
        out["wk_b"] = kvb[..., :dn].astype(jnp.dtype(dtype))
        out["wv_b"] = kvb[..., dn:].astype(jnp.dtype(dtype))
        akey = prefix + "self_attn.kv_a_proj_with_mqa.weight"
        if akey in state:
            out["wkv_a"] = np.asarray(state[akey]).T.astype(jnp.dtype(dtype))
        nkey = prefix + "self_attn.kv_a_layernorm.weight"
        if nkey in state:
            out["kv_norm"] = np.asarray(state[nkey]).astype(jnp.dtype(dtype))
    # Mixtral MoE: gate (router) + per-expert w1/w3/w2 → stacked [E, …].
    gate_key = prefix + "block_sparse_moe.gate.weight"
    if gate_key in state and cfg.num_experts > 0:
        out["router"] = np.asarray(state[gate_key]).T.astype(jnp.dtype(dtype))
        ep = prefix + "block_sparse_moe.experts.{e}.{w}.weight"
        stack = lambda w: np.stack([
            np.asarray(state[ep.format(e=e, w=w)]).T
            for e in range(cfg.num_experts)
        ]).astype(jnp.dtype(dtype))
        out["we_g"] = stack("w1")  # gate_proj
        out["we_d"] = stack("w2")  # down_proj
        out["we_u"] = stack("w3")  # up_proj
    return out


def convert_hf_state_dict(
    cfg: ModelConfig,
    state: Mapping[str, np.ndarray],
    layer_ids: Optional[Sequence[int]] = None,
    dtype=jnp.bfloat16,
) -> Params:
    """Convert an HF Llama/Mistral/Qwen2 state dict into our param pytree.

    ``layer_ids`` selects an arbitrary list of layers (the block a node
    serves), mirroring ``LlamaBlock(config, layer_ids)``
    (``/root/reference/distributed_llm_inference/models/llama/model.py:17``).
    When ``layer_ids`` is None, converts the full model including embeddings
    and head.
    """
    ids: List[int] = list(layer_ids) if layer_ids is not None else list(
        range(cfg.num_layers)
    )
    per_layer = [convert_hf_layer(cfg, state, i, dtype) for i in ids]
    stacked = {
        name: jnp.asarray(np.stack([layer[name] for layer in per_layer]))
        for name in per_layer[0]
    }
    params: Params = {"layers": stacked}
    if layer_ids is None:
        params.update(convert_hf_non_layer(cfg, state, dtype))
    return params


def convert_hf_non_layer(
    cfg: ModelConfig, state: Mapping[str, np.ndarray], dtype=jnp.bfloat16
) -> Params:
    """The client-side tensors (embedding, final norm, lm_head) — what a
    mid-pipeline block node never loads (SURVEY §1: the reference has no
    client layer at all)."""
    params: Params = {
        "embed": jnp.asarray(
            np.asarray(state["model.embed_tokens.weight"]).astype(jnp.dtype(dtype))
        ),
        "final_norm": jnp.asarray(
            np.asarray(state["model.norm.weight"]).astype(jnp.dtype(dtype))
        ),
    }
    if not cfg.tie_word_embeddings and "lm_head.weight" in state:
        params["lm_head"] = jnp.asarray(
            np.asarray(state["lm_head.weight"]).T.astype(jnp.dtype(dtype))
        )
    return params
