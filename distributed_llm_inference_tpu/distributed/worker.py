"""Block worker: serve a layer block over the relay, with health + leases.

Completes what the reference left as stubs: the worker skeleton
(``/root/reference/distributed_llm_inference/server/worker.py:9-23`` — load a
block ``[block_index_start, block_index_end]`` and expose it) and the server
health/rebalance pseudocode (``server/server.py:5-24`` — register, monitor,
heartbeat, restart). One ``ServingNode`` =

* a :class:`BlockBackend` holding the layers this node serves,
* a consume loop on the node's relay queue (source-routed frames:
  ``hops[0]`` is the next destination — forward the block output there),
* a heartbeat thread renewing the directory lease (failure detection:
  a dead node's lease lapses and routing drops it),
* a watchdog that restarts the consume loop if it dies (the
  ``module.restart()`` intent of ``server.py:23``).

Frame header ops: ``forward`` (run the block), ``end`` (free the session),
``shutdown`` (stop the node; used by tests).
"""

from __future__ import annotations

import threading
import traceback
import uuid
from typing import Dict, List, Optional

import numpy as np

from ..config import ModelConfig
from .backend import BlockBackend, SchemaError
from .directory import DirectoryClient
from ..utils.metrics import Metrics
from .messages import pack_frame, unpack_frame
from .relay import RelayClient
from .task_pool import TaskPool

__all__ = ["ServingNode", "error_code"]


def error_code(e: Exception) -> str:
    """Machine-readable error classification for error frames. Clients key
    retry/failover decisions on this, never on message text (a reworded
    message must not silently disable replay)."""
    if isinstance(e, KeyError):
        return "unknown_generation"
    if isinstance(e, SchemaError):
        return "schema"
    if isinstance(e, RuntimeError) and "node full" in str(e):
        return "node_full"
    return "internal"


class ServingNode:
    def __init__(
        self,
        relay_port: int,
        cfg: ModelConfig,
        layer_params,
        first_layer: int,
        last_layer: int,
        host: str = "127.0.0.1",
        node_id: Optional[str] = None,
        max_sessions: int = 8,
        max_seq_len: int = 512,
        heartbeat_s: float = 2.0,
        lease_ttl: float = 10.0,
        dtype=None,
        batch_window_s: float = 0.002,
        quantize=None,
        kv_quant=None,
        cache_cfg=None,
        mesh_cfg=None,
        pool_max_batch: Optional[int] = None,
        epoch: int = 1,
    ):
        self.node_id = node_id or f"node-{uuid.uuid4().hex[:8]}"
        self.queue = f"block.{self.node_id}"
        self.host, self.relay_port = host, relay_port
        self.heartbeat_s, self.lease_ttl = heartbeat_s, lease_ttl
        # Incarnation number for lease fencing: a restart must register
        # with a HIGHER epoch than any previous life of this node_id, or
        # the directory (rightly) treats it as a zombie.
        self.epoch = int(epoch)
        kw = {} if dtype is None else {"dtype": dtype}
        self.backend = BlockBackend(
            cfg, layer_params, first_layer, last_layer, max_sessions,
            max_seq_len, quantize=quantize, kv_quant=kv_quant,
            cache_cfg=cache_cfg, mesh_cfg=mesh_cfg, **kw,
        )
        self._stop = threading.Event()
        # Crash log: consume + pool threads append (GIL-atomic), tests read
        # after join — no torn state to guard.
        # distcheck: unguarded-ok(list.append is atomic; read after join)
        self.errors: List[str] = []
        # distcheck: unguarded-ok(health thread is the only writer)
        self.restarts = 0
        self.metrics = Metrics()  # /metrics surface for chaos observability
        # Highest hop seq applied per generation (pool thread only). An
        # at-least-once transport (duplicated PUT) must not advance a
        # session's KV cache twice — the duplicate is skipped, no reply.
        self._applied_seq: Dict[str, int] = {}
        # Prune threshold precomputed once: the per-batch check is a bare
        # len() compare, and small dicts are never scanned at all.
        self._seq_prune_at = 4 * max_sessions + 16

        # Register FIRST: a directory/relay failure here must not leak the
        # pool thread or relay sockets (there is no node object to stop()).
        self._directory = DirectoryClient(relay_port, host)
        try:
            if not self._directory.register(
                self.node_id, first_layer, last_layer, self.queue,
                ttl=lease_ttl, epoch=self.epoch,
            ):
                raise RuntimeError(
                    f"registration fenced: node {self.node_id} epoch "
                    f"{self.epoch} is stale — restart with a higher epoch"
                )
            # All backend work flows through the task pool (one thread): N
            # concurrent sessions' compatible hops (same op + padded length)
            # group into ONE batched device call instead of N serial ones,
            # and backend state needs no locking. Replies are sent from the
            # pool thread over its own relay connection.
            self._out = RelayClient(host, relay_port)
        except Exception:
            self._directory.close()
            raise
        try:
            # ``pool_max_batch`` exists for A/B measurement (bench.py's
            # distributed phase): 1 disables co-batching so the batching
            # win is quantifiable; serving keeps the default.
            self._pool = TaskPool(
                self._process_batch, max_batch=pool_max_batch or max_sessions,
                window_s=batch_window_s, signature=lambda item: item[0],
                name=f"{self.node_id}.pool", metrics=self.metrics,
            )
        except Exception:
            self._out.close()
            self._directory.close()
            raise
        # Rebound by the health watchdog when a consumer dies; readers only
        # probe .is_alive() on whichever generation they observe.
        # distcheck: unguarded-ok(single rebinding writer; stale reads safe)
        self._consume_thread = self._spawn_consumer()
        self._health_thread = threading.Thread(
            target=self._health_loop, daemon=True
        )
        self._health_thread.start()

    # -- serve loop -----------------------------------------------------------

    def _spawn_consumer(self) -> threading.Thread:
        t = threading.Thread(target=self._consume, daemon=True,
                             name=f"{self.node_id}.consume")
        t.start()
        return t

    def _consume(self) -> None:
        client = RelayClient(self.host, self.relay_port)
        try:
            while not self._stop.is_set():
                try:
                    frame = client.get(self.queue, timeout=0.5)
                except TimeoutError:
                    continue
                header, arr = unpack_frame(frame)
                op = header.get("op")
                if op == "shutdown":
                    return  # distcheck: reply-ok(shutdown frames are fire-and-forget)
                if op == "end":
                    # Through the pool so backend state stays single-threaded.
                    self._pool.submit((("end",), header, None),
                                      eager=bool(header.get("gens")))
                    continue
                if op != "forward":
                    # An op this node doesn't speak: the drop must at least
                    # be visible on /metrics, or a protocol skew between
                    # client and worker looks like silent request loss.
                    self.metrics.counter("unknown_ops_dropped")
                    continue
                if not header.get("hops"):
                    continue  # distcheck: reply-ok(frame carries no reply address)
                # Group key: hops of equal padded length batch together
                # (decode steps with decode steps, like-bucketed prefills
                # with each other). Stacked multi-generation frames
                # (``gens`` header, ``[N, S, H]`` payload) share the key
                # space — axis 1 is the padded length for both layouts, so
                # a stacked decode frame co-batches with single decode hops.
                # Malformed payloads (missing / wrong-rank tensor) get a
                # degenerate key and fail per-item in backend.validate →
                # error reply, never the consume loop.
                shape = getattr(arr, "shape", ())
                s_key = shape[1] if len(shape) >= 2 else -1
                # Stacked frames were co-batched at the source: dispatching
                # them without the linger is what keeps the lockstep decode
                # loop's per-hop cost at compute + transit, not + window_s.
                self._pool.submit((("fwd", s_key), header, arr),
                                  eager=bool(header.get("gens")))
        except (ConnectionError, OSError):
            # Relay gone: health loop notices / tests tear down.
            return  # distcheck: reply-ok(no transport left to reply over)
        except Exception:
            # Record the real cause here, where the exception is live — the
            # watchdog thread only sees that the loop died.
            self.errors.append(traceback.format_exc())
            raise
        finally:
            client.close()

    def _process_batch(self, items) -> List[None]:
        """Task-pool fn: one batch of same-signature frames → one backend
        call; replies/errors go straight back over the relay (futures are
        fire-and-forget).

        A frame is either a single hop (``gen_id`` header, ``[1, S, H]``
        payload) or a stacked multi-generation hop from a batched client
        (``gens``/``num_new`` lists, ``[N, S, H]`` payload). Stacked frames
        flatten into the same ``forward_many`` group as the singles —
        everything in the pool batch runs as ONE backend call — and each
        stacked frame is re-stacked into one reply (failed rows peel off as
        individual error frames). All replies for the batch then leave in
        one pipelined ``put_many`` (a single syscall for the whole fan-out).
        """
        try:
            if items[0][0] == ("end",):
                for _, header, _ in items:
                    for gid in header.get("gens") or [header.get("gen_id", "")]:
                        self.backend.end(gid)
                        self._applied_seq.pop(gid, None)
                return [None] * len(items)
            # Flatten every frame into per-generation rows, with hop-seq
            # dedup (pool thread serializes, so no lock): a row whose seq
            # this node already applied is a duplicated delivery — skip it
            # with NO reply (the original's reply already went out; a second
            # reply would itself be a duplicate downstream).
            # Invariant reply fields computed once per batch, not per item.
            node = self.node_id
            shipments = []  # (queue, frame bytes) for ONE pipelined send
            reqs = []    # flattened forward_many items
            frames = []  # (header, rows) — rows: (req_idx | None, gid, nn)
            for _, header, arr in items:
                gens = header.get("gens")
                if gens is not None:
                    nns = header.get("num_new")
                    n_rows = (getattr(arr, "shape", None) or (0,))[0]
                    if (not isinstance(nns, (list, tuple))
                            or len(nns) != len(gens)
                            or n_rows != len(gens)):
                        # Malformed stacked frame: every row gets an explicit
                        # error reply — silently dropping rows would leave
                        # the client blocked for its full hop timeout.
                        self.metrics.counter("malformed_frames")
                        hops = header.get("hops") or []
                        if hops:
                            for gid in gens:
                                shipments.append((hops[-1], pack_frame({
                                    "op": "error", "gen_id": gid,
                                    "error": "stacked frame: gens/num_new/"
                                             "payload row counts disagree",
                                    "code": "schema", "from": node,
                                })))
                        continue
                    metas = list(zip(gens, nns))
                else:
                    metas = [(header.get("gen_id", ""),
                              header.get("num_new", 0))]
                seq = header.get("seq")
                new = bool(header.get("new", False))
                rows = []
                for i, (gid, nn) in enumerate(metas):
                    if seq is not None:
                        last = self._applied_seq.get(gid)
                        if last is not None and seq <= last:
                            self.metrics.counter("duplicate_hops_skipped")
                            rows.append((None, gid, nn))
                            continue
                        self._applied_seq[gid] = seq
                    x = arr[i : i + 1] if gens is not None else arr
                    rows.append((len(reqs), gid, nn))
                    reqs.append((gid, x, nn, new))
                frames.append((header, rows))
            if len(self._applied_seq) > self._seq_prune_at:
                # "end" frames are best-effort, so entries can leak; prune
                # against the backend's live session table.
                live = self.backend.sessions
                self._applied_seq = {
                    g: s for g, s in self._applied_seq.items() if g in live
                }
            outs = self.backend.forward_many(reqs) if reqs else []
            for header, rows in frames:
                hops = header.get("hops") or []
                fresh = [(ri, gid, nn) for ri, gid, nn in rows
                         if ri is not None]
                if not fresh or not hops:
                    continue  # wholly-duplicated frame: no reply
                ok_rows = []
                for ri, gid, nn in fresh:
                    y = outs[ri]
                    if isinstance(y, Exception):
                        # Protocol/session errors go back to the client's
                        # reply queue (last hop) so generate() fails fast
                        # instead of hanging; surviving rows of a stacked
                        # frame still travel on below.
                        err = {"op": "error", "gen_id": gid,
                               "error": f"{type(y).__name__}: {y}",
                               "code": error_code(y), "from": node}
                        shipments.append((hops[-1], pack_frame(err)))
                    else:
                        ok_rows.append((gid, nn, y))
                if not ok_rows:
                    continue
                if header.get("gens") is not None:
                    reply = {"op": "forward",
                             "gens": [g for g, _, _ in ok_rows],
                             "num_new": [n for _, n, _ in ok_rows],
                             "new": header.get("new", False),
                             "seq": header.get("seq"),
                             "hops": hops[1:], "from": node}
                    y = np.concatenate([y for _, _, y in ok_rows], axis=0)
                else:
                    reply = {**header, "hops": hops[1:], "from": node}
                    y = ok_rows[0][2]
                shipments.append((hops[0], pack_frame(reply, y)))
            if shipments:
                self._out.put_many(shipments)
            return [None] * len(items)
        except (ConnectionError, OSError):
            return [None] * len(items)  # relay gone mid-reply: teardown
        except Exception:
            self.errors.append(traceback.format_exc())
            raise

    # -- health / leases ------------------------------------------------------

    def _health_loop(self) -> None:
        while not self._stop.is_set():
            # Event.wait, not time.sleep: stop() must return promptly, not
            # block up to a full heartbeat interval.
            if self._stop.wait(self.heartbeat_s):
                return
            try:
                alive = self._directory.heartbeat(
                    self.node_id, load=self.backend.load,
                    ttl=self.lease_ttl, epoch=self.epoch,
                )
                if not alive:  # lease lapsed (e.g. directory restart)
                    if not self._directory.register(
                        self.node_id, self.backend.first_layer,
                        self.backend.last_layer, self.queue,
                        ttl=self.lease_ttl, epoch=self.epoch,
                    ):
                        # Fenced: this incarnation was declared dead and
                        # its work re-homed. Serving on would split-brain
                        # the fleet — wind the node down instead.
                        self._stop.set()
                        return
            except (ConnectionError, OSError, TimeoutError, RuntimeError):
                continue
            if not self._consume_thread.is_alive():
                # The cause was recorded by _consume's own except hook; the
                # watchdog just restarts (``module.restart()`` intent,
                # reference server.py:23).
                self.restarts += 1
                self.metrics.counter("worker_restarts")
                self._consume_thread = self._spawn_consumer()

    def is_healthy(self) -> bool:
        return self._consume_thread.is_alive() and not self._stop.is_set()

    # -- lifecycle ------------------------------------------------------------

    def stop(self) -> None:
        if self._stop.is_set():
            return  # idempotent: fixtures and tests may both stop a node
        self._stop.set()
        try:
            self._directory.remove(self.node_id)
        except (ConnectionError, OSError, TimeoutError, RuntimeError):
            pass
        self._directory.close()
        self._consume_thread.join(timeout=5)
        self._health_thread.join(timeout=5)
        self._pool.stop()
        self._out.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
