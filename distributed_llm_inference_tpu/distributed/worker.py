"""Block worker: serve a layer block over the relay, with health + leases.

Completes what the reference left as stubs: the worker skeleton
(``/root/reference/distributed_llm_inference/server/worker.py:9-23`` — load a
block ``[block_index_start, block_index_end]`` and expose it) and the server
health/rebalance pseudocode (``server/server.py:5-24`` — register, monitor,
heartbeat, restart). One ``ServingNode`` =

* a :class:`BlockBackend` holding the layers this node serves,
* a consume loop on the node's relay queue (source-routed frames:
  ``hops[0]`` is the next destination — forward the block output there),
* a heartbeat thread renewing the directory lease (failure detection:
  a dead node's lease lapses and routing drops it),
* a watchdog that restarts the consume loop if it dies (the
  ``module.restart()`` intent of ``server.py:23``).

Frame header ops: ``forward`` (run the block), ``end`` (free the session),
``shutdown`` (stop the node; used by tests).
"""

from __future__ import annotations

import threading
import time
import traceback
import uuid
from typing import Dict, List, Optional

from ..config import ModelConfig
from .backend import BlockBackend, SchemaError
from .directory import DirectoryClient
from .messages import pack_frame, unpack_frame
from .relay import RelayClient

__all__ = ["ServingNode"]


class ServingNode:
    def __init__(
        self,
        relay_port: int,
        cfg: ModelConfig,
        layer_params,
        first_layer: int,
        last_layer: int,
        host: str = "127.0.0.1",
        node_id: Optional[str] = None,
        max_sessions: int = 8,
        max_seq_len: int = 512,
        heartbeat_s: float = 2.0,
        lease_ttl: float = 10.0,
        dtype=None,
    ):
        self.node_id = node_id or f"node-{uuid.uuid4().hex[:8]}"
        self.queue = f"block.{self.node_id}"
        self.host, self.relay_port = host, relay_port
        self.heartbeat_s, self.lease_ttl = heartbeat_s, lease_ttl
        kw = {} if dtype is None else {"dtype": dtype}
        self.backend = BlockBackend(
            cfg, layer_params, first_layer, last_layer, max_sessions,
            max_seq_len, **kw,
        )
        self._stop = threading.Event()
        self.errors: List[str] = []
        self.restarts = 0

        self._directory = DirectoryClient(relay_port, host)
        self._directory.register(
            self.node_id, first_layer, last_layer, self.queue, ttl=lease_ttl
        )
        self._consume_thread = self._spawn_consumer()
        self._health_thread = threading.Thread(
            target=self._health_loop, daemon=True
        )
        self._health_thread.start()

    # -- serve loop -----------------------------------------------------------

    def _spawn_consumer(self) -> threading.Thread:
        t = threading.Thread(target=self._consume, daemon=True,
                             name=f"{self.node_id}.consume")
        t.start()
        return t

    def _consume(self) -> None:
        client = RelayClient(self.host, self.relay_port)
        out = RelayClient(self.host, self.relay_port)
        try:
            while not self._stop.is_set():
                try:
                    frame = client.get(self.queue, timeout=0.5)
                except TimeoutError:
                    continue
                header, arr = unpack_frame(frame)
                op = header.get("op")
                if op == "shutdown":
                    return
                if op == "end":
                    self.backend.end(header.get("gen_id", ""))
                    continue
                if op != "forward":
                    continue
                hops = header.get("hops") or []
                try:
                    if not hops:
                        raise SchemaError("forward frame without hops")
                    y = self.backend.forward(
                        header["gen_id"], arr, header["num_new"],
                        create=bool(header.get("new", False)),
                    )
                    reply = {**header, "hops": hops[1:], "from": self.node_id}
                    out.put(hops[0], pack_frame(reply, y))
                except (SchemaError, KeyError, RuntimeError) as e:
                    # Protocol/session errors go back to the client's reply
                    # queue (last hop) so generate() fails fast instead of
                    # hanging; a hops-less frame has nowhere to report to.
                    if hops:
                        err = {"op": "error", "gen_id": header.get("gen_id"),
                               "error": f"{type(e).__name__}: {e}",
                               "from": self.node_id}
                        out.put(hops[-1], pack_frame(err))
        except (ConnectionError, OSError):
            return  # relay gone: health loop will notice / tests tear down
        except Exception:
            # Record the real cause here, where the exception is live — the
            # watchdog thread only sees that the loop died.
            self.errors.append(traceback.format_exc())
            raise
        finally:
            client.close()
            out.close()

    # -- health / leases ------------------------------------------------------

    def _health_loop(self) -> None:
        while not self._stop.is_set():
            time.sleep(self.heartbeat_s)
            if self._stop.is_set():
                return
            try:
                alive = self._directory.heartbeat(
                    self.node_id, load=self.backend.load, ttl=self.lease_ttl
                )
                if not alive:  # lease lapsed (e.g. directory restart)
                    self._directory.register(
                        self.node_id, self.backend.first_layer,
                        self.backend.last_layer, self.queue,
                        ttl=self.lease_ttl,
                    )
            except (ConnectionError, OSError, TimeoutError, RuntimeError):
                continue
            if not self._consume_thread.is_alive():
                # The cause was recorded by _consume's own except hook; the
                # watchdog just restarts (``module.restart()`` intent,
                # reference server.py:23).
                self.restarts += 1
                self._consume_thread = self._spawn_consumer()

    def is_healthy(self) -> bool:
        return self._consume_thread.is_alive() and not self._stop.is_set()

    # -- lifecycle ------------------------------------------------------------

    def stop(self) -> None:
        if self._stop.is_set():
            return  # idempotent: fixtures and tests may both stop a node
        self._stop.set()
        try:
            self._directory.remove(self.node_id)
        except (ConnectionError, OSError, TimeoutError, RuntimeError):
            pass
        self._directory.close()
        self._consume_thread.join(timeout=5)
        self._health_thread.join(timeout=5)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
