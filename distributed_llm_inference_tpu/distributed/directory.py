"""Block directory: which node serves which decoder layers.

Replaces the DHT the reference leaned on hivemind for (SURVEY §2.3 item 4,
§5.8): nodes serving a contiguous layer block register under a lease and
heartbeat to keep it alive (the serve-loop intent sketched at
``/root/reference/distributed_llm_inference/server/server.py:13-24``); clients
ask for a route — an ordered chain of nodes covering layers ``[0, L)``.

The directory state is plain Python (``BlockDirectory``); ``DirectoryService``
exposes it as a request/reply service over the activation relay (JSON frames,
reply-queue pattern), so the whole control+data plane rides one native
transport. Leases that miss their TTL expire and drop out of routing — the
failure-detection half of SURVEY §5.3.
"""

from __future__ import annotations

import json
import threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .relay import RelayClient

__all__ = ["BlockDirectory", "DirectoryService", "DirectoryClient", "NodeInfo"]

DIR_QUEUE = "directory.req"


@dataclass
class NodeInfo:
    node_id: str
    first_layer: int
    last_layer: int  # inclusive
    queue: str  # relay queue the node's worker consumes
    lease_expiry: float = 0.0
    load: int = 0  # active sessions (rebalance hint)
    # Provisional reservation from assign(): counts toward COVERAGE (the
    # next joiner is steered elsewhere) but never toward ROUTING (there is
    # no queue to send to until the node loads its weights and registers).
    pending: bool = False
    # Serving role: "both" (classic block worker), "decode" (decode-pool
    # member), or "prefill" (prefill-only worker — excluded from layer
    # routes; disaggregated gateways pick it by role instead).
    role: str = "both"
    # The node is handing its sessions off (fleet drain / scale-in):
    # gateways stop routing new work to it, but in-flight streams keep
    # flowing until the handoff lands and the lease is fenced.
    draining: bool = False
    # Monotonically increasing incarnation number the node picked when it
    # (re)started. Registrations and heartbeats carrying an epoch OLDER
    # than the table's are rejected — a partitioned zombie that wakes up
    # after its sessions were migrated cannot re-enter the fleet under
    # its stale identity (lease fencing).
    epoch: int = 0

    def covers(self, layer: int) -> bool:
        return self.first_layer <= layer <= self.last_layer


class BlockDirectory:
    """In-memory lease table. Thread-safe; embeds in the directory service
    process (single-writer), the analog of a DHT's authoritative record."""

    def __init__(self, default_ttl: float = 10.0):
        self.default_ttl = default_ttl
        self._nodes: Dict[str, NodeInfo] = {}
        self._lock = threading.Lock()
        # node_id -> fence floor: epochs <= floor may never register or
        # heartbeat again. Written by fence() when a gateway declares the
        # node dead and migrates its sessions away.
        self._fenced: Dict[str, int] = {}
        # Plain observability counters (the directory embeds in the
        # service process; scraping happens via snapshots, not Metrics).
        self.fenced_rejections = 0
        self.stale_heartbeats = 0
        # Prefix-cache advertisements (prefixstore/): node_id -> (page_size,
        # hex chain-key set) — which prompt-prefix pages each node can serve
        # a cache hit from. Refreshed whole-set each heartbeat cycle and
        # dropped with the lease: stale entries only cost a suboptimal
        # route, never a wrong answer (the engine recomputes on a miss).
        self._prefix: Dict[str, Tuple[int, frozenset]] = {}

    def register(
        self, node_id: str, first_layer: int, last_layer: int, queue: str,
        ttl: Optional[float] = None, role: str = "both", epoch: int = 0,
    ) -> bool:
        """Returns ``True`` when the lease was granted. ``False`` means the
        registration was FENCED: the epoch is at or below this node_id's
        fence floor, or older than the incarnation already holding the
        lease — the caller is a zombie and must stop serving."""
        if last_layer < first_layer:
            raise ValueError(f"bad layer range [{first_layer}, {last_layer}]")
        if role not in ("both", "decode", "prefill"):
            raise ValueError(f"bad role {role!r}")
        epoch = int(epoch)
        with self._lock:
            if epoch <= self._fenced.get(node_id, -1):
                self.fenced_rejections += 1
                return False
            cur = self._nodes.get(node_id)
            if cur is not None and not cur.pending and epoch < cur.epoch:
                self.fenced_rejections += 1
                return False
            # A real node arriving retires ONE matching pending reservation
            # immediately (the provisional lease assign() parked on this
            # range): leaving it to TTL out would double-count the range in
            # assign()'s coverage math and steer the next joiner away from
            # a hole that is in fact still open. Exact range match wins;
            # otherwise any reservation fully covered by the new node.
            for exact_only in (True, False):
                rid = next(
                    (
                        r for r, n in self._nodes.items()
                        if n.pending
                        and (
                            (n.first_layer, n.last_layer)
                            == (first_layer, last_layer)
                            if exact_only
                            else (first_layer <= n.first_layer
                                  and n.last_layer <= last_layer)
                        )
                    ),
                    None,
                )
                if rid is not None:
                    del self._nodes[rid]
                    break
            self._nodes[node_id] = NodeInfo(
                node_id, first_layer, last_layer, queue,
                time.monotonic() + (ttl or self.default_ttl),
                role=role, epoch=epoch,
            )
            return True

    def heartbeat(self, node_id: str, load: int = 0,
                  ttl: Optional[float] = None,
                  epoch: Optional[int] = None,
                  draining: bool = False) -> bool:
        with self._lock:
            info = self._nodes.get(node_id)
            if info is None:
                return False  # lease already expired: node must re-register
            if epoch is not None and int(epoch) != info.epoch:
                # A different incarnation holds the lease now (or the
                # caller never re-registered after fencing): refuse the
                # renewal so the zombie learns it is no longer a member.
                self.stale_heartbeats += 1
                return False
            info.lease_expiry = time.monotonic() + (ttl or self.default_ttl)
            info.load = load
            info.draining = bool(draining)
            return True

    def remove(self, node_id: str) -> None:
        with self._lock:
            self._nodes.pop(node_id, None)
            self._prefix.pop(node_id, None)

    def fence(self, node_id: str, epoch: Optional[int] = None) -> int:
        """Evict ``node_id`` and bar its current incarnation from ever
        re-joining: the fence floor becomes ``max(floor, epoch)`` (default:
        the epoch holding the lease right now). A genuinely restarted node
        re-registers above the floor with a fresh, higher epoch. Returns
        the new floor. Called by gateways before migrating the node's
        sessions — after this, a partitioned zombie's register/heartbeat
        both return False, so it can never serve (or corrupt) a stream
        that now lives elsewhere."""
        with self._lock:
            info = self._nodes.pop(node_id, None)
            self._prefix.pop(node_id, None)
            floor = self._fenced.get(node_id, -1)
            if epoch is not None:
                floor = max(floor, int(epoch))
            elif info is not None:
                floor = max(floor, info.epoch)
            else:
                floor = max(floor, 0)
            self._fenced[node_id] = floor
            return floor

    def _expire_locked(self) -> None:
        now = time.monotonic()
        for nid in [n for n, i in self._nodes.items() if i.lease_expiry < now]:
            del self._nodes[nid]
            self._prefix.pop(nid, None)

    def alive(self) -> List[NodeInfo]:
        with self._lock:
            self._expire_locked()
            return list(self._nodes.values())

    def assign(self, num_layers: int, span: Optional[int] = None,
               reserve_ttl: Optional[float] = None) -> Tuple[int, int]:
        """Choose the layer range a JOINING node should serve — the "choose
        optimal block ids" intent the reference sketched and never built
        (``/root/reference/distributed_llm_inference/server/server.py:8``).

        Policy, against the LIVE lease table (expired leases have already
        re-opened their layers, so a dead node's hole is re-advertised
        here automatically):

        * any uncovered layer → the range starting at the FIRST uncovered
          layer, extending ``span`` layers (restoring routability beats
          everything else);
        * full coverage → the ``span``-wide window with the THINNEST total
          replication (add redundancy where the chain is most fragile).

        ``span`` (default: whole model) caps how many layers the joining
        node is willing to hold. ``reserve_ttl`` records a PROVISIONAL
        reservation for the returned range (a pending lease: counted as
        coverage by later assign() calls, never routed to) so two spares
        joining concurrently — each spending minutes streaming weights
        before registering — don't both adopt the same hole while another
        stays open; the reservation expires on its own if the node never
        arrives, and the node's real register simply supersedes it.
        """
        if span is not None and span < 1:
            raise ValueError(f"span must be positive, got {span}")
        span = min(span or num_layers, num_layers)
        # Coverage read and reservation insert form ONE atomic step: two
        # joiners racing between an unlocked snapshot and the reserve would
        # both see the same hole, adopt it, and later collide in register()
        # while another hole stays open.
        with self._lock:
            self._expire_locked()
            cov = [0] * num_layers
            for n in self._nodes.values():
                for layer in range(n.first_layer, min(n.last_layer + 1,
                                                      num_layers)):
                    cov[layer] += 1
            if 0 in cov:
                # Start AT the gap (moving the range to fit a full span
                # would drift away from it); a tail gap simply yields a
                # shorter range.
                first = cov.index(0)
                last = min(first + span, num_layers) - 1
            else:
                sums = [
                    sum(cov[i : i + span])
                    for i in range(num_layers - span + 1)
                ]
                first = min(range(len(sums)), key=sums.__getitem__)
                last = first + span - 1
            if reserve_ttl:
                rid = f"reserved-{uuid.uuid4().hex[:8]}"
                self._nodes[rid] = NodeInfo(
                    rid, first, last, queue="",
                    lease_expiry=time.monotonic() + reserve_ttl,
                    pending=True,
                )
        return first, last

    # Per-node advertisement cap: a decode node's working set of REGISTERED
    # prefix pages, not its whole history — bounds directory memory at
    # ~forty bytes per key without changing match results for live prefixes
    # (the engine advertises its newest keys, matching its LRU survivors).
    MAX_PREFIX_HEADS = 4096

    def advertise_prefixes(self, node_id: str, page_size: int,
                           heads: List[str]) -> bool:
        """Replace ``node_id``'s advertised prefix-key set (hex chain keys
        of the prefix pages it can serve a cache hit from — device registry
        plus host spill arena). Whole-set replacement per heartbeat keeps
        the directory trivially consistent with the node's LRU: no
        tombstone protocol for evicted pages. Returns ``False`` (dropped)
        when the node holds no live lease — an advertisement must never
        outlive membership."""
        if page_size < 1:
            raise ValueError(f"page_size must be positive, got {page_size}")
        with self._lock:
            self._expire_locked()
            info = self._nodes.get(node_id)
            if info is None or info.pending:
                self._prefix.pop(node_id, None)
                return False
            self._prefix[node_id] = (
                int(page_size),
                frozenset(heads[-self.MAX_PREFIX_HEADS:]),
            )
            return True

    def match_prefix(self, prompt: List[int]) -> Tuple[Optional[str], int]:
        """The decode node holding the LONGEST advertised prefix of
        ``prompt`` (in tokens, page-granular), lower load breaking ties —
        the prefix-aware routing primitive. ``(None, 0)`` when nothing
        matches; pending/prefill-only nodes never match (there is no
        decode engine to hit)."""
        from ..prefixstore.index import match_tokens

        best: Optional[str] = None
        best_tokens = 0
        best_load = 0
        with self._lock:
            self._expire_locked()
            for nid, (ps, heads) in self._prefix.items():
                info = self._nodes.get(nid)
                if info is None or info.pending or info.role == "prefill":
                    continue
                got = match_tokens(prompt, ps, heads)
                if got > best_tokens or (
                    got == best_tokens and got > 0 and info.load < best_load
                ):
                    best, best_tokens, best_load = nid, got, info.load
        return best, best_tokens

    def plan_route(self, num_layers: int) -> List[NodeInfo]:
        """Greedy chain cover of layers ``[0, num_layers)``: at each position
        pick the live node extending coverage furthest (least-loaded on
        ties). Raises if there is a gap — the health signal a client acts on.
        """
        # Prefill-only workers never appear in layer routes: they hold full
        # weights but serve the admission phase, not the decode chain.
        nodes = [
            n for n in self.alive() if not n.pending and n.role != "prefill"
        ]
        route: List[NodeInfo] = []
        layer = 0
        while layer < num_layers:
            candidates = [
                n for n in nodes if n.first_layer <= layer <= n.last_layer
            ]
            if not candidates:
                raise LookupError(f"no live node serves layer {layer}")
            best = max(candidates, key=lambda n: (n.last_layer, -n.load))
            route.append(best)
            layer = best.last_layer + 1
        return route


class DirectoryService:
    """Serves a :class:`BlockDirectory` over the relay (background thread)."""

    def __init__(self, relay_port: int, host: str = "127.0.0.1",
                 default_ttl: float = 10.0):
        self.directory = BlockDirectory(default_ttl)
        self._client = RelayClient(host, relay_port)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._serve, daemon=True)
        self._thread.start()

    def _serve(self) -> None:
        while not self._stop.is_set():
            try:
                frame = self._client.get(DIR_QUEUE, timeout=0.5)
            except TimeoutError:
                continue
            except (ConnectionError, OSError):
                return
            # A malformed request (garbage frame, missing reply_to) must not
            # kill the control plane — drop it and keep serving.
            try:
                req = json.loads(frame)
                reply_to = req["reply_to"]
            except (ValueError, KeyError, TypeError):
                continue  # distcheck: reply-ok(malformed frame has no reply address)
            reply = self._handle(req)
            reply["rid"] = req.get("rid")
            try:
                self._client.put(reply_to, json.dumps(reply).encode())
            except (ConnectionError, OSError):
                return  # distcheck: reply-ok(no transport left to reply over)

    def _handle(self, req: dict) -> dict:
        d = self.directory
        try:
            op = req["op"]
            if op == "register":
                accepted = d.register(
                    req["node_id"], req["first_layer"],
                    req["last_layer"], req["queue"], req.get("ttl"),
                    req.get("role", "both"), req.get("epoch", 0),
                )
                return {"ok": True, "accepted": accepted}
            if op == "heartbeat":
                ok = d.heartbeat(req["node_id"], req.get("load", 0),
                                 req.get("ttl"), req.get("epoch"),
                                 req.get("draining", False))
                return {"ok": ok}
            if op == "remove":
                d.remove(req["node_id"])
                return {"ok": True}
            if op == "fence":
                floor = d.fence(req["node_id"], req.get("epoch"))
                return {"ok": True, "floor": floor}
            if op == "assign":
                first, last = d.assign(
                    req["num_layers"], req.get("span"),
                    req.get("reserve_ttl"),
                )
                return {"ok": True, "first_layer": first, "last_layer": last}
            if op == "route":
                route = d.plan_route(req["num_layers"])
                return {"ok": True, "route": [
                    {"node_id": n.node_id, "first_layer": n.first_layer,
                     "last_layer": n.last_layer, "queue": n.queue}
                    for n in route
                ]}
            if op == "prefix.advertise":
                ok = d.advertise_prefixes(
                    req["node_id"], req["page_size"],
                    list(req.get("heads", [])),
                )
                return {"ok": ok}
            if op == "prefix.match":
                node_id, tokens = d.match_prefix(list(req["prompt"]))
                return {"ok": True, "node_id": node_id, "tokens": tokens}
            if op == "alive":
                return {"ok": True, "nodes": [
                    {"node_id": n.node_id, "first_layer": n.first_layer,
                     "last_layer": n.last_layer, "queue": n.queue,
                     "load": n.load, "pending": n.pending, "role": n.role,
                     "draining": n.draining, "epoch": n.epoch}
                    for n in d.alive()
                ]}
            return {"ok": False, "error": f"unknown op {op!r}"}
        except (KeyError, ValueError, LookupError) as e:
            return {"ok": False, "error": f"{type(e).__name__}: {e}"}

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)
        self._client.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()


class DirectoryClient:
    """Node/client-side handle to the directory service."""

    def __init__(self, relay_port: int, host: str = "127.0.0.1"):
        self._client = RelayClient(host, relay_port)
        self._reply_queue = f"directory.reply.{uuid.uuid4().hex}"
        # distcheck: unguarded-ok(client contract: externally serialized)
        self._seq = 0

    def _call(self, req: dict, timeout: float = 5.0) -> dict:
        self._seq += 1
        rid = self._seq
        req["reply_to"] = self._reply_queue
        req["rid"] = rid
        self._client.put(DIR_QUEUE, json.dumps(req).encode())
        deadline = time.monotonic() + timeout
        while True:
            remaining = max(deadline - time.monotonic(), 0.001)
            reply = json.loads(
                self._client.get(self._reply_queue, timeout=remaining)
            )
            if reply.get("rid") == rid:
                break
            # Stale reply from an earlier timed-out call: discard so the
            # request/reply stream can never desync.
        if not reply.get("ok", False) and "error" in reply:
            kind = reply["error"].split(":", 1)[0]
            exc = {"LookupError": LookupError, "ValueError": ValueError}.get(
                kind, RuntimeError
            )
            raise exc(reply["error"])
        return reply

    def register(self, node_id: str, first_layer: int, last_layer: int,
                 queue: str, ttl: Optional[float] = None,
                 role: str = "both", epoch: int = 0) -> bool:
        """Returns ``True`` when the lease was granted, ``False`` when the
        registration was fenced (stale epoch) — the caller must stop
        serving under this identity."""
        r = self._call({"op": "register", "node_id": node_id,
                        "first_layer": first_layer, "last_layer": last_layer,
                        "queue": queue, "ttl": ttl, "role": role,
                        "epoch": epoch})
        return bool(r.get("accepted", True))

    def heartbeat(self, node_id: str, load: int = 0,
                  ttl: Optional[float] = None,
                  epoch: Optional[int] = None,
                  draining: bool = False) -> bool:
        return self._call({"op": "heartbeat", "node_id": node_id,
                           "load": load, "ttl": ttl, "epoch": epoch,
                           "draining": draining})["ok"]

    def remove(self, node_id: str) -> None:
        self._call({"op": "remove", "node_id": node_id})

    def fence(self, node_id: str, epoch: Optional[int] = None) -> int:
        """Evict and fence a node (see :meth:`BlockDirectory.fence`)."""
        return self._call({"op": "fence", "node_id": node_id,
                           "epoch": epoch})["floor"]

    def route(self, num_layers: int) -> List[dict]:
        return self._call({"op": "route", "num_layers": num_layers})["route"]

    def assign(self, num_layers: int, span: Optional[int] = None,
               reserve_ttl: Optional[float] = None) -> Tuple[int, int]:
        """Ask the directory which layer range a joining node should serve
        (see :meth:`BlockDirectory.assign`)."""
        r = self._call({"op": "assign", "num_layers": num_layers,
                        "span": span, "reserve_ttl": reserve_ttl})
        return r["first_layer"], r["last_layer"]

    def alive(self) -> List[dict]:
        return self._call({"op": "alive"})["nodes"]

    def advertise_prefixes(self, node_id: str, page_size: int,
                           heads: List[str]) -> bool:
        """Refresh this node's advertised prefix-key set (see
        :meth:`BlockDirectory.advertise_prefixes`); rides the heartbeat
        cadence. ``False`` = no live lease, the set was dropped."""
        return self._call({"op": "prefix.advertise", "node_id": node_id,
                           "page_size": page_size, "heads": heads})["ok"]

    def match_prefix(self, prompt: List[int],
                     timeout: float = 5.0) -> Tuple[Optional[str], int]:
        """Which decode node holds the longest cached prefix of ``prompt``
        (see :meth:`BlockDirectory.match_prefix`): ``(node_id | None,
        matched_tokens)``."""
        r = self._call(
            {"op": "prefix.match", "prompt": list(map(int, prompt))},
            timeout=timeout,
        )
        return r.get("node_id"), int(r.get("tokens", 0))

    def close(self) -> None:
        self._client.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
