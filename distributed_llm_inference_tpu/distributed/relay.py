"""Python driver for the native activation relay (``native/relay.cc``).

The relay is the cross-host (DCN) tier of the communication backend — the
role hivemind's libp2p/gRPC fabric plays in the reference (SURVEY §2.2 row 5,
``/root/reference/distributed_llm_inference/server/backend.py:4-7``). The hub
is C++ (epoll, zero-copy forwarding); endpoints speak a length-prefixed
binary protocol over plain TCP sockets.

``RelayServer`` loads the compiled ``.so`` via ctypes (built on demand with
``g++`` — no pybind11 in this image) and runs the hub in-process.
``RelayClient`` is a blocking endpoint with raw-bytes and numpy-tensor
framing; pipeline stages use queue names like ``"stage3.in"``.
"""

from __future__ import annotations

import ctypes
import os
import random
import socket
import struct
import subprocess
import threading
import time
import zlib
from typing import Optional, Tuple

import numpy as np

__all__ = ["RelayServer", "RelayClient", "build_native", "native_available"]

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(__file__)), "native")
_SRC = os.path.join(_NATIVE_DIR, "relay.cc")
_SO = os.path.join(_NATIVE_DIR, "_relay.so")
_build_lock = threading.Lock()

OP_PUT, OP_GET, OP_PING, OP_CANCEL = 1, 2, 3, 4
CANCEL_ACK = (1 << 64) - 1
# Ceiling on how long a frame already in flight may stall between bytes
# before the client treats it as lost and recycles the connection. Far
# above any legit hub→client delivery (frames cap at a few MiB), so the
# only thing it ever fires on is a wedged or fault-injected stream.
MID_FRAME_STALL_S = 30.0


def frame_crc(payload: bytes) -> int:
    """CRC-32 of a frame payload (zlib/IEEE — matches the hub's table)."""
    return zlib.crc32(payload) & 0xFFFFFFFF


def build_native(force: bool = False) -> str:
    """Compile ``relay.cc`` → ``_relay.so`` (cached by source mtime)."""
    with _build_lock:
        if (
            not force
            and os.path.exists(_SO)
            and os.path.getmtime(_SO) >= os.path.getmtime(_SRC)
        ):
            return _SO
        subprocess.run(
            ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", _SRC, "-o", _SO,
             "-pthread"],
            check=True,
            capture_output=True,
        )
        return _SO


def native_available() -> bool:
    try:
        build_native()
        return True
    except (OSError, subprocess.CalledProcessError):
        return False


class RelayServer:
    """In-process relay hub (the C++ epoll loop on a background thread)."""

    def __init__(self, port: int = 0):
        lib = ctypes.CDLL(build_native())
        lib.relay_start.restype = ctypes.c_void_p
        lib.relay_start.argtypes = [ctypes.c_int]
        lib.relay_port.restype = ctypes.c_int
        lib.relay_port.argtypes = [ctypes.c_void_p]
        lib.relay_stop.argtypes = [ctypes.c_void_p]
        self._lib = lib
        self._handle = lib.relay_start(port)
        if not self._handle:
            raise OSError(f"relay failed to bind port {port}")
        self.port = lib.relay_port(self._handle)

    def stop(self) -> None:
        if self._handle:
            self._lib.relay_stop(self._handle)
            self._handle = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()

    def __del__(self):
        try:
            self.stop()
        except Exception:
            pass


class RelayClient:
    """Blocking relay endpoint.

    One TCP connection; ``get`` parks server-side until a message arrives, so
    use one client per consumer thread. On ``get`` timeout the connection is
    recycled (the server drops dead waiters), keeping FIFO semantics clean.
    """

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        reconnect_timeout_s: float = 10.0,
        backoff_base_s: float = 0.05,
        backoff_max_s: float = 1.0,
    ):
        self.host, self.port = host, port
        self.reconnect_timeout_s = reconnect_timeout_s
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        # distcheck: unguarded-ok(one client = one consumer thread)
        self.reconnects = 0  # successful re-dials (observability)
        # close() flips this from any thread while _reconnect polls it;
        # a bool store is atomic and one stale read only costs one dial.
        # distcheck: unguarded-ok(atomic flag; stale read is benign)
        self._closed = False
        self._sock: Optional[socket.socket] = None
        self._connect()

    def _connect(self) -> None:
        self._sock = socket.create_connection((self.host, self.port))
        self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)

    def _reconnect(self) -> None:
        """Drop the (dead) connection and dial again with bounded
        exponential backoff + jitter — the transparent retry path for
        control-plane restarts (SURVEY §5.3: a hub restart of a few seconds
        must not permanently wedge long-lived clients like the worker's
        reply connection or the directory handle, so one failed dial is not
        the end: keep trying inside ``reconnect_timeout_s``)."""
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None
        deadline = time.monotonic() + self.reconnect_timeout_s
        attempt = 0
        while True:
            if self._closed:
                raise ConnectionError("relay client is closed")
            try:
                self._connect()
                self.reconnects += 1
                return
            except OSError as e:
                attempt += 1
                delay = min(
                    self.backoff_max_s, self.backoff_base_s * (2 ** (attempt - 1))
                ) * (0.5 + 0.5 * random.random())  # jitter: desync herds
                if time.monotonic() + delay >= deadline:
                    raise ConnectionError(
                        f"relay {self.host}:{self.port} unreachable after "
                        f"{attempt} attempts: {e}"
                    ) from e
                time.sleep(delay)

    def close(self) -> None:
        self._closed = True  # a concurrent _reconnect must stop dialing
        if self._sock is not None:
            self._sock.close()
            self._sock = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    # -- raw frames -----------------------------------------------------------

    def _require_open(self) -> None:
        if self._sock is None:
            raise ConnectionError("relay client is closed")

    @staticmethod
    def _encode_put(queue: str, payload: bytes) -> bytes:
        """One PUT frame: ``[op][qlen][queue][len:8][crc:4][payload]``. The
        CRC travels in the header so the hub can reject a payload damaged
        in flight at ingress (and the chaos layer can damage the wire bytes
        AFTER the crc is computed — a true corruption, not a re-signed one).
        """
        q = queue.encode()
        return (
            struct.pack(">BH", OP_PUT, len(q)) + q
            + struct.pack(">QI", len(payload), frame_crc(payload))
            + payload
        )

    def put(self, queue: str, payload: bytes) -> None:
        self._require_open()
        frame = self._encode_put(queue, payload)
        try:
            self._sock.sendall(frame)
        except (ConnectionError, OSError):
            # Reconnect so the NEXT op runs on a live connection, but do NOT
            # resend: the hub may have fully received the frame before the
            # connection died, and an at-least-once PUT would double-apply a
            # decode hop (the worker advances its cache twice and the stale
            # duplicate reply silently corrupts the client's token stream).
            # Callers treat the raise as a lost frame: workers drop the
            # reply (the client times out and replays), clients fail over
            # with a fresh generation_id.
            self._reconnect()
            raise

    def put_many(self, items) -> None:
        """Pipelined PUT: encode every ``(queue, payload)`` frame and ship
        them in ONE ``sendall`` — a node's whole fan-out of replies costs a
        single syscall, and the hub parses back-to-back frames straight off
        the stream (its ``process_input`` already loops over complete
        frames, so no protocol change is needed).

        Same no-resend contract as :meth:`put`: on a connection error the
        whole group is treated as lost (any prefix may have been applied, so
        resending could double-apply hops); callers fail over / replay.
        """
        self._require_open()
        data = b"".join(self._encode_put(q, p) for q, p in items)
        if not data:
            return
        try:
            self._sock.sendall(data)
        except (ConnectionError, OSError):
            self._reconnect()
            raise

    def _recv_exact(self, n: int) -> bytes:
        chunks = []
        while n:
            # Re-read self._sock each round: a concurrent close() nulls it,
            # and that race must surface as ConnectionError (the condition
            # callers already handle), never AttributeError.
            sock = self._sock
            if sock is None:
                raise ConnectionError("relay client is closed")
            chunk = sock.recv(min(n, 1 << 20))
            if not chunk:
                raise ConnectionError("relay connection closed")
            chunks.append(chunk)
            n -= len(chunk)
        return b"".join(chunks)

    def _recv_payload(self, length: int, queue: str) -> bytes:
        """Read ``[crc:4][payload:length]`` and verify. A mismatch means the
        hub→client leg damaged the bytes: recycle the connection (the
        stream may be desynced if framing itself was hit) and surface a
        LOST frame — callers time out / fail over and replay; garbage never
        reaches a model layer."""
        (crc,) = struct.unpack(">I", self._recv_exact(4))
        payload = self._recv_exact(length)
        if frame_crc(payload) != crc:
            self._reconnect()
            raise ConnectionError(
                f"corrupt frame on {queue!r} (crc mismatch): treated as lost"
            )
        return payload

    def get(self, queue: str, timeout: Optional[float] = None) -> bytes:
        self._require_open()
        try:
            return self._get_once(queue, timeout)
        except TimeoutError:
            raise  # a timed-out GET is not a broken connection
        except (ConnectionError, OSError):
            self._reconnect()
            return self._get_once(queue, timeout)

    def _get_once(self, queue: str, timeout: Optional[float]) -> bytes:
        sock = self._sock
        if sock is None:
            raise ConnectionError("relay client is closed")
        q = queue.encode()
        sock.sendall(struct.pack(">BH", OP_GET, len(q)) + q)
        # The caller's timeout applies only to the FIRST byte: once the hub
        # has started a reply it is expected to deliver the whole frame, so
        # a mid-frame timeout would normally desync the stream (discarded
        # partial length/payload bytes).
        sock.settimeout(timeout)
        try:
            first = sock.recv(1)
        except socket.timeout:
            self._settimeout(None)
            return self._cancel_pending(queue, timeout)
        finally:
            self._settimeout(None)
        if not first:
            raise ConnectionError("relay connection closed")
        # A started frame must keep flowing. With unbounded mid-frame reads,
        # a half-delivered frame (fault-injected truncation, wedged hub)
        # blocks the caller forever — even `get(timeout=...)` hangs. Bound
        # the remainder generously and surface a stall as a reconnectable
        # ConnectionError; the fresh connection cures the desync.
        self._settimeout(MID_FRAME_STALL_S)
        try:
            (length,) = struct.unpack(">Q", first + self._recv_exact(7))
            return self._recv_payload(length, queue)
        except socket.timeout as exc:
            self._reconnect()
            raise ConnectionError(
                f"frame on {queue!r} stalled mid-delivery: treated as lost"
            ) from exc
        finally:
            self._settimeout(None)

    def _settimeout(self, value) -> None:
        sock = self._sock
        if sock is not None:
            try:
                sock.settimeout(value)
            except OSError:
                pass  # closed concurrently; the next recv raises cleanly

    def _cancel_pending(self, queue: str, timeout) -> bytes:
        """Race-free GET timeout: CANCEL the parked waiter and read frames
        until the ack sentinel. A real reply that raced ahead of the CANCEL
        arrives before the ack — return it (arrived late beats lost). The
        ack sentinel is the bare 8-byte length ``CANCEL_ACK`` (no crc)."""
        sock = self._sock
        if sock is None:
            raise ConnectionError("relay client is closed")
        sock.sendall(struct.pack(">BH", OP_CANCEL, 0))
        self._settimeout(10.0)
        (length,) = struct.unpack(">Q", self._recv_exact(8))
        if length == CANCEL_ACK:
            raise TimeoutError(f"get({queue!r}) timed out after {timeout}s")
        payload = self._recv_payload(length, queue)
        (ack,) = struct.unpack(">Q", self._recv_exact(8))
        assert ack == CANCEL_ACK, "protocol desync after GET cancel"
        return payload

    def ping(self, timeout: float = 5.0) -> bool:
        self._require_open()
        self._sock.sendall(struct.pack(">BH", OP_PING, 0))
        self._settimeout(timeout)
        try:
            (length,) = struct.unpack(">Q", self._recv_exact(8))
            return self._recv_payload(length, "<ping>") == b"PONG"
        finally:
            self._settimeout(None)

    # -- tensor framing -------------------------------------------------------
    # [dtype_len:1][dtype str][ndim:1][dims:8 each][raw bytes]; bfloat16
    # travels as its raw uint16 bits with dtype tag "bfloat16".

    @staticmethod
    def encode_array(arr: np.ndarray, tag: Optional[str] = None) -> bytes:
        dtype = (tag or arr.dtype.str).encode()
        header = struct.pack(">B", len(dtype)) + dtype + struct.pack(
            ">B", arr.ndim
        ) + b"".join(struct.pack(">Q", d) for d in arr.shape)
        return header + arr.tobytes()

    @staticmethod
    def decode_array(buf: bytes) -> Tuple[np.ndarray, str]:
        (dlen,) = struct.unpack_from(">B", buf, 0)
        dtype = buf[1 : 1 + dlen].decode()
        off = 1 + dlen
        (ndim,) = struct.unpack_from(">B", buf, off)
        off += 1
        shape = tuple(
            struct.unpack_from(">Q", buf, off + 8 * i)[0] for i in range(ndim)
        )
        off += 8 * ndim
        raw = np.frombuffer(
            buf, dtype="<u2" if dtype == "bfloat16" else dtype, offset=off
        )
        return raw.reshape(shape), dtype

    def put_array(self, queue: str, arr, tag: Optional[str] = None) -> None:
        a = np.asarray(arr)
        if a.dtype.name == "bfloat16":  # ml_dtypes: send raw bits
            self.put(queue, self.encode_array(a.view(np.uint16), "bfloat16"))
        else:
            self.put(queue, self.encode_array(a, tag))

    def get_array(self, queue: str, timeout: Optional[float] = None):
        arr, dtype = self.decode_array(self.get(queue, timeout))
        if dtype == "bfloat16":
            import ml_dtypes

            return arr.view(ml_dtypes.bfloat16)
        return arr
