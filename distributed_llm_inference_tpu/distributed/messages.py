"""Data-plane frame format for the activation relay.

One frame = ``[header_len:4 BE][JSON header][tensor payload]`` — the role
msgpack/protobuf serialization plays inside hivemind's RPC (SURVEY §2.2 row
5). The header carries routing (source-routed ``hops``) and session metadata;
the payload is one tensor in ``RelayClient`` array framing (bf16-safe).
"""

from __future__ import annotations

import json
import struct
from typing import Any, Dict, Optional, Tuple

import numpy as np

from .relay import RelayClient

__all__ = ["pack_frame", "unpack_frame"]


def pack_frame(header: Dict[str, Any], array: Optional[np.ndarray] = None) -> bytes:
    h = json.dumps(header).encode()
    if array is None:
        payload = b""
    else:
        a = np.asarray(array)
        if a.dtype.name == "bfloat16":
            payload = RelayClient.encode_array(a.view(np.uint16), "bfloat16")
        else:
            payload = RelayClient.encode_array(a)
    return struct.pack(">I", len(h)) + h + payload


def unpack_frame(buf: bytes) -> Tuple[Dict[str, Any], Optional[np.ndarray]]:
    (hlen,) = struct.unpack_from(">I", buf, 0)
    header = json.loads(buf[4 : 4 + hlen].decode())
    body = buf[4 + hlen :]
    if not body:
        return header, None
    arr, dtype = RelayClient.decode_array(body)
    if dtype == "bfloat16":
        import ml_dtypes

        arr = arr.view(ml_dtypes.bfloat16)
    return header, arr
