"""Deterministic fault injection for the relay transport.

The paper's fabric is volunteer hardware over WAN links — frames get
dropped, delayed, duplicated, truncated, and (rarely, below TCP's own
16-bit checksum) corrupted, and whole connections sever mid-operation.
The failover machinery in ``client.py``/``worker.py`` only earns trust if
those faults can be produced ON DEMAND and REPLAYED exactly, so this
module provides:

* :class:`FaultRule` / :class:`FaultPlan` — a seeded schedule of faults,
  matchable by queue glob and operation. Same rules + same seed + same
  traffic ⇒ same injected sequence (the plan keeps an ``injected`` log so
  tests can assert the faults actually fired).
* :class:`ChaosProxy` — an in-process TCP proxy that sits between relay
  endpoints and the native hub, parses the real wire protocol in both
  directions, and applies the plan to individual frames. Because it
  mangles wire bytes AFTER the sender computed the frame CRC, a
  ``corrupt`` fault is a true in-flight corruption, not a re-signed one.
* :class:`ChaosRelayClient` — a :class:`RelayClient` whose connection
  transparently runs through its own :class:`ChaosProxy`.

Fault classes (``FaultRule.kind``):

================  ============================================================
``drop``          frame is swallowed; receiver sees a lost frame
``delay``         frame is forwarded after ``delay_s`` (reordering pressure)
``duplicate``     frame is forwarded twice (at-least-once delivery)
``truncate``      first half of the frame is sent, then the connection severs
``corrupt``       one payload byte is flipped (seeded choice); CRC catches it
``sever``         connection is closed mid-operation; frame is not forwarded
``crash``         whole-node death: every connection through the proxy severs
                  AND new ones are refused, so data frames and heartbeats
                  stop together (lease-expiry failure detection is testable)
================  ============================================================

CANCEL frames and the 8-byte cancel-ack sentinel are control traffic and
always pass untouched — chaosing the timeout handshake itself would test
the injector, not the transport.
"""

from __future__ import annotations

import dataclasses
import fnmatch
import random
import socket
import struct
import threading
import time
from typing import List, Optional, Sequence, Tuple

from .relay import CANCEL_ACK, OP_CANCEL, OP_GET, OP_PING, OP_PUT, RelayClient

__all__ = ["FaultRule", "FaultPlan", "ChaosProxy", "ChaosRelayClient"]

KINDS = ("drop", "delay", "duplicate", "truncate", "corrupt", "sever",
         "crash")

# Wire-direction op names a rule can match. ``put``/``get``/``ping`` are
# client→hub requests; ``reply`` is any hub→client payload frame.
OPS = ("put", "get", "ping", "reply", "any")


@dataclasses.dataclass
class FaultRule:
    """One line of a fault schedule.

    ``queue`` is a glob matched against the frame's queue name (requests
    carry it; replies are attributed to the queue of the GET/PING they
    answer). ``after`` skips the first N matching frames, ``count`` caps
    how many times the rule fires (None = unlimited), ``prob`` draws from
    the plan's seeded RNG.
    """

    kind: str
    queue: str = "*"
    op: str = "any"
    after: int = 0
    count: Optional[int] = 1
    prob: float = 1.0
    delay_s: float = 0.05
    # mutable match state (owned by the plan's lock)
    seen: int = 0
    fired: int = 0

    def __post_init__(self):
        if self.kind not in KINDS:
            raise ValueError(f"unknown fault kind {self.kind!r} (not in {KINDS})")
        if self.op not in OPS:
            raise ValueError(f"unknown op {self.op!r} (not in {OPS})")

    def matches(self, queue: str, op: str) -> bool:
        if self.op != "any" and self.op != op:
            return False
        return fnmatch.fnmatchcase(queue, self.queue)

    @classmethod
    def parse(cls, spec: str) -> "FaultRule":
        """Parse a CLI spec ``kind:queue:op[:k=v,...]``, e.g.
        ``drop:block.*:put:after=3,count=2`` or
        ``delay:client.*:reply:delay_s=0.2,prob=0.5``."""
        parts = spec.split(":")
        if len(parts) < 3:
            raise ValueError(
                f"fault spec {spec!r} needs at least kind:queue:op"
            )
        kind, queue, op = parts[0], parts[1], parts[2]
        kwargs = {}
        if len(parts) > 3 and parts[3]:
            for item in parts[3].split(","):
                k, _, v = item.partition("=")
                k = k.strip()
                if k == "count":
                    kwargs[k] = None if v in ("none", "inf") else int(v)
                elif k == "after":
                    kwargs[k] = int(v)
                elif k in ("prob", "delay_s"):
                    kwargs[k] = float(v)
                else:
                    raise ValueError(f"unknown fault option {k!r} in {spec!r}")
        return cls(kind=kind, queue=queue, op=op, **kwargs)


class FaultPlan:
    """A seeded, thread-safe schedule of :class:`FaultRule`.

    ``decide(queue, op)`` returns the first rule that fires for a frame
    (or None). All randomness — probabilistic firing and the corrupt-byte
    choice — comes from one seeded RNG under one lock, so a plan replays
    identically for identical traffic.
    """

    def __init__(self, rules: Sequence[FaultRule] = (), seed: int = 0):
        self.rules = list(rules)
        self.seed = seed
        self.rng = random.Random(seed)
        self.injected: List[Tuple[str, str, str]] = []  # (kind, queue, op)
        self._lock = threading.Lock()

    @classmethod
    def from_specs(cls, specs: Sequence[str], seed: int = 0) -> "FaultPlan":
        return cls([FaultRule.parse(s) for s in specs], seed=seed)

    def decide(self, queue: str, op: str) -> Optional[FaultRule]:
        with self._lock:
            for rule in self.rules:
                if not rule.matches(queue, op):
                    continue
                rule.seen += 1
                if rule.seen <= rule.after:
                    continue
                if rule.count is not None and rule.fired >= rule.count:
                    continue
                if rule.prob < 1.0 and self.rng.random() >= rule.prob:
                    continue
                rule.fired += 1
                self.injected.append((rule.kind, queue, op))
                return rule
        return None

    def corrupt(self, payload: bytes) -> bytes:
        """Flip one bit of one seeded-chosen byte (never a no-op)."""
        with self._lock:
            i = self.rng.randrange(len(payload))
        b = bytearray(payload)
        b[i] ^= 0x01
        return bytes(b)


class _Pipe:
    """One proxied connection: client socket ↔ upstream hub socket, a
    parsing forwarder thread per direction."""

    def __init__(self, proxy: "ChaosProxy", client: socket.socket):
        self.proxy = proxy
        self.client = client
        self.upstream = socket.create_connection(
            (proxy.upstream_host, proxy.upstream_port)
        )
        self.upstream.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.client.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self._lock = threading.Lock()
        self._severed = False
        # RelayClient is strictly serial (one outstanding GET/PING per
        # connection), so the queue of the last request is enough to
        # attribute the next reply frame.
        # c2s writes it, s2c reads it; the relay protocol is strictly
        # serial per pipe (one in-flight op), so the phases never overlap.
        # distcheck: unguarded-ok(protocol is strictly serial per pipe)
        self.last_tag = "*"
        for name, fn in (("c2s", self._c2s), ("s2c", self._s2c)):
            t = threading.Thread(
                target=self._guard, args=(fn,),
                name=f"chaos-{name}-{id(self) & 0xffff:x}", daemon=True,
            )
            t.start()

    def sever(self) -> None:
        with self._lock:
            if self._severed:
                return
            self._severed = True
        for s in (self.client, self.upstream):
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass
        self.proxy._forget(self)

    def _guard(self, fn) -> None:
        try:
            fn()
        except (ConnectionError, OSError, struct.error):
            pass
        finally:
            self.sever()

    def _read_exact(self, sock: socket.socket, n: int) -> bytes:
        chunks = []
        while n:
            chunk = sock.recv(min(n, 1 << 20))
            if not chunk:
                raise ConnectionError("chaos pipe closed")
            chunks.append(chunk)
            n -= len(chunk)
        return b"".join(chunks)

    def _apply(
        self,
        dst: socket.socket,
        frame: bytes,
        queue: str,
        op: str,
        payload_off: int,
    ) -> None:
        """Run the plan on one complete frame and forward accordingly.
        ``payload_off`` is where the CRC-covered payload starts inside
        ``frame`` (len(frame) for payload-less frames)."""
        rule = None if self.proxy.plan is None else self.proxy.plan.decide(
            queue, op
        )
        if rule is None:
            dst.sendall(frame)
            return
        kind = rule.kind
        if kind == "drop":
            return
        if kind == "delay":
            time.sleep(rule.delay_s)
            dst.sendall(frame)
            return
        if kind == "duplicate":
            dst.sendall(frame + frame)
            return
        if kind == "corrupt" and payload_off < len(frame):
            payload = self.proxy.plan.corrupt(frame[payload_off:])
            dst.sendall(frame[:payload_off] + payload)
            return
        if kind == "truncate":
            dst.sendall(frame[: max(1, len(frame) // 2)])
            self.sever()
            raise ConnectionError("chaos: truncated frame")
        if kind == "crash":
            # Whole-node death: take down every connection riding this
            # proxy (data stream AND the node's heartbeat/control dials)
            # and refuse reconnects — the only recovery signal left is
            # the directory lease expiring.
            self.proxy.crash()
            raise ConnectionError("chaos: node crashed")
        # sever (and corrupt on a payload-less frame, where there is
        # nothing under the CRC to flip): kill the connection.
        self.sever()
        raise ConnectionError("chaos: severed connection")

    def _c2s(self) -> None:
        """Parse client→hub requests: [op:1][qlen:2][queue] plus, for PUT,
        [len:8][crc:4][payload]."""
        while True:
            head = self._read_exact(self.client, 3)
            op, qlen = struct.unpack(">BH", head)
            qbytes = self._read_exact(self.client, qlen)
            queue = qbytes.decode("utf-8", "replace")
            if op == OP_PUT:
                meta = self._read_exact(self.client, 12)
                (plen,) = struct.unpack(">Q", meta[:8])
                payload = self._read_exact(self.client, plen)
                frame = head + qbytes + meta + payload
                self._apply(
                    self.upstream, frame, queue, "put", 3 + qlen + 12
                )
                continue
            frame = head + qbytes
            if op == OP_GET:
                self.last_tag = queue
                self._apply(self.upstream, frame, queue, "get", len(frame))
            elif op == OP_PING:
                self.last_tag = "<ping>"
                self._apply(
                    self.upstream, frame, "<ping>", "ping", len(frame)
                )
            else:  # CANCEL (or unknown): control traffic, never chaosed
                self.upstream.sendall(frame)

    def _s2c(self) -> None:
        """Parse hub→client replies: [len:8][crc:4][payload], or the bare
        8-byte CANCEL_ACK sentinel (forwarded untouched)."""
        while True:
            len8 = self._read_exact(self.upstream, 8)
            (length,) = struct.unpack(">Q", len8)
            if length == CANCEL_ACK:
                self.client.sendall(len8)
                continue
            rest = self._read_exact(self.upstream, 4 + length)
            frame = len8 + rest
            self._apply(self.client, frame, self.last_tag, "reply", 12)


class ChaosProxy:
    """TCP chaos proxy in front of a relay hub.

    Endpoints connect to ``proxy.port`` instead of the hub; every frame in
    either direction is parsed and run through the :class:`FaultPlan`.
    Reconnects (e.g. after a ``sever`` fault) land on a fresh upstream
    connection, so backoff/retry paths are exercised end to end.
    """

    def __init__(
        self,
        upstream_host: str,
        upstream_port: int,
        port: int = 0,
        plan: Optional[FaultPlan] = None,
    ):
        self.upstream_host = upstream_host
        self.upstream_port = upstream_port
        self.plan = plan
        self._pipes: List[_Pipe] = []
        self._plock = threading.Lock()
        # distcheck: unguarded-ok(atomic flag; accept loop tolerates stale)
        self._closed = False
        # Set by crash(): the node this proxy fronts is "dead" — existing
        # pipes are severed and new connections are accepted-then-closed
        # (connection refused semantics without racing the accept loop).
        # distcheck: unguarded-ok(atomic flag; accept loop tolerates stale)
        self._crashed = False
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind(("127.0.0.1", port))
        self._listener.listen(64)
        self.port = self._listener.getsockname()[1]
        self._thread = threading.Thread(
            target=self._accept_loop, name="chaos-accept", daemon=True
        )
        self._thread.start()

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                client, _ = self._listener.accept()
            except OSError:
                return  # listener closed by stop()
            if self._crashed:
                client.close()  # dead node: refuse the dial
                continue
            try:
                pipe = _Pipe(self, client)
            except OSError:
                client.close()  # upstream hub is down right now
                continue
            with self._plock:
                self._pipes.append(pipe)

    def _forget(self, pipe: _Pipe) -> None:
        with self._plock:
            try:
                self._pipes.remove(pipe)
            except ValueError:
                pass

    def sever_all(self) -> None:
        """Kill every live proxied connection (a hub 'blip' on demand)."""
        with self._plock:
            pipes = list(self._pipes)
        for p in pipes:
            p.sever()

    def crash(self) -> None:
        """Simulate whole-node death: sever every proxied connection AND
        refuse new ones until :meth:`revive`. A node whose relay traffic
        (data, directory heartbeats, everything) rides this proxy goes
        dark exactly like a machine losing power — its lease then expires
        on its own, which is the failure signal crash-recovery tests need
        to exercise."""
        self._crashed = True
        self.sever_all()

    @property
    def crashed(self) -> bool:
        return self._crashed

    def revive(self) -> None:
        """Undo :meth:`crash`: accept connections again (the 'zombie wakes
        up' half of fencing tests — the node comes back, the fleet must
        reject it)."""
        self._crashed = False

    def stop(self) -> None:
        self._closed = True
        try:
            self._listener.close()
        except OSError:
            pass
        self.sever_all()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()


class ChaosRelayClient(RelayClient):
    """A :class:`RelayClient` that dials the hub through its own private
    :class:`ChaosProxy`, so one endpoint can be subjected to a fault plan
    while the rest of the cluster stays on the clean path."""

    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 0,
        plan: Optional[FaultPlan] = None,
        **kwargs,
    ):
        self.proxy = ChaosProxy(host, port, plan=plan)
        super().__init__("127.0.0.1", self.proxy.port, **kwargs)

    @property
    def plan(self) -> Optional[FaultPlan]:
        return self.proxy.plan

    def close(self) -> None:
        super().close()
        self.proxy.stop()
