"""Batching task pool: aggregate concurrent requests into one device call.

The reference inherits this from hivemind — ``TaskPool(self.forward, …)`` at
``/root/reference/distributed_llm_inference/server/backend.py:42`` batches
concurrent RPC requests for the module; its own ``server/task_pool.py`` is an
8-line stub of the intended inference-aware replacement. This is that
replacement: a thread that drains a queue, groups compatible requests (same
shape signature) up to ``max_batch`` within ``window_s``, and runs them in one
call — submitters block on per-request futures.

Scheduling contract (the continuous-batching fix):

* Everything already queued is drained greedily (``get_nowait``) — a full
  queue dispatches with ZERO added latency.
* The linger window is a single deadline measured from the FIRST item of the
  batch, never one ``window_s`` per empty poll: worst-case added latency per
  batch is ``window_s``, not ``(max_batch - 1) * window_s``.
* Reaching ``max_batch`` dispatches immediately, deadline or not.
* Items whose signature doesn't match the batch being formed are deferred to
  a local list that is consumed BEFORE newly arrived queue items on later
  rounds — mixed ``end``/``fwd`` traffic can't starve either kind.
* ``submit(item, eager=True)`` marks an item as already-batched (e.g. a
  stacked multi-generation frame co-batched at the source): once the queue
  is drained, a batch containing any eager item dispatches immediately
  instead of lingering for stragglers that aren't coming.
"""

from __future__ import annotations

import queue
import threading
import time
from concurrent.futures import Future, InvalidStateError
from typing import Any, Callable, List, Optional, Tuple

__all__ = ["TaskPool"]


def _complete(fut: Future, result=None, exc: Optional[BaseException] = None):
    """Resolve a future exactly once: stop() failing leftovers can race
    _run() delivering real results (when the join timed out on a wedged
    fn) — the slower writer must lose quietly, not raise InvalidStateError
    out of stop()."""
    if fut.done():
        return
    try:
        if exc is not None:
            fut.set_exception(exc)
        else:
            fut.set_result(result)
    except InvalidStateError:
        pass


class TaskPool:
    """``fn(batch: List[item]) -> List[result]`` applied to drained groups.

    ``signature(item)`` keys compatibility — only items with equal signatures
    are batched together (e.g. decode steps vs differently-bucketed prefills).
    ``metrics`` (a ``utils.metrics.Metrics``), when given, records a
    ``pool_batch_occupancy`` histogram plus per-size counters so the serving
    tier can see how full its device calls actually run.
    """

    def __init__(
        self,
        fn: Callable[[List[Any]], List[Any]],
        max_batch: int = 8,
        window_s: float = 0.002,
        signature: Callable[[Any], Any] = lambda item: None,
        name: str = "task_pool",
        metrics=None,
    ):
        self.fn = fn
        self.max_batch = max_batch
        self.window_s = window_s
        self.signature = signature
        self.name = name
        self.metrics = metrics
        self._queue: "queue.Queue[Tuple[Any, Future, bool]]" = queue.Queue()
        # Incompatible items parked during earlier rounds, consumed before
        # new arrivals (fairness). Normally loop-thread-only, but stop()
        # drains it even when the join times out on a wedged fn — so every
        # access is locked (distcheck DC101: the unguarded drain raced the
        # loop thread's pop/append).
        self._dlock = threading.Lock()
        self._deferred: List[Tuple[Any, Future, bool]] = []  # distcheck: guarded-by(_dlock)
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=name)
        self._thread.start()

    def submit(self, item: Any, eager: bool = False) -> Future:
        """``eager`` marks an item that is already a batch in itself; its
        presence lets the dispatch loop skip the linger once the queue is
        empty."""
        if self._stop.is_set():
            raise RuntimeError(f"{self.name} is stopped")
        fut: Future = Future()
        self._queue.put((item, fut, eager))
        return fut

    def __call__(self, item: Any, timeout: float = 60.0) -> Any:
        return self.submit(item).result(timeout)

    def _take_deferred(self, sig) -> Optional[Tuple[Any, Future]]:
        with self._dlock:
            for i, item in enumerate(self._deferred):
                if self.signature(item[0]) == sig:
                    return self._deferred.pop(i)
        return None

    def _take_oldest(self) -> Optional[Tuple[Any, Future, bool]]:
        with self._dlock:
            if self._deferred:
                return self._deferred.pop(0)  # oldest parked group first
        return None

    def _loop(self) -> None:
        while not self._stop.is_set():
            first = self._take_oldest()
            if first is None:
                try:
                    first = self._queue.get(timeout=0.1)
                except queue.Empty:
                    continue
            batch = [first]
            sig = self.signature(first[0])
            eager = first[2]
            deadline = time.monotonic() + self.window_s
            while len(batch) < self.max_batch and not self._stop.is_set():
                item = self._take_deferred(sig)
                if item is None:
                    try:
                        item = self._queue.get_nowait()  # greedy drain
                    except queue.Empty:
                        # An eager member means this batch was co-batched at
                        # the source — nothing to linger for.
                        if eager:
                            break
                        remaining = deadline - time.monotonic()
                        if remaining <= 0:
                            break
                        try:
                            item = self._queue.get(timeout=remaining)
                        except queue.Empty:
                            break
                    if self.signature(item[0]) != sig:
                        with self._dlock:
                            self._deferred.append(item)
                        continue
                eager = eager or item[2]
                batch.append(item)
            if self.metrics is not None:
                self.metrics.observe("pool_batch_occupancy", len(batch))
                self.metrics.counter(f"pool_batches_size_{len(batch)}")
            self._run(batch)

    def _run(self, batch: List[Tuple[Any, Future, bool]]) -> None:
        items = [entry[0] for entry in batch]
        try:
            results = self.fn(items)
            if len(results) != len(items):
                raise RuntimeError(
                    f"{self.name}: fn returned {len(results)} results for "
                    f"{len(items)} items"
                )
            for entry, res in zip(batch, results):
                _complete(entry[1], result=res)
        except Exception as e:
            for entry in batch:
                _complete(entry[1], exc=e)

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)
        # Fail anything still queued or parked so submitters don't hang.
        # If the join above timed out (fn wedged on the device), the loop
        # thread is still live — drain under the lock and complete futures
        # race-safely rather than double-resolving what _run() just set.
        with self._dlock:
            leftovers = list(self._deferred)
            self._deferred = []
        while True:
            try:
                leftovers.append(self._queue.get_nowait())
            except queue.Empty:
                break
        err = RuntimeError(f"{self.name} stopped")
        for entry in leftovers:
            _complete(entry[1], exc=err)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
