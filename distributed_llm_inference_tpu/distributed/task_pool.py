"""Batching task pool: aggregate concurrent requests into one device call.

The reference inherits this from hivemind — ``TaskPool(self.forward, …)`` at
``/root/reference/distributed_llm_inference/server/backend.py:42`` batches
concurrent RPC requests for the module; its own ``server/task_pool.py`` is an
8-line stub of the intended inference-aware replacement. This is that
replacement: a thread that drains a queue, groups compatible requests (same
shape signature) up to ``max_batch`` within ``window_s``, and runs them in one
call — submitters block on per-request futures.
"""

from __future__ import annotations

import queue
import threading
from concurrent.futures import Future
from typing import Any, Callable, List, Sequence, Tuple

__all__ = ["TaskPool"]


class TaskPool:
    """``fn(batch: List[item]) -> List[result]`` applied to drained groups.

    ``signature(item)`` keys compatibility — only items with equal signatures
    are batched together (e.g. decode steps vs differently-bucketed prefills).
    """

    def __init__(
        self,
        fn: Callable[[List[Any]], List[Any]],
        max_batch: int = 8,
        window_s: float = 0.002,
        signature: Callable[[Any], Any] = lambda item: None,
        name: str = "task_pool",
    ):
        self.fn = fn
        self.max_batch = max_batch
        self.window_s = window_s
        self.signature = signature
        self.name = name
        self._queue: "queue.Queue[Tuple[Any, Future]]" = queue.Queue()
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name=name)
        self._thread.start()

    def submit(self, item: Any) -> Future:
        if self._stop.is_set():
            raise RuntimeError(f"{self.name} is stopped")
        fut: Future = Future()
        self._queue.put((item, fut))
        return fut

    def __call__(self, item: Any, timeout: float = 60.0) -> Any:
        return self.submit(item).result(timeout)

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                first = self._queue.get(timeout=0.1)
            except queue.Empty:
                continue
            batch = [first]
            sig = self.signature(first[0])
            deferred: List[Tuple[Any, Future]] = []
            # Linger up to window_s for compatible co-batchable requests.
            while len(batch) < self.max_batch:
                try:
                    item = self._queue.get(timeout=self.window_s)
                except queue.Empty:
                    break
                if self.signature(item[0]) == sig:
                    batch.append(item)
                else:
                    deferred.append(item)
            for item in deferred:  # incompatible: back for the next round
                self._queue.put(item)
            self._run(batch)

    def _run(self, batch: List[Tuple[Any, Future]]) -> None:
        items = [item for item, _ in batch]
        try:
            results = self.fn(items)
            if len(results) != len(items):
                raise RuntimeError(
                    f"{self.name}: fn returned {len(results)} results for "
                    f"{len(items)} items"
                )
            for (_, fut), res in zip(batch, results):
                fut.set_result(res)
        except Exception as e:
            for _, fut in batch:
                if not fut.done():
                    fut.set_exception(e)

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=5)
        # Fail anything still queued so submitters don't hang.
        while True:
            try:
                _, fut = self._queue.get_nowait()
            except queue.Empty:
                break
            if not fut.done():
                fut.set_exception(RuntimeError(f"{self.name} stopped"))

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.stop()
