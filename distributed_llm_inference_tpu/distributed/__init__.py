"""Cross-host (DCN) tier: native relay transport, block directory, serving
nodes, and the client/orchestrator — the layer hivemind provided (or the
reference left as stubs). Intra-slice parallelism lives in ``parallel/``."""

from .backend import BlockBackend, SchemaError
from .chaos import ChaosProxy, ChaosRelayClient, FaultPlan, FaultRule
from .client import DistributedClient
from .directory import BlockDirectory, DirectoryClient, DirectoryService
from .relay import RelayClient, RelayServer, native_available
from .task_pool import TaskPool
from .worker import ServingNode

__all__ = [
    "BlockBackend",
    "SchemaError",
    "ChaosProxy",
    "ChaosRelayClient",
    "FaultPlan",
    "FaultRule",
    "DistributedClient",
    "BlockDirectory",
    "DirectoryClient",
    "DirectoryService",
    "RelayClient",
    "RelayServer",
    "native_available",
    "TaskPool",
    "ServingNode",
]
