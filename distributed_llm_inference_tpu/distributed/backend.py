"""Inference backend: one resident layer-block behind jitted step functions.

The TPU-native form of ``InferenceBackend``
(``/root/reference/distributed_llm_inference/server/backend.py:11-51``):
inference-only (no backward — ``backend.py:44-48``), declared I/O schema with
the output schema inferred by a dummy forward (``backend.py:31-35``), and
multi-tenant sessions keyed by ``generation_id``
(``models/llama/cache.py:14-19``) mapped onto batch rows of one preallocated
cache. All device computation is cached ``jax.jit`` executables — the role
CUDA-graph capture plays in the reference (``utils/cuda.py:6``).

Two axes the reference prescribed but never composed are first-class here:

* **Cache kind** — the reference's sink cache is literally titled
  "Distributed implementation of sink cache"
  (``models/llama/cache.py:8-10``): its signature bounded-memory policy
  exists *for served blocks*. ``cache_cfg`` selects dense (growth-ladder),
  sink (StreamingLLM ring: unbounded streams, fixed memory) or paged
  (vLLM-style pool: page-granular growth) storage for this node's sessions,
  each optionally int8.
* **Local mesh** — the reference's worker serves
  ``block_index_start..end`` on whatever hardware the node has
  (``server/worker.py:13-14``). On a multi-chip host that means tensor
  parallelism *within* the node: ``mesh_cfg=MeshConfig(tp=N)`` shards the
  block's weights and KV over the host's chips with XLA inserting the ICI
  all-reduces, while the relay protocol (and every peer) is unchanged —
  the two-tier design of SURVEY §5.8 composed at last.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..cache.base import window_ladder
from ..cache.dense import DenseKVCache, QuantizedDenseKVCache
from ..cache.paged import PageAllocator, PagedKVCache, QuantizedPagedKVCache
from ..cache.sink import QuantizedSinkKVCache, SinkKVCache
from ..config import CacheConfig, MeshConfig, ModelConfig
from ..models import llama

__all__ = ["BlockBackend", "SchemaError"]


class SchemaError(ValueError):
    pass


class BlockBackend:
    """Serves ``block_apply`` over layers ``[first_layer, last_layer]`` for up
    to ``max_sessions`` interleaved generations."""

    def __init__(
        self,
        cfg: ModelConfig,
        layer_params,
        first_layer: int,
        last_layer: int,
        max_sessions: int = 8,
        max_seq_len: int = 512,
        dtype=jnp.bfloat16,
        session_idle_timeout: float = 60.0,
        quantize: Optional[str] = None,
        kv_quant: Optional[str] = None,
        cache_cfg: Optional[CacheConfig] = None,
        mesh_cfg: Optional[MeshConfig] = None,
    ):
        """``quantize`` ("int8"/"int4") serves the block with quantized
        weights — the deployment-facing optimization the reference applied
        on its serving node (bitsandbytes ``Linear8bitLt`` swap,
        ``/root/reference/distributed_llm_inference/utils/model.py:93-123``);
        ``kv_quant="int8"`` additionally stores this node's KV cache int8.

        ``cache_cfg`` selects the cache *kind* (dense/sink/paged — see the
        module docstring); omitted it is the dense growth-ladder cache, with
        ``kv_quant`` as shorthand for its int8 variant. ``mesh_cfg`` shards
        the node over its local chips (tp only — the cross-host axes are the
        relay's job, one node per stage)."""
        self.session_idle_timeout = session_idle_timeout
        self.cfg = cfg
        self.mesh = None
        self._shard_cache_fn = None
        tp = 1
        if mesh_cfg is not None:
            if (mesh_cfg.dp, mesh_cfg.pp, mesh_cfg.sp, mesh_cfg.ep) != (
                1, 1, 1, 1,
            ):
                raise ValueError(
                    "a block node shards over tp only (dp/pp/sp/ep are the "
                    f"relay tier's axes — one node per stage); got {mesh_cfg}"
                )
            tp = mesh_cfg.tp
            if cfg.num_kv_heads % tp != 0:
                raise ValueError(
                    f"tp={tp} must divide num_kv_heads={cfg.num_kv_heads}"
                )
            if cfg.intermediate_size % tp != 0:
                raise ValueError(
                    f"tp={tp} must divide intermediate_size="
                    f"{cfg.intermediate_size}"
                )
        if quantize in ("int8", "int4"):
            from ..ops.quant import quantize_params

            qkw = {}
            if quantize == "int4" and tp > 1:
                # The half-split packed layout interleaves channels within a
                # byte column and cannot column-shard; tp nodes keep the
                # grouped XLA layout with whole groups per device (the same
                # rule the engine applies under tp/pp meshes).
                qkw = {"int4_layout": "grouped", "group_multiple": tp}
            layer_params = quantize_params(
                layer_params, bits=4 if quantize == "int4" else 8, **qkw
            )
        elif quantize is not None:
            raise ValueError(f"unknown quantize {quantize!r}")
        if kv_quant not in (None, "int8"):
            raise ValueError(f"unknown kv_quant {kv_quant!r}")
        if cache_cfg is None:
            cache_cfg = CacheConfig(kind="dense", kv_quant=kv_quant)
        elif kv_quant is not None and kv_quant != cache_cfg.kv_quant:
            raise ValueError(
                f"kv_quant={kv_quant!r} conflicts with "
                f"cache_cfg.kv_quant={cache_cfg.kv_quant!r}"
            )
        if cache_cfg.kv_quant not in (None, "int8"):
            raise ValueError(f"unknown kv_quant {cache_cfg.kv_quant!r}")
        self.ccfg = cache_cfg
        self.params = layer_params
        self.first_layer, self.last_layer = first_layer, last_layer
        self.num_block_layers = last_layer - first_layer + 1
        self.max_sessions = max_sessions
        self.max_seq_len = max_seq_len
        self.dtype = jnp.dtype(dtype)

        cc = cache_cfg
        L, B = self.num_block_layers, max_sessions
        q8 = cc.kv_quant == "int8"
        self.allocator: Optional[PageAllocator] = None
        self._slot_pages: Dict[int, List[int]] = {}
        self._windows: Tuple[int, ...] = ()
        if cc.kind == "dense":
            cls = QuantizedDenseKVCache if q8 else DenseKVCache
            # Growth ladder (shared with the engine): the buffer starts at
            # the smallest bucket and zero-pad-grows as resident sessions
            # lengthen, so decode bandwidth tracks LIVE context; max_seq_len
            # is the virtual cap.
            self._windows = window_ladder(max_seq_len)
            self._make_cache = lambda w: cls.create(
                L, B, w, cfg.num_kv_heads, cfg.head_dim, dtype
            )
            self.cache = self._make_cache(self._windows[0])
        elif cc.kind == "sink":
            # StreamingLLM ring: fixed memory, unbounded streams —
            # max_seq_len does not cap sink sessions.
            cls = QuantizedSinkKVCache if q8 else SinkKVCache
            kw = {"use_kernel": False} if q8 else {}
            self.cache = cls.create(
                L, B, cc.window_length, cc.num_sink_tokens,
                cfg.num_kv_heads, cfg.head_dim, dtype, **kw,
            )
        elif cc.kind == "paged":
            slots = max(1, -(-max_seq_len // cc.page_size))
            cls = QuantizedPagedKVCache if q8 else PagedKVCache
            self.cache = cls.create(
                L, B, cc.num_pages, cc.page_size, slots,
                cfg.num_kv_heads, cfg.head_dim, dtype,
            )
            self.allocator = PageAllocator(cc.num_pages)
        else:
            raise ValueError(f"unknown cache kind {cc.kind!r}")

        if tp > 1:
            from ..parallel import (
                build_mesh, cache_pspecs, param_pspecs, shard_pytree,
            )

            self.mesh = build_mesh(mesh_cfg)
            self.params = shard_pytree(
                self.params, self.mesh,
                param_pspecs({"layers": self.params})["layers"],
            )
            self._shard_cache_fn = lambda c: shard_pytree(
                c, self.mesh, cache_pspecs(c)
            )
            self.cache = self._shard_cache_fn(self.cache)

        # generation_id → (slot row, last-touch time); free slots LRU-reused.
        self.sessions: Dict[str, Tuple[int, float]] = {}
        # Host-side per-slot lengths (avoids a device sync per hop).
        self._slot_len: Dict[int, int] = {}

        def _row_step(params, x, cache, row, n_valid):
            sub = cache.select_row(row)
            y, sub = llama.block_apply(self.cfg, params, x, sub, n_valid[None])
            sub = sub.advance(n_valid[None])
            return y, cache.merge_row(sub, row)

        self._row_step = self._in_mesh(jax.jit(_row_step, donate_argnums=(2,)))

        # Batched step over ALL session rows at once (rows with num_new=0 are
        # masked): N concurrent hops become one device call. Single hops keep
        # the row step — it reads only that row's cache, while this one reads
        # every row's.
        def _batch_step(params, x, cache, num_new):
            y, cache = llama.block_apply(self.cfg, params, x, cache, num_new)
            return y, cache.advance(num_new)

        self._batch_step = self._in_mesh(
            jax.jit(_batch_step, donate_argnums=(2,))
        )
        # Observability (tests assert batching actually happens).
        self.batched_calls = 0
        self.batched_items = 0

        # Output schema inferred by a dummy forward (the reference's
        # ``backend.py:31-35`` pattern): hidden-in → hidden-out, same shape.
        # The probe always runs on a throwaway dense cache — the schema
        # depends only on the hidden size, not the serving cache kind.
        probe = jnp.zeros((1, 1, cfg.hidden_size), dtype)
        y, _ = self._row_step(
            self.params, probe,
            DenseKVCache.create(self.num_block_layers, 1, 8,
                                cfg.num_kv_heads, cfg.head_dim, dtype),
            jnp.int32(0), jnp.int32(1),
        )
        self.output_schema = {"shape_suffix": (cfg.hidden_size,),
                              "dtype": str(y.dtype)}

    def _in_mesh(self, fn):
        """Run a jitted step inside the mesh context when serving sharded."""
        if self.mesh is None:
            return fn
        mesh = self.mesh

        def wrapped(*a, **kw):
            with mesh:
                return fn(*a, **kw)

        return wrapped

    # -- session management ---------------------------------------------------

    def _free_slot_pages(self, slot: int) -> None:
        pages = self._slot_pages.pop(slot, None)
        if pages:
            self.allocator.free(pages)

    def _slot_for(self, generation_id: str, create: bool) -> int:
        if generation_id in self.sessions:
            slot = self.sessions[generation_id][0]
            self.sessions[generation_id] = (slot, time.monotonic())
            return slot
        if not create:
            # Decode step for a session this node no longer holds (evicted,
            # restarted, or never prefilled here) — silently creating an
            # empty row would produce garbage tokens; fail loudly instead so
            # the client can restart the generation.
            raise KeyError(f"unknown generation {generation_id}")
        used = {s for s, _ in self.sessions.values()}
        free = [i for i in range(self.max_sessions) if i not in used]
        if free:
            slot = free[0]
        else:
            # Only sessions idle past the timeout may be evicted (abandoned
            # generations); live sessions are never silently corrupted —
            # admission fails instead and the client retries elsewhere.
            now = time.monotonic()
            idle = [
                g for g, (_, touched) in self.sessions.items()
                if now - touched >= self.session_idle_timeout
            ]
            if not idle:
                raise RuntimeError(
                    f"node full: {self.max_sessions} live sessions"
                )
            lru = min(idle, key=lambda g: self.sessions[g][1])
            slot = self.sessions.pop(lru)[0]
        if (
            self._windows
            and not self.sessions
            and self.cache.max_len > self._windows[0]
        ):
            # Nothing resident: drop back to the smallest bucket (no copy).
            self.cache = self._make_cache(self._windows[0])
            if self._shard_cache_fn is not None:
                self.cache = self._shard_cache_fn(self.cache)
        self.sessions[generation_id] = (slot, time.monotonic())
        self._slot_len[slot] = 0
        if self.allocator is not None:
            self._free_slot_pages(slot)
        self.cache = self.cache.reset_rows(
            np.arange(self.max_sessions) == slot
        )
        return slot

    def end(self, generation_id: str) -> None:
        entry = self.sessions.pop(generation_id, None)
        if entry is not None and self.allocator is not None:
            self._free_slot_pages(entry[0])

    @property
    def load(self) -> int:
        return len(self.sessions)

    # -- forward --------------------------------------------------------------

    def validate(self, x: np.ndarray, num_new: int) -> None:
        if x.ndim != 3 or x.shape[0] != 1:
            raise SchemaError(f"expected [1, S, H] hidden states, got {x.shape}")
        if x.shape[-1] != self.cfg.hidden_size:
            raise SchemaError(
                f"hidden dim {x.shape[-1]} != {self.cfg.hidden_size}"
            )
        if not (0 < num_new <= x.shape[1]):
            raise SchemaError(f"num_new {num_new} outside (0, {x.shape[1]}]")

    def _check_capacity(self, needed: int, num_new: int) -> None:
        """Per-kind session-length policy. Dense/paged cap at max_seq_len;
        sink streams are unbounded (the ring's fixed memory IS the policy)
        but a single chunk must fit the ring span."""
        if self.ccfg.kind == "sink":
            span = self.cache.window - self.cache.num_sinks
            if num_new > span:
                raise SchemaError(
                    f"chunk of {num_new} exceeds the sink ring span {span}"
                )
            return
        if needed > self.max_seq_len:
            raise SchemaError(
                f"session exceeds max_seq_len={self.max_seq_len}"
            )

    def _ensure_pages(self, installs, resolved, items, results):
        """Paged kind: map enough pool pages for every resolved hop BEFORE
        the device step (the scheduler half of ``PagedKVCache.fits``).
        Collected installs go to the device in ONE batched scatter.

        Pool pressure fails only the STARVED item (node_full-class error the
        client retries elsewhere), never its co-batched neighbours; a fresh
        admission that could not get pages is rolled back so it does not
        occupy a slot with an unusable empty session."""
        ok = []
        for item in resolved:
            i, slot, _, _, needed = item
            have = self._slot_pages.setdefault(slot, [])
            want = -(-needed // self.ccfg.page_size)
            if want > len(have):
                try:
                    fresh = self.allocator.alloc(want - len(have))
                except MemoryError as e:
                    results[i] = RuntimeError(f"node full: {e}")
                    if self._slot_len.get(slot, 0) == 0:
                        self.sessions.pop(items[i][0], None)
                        self._free_slot_pages(slot)
                    continue
                for j, page in enumerate(fresh):
                    installs.append((slot, len(have) + j, page))
                have.extend(fresh)
            ok.append(item)
        return ok

    def forward(
        self, generation_id: str, x, num_new: int, create: bool = False
    ) -> np.ndarray:
        """Run the block for one session; ``x`` ``[1, S, H]`` (padded to a
        bucket), ``num_new`` = valid token count. ``create`` admits a new
        session (the prefill hop); decode hops require the session to exist.
        Returns ``[1, S, H]``."""
        result = self.forward_many([(generation_id, x, num_new, create)])[0]
        if isinstance(result, Exception):
            raise result
        return result

    def forward_many(self, items) -> List:
        """Run N forward hops in ONE device call — the batching role the
        reference delegated to hivemind's ``TaskPool``
        (``/root/reference/distributed_llm_inference/server/backend.py:42``).

        ``items``: ``[(generation_id, x, num_new, create), …]`` with equal
        padded ``S`` (the task pool's signature guarantees this). Returns one
        result per item, positionally; a failed item carries its exception so
        one bad request cannot fail the co-batched ones.
        """
        results: List = [None] * len(items)
        resolved = []  # (item idx, slot, x, num_new, new total length)
        taken = set()
        deferred = []  # same-slot duplicates: run in a follow-up call
        for i, (gid, x, num_new, create) in enumerate(items):
            try:
                xa = np.asarray(x)
                self.validate(xa, num_new)
                slot = self._slot_for(gid, create=create)
                if slot in taken:
                    deferred.append(i)
                    continue
                needed = self._slot_len.get(slot, 0) + num_new
                self._check_capacity(needed, num_new)
                taken.add(slot)
                resolved.append((i, slot, xa, num_new, needed))
            except Exception as e:
                results[i] = e

        if resolved:
            if self._windows:
                need_max = max(n for *_, n in resolved)
                if need_max > self.cache.max_len:
                    self.cache = self.cache.grow_to(
                        next(w for w in self._windows if w >= need_max)
                    )
                    if self._shard_cache_fn is not None:
                        self.cache = self._shard_cache_fn(self.cache)
            if self.allocator is not None:
                installs: List[Tuple[int, int, int]] = []
                resolved = self._ensure_pages(installs, resolved, items,
                                              results)
                if installs:
                    self.cache = self.cache.assign_pages_batch(
                        [r for r, _, _ in installs],
                        [s for _, s, _ in installs],
                        [p for _, _, p in installs],
                    )
        if resolved:
            if len(resolved) == 1:
                i, slot, xa, num_new, needed = resolved[0]
                y, self.cache = self._row_step(
                    self.params, jnp.asarray(xa, self.dtype), self.cache,
                    jnp.int32(slot), jnp.int32(num_new),
                )
                results[i] = np.asarray(jax.device_get(y))
                self._slot_len[slot] = needed
            else:
                s = resolved[0][2].shape[1]
                xb = np.zeros(
                    (self.max_sessions, s, self.cfg.hidden_size), np.float32
                )
                nn = np.zeros((self.max_sessions,), np.int32)
                for i, slot, xa, num_new, _ in resolved:
                    xb[slot] = xa[0]
                    nn[slot] = num_new
                y, self.cache = self._batch_step(
                    self.params, jnp.asarray(xb, self.dtype), self.cache,
                    jnp.asarray(nn),
                )
                yh = np.asarray(jax.device_get(y))
                self.batched_calls += 1
                self.batched_items += len(resolved)
                for i, slot, _, _, needed in resolved:
                    results[i] = yh[slot : slot + 1]
                    self._slot_len[slot] = needed

        if deferred:
            for i, r in zip(
                deferred, self.forward_many([items[i] for i in deferred])
            ):
                results[i] = r
        return results
