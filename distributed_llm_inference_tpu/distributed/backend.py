"""Inference backend: one resident layer-block behind jitted step functions.

The TPU-native form of ``InferenceBackend``
(``/root/reference/distributed_llm_inference/server/backend.py:11-51``):
inference-only (no backward — ``backend.py:44-48``), declared I/O schema with
the output schema inferred by a dummy forward (``backend.py:31-35``), and
multi-tenant sessions keyed by ``generation_id``
(``models/llama/cache.py:14-19``) mapped onto batch rows of one preallocated
cache. All device computation is cached ``jax.jit`` executables — the role
CUDA-graph capture plays in the reference (``utils/cuda.py:6``).
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..cache.base import window_ladder
from ..cache.dense import DenseKVCache, QuantizedDenseKVCache
from ..config import ModelConfig
from ..models import llama

__all__ = ["BlockBackend", "SchemaError"]


class SchemaError(ValueError):
    pass


class BlockBackend:
    """Serves ``block_apply`` over layers ``[first_layer, last_layer]`` for up
    to ``max_sessions`` interleaved generations."""

    def __init__(
        self,
        cfg: ModelConfig,
        layer_params,
        first_layer: int,
        last_layer: int,
        max_sessions: int = 8,
        max_seq_len: int = 512,
        dtype=jnp.bfloat16,
        session_idle_timeout: float = 60.0,
        quantize: Optional[str] = None,
        kv_quant: Optional[str] = None,
    ):
        """``quantize`` ("int8"/"int4") serves the block with quantized
        weights — the deployment-facing optimization the reference applied
        on its serving node (bitsandbytes ``Linear8bitLt`` swap,
        ``/root/reference/distributed_llm_inference/utils/model.py:93-123``);
        ``kv_quant="int8"`` additionally stores this node's KV cache int8."""
        self.session_idle_timeout = session_idle_timeout
        self.cfg = cfg
        if quantize in ("int8", "int4"):
            from ..ops.quant import quantize_params

            layer_params = quantize_params(
                layer_params, bits=4 if quantize == "int4" else 8
            )
        elif quantize is not None:
            raise ValueError(f"unknown quantize {quantize!r}")
        if kv_quant not in (None, "int8"):
            raise ValueError(f"unknown kv_quant {kv_quant!r}")
        self.params = layer_params
        self.first_layer, self.last_layer = first_layer, last_layer
        self.num_block_layers = last_layer - first_layer + 1
        self.max_sessions = max_sessions
        self.max_seq_len = max_seq_len
        self.dtype = jnp.dtype(dtype)
        self._cache_cls = (
            QuantizedDenseKVCache if kv_quant == "int8" else DenseKVCache
        )

        # Growth ladder (shared with the engine): the buffer starts at the
        # smallest bucket and zero-pad-grows as resident sessions lengthen,
        # so decode bandwidth tracks LIVE context; max_seq_len is the
        # virtual cap.
        self._windows = window_ladder(max_seq_len)
        self.cache = self._cache_cls.create(
            self.num_block_layers, max_sessions, self._windows[0],
            cfg.num_kv_heads, cfg.head_dim, dtype,
        )
        # generation_id → (slot row, last-touch time); free slots LRU-reused.
        self.sessions: Dict[str, Tuple[int, float]] = {}
        # Host-side per-slot lengths (avoids a device sync per hop).
        self._slot_len: Dict[int, int] = {}

        def _row_step(params, x, cache, row, n_valid):
            sub = cache.select_row(row)
            y, sub = llama.block_apply(self.cfg, params, x, sub, n_valid[None])
            sub = sub.advance(n_valid[None])
            return y, cache.merge_row(sub, row)

        self._row_step = jax.jit(_row_step, donate_argnums=(2,))

        # Batched step over ALL session rows at once (rows with num_new=0 are
        # masked): N concurrent hops become one device call. Single hops keep
        # the row step — it reads only that row's cache, while this one reads
        # every row's.
        def _batch_step(params, x, cache, num_new):
            y, cache = llama.block_apply(self.cfg, params, x, cache, num_new)
            return y, cache.advance(num_new)

        self._batch_step = jax.jit(_batch_step, donate_argnums=(2,))
        # Observability (tests assert batching actually happens).
        self.batched_calls = 0
        self.batched_items = 0

        # Output schema inferred by a dummy forward (the reference's
        # ``backend.py:31-35`` pattern): hidden-in → hidden-out, same shape.
        probe = jnp.zeros((1, 1, cfg.hidden_size), dtype)
        y, _ = self._row_step(
            self.params, probe,
            self._cache_cls.create(self.num_block_layers, 1, 8,
                                   cfg.num_kv_heads, cfg.head_dim, dtype),
            jnp.int32(0), jnp.int32(1),
        )
        self.output_schema = {"shape_suffix": (cfg.hidden_size,),
                              "dtype": str(y.dtype)}

    # -- session management ---------------------------------------------------

    def _slot_for(self, generation_id: str, create: bool) -> int:
        if generation_id in self.sessions:
            slot = self.sessions[generation_id][0]
            self.sessions[generation_id] = (slot, time.monotonic())
            return slot
        if not create:
            # Decode step for a session this node no longer holds (evicted,
            # restarted, or never prefilled here) — silently creating an
            # empty row would produce garbage tokens; fail loudly instead so
            # the client can restart the generation.
            raise KeyError(f"unknown generation {generation_id}")
        used = {s for s, _ in self.sessions.values()}
        free = [i for i in range(self.max_sessions) if i not in used]
        if free:
            slot = free[0]
        else:
            # Only sessions idle past the timeout may be evicted (abandoned
            # generations); live sessions are never silently corrupted —
            # admission fails instead and the client retries elsewhere.
            now = time.monotonic()
            idle = [
                g for g, (_, touched) in self.sessions.items()
                if now - touched >= self.session_idle_timeout
            ]
            if not idle:
                raise RuntimeError(
                    f"node full: {self.max_sessions} live sessions"
                )
            lru = min(idle, key=lambda g: self.sessions[g][1])
            slot = self.sessions.pop(lru)[0]
        if not self.sessions and self.cache.max_len > self._windows[0]:
            # Nothing resident: drop back to the smallest bucket (no copy).
            self.cache = self._cache_cls.create(
                self.num_block_layers, self.max_sessions, self._windows[0],
                self.cfg.num_kv_heads, self.cfg.head_dim, self.dtype,
            )
        self.sessions[generation_id] = (slot, time.monotonic())
        self._slot_len[slot] = 0
        self.cache = self.cache.reset_rows(
            np.arange(self.max_sessions) == slot
        )
        return slot

    def end(self, generation_id: str) -> None:
        self.sessions.pop(generation_id, None)

    @property
    def load(self) -> int:
        return len(self.sessions)

    # -- forward --------------------------------------------------------------

    def validate(self, x: np.ndarray, num_new: int) -> None:
        if x.ndim != 3 or x.shape[0] != 1:
            raise SchemaError(f"expected [1, S, H] hidden states, got {x.shape}")
        if x.shape[-1] != self.cfg.hidden_size:
            raise SchemaError(
                f"hidden dim {x.shape[-1]} != {self.cfg.hidden_size}"
            )
        if not (0 < num_new <= x.shape[1]):
            raise SchemaError(f"num_new {num_new} outside (0, {x.shape[1]}]")

    def forward(
        self, generation_id: str, x, num_new: int, create: bool = False
    ) -> np.ndarray:
        """Run the block for one session; ``x`` ``[1, S, H]`` (padded to a
        bucket), ``num_new`` = valid token count. ``create`` admits a new
        session (the prefill hop); decode hops require the session to exist.
        Returns ``[1, S, H]``."""
        result = self.forward_many([(generation_id, x, num_new, create)])[0]
        if isinstance(result, Exception):
            raise result
        return result

    def forward_many(self, items) -> List:
        """Run N forward hops in ONE device call — the batching role the
        reference delegated to hivemind's ``TaskPool``
        (``/root/reference/distributed_llm_inference/server/backend.py:42``).

        ``items``: ``[(generation_id, x, num_new, create), …]`` with equal
        padded ``S`` (the task pool's signature guarantees this). Returns one
        result per item, positionally; a failed item carries its exception so
        one bad request cannot fail the co-batched ones.
        """
        results: List = [None] * len(items)
        resolved = []  # (item idx, slot, x, num_new, new total length)
        taken = set()
        deferred = []  # same-slot duplicates: run in a follow-up call
        for i, (gid, x, num_new, create) in enumerate(items):
            try:
                xa = np.asarray(x)
                self.validate(xa, num_new)
                slot = self._slot_for(gid, create=create)
                if slot in taken:
                    deferred.append(i)
                    continue
                needed = self._slot_len.get(slot, 0) + num_new
                if needed > self.max_seq_len:
                    raise SchemaError(
                        f"session exceeds max_seq_len={self.max_seq_len}"
                    )
                taken.add(slot)
                resolved.append((i, slot, xa, num_new, needed))
            except Exception as e:
                results[i] = e

        if resolved:
            need_max = max(n for *_, n in resolved)
            if need_max > self.cache.max_len:
                self.cache = self.cache.grow_to(
                    next(w for w in self._windows if w >= need_max)
                )
            if len(resolved) == 1:
                i, slot, xa, num_new, needed = resolved[0]
                y, self.cache = self._row_step(
                    self.params, jnp.asarray(xa, self.dtype), self.cache,
                    jnp.int32(slot), jnp.int32(num_new),
                )
                results[i] = np.asarray(jax.device_get(y))
                self._slot_len[slot] = needed
            else:
                s = resolved[0][2].shape[1]
                xb = np.zeros(
                    (self.max_sessions, s, self.cfg.hidden_size), np.float32
                )
                nn = np.zeros((self.max_sessions,), np.int32)
                for i, slot, xa, num_new, _ in resolved:
                    xb[slot] = xa[0]
                    nn[slot] = num_new
                y, self.cache = self._batch_step(
                    self.params, jnp.asarray(xb, self.dtype), self.cache,
                    jnp.asarray(nn),
                )
                yh = np.asarray(jax.device_get(y))
                self.batched_calls += 1
                self.batched_items += len(resolved)
                for i, slot, _, _, needed in resolved:
                    results[i] = yh[slot : slot + 1]
                    self._slot_len[slot] = needed

        if deferred:
            for i, r in zip(
                deferred, self.forward_many([items[i] for i in deferred])
            ):
                results[i] = r
        return results
