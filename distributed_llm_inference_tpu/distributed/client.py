"""Client/orchestrator: the layer the reference never wrote.

SURVEY §1: "There is no client layer (no code that runs the embedding/lm_head,
routes a prompt through a chain of remote blocks, or samples tokens)". This is
that layer: the client holds the embedding + final-norm + lm_head (the
non-layer weights a block node never loads), asks the directory for a route
covering all decoder layers, source-routes hidden states through the chain of
block workers over the relay, and samples tokens.

The per-request ``generation_id`` threads through every hop — the session key
of the reference's multi-tenant cache design (``models/llama/model.py:27`` →
``cache.py:74``) — so each worker pins the session to one cache row.
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..config import ModelConfig
from ..engine.sampling import SamplingOptions, SamplingParams, sample
from ..models import llama
from ..utils.metrics import Metrics
from .directory import DirectoryClient
from .messages import pack_frame, unpack_frame
from .relay import RelayClient

__all__ = ["DistributedClient", "WorkerError"]


class WorkerError(RuntimeError):
    """An error frame reported by a block worker.

    ``retryable`` is True when the condition indicates session loss (worker
    restarted / session evicted — ``KeyError: unknown generation`` from
    ``backend.py``), i.e. a replay on a fresh route can succeed; deterministic
    worker errors (bad request shapes, capacity) are not retried.
    """

    def __init__(self, message: str, retryable: bool):
        super().__init__(message)
        self.retryable = retryable


class DistributedClient:
    """Routes generations through remote block workers.

    ``params`` needs ``embed``, ``final_norm`` and (unless tied) ``lm_head``
    — e.g. from ``checkpoint.load_model_params`` or, leaner, a loader that
    skips the decoder layers.
    """

    def __init__(
        self,
        relay_port: int,
        cfg: ModelConfig,
        params,
        host: str = "127.0.0.1",
        prefill_buckets: Sequence[int] = (32, 128, 512),
        dtype=jnp.bfloat16,
    ):
        self.cfg = cfg
        self.params = params
        self.dtype = jnp.dtype(dtype)
        self.prefill_buckets = tuple(prefill_buckets)
        self.host, self.relay_port = host, relay_port
        # The directory connection is shared across concurrent generations
        # (its request/reply pairs must not interleave); relay connections
        # are per-generation (each owns its reply queue), which is what
        # makes N in-flight generations per client instance safe.
        self._directory = DirectoryClient(relay_port, host)
        self._dir_lock = threading.Lock()
        self.failovers = 0  # mid-generation re-route count (observability)
        self.metrics = Metrics()  # /metrics surface for chaos observability

        self._embed = jax.jit(
            lambda emb, t: jnp.take(emb, t, axis=0).astype(self.dtype)
        )

        def _head_last(params, x, idx):
            last = jax.lax.dynamic_slice_in_dim(x, idx, 1, axis=1)
            return llama.apply_head(self.cfg, params, last)

        self._head_last = jax.jit(_head_last)

        def _sample_last(params, x, idx, key, sp):
            last = jax.lax.dynamic_slice_in_dim(x, idx, 1, axis=1)
            logits = llama.apply_head(self.cfg, params, last)
            return sample(logits[:, 0], key, sp)

        self._sample_last = jax.jit(_sample_last)

    # -- routing --------------------------------------------------------------

    def plan_route(self) -> List[dict]:
        with self._dir_lock:
            return self._directory.route(self.cfg.num_layers)

    def _bucket(self, n: int) -> int:
        for b in self.prefill_buckets:
            if n <= b:
                return b
        raise ValueError(
            f"prompt length {n} exceeds largest bucket "
            f"{self.prefill_buckets[-1]}"
        )

    def _send_through(self, relay, route, gen_id: str, x: np.ndarray,
                      num_new: int, timeout: float, reply_queue: str,
                      new: bool = False, seq: int = 0) -> np.ndarray:
        hops = [n["queue"] for n in route[1:]] + [reply_queue]
        # ``seq`` numbers every hop of a generation: workers skip a frame
        # whose seq they already applied (an at-least-once transport must
        # not advance the KV cache twice), and the reply loop below skips
        # duplicated replies instead of mistaking them for the next hop's.
        header = {"op": "forward", "gen_id": gen_id, "num_new": num_new,
                  "hops": hops, "new": new, "seq": seq}
        relay.put(route[0]["queue"], pack_frame(header, np.asarray(x)))
        deadline = time.monotonic() + timeout
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"no reply for {gen_id} hop seq={seq} within {timeout}s"
                )
            reply_header, y = unpack_frame(
                relay.get(reply_queue, timeout=remaining)
            )
            if reply_header.get("op") == "error":
                msg = (
                    f"worker {reply_header.get('from')}: "
                    f"{reply_header['error']}"
                )
                # Retryability keys on the machine-readable code (worker.py:
                # error_code); the message-text fallback covers frames from
                # older workers that predate the code field.
                code = reply_header.get("code")
                retryable = (
                    code == "unknown_generation" if code is not None
                    else "unknown generation" in reply_header["error"]
                )
                raise WorkerError(msg, retryable=retryable)
            if reply_header.get("gen_id") != gen_id:
                raise RuntimeError(
                    "out-of-order reply on a per-generation queue "
                    "(protocol bug)"
                )
            rseq = reply_header.get("seq")
            if rseq is not None and rseq != seq:
                # A duplicated delivery of an earlier hop's reply: discard
                # and keep waiting for the real one.
                self.metrics.counter("stale_replies_discarded")
                continue
            return y

    def _end_session(self, relay, route, gen_id: str) -> None:
        """Best-effort: surviving nodes free the session's cache row; dead
        nodes/relays are ignored (their rows age out with the node)."""
        for node in route:
            try:
                relay.put(node["queue"], pack_frame(
                    {"op": "end", "gen_id": gen_id}
                ))
            except Exception:
                pass

    def _await_route(self, deadline: float) -> None:
        """Poll the directory until some chain covers all layers again (a
        replacement node's registration is what ends the wait). The attempt
        re-plans for itself — routes can change between poll and use."""
        while True:
            try:
                self.plan_route()
                return
            except (LookupError, TimeoutError, ConnectionError, OSError,
                    RuntimeError):
                # Coverage gap, or the directory/relay itself is still down
                # (control-plane restart) — keep polling until the deadline.
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.25)

    # -- generation -----------------------------------------------------------

    def generate(
        self,
        prompt: Sequence[int],
        max_new_tokens: int = 16,
        eos_token_id: Optional[int] = None,
        timeout: float = 60.0,
        max_retries: int = 2,
        reroute_wait: float = 15.0,
        options: Optional[SamplingOptions] = None,
        seed: int = 0,
        on_token: Optional[Callable[[int], None]] = None,
        stop_check: Optional[Callable[[], bool]] = None,
    ) -> List[int]:
        """Decode one prompt through the remote chain. Thread-safe: each
        call owns its relay connection and reply queue, so N generations may
        run concurrently on one client instance (the multi-tenant sessions
        then co-batch on the serving nodes' task pools).

        ``options`` carries sampling controls (temperature/top-k/top-p —
        sampling happens client-side, where the head lives); default greedy.
        ``seed`` keys the sampling stream: same seed, same tokens.

        Mid-generation failover (SURVEY §5.3): if a hop dies (reply timeout /
        worker error), the client waits for the directory to route around the
        loss, then REPLAYS the session on the new chain — re-prefilling
        ``prompt + tokens so far`` under a fresh ``generation_id`` (the
        replayed prefix is preserved verbatim; the continuation resumes the
        same keyed sampling stream).

        ``on_token`` (the HTTP gateway's streaming hook) is called once per
        FRESH token, in order — a failover replay re-feeds cached tokens
        without re-emitting them. ``stop_check`` is polled between decode
        hops and before each retry; returning True abandons the generation
        (tokens so far are returned) — the gateway's cancel/deadline path.
        """
        if not len(prompt):
            raise ValueError("empty prompt")
        opts = options or SamplingOptions()
        if eos_token_id is None and opts.eos_token_id >= 0:
            eos_token_id = opts.eos_token_id
        out: List[int] = []
        failures = 0
        key = jax.random.PRNGKey(seed)
        while True:
            relay = None
            try:
                # Inside the try: a relay outage at attempt start (the
                # control-plane-restart case) must count as a retried
                # failover, not escape to the caller.
                relay = RelayClient(self.host, self.relay_port)
                return self._generate_attempt(
                    relay, list(prompt), out, max_new_tokens, eos_token_id,
                    timeout, opts, key, on_token, stop_check,
                )
            except (TimeoutError, RuntimeError, ConnectionError, OSError) as e:
                # Besides timeouts and worker errors, a relay/control-plane
                # restart surfaces as a connection error mid-hop — that is a
                # failover, not a client failure.
                if isinstance(e, WorkerError) and not e.retryable:
                    raise  # deterministic worker error: replay cannot help
                failures += 1
                self.failovers += 1
                self.metrics.counter("failovers")
                if failures > max_retries:
                    raise
                if stop_check is not None and stop_check():
                    return out  # caller abandoned it: don't wait for a route
                self._await_route(time.monotonic() + reroute_wait)
            finally:
                if relay is not None:
                    relay.close()

    def _prefill_chunks(self, relay, route, gen_id, tokens, timeout,
                        reply_queue):
        """Push ``tokens`` through the chain in bucket-sized chunks (the
        first with ``new=True``); returns ``(last chunk's hidden states,
        index of the last valid position in that chunk, next hop seq)``."""
        cap = self.prefill_buckets[-1]
        chunks = [tokens[i : i + cap] for i in range(0, len(tokens), cap)]
        y, last_n = None, 0
        for ci, chunk in enumerate(chunks):
            n = len(chunk)
            bucket = self._bucket(n)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :n] = np.asarray(chunk, np.int32)
            x = self._embed(self.params["embed"], jnp.asarray(padded))
            y = self._send_through(relay, route, gen_id, np.asarray(x), n,
                                   timeout, reply_queue, new=(ci == 0),
                                   seq=ci)
            last_n = n
        return y, last_n, len(chunks)

    def _next_token(self, y, idx, opts, key, step):
        """Sample the next token from hidden states ``y`` at position
        ``idx`` (client-side head). Greedy rows bypass the RNG entirely."""
        if opts.temperature <= 0.0:
            logits = self._head_last(self.params, jnp.asarray(y), idx)
            return int(jnp.argmax(logits[0, -1]))
        sp = SamplingParams.create(
            1, opts.temperature, opts.top_k, opts.top_p
        )
        tok = self._sample_last(
            self.params, jnp.asarray(y), idx,
            jax.random.fold_in(key, step), sp,
        )
        return int(tok[0])

    def _generate_attempt(
        self, relay, prompt, out: List[int], max_new_tokens, eos_token_id,
        timeout, opts, key, on_token=None, stop_check=None,
    ) -> List[int]:
        """One route's worth of progress; ``out`` persists across attempts."""
        if out and (len(out) >= max_new_tokens or out[-1] == eos_token_id):
            return out  # the failed hop was already past the last token
        route = self.plan_route()
        gen_id = f"gen-{uuid.uuid4().hex[:12]}"
        # Per-attempt reply queue: a late reply from a slow (not dead) old
        # route must not land in the new attempt's stream.
        reply_queue = f"client.{uuid.uuid4().hex[:12]}"
        try:
            # (Re-)prefill: the prompt plus all but the newest generated
            # token (the newest is not in any cache yet — it is fed as the
            # first decode step below). Chunked, so a replay longer than one
            # bucket (long generation before the failure) still fits.
            replay = prompt + out[:-1]
            y, last_n, seq = self._prefill_chunks(
                relay, route, gen_id, replay, timeout, reply_queue
            )
            if out:
                token = out[-1]
            else:
                token = self._next_token(y, last_n - 1, opts, key, 0)
                out.append(token)
                if on_token is not None:
                    on_token(token)
            # Decode loop: one hidden-state hop per token. The sampling key
            # folds in the token INDEX, so a replayed attempt continues the
            # same stream rather than restarting it.
            while len(out) < max_new_tokens and token != eos_token_id:
                if stop_check is not None and stop_check():
                    return out
                x = self._embed(
                    self.params["embed"], jnp.asarray([[token]], jnp.int32)
                )
                y = self._send_through(relay, route, gen_id, np.asarray(x),
                                       1, timeout, reply_queue, seq=seq)
                seq += 1
                token = self._next_token(y, 0, opts, key, len(out))
                out.append(token)
                if on_token is not None:
                    on_token(token)
            return out
        finally:
            self._end_session(relay, route, gen_id)

    def close(self) -> None:
        self._directory.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
