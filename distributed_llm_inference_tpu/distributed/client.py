"""Client/orchestrator: the layer the reference never wrote.

SURVEY §1: "There is no client layer (no code that runs the embedding/lm_head,
routes a prompt through a chain of remote blocks, or samples tokens)". This is
that layer: the client holds the embedding + final-norm + lm_head (the
non-layer weights a block node never loads), asks the directory for a route
covering all decoder layers, source-routes hidden states through the chain of
block workers over the relay, and samples tokens.

The per-request ``generation_id`` threads through every hop — the session key
of the reference's multi-tenant cache design (``models/llama/model.py:27`` →
``cache.py:74``) — so each worker pins the session to one cache row.
"""

from __future__ import annotations

import threading
import time
import uuid
from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..config import ModelConfig
from ..engine.sampling import SamplingOptions, SamplingParams, sample
from ..models import llama
from ..utils.metrics import Metrics
from .directory import DirectoryClient
from .messages import pack_frame, unpack_frame
from .relay import RelayClient

__all__ = ["DistributedClient", "WorkerError"]


class WorkerError(RuntimeError):
    """An error frame reported by a block worker.

    ``retryable`` is True when the condition indicates session loss (worker
    restarted / session evicted — ``KeyError: unknown generation`` from
    ``backend.py``), i.e. a replay on a fresh route can succeed; deterministic
    worker errors (bad request shapes, capacity) are not retried.
    """

    def __init__(self, message: str, retryable: bool):
        super().__init__(message)
        self.retryable = retryable


class _Row:
    """Per-prompt state for :meth:`DistributedClient.generate_many` —
    persists across failover attempts (``out`` is the replay source)."""

    __slots__ = ("index", "prompt", "out", "opts", "max_new", "eos", "key",
                 "done", "reason")

    def __init__(self, index, prompt, opts, max_new, eos, key):
        self.index = index
        self.prompt = prompt
        self.out: List[int] = []
        self.opts = opts
        self.max_new = max_new
        self.eos = eos
        self.key = key
        self.done = False
        self.reason: Optional[str] = None


class DistributedClient:
    """Routes generations through remote block workers.

    ``params`` needs ``embed``, ``final_norm`` and (unless tied) ``lm_head``
    — e.g. from ``checkpoint.load_model_params`` or, leaner, a loader that
    skips the decoder layers.
    """

    def __init__(
        self,
        relay_port: int,
        cfg: ModelConfig,
        params,
        host: str = "127.0.0.1",
        prefill_buckets: Sequence[int] = (32, 128, 512),
        dtype=jnp.bfloat16,
        max_pooled_connections: int = 4,
    ):
        self.cfg = cfg
        self.params = params
        self.dtype = jnp.dtype(dtype)
        self.prefill_buckets = tuple(prefill_buckets)
        self.host, self.relay_port = host, relay_port
        # The directory connection is shared across concurrent generations
        # (its request/reply pairs must not interleave); relay connections
        # are per-generation (each owns its reply queue), which is what
        # makes N in-flight generations per client instance safe. Idle
        # connections are pooled and reused across attempts/generations —
        # ``connections_opened`` counts actual dials, not attempts.
        self._directory = DirectoryClient(relay_port, host)
        self._dir_lock = threading.Lock()
        self._conn_pool: List[RelayClient] = []
        self._conn_lock = threading.Lock()
        self._max_pooled = max_pooled_connections
        self.failovers = 0  # mid-generation re-route count (observability)
        self.metrics = Metrics()  # /metrics surface for chaos observability

        self._embed = jax.jit(
            lambda emb, t: jnp.take(emb, t, axis=0).astype(self.dtype)
        )

        def _head_last(params, x, idx):
            last = jax.lax.dynamic_slice_in_dim(x, idx, 1, axis=1)
            return llama.apply_head(self.cfg, params, last)

        self._head_last = jax.jit(_head_last)

        def _sample_last(params, x, idx, key, sp):
            last = jax.lax.dynamic_slice_in_dim(x, idx, 1, axis=1)
            logits = llama.apply_head(self.cfg, params, last)
            return sample(logits[:, 0], key, sp)

        self._sample_last = jax.jit(_sample_last)

        # Batched (generate_many) variants: one device call over the whole
        # stack of active rows, with per-row last-position gather.
        def _head_rows(params, x, idx):
            last = jnp.take_along_axis(x, idx[:, None, None], axis=1)
            return llama.apply_head(self.cfg, params, last)  # [A, 1, V]

        self._head_rows = jax.jit(_head_rows)

        def _sample_rows(params, x, idx, keys, steps, temps, tks, tps):
            logits = _head_rows(params, x, idx)[:, 0]  # [A, V]
            # vmap of the SERIAL per-row computation — each row samples a
            # [1, V] slice under its own folded key, so tokens are
            # byte-identical to N independent generate() calls (one shared
            # key over [A, V] would draw a different stream per row).
            def one(lg, k, st, t, tk, tp):
                sp = SamplingParams(
                    temperature=t[None], top_k=tk[None], top_p=tp[None],
                    all_greedy=False,
                )
                return sample(lg[None], jax.random.fold_in(k, st), sp)[0]

            return jax.vmap(one)(logits, keys, steps, temps, tks, tps)

        self._sample_rows = jax.jit(_sample_rows)

    # -- relay connection pool -------------------------------------------------

    def _acquire_relay(self) -> RelayClient:
        with self._conn_lock:
            if self._conn_pool:
                return self._conn_pool.pop()
        self.metrics.counter("connections_opened")
        return RelayClient(self.host, self.relay_port)

    def _release_relay(self, relay: RelayClient) -> None:
        """Return a connection that finished an attempt CLEANLY (no
        outstanding GET, reply queue retired) to the pool; error paths must
        close instead — a half-read stream would desync the next user."""
        with self._conn_lock:
            if len(self._conn_pool) < self._max_pooled:
                self._conn_pool.append(relay)
                return
        relay.close()

    # -- routing --------------------------------------------------------------

    def plan_route(self) -> List[dict]:
        with self._dir_lock:
            # The directory client owns one socket; the lock IS the
            # serialization of that RPC — callers block behind it by design.
            # distcheck: blocking-ok(single shared directory socket; the lock serializes the RPC)
            return self._directory.route(self.cfg.num_layers)

    def _bucket(self, n: int) -> int:
        for b in self.prefill_buckets:
            if n <= b:
                return b
        raise ValueError(
            f"prompt length {n} exceeds largest bucket "
            f"{self.prefill_buckets[-1]}"
        )

    def _send_through(self, relay, route, gen_id: str, x: np.ndarray,
                      num_new: int, timeout: float, reply_queue: str,
                      new: bool = False, seq: int = 0) -> np.ndarray:
        hops = [n["queue"] for n in route[1:]] + [reply_queue]
        # ``seq`` numbers every hop of a generation: workers skip a frame
        # whose seq they already applied (an at-least-once transport must
        # not advance the KV cache twice), and the reply loop below skips
        # duplicated replies instead of mistaking them for the next hop's.
        header = {"op": "forward", "gen_id": gen_id, "num_new": num_new,
                  "hops": hops, "new": new, "seq": seq}
        relay.put(route[0]["queue"], pack_frame(header, np.asarray(x)))
        deadline = time.monotonic() + timeout
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"no reply for {gen_id} hop seq={seq} within {timeout}s"
                )
            reply_header, y = unpack_frame(
                relay.get(reply_queue, timeout=remaining)
            )
            if reply_header.get("op") == "error":
                msg = (
                    f"worker {reply_header.get('from')}: "
                    f"{reply_header['error']}"
                )
                # Retryability keys on the machine-readable code (worker.py:
                # error_code); the message-text fallback covers frames from
                # older workers that predate the code field.
                code = reply_header.get("code")
                retryable = (
                    code == "unknown_generation" if code is not None
                    else "unknown generation" in reply_header["error"]
                )
                raise WorkerError(msg, retryable=retryable)
            if reply_header.get("gen_id") != gen_id:
                raise RuntimeError(
                    "out-of-order reply on a per-generation queue "
                    "(protocol bug)"
                )
            rseq = reply_header.get("seq")
            if rseq is not None and rseq != seq:
                # A duplicated delivery of an earlier hop's reply: discard
                # and keep waiting for the real one.
                self.metrics.counter("stale_replies_discarded")
                continue
            return y

    def _end_session(self, relay, route, gen_id: str) -> None:
        """Best-effort: surviving nodes free the session's cache row; dead
        nodes/relays are ignored (their rows age out with the node)."""
        for node in route:
            try:
                relay.put(node["queue"], pack_frame(
                    {"op": "end", "gen_id": gen_id}
                ))
            except Exception:
                pass

    def _await_route(self, deadline: float) -> None:
        """Poll the directory until some chain covers all layers again (a
        replacement node's registration is what ends the wait). The attempt
        re-plans for itself — routes can change between poll and use."""
        while True:
            try:
                self.plan_route()
                return
            except (LookupError, TimeoutError, ConnectionError, OSError,
                    RuntimeError):
                # Coverage gap, or the directory/relay itself is still down
                # (control-plane restart) — keep polling until the deadline.
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.25)

    # -- generation -----------------------------------------------------------

    def generate(
        self,
        prompt: Sequence[int],
        max_new_tokens: int = 16,
        eos_token_id: Optional[int] = None,
        timeout: float = 60.0,
        max_retries: int = 2,
        reroute_wait: float = 15.0,
        options: Optional[SamplingOptions] = None,
        seed: int = 0,
        on_token: Optional[Callable[[int], None]] = None,
        stop_check: Optional[Callable[[], bool]] = None,
    ) -> List[int]:
        """Decode one prompt through the remote chain. Thread-safe: each
        call owns its relay connection and reply queue, so N generations may
        run concurrently on one client instance (the multi-tenant sessions
        then co-batch on the serving nodes' task pools).

        ``options`` carries sampling controls (temperature/top-k/top-p —
        sampling happens client-side, where the head lives); default greedy.
        ``seed`` keys the sampling stream: same seed, same tokens.

        Mid-generation failover (SURVEY §5.3): if a hop dies (reply timeout /
        worker error), the client waits for the directory to route around the
        loss, then REPLAYS the session on the new chain — re-prefilling
        ``prompt + tokens so far`` under a fresh ``generation_id`` (the
        replayed prefix is preserved verbatim; the continuation resumes the
        same keyed sampling stream).

        ``on_token`` (the HTTP gateway's streaming hook) is called once per
        FRESH token, in order — a failover replay re-feeds cached tokens
        without re-emitting them. ``stop_check`` is polled between decode
        hops and before each retry; returning True abandons the generation
        (tokens so far are returned) — the gateway's cancel/deadline path.
        """
        if not len(prompt):
            raise ValueError("empty prompt")
        opts = options or SamplingOptions()
        if eos_token_id is None and opts.eos_token_id >= 0:
            eos_token_id = opts.eos_token_id
        out: List[int] = []
        failures = 0
        key = jax.random.PRNGKey(seed)
        while True:
            relay = None
            clean = False
            try:
                # Inside the try: a relay outage at attempt start (the
                # control-plane-restart case) must count as a retried
                # failover, not escape to the caller.
                relay = self._acquire_relay()
                result = self._generate_attempt(
                    relay, list(prompt), out, max_new_tokens, eos_token_id,
                    timeout, opts, key, on_token, stop_check,
                )
                clean = True
                return result
            except (TimeoutError, RuntimeError, ConnectionError, OSError) as e:
                # Besides timeouts and worker errors, a relay/control-plane
                # restart surfaces as a connection error mid-hop — that is a
                # failover, not a client failure.
                if isinstance(e, WorkerError) and not e.retryable:
                    raise  # deterministic worker error: replay cannot help
                failures += 1
                # Concurrent generate()/generate_many() callers land here
                # together after a relay restart; unguarded += lost counts
                # (distcheck DC103).
                with self._conn_lock:
                    self.failovers += 1
                self.metrics.counter("failovers")
                if failures > max_retries:
                    raise
                if stop_check is not None and stop_check():
                    return out  # caller abandoned it: don't wait for a route
                self._await_route(time.monotonic() + reroute_wait)
            finally:
                if relay is not None:
                    # Only a cleanly finished attempt may be reused: a
                    # failed one can have a stray reply in flight.
                    if clean:
                        self._release_relay(relay)
                    else:
                        relay.close()

    def _prefill_chunks(self, relay, route, gen_id, tokens, timeout,
                        reply_queue):
        """Push ``tokens`` through the chain in bucket-sized chunks (the
        first with ``new=True``); returns ``(last chunk's hidden states,
        index of the last valid position in that chunk, next hop seq)``."""
        cap = self.prefill_buckets[-1]
        chunks = [tokens[i : i + cap] for i in range(0, len(tokens), cap)]
        y, last_n = None, 0
        for ci, chunk in enumerate(chunks):
            n = len(chunk)
            bucket = self._bucket(n)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :n] = np.asarray(chunk, np.int32)
            x = self._embed(self.params["embed"], jnp.asarray(padded))
            y = self._send_through(relay, route, gen_id, np.asarray(x), n,
                                   timeout, reply_queue, new=(ci == 0),
                                   seq=ci)
            last_n = n
        return y, last_n, len(chunks)

    def _next_token(self, y, idx, opts, key, step):
        """Sample the next token from hidden states ``y`` at position
        ``idx`` (client-side head). Greedy rows bypass the RNG entirely."""
        if opts.temperature <= 0.0:
            logits = self._head_last(self.params, jnp.asarray(y), idx)
            return int(jnp.argmax(logits[0, -1]))
        sp = SamplingParams.create(
            1, opts.temperature, opts.top_k, opts.top_p
        )
        tok = self._sample_last(
            self.params, jnp.asarray(y), idx,
            jax.random.fold_in(key, step), sp,
        )
        return int(tok[0])

    def _generate_attempt(
        self, relay, prompt, out: List[int], max_new_tokens, eos_token_id,
        timeout, opts, key, on_token=None, stop_check=None,
    ) -> List[int]:
        """One route's worth of progress; ``out`` persists across attempts."""
        if out and (len(out) >= max_new_tokens or out[-1] == eos_token_id):
            return out  # the failed hop was already past the last token
        route = self.plan_route()
        gen_id = f"gen-{uuid.uuid4().hex[:12]}"
        # Per-attempt reply queue: a late reply from a slow (not dead) old
        # route must not land in the new attempt's stream.
        reply_queue = f"client.{uuid.uuid4().hex[:12]}"
        try:
            # (Re-)prefill: the prompt plus all but the newest generated
            # token (the newest is not in any cache yet — it is fed as the
            # first decode step below). Chunked, so a replay longer than one
            # bucket (long generation before the failure) still fits.
            replay = prompt + out[:-1]
            y, last_n, seq = self._prefill_chunks(
                relay, route, gen_id, replay, timeout, reply_queue
            )
            if out:
                token = out[-1]
            else:
                token = self._next_token(y, last_n - 1, opts, key, 0)
                out.append(token)
                if on_token is not None:
                    on_token(token)
            # Decode loop: one hidden-state hop per token. The sampling key
            # folds in the token INDEX, so a replayed attempt continues the
            # same stream rather than restarting it.
            while len(out) < max_new_tokens and token != eos_token_id:
                if stop_check is not None and stop_check():
                    return out
                x = self._embed(
                    self.params["embed"], jnp.asarray([[token]], jnp.int32)
                )
                y = self._send_through(relay, route, gen_id, np.asarray(x),
                                       1, timeout, reply_queue, seq=seq)
                seq += 1
                token = self._next_token(y, 0, opts, key, len(out))
                out.append(token)
                if on_token is not None:
                    on_token(token)
            return out
        finally:
            self._end_session(relay, route, gen_id)

    # -- batched generation (generate_many) ------------------------------------
    #
    # N prompts decoded in LOCKSTEP over one relay connection and one reply
    # queue: the hidden states of every active row travel as a single
    # stacked ``[A, S, H]`` frame per hop (co-batched at the SOURCE, so the
    # chain runs one device call per hop regardless of pool-window luck),
    # and the client runs one jitted embed/head/sample call over the whole
    # stack. Rows that hit EOS / their token budget / a stop signal drop
    # out of the stack without stalling the rest.

    def generate_many(
        self,
        prompts: Sequence[Sequence[int]],
        max_new_tokens=16,
        eos_token_id: Optional[int] = None,
        timeout: float = 60.0,
        max_retries: int = 2,
        reroute_wait: float = 15.0,
        options=None,
        seeds: Optional[Sequence[int]] = None,
        on_token: Optional[Callable[[int, int], None]] = None,
        stop_check: Optional[Callable[[int], bool]] = None,
        on_finish: Optional[Callable[[int, str], None]] = None,
    ) -> List[List[int]]:
        """Decode ``prompts`` together; returns one token list per prompt,
        byte-identical to N serial :meth:`generate` calls at the same seeds
        (per-row sampling keys fold the token index exactly as the serial
        path does; greedy rows take the same argmax).

        ``max_new_tokens`` / ``options`` / ``seeds`` may be a single value
        or one per row. ``on_token(row, token)`` fires once per FRESH token;
        ``stop_check(row)`` abandons that row when True; ``on_finish(row,
        reason)`` reports ``eos`` / ``length`` / ``stopped`` / ``error: …``.

        Failover is cohort-wide: a lost hop (timeout / retryable worker
        error / relay restart) replays every unfinished row on a fresh
        route under fresh generation ids — finished rows and already-
        emitted tokens are untouched. A non-retryable error on one row
        drops only that row (its tokens so far are returned); the rest of
        the stack decodes on.
        """
        n = len(prompts)
        if n == 0:
            return []
        for p in prompts:
            if not len(p):
                raise ValueError("empty prompt")
        def per_row(name, val):
            if not isinstance(val, (list, tuple)):
                return [val] * n
            if len(val) != n:
                raise ValueError(
                    f"{name} has {len(val)} entries for {n} prompts"
                )
            return list(val)

        max_news = per_row("max_new_tokens", max_new_tokens)
        opt_list = per_row("options", options)
        seed_list = ([0] * n if seeds is None
                     else per_row("seeds", list(seeds)))
        rows = []
        for i in range(n):
            opts = opt_list[i] or SamplingOptions()
            eos = eos_token_id
            if eos is None and opts.eos_token_id >= 0:
                eos = opts.eos_token_id
            rows.append(_Row(i, list(prompts[i]), opts, max_news[i], eos,
                             jax.random.PRNGKey(seed_list[i])))
        failures = 0
        while True:
            relay = None
            clean = False
            try:
                relay = self._acquire_relay()
                self._generate_many_attempt(
                    relay, rows, timeout, on_token, stop_check, on_finish
                )
                clean = True
                return [r.out for r in rows]
            except (TimeoutError, RuntimeError, ConnectionError, OSError) as e:
                if isinstance(e, WorkerError) and not e.retryable:
                    raise
                failures += 1
                # Concurrent generate()/generate_many() callers land here
                # together after a relay restart; unguarded += lost counts
                # (distcheck DC103).
                with self._conn_lock:
                    self.failovers += 1
                self.metrics.counter("failovers")
                if failures > max_retries:
                    raise
                if stop_check is not None and all(
                    r.done or stop_check(r.index) for r in rows
                ):
                    return [r.out for r in rows]
                self._await_route(time.monotonic() + reroute_wait)
            finally:
                if relay is not None:
                    if clean:
                        self._release_relay(relay)
                    else:
                        relay.close()

    def _generate_many_attempt(self, relay, rows, timeout, on_token,
                               stop_check, on_finish) -> None:
        """One route's worth of lockstep progress; row state (``out``)
        persists across attempts exactly like the serial path's."""

        def finish(row, reason):
            row.done = True
            row.reason = reason
            if on_finish is not None:
                on_finish(row.index, reason)

        def check_done(row):
            if row.out[-1] == row.eos:
                finish(row, "eos")
            elif len(row.out) >= row.max_new:
                finish(row, "length")

        for row in rows:  # the failed hop may have been past the last token
            if not row.done and row.out:
                check_done(row)
        active = [r for r in rows if not r.done]
        if not active:
            return
        route = self.plan_route()
        gen_ids = {r.index: f"gen-{uuid.uuid4().hex[:12]}" for r in active}
        reply_queue = f"client.{uuid.uuid4().hex[:12]}"
        ended: set = set()
        try:
            seq, ys, lens = self._prefill_many_rows(
                relay, route, active, gen_ids, timeout, reply_queue, finish
            )
            fresh = [r for r in active if not r.done and not r.out]
            if fresh:
                toks = self._next_tokens_rows(
                    [ys[r.index] for r in fresh],
                    [lens[r.index] - 1 for r in fresh], fresh,
                )
                for r, t in zip(fresh, toks):
                    r.out.append(t)
                    if on_token is not None:
                        on_token(r.index, t)
                    check_done(r)
            self._end_gens(relay, route,
                           [gen_ids[r.index] for r in active if r.done],
                           ended)
            while True:
                live = [r for r in active if not r.done]
                if stop_check is not None:
                    for r in live:
                        if stop_check(r.index):
                            finish(r, "stopped")
                    live = [r for r in live if not r.done]
                if not live:
                    return
                x = self._embed(
                    self.params["embed"],
                    jnp.asarray([[r.out[-1]] for r in live], jnp.int32),
                )
                gens = [gen_ids[r.index] for r in live]
                self._send_stacked(relay, route, gens, [1] * len(live),
                                   np.asarray(x), False, seq, reply_queue)
                results = self._collect_stacked(relay, reply_queue, gens,
                                                seq, timeout)
                seq += 1
                ok_rows, ys_list = [], []
                for r in live:
                    res = results[gen_ids[r.index]]
                    if isinstance(res, Exception):
                        self.metrics.counter("row_errors")
                        finish(r, f"error: {res}")
                    else:
                        ok_rows.append(r)
                        ys_list.append(res)
                if ok_rows:
                    toks = self._next_tokens_rows(
                        ys_list, [0] * len(ok_rows), ok_rows
                    )
                    for r, t in zip(ok_rows, toks):
                        r.out.append(t)
                        if on_token is not None:
                            on_token(r.index, t)
                        check_done(r)
                # Early leavers free their cache rows now, not at cohort end.
                self._end_gens(relay, route,
                               [gen_ids[r.index] for r in active if r.done],
                               ended)
        finally:
            self._end_gens(relay, route, list(gen_ids.values()), ended)

    def _prefill_many_rows(self, relay, route, rows, gen_ids, timeout,
                           reply_queue, finish):
        """Chunked replay prefill for the whole cohort: each round groups
        rows by bucket and sends one stacked frame per group (pipelined —
        replies for a round are collected together). Returns ``(next hop
        seq, {row: last chunk's hidden states}, {row: last valid pos+1})``.
        """
        cap = self.prefill_buckets[-1]
        chunks = {}
        for r in rows:
            replay = r.prompt + r.out[:-1]
            chunks[r.index] = [replay[i : i + cap]
                               for i in range(0, len(replay), cap)]
        ys, lens = {}, {}
        seq = 0
        for ci in range(max(len(c) for c in chunks.values())):
            todo = [r for r in rows
                    if not r.done and ci < len(chunks[r.index])]
            if not todo:
                break
            groups = {}
            for r in todo:
                b = self._bucket(len(chunks[r.index][ci]))
                groups.setdefault(b, []).append(r)
            expected = []
            for b in sorted(groups):
                grp = groups[b]
                padded = np.zeros((len(grp), b), np.int32)
                nns = []
                for gi, r in enumerate(grp):
                    ch = chunks[r.index][ci]
                    padded[gi, : len(ch)] = np.asarray(ch, np.int32)
                    nns.append(len(ch))
                x = self._embed(self.params["embed"], jnp.asarray(padded))
                gens = [gen_ids[r.index] for r in grp]
                self._send_stacked(relay, route, gens, nns, np.asarray(x),
                                   ci == 0, seq, reply_queue)
                expected.extend(gens)
            results = self._collect_stacked(relay, reply_queue, expected,
                                            seq, timeout)
            seq += 1
            for grp in groups.values():
                for r in grp:
                    res = results[gen_ids[r.index]]
                    if isinstance(res, Exception):
                        self.metrics.counter("row_errors")
                        finish(r, f"error: {res}")
                    else:
                        ys[r.index] = res
                        lens[r.index] = len(chunks[r.index][ci])
        return seq, ys, lens

    def _send_stacked(self, relay, route, gens, num_new, x, new, seq,
                      reply_queue) -> None:
        hops = [n["queue"] for n in route[1:]] + [reply_queue]
        header = {"op": "forward", "gens": list(gens),
                  "num_new": [int(v) for v in num_new],
                  "hops": hops, "new": bool(new), "seq": seq}
        relay.put(route[0]["queue"], pack_frame(header, np.asarray(x)))

    def _collect_stacked(self, relay, reply_queue, gens, seq, timeout):
        """Collect replies until every generation in ``gens`` is accounted
        for. Returns {gen_id: [1, S, H] row} — or a non-retryable
        WorkerError for rows a worker rejected deterministically (retryable
        errors raise: session loss means the whole cohort fails over)."""
        pending = set(gens)
        results: Dict[str, object] = {}
        deadline = time.monotonic() + timeout
        while pending:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                raise TimeoutError(
                    f"no reply for {len(pending)} generations hop seq={seq} "
                    f"within {timeout}s"
                )
            header, y = unpack_frame(relay.get(reply_queue, timeout=remaining))
            if header.get("op") == "error":
                code = header.get("code")
                retryable = (
                    code == "unknown_generation" if code is not None
                    else "unknown generation" in header.get("error", "")
                )
                err = WorkerError(
                    f"worker {header.get('from')}: {header.get('error')}",
                    retryable=retryable,
                )
                if retryable:
                    raise err
                gid = header.get("gen_id")
                if gid in pending:
                    results[gid] = err
                    pending.discard(gid)
                continue
            rseq = header.get("seq")
            if rseq is not None and rseq != seq:
                self.metrics.counter("stale_replies_discarded")
                continue
            rgens = header.get("gens")
            if rgens is None:
                rgens, rows = [header.get("gen_id")], [y]
            else:
                rows = [y[i : i + 1] for i in range(len(rgens))]
            matched = False
            for gid, row in zip(rgens, rows):
                if gid in pending:
                    results[gid] = np.asarray(row)
                    pending.discard(gid)
                    matched = True
            if not matched:  # duplicated delivery of this hop's reply
                self.metrics.counter("stale_replies_discarded")
        return results

    def _next_tokens_rows(self, ys, idxs, rows) -> List[int]:
        """One jitted head (+ per-row-keyed sample) call over the stacked
        rows — ``ys`` are ``[1, S, H]`` slices whose S may DIFFER (rows of
        a cohort can end prefill in different buckets), so each row's last
        valid position is gathered first and the device call always sees a
        ``[A, 1, H]`` stack (which also keys the jit cache on A alone).
        Greedy-only stacks skip the RNG entirely, like the serial path."""
        slices = [np.asarray(y)[:, i : i + 1] for y, i in zip(ys, idxs)]
        x = jnp.asarray(np.concatenate(slices, axis=0))
        idx = jnp.zeros(len(rows), jnp.int32)
        if all(r.opts.temperature <= 0.0 for r in rows):
            logits = self._head_rows(self.params, x, idx)
            return [int(t) for t in
                    np.asarray(jnp.argmax(logits[:, -1], axis=-1))]
        toks = self._sample_rows(
            self.params, x, idx,
            jnp.stack([r.key for r in rows]),
            jnp.asarray([len(r.out) for r in rows], jnp.int32),
            jnp.asarray([r.opts.temperature for r in rows], jnp.float32),
            jnp.asarray([r.opts.top_k for r in rows], jnp.int32),
            jnp.asarray([r.opts.top_p for r in rows], jnp.float32),
        )
        return [int(t) for t in np.asarray(toks)]

    def _end_gens(self, relay, route, gids, ended) -> None:
        """Best-effort session teardown for a batch of generations: ONE
        pipelined send carries an ``end`` frame to every route node."""
        fresh = [g for g in gids if g not in ended]
        if not fresh:
            return
        ended.update(fresh)
        frame = pack_frame({"op": "end", "gens": fresh})
        try:
            relay.put_many([(node["queue"], frame) for node in route])
        except Exception:
            pass

    def close(self) -> None:
        self._directory.close()
        with self._conn_lock:
            pool, self._conn_pool = self._conn_pool, []
        for relay in pool:
            relay.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
