"""Client/orchestrator: the layer the reference never wrote.

SURVEY §1: "There is no client layer (no code that runs the embedding/lm_head,
routes a prompt through a chain of remote blocks, or samples tokens)". This is
that layer: the client holds the embedding + final-norm + lm_head (the
non-layer weights a block node never loads), asks the directory for a route
covering all decoder layers, source-routes hidden states through the chain of
block workers over the relay, and samples tokens.

The per-request ``generation_id`` threads through every hop — the session key
of the reference's multi-tenant cache design (``models/llama/model.py:27`` →
``cache.py:74``) — so each worker pins the session to one cache row.
"""

from __future__ import annotations

import time
import uuid
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..config import ModelConfig
from ..models import llama
from .directory import DirectoryClient
from .messages import pack_frame, unpack_frame
from .relay import RelayClient

__all__ = ["DistributedClient", "WorkerError"]


class WorkerError(RuntimeError):
    """An error frame reported by a block worker.

    ``retryable`` is True when the condition indicates session loss (worker
    restarted / session evicted — ``KeyError: unknown generation`` from
    ``backend.py``), i.e. a replay on a fresh route can succeed; deterministic
    worker errors (bad request shapes, capacity) are not retried.
    """

    def __init__(self, message: str, retryable: bool):
        super().__init__(message)
        self.retryable = retryable


class DistributedClient:
    """Routes generations through remote block workers.

    ``params`` needs ``embed``, ``final_norm`` and (unless tied) ``lm_head``
    — e.g. from ``checkpoint.load_model_params`` or, leaner, a loader that
    skips the decoder layers.
    """

    def __init__(
        self,
        relay_port: int,
        cfg: ModelConfig,
        params,
        host: str = "127.0.0.1",
        prefill_buckets: Sequence[int] = (32, 128, 512),
        dtype=jnp.bfloat16,
    ):
        self.cfg = cfg
        self.params = params
        self.dtype = jnp.dtype(dtype)
        self.prefill_buckets = tuple(prefill_buckets)
        self.host, self.relay_port = host, relay_port
        self._relay = RelayClient(host, relay_port)
        self._directory = DirectoryClient(relay_port, host)
        self.failovers = 0  # mid-generation re-route count (observability)

        self._embed = jax.jit(
            lambda emb, t: jnp.take(emb, t, axis=0).astype(self.dtype)
        )

        def _head_last(params, x, idx):
            last = jax.lax.dynamic_slice_in_dim(x, idx, 1, axis=1)
            return llama.apply_head(self.cfg, params, last)

        self._head_last = jax.jit(_head_last)

    # -- routing --------------------------------------------------------------

    def plan_route(self) -> List[dict]:
        return self._directory.route(self.cfg.num_layers)

    def _bucket(self, n: int) -> int:
        for b in self.prefill_buckets:
            if n <= b:
                return b
        raise ValueError(
            f"prompt length {n} exceeds largest bucket "
            f"{self.prefill_buckets[-1]}"
        )

    def _send_through(self, route, gen_id: str, x: np.ndarray, num_new: int,
                      timeout: float, reply_queue: str,
                      new: bool = False) -> np.ndarray:
        hops = [n["queue"] for n in route[1:]] + [reply_queue]
        header = {"op": "forward", "gen_id": gen_id, "num_new": num_new,
                  "hops": hops, "new": new}
        self._relay.put(route[0]["queue"], pack_frame(header, np.asarray(x)))
        reply_header, y = unpack_frame(self._relay.get(reply_queue,
                                                       timeout=timeout))
        if reply_header.get("op") == "error":
            msg = f"worker {reply_header.get('from')}: {reply_header['error']}"
            # Retryability keys on the machine-readable code (worker.py:
            # error_code); the message-text fallback covers frames from
            # older workers that predate the code field.
            code = reply_header.get("code")
            retryable = (
                code == "unknown_generation" if code is not None
                else "unknown generation" in reply_header["error"]
            )
            raise WorkerError(msg, retryable=retryable)
        if reply_header.get("gen_id") != gen_id:
            raise RuntimeError("out-of-order reply (concurrent use of one "
                               "client instance is not supported)")
        return y

    def _end_session(self, route, gen_id: str) -> None:
        """Best-effort: surviving nodes free the session's cache row; dead
        nodes/relays are ignored (their rows age out with the node)."""
        for node in route:
            try:
                self._relay.put(node["queue"], pack_frame(
                    {"op": "end", "gen_id": gen_id}
                ))
            except Exception:
                pass

    def _await_route(self, deadline: float) -> None:
        """Poll the directory until some chain covers all layers again (a
        replacement node's registration is what ends the wait). The attempt
        re-plans for itself — routes can change between poll and use."""
        while True:
            try:
                self.plan_route()
                return
            except LookupError:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.25)

    # -- generation -----------------------------------------------------------

    def generate(
        self,
        prompt: Sequence[int],
        max_new_tokens: int = 16,
        eos_token_id: Optional[int] = None,
        timeout: float = 60.0,
        max_retries: int = 2,
        reroute_wait: float = 15.0,
    ) -> List[int]:
        """Greedy decode of one prompt through the remote chain.

        Mid-generation failover (SURVEY §5.3): if a hop dies (reply timeout /
        worker error), the client waits for the directory to route around the
        loss, then REPLAYS the session on the new chain — re-prefilling
        ``prompt + tokens so far`` under a fresh ``generation_id`` (greedy
        decoding is deterministic, so the replayed stream continues exactly;
        inference needs no optimizer state — recovery is reload + replay).
        """
        if not len(prompt):
            raise ValueError("empty prompt")
        out: List[int] = []
        failures = 0
        while True:
            try:
                return self._generate_attempt(
                    list(prompt), out, max_new_tokens, eos_token_id, timeout
                )
            except (TimeoutError, RuntimeError) as e:
                if isinstance(e, WorkerError) and not e.retryable:
                    raise  # deterministic worker error: replay cannot help
                failures += 1
                self.failovers += 1
                if failures > max_retries:
                    raise
                self._await_route(time.monotonic() + reroute_wait)

    def _prefill_chunks(self, route, gen_id, tokens, timeout, reply_queue):
        """Push ``tokens`` through the chain in bucket-sized chunks (the
        first with ``new=True``); returns ``(last chunk's hidden states,
        index of the last valid position in that chunk)``."""
        cap = self.prefill_buckets[-1]
        chunks = [tokens[i : i + cap] for i in range(0, len(tokens), cap)]
        y, last_n = None, 0
        for ci, chunk in enumerate(chunks):
            n = len(chunk)
            bucket = self._bucket(n)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :n] = np.asarray(chunk, np.int32)
            x = self._embed(self.params["embed"], jnp.asarray(padded))
            y = self._send_through(route, gen_id, np.asarray(x), n, timeout,
                                   reply_queue, new=(ci == 0))
            last_n = n
        return y, last_n

    def _generate_attempt(
        self, prompt, out: List[int], max_new_tokens, eos_token_id, timeout
    ) -> List[int]:
        """One route's worth of progress; ``out`` persists across attempts."""
        if out and (len(out) >= max_new_tokens or out[-1] == eos_token_id):
            return out  # the failed hop was already past the last token
        route = self.plan_route()
        gen_id = f"gen-{uuid.uuid4().hex[:12]}"
        # Per-attempt reply queue: a late reply from a slow (not dead) old
        # route must not land in the new attempt's stream.
        reply_queue = f"client.{uuid.uuid4().hex[:12]}"
        try:
            # (Re-)prefill: the prompt plus all but the newest generated
            # token (the newest is not in any cache yet — it is fed as the
            # first decode step below). Chunked, so a replay longer than one
            # bucket (long generation before the failure) still fits.
            replay = prompt + out[:-1]
            y, last_n = self._prefill_chunks(
                route, gen_id, replay, timeout, reply_queue
            )
            if out:
                token = out[-1]
            else:
                logits = self._head_last(self.params, jnp.asarray(y), last_n - 1)
                token = int(jnp.argmax(logits[0, -1]))
                out.append(token)
            # Decode loop: one hidden-state hop per token.
            while len(out) < max_new_tokens and token != eos_token_id:
                x = self._embed(
                    self.params["embed"], jnp.asarray([[token]], jnp.int32)
                )
                y = self._send_through(route, gen_id, np.asarray(x), 1,
                                       timeout, reply_queue)
                logits = self._head_last(self.params, jnp.asarray(y), 0)
                token = int(jnp.argmax(logits[0, -1]))
                out.append(token)
            return out
        finally:
            self._end_session(route, gen_id)

    def close(self) -> None:
        self._relay.close()
        self._directory.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
