"""Client/orchestrator: the layer the reference never wrote.

SURVEY §1: "There is no client layer (no code that runs the embedding/lm_head,
routes a prompt through a chain of remote blocks, or samples tokens)". This is
that layer: the client holds the embedding + final-norm + lm_head (the
non-layer weights a block node never loads), asks the directory for a route
covering all decoder layers, source-routes hidden states through the chain of
block workers over the relay, and samples tokens.

The per-request ``generation_id`` threads through every hop — the session key
of the reference's multi-tenant cache design (``models/llama/model.py:27`` →
``cache.py:74``) — so each worker pins the session to one cache row.
"""

from __future__ import annotations

import uuid
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from ..config import ModelConfig
from ..models import llama
from .directory import DirectoryClient
from .messages import pack_frame, unpack_frame
from .relay import RelayClient

__all__ = ["DistributedClient"]


class DistributedClient:
    """Routes generations through remote block workers.

    ``params`` needs ``embed``, ``final_norm`` and (unless tied) ``lm_head``
    — e.g. from ``checkpoint.load_model_params`` or, leaner, a loader that
    skips the decoder layers.
    """

    def __init__(
        self,
        relay_port: int,
        cfg: ModelConfig,
        params,
        host: str = "127.0.0.1",
        prefill_buckets: Sequence[int] = (32, 128, 512),
        dtype=jnp.bfloat16,
    ):
        self.cfg = cfg
        self.params = params
        self.dtype = jnp.dtype(dtype)
        self.prefill_buckets = tuple(prefill_buckets)
        self.host, self.relay_port = host, relay_port
        self.reply_queue = f"client.{uuid.uuid4().hex[:12]}"
        self._relay = RelayClient(host, relay_port)
        self._directory = DirectoryClient(relay_port, host)

        self._embed = jax.jit(
            lambda emb, t: jnp.take(emb, t, axis=0).astype(self.dtype)
        )

        def _head_last(params, x, idx):
            last = jax.lax.dynamic_slice_in_dim(x, idx, 1, axis=1)
            return llama.apply_head(self.cfg, params, last)

        self._head_last = jax.jit(_head_last)

    # -- routing --------------------------------------------------------------

    def plan_route(self) -> List[dict]:
        return self._directory.route(self.cfg.num_layers)

    def _bucket(self, n: int) -> int:
        for b in self.prefill_buckets:
            if n <= b:
                return b
        raise ValueError(
            f"prompt length {n} exceeds largest bucket "
            f"{self.prefill_buckets[-1]}"
        )

    def _send_through(self, route, gen_id: str, x: np.ndarray, num_new: int,
                      timeout: float, new: bool = False) -> np.ndarray:
        hops = [n["queue"] for n in route[1:]] + [self.reply_queue]
        header = {"op": "forward", "gen_id": gen_id, "num_new": num_new,
                  "hops": hops, "new": new}
        self._relay.put(route[0]["queue"], pack_frame(header, np.asarray(x)))
        reply_header, y = unpack_frame(self._relay.get(self.reply_queue,
                                                       timeout=timeout))
        if reply_header.get("op") == "error":
            raise RuntimeError(
                f"worker {reply_header.get('from')}: {reply_header['error']}"
            )
        if reply_header.get("gen_id") != gen_id:
            raise RuntimeError("out-of-order reply (concurrent use of one "
                               "client instance is not supported)")
        return y

    def _end_session(self, route, gen_id: str) -> None:
        for node in route:
            self._relay.put(node["queue"], pack_frame(
                {"op": "end", "gen_id": gen_id}
            ))

    # -- generation -----------------------------------------------------------

    def generate(
        self,
        prompt: Sequence[int],
        max_new_tokens: int = 16,
        eos_token_id: Optional[int] = None,
        timeout: float = 60.0,
    ) -> List[int]:
        """Greedy decode of one prompt through the remote chain."""
        if not len(prompt):
            raise ValueError("empty prompt")
        route = self.plan_route()
        gen_id = f"gen-{uuid.uuid4().hex[:12]}"
        try:
            # Prefill: embed the padded prompt, push through the chain.
            n = len(prompt)
            bucket = self._bucket(n)
            padded = np.zeros((1, bucket), np.int32)
            padded[0, :n] = np.asarray(prompt, np.int32)
            x = self._embed(self.params["embed"], jnp.asarray(padded))
            y = self._send_through(route, gen_id, np.asarray(x), n, timeout,
                                   new=True)
            logits = self._head_last(self.params, jnp.asarray(y), n - 1)
            token = int(jnp.argmax(logits[0, -1]))
            out = [token]
            # Decode loop: one hidden-state hop per token.
            while len(out) < max_new_tokens and token != eos_token_id:
                x = self._embed(
                    self.params["embed"], jnp.asarray([[token]], jnp.int32)
                )
                y = self._send_through(route, gen_id, np.asarray(x), 1, timeout)
                logits = self._head_last(self.params, jnp.asarray(y), 0)
                token = int(jnp.argmax(logits[0, -1]))
                out.append(token)
            return out
        finally:
            self._end_session(route, gen_id)

    def close(self) -> None:
        self._relay.close()
        self._directory.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
