"""``python -m distributed_llm_inference_tpu`` → the ``distribute`` CLI."""

import sys

from .cli import main

sys.exit(main())
