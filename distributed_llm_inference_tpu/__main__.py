"""``python -m distributed_llm_inference_tpu`` → the ``distribute`` CLI
(subcommands: relay / serve / generate / local / api / info — ``api`` is
the OpenAI-compatible HTTP gateway; see ``cli.py``)."""

import sys

from .cli import main

sys.exit(main())
