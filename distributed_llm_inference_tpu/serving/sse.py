"""Server-Sent Events framing for the streaming completions path.

The OpenAI streaming wire format: each chunk is one ``data: <json>``
event, the stream ends with the literal ``data: [DONE]`` sentinel. SSE
needs no Content-Length — the gateway closes the connection to delimit
the body (HTTP/1.1 ``Connection: close``), so chunked encoding stays out
of the stdlib-only server.
"""

from __future__ import annotations

import json
from typing import Any, Optional

SSE_DONE = b"data: [DONE]\n\n"


def sse_event(data: Any, seq: Optional[int] = None) -> bytes:
    """One SSE frame: ``data: <compact json>\\n\\n``.

    ``seq`` stamps a dict payload with the token's index in the generated
    sequence — the exactly-once key a client can use to detect duplicated
    or lost tokens across a mid-stream node recovery (FleetBackend)."""
    if seq is not None and isinstance(data, dict):
        data = dict(data, seq=int(seq))
    return b"data: " + json.dumps(data, separators=(",", ":")).encode() + b"\n\n"


def sse_headers(status: str = "200 OK", extra: str = "") -> bytes:
    """``extra`` carries pre-formatted additional header lines (each
    ``Name: value\\r\\n``) — e.g. the gateway's ``X-Trace-Id`` echo."""
    return (
        f"HTTP/1.1 {status}\r\n"
        "Content-Type: text/event-stream\r\n"
        "Cache-Control: no-cache\r\n"
        "Connection: close\r\n"
        f"{extra}\r\n"
    ).encode()
