"""HTTP serving gateway (the tier the reference left as pseudocode).

An OpenAI-compatible ``/v1/completions`` front door — JSON and SSE token
streaming — over either a local :class:`~..engine.engine.InferenceEngine`
or a relay-tier :class:`~..distributed.client.DistributedClient`, behind
the common :class:`Backend` protocol. Stdlib-only: raw
``asyncio.start_server`` HTTP/1.1, one request per connection.

Admission control (bounded in-flight, 429 + ``Retry-After``), per-request
deadlines that cancel the underlying generation, graceful SIGTERM drain,
``/metrics`` (Prometheus text) and ``/healthz`` — see
:class:`~..config.ServingConfig` for the policy knobs and the README
"HTTP serving" section for the curl quickstart.
"""

from .backends import (
    Backend,
    ClientBackend,
    DisaggBackend,
    EngineBackend,
    FleetBackend,
    Handle,
    TokenEvent,
)
from .breaker import CircuitBreaker
from .server import ApiServer

__all__ = [
    "ApiServer",
    "Backend",
    "CircuitBreaker",
    "ClientBackend",
    "DisaggBackend",
    "EngineBackend",
    "FleetBackend",
    "Handle",
    "TokenEvent",
]
