"""Circuit breaker for the HTTP gateway's backend.

When the backend is down (relay hub unreachable, every worker lease
lapsed, engine driver dead), each admitted request burns a full client
timeout before failing — a thundering herd of doomed requests. The
breaker fails them fast instead: after ``failure_threshold`` consecutive
failures it OPENS (requests get 503 + Retry-After immediately); after
``recovery_s`` it goes HALF_OPEN and lets a limited number of trial
requests through; ``success_threshold`` consecutive successes CLOSE it
again, any failure re-opens it.

Signals come from two places: real request outcomes
(:meth:`record_success`/:meth:`record_failure`, fed by the server's
completion paths) and background health probes (:meth:`record_probe`,
fed by the server's probe loop pinging the backend). Probe failures
always count — the breaker must open even when no traffic is arriving —
but probe successes only act when the breaker is already tripped, so a
healthy-looking probe can never mask live request failures.

State is observable: transition counters plus a ``breaker_state`` gauge
(0 = closed, 1 = open, 2 = half-open) land in ``Metrics`` and therefore
in ``/metrics``. The clock is injectable for deterministic tests.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Optional

from ..utils.metrics import Metrics

__all__ = ["CircuitBreaker", "CLOSED", "OPEN", "HALF_OPEN"]

CLOSED, OPEN, HALF_OPEN = "closed", "open", "half_open"
_STATE_GAUGE = {CLOSED: 0.0, OPEN: 1.0, HALF_OPEN: 2.0}


class CircuitBreaker:
    def __init__(
        self,
        failure_threshold: int = 5,
        recovery_s: float = 5.0,
        success_threshold: int = 1,
        metrics: Optional[Metrics] = None,
        clock: Callable[[], float] = time.monotonic,
    ):
        if failure_threshold < 1 or success_threshold < 1:
            raise ValueError("breaker thresholds must be >= 1")
        self.failure_threshold = failure_threshold
        self.recovery_s = recovery_s
        self.success_threshold = success_threshold
        self.metrics = metrics if metrics is not None else Metrics()
        self._clock = clock
        self._lock = threading.Lock()
        self._state = CLOSED
        self._failures = 0  # consecutive, while CLOSED
        self._successes = 0  # consecutive, while HALF_OPEN
        self._opened_at = 0.0
        self._trials = 0  # requests admitted since entering HALF_OPEN
        self.metrics.gauge("breaker_state", _STATE_GAUGE[CLOSED])

    # -- state machine (callers hold self._lock) ------------------------------

    def _set_state(self, state: str) -> None:  # distcheck: holds-lock(_lock)
        if state == self._state:
            return
        self._state = state
        self.metrics.counter(f"breaker_{state}_transitions")
        self.metrics.gauge("breaker_state", _STATE_GAUGE[state])
        if state == OPEN:
            self._opened_at = self._clock()
        elif state == HALF_OPEN:
            self._successes = 0
            self._trials = 0
        else:  # CLOSED
            self._failures = 0

    def _maybe_half_open(self) -> None:  # distcheck: holds-lock(_lock)
        if (
            self._state == OPEN
            and self._clock() - self._opened_at >= self.recovery_s
        ):
            self._set_state(HALF_OPEN)

    # -- admission ------------------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            self._maybe_half_open()
            return self._state

    def allow(self) -> bool:
        """May a request proceed right now? OPEN → no (503); HALF_OPEN →
        only the trial budget (``success_threshold`` requests) passes."""
        with self._lock:
            self._maybe_half_open()
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                return False
            if self._trials >= self.success_threshold:
                return False
            self._trials += 1
            return True

    def retry_after(self) -> float:
        """Seconds until the next trial is worth attempting (the 503's
        Retry-After value; >= 1 so clients don't busy-spin)."""
        with self._lock:
            remaining = self.recovery_s - (self._clock() - self._opened_at)
            return max(1.0, remaining)

    # -- outcome signals ------------------------------------------------------

    def record_success(self) -> None:
        with self._lock:
            if self._state == HALF_OPEN:
                self._successes += 1
                if self._successes >= self.success_threshold:
                    self._set_state(CLOSED)
            elif self._state == CLOSED:
                self._failures = 0

    def record_failure(self) -> None:
        with self._lock:
            self.metrics.counter("breaker_failures_recorded")
            if self._state == HALF_OPEN:
                self._set_state(OPEN)  # trial failed: back off again
            elif self._state == CLOSED:
                self._failures += 1
                if self._failures >= self.failure_threshold:
                    self._set_state(OPEN)
            else:  # already OPEN: refresh the window
                self._opened_at = self._clock()

    def record_probe(self, ok: bool) -> None:
        """Background health-probe outcome. Failures always count toward
        opening; successes only advance recovery (OPEN → HALF_OPEN →
        CLOSED) — they never reset the live-failure streak, so probes
        cannot mask a failing request path."""
        if not ok:
            self.record_failure()
            return
        with self._lock:
            self._maybe_half_open()
            if self._state == HALF_OPEN:
                self._successes += 1
                if self._successes >= self.success_threshold:
                    self._set_state(CLOSED)
